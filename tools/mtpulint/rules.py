"""mtpulint rules: the project invariants, one class each.

Every rule encodes a structural property PRs 1-4 established and a refactor
could silently drop: error transport (swallowed-except, typed-errors),
deadline plumbing (raw-transport, deadline-rebind), lock hygiene
(lock-blocking-io, unlocked-global), resource lifetime (resource-leak), and
the observability seams (stage-key, metrics-rendered). Rules are AST-based
-- they see structure, not text -- so renames and reformatting can't dodge
them, and suppressions (`# mtpulint: disable=<rule>`) are visible decisions
in the diff rather than regex blind spots.
"""

from __future__ import annotations

import ast

from .engine import Finding, ProjectContext, Rule

# Hot-path packages: where a swallowed error means silent data-plane damage.
HOT_PATHS = (
    "minio_tpu/api/",
    "minio_tpu/object/",
    "minio_tpu/dist/",
    "minio_tpu/storage/",
    "minio_tpu/chaos/",
)

TRANSPORT = "minio_tpu/dist/transport.py"
PERF = "minio_tpu/control/perf.py"
METRICS = "minio_tpu/control/metrics.py"
DEGRADE = "minio_tpu/control/degrade.py"


def _call_name(node: ast.Call) -> str:
    """Dotted best-effort name of a call: `a.b.c(...)` -> 'a.b.c',
    `f(...)` -> 'f'. Unresolvable pieces render as '?'."""
    parts: list[str] = []
    cur = node.func
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
    else:
        parts.append("?")
    return ".".join(reversed(parts))


def _str_const(node: ast.AST | None) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


# ---------------------------------------------------------------------------
# swallowed-except
# ---------------------------------------------------------------------------


class SwallowedExceptRule(Rule):
    """Broad `except` that swallows silently on a hot path.

    A handler for bare/`Exception`/`BaseException` whose body neither
    re-raises, returns, logs, counts, nor calls anything is a black hole:
    the error happened, nobody will ever know. Narrow the type, or make the
    swallow observable (log + metric)."""

    id = "swallowed-except"
    title = "broad except swallows without logging or re-raising"
    scope = HOT_PATHS

    BROAD = {"Exception", "BaseException"}

    def _is_broad(self, handler: ast.ExceptHandler) -> bool:
        t = handler.type
        if t is None:
            return True
        if isinstance(t, ast.Name):
            return t.id in self.BROAD
        if isinstance(t, ast.Tuple):
            return any(
                isinstance(e, ast.Name) and e.id in self.BROAD for e in t.elts
            )
        return False

    def _is_silent(self, handler: ast.ExceptHandler) -> bool:
        """Silent = nothing in the body raises, returns, or calls anything.
        A bare `return`/`continue`/`pass` body observes nothing."""
        for stmt in handler.body:
            for node in ast.walk(stmt):
                if isinstance(node, (ast.Raise, ast.Call, ast.Yield, ast.YieldFrom)):
                    return False
                if isinstance(node, ast.Return) and node.value is not None:
                    return False
        return True

    def check(self, project: ProjectContext):
        for ctx in project.iter_files(*self.scope):
            for node in ast.walk(ctx.tree):
                if not isinstance(node, ast.ExceptHandler):
                    continue
                if self._is_broad(node) and self._is_silent(node):
                    what = "bare except" if node.type is None else "broad except"
                    yield Finding(
                        self.id,
                        ctx.relpath,
                        node.lineno,
                        f"{what} swallows silently -- narrow the type, or "
                        "log-and-count before continuing",
                    )


# ---------------------------------------------------------------------------
# raw-transport
# ---------------------------------------------------------------------------


class RawTransportRule(Rule):
    """Raw `requests`/`socket` traffic outside dist/transport.py.

    All internode RPC must ride RestClient.call: that is where the deadline
    budget caps the socket timeout, the X-Mtpu-Deadline header is stamped,
    chaos faults inject, and per-peer histograms record. A module opening
    its own HTTP session or socket re-introduces the unbounded hop. External
    backends (the S3 gateway) are the one legitimate exception -- suppress
    with a justification comment."""

    id = "raw-transport"
    title = "raw requests/socket use outside dist/transport.py"
    scope = ("minio_tpu/dist/", "minio_tpu/storage/", "minio_tpu/object/")

    def check(self, project: ProjectContext):
        for ctx in project.iter_files(*self.scope):
            if ctx.relpath == TRANSPORT:
                continue
            for node in ast.walk(ctx.tree):
                if isinstance(node, ast.Import):
                    for alias in node.names:
                        if alias.name.split(".")[0] in ("requests", "socket"):
                            yield self._finding(ctx, node, f"import {alias.name}")
                elif isinstance(node, ast.ImportFrom):
                    if (node.module or "").split(".")[0] in ("requests", "socket"):
                        yield self._finding(ctx, node, f"from {node.module} import ...")
                elif isinstance(node, ast.Call):
                    name = _call_name(node)
                    root = name.split(".")[0]
                    if root in ("requests", "socket") and "." in name:
                        yield self._finding(ctx, node, f"{name}(...)")

    def _finding(self, ctx, node, what: str) -> Finding:
        return Finding(
            self.id,
            ctx.relpath,
            node.lineno,
            f"{what} -- internode traffic must ride dist/transport.py "
            "RestClient so the deadline/chaos/metrics seams apply",
        )


# ---------------------------------------------------------------------------
# deadline-rebind
# ---------------------------------------------------------------------------


class DeadlineRebindRule(Rule):
    """The deadline budget must ride EVERY hop (tools/deadline_lint.py,
    generalized to the AST).

    Two obligations:
      1. dist/transport.py keeps the plumbing: a `deadline.remaining()`
         check, a DEADLINE_HEADER stamp on outgoing requests
         (`headers[DEADLINE_HEADER] = ...`), and a DeadlineExceeded raise.
      2. Every internode REST *server* module (one that authenticates
         TOKEN_HEADER on inbound requests) re-binds the propagated budget
         with `deadline.bind_header(...)` -- a hop that drops the header
         resets the budget to infinity for everything downstream."""

    id = "deadline-rebind"
    title = "deadline propagation plumbing dropped"
    scope = ("minio_tpu/",)

    def check(self, project: ProjectContext):
        tctx = project.get(TRANSPORT)
        if tctx is not None:
            yield from self._check_transport(tctx)
        for ctx in project.iter_files(*self.scope):
            if ctx.relpath == TRANSPORT:
                continue
            if self._authenticates_token(ctx) and not self._rebinds(ctx):
                yield Finding(
                    self.id,
                    ctx.relpath,
                    1,
                    "authenticates TOKEN_HEADER (REST server) but never calls "
                    "deadline.bind_header -- inbound budgets are dropped here",
                )

    def _check_transport(self, ctx):
        has_remaining = False
        has_stamp = False
        has_exceeded = False
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call) and _call_name(node).endswith(
                "deadline.remaining"
            ):
                has_remaining = True
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if (
                        isinstance(tgt, ast.Subscript)
                        and isinstance(tgt.slice, ast.Name)
                        and tgt.slice.id == "DEADLINE_HEADER"
                    ):
                        has_stamp = True
            if isinstance(node, ast.Raise) and node.exc is not None:
                name = ""
                if isinstance(node.exc, ast.Call):
                    name = _call_name(node.exc)
                elif isinstance(node.exc, (ast.Name, ast.Attribute)):
                    cur = node.exc
                    name = cur.attr if isinstance(cur, ast.Attribute) else cur.id
                if "DeadlineExceeded" in name:
                    has_exceeded = True
        if not has_remaining:
            yield Finding(self.id, ctx.relpath, 1,
                          "missing deadline.remaining() budget check before the hop")
        if not has_stamp:
            yield Finding(self.id, ctx.relpath, 1,
                          "missing headers[DEADLINE_HEADER] stamp on outgoing RPCs")
        if not has_exceeded:
            yield Finding(self.id, ctx.relpath, 1,
                          "missing DeadlineExceeded raise for a spent budget")

    @staticmethod
    def _authenticates_token(ctx) -> bool:
        for node in ast.walk(ctx.tree):
            if (
                isinstance(node, ast.Call)
                and _call_name(node).endswith("headers.get")
                and node.args
                and isinstance(node.args[0], ast.Name)
                and node.args[0].id == "TOKEN_HEADER"
            ):
                return True
        return False

    @staticmethod
    def _rebinds(ctx) -> bool:
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call) and _call_name(node).endswith(
                "deadline.bind_header"
            ):
                return True
        return False


# ---------------------------------------------------------------------------
# lock-blocking-io
# ---------------------------------------------------------------------------


class LockBlockingIORule(Rule):
    """Blocking I/O inside a `with <lock>:` body.

    A sleep, HTTP call, or file open while holding a mutex convoys every
    other thread that needs it -- the exact pattern behind the refresh-
    daemon redesign in dist/locks.py. Do the I/O outside, publish results
    under the lock."""

    id = "lock-blocking-io"
    title = "blocking I/O while holding a lock"
    scope = ("minio_tpu/storage/", "minio_tpu/dist/", "minio_tpu/control/")

    _LOCK_HINTS = ("lock", "mutex", "_mu", "sem")
    _BLOCKING_EXACT = {
        "time.sleep", "sleep", "open", "subprocess.run", "subprocess.Popen",
        "subprocess.check_call", "subprocess.check_output",
        "socket.create_connection", "tempfile.NamedTemporaryFile",
    }
    _BLOCKING_PREFIX = ("requests.",)
    _BLOCKING_SUFFIX = (".read_file", ".write_all", ".create_file", ".append_file")

    def _is_lock_expr(self, expr: ast.AST) -> bool:
        name = ""
        if isinstance(expr, ast.Attribute):
            name = expr.attr
        elif isinstance(expr, ast.Name):
            name = expr.id
        elif isinstance(expr, ast.Call):
            # with self._locks[i] / with lock() styles resolve via the callee
            return self._is_lock_expr(expr.func)
        elif isinstance(expr, ast.Subscript):
            return self._is_lock_expr(expr.value)
        low = name.lower()
        return any(h in low for h in self._LOCK_HINTS)

    def _is_blocking(self, call: ast.Call) -> bool:
        name = _call_name(call)
        if name in self._BLOCKING_EXACT:
            return True
        if any(name.startswith(p) for p in self._BLOCKING_PREFIX):
            return True
        return any(name.endswith(s) for s in self._BLOCKING_SUFFIX)

    def check(self, project: ProjectContext):
        for ctx in project.iter_files(*self.scope):
            for node in ast.walk(ctx.tree):
                if not isinstance(node, (ast.With, ast.AsyncWith)):
                    continue
                if not any(
                    self._is_lock_expr(item.context_expr) for item in node.items
                ):
                    continue
                for stmt in node.body:
                    for sub in ast.walk(stmt):
                        # Deferred work (nested defs) runs after release.
                        if isinstance(
                            sub, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
                        ):
                            break
                        if isinstance(sub, ast.Call) and self._is_blocking(sub):
                            yield Finding(
                                self.id,
                                ctx.relpath,
                                sub.lineno,
                                f"{_call_name(sub)}(...) inside a `with lock:` "
                                "body -- do the I/O outside, publish under "
                                "the lock",
                            )


# ---------------------------------------------------------------------------
# resource-leak
# ---------------------------------------------------------------------------


class ResourceLeakRule(Rule):
    """open()/NamedTemporaryFile() without `with` or a closing try/finally.

    A handle that leaks on the exception path pins an fd (and on staged
    writes, a .tmp file) until GC happens to run -- under load that is fd
    exhaustion. Acceptable shapes: `with open(...)`, `f = open(...)` later
    entered as `with f:` or closed via `f.close()` in a `finally:`, or the
    handle escaping as a return value / argument (ownership transferred)."""

    id = "resource-leak"
    title = "file handle not closed on all paths"
    scope = HOT_PATHS

    _OPENERS = {
        "open", "tempfile.NamedTemporaryFile", "tempfile.TemporaryFile",
        "NamedTemporaryFile", "TemporaryFile", "io.open",
    }

    def check(self, project: ProjectContext):
        for ctx in project.iter_files(*self.scope):
            for fn in ast.walk(ctx.tree):
                if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    yield from self._check_function(ctx, fn)

    def _check_function(self, ctx, fn):
        with_exprs: set[int] = set()     # id() of calls used as with-items
        owned: set[int] = set()          # id() of calls whose result escapes
        assigns: dict[int, str] = {}     # id(call) -> simple target name
        calls: list[ast.Call] = []

        for node in ast.walk(fn):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    for sub in ast.walk(item.context_expr):
                        if isinstance(sub, ast.Call):
                            with_exprs.add(id(sub))
            if isinstance(node, ast.Call):
                if isinstance(node.func, ast.Call):
                    pass
                for arg in list(node.args) + [kw.value for kw in node.keywords]:
                    for sub in ast.walk(arg):
                        if isinstance(sub, ast.Call):
                            owned.add(id(sub))
            if isinstance(node, ast.Return) and node.value is not None:
                for sub in ast.walk(node.value):
                    if isinstance(sub, ast.Call):
                        owned.add(id(sub))
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                tgt = node.targets[0]
                if isinstance(tgt, ast.Name):
                    for sub in ast.walk(node.value):
                        if isinstance(sub, ast.Call):
                            assigns[id(sub)] = tgt.id
            if isinstance(node, ast.Call) and self._is_opener(node):
                calls.append(node)

        closed_names = self._names_closed_or_withed(fn)
        for call in calls:
            if id(call) in with_exprs or id(call) in owned:
                continue
            name = assigns.get(id(call))
            if name is not None and name in closed_names:
                continue
            yield Finding(
                self.id,
                ctx.relpath,
                call.lineno,
                f"{_call_name(call)}(...) result is neither entered as "
                "`with` nor closed in a try/finally -- leaks the handle "
                "on the exception path",
            )

    def _is_opener(self, call: ast.Call) -> bool:
        return _call_name(call) in self._OPENERS

    @staticmethod
    def _names_closed_or_withed(fn) -> set[str]:
        """Names later entered as `with <name>:` anywhere in the function,
        or `.close()`d inside a `finally:` block."""
        names: set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    if isinstance(item.context_expr, ast.Name):
                        names.add(item.context_expr.id)
            if isinstance(node, ast.Try):
                for stmt in node.finalbody:
                    for sub in ast.walk(stmt):
                        if (
                            isinstance(sub, ast.Call)
                            and isinstance(sub.func, ast.Attribute)
                            and sub.func.attr == "close"
                            and isinstance(sub.func.value, ast.Name)
                        ):
                            names.add(sub.func.value.id)
        return names


# ---------------------------------------------------------------------------
# stage-key
# ---------------------------------------------------------------------------


class StageKeyRule(Rule):
    """Every literal stage mark must name a registered (layer, stage) key.

    control/perf.py declares STAGES (the literal registry) and
    DYNAMIC_STAGE_LAYERS (layers whose stage names are computed at runtime:
    per-peer endpoints, per-storage-API names). A mark outside both would
    silently mint a new unaggregated ledger series no dashboard knows about
    -- register it (and its dashboard row) or fix the typo."""

    id = "stage-key"
    title = "stage mark not registered in control/perf.py"
    scope = ("minio_tpu/",)

    def _load_registry(self, project):
        stages: set[tuple[str, str]] = set()
        dynamic: set[str] = set()
        ctx = project.get(PERF)
        if ctx is None:
            return None, None
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                tgt, value = node.targets[0], node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                tgt, value = node.target, node.value
            else:
                continue
            if not isinstance(tgt, ast.Name):
                continue
            if tgt.id == "STAGES":
                for sub in ast.walk(value):
                    if isinstance(sub, ast.Tuple) and len(sub.elts) == 2:
                        layer = _str_const(sub.elts[0])
                        stage = _str_const(sub.elts[1])
                        if layer is not None and stage is not None:
                            stages.add((layer, stage))
            elif tgt.id == "DYNAMIC_STAGE_LAYERS":
                for sub in ast.walk(value):
                    s = _str_const(sub)
                    if s is not None:
                        dynamic.add(s)
        return (stages or None), (dynamic or None)

    def check(self, project: ProjectContext):
        stages, dynamic = self._load_registry(project)
        if stages is None:
            ctx = project.get(PERF)
            if ctx is not None:
                yield Finding(
                    self.id, PERF, 1,
                    "STAGES registry literal not found in control/perf.py",
                )
            return
        dynamic = dynamic or set()
        layers = {l for l, _ in stages} | dynamic
        for ctx in project.iter_files("minio_tpu/"):
            if ctx.relpath in (PERF, "minio_tpu/control/tracing.py"):
                continue
            for node in ast.walk(ctx.tree):
                if not isinstance(node, ast.Call):
                    continue
                name = _call_name(node)
                if name.endswith("tracing.span") or name.endswith("tracing.root_span"):
                    if len(node.args) < 2:
                        continue
                    stage_arg, layer_arg = node.args[0], node.args[1]
                elif name.endswith("ledger.record"):
                    if len(node.args) < 2:
                        continue
                    layer_arg, stage_arg = node.args[0], node.args[1]
                else:
                    continue
                layer = _str_const(layer_arg)
                stage = _str_const(stage_arg)
                if layer is None:
                    continue  # computed layer: nothing checkable statically
                if stage is None:
                    if layer not in layers:
                        yield Finding(
                            self.id, ctx.relpath, node.lineno,
                            f"dynamic stage mark in unregistered layer "
                            f"{layer!r} -- add it to DYNAMIC_STAGE_LAYERS "
                            "in control/perf.py",
                        )
                elif (layer, stage) not in stages and layer not in dynamic:
                    yield Finding(
                        self.id, ctx.relpath, node.lineno,
                        f"stage key ({layer!r}, {stage!r}) not in the "
                        "control/perf.py STAGES registry",
                    )


# ---------------------------------------------------------------------------
# metrics-rendered
# ---------------------------------------------------------------------------


class MetricsRenderedRule(Rule):
    """Counters bumped in control/degrade.py and control/perf.py must be
    rendered by control/metrics.py.

    A counter nobody exports is a measurement nobody sees: the increment
    costs a lock on the hot path and buys zero observability. Every public
    `self.<name> += ...` / keyed-dict bump in DegradeStats and
    SlowRequestCapture must appear (as a string key or attribute) in the
    exposition renderer."""

    id = "metrics-rendered"
    title = "counter incremented but never rendered in control/metrics.py"
    scope = (DEGRADE, PERF)

    _COUNTER_CLASSES = {"DegradeStats", "SlowRequestCapture"}

    def _counters(self, ctx) -> list[tuple[str, int]]:
        out: list[tuple[str, int]] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            if node.name not in self._COUNTER_CLASSES:
                continue
            for sub in ast.walk(node):
                if not isinstance(sub, ast.AugAssign) or not isinstance(
                    sub.op, ast.Add
                ):
                    continue
                tgt = sub.target
                name = None
                if (
                    isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "self"
                ):
                    name = tgt.attr
                elif (
                    isinstance(tgt, ast.Subscript)
                    and isinstance(tgt.value, ast.Attribute)
                    and isinstance(tgt.value.value, ast.Name)
                    and tgt.value.value.id == "self"
                ):
                    name = tgt.value.attr
                if name and not name.startswith("_"):
                    out.append((name, sub.lineno))
        # keyed bumps written as self.d[k] = self.d.get(k, 0) + 1
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            tgt = node.targets[0]
            if (
                isinstance(tgt, ast.Subscript)
                and isinstance(tgt.value, ast.Attribute)
                and isinstance(tgt.value.value, ast.Name)
                and tgt.value.value.id == "self"
                and not tgt.value.attr.startswith("_")
                and isinstance(node.value, ast.BinOp)
                and isinstance(node.value.op, ast.Add)
            ):
                out.append((tgt.value.attr, node.lineno))
        return out

    @staticmethod
    def _rendered_tokens(metrics_ctx) -> set[str]:
        tokens: set[str] = set()
        for node in ast.walk(metrics_ctx.tree):
            if isinstance(node, ast.Constant) and isinstance(node.value, str):
                tokens.add(node.value)
            if isinstance(node, ast.Attribute):
                tokens.add(node.attr)
        return tokens

    def check(self, project: ProjectContext):
        metrics_ctx = project.get(METRICS)
        if metrics_ctx is None:
            return
        tokens = self._rendered_tokens(metrics_ctx)
        seen: set[str] = set()
        for relpath in self.scope:
            ctx = project.get(relpath)
            if ctx is None:
                continue
            for name, lineno in self._counters(ctx):
                if name in seen:
                    continue
                seen.add(name)
                if name not in tokens:
                    yield Finding(
                        self.id, ctx.relpath, lineno,
                        f"counter {name!r} is incremented here but "
                        "control/metrics.py never renders it",
                    )


# ---------------------------------------------------------------------------
# typed-errors
# ---------------------------------------------------------------------------


class TypedErrorsRule(Rule):
    """API handlers must raise typed errors, never `raise Exception(...)`.

    api/errors.py maps exception TYPES onto S3 wire codes; an untyped raise
    can only ever surface as a 500 InternalError with a leaked str(e). Use
    S3Error / utils.errors types so the client sees the right code."""

    id = "typed-errors"
    title = "untyped raise in an API module"
    scope = ("minio_tpu/api/",)

    _UNTYPED = {"Exception", "BaseException", "RuntimeError"}

    def check(self, project: ProjectContext):
        for ctx in project.iter_files(*self.scope):
            for node in ast.walk(ctx.tree):
                if not isinstance(node, ast.Raise) or node.exc is None:
                    continue
                exc = node.exc
                name = None
                if isinstance(exc, ast.Call) and isinstance(exc.func, ast.Name):
                    name = exc.func.id
                elif isinstance(exc, ast.Name):
                    name = exc.id
                if name in self._UNTYPED:
                    yield Finding(
                        self.id, ctx.relpath, node.lineno,
                        f"raise {name}(...) in an API module -- raise "
                        "S3Error or a typed utils.errors class so the "
                        "client sees a real S3 code",
                    )


# ---------------------------------------------------------------------------
# unlocked-global
# ---------------------------------------------------------------------------


class UnlockedGlobalRule(Rule):
    """Mutable module globals mutated outside a lock.

    A module-level dict/list/set written from request or worker threads
    without a lock is a check-then-act race (the `_HASH_SELECT` class of
    bug). Either guard every mutation with a module lock, or mark the
    binding `# mtpulint: immutable` when it is write-once at import time."""

    id = "unlocked-global"
    title = "mutable module global mutated without a lock"
    scope = ("minio_tpu/",)

    _MUTABLE_CTORS = {
        "dict", "list", "set", "OrderedDict", "defaultdict", "deque",
        "collections.OrderedDict", "collections.defaultdict",
        "collections.deque",
    }
    _MUTATORS = {
        "append", "add", "update", "pop", "popitem", "clear", "extend",
        "insert", "remove", "discard", "setdefault", "appendleft",
    }
    _LOCK_HINTS = ("lock", "mutex", "_mu", "sem")

    def _module_mutables(self, ctx) -> dict[str, int]:
        """Module-level `NAME = {}/[]/set()/...` bindings -> lineno."""
        out: dict[str, int] = {}
        body = getattr(ctx.tree, "body", [])
        for node in body:
            targets = []
            value = None
            if isinstance(node, ast.Assign):
                targets, value = node.targets, node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                targets, value = [node.target], node.value
            if value is None:
                continue
            mutable = isinstance(value, (ast.Dict, ast.List, ast.Set)) or (
                isinstance(value, ast.Call)
                and _call_name(value) in self._MUTABLE_CTORS
            )
            if not mutable:
                continue
            for tgt in targets:
                if isinstance(tgt, ast.Name) and not self._marked_immutable(
                    ctx, node.lineno
                ):
                    out[tgt.id] = node.lineno
        return out

    @staticmethod
    def _marked_immutable(ctx, lineno: int) -> bool:
        lines = ctx.lines
        if 1 <= lineno <= len(lines) and "immutable" in lines[lineno - 1]:
            return True
        return lineno >= 2 and "immutable" in lines[lineno - 2]

    def _is_lock_expr(self, expr: ast.AST) -> bool:
        name = ""
        if isinstance(expr, ast.Attribute):
            name = expr.attr
        elif isinstance(expr, ast.Name):
            name = expr.id
        elif isinstance(expr, ast.Subscript):
            return self._is_lock_expr(expr.value)
        low = name.lower()
        return any(h in low for h in self._LOCK_HINTS)

    def _mutation_at(self, node, names: set[str]):
        """(name, lineno) when THIS node (not its subtree) mutates a
        watched global: subscript assign/del/augassign, or a mutator-method
        call (`g.append(...)`, `g.setdefault(...)`, ...)."""
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            tgts = node.targets if isinstance(node, ast.Assign) else [node.target]
            for tgt in tgts:
                if (
                    isinstance(tgt, ast.Subscript)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id in names
                ):
                    return (tgt.value.id, node.lineno)
        if isinstance(node, ast.Delete):
            for tgt in node.targets:
                if (
                    isinstance(tgt, ast.Subscript)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id in names
                ):
                    return (tgt.value.id, node.lineno)
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in self._MUTATORS
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id in names
        ):
            return (node.func.value.id, node.lineno)
        return None

    def _mutations(self, fn, names: set[str]):
        """(name, lineno, locked) for every mutation of a watched global
        inside `fn`, where locked = lexically inside a `with <lock>:` body
        at any nesting depth. Each node is visited exactly once, carrying
        the innermost lock state down the tree."""

        def scan(node, locked: bool):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                body_locked = locked or any(
                    self._is_lock_expr(i.context_expr) for i in node.items
                )
                for item in node.items:
                    yield from scan(item.context_expr, locked)
                for child in node.body:
                    yield from scan(child, body_locked)
                return
            hit = self._mutation_at(node, names)
            if hit is not None:
                yield (*hit, locked)
            for child in ast.iter_child_nodes(node):
                yield from scan(child, locked)

        for stmt in fn.body:
            yield from scan(stmt, False)

    def check(self, project: ProjectContext):
        for ctx in project.iter_files(*self.scope):
            mutables = self._module_mutables(ctx)
            if not mutables:
                continue
            names = set(mutables)
            for node in ast.walk(ctx.tree):
                if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                for name, lineno, locked in self._mutations(node, names):
                    if not locked:
                        yield Finding(
                            self.id, ctx.relpath, lineno,
                            f"module global {name!r} mutated outside a "
                            "lock -- guard it, or mark the binding "
                            "`# mtpulint: immutable` if write-once",
                        )


ALL_RULES: list[Rule] = [
    SwallowedExceptRule(),
    RawTransportRule(),
    DeadlineRebindRule(),
    LockBlockingIORule(),
    ResourceLeakRule(),
    StageKeyRule(),
    MetricsRenderedRule(),
    TypedErrorsRule(),
    UnlockedGlobalRule(),
]

# deadline_lint.py's historical surface: the two rules that together are the
# old regex lint, runnable standalone by the shim and chaos_check.
DEADLINE_RULES: list[Rule] = [
    RawTransportRule(),
    DeadlineRebindRule(),
]
