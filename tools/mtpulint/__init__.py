"""mtpulint: AST-based project-invariant checker for minio_tpu.

The `go vet`/staticcheck analogue for this tree (the reference runs its
whole suite under vet + the race detector in CI; see docs/STATIC_ANALYSIS.md
for how mtpulint / race_gate / metrics_lint / chaos_check divide that
surface). Engine in engine.py, rules in rules.py, CLI in __main__.py:

    python -m tools.mtpulint minio_tpu/            # lint against the baseline
    python -m tools.mtpulint --no-baseline ...     # full scan, nothing hidden
    python -m tools.mtpulint --write-baseline ...  # regenerate the baseline
    python -m tools.mtpulint --list-rules
"""

from __future__ import annotations

import os

from .engine import (  # noqa: F401 - public surface
    Finding,
    ProjectContext,
    Rule,
    apply_baseline,
    build_project,
    format_baseline,
    load_baseline,
    run_rules,
)
from .rules import ALL_RULES, DEADLINE_RULES  # noqa: F401

REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
BASELINE_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)), "baseline.txt")


def lint_tree(
    root: str | None = None,
    paths: list[str] | None = None,
    rules: list[Rule] | None = None,
) -> list[Finding]:
    """One-call scan (no baseline applied): parse + run + suppressions."""
    project = build_project(root or REPO_ROOT, paths or ["minio_tpu"])
    return run_rules(project, rules if rules is not None else ALL_RULES)
