"""mtpulint CLI. Exit 0 = no findings beyond the committed baseline."""

from __future__ import annotations

import argparse
import sys

try:
    from . import (
        ALL_RULES,
        BASELINE_PATH,
        REPO_ROOT,
        apply_baseline,
        format_baseline,
        lint_tree,
        load_baseline,
    )
except ImportError:  # executed as a loose script: python tools/mtpulint/__main__.py
    import os

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    from mtpulint import (  # type: ignore[no-redef]
        ALL_RULES,
        BASELINE_PATH,
        REPO_ROOT,
        apply_baseline,
        format_baseline,
        lint_tree,
        load_baseline,
    )


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="mtpulint", description="AST-based project-invariant checker"
    )
    ap.add_argument("paths", nargs="*", default=["minio_tpu"],
                    help="files/dirs to lint (default: minio_tpu)")
    ap.add_argument("--root", default=REPO_ROOT,
                    help="project root (directory containing minio_tpu/)")
    ap.add_argument("--baseline", default=BASELINE_PATH,
                    help="baseline file of grandfathered findings")
    ap.add_argument("--no-baseline", action="store_true",
                    help="full scan: report every finding, grandfathered or not")
    ap.add_argument("--write-baseline", action="store_true",
                    help="rewrite the baseline from the current scan and exit 0")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule in ALL_RULES:
            print(f"{rule.id:18s} {rule.title}")
        return 0

    findings = lint_tree(args.root, args.paths or ["minio_tpu"])

    if args.write_baseline:
        header = (
            "# mtpulint baseline -- grandfathered findings (relpath::rule::count).\n"
            "# Shrink-only: fix a finding, delete its line. New code must be clean.\n"
            "# Regenerate: python -m tools.mtpulint --write-baseline"
        )
        with open(args.baseline, "w", encoding="utf-8") as f:
            f.write(format_baseline(findings, header))
        print(f"mtpulint: baseline written: {len(findings)} findings -> {args.baseline}")
        return 0

    if args.no_baseline:
        new, stale = findings, []
    else:
        new, stale = apply_baseline(findings, load_baseline(args.baseline))

    for f in new:
        print(f.render(), file=sys.stderr)
    for s in stale:
        print(f"mtpulint: stale baseline entry: {s}", file=sys.stderr)
    if new:
        print(
            f"mtpulint: {len(new)} finding(s) "
            f"({len(findings)} total, {len(findings) - len(new)} baselined)",
            file=sys.stderr,
        )
        return 1
    print(
        f"mtpulint: ok ({len(findings)} baselined finding(s) remain)"
        if findings
        else "mtpulint: ok (clean tree)"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
