#!/usr/bin/env python3
"""Diff two continuous-profiling captures: which frames got hotter?

Usage:
    python tools/profile_diff.py BEFORE AFTER [--top N] [--min-delta PCT]

BEFORE/AFTER are either flamegraph collapsed-stack text files (the
`/mtpu/admin/v1/profile?collapsed=1` download: one "role;file:fn;... count"
line per stack) or `/profile` JSON payloads (a node snapshot with
"windows", or a ?cluster=1 merge with a flat "stacks" map).

Counts are normalized to per-capture SHARES before diffing -- two captures
rarely cover the same wall time, so raw sample deltas would just measure
capture length. Output: the top regressed (share grew) and improved (share
shrank) stacks, with before/after shares side by side.

Exit 0 always (it's a lens, not a gate).
"""

from __future__ import annotations

import argparse
import json
import sys


def _shares(counts: dict[str, float]) -> dict[str, float]:
    total = sum(counts.values())
    if total <= 0:
        return {}
    return {k: v / total for k, v in counts.items()}


def _from_json(doc) -> dict[str, float] | None:
    """Stack counts from a /profile payload, or None if it isn't one."""
    if not isinstance(doc, dict):
        return None
    counts: dict[str, float] = {}
    if isinstance(doc.get("stacks"), dict):  # ?cluster=1 merge / summary-ish
        for k, v in doc["stacks"].items():
            counts[str(k)] = counts.get(str(k), 0.0) + float(v)
        return counts
    if isinstance(doc.get("windows"), list):  # node snapshot
        for w in doc["windows"]:
            for k, v in (w.get("stacks") or {}).items():
                counts[str(k)] = counts.get(str(k), 0.0) + float(v)
        return counts
    return None


def load_capture(path: str) -> dict[str, float]:
    """Collapsed-stack text OR /profile JSON -> {stack: samples}."""
    with open(path) as f:
        raw = f.read()
    stripped = raw.lstrip()
    if stripped.startswith("{"):
        counts = _from_json(json.loads(stripped))
        if counts is None:
            raise ValueError(f"{path}: JSON but not a /profile payload")
        return counts
    counts = {}
    for ln, line in enumerate(raw.splitlines(), 1):
        line = line.strip()
        if not line:
            continue
        stack, _, n = line.rpartition(" ")
        if not stack:
            raise ValueError(f"{path}:{ln}: not a 'stack count' line: {line!r}")
        try:
            counts[stack] = counts.get(stack, 0.0) + float(n)
        except ValueError:
            raise ValueError(f"{path}:{ln}: bad count {n!r}")
    return counts


def diff_captures(
    before: dict[str, float], after: dict[str, float], min_delta: float = 0.005
) -> list[dict]:
    """Per-stack share deltas, biggest absolute movement first."""
    sa, sb = _shares(before), _shares(after)
    rows = []
    for stack in set(sa) | set(sb):
        b, a = sa.get(stack, 0.0), sb.get(stack, 0.0)
        d = a - b
        if abs(d) < min_delta:
            continue
        rows.append(
            {
                "stack": stack,
                "before_share": round(b, 4),
                "after_share": round(a, 4),
                "delta": round(d, 4),
            }
        )
    rows.sort(key=lambda r: -abs(r["delta"]))
    return rows


def _fmt(rows: list[dict], top: int, sign: int) -> list[str]:
    out = []
    picked = [r for r in rows if (r["delta"] > 0) == (sign > 0)][:top]
    for r in picked:
        out.append(
            f"  {r['delta']:+7.2%}  {r['before_share']:6.2%} -> "
            f"{r['after_share']:6.2%}  {r['stack']}"
        )
    return out or ["  (none)"]


def main(argv: list[str]) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("before", help="collapsed-stack text or /profile JSON")
    ap.add_argument("after", help="collapsed-stack text or /profile JSON")
    ap.add_argument("--top", type=int, default=10, help="rows per direction")
    ap.add_argument(
        "--min-delta", type=float, default=0.005,
        help="ignore stacks whose share moved less than this fraction",
    )
    ap.add_argument("--json", action="store_true", help="emit the diff as JSON")
    args = ap.parse_args(argv)

    try:
        before = load_capture(args.before)
        after = load_capture(args.after)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"profile_diff: {e}", file=sys.stderr)
        return 2

    rows = diff_captures(before, after, min_delta=args.min_delta)
    if args.json:
        print(json.dumps({"diff": rows[: 2 * args.top]}, sort_keys=True))
        return 0
    print(
        f"profile_diff: {len(before)} stacks before, {len(after)} after, "
        f"{len(rows)} moved >= {args.min_delta:.1%}"
    )
    print("regressed (share grew):")
    print("\n".join(_fmt(rows, args.top, +1)))
    print("improved (share shrank):")
    print("\n".join(_fmt(rows, args.top, -1)))
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
