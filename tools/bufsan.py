"""bufsan driver: static + runtime buffer-lifetime scan, gated on findings.

The third leg of the correctness-tooling tripod (mtpulint: static project
invariants; mtpusan: runtime concurrency sanitizer; bufsan: buffer lifetime
on the zero-copy plane). This driver runs BOTH halves:

  1. the static half -- the mtpulint buffer rules (`release-on-all-paths`,
     `double-release`, `view-escape`, `interface-conformance`) over the
     tree, so an escape on a path the replay never exercises still gates;
  2. the runtime half -- loadgen scenario replays with ``MTPU_BUFSAN=1``
     (minio_tpu/control/bufsan.py): every acquisition site-tagged, free-list
     storage sentinel-poisoned and verified on reuse, live view exports
     probed at the last release, handles weakref-tracked for leaks. The
     full run replays ``put_scaling`` AND ``hot_get_storm`` (the PUT window
     pipeline and the GET shard-row fan-out are disjoint buffer planes);
     ``--smoke`` replays ``smoke`` only, fast enough for
     ``chaos_check --invariants``;
  3. merges every subprocess's ``MTPU_BUFSAN_OUT`` artifact, drops rows the
     in-code SUPPRESSIONS table already justified, applies the shrink-only
     baseline (``tools/bufsan_baseline.txt``, site::rule::count -- kept
     EMPTY: every true positive gets fixed, not grandfathered), and fails
     on anything left.

    python tools/bufsan.py                  # static + both replays, gate
    python tools/bufsan.py --smoke          # static + smoke replay only
    python tools/bufsan.py --static-only
    python tools/bufsan.py --scenarios-only
    python tools/bufsan.py --out /tmp/bufsan.json     # merged report JSON
    python tools/bufsan.py --write-baseline           # grandfather (shrink-only)
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time

_HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(_HERE)
sys.path.insert(0, _HERE)
sys.path.insert(0, ROOT)

from mtpulint.engine import (  # noqa: E402
    Finding,
    apply_baseline,
    build_project,
    format_baseline,
    load_baseline,
    run_rules,
)
from mtpulint.rules import (  # noqa: E402
    DoubleReleaseRule,
    InterfaceConformanceRule,
    ReleaseOnAllPathsRule,
    ViewEscapeRule,
)

BASELINE_PATH = os.path.join(_HERE, "bufsan_baseline.txt")
FULL_SCENARIOS = ("put_scaling", "hot_get_storm")
SMOKE_SCENARIOS = ("smoke",)
TIMEOUT_S = int(os.environ.get("BUFSAN_TIMEOUT_S", "1200"))

BUFFER_RULES = [
    ReleaseOnAllPathsRule(),
    DoubleReleaseRule(),
    ViewEscapeRule(),
    InterfaceConformanceRule(),
]


def _read_report(path: str) -> dict | None:
    try:
        with open(path, encoding="utf-8") as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def run_static(reports: list[dict]) -> int:
    """The four buffer rules over minio_tpu, reported in the same shape as
    a runtime artifact so one merge/gate handles both halves. Inline
    mtpulint suppressions already filtered these; anything left is real
    (or belongs in the shrink-only baseline, which stays empty)."""
    project = build_project(ROOT, ["minio_tpu"])
    findings = [
        {"rule": f.rule, "site": f"{f.relpath}:{f.line}", "message": f.message}
        for f in run_rules(project, BUFFER_RULES)
    ]
    reports.append({"source": "static", "findings": findings})
    print(f"[bufsan] static scan: {len(project.files)} file(s), "
          f"{len(findings)} finding(s)")
    return 0


def run_scenario(name: str, reports: list[dict]) -> int:
    """One loadgen replay with the runtime sanitizer armed."""
    scen = os.path.join(ROOT, "scenarios", f"{name}.yaml")
    if not os.path.exists(scen):
        print(f"[bufsan] scenario not found: {scen}", file=sys.stderr)
        return 2
    print(f"[bufsan] sanitized scenario replay: {name}")
    with tempfile.NamedTemporaryFile(suffix=".json", delete=False) as tf:
        out = tf.name
    report_path = os.path.join(tempfile.gettempdir(), f"bufsan_{name}.json")
    env = dict(os.environ, MTPU_BUFSAN="1", MTPU_BUFSAN_OUT=out)
    try:
        t0 = time.time()
        proc = subprocess.run(
            [sys.executable, os.path.join(_HERE, "loadgen.py"), scen,
             "--out", report_path],
            cwd=ROOT, env=env, timeout=TIMEOUT_S,
        )
        rep = _read_report(out)
        if rep is not None:
            rep["source"] = f"scenario:{name}"
            reports.append(rep)
        counters = (rep or {}).get("counters") or {}
        print(f"[bufsan] scenario {name}: rc={proc.returncode} "
              f"({time.time() - t0:.0f}s, "
              f"{counters.get('acquires', '?')} acquire(s), "
              f"{counters.get('sentinel_checks', '?')} sentinel check(s), "
              f"{len((rep or {}).get('findings', []))} raw finding(s))")
        if rep is None:
            print(f"[bufsan] scenario {name}: no sanitizer artifact -- "
                  "the armed run died before atexit", file=sys.stderr)
            return max(proc.returncode, 1)
        # The scenario's SLO verdict is tools/perf_gate.py's business; only
        # lifetime findings gate here, so a perf regression cannot mask (or
        # be masked by) a buffer bug.
        return 0 if proc.returncode in (0, 1) else proc.returncode
    except subprocess.TimeoutExpired:
        print(f"[bufsan] scenario {name}: timed out after {TIMEOUT_S}s",
              file=sys.stderr)
        return 1
    finally:
        try:
            os.unlink(out)
        except OSError:
            pass


def merge_findings(reports: list[dict]) -> tuple[list[dict], list[dict]]:
    """(unsuppressed, suppressed) across runs, deduped by (rule, site)."""
    seen: set[tuple[str, str]] = set()
    unsup: list[dict] = []
    sup: list[dict] = []
    for rep in reports:
        for f in rep.get("findings", []):
            key = (f.get("rule", "?"), f.get("site", "?"))
            if key in seen:
                continue
            seen.add(key)
            f = dict(f, source=rep.get("source", "?"))
            (sup if "suppressed" in f else unsup).append(f)
    return unsup, sup


def gate(unsup: list[dict], baseline_path: str, write: bool) -> int:
    """Apply the shrink-only baseline; 0 iff nothing new."""
    as_findings = [
        Finding(f["rule"], f["site"], 0, f.get("message", "")) for f in unsup
    ]
    if write:
        header = (
            "# bufsan baseline -- grandfathered buffer-lifetime findings\n"
            "# (site::rule::count). Shrink-only, and kept EMPTY on purpose:\n"
            "# a buffer-lifetime finding is a data-corruption class, fix it\n"
            "# in the same PR. Regenerate: python tools/bufsan.py --write-baseline"
        )
        with open(baseline_path, "w", encoding="utf-8") as f:
            f.write(format_baseline(as_findings, header))
        print(f"[bufsan] baseline written: {len(as_findings)} finding(s) "
              f"-> {baseline_path}")
        return 0
    new, stale = apply_baseline(as_findings, load_baseline(baseline_path))
    for f in new:
        print(f"[bufsan] FINDING {f.rule} @ {f.relpath}: {f.message}",
              file=sys.stderr)
    for s in stale:
        print(f"[bufsan] stale baseline entry: {s}", file=sys.stderr)
    if new:
        print(f"[bufsan] {len(new)} unsuppressed finding(s)", file=sys.stderr)
        return 1
    return 0


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="bufsan", description="buffer-lifetime sanitizer driver"
    )
    ap.add_argument("--smoke", action="store_true",
                    help="fast gate: static rules + the smoke scenario only")
    ap.add_argument("--static-only", action="store_true")
    ap.add_argument("--scenarios-only", action="store_true")
    ap.add_argument("--baseline", default=BASELINE_PATH)
    ap.add_argument("--write-baseline", action="store_true",
                    help="grandfather current findings (shrink-only) and exit 0")
    ap.add_argument("--out", default=None,
                    help="write the merged bufsan report JSON here")
    args = ap.parse_args(argv)

    reports: list[dict] = []
    rc = 0
    if not args.scenarios_only:
        rc = max(rc, run_static(reports))
    if not args.static_only:
        names = SMOKE_SCENARIOS if args.smoke else FULL_SCENARIOS
        for name in names:
            rc = max(rc, run_scenario(name, reports))

    unsup, sup = merge_findings(reports)
    for f in sup:
        print(f"[bufsan] suppressed: {f['rule']} @ {f['site']} "
              f"({f['suppressed']})")
    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            json.dump(
                {"bufsan": 1, "findings": unsup, "suppressed": sup,
                 "runs": len(reports)},
                f, indent=2, sort_keys=True,
            )
        print(f"[bufsan] merged report: {args.out}")
    gate_rc = gate(unsup, args.baseline, args.write_baseline)
    rc = max(rc, gate_rc)
    print(f"[bufsan] {'PASS' if rc == 0 else 'FAIL'}")
    return rc


if __name__ == "__main__":
    sys.exit(main())
