"""Deadline-propagation lint -- thin shim over tools/mtpulint.

The budget must ride EVERY internode hop: one module issuing raw HTTP, or
one REST server not re-binding X-Mtpu-Deadline, silently re-introduces the
unbounded hop the deadline exists to prevent. The checks now live as real
AST rules in tools/mtpulint/rules.py (`raw-transport`, `deadline-rebind`);
this module keeps the historical `lint() -> list[str]` / `main()` surface
so tools/chaos_check.py and tests/test_degradation.py keep working:

  * dist/transport.py (the single RPC seam) still checks the remaining
    budget, stamps DEADLINE_HEADER on outgoing requests, and raises
    DeadlineExceeded when the budget is spent.
  * Every REST *server* module (authenticates TOKEN_HEADER on inbound
    requests) re-binds the propagated budget with deadline.bind_header.
  * No dist/storage/object module other than transport.py talks
    `requests.`/`socket.` directly: RPCs ride RestClient.call.

    python tools/deadline_lint.py          # lint the tree, exit 1 on violations
    python -m tools.mtpulint minio_tpu     # the full rule set, same engine
"""

from __future__ import annotations

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    # Loaded by file path (importlib in tests, chaos_check, direct script
    # execution): make the `tools.mtpulint` package importable either way.
    sys.path.insert(0, REPO)

from tools.mtpulint import DEADLINE_RULES, lint_tree  # noqa: E402


def lint() -> list[str]:
    """Deadline-invariant findings as display strings ([] = clean)."""
    return [f.render() for f in lint_tree(REPO, ["minio_tpu"], DEADLINE_RULES)]


def main() -> int:
    problems = lint()
    for p in problems:
        print(f"deadline_lint: {p}", file=sys.stderr)
    if not problems:
        print("deadline_lint: ok")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
