"""Deadline-propagation lint: the budget must ride EVERY internode hop.

End-to-end deadlines (minio_tpu/utils/deadline.py) only work if no RPC path
forgets the plumbing: one module issuing raw HTTP, or one REST server not
re-binding the X-Mtpu-Deadline header, silently re-introduces the unbounded
hop the budget exists to prevent. This lint enforces the three structural
invariants statically, so a refactor that drops the plumbing fails CI
instead of failing a production deadline:

  1. dist/transport.py (the single RPC seam) still checks the remaining
     budget, caps the socket timeout with it, and stamps DEADLINE_HEADER
     on outgoing requests.
  2. Every dist/ REST *server* (a module that authenticates TOKEN_HEADER
     on inbound requests) re-binds the propagated budget with
     deadline.bind_header -- a hop that drops the header resets the
     budget to infinity for everything downstream.
  3. No dist/ module other than transport.py talks `requests.` directly:
     all RPCs must ride RestClient.call, where the deadline is enforced.

    python tools/deadline_lint.py          # lint the tree, exit 1 on violations

Run by tools/chaos_check.py and wired into tier-1 via tests/test_degradation.py.
"""

from __future__ import annotations

import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DIST = os.path.join(REPO, "minio_tpu", "dist")

# transport.py must keep these markers (invariant 1).
TRANSPORT_MARKERS = [
    ("deadline.remaining()", "budget check before each hop"),
    ("DEADLINE_HEADER", "deadline header stamped on outgoing RPCs"),
    ("DeadlineExceeded", "expired budget surfaces as the typed error"),
]

# Inbound-auth marker: a module matching this hosts a REST server.
_SERVER_RE = re.compile(r"request\.headers\.get\(TOKEN_HEADER")
_BIND_RE = re.compile(r"deadline\.bind_header\(")
_RAW_REQUESTS_RE = re.compile(r"^\s*(?:import requests|from requests)|[^.\w]requests\.(?:get|post|put|delete|request|Session)\(", re.M)


def lint() -> list[str]:
    problems: list[str] = []

    transport = os.path.join(DIST, "transport.py")
    with open(transport, encoding="utf-8") as f:
        tsrc = f.read()
    for marker, why in TRANSPORT_MARKERS:
        if marker not in tsrc:
            problems.append(f"dist/transport.py: missing `{marker}` ({why})")

    for name in sorted(os.listdir(DIST)):
        if not name.endswith(".py") or name == "transport.py":
            continue
        path = os.path.join(DIST, name)
        with open(path, encoding="utf-8") as f:
            src = f.read()
        if _SERVER_RE.search(src) and not _BIND_RE.search(src):
            problems.append(
                f"dist/{name}: authenticates TOKEN_HEADER but never calls "
                "deadline.bind_header -- inbound budgets are dropped here"
            )
        if _RAW_REQUESTS_RE.search(src):
            problems.append(
                f"dist/{name}: raw `requests` usage -- RPCs must ride "
                "RestClient.call so the deadline caps the socket timeout"
            )
    return problems


def main() -> int:
    problems = lint()
    for p in problems:
        print(f"deadline_lint: {p}", file=sys.stderr)
    if not problems:
        print("deadline_lint: ok")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
