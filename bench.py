"""Benchmark: erasure codec throughput, 12+4 @ 1 MiB blocks (BASELINE.md).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.

  value       = device Reed-Solomon encode GiB/s over a BATCH-block batch,
                data-bytes counted (the reference benchmark convention,
                cmd/erasure-encode_test.go b.SetBytes).
  vs_baseline = value / CPU-AVX2 GiB/s measured on this machine with the
                native C++ kernel (native/minio_native.cpp) across all cores
                -- the stand-in for klauspost/reedsolomon's AVX2 path, same
                nibble-table algorithm the Go assembly uses.

Extra fields carry the secondary BASELINE configs: fused encode+hash,
decode/reconstruct with 4 missing data shards (BASELINE.md #2), and the CPU
numbers each is measured against.

If device init fails or wedges (tunnel flake), the line reports the CPU
numbers honestly: "device": false, vs_baseline 0.0 -- a fallback is not
parity -- plus a "probe_error" diagnostic: the probe child's captured
stdout/stderr tail (relay-port TCP reachability, faulthandler dump of the
wedged stack). One long bounded probe attempt (default 600 s -- a cold
tunnel may just be slow); the in-process run sits under a watchdog alarm.

Run directly on the bench machine: python bench.py
"""

from __future__ import annotations

import json
import os
import signal
import sys
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np

K, M = 12, 4
BLOCK = int(os.environ.get("BENCH_BLOCK", str(1 << 20)))
# Aggregate throughput batch: 512 x 1 MiB blocks in flight (the batching
# runtime's cross-upload fan-in, SURVEY.md section 7 step 2). Dispatch
# overhead dominates small batches.
BATCH = int(os.environ.get("BENCH_BATCH", "512"))
SHARD = -(-BLOCK // K)
ITERS = 16
PROBE_TIMEOUT_S = int(os.environ.get("BENCH_PROBE_TIMEOUT_S", "600"))

# 4 missing data shards: rows 0..3 lost, rebuilt from shards 4..15.
MISSING = (0, 1, 2, 3)
PRESENT = tuple(i not in MISSING for i in range(K + M))


def cpu_encode_gibs(blocks: np.ndarray) -> float:
    """Multi-core AVX2 encode throughput (data GiB/s)."""
    from minio_tpu.ops import native, rs_matrix

    if not native.available():
        return 0.0
    pm = np.ascontiguousarray(rs_matrix.parity_matrix(K, M))
    pool = ThreadPoolExecutor(max_workers=os.cpu_count() or 1)

    def enc(i):
        native.rs_encode(blocks[i], pm)

    list(pool.map(enc, range(len(blocks))))  # warmup
    t0 = time.perf_counter()
    n_iters = max(4, ITERS // 2)
    for _ in range(n_iters):
        list(pool.map(enc, range(len(blocks))))
    dt = time.perf_counter() - t0
    return len(blocks) * BLOCK * n_iters / dt / (1 << 30)


def cpu_decode_gibs(blocks: np.ndarray) -> float:
    """Multi-core reconstruct-4-missing throughput (data GiB/s)."""
    from minio_tpu.ops import native, rs_matrix

    if not native.available():
        return 0.0
    coeffs = np.ascontiguousarray(rs_matrix.reconstruct_rows(K, M, PRESENT, MISSING))
    # Survivors: first K present rows of the encoded block.
    pm = np.ascontiguousarray(rs_matrix.parity_matrix(K, M))
    surv = []
    for i in range(len(blocks)):
        full = np.concatenate([blocks[i], native.rs_encode(blocks[i], pm)], axis=0)
        surv.append(np.ascontiguousarray(full[[j for j in range(K + M) if PRESENT[j]][:K]]))
    pool = ThreadPoolExecutor(max_workers=os.cpu_count() or 1)

    def rec(i):
        native.rs_apply(surv[i], coeffs)

    list(pool.map(rec, range(len(blocks))))  # warmup
    t0 = time.perf_counter()
    n_iters = max(4, ITERS // 2)
    for _ in range(n_iters):
        list(pool.map(rec, range(len(blocks))))
    dt = time.perf_counter() - t0
    return len(blocks) * BLOCK * n_iters / dt / (1 << 30)


FUSED_BATCH = 64  # the fused encode+hash probe stays at the hash's sweet spot


def device_metrics() -> dict:
    """Encode / fused encode+hash / reconstruct GiB/s on the live device."""
    import jax
    import jax.numpy as jnp

    from minio_tpu.ops import rs
    from minio_tpu.ops import highwayhash_jax as hhj

    platform = jax.devices()[0].platform
    rng = np.random.default_rng(0)
    data = rng.integers(0, 256, (BATCH, K, SHARD), dtype=np.uint8)
    dev = jax.device_put(jnp.asarray(data))

    codec = rs.RSCodec(K, M)

    @jax.jit
    def encode_only(x):
        return codec.encode(x)

    @jax.jit
    def fused(x):
        shards = codec.encode_all(x)
        return shards, hhj.hash256_batch(shards)

    encode_only(dev).block_until_ready()
    t0 = time.perf_counter()
    for _ in range(ITERS):
        out = encode_only(dev)
    out.block_until_ready()
    enc_gibs = BATCH * BLOCK * ITERS / (time.perf_counter() - t0) / (1 << 30)

    # Reconstruct 4 missing data shards from the 12 surviving rows.
    w = codec.reconstruct_weights(PRESENT, MISSING)
    full = np.asarray(codec.encode_all(dev))
    surv = jnp.asarray(full[:, [j for j in range(K + M) if PRESENT[j]][:K], :])
    recon = jax.jit(lambda s: codec.apply(s, w))
    recon(surv).block_until_ready()
    t0 = time.perf_counter()
    for _ in range(ITERS):
        out = recon(surv)
    out.block_until_ready()
    dec_gibs = BATCH * BLOCK * ITERS / (time.perf_counter() - t0) / (1 << 30)

    fdev = jax.device_put(jnp.asarray(data[:FUSED_BATCH]))
    jax.block_until_ready(fused(fdev))
    fiters = max(4, ITERS // 2)
    t0 = time.perf_counter()
    for _ in range(fiters):
        r = fused(fdev)
    jax.block_until_ready(r)
    fused_gibs = FUSED_BATCH * BLOCK * fiters / (time.perf_counter() - t0) / (1 << 30)

    # Fused Pallas kernel (ops/rs_pallas.py): VMEM-resident bit expansion.
    # Never let a Mosaic regression break the bench line — but a 0.0 must
    # carry its cause (pallas_error), not masquerade as "not measured".
    pallas_gibs = 0.0
    pallas_error = ""
    try:
        from minio_tpu.ops.rs_pallas import RSPallasCodec

        pcodec = RSPallasCodec(K, M)
        penc = jax.jit(pcodec.encode)
        penc(dev).block_until_ready()
        t0 = time.perf_counter()
        for _ in range(ITERS):
            out = penc(dev)
        out.block_until_ready()
        pallas_gibs = BATCH * BLOCK * ITERS / (time.perf_counter() - t0) / (1 << 30)
    except Exception as e:  # noqa: BLE001
        pallas_error = f"{type(e).__name__}: {e}"[:500]
    return {
        "platform": platform,
        "encode_gibs": enc_gibs,
        "decode_recon4_gibs": dec_gibs,
        "fused_encode_hash_gibs": fused_gibs,
        "pallas_encode_gibs": pallas_gibs,
        "pallas_error": pallas_error,
    }


def emit(payload: dict) -> None:
    print(json.dumps(payload))


def fallback_line(cpu_enc: float, cpu_dec: float, reason: str, probe=None) -> dict:
    line = {
        "metric": f"erasure-encode GiB/s (12+4 @ 1MiB, CPU fallback: {reason})",
        "value": round(cpu_enc, 3),
        "unit": "GiB/s",
        "vs_baseline": 0.0,
        "device": False,
        "cpu_avx2_gibs": round(cpu_enc, 3),
        "cpu_decode_recon4_gibs": round(cpu_dec, 3),
    }
    if probe is not None:
        # The whole point of the diagnostic probe: a timeout carries the
        # child's relay-reachability lines + faulthandler dump, not nothing.
        line["probe_error"] = probe.error or ""
        line["probe_detail"] = probe.detail[-3000:]
    return line


def main() -> None:
    from minio_tpu.runtime import probe_device

    # Launch the bounded probe child first (it mostly blocks on the tunnel,
    # not the CPU), overlap the CPU baselines with it, then join.
    probe_box: dict = {}

    def _probe():
        probe_box["r"] = probe_device(PROBE_TIMEOUT_S)

    pt = ThreadPoolExecutor(max_workers=1).submit(_probe)

    rng = np.random.default_rng(1)
    blocks = rng.integers(0, 256, (BATCH, K, SHARD), dtype=np.uint8)
    cpu_enc = cpu_encode_gibs(blocks)
    cpu_dec = cpu_decode_gibs(blocks[: max(32, BATCH // 8)])

    pt.result()
    probe = probe_box["r"]
    if not probe.ok:
        reason = (
            "no accelerator (cpu-only jax)" if probe.platform == "cpu"
            else probe.error or "device probe failed"
        )
        emit(fallback_line(cpu_enc, cpu_dec, reason, probe))
        return

    # Watchdog: if the in-process run wedges anyway, still print a line.
    def on_timeout(signum, frame):
        emit(fallback_line(cpu_enc, cpu_dec, "device run watchdog timeout"))
        os._exit(0)

    signal.signal(signal.SIGALRM, on_timeout)
    signal.alarm(900)
    try:
        dm = device_metrics()
    except Exception as e:  # noqa: BLE001 - report, never crash the driver
        signal.alarm(0)
        emit(fallback_line(cpu_enc, cpu_dec, f"device run failed: {type(e).__name__}"))
        return
    finally:
        signal.alarm(0)

    enc = dm["encode_gibs"]
    emit(
        {
            "metric": f"erasure-encode GiB/s (12+4 @ 1MiB, batch {BATCH}, {dm['platform']})",
            "value": round(enc, 3),
            "unit": "GiB/s",
            "vs_baseline": round(enc / cpu_enc, 3) if cpu_enc else 0.0,
            "device": dm["platform"] != "cpu",
            "cpu_avx2_gibs": round(cpu_enc, 3),
            "fused_encode_hash_gibs": round(dm["fused_encode_hash_gibs"], 3),
            "pallas_encode_gibs": round(dm.get("pallas_encode_gibs", 0.0), 3),
            "pallas_error": dm.get("pallas_error", ""),
            "decode_recon4_gibs": round(dm["decode_recon4_gibs"], 3),
            "cpu_decode_recon4_gibs": round(cpu_dec, 3),
            "decode_vs_baseline": (
                round(dm["decode_recon4_gibs"] / cpu_dec, 3) if cpu_dec else 0.0
            ),
        }
    )


if __name__ == "__main__":
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    main()
