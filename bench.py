"""Benchmark: erasure-encode throughput, 12+4 @ 1 MiB blocks (BASELINE.md #1).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.

  value       = device (TPU) Reed-Solomon encode GiB/s over a BATCH-block batch,
                data-bytes counted (the reference benchmark convention,
                cmd/erasure-encode_test.go b.SetBytes).
  vs_baseline = value / CPU-AVX2 GiB/s measured on this machine with the
                native C++ kernel (native/minio_native.cpp) across all cores
                -- the stand-in for klauspost/reedsolomon's AVX2 path, same
                nibble-table algorithm the Go assembly uses.

Run directly on the bench machine: python bench.py
"""

from __future__ import annotations

import json
import os
import signal
import sys
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np

K, M = 12, 4
BLOCK = 1 << 20
# Aggregate throughput batch: 512 x 1 MiB blocks in flight (the batching
# runtime's cross-upload fan-in, SURVEY.md section 7 step 2). Dispatch
# overhead dominates small batches: 64 -> ~12 GiB/s, 512 -> ~45 GiB/s.
BATCH = 512
SHARD = -(-BLOCK // K)
ITERS = 16


def cpu_baseline_gibs(blocks: np.ndarray) -> float:
    """Multi-core AVX2 encode throughput (data GiB/s)."""
    from minio_tpu.ops import native, rs_matrix

    if not native.available():
        return 0.0
    pm = np.ascontiguousarray(rs_matrix.parity_matrix(K, M))
    nproc = os.cpu_count() or 1
    pool = ThreadPoolExecutor(max_workers=nproc)

    def enc(i):
        native.rs_encode(blocks[i], pm)

    # Warmup.
    list(pool.map(enc, range(len(blocks))))
    t0 = time.perf_counter()
    n_iters = max(4, ITERS // 2)
    for _ in range(n_iters):
        list(pool.map(enc, range(len(blocks))))
    dt = time.perf_counter() - t0
    return len(blocks) * BLOCK * n_iters / dt / (1 << 30)


FUSED_BATCH = 64  # the fused encode+hash probe stays at the hash's sweet spot


def device_gibs() -> tuple[float, float, str]:
    """(encode_gibs, fused_encode_hash_gibs, platform)."""
    import jax
    import jax.numpy as jnp

    from minio_tpu.ops import rs
    from minio_tpu.ops import highwayhash_jax as hhj

    platform = jax.devices()[0].platform
    rng = np.random.default_rng(0)
    data = rng.integers(0, 256, (BATCH, K, SHARD), dtype=np.uint8)
    dev = jax.device_put(jnp.asarray(data))

    codec = rs.RSCodec(K, M)

    @jax.jit
    def encode_only(x):
        return codec.encode(x)

    @jax.jit
    def fused(x):
        shards = codec.encode_all(x)
        return shards, hhj.hash256_batch(shards)

    encode_only(dev).block_until_ready()
    t0 = time.perf_counter()
    for _ in range(ITERS):
        out = encode_only(dev)
    out.block_until_ready()
    enc_gibs = BATCH * BLOCK * ITERS / (time.perf_counter() - t0) / (1 << 30)

    fdev = jax.device_put(jnp.asarray(data[:FUSED_BATCH]))
    r = fused(fdev)
    jax.block_until_ready(r)
    fiters = max(4, ITERS // 2)
    t0 = time.perf_counter()
    for _ in range(fiters):
        r = fused(fdev)
    jax.block_until_ready(r)
    fused_gibs = FUSED_BATCH * BLOCK * fiters / (time.perf_counter() - t0) / (1 << 30)
    return enc_gibs, fused_gibs, platform


def main() -> None:
    rng = np.random.default_rng(1)
    blocks = rng.integers(0, 256, (BATCH, K, SHARD), dtype=np.uint8)
    cpu = cpu_baseline_gibs(blocks)

    # Watchdog: if device init wedges (tunnel flake), still print a line.
    def on_timeout(signum, frame):
        print(
            json.dumps(
                {
                    "metric": "erasure-encode GiB/s (12+4 @ 1MiB, CPU fallback: device init timeout)",
                    "value": round(cpu, 3),
                    "unit": "GiB/s",
                    "vs_baseline": 1.0,
                }
            )
        )
        os._exit(0)

    signal.signal(signal.SIGALRM, on_timeout)
    signal.alarm(600)
    try:
        enc, fused, platform = device_gibs()
    finally:
        signal.alarm(0)

    print(
        json.dumps(
            {
                "metric": f"erasure-encode GiB/s (12+4 @ 1MiB, batch {BATCH}, {platform})",
                "value": round(enc, 3),
                "unit": "GiB/s",
                "vs_baseline": round(enc / cpu, 3) if cpu else 0.0,
                "cpu_avx2_gibs": round(cpu, 3),
                "fused_encode_hash_gibs": round(fused, 3),
            }
        )
    )


if __name__ == "__main__":
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    main()
