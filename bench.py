"""Benchmark: erasure codec throughput, 12+4 @ 1 MiB blocks (BASELINE.md).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.

  value       = device Reed-Solomon encode GiB/s over a BATCH-block batch,
                data-bytes counted (the reference benchmark convention,
                cmd/erasure-encode_test.go b.SetBytes).
  vs_baseline = value / CPU-AVX2 GiB/s measured on this machine with the
                native C++ kernel (native/minio_native.cpp) across all cores
                -- the stand-in for klauspost/reedsolomon's AVX2 path, same
                nibble-table algorithm the Go assembly uses.

Extra fields carry the secondary BASELINE configs: fused encode+hash,
decode/reconstruct with 4 missing data shards (BASELINE.md #2), and the CPU
numbers each is measured against.

If device init fails or wedges (tunnel flake), the line reports the CPU
numbers honestly: "device": false, vs_baseline 0.0 -- a fallback is not
parity -- plus a "probe_error" field; the probe child's captured
stdout/stderr (relay-port TCP reachability, faulthandler dump of the
wedged stack) goes to the BENCH_probe_detail.txt sidecar so the final
line stays one parseable JSON object. One bounded probe attempt (default
180 s: a healthy tunnel inits in 20-40 s, a wedged relay never answers
late -- raise BENCH_PROBE_TIMEOUT_S if a genuinely cold tunnel needs it);
the in-process run sits under a watchdog alarm.

Run directly on the bench machine: python bench.py
"""

from __future__ import annotations

import json
import os
import signal
import sys
import tempfile
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np

K, M = 12, 4
BLOCK = int(os.environ.get("BENCH_BLOCK", str(1 << 20)))
# Aggregate throughput batch: 512 x 1 MiB blocks in flight (the batching
# runtime's cross-upload fan-in, SURVEY.md section 7 step 2). Dispatch
# overhead dominates small batches.
BATCH = int(os.environ.get("BENCH_BATCH", "512"))
SHARD = -(-BLOCK // K)
ITERS = 16
# 180 s: a healthy tunnel inits in 20-40 s; a wedged relay hangs forever (it
# has never been observed to answer late), so a longer wait only stalls the
# driver — round 4 burned 8.5 min against a refused relay at the old 600 s.
PROBE_TIMEOUT_S = int(os.environ.get("BENCH_PROBE_TIMEOUT_S", "180"))

# 4 missing data shards: rows 0..3 lost, rebuilt from shards 4..15.
MISSING = (0, 1, 2, 3)
PRESENT = tuple(i not in MISSING for i in range(K + M))


def cpu_encode_gibs(blocks: np.ndarray) -> float:
    """Multi-core AVX2 encode throughput (data GiB/s)."""
    from minio_tpu.ops import native, rs_matrix

    if not native.available():
        return 0.0
    pm = np.ascontiguousarray(rs_matrix.parity_matrix(K, M))
    pool = ThreadPoolExecutor(max_workers=os.cpu_count() or 1)

    def enc(i):
        native.rs_encode(blocks[i], pm)

    list(pool.map(enc, range(len(blocks))))  # warmup
    t0 = time.perf_counter()
    n_iters = max(4, ITERS // 2)
    for _ in range(n_iters):
        list(pool.map(enc, range(len(blocks))))
    dt = time.perf_counter() - t0
    return len(blocks) * BLOCK * n_iters / dt / (1 << 30)


def cpu_decode_gibs(blocks: np.ndarray) -> float:
    """Multi-core reconstruct-4-missing throughput (data GiB/s)."""
    from minio_tpu.ops import native, rs_matrix

    if not native.available():
        return 0.0
    coeffs = np.ascontiguousarray(rs_matrix.reconstruct_rows(K, M, PRESENT, MISSING))
    # Survivors: first K present rows of the encoded block.
    pm = np.ascontiguousarray(rs_matrix.parity_matrix(K, M))
    surv = []
    for i in range(len(blocks)):
        full = np.concatenate([blocks[i], native.rs_encode(blocks[i], pm)], axis=0)
        surv.append(np.ascontiguousarray(full[[j for j in range(K + M) if PRESENT[j]][:K]]))
    pool = ThreadPoolExecutor(max_workers=os.cpu_count() or 1)

    def rec(i):
        native.rs_apply(surv[i], coeffs)

    list(pool.map(rec, range(len(blocks))))  # warmup
    t0 = time.perf_counter()
    n_iters = max(4, ITERS // 2)
    for _ in range(n_iters):
        list(pool.map(rec, range(len(blocks))))
    dt = time.perf_counter() - t0
    return len(blocks) * BLOCK * n_iters / dt / (1 << 30)


FUSED_BATCH = 64  # the fused encode+hash probe stays at the hash's sweet spot

# Object-layer end-to-end benches (BASELINE.md configs #4 and #5). Sizes are
# env-tunable so constrained bench machines can shrink them; defaults keep
# the full run under a few minutes on local disk.
PUT_OBJECTS = int(os.environ.get("BENCH_PUT_OBJECTS", "32"))
PUT_SIZE = int(os.environ.get("BENCH_PUT_SIZE", str(128 << 20)))  # 128 MiB
HEAL_BYTES = int(os.environ.get("BENCH_HEAL_GB", "10")) << 30
CONCURRENT_PUTS = 8
CONCURRENT_SIZE = 16 << 20


def _stage_breakdown(
    snap: dict,
    phase: str,
    leaves: tuple[str, ...],
    nested: tuple[str, ...] = (),
    aliases: dict[str, str] | None = None,
) -> dict:
    """Per-stage share of a bench phase from a perf-ledger snapshot.

    `leaves` are DISJOINT object-layer stages; "other" is the end-to-end
    root-span total minus the leaf sums, so the stage sums equal the
    measured end-to-end time by construction (an honest remainder, not a
    fudge factor -- it is the unattributed pipeline cost the ISSUE wants
    localized).

    `nested` stages ride INSIDE a leaf (drive-sync barriers fire under the
    commit span's rename fan-out, and under shard-fanout in always mode), so
    they are reported with their share of the end-to-end wall but excluded
    from the leaf sum -- adding them would double-count the same seconds.

    `aliases` maps a REPORTED row name onto the ledger stage actually
    recorded (drive-read -> the metered read_file_into histogram): the row
    set keeps the copy-ledger hop vocabulary without minting duplicate
    stage keys."""
    from minio_tpu.control.perf import quantile

    stages = snap.get("stages", {})
    obj = stages.get("object", {})
    stor = stages.get("storage", {})
    api = stages.get("api", {})

    def _hist(name: str) -> dict | None:
        src = (aliases or {}).get(name, name)
        return obj.get(src) or stor.get(src) or api.get(src)
    root = stages.get("bench", {}).get(phase)
    e2e_s = root["sum"] if root else 0.0
    n = sum(root["counts"]) if root else 0
    rows: dict[str, dict] = {}
    leaf_total = 0.0

    def _row(h: dict) -> dict:
        return {
            "total_ms": round(h["sum"] * 1e3, 1),
            # Wall-vs-cpu attribution (thread_time deltas recorded alongside
            # the span walls): cpu_ms ~= total_ms means the stage burns the
            # core; cpu_ms << total_ms means it waits (GIL, device, disk).
            "cpu_ms": round(h.get("cpu", 0.0) * 1e3, 1),
            "count": sum(h["counts"]),
            "p50_ms": round(quantile(h["counts"], 0.5) * 1e3, 3),
            "share": round(h["sum"] / e2e_s, 3) if e2e_s else 0.0,
        }

    for name in leaves:
        h = _hist(name)
        if not h:
            continue
        leaf_total += h["sum"]
        rows[name] = _row(h)
    for name in nested:
        h = _hist(name)
        if not h:
            continue
        r = _row(h)
        # Barriers fan out across all 16 drives concurrently, so the summed
        # stage wall can exceed the end-to-end wall; call the ratio what it
        # is instead of a "share" that can read > 1.
        r["sum_over_e2e"] = r.pop("share")
        rows[name] = {**r, "nested": True}
    other = max(e2e_s - leaf_total, 0.0)
    rows["other"] = {
        "total_ms": round(other * 1e3, 1),
        "share": round(other / e2e_s, 3) if e2e_s else 0.0,
    }
    return {
        "ops": n,
        "end_to_end_ms": round(e2e_s * 1e3, 1),
        "end_to_end_cpu_ms": round(root.get("cpu", 0.0) * 1e3, 1) if root else 0.0,
        "stages": rows,
    }


def object_layer_metrics(use_device: bool) -> dict:
    """PutObject / heal / concurrent-PUT throughput through ErasureObjects
    over 16 local drives (runPutObjectBenchmark + verify-healing roles,
    /root/reference/cmd/benchmark-utils_test.go:33,
    buildscripts/verify-healing.sh:16)."""
    import shutil
    import statistics
    import tempfile

    from minio_tpu.control import tracing
    from minio_tpu.control.perf import GLOBAL_PERF
    from minio_tpu.control.profiler import GLOBAL_PROFILER
    from minio_tpu.object.erasure import ErasureObjects
    from minio_tpu.storage import format as fmt
    from minio_tpu.storage import local as local_mod
    from minio_tpu.storage.local import LocalDrive
    from minio_tpu.storage.metered import MeteredDrive

    # Arm the continuous profiling plane for the bench run: the BENCH JSON
    # carries its summary (gil_load, top role stacks, copy ledger) so a
    # number regression comes with its own attribution.
    GLOBAL_PROFILER.ensure_started()

    codec = None
    if use_device:
        from minio_tpu.parallel.batching import BatchingDeviceCodec

        codec = BatchingDeviceCodec(max_batch=64)

    root = tempfile.mkdtemp(prefix="bench-objs-", dir=os.path.dirname(os.path.abspath(__file__)))
    out: dict = {}
    try:
        dirs = [os.path.join(root, f"disk{i}") for i in range(16)]
        formats = fmt.init_format(1, 16)
        drives = []
        # Metered, as production stacks them (dist/node.py): the per-call
        # storage ledger is what backs the breakdown's drive-read row.
        for d, f in zip(dirs, formats):
            os.makedirs(d)
            f.save(d)
            drives.append(MeteredDrive(LocalDrive(d)))
        layer = ErasureObjects(drives, codec=codec)  # parity 4 -> 12+4
        layer.make_bucket("bench")

        rng = np.random.default_rng(3)
        body = rng.integers(0, 256, PUT_SIZE, dtype=np.uint8).tobytes()
        # Warm the jit/codec paths off the clock: a 17 MiB put covers the
        # full GROUP_BLOCKS bucket and the tail path, a 1 MiB put covers the
        # single-block bucket used by the latency probe.
        layer.put_object("bench", "warm", body[: 17 << 20])
        layer.put_object("bench", "warm1", body[: 1 << 20])
        layer.delete_object("bench", "warm")
        layer.delete_object("bench", "warm1")

        # --- BASELINE #4: serial PutObject (GiB/s + p50 latency) -----------
        # Each op runs under a bench root span so the always-on stage ledger
        # (control/perf.py) attributes where the wall clock went; the ledger
        # is reset per phase so the breakdown covers exactly these ops.
        GLOBAL_PERF.ledger.reset()
        lat = []
        for i in range(PUT_OBJECTS):
            t0 = time.perf_counter()
            with tracing.root_span("bench.put", "bench", f"bench-put-{i}"):
                layer.put_object("bench", f"o-{i}", body)
            lat.append(time.perf_counter() - t0)
            layer.delete_object("bench", f"o-{i}")  # bound disk use, off-clock
        total = sum(lat)
        put_snap = GLOBAL_PERF.ledger.snapshot()
        out["putobject_gibs"] = round(PUT_OBJECTS * PUT_SIZE / total / (1 << 30), 3)
        out["putobject_p50_ms"] = round(statistics.median(lat) * 1000, 1)
        # Requests/second as a first-class axis (the live cluster reports the
        # same unit via /mtpu/admin/v1/timeseries and the object speedtest).
        out["puts_per_s"] = round(PUT_OBJECTS / total, 2) if total else 0.0
        out["fsync_mode"] = local_mod.fsync_mode()

        # --- durability-knob overhead: same single-stream PUT, barriers off -
        # The crash-consistency plane put fdatasync barriers on the commit
        # path (MTPU_FSYNC, default `commit`); this phase prices them by
        # re-running a shorter single-stream PUT with MTPU_FSYNC=never. The
        # gap between putobject_nosync_gibs and putobject_gibs is exactly
        # what the barriers cost on this disk.
        n_nosync = max(4, PUT_OBJECTS // 4)
        prev_fsync = os.environ.get("MTPU_FSYNC")
        os.environ["MTPU_FSYNC"] = local_mod.FSYNC_NEVER
        try:
            lat_ns = []
            for i in range(n_nosync):
                t0 = time.perf_counter()
                layer.put_object("bench", f"ns-{i}", body)
                lat_ns.append(time.perf_counter() - t0)
                layer.delete_object("bench", f"ns-{i}")
        finally:
            if prev_fsync is None:
                os.environ.pop("MTPU_FSYNC", None)
            else:
                os.environ["MTPU_FSYNC"] = prev_fsync
        out["putobject_nosync_gibs"] = round(
            n_nosync * PUT_SIZE / sum(lat_ns) / (1 << 30), 3
        )

        # BASELINE primary metric geometry: PutObject p50 at 1 MiB objects
        # (12+4 @ 1 MiB block -- one block per object, latency-bound).
        small = body[: 1 << 20]
        lat1 = []
        for i in range(50):
            t0 = time.perf_counter()
            layer.put_object("bench", f"s-{i}", small)
            lat1.append(time.perf_counter() - t0)
        out["putobject_1mib_p50_ms"] = round(statistics.median(lat1) * 1000, 2)
        for i in range(50):
            layer.delete_object("bench", f"s-{i}")

        # --- GetObject throughput (the speedtest GET side, cmd/utils.go:976) -
        # Chunks land in a reusable sink via memoryview assignment -- the
        # bench's stand-in for the server's socket writev -- so the GET
        # breakdown carries an honest response-write row instead of folding
        # the consumer into "other".
        sink = bytearray(4 << 20)

        def read_once(lyr, key: str) -> int:
            _, it = lyr.get_object_stream("bench", key)
            n = 0
            wr_w = wr_c = 0.0
            for c in it:
                lc = len(c)
                if lc > len(sink):
                    sink.extend(bytes(lc - len(sink)))
                t0 = time.perf_counter()
                c0 = time.thread_time()
                sink[:lc] = c
                wr_w += time.perf_counter() - t0
                wr_c += time.thread_time() - c0
                n += lc
            GLOBAL_PERF.ledger.record("api", "response-write", wr_w, wr_c)
            return n

        layer.put_object("bench", "getobj", body)
        assert read_once(layer, "getobj") == PUT_SIZE
        GLOBAL_PERF.ledger.reset()
        copy0 = GLOBAL_PROFILER.copy.snapshot()["hops"]
        t0 = time.perf_counter()
        get_iters = 4
        for gi in range(get_iters):
            with tracing.root_span("bench.get", "bench", f"bench-get-{gi}"):
                read_once(layer, "getobj")
        get_dt = time.perf_counter() - t0
        out["getobject_gibs"] = round(get_iters * PUT_SIZE / get_dt / (1 << 30), 3)
        out["gets_per_s"] = round(get_iters / get_dt, 2) if get_dt else 0.0
        out["total_ops_per_s"] = round(
            (PUT_OBJECTS + get_iters) / (total + get_dt), 2
        ) if (total + get_dt) else 0.0
        # Zero-copy scorecard for the healthy cold loop just timed: readinto
        # drive reads and memoryview frame-parse are MOVED hops; a single
        # COPIED byte here is a read-pipeline regression (the ISSUE 13
        # acceptance line, twin of the conservation test).
        copy1 = GLOBAL_PROFILER.copy.snapshot()["hops"]

        def _copy_delta(kind: str) -> int:
            after = sum(h[kind] for h in copy1.values())
            return after - sum(h[kind] for h in copy0.values())

        out["get_copied_bytes"] = _copy_delta("copied_bytes")
        out["get_moved_bytes"] = _copy_delta("moved_bytes")
        layer.delete_object("bench", "getobj")

        # --- hot-read tier: memcache cold/hot split ------------------------
        # The same GET geometry through the coherent memory cache
        # (object/memcache.py): the first read misses and fills (the cold
        # half of the split -- full shard IO plus the fill admit), the rest
        # serve from process memory. getobject_hot_gibs is the acceptance
        # headline: >= 2x the cold streaming number above. Validation off:
        # a single-process bench has no peers to stay coherent with.
        from minio_tpu.object.memcache import (
            MemCacheConfig,
            MemCacheObjectLayer,
            MemObjectCache,
        )

        hot_size = min(PUT_SIZE, 32 << 20)
        mc = MemObjectCache(MemCacheConfig(limit_bytes=256 << 20, validate=False))
        mc_layer = MemCacheObjectLayer(layer, mc)
        layer.put_object("bench", "hotobj", body[:hot_size])
        t0 = time.perf_counter()
        with tracing.root_span("bench.get", "bench", "bench-hotget-fill"):
            assert read_once(mc_layer, "hotobj") == hot_size  # miss + fill
        out["getobject_fill_gibs"] = round(
            hot_size / (time.perf_counter() - t0) / (1 << 30), 3
        )
        hot_iters = 8
        t0 = time.perf_counter()
        for gi in range(hot_iters):
            with tracing.root_span("bench.get", "bench", f"bench-hotget-{gi}"):
                assert read_once(mc_layer, "hotobj") == hot_size
        out["getobject_hot_gibs"] = round(
            hot_iters * hot_size / (time.perf_counter() - t0) / (1 << 30), 3
        )
        out["memcache"] = mc.stats()  # incl. hit_ratio of this split
        layer.delete_object("bench", "hotobj")

        # One GET row set spanning both halves of the split (cold loop +
        # fill + hot serves ran in the same ledger window under bench.get
        # roots). drive-read/frame-parse run on fan-out pool threads inside
        # the shard-read gather, and the fill's backend read re-enters
        # shard-read -- nested, not leaves, or the same seconds would count
        # twice.
        get_snap = GLOBAL_PERF.ledger.snapshot()
        out["stage_breakdown"] = {
            "put": _stage_breakdown(
                put_snap, "bench.put", ("encode", "shard-fanout", "commit"),
                nested=("drive-sync",),
            ),
            "get": _stage_breakdown(
                get_snap, "bench.get",
                ("shard-read", "decode", "cache-hit", "response-write"),
                nested=("drive-read", "frame-parse", "cache-fill"),
                aliases={"drive-read": "read_file_into"},
            ),
        }
        out["profile"] = GLOBAL_PROFILER.summary()

        # --- 8-concurrent-PUT aggregate (batching fan-in under load) -------
        cbody = body[:CONCURRENT_SIZE]
        rounds = 4

        def cput(i):
            for r in range(rounds):
                layer.put_object("bench", f"c-{i}-{r}", cbody)

        pool = ThreadPoolExecutor(max_workers=CONCURRENT_PUTS)
        t0 = time.perf_counter()
        list(pool.map(cput, range(CONCURRENT_PUTS)))
        dt = time.perf_counter() - t0
        out["concurrent_put_gibs"] = round(
            CONCURRENT_PUTS * rounds * CONCURRENT_SIZE / dt / (1 << 30), 3
        )
        for i in range(CONCURRENT_PUTS):
            for r in range(rounds):
                layer.delete_object("bench", f"c-{i}-{r}")

        # --- BASELINE #5: heal with 3 shards lost (GiB/s of object data) ---
        part_body = body  # PUT_SIZE-sized parts (128 MiB by default)
        n_parts = int(max(1, HEAL_BYTES // len(part_body)))
        try:
            up = layer.multipart.new_multipart_upload("bench", "healobj")
            parts = []
            for p in range(1, n_parts + 1):
                pi = layer.multipart.put_object_part("bench", "healobj", up, p, part_body)
                parts.append((p, pi.etag))
            layer.multipart.complete_multipart_upload("bench", "healobj", up, parts)
        except OSError:
            out["heal_gibs"] = 0.0
            out["heal_error"] = "disk too small for heal bench"
            return out
        # Lose 3 data-row shard files.
        fi, _, _ = layer._read_quorum_fi("bench", "healobj", "")
        lost = 0
        for i, rot in enumerate(fi.erasure.distribution):
            if rot - 1 < 12:  # data row
                obj_dir = os.path.join(dirs[i], "bench", "healobj")
                if os.path.isdir(obj_dir):
                    shutil.rmtree(obj_dir)
                    lost += 1
            if lost == 3:
                break
        t0 = time.perf_counter()
        res = layer.heal_object("bench", "healobj")
        dt = time.perf_counter() - t0
        out["heal_disks_healed"] = res.disks_healed
        out["heal_gibs"] = round(n_parts * len(part_body) / dt / (1 << 30), 3)

        # --- transparent-compression codec (S2 role, object-api-utils.go:907)
        try:
            from minio_tpu.control import compress as compress_mod

            src = open(os.path.abspath(__file__), "rb").read()
            text = (src * (1 + (64 << 20) // len(src)))[: 64 << 20]
            t0 = time.perf_counter()
            blob, cmeta = compress_mod.compress(text)
            ct = time.perf_counter() - t0
            t0 = time.perf_counter()
            back = compress_mod.decompress(blob, cmeta)
            dt = time.perf_counter() - t0
            assert back == text
            out["compress_algo"] = cmeta[compress_mod.META_COMPRESSION]
            out["compress_gibs"] = round(len(text) / ct / (1 << 30), 3)
            out["decompress_gibs"] = round(len(text) / dt / (1 << 30), 3)
            out["compress_ratio"] = round(len(blob) / len(text), 3)
        except Exception as e:  # noqa: BLE001
            out["compress_error"] = f"{type(e).__name__}: {e}"[:200]
    finally:
        if codec is not None:
            codec.close()
        shutil.rmtree(root, ignore_errors=True)
    return out


def device_metrics(progress: dict | None = None) -> dict:
    """Encode / hash / fused / reconstruct GiB/s on the live device.

    Results are ALSO written into `progress` as each lands, so a watchdog
    firing mid-run can emit the numbers already measured (first device
    compiles can be slow; losing a measured 18x headline to a timeout in a
    later secondary metric would be self-inflicted)."""
    import jax
    import jax.numpy as jnp

    from minio_tpu.ops import rs
    from minio_tpu.ops import highwayhash_jax as hhj

    progress = progress if progress is not None else {}
    platform = jax.devices()[0].platform
    progress["platform"] = platform
    rng = np.random.default_rng(0)
    data = rng.integers(0, 256, (BATCH, K, SHARD), dtype=np.uint8)
    dev = jax.device_put(jnp.asarray(data))

    codec = rs.RSCodec(K, M)

    @jax.jit
    def encode_only(x):
        return codec.encode(x)

    encode_only(dev).block_until_ready()
    t0 = time.perf_counter()
    for _ in range(ITERS):
        out = encode_only(dev)
    out.block_until_ready()
    enc_gibs = BATCH * BLOCK * ITERS / (time.perf_counter() - t0) / (1 << 30)
    progress["encode_gibs"] = enc_gibs

    # Hash-only throughput of both device implementations over the fused
    # batch's stream shape; the fused number below uses the winner (also
    # what pipeline.hash_batch_fn serves with).
    hdata = jax.device_put(
        jnp.asarray(
            rng.integers(0, 256, (FUSED_BATCH * (K + M), SHARD), dtype=np.uint8)
        )
    )
    hash_impls: dict[str, object] = {"xla": hhj.hash256_batch}
    hash_errors: dict[str, str] = {}
    try:
        from minio_tpu.ops import highwayhash_pallas as hhp

        hash_impls["pallas"] = hhp.hash256_batch
    except Exception as e:  # noqa: BLE001
        hash_errors["pallas"] = f"{type(e).__name__}: {e}"[:300]
    hash_gibs: dict[str, float] = {}
    for name, fn in hash_impls.items():
        try:
            jfn = jax.jit(fn)
            jfn(hdata).block_until_ready()
            hiters = max(4, ITERS // 2)
            t0 = time.perf_counter()
            for _ in range(hiters):
                hout = jfn(hdata)
            hout.block_until_ready()
            hash_gibs[name] = (
                hdata.size * hiters / (time.perf_counter() - t0) / (1 << 30)
            )
        except Exception as e:  # noqa: BLE001
            hash_errors[name] = f"{type(e).__name__}: {e}"[:300]
        progress[f"hash_{name}_gibs"] = round(hash_gibs.get(name, 0.0), 3)
        progress["hash_errors"] = dict(hash_errors)
    best_hash = max(hash_gibs, key=hash_gibs.get) if hash_gibs else "xla"
    progress["fused_hash_impl"] = best_hash
    best_hash_fn = hash_impls.get(best_hash, hhj.hash256_batch)

    @jax.jit
    def fused(x):
        shards = codec.encode_all(x)
        b, t, s = shards.shape
        return shards, best_hash_fn(shards.reshape(b * t, s))

    # Reconstruct 4 missing data shards from the 12 surviving rows.
    w = codec.reconstruct_weights(PRESENT, MISSING)
    full = np.asarray(codec.encode_all(dev))
    surv = jnp.asarray(full[:, [j for j in range(K + M) if PRESENT[j]][:K], :])
    recon = jax.jit(lambda s: codec.apply(s, w))
    recon(surv).block_until_ready()
    t0 = time.perf_counter()
    for _ in range(ITERS):
        out = recon(surv)
    out.block_until_ready()
    dec_gibs = BATCH * BLOCK * ITERS / (time.perf_counter() - t0) / (1 << 30)
    progress["decode_recon4_gibs"] = dec_gibs

    fdev = jax.device_put(jnp.asarray(data[:FUSED_BATCH]))
    jax.block_until_ready(fused(fdev))
    fiters = max(4, ITERS // 2)
    t0 = time.perf_counter()
    for _ in range(fiters):
        r = fused(fdev)
    jax.block_until_ready(r)
    fused_gibs = FUSED_BATCH * BLOCK * fiters / (time.perf_counter() - t0) / (1 << 30)
    progress["fused_encode_hash_gibs"] = fused_gibs

    # Fused Pallas kernel (ops/rs_pallas.py): VMEM-resident bit expansion.
    # Never let a Mosaic regression break the bench line — but a 0.0 must
    # carry its cause (pallas_error), not masquerade as "not measured".
    pallas_gibs = 0.0
    pallas_error = ""
    try:
        from minio_tpu.ops.rs_pallas import RSPallasCodec

        pcodec = RSPallasCodec(K, M)
        penc = jax.jit(pcodec.encode)
        penc(dev).block_until_ready()
        t0 = time.perf_counter()
        for _ in range(ITERS):
            out = penc(dev)
        out.block_until_ready()
        pallas_gibs = BATCH * BLOCK * ITERS / (time.perf_counter() - t0) / (1 << 30)
    except Exception as e:  # noqa: BLE001
        pallas_error = f"{type(e).__name__}: {e}"[:500]
    progress["pallas_encode_gibs"] = pallas_gibs
    progress["pallas_error"] = pallas_error

    # Fused XOR-bitmatrix encode + on-device hash in ONE jitted program
    # (ops/fused.py): what a PUT window actually pays when the Pallas
    # codec serves.
    pallas_fused_gibs = 0.0
    pallas_fused_error = ""
    if pallas_gibs > 0:
        try:
            from minio_tpu.ops import fused as fused_ops

            fdev2 = jax.device_put(jnp.asarray(data[:FUSED_BATCH]))
            jax.block_until_ready(
                fused_ops.fused_encode_hash(fdev2, K, M, "pallas", best_hash)
            )
            fiters2 = max(4, ITERS // 2)
            t0 = time.perf_counter()
            for _ in range(fiters2):
                r2 = fused_ops.fused_encode_hash(fdev2, K, M, "pallas", best_hash)
            jax.block_until_ready(r2)
            pallas_fused_gibs = (
                FUSED_BATCH * BLOCK * fiters2 / (time.perf_counter() - t0) / (1 << 30)
            )
        except Exception as e:  # noqa: BLE001
            pallas_fused_error = f"{type(e).__name__}: {e}"[:500]
    progress["pallas_fused_gibs"] = pallas_fused_gibs
    progress["pallas_fused_error"] = pallas_fused_error

    # Multi-chip fan-out: data-parallel encode over every local device via
    # shard_map ((n,1,1) mesh — the BatchingDeviceCodec layout). Scaling
    # efficiency is vs n * the single-chip Pallas number.
    multichip_gibs = 0.0
    multichip_eff = 0.0
    n_dev = len(jax.devices())
    multichip_error = ""
    if pallas_gibs > 0 and n_dev > 1:
        try:
            from jax.sharding import PartitionSpec as P

            from minio_tpu.parallel import mesh as mesh_lib

            mesh = mesh_lib.make_mesh(n_dev, (n_dev, 1, 1))
            menc = jax.jit(
                mesh_lib.shard_map_compat(
                    pcodec.encode,
                    mesh=mesh,
                    in_specs=P("dp", None, None),
                    out_specs=P("dp", None, None),
                )
            )
            mb = -(-BATCH // n_dev) * n_dev
            mdata = jax.device_put(
                jnp.asarray(rng.integers(0, 256, (mb, K, SHARD), dtype=np.uint8)),
                mesh_lib.data_sharding(mesh),
            )
            menc(mdata).block_until_ready()
            t0 = time.perf_counter()
            for _ in range(ITERS):
                mout = menc(mdata)
            mout.block_until_ready()
            multichip_gibs = (
                mb * BLOCK * ITERS / (time.perf_counter() - t0) / (1 << 30)
            )
            multichip_eff = multichip_gibs / (pallas_gibs * n_dev)
        except Exception as e:  # noqa: BLE001
            multichip_error = f"{type(e).__name__}: {e}"[:500]
    progress["multichip_encode_gibs"] = multichip_gibs
    progress["multichip_devices"] = n_dev
    progress["multichip_scaling_eff"] = round(multichip_eff, 3)
    return {
        "platform": platform,
        "encode_gibs": enc_gibs,
        "decode_recon4_gibs": dec_gibs,
        "fused_encode_hash_gibs": fused_gibs,
        "fused_hash_impl": best_hash,
        "hash_xla_gibs": round(hash_gibs.get("xla", 0.0), 3),
        "hash_pallas_gibs": round(hash_gibs.get("pallas", 0.0), 3),
        "hash_errors": hash_errors,
        "pallas_encode_gibs": pallas_gibs,
        "pallas_error": pallas_error,
        "pallas_fused_gibs": pallas_fused_gibs,
        "pallas_fused_error": pallas_fused_error,
        "multichip_encode_gibs": multichip_gibs,
        "multichip_devices": n_dev,
        "multichip_scaling_eff": round(multichip_eff, 3),
        "multichip_error": multichip_error,
    }


_probe_cached = False  # set by main() once the probe verdict lands


def emit(payload: dict) -> None:
    payload.setdefault("probe_cached", _probe_cached)
    # Latest fallback/recovery flip of the probe verdict (ok<->fail), read
    # from the cross-run cache: a driver diffing BENCH lines sees not just
    # the current platform but that (and roughly when) it changed.
    try:
        from minio_tpu.runtime import probe_transition

        payload.setdefault("probe_transition", probe_transition())
    except Exception:  # noqa: BLE001 - the bench line must still emit
        payload.setdefault("probe_transition", None)
    # Flight triggers fired mid-round taint the numbers: a bench second that
    # also dumped a diagnostic bundle measured the incident, not the code.
    try:
        from minio_tpu.control.flight import GLOBAL_FLIGHT

        payload.setdefault(
            "flight_triggers_fired",
            sum(GLOBAL_FLIGHT.stats()["triggers"].values()),
        )
    except Exception:  # noqa: BLE001 - the bench line must still emit
        payload.setdefault("flight_triggers_fired", None)
    print(json.dumps(payload))


def xor_schedule_stats() -> dict:
    """CSE'd XOR-schedule shape for the production geometry (pure host
    computation -- rides every bench line, device or fallback)."""
    try:
        from minio_tpu.ops import bitmatrix

        return bitmatrix.schedule_stats(K, M)
    except Exception as e:  # noqa: BLE001
        return {"error": f"{type(e).__name__}: {e}"[:200]}


def kernel_status_line() -> dict:
    """Honest per-kernel selection report (models/pipeline.kernel_status)."""
    try:
        from minio_tpu.models.pipeline import kernel_status

        return kernel_status(K, M)
    except Exception as e:  # noqa: BLE001
        return {"error": f"{type(e).__name__}: {e}"[:200]}


def fallback_line(cpu_enc: float, cpu_dec: float, reason: str, probe=None) -> dict:
    line = {
        "metric": f"erasure-encode GiB/s (12+4 @ 1MiB, CPU fallback: {reason})",
        "value": round(cpu_enc, 3),
        "unit": "GiB/s",
        "vs_baseline": 0.0,
        "device": False,
        "cpu_avx2_gibs": round(cpu_enc, 3),
        "cpu_decode_recon4_gibs": round(cpu_dec, 3),
        "xor_schedule": xor_schedule_stats(),
    }
    if probe is not None:
        # The probe evidence (relay-reachability lines + faulthandler dump)
        # goes to a sidecar file: the driver's contract is that the bench's
        # final line is ONE parseable JSON object, and a multi-KB multi-line
        # traceback embedded in it broke that in round 4 (parsed: null).
        line["probe_error"] = probe.error or ""
        sidecar = os.path.join(os.path.dirname(os.path.abspath(__file__)), "BENCH_probe_detail.txt")
        try:
            with open(sidecar, "w") as f:
                f.write(probe.detail or "")
            line["probe_detail_file"] = sidecar
        except OSError:
            line["probe_detail"] = (probe.detail or "")[-500:].replace("\n", " | ")
    return line


def main() -> None:
    from minio_tpu.runtime import probe_device

    # Cross-run probe verdict cache: rounds 4-5 re-paid a 180 s init wedge
    # per process just to re-learn "device gone". Opt out by exporting
    # MTPU_PROBE_CACHE= (empty).
    os.environ.setdefault(
        "MTPU_PROBE_CACHE", os.path.join(tempfile.gettempdir(), "mtpu_probe_cache.json")
    )

    # Launch the bounded probe child first (it mostly blocks on the tunnel,
    # not the CPU), overlap the CPU baselines with it, then join.
    probe_box: dict = {}

    def _probe():
        probe_box["r"] = probe_device(PROBE_TIMEOUT_S)

    pt = ThreadPoolExecutor(max_workers=1).submit(_probe)

    rng = np.random.default_rng(1)
    blocks = rng.integers(0, 256, (BATCH, K, SHARD), dtype=np.uint8)
    cpu_enc = cpu_encode_gibs(blocks)
    cpu_dec = cpu_decode_gibs(blocks[: max(32, BATCH // 8)])

    pt.result()
    probe = probe_box["r"]
    global _probe_cached
    _probe_cached = probe.cached
    if not probe.ok:
        reason = (
            "no accelerator (cpu-only jax)" if probe.platform == "cpu"
            else probe.error or "device probe failed"
        )
        line = fallback_line(cpu_enc, cpu_dec, reason, probe)
        try:
            line.update(object_layer_metrics(use_device=False))
        except Exception as e:  # noqa: BLE001
            line["object_bench_error"] = f"{type(e).__name__}: {e}"[:300]
        emit(line)
        return

    # Watchdog: if the in-process run wedges, emit whatever device numbers
    # already landed (progressive `progress` dict) rather than the CPU
    # fallback — a slow secondary compile must not erase a measured headline.
    progress: dict = {}

    def on_timeout(signum, frame):
        if progress.get("encode_gibs"):
            progress.setdefault("fused_encode_hash_gibs", 0.0)
            progress.setdefault("decode_recon4_gibs", 0.0)
            emit(
                device_line(
                    progress, cpu_enc, cpu_dec,
                    {"device_bench_error": "watchdog timeout mid-run (partial numbers)"},
                )
            )
        else:
            emit(fallback_line(cpu_enc, cpu_dec, "device run watchdog timeout"))
        os._exit(0)

    signal.signal(signal.SIGALRM, on_timeout)
    signal.alarm(1200)
    try:
        dm = device_metrics(progress)
    except Exception as e:  # noqa: BLE001 - report, never crash the driver
        signal.alarm(0)
        if progress.get("encode_gibs"):
            progress.setdefault("fused_encode_hash_gibs", 0.0)
            progress.setdefault("decode_recon4_gibs", 0.0)
            emit(
                device_line(
                    progress, cpu_enc, cpu_dec,
                    {"device_bench_error": f"{type(e).__name__}: {e}"[:300]},
                )
            )
        else:
            emit(fallback_line(cpu_enc, cpu_dec, f"device run failed: {type(e).__name__}"))
        return
    finally:
        signal.alarm(0)

    # Object-layer end-to-end numbers (own watchdog budget: disk-bound).
    # A timeout here must NOT discard the device metrics already in dm, so
    # the handler is swapped for one that emits the real line sans object
    # numbers instead of the device-fallback line.
    def on_obj_timeout(signum, frame):
        emit(device_line(dm, cpu_enc, cpu_dec, {"object_bench_error": "watchdog timeout"}))
        os._exit(0)

    signal.signal(signal.SIGALRM, on_obj_timeout)
    signal.alarm(1200)
    try:
        obj = object_layer_metrics(use_device=dm["platform"] != "cpu")
    except Exception as e:  # noqa: BLE001
        obj = {"object_bench_error": f"{type(e).__name__}: {e}"[:300]}
    finally:
        signal.alarm(0)

    emit(device_line(dm, cpu_enc, cpu_dec, obj))


def device_line(dm: dict, cpu_enc: float, cpu_dec: float, obj: dict) -> dict:
    enc = dm["encode_gibs"]
    return {
        "metric": f"erasure-encode GiB/s (12+4 @ 1MiB, batch {BATCH}, {dm['platform']})",
        "value": round(enc, 3),
        "unit": "GiB/s",
        "vs_baseline": round(enc / cpu_enc, 3) if cpu_enc else 0.0,
        "device": dm["platform"] != "cpu",
        "cpu_avx2_gibs": round(cpu_enc, 3),
        "fused_encode_hash_gibs": round(dm["fused_encode_hash_gibs"], 3),
        "fused_hash_impl": dm.get("fused_hash_impl", ""),
        "hash_xla_gibs": dm.get("hash_xla_gibs", 0.0),
        "hash_pallas_gibs": dm.get("hash_pallas_gibs", 0.0),
        "hash_errors": dm.get("hash_errors", {}),
        "pallas_encode_gibs": round(dm.get("pallas_encode_gibs", 0.0), 3),
        "pallas_error": dm.get("pallas_error", ""),
        "pallas_fused_gibs": round(dm.get("pallas_fused_gibs", 0.0), 3),
        "pallas_fused_error": dm.get("pallas_fused_error", ""),
        "multichip_encode_gibs": round(dm.get("multichip_encode_gibs", 0.0), 3),
        "multichip_devices": dm.get("multichip_devices", 1),
        "multichip_scaling_eff": dm.get("multichip_scaling_eff", 0.0),
        "multichip_error": dm.get("multichip_error", ""),
        "xor_schedule": xor_schedule_stats(),
        "kernel_status": kernel_status_line(),
        "decode_recon4_gibs": round(dm["decode_recon4_gibs"], 3),
        "cpu_decode_recon4_gibs": round(cpu_dec, 3),
        "decode_vs_baseline": (
            round(dm["decode_recon4_gibs"] / cpu_dec, 3) if cpu_dec else 0.0
        ),
        **obj,
    }


if __name__ == "__main__":
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    main()
