"""Pool lifecycle subsystem tests: attach, checkpointed decommission with
crash/resume, rebalance-on-expansion, throttle math, status + metrics.

The unit-level half of cmd/erasure-server-pool-decom.go coverage; the
under-live-traffic end (node killed mid-drain, loadgen SLO gates) lives in
tests/chaos_scenarios.py and scenarios/decommission_under_load.yaml.
"""

import json
import os

import pytest

from minio_tpu.control.rebalance import RebalanceEngine, ThrottleBudget
from minio_tpu.object import poolmgr as poolmgr_mod
from minio_tpu.object.poolmgr import (
    CONFIG_FILE,
    DecommissionTracker,
    PoolManager,
    _read_sys,
)
from minio_tpu.object.pools import (
    POOL_ACTIVE,
    POOL_DECOMMISSIONED,
    POOL_DRAINING,
    ServerPools,
)
from minio_tpu.object.sets import ErasureSets
from minio_tpu.object.types import DeleteObjectOptions, PutObjectOptions
from minio_tpu.storage import format as fmt
from minio_tpu.storage.local import LocalDrive
from minio_tpu.utils import errors


def make_sets(tmp_path, pi: int, n_disks: int = 4) -> ErasureSets:
    formats = fmt.init_format(1, n_disks)
    drives = []
    for i in range(n_disks):
        root = str(tmp_path / f"pool{pi}" / f"disk{i}")
        os.makedirs(root, exist_ok=True)
        formats[i].save(root)
        drives.append(LocalDrive(root))
    return ErasureSets.from_drives(drives, formats[0], pool_index=pi)


@pytest.fixture
def layer(tmp_path):
    lp = ServerPools([make_sets(tmp_path, 0), make_sets(tmp_path, 1)])
    lp.make_bucket("bucket")
    return lp


class TestThrottleBudget:
    def test_unlimited_never_sleeps(self):
        slept = []
        b = ThrottleBudget(bytes_per_s=0, ops_per_s=0,
                           clock=lambda: 0.0, sleep=slept.append)
        for _ in range(10):
            assert b.consume(1 << 20) == 0.0
        assert slept == []
        assert b.throttle_waits == 0
        assert b.bytes == 10 << 20

    def test_bytes_budget_paces(self):
        now = [0.0]
        slept = []
        b = ThrottleBudget(bytes_per_s=1000, ops_per_s=0,
                           clock=lambda: now[0], sleep=slept.append)
        assert b.consume(500) == 0.0               # first move rides free
        assert b.consume(500) == pytest.approx(0.5)  # clock ran 0.5s ahead
        assert slept == [pytest.approx(0.5)]
        assert b.throttle_waits == 1
        assert b.throttled_seconds == pytest.approx(0.5)
        now[0] = 10.0                               # idle drains the debt
        assert b.consume(500) == 0.0

    def test_ops_budget_paces(self):
        now = [0.0]
        slept = []
        b = ThrottleBudget(bytes_per_s=0, ops_per_s=2,
                           clock=lambda: now[0], sleep=slept.append)
        assert b.consume(0) == 0.0
        assert b.consume(0) == pytest.approx(0.5)
        assert b.ops == 2

    def test_env_defaults(self, monkeypatch):
        monkeypatch.setenv("MTPU_REBALANCE_BYTES_PER_S", "2048")
        monkeypatch.setenv("MTPU_REBALANCE_OPS_PER_S", "7")
        b = ThrottleBudget(clock=lambda: 0.0, sleep=lambda s: None)
        assert b.bytes_per_s == 2048.0
        assert b.ops_per_s == 7.0


class TestAttach:
    def test_attach_is_two_phase_and_persisted(self, tmp_path, layer):
        pm = PoolManager(layer)
        idx = pm.attach(make_sets(tmp_path, 2), endpoints=["/fake/ep"])
        assert idx == 2
        assert layer.statuses == [POOL_ACTIVE] * 3
        # SUSPENDED fanout + ACTIVE fanout = two epoch bumps.
        assert pm.epoch == 2
        doc = json.loads(_read_sys(layer, CONFIG_FILE).decode())
        assert doc["epoch"] == 2
        assert [p["status"] for p in doc["pools"]] == [POOL_ACTIVE] * 3
        assert doc["pools"][2]["endpoints"] == ["/fake/ep"]

    def test_attach_replicates_buckets(self, tmp_path, layer):
        pm = PoolManager(layer)
        pm.attach(make_sets(tmp_path, 2))
        assert layer.pools[2].get_bucket_info("bucket").name == "bucket"
        # And the joined pool takes part in the namespace immediately.
        layer.pools[2].put_object("bucket", "landed", b"x")
        _, data = layer.get_object("bucket", "landed")
        assert data == b"x"

    def test_load_config_applies_newer_epoch(self, tmp_path, layer):
        pm = PoolManager(layer)
        pm.attach(make_sets(tmp_path, 2))
        layer.set_pool_status(2, POOL_DRAINING)
        pm._bump_epoch_and_fanout()
        # A fresh manager over the same pools (epoch 0) catches up from
        # the persisted config; an already-current one is a no-op.
        pm2 = PoolManager(layer)
        assert pm2.load_config() is True
        assert pm2.epoch == 3
        assert layer.statuses[2] == POOL_DRAINING
        assert pm2.load_config() is False


class TestDecommission:
    def _fill(self, layer, n=12, prefix="obj"):
        for i in range(n):
            layer.pools[0].put_object("bucket", f"{prefix}-{i:03d}",
                                      f"payload-{i}".encode() * 8)

    def test_drain_moves_everything(self, layer):
        self._fill(layer, 12)
        pm = PoolManager(layer)
        pm.start_decommission(0, wait=True)
        tr = pm.trackers[0]
        assert tr.finished and not tr.failed
        assert tr.objects_moved == 12
        assert layer.statuses[0] == POOL_DECOMMISSIONED
        assert pm._pool_object_count(layer.pools[0]) == 0
        names = [o.name for o in layer.list_objects("bucket", max_keys=100).objects]
        assert len(names) == 12
        for i in range(12):
            _, data = layer.get_object("bucket", f"obj-{i:03d}")
            assert data == f"payload-{i}".encode() * 8

    def test_drain_preserves_versions_and_markers(self, layer):
        vids = []
        for i in range(3):
            oi = layer.pools[0].put_object(
                "bucket", "ver", f"v{i}".encode(),
                PutObjectOptions(versioned=True),
            )
            vids.append(oi.version_id)
        layer.pools[0].put_object("bucket", "gone", b"soon",
                                  PutObjectOptions(versioned=True))
        layer.pools[0].delete_object("bucket", "gone",
                                     DeleteObjectOptions(versioned=True))
        pm = PoolManager(layer)
        pm.start_decommission(0, wait=True)
        assert pm.trackers[0].finished
        # Every version is readable from the surviving pool, by id.
        for i, vid in enumerate(vids):
            from minio_tpu.object.types import GetObjectOptions

            _, data = layer.get_object("bucket", "ver",
                                       GetObjectOptions(version_id=vid))
            assert data == f"v{i}".encode()
        # The delete marker still shadows the deleted object.
        with pytest.raises(errors.ObjectError):
            layer.get_object("bucket", "gone")

    def test_cannot_drain_last_active_pool(self, layer):
        layer.set_pool_status(1, POOL_DRAINING)
        pm = PoolManager(layer)
        with pytest.raises(errors.InvalidArgument):
            pm.start_decommission(0)

    def test_double_drain_rejected(self, layer):
        self._fill(layer, 4)
        pm = PoolManager(layer)
        pm.start_decommission(0, wait=True)
        with pytest.raises(errors.InvalidArgument):
            pm.start_decommission(0)

    def test_drain_excluded_from_placement(self, layer):
        layer.set_pool_status(0, POOL_DRAINING)
        assert layer._pool_with_space() is layer.pools[1]


class _Killed(Exception):
    pass


class TestCrashResume:
    def test_kill_mid_drain_resumes_from_checkpoint(self, layer):
        n = 24
        for i in range(n):
            layer.pools[0].put_object("bucket", f"k-{i:03d}", b"d" * 64)
        pm = PoolManager(layer)
        kills = {"left": 2}

        def hook(tracker):
            # Simulated hard kill after two move batches: the exception
            # tears down the drain thread exactly like a process death
            # would, leaving only the journaled checkpoint behind.
            kills["left"] -= 1
            if kills["left"] == 0:
                raise _Killed("node killed mid-drain")

        pm._drain_hook = hook
        pm.start_decommission(0, wait=True, checkpoint_every=4)
        tr1 = pm.trackers[0]
        assert not tr1.finished and "Killed" in tr1.failed
        moved_before = tr1.objects_moved
        assert 0 < moved_before < n
        assert layer.statuses[0] == POOL_DRAINING  # still mid-flight

        # "Restart": a brand-new manager over the same storage. It reads
        # the persisted pool config + drain journal and resumes the drain
        # from the cursor -- no re-walk from the top.
        pm2 = PoolManager(layer)
        pm2.load_config()
        saved = DecommissionTracker.load(layer, 0)
        assert saved is not None and saved.resume_object
        assert saved.objects_moved == moved_before
        assert pm2.resume_pending() == [0]
        pm2.join()
        tr2 = pm2.trackers[0]
        assert tr2.finished and not tr2.failed
        # Resumed, not restarted: the tracker is cumulative across the
        # kill, so the second leg moved only what the first leg left...
        assert tr2.objects_moved - saved.objects_moved == n - moved_before
        assert layer.statuses[0] == POOL_DECOMMISSIONED
        # ...and nothing was lost or doubled.
        listing = layer.list_objects("bucket", max_keys=100).objects
        assert [o.name for o in listing] == [f"k-{i:03d}" for i in range(n)]
        for i in range(n):
            _, data = layer.get_object("bucket", f"k-{i:03d}")
            assert data == b"d" * 64
        assert pm2._pool_object_count(layer.pools[0]) == 0

    def test_resume_noop_when_nothing_draining(self, layer):
        pm = PoolManager(layer)
        assert pm.resume_pending() == []


class TestRebalance:
    def test_skew_converges_without_oscillation(self, tmp_path, layer):
        for i in range(20):
            layer.pools[0].put_object("bucket", f"r-{i:03d}", b"z" * 256)
        pm = PoolManager(layer)
        pm.attach(make_sets(tmp_path, 2))
        eng: RebalanceEngine = pm.rebalancer
        pm.start_rebalance(threshold=0.10)
        eng.join(60)
        assert not eng.running
        assert eng.objects_moved > 0
        assert max(eng._skews().values()) <= 0.10
        # The donor was not drained past its fair share into a ping-pong.
        for i in range(20):
            _, data = layer.get_object("bucket", f"r-{i:03d}")
            assert data == b"z" * 256

    def test_balanced_cluster_is_noop(self, layer):
        pm = PoolManager(layer)
        eng = pm.rebalancer
        assert eng._round(0.10) == 0
        assert eng.objects_moved == 0


class TestStatusAndMetrics:
    def test_status_shape(self, tmp_path, layer):
        layer.pools[0].put_object("bucket", "one", b"x" * 100)
        pm = PoolManager(layer)
        st = pm.status()
        assert st["epoch"] == 0
        assert {"pools_attached", "objects_moved", "checkpoints"} <= set(st["stats"])
        assert len(st["pools"]) == 2
        row = st["pools"][0]
        assert row["status"] == POOL_ACTIVE
        assert row["capacity_bytes"] > 0
        assert row["objects"] >= 1

    def test_drain_progress_in_status(self, layer):
        for i in range(6):
            layer.pools[0].put_object("bucket", f"s-{i}", b"y" * 32)
        pm = PoolManager(layer)
        pm.start_decommission(0, wait=True)
        pm._gauge_cache.clear()  # gauges were cached mid-drain
        row = pm.status()["pools"][0]
        assert row["status"] == POOL_DECOMMISSIONED
        assert row["drain"]["finished"] is True
        assert row["drain"]["objects_moved"] == 6

    def test_metrics_exposition_renders_pool_series(self, layer):
        from minio_tpu.control.metrics import MetricsSys

        layer.pools[0].put_object("bucket", "m-0", b"w" * 50)
        pm = PoolManager(layer)
        pm.start_decommission(0, wait=True)
        m = MetricsSys()
        m.poolmgr = pm
        text = m.render_node()
        assert "minio_tpu_pool_attached_total" in text
        assert "minio_tpu_pool_objects_moved_total" in text
        assert 'minio_tpu_pool_capacity_bytes{pool="0"' in text
        assert 'minio_tpu_pool_drain_finished{pool="0"} 1' in text

    def test_tracker_roundtrip(self, layer):
        tr = DecommissionTracker(pool_index=0, started=1.0, objects_moved=7,
                                 resume_bucket="bucket", resume_object="k-5")
        tr.save(layer)
        back = DecommissionTracker.load(layer, 0)
        assert back is not None
        assert back.objects_moved == 7
        assert (back.resume_bucket, back.resume_object) == ("bucket", "k-5")
        # Journal lives OFF the draining pool: every copy is on pool 1.
        assert DecommissionTracker.load(
            ServerPools([layer.pools[1]]), 0
        ) is not None
