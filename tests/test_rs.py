"""Reed-Solomon codec tests: golden bit-exactness + reconstruct round-trips.

Mirrors the reference's boot self-test (cmd/erasure-coding.go:158-216) and its
unit tests (cmd/erasure-encode_test.go, erasure-decode_test.go): encode over
all supported geometries, hash-compare against golden vectors, then knock out
shards and reconstruct.
"""

import numpy as np
import pytest
import xxhash

from minio_tpu.ops import gf, rs, rs_matrix, rs_ref
from tests.golden_rs import GOLDEN

TESTDATA = bytes(range(256))


def _golden_hash(encoded: np.ndarray) -> int:
    h = xxhash.xxh64()
    for i in range(encoded.shape[0]):
        h.update(bytes([i]))
        h.update(encoded[i].tobytes())
    return h.intdigest()


def test_gf_tables_sane():
    mul = gf.mul_table()
    assert mul[1, 57] == 57
    assert mul[0, 200] == 0
    # a * inv(a) == 1
    for a in range(1, 256):
        assert gf.gf_mul(a, gf.gf_inv(a)) == 1
    # distributivity spot check
    rng = np.random.default_rng(0)
    for _ in range(100):
        a, b, c = rng.integers(0, 256, 3)
        assert gf.gf_mul(int(a), int(b) ^ int(c)) == gf.gf_mul(int(a), int(b)) ^ gf.gf_mul(
            int(a), int(c)
        )


def test_matrix_inverse_roundtrip():
    rng = np.random.default_rng(1)
    for n in (2, 5, 12):
        while True:
            m = rng.integers(0, 256, (n, n)).astype(np.uint8)
            try:
                inv = gf.mat_inv(m)
                break
            except ValueError:
                continue
        prod = gf.mat_mul(m, inv)
        assert np.array_equal(prod, np.eye(n, dtype=np.uint8))


def test_encode_matrix_systematic():
    em = rs_matrix.encode_matrix(12, 4)
    assert np.array_equal(em[:12], np.eye(12, dtype=np.uint8))


@pytest.mark.parametrize("geometry", sorted(GOLDEN))
def test_golden_numpy(geometry):
    k, m = geometry
    enc = rs_ref.encode_data(TESTDATA, k, m)
    assert _golden_hash(enc) == GOLDEN[geometry]


# Subset for the device path: every geometry forces a fresh XLA compile, so the
# full 60-config sweep lives on the numpy path and this samples the corners.
JAX_GOLDEN_SUBSET = [(2, 2), (3, 4), (5, 3), (8, 7), (12, 3), (14, 1)]


@pytest.mark.parametrize("geometry", JAX_GOLDEN_SUBSET)
def test_golden_jax(geometry):
    k, m = geometry
    shards = rs_matrix.split(TESTDATA, k)
    codec = rs.RSCodec(k, m)
    enc = np.asarray(codec.encode_all(shards[None]))[0]
    assert _golden_hash(enc) == GOLDEN[geometry]


def test_jax_matches_numpy_random():
    rng = np.random.default_rng(2)
    for k, m, s, b in [(12, 4, 1024, 3), (4, 2, 333, 1), (8, 8, 64, 5)]:
        data = rng.integers(0, 256, (b, k, s)).astype(np.uint8)
        codec = rs.RSCodec(k, m)
        parity = np.asarray(codec.encode(data))
        for i in range(b):
            ref = rs_ref.encode(data[i], m)
            assert np.array_equal(parity[i], ref[k:]), (k, m, i)


@pytest.mark.parametrize("missing", [(0,), (0, 1, 2, 3), (11, 12, 13), (12, 13, 14, 15)])
def test_reconstruct_numpy(missing):
    k, m = 12, 4
    rng = np.random.default_rng(3)
    data = rng.integers(0, 256, (k, 512)).astype(np.uint8)
    full = rs_ref.encode(data, m)
    shards: list = [full[i].copy() for i in range(k + m)]
    for i in missing:
        shards[i] = None
    out = rs_ref.reconstruct(shards, k, m)
    for i in range(k + m):
        assert np.array_equal(out[i], full[i]), i


def test_reconstruct_data_only_skips_parity():
    k, m = 4, 2
    rng = np.random.default_rng(4)
    data = rng.integers(0, 256, (k, 100)).astype(np.uint8)
    full = rs_ref.encode(data, m)
    shards: list = [full[i].copy() for i in range(k + m)]
    shards[1] = None
    shards[5] = None
    out = rs_ref.reconstruct(shards, k, m, data_only=True)
    assert np.array_equal(out[1], full[1])
    assert out[5] is None


def test_reconstruct_jax():
    k, m = 12, 4
    rng = np.random.default_rng(5)
    data = rng.integers(0, 256, (2, k, 256)).astype(np.uint8)
    codec = rs.RSCodec(k, m)
    full = np.asarray(codec.encode_all(data))
    # Lose shards 0, 5, 13 (two data + one parity); rebuild all three.
    missing = (0, 5, 13)
    present = tuple(i not in missing for i in range(k + m))
    survivor_idx = [i for i in range(k + m) if present[i]][:k]
    survivors = full[:, survivor_idx]
    w = codec.reconstruct_weights(present, missing)
    rebuilt = np.asarray(codec.apply(survivors, w))
    for j, i in enumerate(missing):
        assert np.array_equal(rebuilt[:, j], full[:, i]), i


def test_insufficient_shards_raises():
    k, m = 4, 2
    shards = [None] * 3 + [np.zeros(10, np.uint8)] * 3
    with pytest.raises(ValueError):
        rs_ref.reconstruct(shards, k, m)


def test_split_semantics():
    # 256 bytes into 5 shards: per-shard ceil(256/5)=52, tail zero-padded.
    shards = rs_matrix.split(TESTDATA, 5)
    assert shards.shape == (5, 52)
    flat = shards.reshape(-1)
    assert bytes(flat[:256].tobytes()) == TESTDATA
    assert not flat[256:].any()


def test_shard_sizes_match_reference_formulas():
    # ShardSize = ceil(blockSize/K)  (cmd/erasure-coding.go:122)
    assert rs_matrix.shard_size(1 << 20, 12) == 87382
