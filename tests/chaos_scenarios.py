"""Chaos scenario harness: deterministic fault schedules against the real
erasure/heal/lock stack.

The analogue of the reference's chaos tooling (buildscripts/verify-healing.sh
kills server processes; minio/mint drives black-box scenarios): arm a seeded
FaultRegistry (minio_tpu/chaos/) under a live object layer, break drives /
links / lock servers mid-operation, and assert the invariants the paper's
recovery story promises -- quorum reads keep succeeding, MRF re-drives
partial writes, heal converges, and post-heal reads are bit-identical.

Collected via tests/test_chaos_scenarios.py (pytest only picks up test_*.py);
tools/chaos_check.py runs this file directly, including the `slow` scenarios
tier-1 skips.
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time
from types import SimpleNamespace

import pytest
from aiohttp import web

from minio_tpu.chaos.disk import FaultyDisk, flip_byte
from minio_tpu.chaos.faults import (
    BITROT,
    DRIVE_ERROR,
    DRIVE_HANG,
    DRIVE_LATENCY,
    LOCK_DEATH,
    PARTITION,
    REGISTRY,
    SLOW_RPC,
    FaultRegistry,
    FaultSpec,
)
from minio_tpu.control.degrade import GLOBAL_DEGRADE
from minio_tpu.control.healmgr import (
    DiskHealMonitor,
    HealingTracker,
    MRFQueue,
    mark_drive_for_healing,
)
from minio_tpu.dist.locks import LOCK_PREFIX, DRWMutex, LocalLocker, RemoteLocker, make_lock_app
from minio_tpu.dist.transport import RestClient, cluster_token, jitter
from minio_tpu.object.pools import ServerPools
from minio_tpu.object.sets import ErasureSets
from minio_tpu.storage.breaker import CircuitBreaker, HealthGatedDrive
from minio_tpu.utils import deadline, errors
from minio_tpu.utils.hashes import hash_order
from tests.harness import ErasureHarness
from tests.test_healing_tracker import _replace_drive

TOKEN = cluster_token("chaos-secret")


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def _has_xl(drive, bucket: str, name: str) -> bool:
    try:
        return drive.read_xl(bucket, name) is not None
    except errors.StorageError:
        return False


def chaos_harness(tmp_path, n_disks: int = 8, parity: int = 2):
    """ErasureHarness whose drives are wrapped in FaultyDisk over a PRIVATE
    registry (the process-global one is the admin plane's; tests isolate)."""
    reg = FaultRegistry()
    hz = ErasureHarness(tmp_path, n_disks=n_disks, parity=parity)
    hz.layer.disks = [FaultyDisk(d, reg) for d in hz.drives]
    return hz, reg


# ---------------------------------------------------------------------------
# Registry semantics: validation, determinism, budgets, zero overhead
# ---------------------------------------------------------------------------


class TestRegistry:
    def test_spec_validation(self):
        with pytest.raises(ValueError):
            FaultSpec(kind="melt-the-cpu")
        with pytest.raises(ValueError):
            FaultSpec(kind=DRIVE_ERROR, probability=0.0)
        with pytest.raises(ValueError):
            FaultSpec.from_dict({"target": "no-kind"})
        spec = FaultSpec.from_dict({"kind": BITROT, "count": 3, "seed": 7})
        # Bitrot defaults to the write side (corruption at rest).
        assert spec.ops == ("create_file", "append_file", "append_iov")
        assert FaultSpec.from_dict(spec.to_dict()).ops == spec.ops

    def test_fixed_seed_reproduces_schedule(self):
        def pattern(seed: int) -> list[bool]:
            reg = FaultRegistry()
            reg.arm(FaultSpec(kind=DRIVE_ERROR, probability=0.5, seed=seed))
            return [
                reg.match_disk("/x/disk0", "read_all", "b", f"o{i}") is not None
                for i in range(64)
            ]

        first = pattern(99)
        assert pattern(99) == first  # same seed, same call sequence => replay
        assert pattern(100) != first
        assert any(first) and not all(first)

    def test_budget_exhaustion_restores_passthrough(self, tmp_path):
        hz, reg = chaos_harness(tmp_path, n_disks=4, parity=2)
        fd = hz.layer.disks[0]
        reg.arm(FaultSpec(kind=DRIVE_ERROR, count=2))
        for _ in range(2):
            with pytest.raises(errors.FaultyDisk):
                fd.disk_info()
        # Budget spent: the snapshot empties and calls flow through again.
        assert reg.disk is None
        assert fd.disk_info().total > 0
        assert reg.list()[0]["remaining"] == 0
        assert reg.injected_counts()[(DRIVE_ERROR, "*")] == 2

    def test_disarmed_passthrough_is_identity(self, tmp_path):
        hz, reg = chaos_harness(tmp_path, n_disks=4, parity=2)
        fd, inner = hz.layer.disks[1], hz.drives[1]
        # Disarmed: the wrapper returns the INNER bound method itself -- the
        # "one None check" zero-overhead contract from the issue.
        assert fd.read_all.__self__ is inner
        fid = reg.arm(FaultSpec(kind=DRIVE_LATENCY, delay_ms=1))
        assert getattr(fd.read_all, "__self__", None) is not inner
        reg.disarm(fid)
        assert fd.read_all.__self__ is inner

    def test_latency_and_hang(self, tmp_path):
        hz, reg = chaos_harness(tmp_path, n_disks=4, parity=2)
        fd = hz.layer.disks[0]
        fd.make_vol("lat")
        fd.write_all("lat", "a", b"x")
        fid = reg.arm(FaultSpec(kind=DRIVE_LATENCY, delay_ms=60, ops=("read_all",)))
        t0 = time.monotonic()
        assert fd.read_all("lat", "a") == b"x"  # delayed, not broken
        assert time.monotonic() - t0 >= 0.05
        reg.disarm(fid)
        reg.arm(FaultSpec(kind=DRIVE_HANG, delay_ms=20, ops=("read_all",)))
        t0 = time.monotonic()
        with pytest.raises(errors.FaultyDisk):
            fd.read_all("lat", "a")
        assert time.monotonic() - t0 >= 0.015

    def test_flip_byte_changes_exactly_one_byte(self):
        buf = bytes(range(256))
        out = flip_byte(buf)
        assert len(out) == len(buf)
        assert sum(1 for a, b in zip(buf, out) if a != b) == 1
        assert flip_byte(b"") == b""


# ---------------------------------------------------------------------------
# Scenario: corrupt shard (bitrot at rest) -> GET reconstructs, heal converges
# ---------------------------------------------------------------------------


class TestBitrotScenario:
    def test_bitrot_then_get_then_heal_bit_identical(self, tmp_path):
        """The fast tier-1 smoke scenario: one drive writes a corrupt shard,
        reads still verify+reconstruct, heal rewrites it, and the healed
        shard alone serves bit-identical bytes."""
        hz, reg = chaos_harness(tmp_path, n_disks=8, parity=2)
        hz.layer.make_bucket("cb")
        data = bytes(i % 251 for i in range(300_000))  # > inline threshold
        reg.arm(FaultSpec(kind=BITROT, target="disk3", count=1, seed=1))
        hz.layer.put_object("cb", "obj", data)
        assert reg.disk is None  # budget spent during the put
        assert reg.injected_counts()[(BITROT, "disk3")] == 1

        # Read with the corruption at rest: frame digests flag the bad shard
        # and the decoder reconstructs from the healthy rows.
        _, got = hz.layer.get_object("cb", "obj")
        assert got == data

        res = hz.layer.heal_object("cb", "obj")
        assert res.disks_healed >= 1

        # Reads after heal are bit-identical THROUGH the healed shard: drop
        # the full parity budget elsewhere so disk3's row must participate.
        others = [i for i in range(8) if i != 3][:2]
        hz.take_offline(*others)
        _, got = hz.layer.get_object("cb", "obj")
        assert got == data


# ---------------------------------------------------------------------------
# Scenario: kill k drives mid-PUT -> quorum holds, heal converges
# ---------------------------------------------------------------------------


@pytest.mark.slow
class TestDriveLossScenario:
    def test_kill_four_drives_mid_put_quorum_reads_and_heal(self, tmp_path):
        """The issue's n=12+4 acceptance scenario: the full parity budget of
        drives dies during a streaming PUT; the write lands at quorum, reads
        succeed while the drives are still dead, heal re-protects, and the
        healed shards alone are bit-identical."""
        hz, reg = chaos_harness(tmp_path, n_disks=16, parity=4)
        hz.layer.make_bucket("kb")
        data = bytes((i * 31) % 256 for i in range(3 << 20))
        dead = [2, 3, 4, 5]  # disk2..disk5: no substring collision with 10-15
        fids = [
            reg.arm(FaultSpec(kind=DRIVE_ERROR, target=f"disk{i}", seed=i))
            for i in dead
        ]
        oi = hz.layer.put_object("kb", "big", data)
        assert oi.size == len(data)

        # Quorum reads succeed with the faults still armed.
        _, got = hz.layer.get_object("kb", "big")
        assert got == data

        for fid in fids:
            reg.disarm(fid)
        assert reg.disk is None
        res = hz.layer.heal_object("kb", "big")
        assert res.disks_healed == len(dead)

        # Force reads through the healed rows: take four HEALTHY drives away.
        hz.take_offline(6, 7, 8, 9)
        _, got = hz.layer.get_object("kb", "big")
        assert got == data
        # Heal converged: a re-heal has nothing left to do.
        assert hz.layer.heal_object("kb", "big").disks_healed == 0


# ---------------------------------------------------------------------------
# Scenario: partial PUT -> MRF re-drives the repair
# ---------------------------------------------------------------------------


class TestMRF:
    def test_partial_put_feeds_mrf_and_drain_redrives(self, tmp_path):
        hz, reg = chaos_harness(tmp_path, n_disks=8, parity=2)
        mrf = MRFQueue(hz.layer, start=False)
        hz.layer.on_partial = mrf.add
        hz.layer.make_bucket("mb")
        reg.arm(FaultSpec(kind=DRIVE_ERROR, target="disk2"))
        hz.layer.put_object("mb", "part", b"p" * 1000)  # inline, 7/8 drives
        assert mrf.pending() == 1
        assert not _has_xl(hz.drives[2], "mb", "part")  # drive missed it

        reg.disarm_all()
        assert mrf.drain() == 1
        assert mrf.healed == 1 and mrf.pending() == 0
        assert _has_xl(hz.drives[2], "mb", "part")  # re-driven

    def test_full_quorum_put_does_not_feed_mrf(self, tmp_path):
        hz, _ = chaos_harness(tmp_path, n_disks=8, parity=2)
        mrf = MRFQueue(hz.layer, start=False)
        hz.layer.on_partial = mrf.add
        hz.layer.make_bucket("mb")
        hz.layer.put_object("mb", "clean", b"c" * 1000)
        assert mrf.pending() == 0

    def test_drop_counter_and_once_per_episode_log(self, caplog):
        mrf = MRFQueue(None, maxsize=2, start=False)
        with caplog.at_level("WARNING", logger="minio_tpu.heal"):
            for i in range(5):
                mrf.add("b", f"o{i}")
        assert mrf.pending() == 2
        assert mrf.dropped == 3
        # One warning for the whole overflow episode, not one per drop.
        episode_logs = [r for r in caplog.records if "MRF queue full" in r.message]
        assert len(episode_logs) == 1
        # Queue drains -> a successful add closes the episode; the NEXT
        # overflow logs again.
        mrf.q.get_nowait()
        caplog.clear()
        with caplog.at_level("WARNING", logger="minio_tpu.heal"):
            mrf.add("b", "ok")      # fits: episode over
            mrf.add("b", "drop2")   # full again: new episode, new log line
        assert mrf.dropped == 4
        assert sum("MRF queue full" in r.message for r in caplog.records) == 1


# ---------------------------------------------------------------------------
# Network faults through the one RestClient seam
# ---------------------------------------------------------------------------


@pytest.fixture()
def lock_cluster():
    """Three in-process lock REST servers (dsync-server_test.go analogue)."""
    from minio_tpu.api.server import ThreadedServer

    lockers = [LocalLocker() for _ in range(3)]
    ports = [_free_port() for _ in range(3)]
    servers = []
    for lk, port in zip(lockers, ports):
        app = web.Application()
        app.add_subapp(LOCK_PREFIX, make_lock_app(lk, TOKEN))
        ts = ThreadedServer(SimpleNamespace(app=app), port=port)
        ts.start()
        servers.append(ts)
    urls = [f"http://127.0.0.1:{p}" for p in ports]
    yield {"lockers": lockers, "urls": urls, "servers": servers}
    for ts in servers:
        ts.stop()


class TestNetFaults:
    def test_partition_and_slow_rpc_on_restclient(self, lock_cluster):
        url = lock_cluster["urls"][0]
        client = RestClient(url + LOCK_PREFIX, TOKEN)
        args = {"resource": "net/res", "uid": "u1"}
        assert client.call("/refresh", args) == {"ok": False}

        port = url.rsplit(":", 1)[1]
        fid = REGISTRY.arm(
            FaultSpec(kind=PARTITION, target=f"127.0.0.1:{port}", count=1)
        )
        try:
            with pytest.raises(errors.DiskNotFound, match="chaos"):
                client.call("/refresh", args)
            assert client.is_online()  # injected failure, not a marked peer
            assert client.call("/refresh", args) == {"ok": False}  # budget spent
        finally:
            REGISTRY.disarm(fid)

        fid = REGISTRY.arm(
            FaultSpec(kind=SLOW_RPC, target=f"127.0.0.1:{port}", delay_ms=80, count=1)
        )
        try:
            t0 = time.monotonic()
            assert client.call("/refresh", args) == {"ok": False}
            assert time.monotonic() - t0 >= 0.07
        finally:
            REGISTRY.disarm(fid)

    def test_injected_counts_surface_in_metrics(self):
        from minio_tpu.control.metrics import MetricsSys

        # Target matches nothing real: consume the budget directly so the
        # counter moves without touching live traffic.
        fid = REGISTRY.arm(FaultSpec(kind=PARTITION, target="metrics-probe", count=1))
        try:
            assert REGISTRY.match_net("http://x/", "/metrics-probe") is not None
            text = MetricsSys().render_node()
        finally:
            REGISTRY.disarm(fid)
        assert "minio_tpu_chaos_injected_total" in text
        assert 'kind="partition"' in text
        assert 'target="metrics-probe"' in text


class TestLockDeath:
    def test_quorum_acquire_with_one_lock_server_down(self, lock_cluster):
        urls = lock_cluster["urls"]
        dead = f"http://127.0.0.1:{_free_port()}"  # nothing listening
        lockers = [RemoteLocker(urls[0], TOKEN), RemoteLocker(urls[1], TOKEN),
                   RemoteLocker(dead, TOKEN)]
        m = DRWMutex(lockers, "chaos/one-down")
        assert m.acquire(writer=True, timeout=5)  # 2/3 = write quorum
        m.release()
        # Two dead servers: quorum unreachable, acquire must give up.
        lockers2 = [RemoteLocker(urls[0], TOKEN), RemoteLocker(dead, TOKEN),
                    RemoteLocker(f"http://127.0.0.1:{_free_port()}", TOKEN)]
        m2 = DRWMutex(lockers2, "chaos/two-down")
        assert not m2.acquire(writer=True, timeout=0.8)

    def test_lock_death_fault_fires_on_lost(self, lock_cluster):
        """Drop the lock quorum mid-hold: the chaos lock-death fault blackholes
        lock REST only, the refresh round loses quorum, and the holder's
        on_lost cancellation hook fires (drwmutex.go:221 semantics)."""
        urls = lock_cluster["urls"]
        lost_calls = []
        lockers = [RemoteLocker(u, TOKEN) for u in urls]
        m = DRWMutex(lockers, "chaos/mid-write", on_lost=lambda: lost_calls.append(1))
        assert m.acquire(writer=True, timeout=5)
        assert m._refresh_round()  # healthy refresh first

        fid = REGISTRY.arm(FaultSpec(kind=LOCK_DEATH))
        try:
            assert not m._refresh_round()
        finally:
            REGISTRY.disarm(fid)
        assert m.lost.is_set()
        assert lost_calls == [1]
        m.release()

    def test_force_unlock_fanout_frees_a_wedged_resource(self, lock_cluster):
        urls = lock_cluster["urls"]
        lockers = [RemoteLocker(u, TOKEN) for u in urls]
        holder = DRWMutex(lockers, "chaos/wedged")
        assert holder.acquire(writer=True, timeout=5)
        waiter = DRWMutex(lockers, "chaos/wedged")
        assert not waiter.acquire(writer=True, timeout=0.4)
        # Admin force-unlock fans out to every locker (the mc admin
        # force-unlock story for a crashed holder).
        for lk in lockers:
            assert lk.force_unlock("chaos/wedged")
        assert waiter.acquire(writer=True, timeout=5)
        waiter.release()
        holder.release()


# ---------------------------------------------------------------------------
# Retry jitter (dist/transport.py satellite)
# ---------------------------------------------------------------------------


def test_retry_jitter_bounds_and_spread():
    vals = [jitter(3.0) for _ in range(300)]
    assert all(2.6999 <= v <= 3.3001 for v in vals)
    assert max(vals) - min(vals) > 0.01  # actually random, not a constant
    assert all(0.89999 <= jitter(1.0, frac=0.1) <= 1.10001 for _ in range(50))


# ---------------------------------------------------------------------------
# DiskHealMonitor: stop() checkpoints, restart resumes (satellite)
# ---------------------------------------------------------------------------


class TestHealRestartResume:
    def test_stop_checkpoints_cursor_and_restart_resumes(self, tmp_path):
        hz = ErasureHarness(tmp_path, n_disks=8)
        pools = ServerPools([ErasureSets(list(hz.drives), 8)])
        pools.make_bucket("resume-bkt")
        names = [f"obj-{i:02d}" for i in range(6)]
        for n in names:
            pools.put_object("resume-bkt", n, b"r" * 1000)

        fresh = _replace_drive(hz, 3)
        for s in pools.pools[0].sets:
            s.disks[3] = fresh
        mark_drive_for_healing(fresh)

        eo = pools.pools[0].sets[0]
        real_heal = eo.heal_object
        mon = DiskHealMonitor(pools, interval=999, checkpoint_every=100, start=False)
        first_pass: list[str] = []

        def stopping_heal(bucket, name, vid="", **kw):
            first_pass.append(name)
            if len(first_pass) == 3:
                mon.stop()  # a restart arrives mid-sweep
            return real_heal(bucket, name, vid, **kw)

        eo.heal_object = stopping_heal
        assert mon.tick() == 0  # interrupted, not finished

        # The stop checkpointed the cursor at the last healed object.
        tr = HealingTracker.load(fresh)
        assert tr is not None and not tr.finished
        assert (tr.resume_bucket, tr.resume_object) == ("resume-bkt", names[2])

        # "Restart": a new monitor resumes from the cursor and only walks the
        # tail, then converges and removes the tracker.
        second_pass: list[str] = []

        def counting_heal(bucket, name, vid="", **kw):
            second_pass.append(name)
            return real_heal(bucket, name, vid, **kw)

        eo.heal_object = counting_heal
        mon2 = DiskHealMonitor(pools, interval=999, start=False)
        assert mon2.tick() == 1
        assert second_pass == names[3:]
        assert HealingTracker.load(fresh) is None
        for n in names:
            assert _has_xl(fresh, "resume-bkt", n)


# ---------------------------------------------------------------------------
# Graceful degradation: hedged reads, circuit breakers, deadline propagation
# ---------------------------------------------------------------------------

# Fault targets match by SUBSTRING against the drive path, so "disk1" also
# matches disk10..disk15 on a 16-drive harness; deterministic scenarios pick
# targets from the collision-free index set.
SAFE_TARGETS = (0, 2, 3, 4, 5, 6, 7, 8, 9)


class TestHedgedReads:
    def test_slow_drive_get_hedges_within_slo(self, tmp_path):
        """The issue's acceptance SLO: a 10x-latency fault on ONE of 16
        drives must not 10x the GET -- the hedge fires after ~3x the median
        shard read and a parity row covers the straggler, so the wall stays
        near the fault-free baseline instead of the injected 1s stall."""
        hz, reg = chaos_harness(tmp_path, n_disks=16, parity=4)
        hz.layer.make_bucket("hb")
        data = bytes((i * 13) % 256 for i in range(4 << 20))
        hz.layer.put_object("hb", "big", data)

        t0 = time.monotonic()
        _, got = hz.layer.get_object("hb", "big")
        base = time.monotonic() - t0
        assert got == data

        # Find a collision-safe drive holding a DATA slot (drive i holds
        # shard distribution[i]-1; slots < k are data and read first).
        k = 12
        dist = hash_order("hb/big", 16)
        target = next(i for i in SAFE_TARGETS if dist[i] - 1 < k)

        before = GLOBAL_DEGRADE.snapshot()
        fid = reg.arm(FaultSpec(
            kind=DRIVE_LATENCY, target=f"disk{target}", delay_ms=1000,
            ops=("read_file", "read_file_into"),
        ))
        try:
            t0 = time.monotonic()
            _, got = hz.layer.get_object("hb", "big")
            wall = time.monotonic() - t0
        finally:
            reg.disarm(fid)
        assert got == data
        after = GLOBAL_DEGRADE.snapshot()
        # The hedge actually fired AND won (the counter the dashboards watch).
        assert after["hedge_launched"] > before["hedge_launched"]
        assert after["hedge_wins"] > before["hedge_wins"]
        # Wall bounded by the SLO, far under the injected 1s stall.
        assert wall < max(2 * base, 0.8), f"hedged GET took {wall:.3f}s (base {base:.3f}s)"


class TestBreakerScenario:
    def test_drive_error_trips_breaker_then_recloses(self, tmp_path):
        """Sustained drive errors trip the breaker within the threshold,
        reads keep succeeding at quorum while the drive fails fast, and the
        background probe re-closes the breaker once the fault is gone."""
        reg = FaultRegistry()
        hz = ErasureHarness(tmp_path, n_disks=8, parity=2)
        gated = [
            HealthGatedDrive(
                FaultyDisk(d, reg),
                breaker=CircuitBreaker(
                    name=f"disk{i}", error_threshold=3, cooldown=0.2, max_cooldown=1.0
                ),
            )
            for i, d in enumerate(hz.drives)
        ]
        hz.layer.disks = gated
        hz.layer.make_bucket("bb")
        data = bytes(i % 251 for i in range(300_000))
        hz.layer.put_object("bb", "obj", data)

        before = GLOBAL_DEGRADE.snapshot()
        fid = reg.arm(FaultSpec(kind=DRIVE_ERROR, target="disk3"))
        try:
            # Each GET scores health errors on disk3; within the threshold
            # the breaker opens -- and every read still succeeds at quorum.
            for _ in range(4):
                _, got = hz.layer.get_object("bb", "obj")
                assert got == data
            assert gated[3].breaker_state()["state"] == "open"
            assert not gated[3].is_online()
            # Open = fail-fast refusal, not a 30s hang on a sick drive.
            with pytest.raises(errors.CircuitOpen):
                gated[3].disk_info()
        finally:
            reg.disarm(fid)

        # Fault gone: the jittered background probe re-closes the breaker.
        wait_until = time.monotonic() + 5.0
        while time.monotonic() < wait_until and not gated[3].breaker.allows():
            time.sleep(0.05)
        assert gated[3].breaker.allows(), "breaker never re-closed after fault removal"
        assert gated[3].is_online()
        after = GLOBAL_DEGRADE.snapshot()
        assert after["breaker_trips"] > before["breaker_trips"]
        assert after["breaker_closes"] > before["breaker_closes"]
        _, got = hz.layer.get_object("bb", "obj")
        assert got == data


class TestDeadlinePropagation:
    def test_deadline_aborts_chaos_stalled_rpc_chain(self, lock_cluster):
        """The issue's acceptance bound: a propagated 0.5s budget aborts an
        RPC chain stalled by an injected slow link in well under 2s, instead
        of riding the channel's full 30s timeout."""
        url = lock_cluster["urls"][0]
        client = RestClient(url + LOCK_PREFIX, TOKEN)
        args = {"resource": "dl/res", "uid": "u1"}
        assert client.call("/refresh", args) == {"ok": False}  # channel healthy

        port = url.rsplit(":", 1)[1]
        fid = REGISTRY.arm(
            FaultSpec(kind=SLOW_RPC, target=f"127.0.0.1:{port}", delay_ms=800)
        )
        try:
            t0 = time.monotonic()
            with deadline.scope(0.5):
                with pytest.raises(errors.DeadlineExceeded):
                    client.call("/refresh", args)
            wall = time.monotonic() - t0
        finally:
            REGISTRY.disarm(fid)
        assert wall < 2.0, f"deadline abort took {wall:.3f}s"
        # Outside the scope the budget is gone and the channel still works.
        assert client.call("/refresh", args) == {"ok": False}

    def test_deadline_caps_socket_timeout_in_flight(self):
        """A peer that accepts but never answers: the remaining budget caps
        the socket timeout, and the capped timeout surfaces as
        DeadlineExceeded (budget spent) rather than DiskNotFound."""
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        s.listen(1)  # handshake completes; no byte is ever answered
        port = s.getsockname()[1]
        try:
            client = RestClient(f"http://127.0.0.1:{port}", TOKEN)
            t0 = time.monotonic()
            with deadline.scope(0.4):
                with pytest.raises(errors.DeadlineExceeded):
                    client.call("/refresh", {"resource": "x", "uid": "u"})
            assert time.monotonic() - t0 < 2.0
        finally:
            s.close()

    def test_expired_deadline_aborts_erasure_get(self, tmp_path):
        hz, _ = chaos_harness(tmp_path, n_disks=8, parity=2)
        hz.layer.make_bucket("db")
        hz.layer.put_object("db", "obj", bytes(300_000))  # > inline threshold
        before = GLOBAL_DEGRADE.snapshot()
        with deadline.scope(0.001):
            time.sleep(0.005)  # spend the budget before the read starts
            with pytest.raises(errors.DeadlineExceeded):
                hz.layer.get_object("db", "obj")
        after = GLOBAL_DEGRADE.snapshot()
        assert (
            after["deadline_aborts"].get("erasure-get", 0)
            > before["deadline_aborts"].get("erasure-get", 0)
        )

    def test_multipart_deadline_expiry_leaks_no_stage_files(self, tmp_path, monkeypatch):
        """Deadline expiry mid-part-upload aborts with DeadlineExceeded and
        the staged shard files are cleaned up on every drive (the
        no-leaked-stage-files invariant of the put cleanup path)."""
        import minio_tpu.object.erasure as erasure_mod

        monkeypatch.setattr(erasure_mod, "GROUP_BLOCKS", 2)
        hz = ErasureHarness(tmp_path, n_disks=8, parity=2)
        hz.layer.make_bucket("mdb")
        mp = hz.layer.multipart
        uid = mp.new_multipart_upload("mdb", "obj")
        data = bytes(3 << 20)  # 3 blocks: the check fires at the group boundary
        before = GLOBAL_DEGRADE.snapshot()
        with deadline.scope(0.001):
            time.sleep(0.005)
            with pytest.raises(errors.DeadlineExceeded):
                mp.put_object_part("mdb", "obj", uid, 1, data)
        after = GLOBAL_DEGRADE.snapshot()
        assert (
            after["deadline_aborts"].get("multipart-put", 0)
            > before["deadline_aborts"].get("multipart-put", 0)
        )
        leaked = [
            os.path.join(root, f)
            for d in hz.dirs
            for root, _, files in os.walk(d)
            for f in files
            if ".tmp." in f
        ]
        assert not leaked, f"stage files leaked past the deadline abort: {leaked}"
        # The upload itself survives: only the aborted part was rolled back.
        assert mp.list_parts("mdb", "obj", uid) == []

    def test_streaming_put_deadline_expiry_cleans_up(self, tmp_path, monkeypatch):
        import minio_tpu.object.erasure as erasure_mod

        monkeypatch.setattr(erasure_mod, "GROUP_BLOCKS", 2)
        hz = ErasureHarness(tmp_path, n_disks=8, parity=2)
        hz.layer.make_bucket("sdb")
        with deadline.scope(0.001):
            time.sleep(0.005)
            with pytest.raises(errors.DeadlineExceeded):
                hz.layer.put_object("sdb", "big", bytes(3 << 20))
        with pytest.raises(errors.ObjectNotFound):
            hz.layer.get_object("sdb", "big")
        leaked = [
            f
            for d in hz.dirs
            for _, _, files in os.walk(d)
            for f in files
            if ".tmp." in f or f.startswith("part.")
        ]
        assert not leaked, f"shards leaked past the deadline abort: {leaked}"


# ---------------------------------------------------------------------------
# Cluster plane: admin /chaos API + partition during multipart complete
# ---------------------------------------------------------------------------


ADMIN = "/mtpu/admin/v1"


@pytest.mark.slow
class TestClusterChaos:
    @pytest.fixture(scope="class")
    def cluster(self, tmp_path_factory):
        from minio_tpu.api.server import ThreadedServer
        from minio_tpu.dist.node import Node
        from tests.s3client import S3TestClient

        root, secret = "chaosadmin", "chaos-secret-key"
        tmp = tmp_path_factory.mktemp("chaoscluster")
        ports = [_free_port(), _free_port()]
        urls = [f"http://127.0.0.1:{p}" for p in ports]
        endpoints = []
        for ni in range(2):
            for di in range(4):
                endpoints.append(f"{urls[ni]}{tmp}/n{ni}d{di}")
        nodes = [
            Node(endpoints, url=urls[ni], root_user=root, root_password=secret,
                 set_drive_count=8)
            for ni in range(2)
        ]
        servers = []
        for ni, node in enumerate(nodes):
            ts = ThreadedServer(SimpleNamespace(app=node.make_app()), port=ports[ni])
            ts.start()
            servers.append(ts)
        threads = [threading.Thread(target=n.build) for n in nodes]
        for t in threads:
            t.start()
        for t in threads:
            t.join(60)
        assert all(n.pools is not None for n in nodes), "cluster failed to build"
        clients = [S3TestClient(urls[ni], root, secret) for ni in range(2)]
        yield {"nodes": nodes, "clients": clients, "urls": urls, "ports": ports}
        REGISTRY.disarm_all()  # never leak armed faults past the fixture
        for ts in servers:
            ts.stop()

    def test_admin_arm_list_disarm_lifecycle(self, cluster):
        c0 = cluster["clients"][0]
        r = c0.request(
            "POST", ADMIN + "/chaos",
            body=json.dumps({"kind": "slow-rpc", "delay_ms": 1}).encode(),
        )
        assert r.status_code == 200, r.text
        fid = r.json()["fault_id"]
        assert fid

        r = c0.request("GET", ADMIN + "/chaos")
        assert r.status_code == 200
        listing = r.json()
        assert any(f["fault_id"] == fid for f in listing["local"])
        # Cluster-wide view includes every peer's registry.
        peer_lists = [v for k, v in listing.items() if k != "local"]
        assert peer_lists and all(
            any(f["fault_id"] == fid for f in faults) for faults in peer_lists if faults
        )

        r = c0.request("POST", ADMIN + "/chaos", body=b"{\"kind\": \"not-a-kind\"}")
        assert r.status_code == 400  # InvalidArgument, not a 500

        r = c0.request("DELETE", ADMIN + "/chaos", query=[("fault-id", fid)])
        assert r.status_code == 200
        r = c0.request("GET", ADMIN + "/chaos")
        assert not r.json()["local"]

    def test_partition_during_multipart_complete(self, cluster):
        """Blackhole part of the commit fanout to the peer node DURING
        complete-multipart: the commit still lands at write quorum and the
        assembled object reads back bit-identical."""
        import xml.etree.ElementTree as ET

        NS = "{http://s3.amazonaws.com/doc/2006-03-01/}"
        c0 = cluster["clients"][0]
        port1 = cluster["ports"][1]
        c0.make_bucket("mpchaos")
        part1 = bytes((i * 7) % 256 for i in range(5 << 20))
        part2 = b"tail" * 64

        r = c0.request("POST", "/mpchaos/big", query=[("uploads", "")])
        assert r.status_code == 200, r.text
        uid = ET.fromstring(r.content).find(f"{NS}UploadId").text
        e1 = c0.request(
            "PUT", "/mpchaos/big", query=[("partNumber", "1"), ("uploadId", uid)],
            body=part1,
        ).headers["ETag"]
        e2 = c0.request(
            "PUT", "/mpchaos/big", query=[("partNumber", "2"), ("uploadId", uid)],
            body=part2,
        ).headers["ETag"]

        # Partition exactly the per-drive commit RPCs to the peer node, with
        # a budget below the parity slack: 2 of the 4 remote rename_data
        # calls fail, 6/8 drives commit >= the k+1=5 write quorum.
        r = c0.request(
            "POST", ADMIN + "/chaos",
            body=json.dumps({
                "kind": "partition",
                "target": f"127.0.0.1:{port1}/mtpu/storage/v1/renamedata",
                "count": 2,
                "cluster": False,
            }).encode(),
        )
        assert r.status_code == 200, r.text
        fid = r.json()["fault_id"]
        try:
            body = (
                f"<CompleteMultipartUpload>"
                f"<Part><PartNumber>1</PartNumber><ETag>{e1}</ETag></Part>"
                f"<Part><PartNumber>2</PartNumber><ETag>{e2}</ETag></Part>"
                f"</CompleteMultipartUpload>"
            ).encode()
            r = c0.request("POST", "/mpchaos/big", query=[("uploadId", uid)], body=body)
            assert r.status_code == 200, r.text
        finally:
            c0.request("DELETE", ADMIN + "/chaos", query=[("fault-id", fid)])

        got = c0.get_object("mpchaos", "big")
        assert got.status_code == 200
        assert got.content == part1 + part2

        # The injections really happened and are visible on the metrics plane.
        m = c0.request("GET", "/minio/v2/metrics/node")
        assert m.status_code == 200
        assert "minio_tpu_chaos_injected_total" in m.text


class TestDecommission:
    """Pool decommission under fire: writers racing the drain, a kill
    mid-drain resumed from the journaled checkpoint -- the invariant in
    every case is zero objects lost, zero doubled."""

    @staticmethod
    def _make_pools(tmp_path, n_pools=2, n_disks=4):
        from minio_tpu.storage import format as fmt_mod
        from minio_tpu.storage.local import LocalDrive

        pools = []
        for pi in range(n_pools):
            formats = fmt_mod.init_format(1, n_disks)
            drives = []
            for i in range(n_disks):
                root = str(tmp_path / f"pool{pi}" / f"disk{i}")
                os.makedirs(root, exist_ok=True)
                formats[i].save(root)
                drives.append(LocalDrive(root))
            pools.append(
                ErasureSets.from_drives(drives, formats[0], pool_index=pi)
            )
        return ServerPools(pools)

    def test_decommission_under_concurrent_writes(self, tmp_path):
        from minio_tpu.object.poolmgr import PoolManager

        layer = self._make_pools(tmp_path)
        layer.make_bucket("chaos-bkt")
        for i in range(16):
            layer.pools[0].put_object("chaos-bkt", f"pre-{i:03d}", b"p" * 128)

        stop_writing = threading.Event()
        written: list[str] = []

        def writer(wi: int) -> None:
            # Live traffic racing the drain: overwrites of draining-pool
            # objects and fresh keys, all through the placement path.
            i = 0
            while not stop_writing.is_set():
                name = f"live-{wi}-{i:03d}"
                layer.put_object("chaos-bkt", name, b"w" * 64)
                written.append(name)
                layer.put_object("chaos-bkt", f"pre-{(i + wi) % 16:03d}",
                                 b"overwrite" * 8)
                i += 1
                time.sleep(0.002)

        threads = [
            threading.Thread(target=writer, args=(wi,)) for wi in range(2)
        ]
        pm = PoolManager(layer)
        for t in threads:
            t.start()
        try:
            pm.start_decommission(0, wait=True, checkpoint_every=4)
        finally:
            stop_writing.set()
            for t in threads:
                t.join(10)
        tracker = pm.trackers[0]
        assert tracker.finished, tracker.failed
        assert layer.statuses[0] == "decommissioned"
        assert pm._pool_object_count(layer.pools[0]) == 0
        # Zero lost, zero doubled: every acked write reads back, and the
        # merged listing holds exactly one entry per name.
        expected = {f"pre-{i:03d}" for i in range(16)} | set(written)
        listed = [
            o.name
            for o in layer.list_objects("chaos-bkt", max_keys=10000).objects
        ]
        assert sorted(listed) == sorted(expected)
        assert len(listed) == len(set(listed))
        for name in expected:
            _info, data = layer.get_object("chaos-bkt", name)
            assert data in (b"p" * 128, b"w" * 64, b"overwrite" * 8)

    def test_decommission_killed_then_resumed_no_loss(self, tmp_path):
        from minio_tpu.object.poolmgr import DecommissionTracker, PoolManager

        layer = self._make_pools(tmp_path)
        layer.make_bucket("chaos-bkt")
        n = 20
        for i in range(n):
            layer.pools[0].put_object("chaos-bkt", f"k-{i:03d}", b"d" * 96)

        pm = PoolManager(layer)
        state = {"batches": 0}

        def kill_hook(_tracker):
            state["batches"] += 1
            if state["batches"] == 2:
                raise RuntimeError("chaos: node killed mid-decommission")

        pm._drain_hook = kill_hook
        pm.start_decommission(0, wait=True, checkpoint_every=4)
        assert not pm.trackers[0].finished
        assert "killed" in pm.trackers[0].failed

        # Another process takes over from the journal (the checkpoint was
        # written OFF the draining pool, so it survived).
        pm2 = PoolManager(layer)
        pm2.load_config()
        assert DecommissionTracker.load(layer, 0) is not None
        assert pm2.resume_pending() == [0]
        pm2.join()
        assert pm2.trackers[0].finished, pm2.trackers[0].failed
        assert layer.statuses[0] == "decommissioned"
        listed = [
            o.name
            for o in layer.list_objects("chaos-bkt", max_keys=1000).objects
        ]
        assert listed == [f"k-{i:03d}" for i in range(n)]
        for i in range(n):
            _info, data = layer.get_object("chaos-bkt", f"k-{i:03d}")
            assert data == b"d" * 96


@pytest.mark.slow
class TestDecommissionCluster:
    """Two real nodes over a two-pool endpoint layout: node 0 starts the
    drain and dies mid-flight; node 1 picks the journal up, finishes it,
    and the epoch fanout leaves both nodes agreeing pool 0 is gone."""

    def test_decommission_node_kill_peer_resumes(self, tmp_path):
        from minio_tpu.api.server import ThreadedServer
        from minio_tpu.dist.node import Node
        from tests.s3client import S3TestClient

        root, secret = "chaosadmin", "chaos-secret-key"
        ports = [_free_port(), _free_port()]
        urls = [f"http://127.0.0.1:{p}" for p in ports]
        pools = [
            [f"{urls[ni]}{tmp_path}/p{pi}n{ni}d{di}" for ni in range(2)
             for di in range(4)]
            for pi in range(2)
        ]
        nodes = [
            Node(pools, url=urls[ni], root_user=root, root_password=secret,
                 set_drive_count=8)
            for ni in range(2)
        ]
        servers = []
        try:
            for ni, node in enumerate(nodes):
                ts = ThreadedServer(
                    SimpleNamespace(app=node.make_app()), port=ports[ni]
                )
                ts.start()
                servers.append(ts)
            threads = [threading.Thread(target=n.build) for n in nodes]
            for t in threads:
                t.start()
            for t in threads:
                t.join(60)
            assert all(n.pools is not None for n in nodes), "build failed"

            c0 = S3TestClient(urls[0], root, secret)
            c0.make_bucket("decom")
            for i in range(24):
                # Pin half the keyspace onto pool 0 directly so the drain
                # has real work regardless of free-space placement.
                nodes[0].pools.pools[0].put_object(
                    "decom", f"obj-{i:03d}", b"c" * 256
                )

            state = {"batches": 0}

            def kill_hook(_tracker):
                state["batches"] += 1
                if state["batches"] == 2:
                    raise RuntimeError("chaos: node 0 killed mid-drain")

            nodes[0].poolmgr._drain_hook = kill_hook
            r = c0.request(
                "POST", ADMIN + "/pools/decommission",
                body=json.dumps({"pool": 0, "wait": True}).encode(),
            )
            assert r.status_code == 200, r.text
            assert not r.json()["drain"]["finished"]

            # Node 1 learned DRAINING from the epoch fanout; its resume
            # picks the journal up and finishes what node 0 started.
            assert nodes[1].pools.statuses[0] == "draining"
            assert nodes[1].poolmgr.resume_pending() == [0]
            nodes[1].poolmgr.join()
            tr = nodes[1].poolmgr.trackers[0]
            assert tr.finished, tr.failed

            # Fanout propagated the terminal state back to node 0.
            assert nodes[0].reload_pools() or (
                nodes[0].pools.statuses[0] == "decommissioned"
            )
            assert nodes[0].pools.statuses[0] == "decommissioned"
            assert nodes[1].pools.statuses[0] == "decommissioned"
            # Every object survived, served through either node.
            for ni in (0, 1):
                c = S3TestClient(urls[ni], root, secret)
                for i in range(24):
                    got = c.get_object("decom", f"obj-{i:03d}")
                    assert got.status_code == 200, (ni, i, got.status_code)
                    assert got.content == b"c" * 256
            st = c0.request("GET", ADMIN + "/pools/status")
            assert st.status_code == 200
            rows = st.json()["pools"]
            assert rows[0]["status"] == "decommissioned"
            assert rows[0]["drain"]["objects_moved"] >= 24
        finally:
            for ts in servers:
                ts.stop()
            for node in nodes:
                try:
                    node.close()
                except Exception:  # noqa: BLE001 - teardown best effort
                    pass
