"""Hot-read memory cache: coherence, fault isolation, singleflight.

The tier's three promises, each with a test class:

  * Coherence -- a PUT/DELETE through any node drops every node's cached
    entries BEFORE the write acks (write-path invalidation + synchronous
    peer fanout), so no reader anywhere observes pre-write bytes from
    cache after the writer's ack.
  * Fault isolation -- drive faults during a fill (offline drives, bitrot)
    either reconstruct the true bytes or cache nothing; a degraded read
    never poisons the tier with wrong data.
  * Singleflight -- N concurrent misses on one hot key cost exactly one
    backend read; followers wait on the leader's flight and serve the
    fresh entry.
"""

from __future__ import annotations

import os
import socket
import threading
import time
from types import SimpleNamespace

import pytest

from minio_tpu.object.memcache import (
    MemCacheConfig,
    MemCacheObjectLayer,
    MemObjectCache,
)
from minio_tpu.object.types import GetObjectOptions, ObjectInfo
from minio_tpu.utils import errors
from tests.harness import ErasureHarness


def _mc_layer(backend, limit_mb: int = 64, validate: bool = False):
    store = MemObjectCache(MemCacheConfig(limit_bytes=limit_mb << 20, validate=validate))
    return MemCacheObjectLayer(backend, store), store


def _read_all(layer, bucket: str, key: str) -> bytes:
    _, data = layer.get_object(bucket, key)
    return data


# -- store basics -------------------------------------------------------------


class TestMemObjectCache:
    def test_lru_evicts_under_budget(self):
        store = MemObjectCache(MemCacheConfig(limit_bytes=1 << 20, max_entry_bytes=1 << 20))
        oi = ObjectInfo(bucket="b", name="o", size=300 << 10, etag="e")
        for i in range(5):
            assert store.put(("b", f"o{i}", "", ()), oi, bytes(300 << 10))
        st = store.stats()
        assert st["bytes"] <= 1 << 20
        assert st["evictions"] >= 2
        # Evicted keys left no reverse-index debris: invalidating them is a
        # no-op, invalidating a live one drops exactly its entry.
        live = [k for k in [("b", f"o{i}", "", ()) for i in range(5)] if store.get(k)]
        assert store.invalidate_object("b", live[0][1]) == 1
        assert store.get(live[0]) is None

    def test_oversized_entry_rejected(self):
        store = MemObjectCache(MemCacheConfig(limit_bytes=1 << 20, max_entry_bytes=64 << 10))
        oi = ObjectInfo(bucket="b", name="o", size=65 << 10, etag="e")
        assert not store.put(("b", "o", "", ()), oi, bytes(65 << 10))
        assert store.stats()["entries"] == 0


# -- write-path invalidation (single node) ------------------------------------


class TestWriteInvalidation:
    def test_put_drops_cached_entry_before_ack(self, tmp_path):
        h = ErasureHarness(tmp_path)
        h.layer.make_bucket("b")
        mc, store = _mc_layer(h.layer)
        v1 = os.urandom(1 << 20)
        mc.put_object("b", "obj", v1)
        assert _read_all(mc, "b", "obj") == v1  # miss + fill
        assert store.stats()["fills"] == 1
        v2 = os.urandom(1 << 20)
        mc.put_object("b", "obj", v2)
        # The ack already returned: the stale entry must be gone NOW.
        assert store.get(("b", "obj", "", ())) is None
        assert store.stats()["invalidations"] >= 1
        assert _read_all(mc, "b", "obj") == v2

    def test_delete_drops_cached_entry(self, tmp_path):
        h = ErasureHarness(tmp_path)
        h.layer.make_bucket("b")
        mc, store = _mc_layer(h.layer)
        mc.put_object("b", "obj", os.urandom(256 << 10))
        _read_all(mc, "b", "obj")
        mc.delete_object("b", "obj")
        assert store.get(("b", "obj", "", ())) is None
        with pytest.raises(errors.ObjectNotFound):
            mc.get_object("b", "obj")


# -- drive faults during hot GETs ---------------------------------------------


class TestFaultsDontPoison:
    def test_degraded_fill_caches_reconstructed_truth(self, tmp_path):
        """A fill racing drive loss reconstructs through parity; the entry
        admitted to the tier must be the true bytes, and later healthy hits
        serve those same bytes."""
        h = ErasureHarness(tmp_path)
        h.layer.make_bucket("b")
        mc, store = _mc_layer(h.layer)
        body = os.urandom(2 << 20)
        mc.put_object("b", "hot", body)
        h.take_offline(0, 1)
        try:
            assert _read_all(mc, "b", "hot") == body  # degraded fill
        finally:
            h.bring_online(0, 1)
        assert store.stats()["fills"] == 1
        assert _read_all(mc, "b", "hot") == body  # served from cache
        assert store.stats()["hits"] >= 1

    def test_bitrot_during_fill_caches_reconstructed_truth(self, tmp_path):
        h = ErasureHarness(tmp_path)
        h.layer.make_bucket("b")
        mc, store = _mc_layer(h.layer)
        body = os.urandom(2 << 20)
        mc.put_object("b", "hot", body)
        corrupted = sum(
            1 for i in range(2) if h.corrupt_shard(i, "b", "hot")
        )
        assert corrupted  # at least one shard really flipped
        assert _read_all(mc, "b", "hot") == body
        assert _read_all(mc, "b", "hot") == body  # the cached copy is true
        assert store.stats()["hits"] >= 1

    def test_failed_read_caches_nothing(self, tmp_path):
        """Below read quorum the GET raises -- and the tier must hold NO
        entry for the key (caching an error or a partial read would pin the
        outage past drive recovery)."""
        h = ErasureHarness(tmp_path)
        h.layer.make_bucket("b")
        mc, store = _mc_layer(h.layer)
        body = os.urandom(1 << 20)
        mc.put_object("b", "hot", body)
        h.take_offline(0, 1, 2, 3, 4)  # 11 of 16 rows < k=12
        try:
            with pytest.raises(errors.StorageError):
                _read_all(mc, "b", "hot")
        finally:
            h.bring_online(0, 1, 2, 3, 4)
        assert store.get(("b", "hot", "", ())) is None
        assert store.stats()["entries"] == 0
        assert _read_all(mc, "b", "hot") == body  # recovers on healthy drives


# -- singleflight -------------------------------------------------------------


class _SlowBackend:
    """Counting stand-in for the erasure layer: one slow read, thread-safe
    counters, deterministic bytes."""

    def __init__(self, data: bytes, delay_s: float = 0.25):
        self.data = data
        self.delay_s = delay_s
        self.oi = ObjectInfo(bucket="b", name="hot", size=len(data), etag="e1")
        self.reads = 0
        self.infos = 0
        self._lock = threading.Lock()

    def get_object_info(self, bucket, object_name, opts=None):
        with self._lock:
            self.infos += 1
        return self.oi

    def get_object(self, bucket, object_name, opts=None, offset=0, length=-1):
        with self._lock:
            self.reads += 1
        time.sleep(self.delay_s)
        return self.oi, self.data


@pytest.mark.race
class TestSingleflight:
    def test_concurrent_hot_misses_read_backend_once(self):
        """N threads stampede one cold key: exactly one leader pays the
        backend read; every follower waits on its flight and serves the
        fresh entry."""
        n = 8
        backend = _SlowBackend(os.urandom(512 << 10))
        mc, store = _mc_layer(backend)
        barrier = threading.Barrier(n)
        results: list[bytes | None] = [None] * n
        failures: list[BaseException] = []

        def reader(i: int) -> None:
            try:
                barrier.wait(timeout=10)
                _, stream = mc.get_object_stream("b", "hot")
                results[i] = b"".join(bytes(c) for c in stream)
            except BaseException as e:  # noqa: BLE001 - surfaced below
                failures.append(e)

        threads = [threading.Thread(target=reader, args=(i,)) for i in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(30)
        assert not failures
        assert all(r == backend.data for r in results)
        assert backend.reads == 1
        st = store.stats()
        assert st["fills"] == 1
        assert st["singleflight_waits"] == n - 1


# -- cross-node coherence (2-node cluster) ------------------------------------


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


ROOT = "memadmin"
SECRET = "memcache-secret-key"


@pytest.fixture(scope="module")
def memcluster(tmp_path_factory):
    """Two nodes, both with the memory tier armed and per-hit validation
    off: coherence rides ENTIRELY on the write-path peer fanout, which is
    exactly what these tests must prove."""
    from minio_tpu.api.server import ThreadedServer
    from minio_tpu.dist.node import Node
    from tests.s3client import S3TestClient

    saved = {
        k: os.environ.get(k) for k in ("MTPU_MEMCACHE_MB", "MTPU_MEMCACHE_VALIDATE")
    }
    os.environ["MTPU_MEMCACHE_MB"] = "64"
    os.environ["MTPU_MEMCACHE_VALIDATE"] = "0"
    tmp = tmp_path_factory.mktemp("memcluster")
    ports = [_free_port(), _free_port()]
    urls = [f"http://127.0.0.1:{p}" for p in ports]
    endpoints = []
    for ni in range(2):
        for di in range(4):
            endpoints.append(f"{urls[ni]}{tmp}/n{ni}d{di}")
    servers = []
    try:
        nodes = [
            Node(endpoints, url=urls[ni], root_user=ROOT, root_password=SECRET,
                 set_drive_count=8)
            for ni in range(2)
        ]
        for ni, node in enumerate(nodes):
            ts = ThreadedServer(SimpleNamespace(app=node.make_app()), port=ports[ni])
            ts.start()
            servers.append(ts)
        threads = [threading.Thread(target=n.build) for n in nodes]
        for t in threads:
            t.start()
        for t in threads:
            t.join(60)
        assert all(n.pools is not None for n in nodes), "cluster failed to build"
        assert all(n.memcache is not None for n in nodes), "memcache tier absent"
        clients = [S3TestClient(urls[ni], ROOT, SECRET) for ni in range(2)]
        yield {"nodes": nodes, "clients": clients}
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        for ts in servers:
            ts.stop()


class TestCrossNodeCoherence:
    def test_put_on_a_invalidates_b_memcache_before_ack(self, memcluster):
        a, b = memcluster["clients"]
        node_b = memcluster["nodes"][1]
        assert a.make_bucket("cohere").status_code == 200
        v1 = os.urandom(128 << 10)
        assert a.put_object("cohere", "hot.bin", v1).status_code == 200
        # Warm node B's tier.
        r = b.get_object("cohere", "hot.bin")
        assert r.status_code == 200 and r.content == v1
        assert node_b.memcache.get(("cohere", "hot.bin", "", ())) is not None
        # Overwrite through node A. The fanout runs before A's ack, so by
        # the time put_object returns, B's entry is ALREADY gone -- no
        # sleep, no retry loop.
        v2 = os.urandom(128 << 10)
        assert a.put_object("cohere", "hot.bin", v2).status_code == 200
        assert node_b.memcache.get(("cohere", "hot.bin", "", ())) is None
        r = b.get_object("cohere", "hot.bin")
        assert r.status_code == 200 and r.content == v2

    def test_delete_on_a_404s_warm_reader_on_b(self, memcluster):
        a, b = memcluster["clients"]
        node_b = memcluster["nodes"][1]
        body = os.urandom(64 << 10)
        assert a.put_object("cohere", "gone.bin", body).status_code == 200
        assert b.get_object("cohere", "gone.bin").content == body
        assert a.delete_object("cohere", "gone.bin").status_code == 204
        assert node_b.memcache.get(("cohere", "gone.bin", "", ())) is None
        assert b.get_object("cohere", "gone.bin").status_code == 404
