"""Tier-1 static-analysis gate: the real tree must satisfy every mtpulint
invariant (against the committed baseline), the deadline_lint shim must keep
its historical surface, and the race gate must discover its file list from
the `race` marker instead of a hardcoded list."""

from __future__ import annotations

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_mtpulint_tree_is_clean_against_baseline():
    proc = subprocess.run(
        [sys.executable, "-m", "tools.mtpulint", "minio_tpu"],
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert proc.returncode == 0, (
        "mtpulint found new findings (fix them, add a justified inline "
        "suppression, or -- for grandfathered code only -- extend the "
        f"baseline):\n{proc.stdout}{proc.stderr}"
    )


def test_mtpulint_lists_all_rules():
    proc = subprocess.run(
        [sys.executable, "-m", "tools.mtpulint", "--list-rules"],
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=60,
    )
    assert proc.returncode == 0
    for rule_id in (
        "swallowed-except", "raw-transport", "deadline-rebind",
        "lock-blocking-io", "resource-leak", "stage-key",
        "metrics-rendered", "typed-errors", "unlocked-global",
        "lock-order", "unjoined-thread", "cond-wait-loop", "shared-publish",
        "release-on-all-paths", "double-release", "view-escape",
        "interface-conformance",
    ):
        assert rule_id in proc.stdout, f"rule {rule_id} missing from --list-rules"


def test_deadline_shim_keeps_lint_surface():
    """tools/deadline_lint.py is a shim over mtpulint's deadline rules; the
    lint()/main() API that chaos_check and test_degradation consume must
    survive, and the shipped tree must be clean."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import deadline_lint
    finally:
        sys.path.pop(0)
    assert deadline_lint.lint() == []
    assert callable(deadline_lint.main)


def test_race_gate_discovers_marked_files():
    from tools.race_gate import discover_race_tests

    found = discover_race_tests(REPO)
    assert "tests/test_concurrency_stress.py" in found
    assert "tests/test_dist.py" in found
    assert len(found) >= 5
