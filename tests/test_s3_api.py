"""End-to-end S3 API tests: signed HTTP against the in-process server.

The analogue of the reference's server_test.go (~100 signed S3 scenarios
against an httptest server, cmd/server_test.go + test-utils_test.go:290):
boots the full stack (HTTP router -> auth -> object layer -> 16 temp-dir
drives) and exercises the S3 wire protocol.
"""

import xml.etree.ElementTree as ET

import pytest

from minio_tpu.api.server import S3Server, ThreadedServer
from minio_tpu.control.iam import IAMSys
from tests.harness import ErasureHarness
from tests.s3client import S3TestClient

ROOT_AK = "minioadmin"
ROOT_SK = "minioadmin-secret"
NS = "{http://s3.amazonaws.com/doc/2006-03-01/}"


@pytest.fixture(scope="module")
def stack(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("s3api")
    hz = ErasureHarness(tmp, n_disks=8)
    from minio_tpu.object.pools import ServerPools
    from minio_tpu.object.sets import ErasureSets

    layer = ServerPools([ErasureSets([d for d in hz.drives], 8)])
    iam = IAMSys(ROOT_AK, ROOT_SK)
    srv = S3Server(layer, iam, check_skew=False)
    ts = ThreadedServer(srv)
    endpoint = ts.start()
    client = S3TestClient(endpoint, ROOT_AK, ROOT_SK)
    yield {"client": client, "endpoint": endpoint, "iam": iam, "server": srv, "layer": layer}
    ts.stop()


@pytest.fixture
def client(stack):
    return stack["client"]


def _fresh_bucket(client, name):
    client.delete_bucket(name)
    r = client.make_bucket(name)
    assert r.status_code == 200, r.text
    return name


class TestBuckets:
    def test_bucket_lifecycle(self, client):
        r = client.make_bucket("apibucket")
        assert r.status_code == 200
        assert client.head_bucket("apibucket").status_code == 200
        # ListBuckets contains it.
        r = client.request("GET", "/")
        assert r.status_code == 200
        names = [e.text for e in ET.fromstring(r.content).iter(f"{NS}Name")]
        assert "apibucket" in names
        # Double create conflicts.
        assert client.make_bucket("apibucket").status_code == 409
        assert client.delete_bucket("apibucket").status_code == 204
        assert client.head_bucket("apibucket").status_code == 404

    def test_invalid_bucket_name(self, client):
        r = client.make_bucket("AB")
        assert r.status_code == 400
        assert b"InvalidBucketName" in r.content

    def test_location(self, client):
        _fresh_bucket(client, "locbucket")
        r = client.request("GET", "/locbucket", query=[("location", "")])
        assert r.status_code == 200
        assert b"LocationConstraint" in r.content


class TestObjects:
    def test_put_get_roundtrip(self, client):
        _fresh_bucket(client, "objb")
        data = b"hello s3 world" * 1000
        r = client.put_object("objb", "dir/key.txt", data, headers={"Content-Type": "text/plain"})
        assert r.status_code == 200, r.text
        etag = r.headers["ETag"]
        r = client.get_object("objb", "dir/key.txt")
        assert r.status_code == 200
        assert r.content == data
        assert r.headers["ETag"] == etag
        assert r.headers["Content-Type"] == "text/plain"
        r = client.head_object("objb", "dir/key.txt")
        assert r.status_code == 200
        assert int(r.headers["Content-Length"]) == len(data)
        assert client.delete_object("objb", "dir/key.txt").status_code == 204
        assert client.get_object("objb", "dir/key.txt").status_code == 404

    def test_missing_key_and_bucket(self, client):
        _fresh_bucket(client, "objb2")
        r = client.get_object("objb2", "missing")
        assert r.status_code == 404
        assert b"NoSuchKey" in r.content
        r = client.get_object("nonexistentbkt", "k")
        assert r.status_code == 404
        assert b"NoSuchBucket" in r.content

    def test_user_metadata(self, client):
        _fresh_bucket(client, "metab")
        client.put_object("metab", "k", b"x", headers={"x-amz-meta-owner": "tester"})
        r = client.head_object("metab", "k")
        assert r.headers.get("x-amz-meta-owner") == "tester"

    def test_range_request(self, client):
        _fresh_bucket(client, "rangeb")
        data = bytes(range(256)) * 10
        client.put_object("rangeb", "r", data)
        r = client.get_object("rangeb", "r", headers={"Range": "bytes=10-19"})
        assert r.status_code == 206
        assert r.content == data[10:20]
        assert r.headers["Content-Range"] == f"bytes 10-19/{len(data)}"

    def test_copy_object(self, client):
        _fresh_bucket(client, "copyb")
        client.put_object("copyb", "src", b"copy-me", headers={"x-amz-meta-tag": "v"})
        r = client.request(
            "PUT", "/copyb/dst", headers={"x-amz-copy-source": "/copyb/src"}
        )
        assert r.status_code == 200
        assert b"CopyObjectResult" in r.content
        r = client.get_object("copyb", "dst")
        assert r.content == b"copy-me"
        assert r.headers.get("x-amz-meta-tag") == "v"

    def test_content_md5_check(self, client):
        _fresh_bucket(client, "md5b")
        import base64, hashlib

        good = base64.b64encode(hashlib.md5(b"data").digest()).decode()
        assert client.put_object("md5b", "k", b"data", headers={"Content-Md5": good}).status_code == 200
        bad = base64.b64encode(hashlib.md5(b"other").digest()).decode()
        r = client.put_object("md5b", "k2", b"data", headers={"Content-Md5": bad})
        assert r.status_code == 400
        assert b"BadDigest" in r.content

    def test_conditional_get(self, client):
        _fresh_bucket(client, "condb")
        etag = client.put_object("condb", "k", b"v").headers["ETag"]
        r = client.get_object("condb", "k", headers={"If-None-Match": etag})
        assert r.status_code == 304
        r = client.get_object("condb", "k", headers={"If-Match": '"wrong"'})
        assert r.status_code == 412


class TestListing:
    def test_list_v1_and_v2(self, client):
        _fresh_bucket(client, "listb")
        for k in ["a.txt", "b/one", "b/two", "c.txt"]:
            client.put_object("listb", k, b"x")
        r = client.list_objects("listb")
        root = ET.fromstring(r.content)
        keys = [e.text for e in root.iter(f"{NS}Key")]
        assert keys == ["a.txt", "b/one", "b/two", "c.txt"]
        r = client.list_objects("listb", **{"list-type": "2", "delimiter": "/"})
        root = ET.fromstring(r.content)
        keys = [e.text for e in root.iter(f"{NS}Key")]
        assert keys == ["a.txt", "c.txt"]
        prefixes = [e.text for e in root.iter(f"{NS}Prefix") if e.text and e.text != ""]
        assert "b/" in prefixes
        assert root.find(f"{NS}KeyCount").text == "3"

    def test_bulk_delete(self, client):
        _fresh_bucket(client, "bulkb")
        for i in range(3):
            client.put_object("bulkb", f"k{i}", b"x")
        body = (
            '<Delete><Object><Key>k0</Key></Object>'
            "<Object><Key>k1</Key></Object><Object><Key>k2</Key></Object></Delete>"
        ).encode()
        r = client.request("POST", "/bulkb", query=[("delete", "")], body=body)
        assert r.status_code == 200
        assert r.content.count(b"<Deleted>") == 3
        assert len(ET.fromstring(client.list_objects("bulkb").content).findall(f"{NS}Contents")) == 0


class TestVersioning:
    def test_versioning_flow(self, client):
        _fresh_bucket(client, "verb")
        cfg = f'<VersioningConfiguration xmlns="{NS[1:-1]}"><Status>Enabled</Status></VersioningConfiguration>'
        r = client.request("PUT", "/verb", query=[("versioning", "")], body=cfg.encode())
        assert r.status_code == 200, r.text
        r = client.request("GET", "/verb", query=[("versioning", "")])
        assert b"Enabled" in r.content
        v1 = client.put_object("verb", "obj", b"one").headers.get("x-amz-version-id")
        v2 = client.put_object("verb", "obj", b"two").headers.get("x-amz-version-id")
        assert v1 and v2 and v1 != v2
        assert client.get_object("verb", "obj").content == b"two"
        r = client.get_object("verb", "obj", query=[("versionId", v1)])
        assert r.content == b"one"
        # Delete -> marker; older versions still reachable.
        r = client.delete_object("verb", "obj")
        assert r.status_code == 204
        assert r.headers.get("x-amz-delete-marker") == "true"
        assert client.get_object("verb", "obj").status_code == 404
        assert client.get_object("verb", "obj", query=[("versionId", v2)]).content == b"two"
        # List versions shows marker + 2 versions.
        r = client.request("GET", "/verb", query=[("versions", "")])
        root = ET.fromstring(r.content)
        assert len(root.findall(f"{NS}Version")) == 2
        assert len(root.findall(f"{NS}DeleteMarker")) == 1


class TestAuth:
    def test_bad_secret_rejected(self, stack):
        bad = S3TestClient(stack["endpoint"], ROOT_AK, "wrong-secret")
        r = bad.request("GET", "/")
        assert r.status_code == 403
        assert b"SignatureDoesNotMatch" in r.content

    def test_unknown_access_key(self, stack):
        bad = S3TestClient(stack["endpoint"], "no-such-key", "x")
        r = bad.request("GET", "/")
        assert r.status_code == 403
        assert b"InvalidAccessKeyId" in r.content

    def test_anonymous_denied(self, stack, client):
        _fresh_bucket(client, "authb")
        client.put_object("authb", "k", b"secret")
        anon = S3TestClient(stack["endpoint"], "", "")
        r = anon.request("GET", "/authb/k", anonymous=True)
        assert r.status_code == 403

    def test_anonymous_allowed_by_policy(self, stack, client):
        _fresh_bucket(client, "pubbkt")
        client.put_object("pubbkt", "k", b"public-data")
        policy = (
            '{"Version":"2012-10-17","Statement":[{"Effect":"Allow","Principal":"*",'
            '"Action":["s3:GetObject"],"Resource":["arn:aws:s3:::pubbkt/*"]}]}'
        )
        r = stack["client"].request("PUT", "/pubbkt", query=[("policy", "")], body=policy.encode())
        assert r.status_code == 204, r.text
        anon = S3TestClient(stack["endpoint"], "", "")
        assert anon.request("GET", "/pubbkt/k", anonymous=True).content == b"public-data"
        # Write still denied.
        assert anon.request("PUT", "/pubbkt/new", body=b"x", anonymous=True).status_code == 403

    def test_iam_user_policies(self, stack, client):
        _fresh_bucket(client, "iamb")
        client.put_object("iamb", "k", b"data")
        stack["iam"].add_user("reader", "reader-secret-key", ["readonly"])
        reader = S3TestClient(stack["endpoint"], "reader", "reader-secret-key")
        assert reader.get_object("iamb", "k").status_code == 200
        assert reader.put_object("iamb", "new", b"x").status_code == 403
        stack["iam"].set_user_status("reader", "disabled")
        assert reader.get_object("iamb", "k").status_code == 403

    def test_presigned_url(self, stack, client):
        import requests as rq

        _fresh_bucket(client, "presb")
        client.put_object("presb", "k", b"presigned-data")
        url = stack["server"].verifier.presign_url(
            client.creds, "GET", "/presb/k", [], client.host
        )
        r = rq.get(url)
        assert r.status_code == 200, r.text
        assert r.content == b"presigned-data"
        # Tampered signature fails.
        r = rq.get(url[:-4] + "0000")
        assert r.status_code == 403


class TestCopyAndMultipartHTTP:
    """CopyObject preconditions, metadata directives, UploadPartCopy, and
    the full multipart flow over the wire (cmd/object-handlers_test.go and
    CopyObjectPartHandler scenarios)."""

    def test_copy_conditionals(self, client):
        b = _fresh_bucket(client, "copycond")
        client.put_object(b, "src", b"copy-me")
        etag = client.head_object(b, "src").headers["ETag"].strip('"')

        r = client.request("PUT", f"/{b}/dst", headers={
            "x-amz-copy-source": f"/{b}/src",
            "x-amz-copy-source-if-match": "deadbeef" * 4,
        })
        assert r.status_code == 412
        r = client.request("PUT", f"/{b}/dst", headers={
            "x-amz-copy-source": f"/{b}/src",
            "x-amz-copy-source-if-none-match": etag,
        })
        assert r.status_code == 412
        r = client.request("PUT", f"/{b}/dst", headers={
            "x-amz-copy-source": f"/{b}/src",
            "x-amz-copy-source-if-match": etag,
        })
        assert r.status_code == 200
        assert client.get_object(b, "dst").content == b"copy-me"

    def test_copy_unmodified_since(self, client):
        b = _fresh_bucket(client, "copydate")
        client.put_object(b, "src", b"dated")
        r = client.request("PUT", f"/{b}/dst", headers={
            "x-amz-copy-source": f"/{b}/src",
            "x-amz-copy-source-if-unmodified-since": "Mon, 01 Jan 2001 00:00:00 GMT",
        })
        assert r.status_code == 412  # modified after 2001
        r = client.request("PUT", f"/{b}/dst", headers={
            "x-amz-copy-source": f"/{b}/src",
            "x-amz-copy-source-if-modified-since": "Mon, 01 Jan 2001 00:00:00 GMT",
        })
        assert r.status_code == 200

    def test_copy_metadata_directive(self, client):
        b = _fresh_bucket(client, "copymeta")
        client.put_object(b, "src", b"meta", headers={"x-amz-meta-color": "blue"})
        client.request("PUT", f"/{b}/copy", headers={"x-amz-copy-source": f"/{b}/src"})
        assert client.head_object(b, "copy").headers.get("x-amz-meta-color") == "blue"
        client.request("PUT", f"/{b}/repl", headers={
            "x-amz-copy-source": f"/{b}/src",
            "x-amz-metadata-directive": "REPLACE",
            "x-amz-meta-color": "red",
        })
        assert client.head_object(b, "repl").headers.get("x-amz-meta-color") == "red"

    def test_multipart_flow(self, client):
        b = _fresh_bucket(client, "mpflow")
        r = client.request("POST", f"/{b}/big", query=[("uploads", "")])
        assert r.status_code == 200, r.text
        upload_id = ET.fromstring(r.text).find(f"{NS}UploadId").text

        import numpy as np

        part1 = np.random.default_rng(1).integers(0, 256, 5 << 20, dtype=np.uint8).tobytes()
        part2 = b"tail-part"
        etags = []
        for n, body in ((1, part1), (2, part2)):
            r = client.request(
                "PUT", f"/{b}/big",
                query=[("partNumber", str(n)), ("uploadId", upload_id)], body=body,
            )
            assert r.status_code == 200, r.text
            etags.append(r.headers["ETag"].strip('"'))

        r = client.request("GET", f"/{b}/big", query=[("uploadId", upload_id)])
        nums = [int(e.text) for e in ET.fromstring(r.text).iter(f"{NS}PartNumber")]
        assert nums == [1, 2]

        complete = (
            "<CompleteMultipartUpload>"
            + "".join(
                f"<Part><PartNumber>{n}</PartNumber><ETag>{e}</ETag></Part>"
                for n, e in zip((1, 2), etags)
            )
            + "</CompleteMultipartUpload>"
        )
        r = client.request(
            "POST", f"/{b}/big", query=[("uploadId", upload_id)], body=complete.encode()
        )
        assert r.status_code == 200, r.text
        got = client.get_object(b, "big").content
        assert got == part1 + part2
        # Multipart etag convention: md5-of-md5s with part count suffix.
        assert client.head_object(b, "big").headers["ETag"].strip('"').endswith("-2")

    def test_multipart_abort(self, client):
        b = _fresh_bucket(client, "mpabort")
        r = client.request("POST", f"/{b}/gone", query=[("uploads", "")])
        upload_id = ET.fromstring(r.text).find(f"{NS}UploadId").text
        client.request(
            "PUT", f"/{b}/gone",
            query=[("partNumber", "1"), ("uploadId", upload_id)], body=b"x" * 1000,
        )
        r = client.request("DELETE", f"/{b}/gone", query=[("uploadId", upload_id)])
        assert r.status_code == 204
        r = client.request(
            "POST", f"/{b}/gone", query=[("uploadId", upload_id)],
            body=b"<CompleteMultipartUpload></CompleteMultipartUpload>",
        )
        assert r.status_code == 404

    def test_upload_part_copy(self, client):
        b = _fresh_bucket(client, "mpcopy")
        src = (bytes(range(256)) * (20 * 1024 + 1))[: 5 << 20]  # 5 MiB: min part size
        client.put_object(b, "src", src)
        r = client.request("POST", f"/{b}/assembled", query=[("uploads", "")])
        upload_id = ET.fromstring(r.text).find(f"{NS}UploadId").text

        r = client.request(
            "PUT", f"/{b}/assembled",
            query=[("partNumber", "1"), ("uploadId", upload_id)],
            headers={"x-amz-copy-source": f"/{b}/src"},
        )
        assert r.status_code == 200, r.text
        etag1 = ET.fromstring(r.text).find(f"{NS}ETag").text.strip('"')

        r = client.request(
            "PUT", f"/{b}/assembled",
            query=[("partNumber", "2"), ("uploadId", upload_id)],
            headers={
                "x-amz-copy-source": f"/{b}/src",
                "x-amz-copy-source-range": "bytes=0-99",
            },
        )
        assert r.status_code == 200, r.text
        etag2 = ET.fromstring(r.text).find(f"{NS}ETag").text.strip('"')

        complete = (
            "<CompleteMultipartUpload>"
            f"<Part><PartNumber>1</PartNumber><ETag>{etag1}</ETag></Part>"
            f"<Part><PartNumber>2</PartNumber><ETag>{etag2}</ETag></Part>"
            "</CompleteMultipartUpload>"
        )
        r = client.request(
            "POST", f"/{b}/assembled", query=[("uploadId", upload_id)],
            body=complete.encode(),
        )
        assert r.status_code == 200, r.text
        assert client.get_object(b, "assembled").content == src + src[:100]

    def test_upload_part_copy_bad_range(self, client):
        b = _fresh_bucket(client, "mpbadrange")
        client.put_object(b, "src", b"tiny")
        r = client.request("POST", f"/{b}/x", query=[("uploads", "")])
        upload_id = ET.fromstring(r.text).find(f"{NS}UploadId").text
        r = client.request(
            "PUT", f"/{b}/x",
            query=[("partNumber", "1"), ("uploadId", upload_id)],
            headers={
                "x-amz-copy-source": f"/{b}/src",
                "x-amz-copy-source-range": "bytes=100-200",
            },
        )
        assert r.status_code == 416


class TestRangesAndTagging:
    def test_suffix_and_invalid_ranges(self, client):
        b = _fresh_bucket(client, "ranges")
        data = bytes(range(256)) * 10
        client.put_object(b, "obj", data)
        r = client.get_object(b, "obj", headers={"Range": "bytes=-100"})
        assert r.status_code == 206 and r.content == data[-100:]
        r = client.get_object(b, "obj", headers={"Range": "bytes=50-59"})
        assert r.status_code == 206 and r.content == data[50:60]
        assert r.headers["Content-Range"] == f"bytes 50-59/{len(data)}"
        r = client.get_object(b, "obj", headers={"Range": f"bytes={len(data) + 10}-"})
        assert r.status_code == 416

    def test_object_tagging_roundtrip(self, client):
        b = _fresh_bucket(client, "tagb")
        client.put_object(b, "obj", b"tagged")
        tags = (
            '<Tagging><TagSet><Tag><Key>env</Key><Value>prod</Value></Tag>'
            "</TagSet></Tagging>"
        )
        r = client.request("PUT", f"/{b}/obj", query=[("tagging", "")], body=tags.encode())
        assert r.status_code == 200, r.text
        r = client.request("GET", f"/{b}/obj", query=[("tagging", "")])
        assert "<Key>env</Key>" in r.text and "<Value>prod</Value>" in r.text
        assert client.head_object(b, "obj").headers.get("x-amz-tagging-count") == "1"
        r = client.request("DELETE", f"/{b}/obj", query=[("tagging", "")])
        assert r.status_code == 204
        r = client.request("GET", f"/{b}/obj", query=[("tagging", "")])
        assert "<Key>" not in r.text


class TestEncodingType:
    def test_url_encoding_type(self, client):
        b = _fresh_bucket(client, "encb")
        weird = "dir/sp ace+plus#hash.txt"
        client.put_object(b, weird, b"x")
        r = client.request("GET", f"/{b}", query=[("encoding-type", "url"), ("list-type", "2")])
        assert r.status_code == 200
        assert "<EncodingType>url</EncodingType>" in r.text
        import urllib.parse

        assert f"<Key>{urllib.parse.quote(weird, safe='/')}</Key>" in r.text
        # Without encoding-type the raw (xml-escaped) key is returned.
        r = client.request("GET", f"/{b}")
        assert "<Key>dir/sp ace+plus#hash.txt</Key>" in r.text

    def test_url_encoding_versions(self, client):
        b = _fresh_bucket(client, "encvb")
        weird = "v dir/a+b.txt"
        client.put_object(b, weird, b"x")
        r = client.request("GET", f"/{b}", query=[("versions", ""), ("encoding-type", "url")])
        assert r.status_code == 200
        assert "<EncodingType>url</EncodingType>" in r.text
        import urllib.parse

        assert f"<Key>{urllib.parse.quote(weird, safe='/')}</Key>" in r.text


class TestPartNumberGet:
    def test_get_by_part_number(self, client):
        b = _fresh_bucket(client, "pnget")
        r = client.request("POST", f"/{b}/mp", query=[("uploads", "")])
        uid = ET.fromstring(r.text).find(f"{NS}UploadId").text
        import numpy as np

        p1 = np.random.default_rng(5).integers(0, 256, 5 << 20, dtype=np.uint8).tobytes()
        p2 = b"secondpart" * 100
        etags = []
        for n, body in ((1, p1), (2, p2)):
            r = client.request(
                "PUT", f"/{b}/mp", query=[("partNumber", str(n)), ("uploadId", uid)], body=body
            )
            etags.append(r.headers["ETag"].strip('"'))
        complete = (
            "<CompleteMultipartUpload>"
            + "".join(
                f"<Part><PartNumber>{n}</PartNumber><ETag>{e}</ETag></Part>"
                for n, e in zip((1, 2), etags)
            )
            + "</CompleteMultipartUpload>"
        )
        assert client.request(
            "POST", f"/{b}/mp", query=[("uploadId", uid)], body=complete.encode()
        ).status_code == 200

        r = client.get_object(b, "mp", query=[("partNumber", "2")])
        assert r.status_code == 206, r.text
        assert r.content == p2
        assert r.headers["x-amz-mp-parts-count"] == "2"
        assert r.headers["Content-Range"].startswith(f"bytes {len(p1)}-")

        r = client.request("HEAD", f"/{b}/mp", query=[("partNumber", "1")])
        assert r.status_code == 206
        assert int(r.headers["Content-Length"]) == len(p1)
        assert r.headers["x-amz-mp-parts-count"] == "2"

        r = client.get_object(b, "mp", query=[("partNumber", "9")])
        assert r.status_code == 416

    def test_part_number_on_simple_object(self, client):
        b = _fresh_bucket(client, "pnsimple")
        client.put_object(b, "one", b"x" * 200_000)
        r = client.get_object(b, "one", query=[("partNumber", "1")])
        assert r.status_code == 206
        assert len(r.content) == 200_000
        assert r.headers["x-amz-mp-parts-count"] == "1"

    def test_part_number_empty_object(self, client):
        b = _fresh_bucket(client, "pnempty")
        client.put_object(b, "empty", b"")
        r = client.get_object(b, "empty", query=[("partNumber", "1")])
        assert r.status_code == 200 and r.content == b""
        r = client.request("HEAD", f"/{b}/empty", query=[("partNumber", "1")])
        assert r.status_code == 200
        assert "Content-Range" not in r.headers


class TestDateConditionalsAndCors:
    def test_modified_since_conditionals(self, client):
        b = _fresh_bucket(client, "dcond")
        client.put_object(b, "k", b"dated")
        lm = client.head_object(b, "k").headers["Last-Modified"]
        r = client.get_object(b, "k", headers={"If-Modified-Since": lm})
        assert r.status_code == 304
        r = client.get_object(b, "k", headers={"If-Modified-Since": "Mon, 01 Jan 2001 00:00:00 GMT"})
        assert r.status_code == 200
        r = client.get_object(b, "k", headers={"If-Unmodified-Since": lm})
        assert r.status_code == 200
        r = client.get_object(b, "k", headers={"If-Unmodified-Since": "Mon, 01 Jan 2001 00:00:00 GMT"})
        assert r.status_code == 412
        # If-None-Match supersedes If-Modified-Since.
        r = client.get_object(
            b, "k", headers={"If-None-Match": '"nomatch"', "If-Modified-Since": lm}
        )
        assert r.status_code == 200
        # HEAD honors the same conditionals.
        r = client.request("HEAD", f"/{b}/k", headers={"If-Modified-Since": lm})
        assert r.status_code == 304

    def test_cors_preflight_and_echo(self, client, stack):
        import requests as _rq

        r = _rq.options(
            f"{stack['endpoint']}/whatever/key",
            headers={"Origin": "https://app.example", "Access-Control-Request-Method": "PUT"},
            timeout=10,
        )
        assert r.status_code == 200
        assert r.headers["Access-Control-Allow-Origin"] == "*"
        assert "PUT" in r.headers["Access-Control-Allow-Methods"]

        b = _fresh_bucket(client, "corsb")
        client.put_object(b, "k", b"x")
        r = client.get_object(b, "k", headers={"Origin": "https://app.example"})
        assert r.headers.get("Access-Control-Allow-Origin") == "*"


class TestStorageClass:
    def test_rrs_reduced_parity(self, client, stack):
        b = _fresh_bucket(client, "rrsb")
        data = b"r" * 300_000
        r = client.put_object(
            b, "rrs-obj", data, headers={"x-amz-storage-class": "REDUCED_REDUNDANCY"}
        )
        assert r.status_code == 200, r.text
        h = client.head_object(b, "rrs-obj")
        assert h.headers.get("x-amz-storage-class") == "REDUCED_REDUNDANCY"
        assert client.get_object(b, "rrs-obj").content == data
        # The stored geometry really uses reduced parity (EC:2 on 8 drives).
        eo = stack["layer"].pools[0].get_hashed_set("rrsb/rrs-obj")
        fi, _, _ = eo._read_quorum_fi(b, "rrs-obj", "")
        assert fi.erasure.parity_blocks == 2
        assert fi.erasure.data_blocks == 6

        client.put_object(b, "std-obj", data)
        assert "x-amz-storage-class" not in client.head_object(b, "std-obj").headers
        r = client.put_object(b, "bad", data, headers={"x-amz-storage-class": "GLACIER"})
        assert r.status_code == 400
        assert b"InvalidStorageClass" in r.content


class TestPolicyConditions:
    def test_source_ip_and_prefix_conditions(self, client, stack):
        import json as _json

        b = _fresh_bucket(client, "condpol")
        client.put_object(b, "public/x", b"open")
        client.put_object(b, "private/y", b"closed")

        # Anonymous read allowed only from loopback and only under public/.
        policy = _json.dumps({
            "Version": "2012-10-17",
            "Statement": [{
                "Effect": "Allow",
                "Principal": "*",
                "Action": ["s3:GetObject"],
                "Resource": [f"arn:aws:s3:::{b}/public/*"],
                "Condition": {"IpAddress": {"aws:SourceIp": "127.0.0.0/8"}},
            }],
        })
        r = client.request("PUT", f"/{b}", query=[("policy", "")], body=policy.encode())
        assert r.status_code in (200, 204), r.text
        r = client.request("GET", f"/{b}/public/x", anonymous=True)
        assert r.status_code == 200 and r.content == b"open"
        r = client.request("GET", f"/{b}/private/y", anonymous=True)
        assert r.status_code == 403

        # Same policy but a non-matching CIDR: denied despite the path.
        policy = policy.replace("127.0.0.0/8", "10.9.8.0/24")
        client.request("PUT", f"/{b}", query=[("policy", "")], body=policy.encode())
        r = client.request("GET", f"/{b}/public/x", anonymous=True)
        assert r.status_code == 403

    def test_string_condition_on_listing(self, client):
        import json as _json

        b = _fresh_bucket(client, "condlist")
        client.put_object(b, "team-a/doc", b"a")
        policy = _json.dumps({
            "Statement": [{
                "Effect": "Allow",
                "Principal": "*",
                "Action": ["s3:ListBucket"],
                "Resource": [f"arn:aws:s3:::{b}"],
                "Condition": {"StringLike": {"s3:prefix": "team-a/*"}},
            }],
        })
        client.request("PUT", f"/{b}", query=[("policy", "")], body=policy.encode())
        r = client.request("GET", f"/{b}", query=[("prefix", "team-a/")], anonymous=True)
        assert r.status_code == 200
        r = client.request("GET", f"/{b}", query=[("prefix", "team-b/")], anonymous=True)
        assert r.status_code == 403
        r = client.request("GET", f"/{b}", anonymous=True)  # no prefix at all
        assert r.status_code == 403

    def test_invalid_condition_rejected_at_write(self, client):
        import json as _json

        b = _fresh_bucket(client, "condbad")
        for bad in (
            {"NumericLessThan": {"s3:max-keys": "10"}},      # unsupported op
            {"IpAddress": {"aws:SourceIp": "10.0.0.0/33"}},  # bad CIDR
            {"Bool": {"aws:SecureTransport": []}},           # empty values
        ):
            policy = _json.dumps({
                "Statement": [{
                    "Effect": "Deny", "Principal": "*",
                    "Action": ["s3:GetObject"],
                    "Resource": [f"arn:aws:s3:::{b}/*"],
                    "Condition": bad,
                }],
            })
            r = client.request("PUT", f"/{b}", query=[("policy", "")], body=policy.encode())
            assert r.status_code == 400, (bad, r.text)
            assert b"MalformedPolicy" in r.content


class TestCompatSubresources:
    """AWS-compat fixed-config subresources + ACL endpoints
    (cmd/dummy-handlers.go, PutBucketACL/PutObjectACL handlers)."""

    def test_dummy_bucket_configs(self, client):
        b = _fresh_bucket(client, "compat")
        r = client.request("GET", f"/{b}", query=[("accelerate", "")])
        assert r.status_code == 200 and b"AccelerateConfiguration" in r.content
        r = client.request("GET", f"/{b}", query=[("requestPayment", "")])
        assert r.status_code == 200 and b"BucketOwner" in r.content
        r = client.request("GET", f"/{b}", query=[("logging", "")])
        assert r.status_code == 200 and b"BucketLoggingStatus" in r.content
        r = client.request("GET", f"/{b}", query=[("website", "")])
        assert r.status_code == 404 and b"NoSuchWebsiteConfiguration" in r.content
        # Dummy DELETE website succeeds without doing anything.
        assert client.request("DELETE", f"/{b}", query=[("website", "")]).status_code == 200
        # Unknown bucket still 404s through the dummy paths.
        r = client.request("GET", "/no-such-bkt", query=[("accelerate", "")])
        assert r.status_code == 404

    def test_policy_status(self, client):
        import json as _json

        b = _fresh_bucket(client, "polstatus")
        r = client.request("GET", f"/{b}", query=[("policyStatus", "")])
        assert r.status_code == 200 and b"<IsPublic>FALSE</IsPublic>" in r.content
        policy = _json.dumps({
            "Statement": [{
                "Effect": "Allow", "Principal": "*",
                "Action": ["s3:GetObject"],
                "Resource": [f"arn:aws:s3:::{b}/*"],
            }],
        })
        assert (
            client.request("PUT", f"/{b}", query=[("policy", "")], body=policy.encode()).status_code
            == 204
        )
        r = client.request("GET", f"/{b}", query=[("policyStatus", "")])
        assert r.status_code == 200 and b"<IsPublic>TRUE</IsPublic>" in r.content

    def test_policy_status_deny_overrides(self, client):
        import json as _json

        b = _fresh_bucket(client, "polstatus2")
        policy = _json.dumps({
            "Statement": [
                {"Effect": "Allow", "Principal": "*",
                 "Action": ["s3:GetObject"],
                 "Resource": [f"arn:aws:s3:::{b}/*"]},
                {"Effect": "Deny", "Principal": "*",
                 "Action": ["s3:*"],
                 "Resource": [f"arn:aws:s3:::{b}", f"arn:aws:s3:::{b}/*"]},
            ],
        })
        assert (
            client.request("PUT", f"/{b}", query=[("policy", "")], body=policy.encode()).status_code
            == 204
        )
        # The Allow is nullified by the blanket Deny: not public.
        r = client.request("GET", f"/{b}", query=[("policyStatus", "")])
        assert r.status_code == 200 and b"<IsPublic>FALSE</IsPublic>" in r.content

    def test_acl_endpoints(self, client):
        b = _fresh_bucket(client, "aclbkt")
        client.put_object(b, "k", b"v")
        # GET bucket/object ACL: canned owner FULL_CONTROL document.
        for path, query in ((f"/{b}", [("acl", "")]), (f"/{b}/k", [("acl", "")])):
            r = client.request("GET", path, query=query)
            assert r.status_code == 200 and b"FULL_CONTROL" in r.content, path
        # PUT private canned ACL is accepted; anything else is NotImplemented.
        for path in (f"/{b}", f"/{b}/k"):
            assert (
                client.request(
                    "PUT", path, query=[("acl", "")], headers={"x-amz-acl": "private"}
                ).status_code
                == 200
            )
            r = client.request(
                "PUT", path, query=[("acl", "")], headers={"x-amz-acl": "public-read"}
            )
            assert r.status_code == 501, path
        # ACL on a missing object 404s.
        assert client.request("GET", f"/{b}/gone", query=[("acl", "")]).status_code == 404

    def test_delete_encryption_and_replication_config(self, client):
        b = _fresh_bucket(client, "delcfg")
        sse = (
            '<ServerSideEncryptionConfiguration xmlns="http://s3.amazonaws.com/doc/2006-03-01/">'
            "<Rule><ApplyServerSideEncryptionByDefault><SSEAlgorithm>AES256</SSEAlgorithm>"
            "</ApplyServerSideEncryptionByDefault></Rule></ServerSideEncryptionConfiguration>"
        )
        assert client.request("PUT", f"/{b}", query=[("encryption", "")], body=sse.encode()).status_code in (200, 204)
        assert client.request("GET", f"/{b}", query=[("encryption", "")]).status_code == 200
        assert client.request("DELETE", f"/{b}", query=[("encryption", "")]).status_code in (200, 204)
        r = client.request("GET", f"/{b}", query=[("encryption", "")])
        assert r.status_code == 404 and b"ServerSideEncryptionConfigurationNotFoundError" in r.content


class TestListenNotification:
    """Live event stream (ListenNotificationHandler,
    cmd/listen-notification-handlers.go:31)."""

    def test_listen_receives_put_event(self, stack, client):
        import json as _json
        import threading

        from minio_tpu.control.events import EventNotifier

        srv = stack["server"]
        old = srv.notifier
        srv.notifier = EventNotifier()
        try:
            b = _fresh_bucket(client, "watchbkt")
            got: list[dict] = []
            ready = threading.Event()
            done = threading.Event()

            def listen():
                r = client.request(
                    "GET",
                    f"/{b}",
                    query=[("events", "s3:ObjectCreated:*"), ("prefix", "pfx/")],
                    stream=True,
                )
                assert r.status_code == 200
                ready.set()
                for line in r.iter_lines():
                    if line.strip():
                        got.append(_json.loads(line))
                        break
                r.close()
                done.set()

            t = threading.Thread(target=listen, daemon=True)
            t.start()
            assert ready.wait(10)
            # Non-matching prefix is filtered out; matching one arrives.
            client.put_object(b, "other/x", b"nope")
            client.put_object(b, "pfx/hit", b"data")
            assert done.wait(15), "no event arrived on the listen stream"
            rec = got[0]
            assert rec["EventName"].startswith("s3:ObjectCreated")
            key = rec["Records"][0]["s3"]["object"]["key"]
            assert key == "pfx/hit"
            assert rec["Records"][0]["s3"]["bucket"]["name"] == b
        finally:
            srv.notifier = old
