"""Event broker targets against in-process fake brokers.

Mirrors the reference's internal/event/target tests: each broker target
speaks its real wire protocol against a minimal fake server; durable spool
behavior (broker down -> queue -> drain on recovery) is exercised via the
shared TargetQueue; gated targets (kafka/amqp/mysql/postgres) error clearly
without their client libraries.
"""

import json
import socket
import struct
import threading
import time

import pytest

from minio_tpu.control import event_targets as et
from minio_tpu.control.config import ConfigSys
from minio_tpu.control.events import Event, EventNotifier
from minio_tpu.utils import errors

RECORD = {"EventName": "s3:ObjectCreated:Put", "Key": "b/o.txt", "Records": []}


def _wait(cond, timeout=15.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.02)
    return False


# -- fake brokers -------------------------------------------------------------


class FakeRedis(threading.Thread):
    def __init__(self):
        super().__init__(daemon=True)
        self.sock = socket.socket()
        self.sock.bind(("127.0.0.1", 0))
        self.sock.listen(4)
        self.port = self.sock.getsockname()[1]
        self.commands = []
        self.start()

    def run(self):
        while True:
            try:
                conn, _ = self.sock.accept()
            except OSError:
                return
            with conn:
                try:
                    data = b""
                    conn.settimeout(2)
                    while True:
                        chunk = conn.recv(65536)
                        if not chunk:
                            break
                        data += chunk
                        # Parse complete RESP arrays, reply +OK / :1 each.
                        while data.startswith(b"*"):
                            parts, rest = self._parse(data)
                            if parts is None:
                                break
                            self.commands.append(parts)
                            conn.sendall(b":1\r\n")
                            data = rest
                except OSError:
                    pass

    @staticmethod
    def _parse(data):
        try:
            head, rest = data.split(b"\r\n", 1)
            n = int(head[1:])
            parts = []
            for _ in range(n):
                lh, rest = rest.split(b"\r\n", 1)
                ln = int(lh[1:])
                if len(rest) < ln + 2:
                    return None, data
                parts.append(rest[:ln])
                rest = rest[ln + 2 :]
            return parts, rest
        except (ValueError, IndexError):
            return None, data


class FakeNATS(threading.Thread):
    def __init__(self):
        super().__init__(daemon=True)
        self.sock = socket.socket()
        self.sock.bind(("127.0.0.1", 0))
        self.sock.listen(4)
        self.port = self.sock.getsockname()[1]
        self.published = []
        self.start()

    def run(self):
        while True:
            try:
                conn, _ = self.sock.accept()
            except OSError:
                return
            with conn:
                try:
                    conn.settimeout(2)
                    conn.sendall(b'INFO {"server_id":"fake"}\r\n')
                    buf = b""
                    while True:
                        chunk = conn.recv(65536)
                        if not chunk:
                            break
                        buf += chunk
                        while b"\r\n" in buf:
                            line, buf = buf.split(b"\r\n", 1)
                            if line.startswith(b"PUB "):
                                _, subject, size = line.split(b" ")
                                need = int(size) + 2
                                while len(buf) < need:
                                    buf += conn.recv(65536)
                                self.published.append((subject.decode(), buf[: int(size)]))
                                buf = buf[need:]
                            elif line == b"PING":
                                conn.sendall(b"PONG\r\n")
                except OSError:
                    pass


class FakeMQTT(threading.Thread):
    def __init__(self):
        super().__init__(daemon=True)
        self.sock = socket.socket()
        self.sock.bind(("127.0.0.1", 0))
        self.sock.listen(4)
        self.port = self.sock.getsockname()[1]
        self.published = []
        self.start()

    def run(self):
        while True:
            try:
                conn, _ = self.sock.accept()
            except OSError:
                return
            with conn:
                try:
                    conn.settimeout(2)
                    buf = b""
                    while True:
                        chunk = conn.recv(65536)
                        if not chunk:
                            break
                        buf += chunk
                        while len(buf) >= 2:
                            ptype = buf[0] >> 4
                            # remaining length varint
                            rl, i, mult = 0, 1, 1
                            while True:
                                byte = buf[i]
                                rl += (byte & 0x7F) * mult
                                mult *= 128
                                i += 1
                                if not byte & 0x80:
                                    break
                            if len(buf) < i + rl:
                                break
                            body = buf[i : i + rl]
                            buf = buf[i + rl :]
                            if ptype == 1:  # CONNECT
                                conn.sendall(bytes([0x20, 0x02, 0x00, 0x00]))
                            elif ptype == 3:  # PUBLISH QoS0
                                tl = struct.unpack(">H", body[:2])[0]
                                topic = body[2 : 2 + tl].decode()
                                self.published.append((topic, body[2 + tl :]))
                except OSError:
                    pass


class FakeHTTPBroker(threading.Thread):
    """Accepts any POST/PUT with a JSON body (nsq /pub, elasticsearch _doc)."""

    def __init__(self):
        super().__init__(daemon=True)
        import http.server

        outer = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def _handle(self):
                n = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(n)
                outer.requests.append((self.command, self.path, body))
                self.send_response(200)
                self.send_header("Content-Length", "2")
                self.end_headers()
                self.wfile.write(b"{}")

            do_POST = _handle
            do_PUT = _handle

            def log_message(self, *a):
                pass

        self.requests = []
        self.httpd = http.server.HTTPServer(("127.0.0.1", 0), Handler)
        self.port = self.httpd.server_address[1]
        self.start()

    def run(self):
        self.httpd.serve_forever()


# -- native targets -----------------------------------------------------------


def test_redis_access_format():
    broker = FakeRedis()
    t = et.RedisEventTarget("redis", f"127.0.0.1:{broker.port}", "evkey", fmt="access")
    t.send(RECORD)
    assert _wait(lambda: broker.commands)
    cmd = broker.commands[0]
    assert cmd[0] == b"RPUSH" and cmd[1] == b"evkey"
    assert json.loads(cmd[2]) == RECORD
    t.close()


def test_redis_namespace_format():
    broker = FakeRedis()
    t = et.RedisEventTarget("redis", f"127.0.0.1:{broker.port}", "evkey", fmt="namespace")
    t.send(RECORD)
    assert _wait(lambda: broker.commands)
    cmd = broker.commands[0]
    assert cmd[0] == b"HSET" and cmd[2] == b"b/o.txt"
    t.close()


def test_nats_publish():
    broker = FakeNATS()
    t = et.NATSEventTarget("nats", f"127.0.0.1:{broker.port}", "bucketevents")
    t.send(RECORD)
    assert _wait(lambda: broker.published)
    subject, payload = broker.published[0]
    assert subject == "bucketevents" and json.loads(payload) == RECORD
    t.close()


def test_mqtt_publish():
    broker = FakeMQTT()
    t = et.MQTTEventTarget("mqtt", f"127.0.0.1:{broker.port}", "events/topic")
    t.send(RECORD)
    assert _wait(lambda: broker.published)
    topic, payload = broker.published[0]
    assert topic == "events/topic" and json.loads(payload) == RECORD
    t.close()


def test_nsq_publish():
    broker = FakeHTTPBroker()
    t = et.NSQEventTarget("nsq", f"127.0.0.1:{broker.port}", "miniotopic")
    t.send(RECORD)
    assert _wait(lambda: broker.requests)
    method, path, body = broker.requests[0]
    assert method == "POST" and path == "/pub?topic=miniotopic"
    assert json.loads(body) == RECORD


def test_elasticsearch_namespace():
    broker = FakeHTTPBroker()
    t = et.ElasticsearchEventTarget(
        "es", f"http://127.0.0.1:{broker.port}", "events", fmt="namespace"
    )
    t.send(RECORD)
    assert _wait(lambda: broker.requests)
    method, path, body = broker.requests[0]
    assert method == "PUT" and path == "/events/_doc/b%2Fo.txt"


# -- durability ---------------------------------------------------------------


def test_spool_survives_broker_outage(tmp_path):
    # No broker listening: event spools to disk; a new target instance with
    # a live broker drains it (queuestore.go recovery semantics).
    # The dead port stays BOUND (not listening) for the whole test: a
    # merely-freed port could be re-bound by a concurrent test's server,
    # silently turning "broker down" into "broker up" (observed flake).
    import socket

    holder = socket.socket()
    holder.bind(("127.0.0.1", 0))  # bound + never listen = connection refused
    dead_port = holder.getsockname()[1]
    qdir = str(tmp_path / "spool")
    t = et.RedisEventTarget("redis", f"127.0.0.1:{dead_port}", "k", queue_dir=qdir)
    t.send(RECORD)
    assert _wait(lambda: t.queue.pending() == 1)
    t.close()
    import os

    assert os.listdir(qdir)  # spooled on disk

    broker = FakeRedis()
    t2 = et.RedisEventTarget("redis", f"127.0.0.1:{broker.port}", "k", queue_dir=qdir)
    assert _wait(lambda: broker.commands)
    assert _wait(lambda: not os.listdir(qdir))  # spool drained + removed
    t2.close()
    holder.close()


# -- gating -------------------------------------------------------------------


def test_gated_targets_error_without_libs():
    for ctor in (et.KafkaEventTarget, et.AMQPEventTarget, et.MySQLEventTarget, et.PostgresEventTarget):
        import importlib.util

        if importlib.util.find_spec(ctor.lib) is not None:
            pytest.skip(f"{ctor.lib} installed in this build")
        with pytest.raises(errors.InvalidArgument) as ei:
            ctor("t")
        assert "client library" in str(ei.value)


# -- config-driven registration ----------------------------------------------


def test_configure_targets_from_config(tmp_path):
    broker = FakeRedis()
    config = ConfigSys()
    config.set("notify_redis", "enable", "on")
    config.set("notify_redis", "address", f"127.0.0.1:{broker.port}")
    config.set("notify_redis", "key", "cfg_events")
    notifier = EventNotifier()
    ids = et.configure_targets(notifier, config, queue_root=str(tmp_path))
    assert ids == ["redis"]
    notifier.set_bucket_rules_from_xml(
        "evb",
        b"<NotificationConfiguration><QueueConfiguration>"
        b"<Queue>arn:minio:sqs::redis:redis</Queue>"
        b"<Event>s3:ObjectCreated:*</Event>"
        b"</QueueConfiguration></NotificationConfiguration>",
    )
    notifier.emit(Event(name="s3:ObjectCreated:Put", bucket="evb", object_name="x.txt"))
    assert _wait(lambda: broker.commands)
    assert broker.commands[0][1] == b"cfg_events"
    for t in notifier.targets.values():
        t.close()
