"""Concurrency stress gate: the Go `-race` analogue (SURVEY.md §5).

Python has no data-race sanitizer, so the concurrency-safety story is an
invariant-checking stress harness: many threads hammer one erasure
namespace with overlapping puts/gets/deletes/lists/heals and every
response must be internally consistent (a GET returns exactly some
complete version that was PUT, never a torn mix; listings never show
phantom keys; the metacache never serves a deleted object after its
delete returned). Runs with the suite (a few seconds), mirroring how the
reference runs its tests under -race in CI (buildscripts/race.sh).
"""

import hashlib
import threading

import numpy as np
import pytest

from minio_tpu.object.types import DeleteObjectOptions
from minio_tpu.utils import errors
from tests.test_sets_pools import make_pools

# Stressed under adversarial thread scheduling by tools/race_gate.py.
pytestmark = pytest.mark.race


BUCKET = "raceb"
KEYS = 6
WRITERS = 4
READERS = 4
ROUNDS = 12


@pytest.fixture
def hz(tmp_path):
    layer = make_pools(tmp_path, n_disks=8, set_drive_count=8)
    layer.make_bucket(BUCKET)
    return layer


def _payload(key: str, round_i: int, writer: int) -> bytes:
    rng = np.random.default_rng((hash(key) & 0xFFFF) * 1000 + round_i * 10 + writer)
    body = rng.integers(0, 256, 200_000 + round_i * 1111, dtype=np.uint8).tobytes()
    # Self-describing payload: header carries the hash of the rest, so a
    # torn read (mixed versions) is detectable without global coordination.
    digest = hashlib.sha256(body).digest()
    return digest + body


def _check_payload(data: bytes) -> bool:
    return len(data) > 32 and hashlib.sha256(data[32:]).digest() == data[:32]


def test_concurrent_namespace_consistency(hz):
    layer = hz
    stop = threading.Event()
    failures: list[str] = []

    def fail(msg: str) -> None:
        failures.append(msg)
        stop.set()

    def writer(w: int) -> None:
        try:
            for r in range(ROUNDS):
                if stop.is_set():
                    return
                key = f"obj-{(w + r) % KEYS}"
                layer.put_object(BUCKET, key, _payload(key, r, w))
                if r % 3 == 2:
                    try:
                        layer.delete_object(BUCKET, key, DeleteObjectOptions())
                    except errors.StorageError:
                        pass
        except Exception as e:  # noqa: BLE001
            fail(f"writer {w}: {type(e).__name__}: {e}")

    def reader(ri: int) -> None:
        try:
            while not stop.is_set():
                key = f"obj-{ri % KEYS}"
                try:
                    _, data = layer.get_object(BUCKET, key)
                except (errors.ObjectNotFound, errors.FileNotFound):
                    continue
                except errors.StorageError:
                    continue
                if not _check_payload(data):
                    fail(f"reader {ri}: torn read on {key} (len {len(data)})")
                    return
        except Exception as e:  # noqa: BLE001
            fail(f"reader {ri}: {type(e).__name__}: {e}")

    def lister() -> None:
        try:
            while not stop.is_set():
                res = layer.list_objects(BUCKET, max_keys=100)
                for o in res.objects:
                    if not o.name.startswith("obj-"):
                        fail(f"lister: phantom key {o.name!r}")
                        return
        except Exception as e:  # noqa: BLE001
            fail(f"lister: {type(e).__name__}: {e}")

    threads = [threading.Thread(target=writer, args=(w,)) for w in range(WRITERS)]
    threads += [threading.Thread(target=reader, args=(ri,)) for ri in range(READERS)]
    threads += [threading.Thread(target=lister)]
    for t in threads:
        t.start()
    for t in threads[:WRITERS]:
        t.join(120)
    stop.set()
    for t in threads:
        t.join(30)
    assert not failures, failures

    # Post-quiescence invariant: every surviving object heals clean and
    # reads back self-consistent.
    res = layer.list_objects(BUCKET, max_keys=1000)
    for o in res.objects:
        _, data = layer.get_object(BUCKET, o.name)
        assert _check_payload(data), o.name
        assert layer.heal_object(BUCKET, o.name, dry_run=True).disks_healed == 0
