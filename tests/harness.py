"""In-process multi-disk test harness.

The workhorse of the test strategy, mirroring the reference's
prepareErasure/ExecObjectLayerTest machinery (cmd/test-utils_test.go:199,
:1791): build a full erasure object layer over N temp-dir "disks" in one
process, expose the dirs for direct fault injection (deleting/corrupting
shard files), and allow taking drives offline mid-test.
"""

from __future__ import annotations

import os

from minio_tpu.loadgen.cluster import InProcessCluster as ClusterHarness  # noqa: F401
from minio_tpu.object.erasure import ErasureObjects
from minio_tpu.storage import format as fmt
from minio_tpu.storage.local import LocalDrive


class ErasureHarness:
    def __init__(self, tmp_path, n_disks: int = 16, parity: int | None = None, codec=None):
        self.dirs = [str(tmp_path / f"disk{i}") for i in range(n_disks)]
        formats = fmt.init_format(1, n_disks)
        self.drives: list[LocalDrive | None] = []
        for d, f in zip(self.dirs, formats):
            os.makedirs(d, exist_ok=True)
            f.save(d)
            self.drives.append(LocalDrive(d))
        self.layer = ErasureObjects(self.drives, parity=parity, codec=codec)

    def take_offline(self, *indices: int) -> None:
        for i in indices:
            self.layer.disks[i] = None

    def bring_online(self, *indices: int) -> None:
        for i in indices:
            self.layer.disks[i] = LocalDrive(self.dirs[i])

    def shard_file(self, disk_index: int, bucket: str, object_name: str) -> str | None:
        """Path to the part.1 shard file on a drive (None if inline/absent)."""
        obj_dir = os.path.join(self.dirs[disk_index], bucket, object_name)
        if not os.path.isdir(obj_dir):
            return None
        for entry in os.listdir(obj_dir):
            p = os.path.join(obj_dir, entry, "part.1")
            if os.path.isfile(p):
                return p
        return None

    def xl_meta_file(self, disk_index: int, bucket: str, object_name: str) -> str:
        return os.path.join(self.dirs[disk_index], bucket, object_name, "xl.meta")

    def corrupt_shard(self, disk_index: int, bucket: str, object_name: str, at: int = 100) -> bool:
        p = self.shard_file(disk_index, bucket, object_name)
        if p is None:
            return False
        with open(p, "r+b") as f:
            f.seek(at)
            b = f.read(1)
            f.seek(at)
            f.write(bytes([b[0] ^ 0xFF]))
        return True

    def delete_shard(self, disk_index: int, bucket: str, object_name: str) -> bool:
        p = self.shard_file(disk_index, bucket, object_name)
        if p is None:
            return False
        os.remove(p)
        return True

    def delete_object_dir(self, disk_index: int, bucket: str, object_name: str) -> None:
        import shutil

        p = os.path.join(self.dirs[disk_index], bucket, object_name)
        if os.path.isdir(p):
            shutil.rmtree(p)
