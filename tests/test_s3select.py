"""S3 Select tests: SQL parsing/eval, readers, event-stream, HTTP end-to-end.

Mirrors the reference's internal/s3select/select_test.go coverage (CSV + JSON
queries, aggregates, functions, output serialization, framing).
"""

import bz2
import gzip
import json

import pytest

from minio_tpu.s3select import decode_messages
from minio_tpu.s3select.eval import StatementExecutor
from minio_tpu.s3select.readers import CSVArgs, JSONArgs, csv_records, json_records
from minio_tpu.s3select.select import S3SelectRequest, run_select
from minio_tpu.s3select.sql import SQLParseError, parse


CSV_DATA = (
    "name,age,city\n"
    "alice,30,paris\n"
    "bob,25,london\n"
    "carol,35,paris\n"
    "dave,28,tokyo\n"
).encode()

JSON_LINES = (
    b'{"name":"alice","age":30,"tags":["a","b"]}\n'
    b'{"name":"bob","age":25,"tags":[]}\n'
    b'{"name":"carol","age":35,"nested":{"x":1}}\n'
)


def run_csv(sql, data=CSV_DATA, header="USE", out="csv"):
    req = S3SelectRequest(expression=sql)
    req.csv_args.file_header_info = header
    req.output_format = out
    frames = b"".join(run_select(req, lambda o, l: data))
    payload = b""
    kinds = []
    for m in decode_messages(frames):
        kinds.append(m["headers"].get(":event-type") or m["headers"].get(":message-type"))
        if m["headers"].get(":event-type") == "Records":
            payload += m["payload"]
    return payload.decode(), kinds


def run_json(sql, data=JSON_LINES, out="json"):
    req = S3SelectRequest(expression=sql)
    req.input_format = "json"
    req.output_format = out
    frames = b"".join(run_select(req, lambda o, l: data))
    payload = b""
    err = None
    for m in decode_messages(frames):
        if m["headers"].get(":event-type") == "Records":
            payload += m["payload"]
        if m["headers"].get(":message-type") == "error":
            err = m["headers"][":error-code"]
    return payload.decode(), err


# ---------------------------------------------------------------- SQL parser


def test_parse_basic():
    s = parse("SELECT * FROM S3Object")
    assert s.where is None and s.limit is None


def test_parse_full():
    s = parse(
        "select s.name, s.age + 1 as agep from S3Object as s "
        "where s.age > 26 and s.city in ('paris', 'tokyo') limit 10"
    )
    assert s.table_alias == "s"
    assert s.limit == 10
    assert len(s.projections) == 2
    assert s.projections[1].alias == "agep"


def test_parse_errors():
    for bad in (
        "SELECT",
        "SELECT * FROM Other",
        "SELECT * FROM S3Object WHERE",
        "SELECT * FROM S3Object LIMIT -1",
        "SELECT * FROM S3Object trailing garbage junk",
    ):
        with pytest.raises(SQLParseError):
            parse(bad)


def test_parse_aggregate_mixing_rejected():
    from minio_tpu.s3select.eval import SelectEvalError

    with pytest.raises(SelectEvalError):
        StatementExecutor(parse("SELECT name, COUNT(*) FROM S3Object"))


# ----------------------------------------------------------------- CSV paths


def test_csv_select_star():
    out, kinds = run_csv("SELECT * FROM S3Object")
    assert out == "alice,30,paris\nbob,25,london\ncarol,35,paris\ndave,28,tokyo\n"
    assert kinds[-2:] == ["Stats", "End"]


def test_csv_where_and_projection():
    out, _ = run_csv("SELECT name FROM S3Object s WHERE s.age > 26")
    assert out == "alice\ncarol\ndave\n"


def test_csv_positional_columns_no_header():
    data = b"1,2,3\n4,5,6\n"
    out, _ = run_csv("SELECT _2 FROM S3Object", data=data, header="NONE")
    assert out == "2\n5\n"


def test_csv_header_ignore():
    out, _ = run_csv("SELECT _1 FROM S3Object", header="IGNORE")
    assert out.splitlines()[0] == "alice"


def test_csv_limit():
    out, _ = run_csv("SELECT name FROM S3Object LIMIT 2")
    assert out == "alice\nbob\n"


def test_csv_arithmetic_and_concat():
    out, _ = run_csv("SELECT s.age * 2, s.name || '!' FROM S3Object s WHERE s.name = 'bob'")
    assert out == "50,bob!\n"


def test_csv_between_like_in():
    out, _ = run_csv("SELECT name FROM S3Object WHERE age BETWEEN 26 AND 31")
    assert out == "alice\ndave\n"
    out, _ = run_csv("SELECT name FROM S3Object WHERE city LIKE 'p%'")
    assert out == "alice\ncarol\n"
    out, _ = run_csv("SELECT name FROM S3Object WHERE name NOT IN ('alice','bob','carol')")
    assert out == "dave\n"


def test_csv_aggregates():
    out, _ = run_csv("SELECT COUNT(*), SUM(age), MIN(age), MAX(age), AVG(age) FROM S3Object")
    assert out == "4,118,25,35,29.5\n"


def test_csv_aggregate_with_where():
    out, _ = run_csv("SELECT COUNT(*) FROM S3Object WHERE city = 'paris'")
    assert out == "2\n"


def test_csv_functions():
    out, _ = run_csv("SELECT UPPER(name), CHAR_LENGTH(city) FROM S3Object LIMIT 1")
    assert out == "ALICE,5\n"
    out, _ = run_csv("SELECT SUBSTRING(name FROM 2 FOR 3) FROM S3Object LIMIT 1")
    assert out == "lic\n"
    out, _ = run_csv("SELECT TRIM('  x  ') FROM S3Object LIMIT 1")
    assert out == "x\n"
    out, _ = run_csv("SELECT COALESCE(missing_col, name) FROM S3Object LIMIT 1")
    assert out == "alice\n"


def test_csv_cast():
    out, _ = run_csv("SELECT CAST(age AS INT) + 1 FROM S3Object LIMIT 1")
    assert out == "31\n"
    out, _ = run_csv("SELECT CAST(age AS FLOAT) / 4 FROM S3Object LIMIT 1")
    assert out == "7.5\n"


def test_csv_output_json():
    out, _ = run_csv("SELECT name, age FROM S3Object LIMIT 1", out="json")
    assert json.loads(out) == {"name": "alice", "age": "30"}


def test_csv_quoted_output():
    data = b'a,b\n"x,y",2\n'
    out, _ = run_csv("SELECT a FROM S3Object", data=data, header="USE")
    assert out == '"x,y"\n'


# ---------------------------------------------------------------- JSON paths


def test_json_select_fields():
    out, _ = run_json("SELECT s.name FROM S3Object s WHERE s.age >= 30")
    rows = [json.loads(l) for l in out.strip().splitlines()]
    assert rows == [{"name": "alice"}, {"name": "carol"}]


def test_json_nested_and_missing():
    out, _ = run_json("SELECT s.nested.x FROM S3Object s")
    rows = [json.loads(l) for l in out.strip().splitlines()]
    # MISSING columns are omitted entirely
    assert rows == [{}, {}, {"x": 1}]


def test_json_is_missing():
    out, _ = run_json("SELECT s.name FROM S3Object s WHERE s.nested IS NOT MISSING")
    assert json.loads(out.strip()) == {"name": "carol"}


def test_json_array_index():
    out, _ = run_json("SELECT s.tags[0] FROM S3Object s WHERE s.name = 'alice'")
    assert json.loads(out.strip()) == {"_1": "a"}


def test_json_document_type():
    doc = json.dumps({"rows": [{"v": 1}, {"v": 2}, {"v": 3}]}).encode()
    req = S3SelectRequest(expression="SELECT r.v FROM S3Object[*].rows[*] r")
    req.input_format = "json"
    req.json_args.json_type = "DOCUMENT"
    req.output_format = "json"
    frames = b"".join(run_select(req, lambda o, l: doc))
    payload = b"".join(
        m["payload"] for m in decode_messages(frames) if m["headers"].get(":event-type") == "Records"
    )
    rows = [json.loads(l) for l in payload.decode().strip().splitlines()]
    assert rows == [{"v": 1}, {"v": 2}, {"v": 3}]


def test_json_select_star():
    out, _ = run_json("SELECT * FROM S3Object WHERE age = 25")
    assert json.loads(out.strip()) == {"name": "bob", "age": 25, "tags": []}


def test_json_aggregate():
    out, _ = run_json("SELECT SUM(s.age) FROM S3Object s", out="csv")
    assert out == "90\n"


# ------------------------------------------------------------- compression


def test_gzip_input():
    req = S3SelectRequest(expression="SELECT COUNT(*) FROM S3Object")
    req.csv_args.file_header_info = "USE"
    req.compression = "GZIP"
    blob = gzip.compress(CSV_DATA)
    frames = b"".join(run_select(req, lambda o, l: blob))
    payload = b"".join(
        m["payload"] for m in decode_messages(frames) if m["headers"].get(":event-type") == "Records"
    )
    assert payload == b"4\n"


def test_bzip2_input():
    req = S3SelectRequest(expression="SELECT COUNT(*) FROM S3Object")
    req.csv_args.file_header_info = "USE"
    req.compression = "BZIP2"
    blob = bz2.compress(CSV_DATA)
    frames = b"".join(run_select(req, lambda o, l: blob))
    payload = b"".join(
        m["payload"] for m in decode_messages(frames) if m["headers"].get(":event-type") == "Records"
    )
    assert payload == b"4\n"


# --------------------------------------------------------------- scan range


def test_scan_range_lines():
    data = b"l1\nl2\nl3\nl4\n"
    # range starting mid-record: skip partial, process until record covering end
    recs = list(csv_records(data, CSVArgs(), scan_start=1, scan_end=7))
    vals = [r.values[0] for r in recs]
    assert vals == ["l2", "l3"]
    recs = list(csv_records(data, CSVArgs(), scan_start=0, scan_end=1))
    assert [r.values[0] for r in recs] == ["l1"]


# -------------------------------------------------------------- eventstream


def test_eventstream_roundtrip():
    from minio_tpu.s3select.eventstream import records_message, stats_message

    buf = records_message(b"hello") + stats_message(1, 2, 3)
    msgs = list(decode_messages(buf))
    assert msgs[0]["headers"][":event-type"] == "Records"
    assert msgs[0]["payload"] == b"hello"
    assert b"<BytesReturned>3</BytesReturned>" in msgs[1]["payload"]


def test_error_frame_for_bad_column_math():
    # arithmetic on a non-numeric string mid-stream -> in-band error frame
    data = b"a\nxyz\n"
    req = S3SelectRequest(expression="SELECT a * 2 FROM S3Object")
    req.csv_args.file_header_info = "USE"
    frames = b"".join(run_select(req, lambda o, l: data))
    kinds = [
        m["headers"].get(":message-type") for m in decode_messages(frames)
    ]
    assert "error" in kinds


def test_request_xml_parsing():
    xml = b"""<?xml version="1.0" encoding="UTF-8"?>
<SelectObjectContentRequest xmlns="http://s3.amazonaws.com/doc/2006-03-01/">
  <Expression>SELECT * FROM S3Object</Expression>
  <ExpressionType>SQL</ExpressionType>
  <InputSerialization>
    <CompressionType>GZIP</CompressionType>
    <CSV><FileHeaderInfo>USE</FileHeaderInfo><FieldDelimiter>;</FieldDelimiter></CSV>
  </InputSerialization>
  <OutputSerialization><JSON><RecordDelimiter>,</RecordDelimiter></JSON></OutputSerialization>
  <RequestProgress><Enabled>true</Enabled></RequestProgress>
  <ScanRange><Start>10</Start><End>100</End></ScanRange>
</SelectObjectContentRequest>"""
    req = S3SelectRequest.from_xml(xml)
    assert req.compression == "GZIP"
    assert req.csv_args.field_delimiter == ";"
    assert req.output_format == "json"
    assert req.out_json.record_delimiter == ","
    assert req.progress is True
    assert (req.scan_start, req.scan_end) == (10, 100)


# ------------------------------------------------------------- HTTP e2e


@pytest.fixture(scope="module")
def http_stack(tmp_path_factory):
    from minio_tpu.api.server import S3Server, ThreadedServer
    from minio_tpu.control.iam import IAMSys
    from minio_tpu.object.pools import ServerPools
    from minio_tpu.object.sets import ErasureSets
    from tests.harness import ErasureHarness
    from tests.s3client import S3TestClient

    tmp = tmp_path_factory.mktemp("s3select")
    hz = ErasureHarness(tmp, n_disks=8)
    layer = ServerPools([ErasureSets([d for d in hz.drives], 8)])
    iam = IAMSys("selectak", "select-secret")
    srv = S3Server(layer, iam, check_skew=False)
    ts = ThreadedServer(srv)
    endpoint = ts.start()
    client = S3TestClient(endpoint, "selectak", "select-secret")
    yield client
    ts.stop()


def test_select_over_http(http_stack):
    client = http_stack
    assert client.make_bucket("selbkt").status_code == 200
    assert client.put_object("selbkt", "data.csv", CSV_DATA).status_code == 200
    body = b"""<?xml version="1.0" encoding="UTF-8"?>
<SelectObjectContentRequest>
  <Expression>SELECT name FROM S3Object WHERE age &gt; 26</Expression>
  <ExpressionType>SQL</ExpressionType>
  <InputSerialization><CSV><FileHeaderInfo>USE</FileHeaderInfo></CSV></InputSerialization>
  <OutputSerialization><CSV/></OutputSerialization>
</SelectObjectContentRequest>"""
    r = client.request(
        "POST", "/selbkt/data.csv",
        query=[("select", ""), ("select-type", "2")], body=body,
    )
    assert r.status_code == 200, r.text
    payload = b"".join(
        m["payload"]
        for m in decode_messages(r.content)
        if m["headers"].get(":event-type") == "Records"
    )
    assert payload == b"alice\ncarol\ndave\n"


def test_select_over_http_json_output(http_stack):
    client = http_stack
    client.make_bucket("selbkt2")
    client.put_object("selbkt2", "d.json", JSON_LINES)
    body = b"""<SelectObjectContentRequest>
  <Expression>SELECT s.name, s.age FROM S3Object s WHERE s.age &lt; 31</Expression>
  <ExpressionType>SQL</ExpressionType>
  <InputSerialization><JSON><Type>LINES</Type></JSON></InputSerialization>
  <OutputSerialization><JSON/></OutputSerialization>
</SelectObjectContentRequest>"""
    r = client.request(
        "POST", "/selbkt2/d.json",
        query=[("select", ""), ("select-type", "2")], body=body,
    )
    assert r.status_code == 200, r.text
    payload = b"".join(
        m["payload"]
        for m in decode_messages(r.content)
        if m["headers"].get(":event-type") == "Records"
    )
    rows = [json.loads(l) for l in payload.decode().strip().splitlines()]
    assert rows == [{"name": "alice", "age": 30}, {"name": "bob", "age": 25}]
