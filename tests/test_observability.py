"""Observability surface: span trees over the trace hub + Prometheus metrics.

Covers the request-scoped tracing subsystem (control/tracing.py) end to end
-- a distributed PUT must yield ONE span tree keyed by the x-amz-request-id,
with api/object/erasure/storage layers and the remote hops carried over the
storage REST trace header -- and the /minio/v2/metrics/{node,cluster}
exposition, validated with the pure-Python checker in tools/metrics_lint.py
(the same one CI runs, so the hand-rendered format cannot drift).
"""

import importlib.util
import queue
import socket
import threading
from pathlib import Path
from types import SimpleNamespace

import pytest

from minio_tpu.api.server import ThreadedServer
from minio_tpu.control import tracing
from minio_tpu.control.pubsub import GLOBAL_TRACE
from minio_tpu.dist.node import Node
from tests.s3client import S3TestClient

_LINT_PATH = Path(__file__).resolve().parent.parent / "tools" / "metrics_lint.py"
_spec = importlib.util.spec_from_file_location("metrics_lint", _LINT_PATH)
metrics_lint = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(metrics_lint)


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


ROOT = "obsadmin"
SECRET = "obs-secret-key-123"


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("obs-cluster")
    ports = [_free_port(), _free_port()]
    urls = [f"http://127.0.0.1:{p}" for p in ports]
    endpoints = []
    for ni in range(2):
        for di in range(4):
            endpoints.append(f"{urls[ni]}{tmp}/n{ni}d{di}")
    nodes = [
        Node(endpoints, url=urls[ni], root_user=ROOT, root_password=SECRET, set_drive_count=8)
        for ni in range(2)
    ]
    servers = []
    for ni, node in enumerate(nodes):
        ts = ThreadedServer(SimpleNamespace(app=node.make_app()), port=ports[ni])
        ts.start()
        servers.append(ts)
    threads = [threading.Thread(target=n.build) for n in nodes]
    for t in threads:
        t.start()
    for t in threads:
        t.join(60)
    assert all(n.pools is not None for n in nodes), "cluster failed to build"
    clients = [S3TestClient(urls[ni], ROOT, SECRET) for ni in range(2)]
    clients[0].make_bucket("obs")
    yield {"nodes": nodes, "clients": clients, "urls": urls}
    for ts in servers:
        ts.stop()


def _drain(q: "queue.Queue") -> list[dict]:
    out = []
    while True:
        try:
            out.append(q.get_nowait())
        except queue.Empty:
            return out


class TestSpanTree:
    def test_distributed_put_single_rooted_span_tree(self, cluster):
        """One PUT through the 2-node erasure set: every span -- api root,
        object op, erasure encode, per-drive storage calls on BOTH nodes --
        shares the request id, and the remote node's storage spans chain
        under the rpc hop spans (trace header over storage REST)."""
        client = cluster["clients"][0]
        sub = GLOBAL_TRACE.subscribe()
        try:
            r = client.put_object("obs", "traced.bin", b"t" * 4096)
            assert r.status_code == 200
            request_id = r.headers["x-amz-request-id"]
            records = _drain(sub)
        finally:
            GLOBAL_TRACE.unsubscribe(sub)

        tree = tracing.build_tree(records, request_id)
        roots = tree.get("", [])
        assert len(roots) == 1, f"expected one root, got {roots}"
        assert roots[0]["layer"] == "api"
        assert roots[0]["name"] == "PutObject"

        spans = list(tracing.walk_tree(tree))
        layers = {s["layer"] for s in spans}
        assert {"api", "object", "erasure", "storage"} <= layers, layers

        # Every span in the tree is reachable from the single root.
        all_for_trace = [
            r for r in records if r.get("type") == "span" and r.get("trace") == request_id
        ]
        assert len(spans) == len(all_for_trace), "disconnected spans in trace"

        # Per-drive storage spans: a write quorum of the 8-drive set.
        storage = [s for s in spans if s["layer"] == "storage"]
        drives = {s.get("drive", "") for s in storage}
        assert len(drives) >= 4, f"expected multi-drive fan-out, got {drives}"

        # Remote hops: node 1's drives (paths .../n1d*) reached over storage
        # REST, their spans parented under this node's rpc spans.
        remote_storage = [s for s in storage if "/n1d" in s.get("drive", "")]
        assert remote_storage, "no storage spans from the remote node"
        rpc_ids = {s["span"] for s in spans if s["layer"] == "rpc"}
        assert rpc_ids, "no rpc hop spans"
        assert all(s["parent"] in rpc_ids for s in remote_storage)

    def test_no_subscriber_means_noop_spans(self):
        assert tracing.span("x", "object") is tracing.NOOP
        with tracing.span("x", "object") as sp:
            assert sp.header() == ""

    def test_span_nesting_and_header_adoption(self):
        sub = GLOBAL_TRACE.subscribe()
        try:
            with tracing.root_span("Req", "api", "TRACE1") as root:
                with tracing.span("child", "object") as child:
                    assert child.trace_id == "TRACE1"
                    assert child.parent_id == root.span_id
                    wire = child.header()
            with tracing.bind_header(wire):
                with tracing.span("far-side", "storage") as far:
                    assert far.trace_id == "TRACE1"
        finally:
            GLOBAL_TRACE.unsubscribe(sub)
        recs = _drain(sub)
        tree = tracing.build_tree(recs, "TRACE1")
        assert len(tree.get("", [])) == 1
        assert len(list(tracing.walk_tree(tree))) == 3


class TestMetricsExposition:
    def test_node_metrics_valid_and_complete(self, cluster):
        client = cluster["clients"][0]
        # Generate traffic so drive/api series exist before the scrape.
        assert client.put_object("obs", "m.bin", b"m" * 1024).status_code == 200
        assert client.get_object("obs", "m.bin").status_code == 200
        r = client.request("GET", "/minio/v2/metrics/node")
        assert r.status_code == 200
        text = r.text
        assert metrics_lint.validate_exposition(text) == []
        assert metrics_lint.lint_exposition(text) == []
        # Series absent from the seed: drive, codec/device, heal/scanner.
        assert "minio_tpu_drive_latency_ms" in text
        assert "minio_tpu_drive_calls_total" in text
        assert "minio_tpu_device_probe_done" in text
        assert "minio_tpu_heal_mrf_pending" in text
        assert "minio_tpu_scanner_cycles_completed_total" in text
        # Histogram survived the refactor.
        assert "minio_tpu_s3_request_duration_seconds_bucket" in text

    def test_cluster_metrics_aggregate_two_nodes(self, cluster):
        client = cluster["clients"][0]
        r = client.request("GET", "/minio/v2/metrics/cluster")
        assert r.status_code == 200
        text = r.text
        assert metrics_lint.validate_exposition(text) == []
        assert metrics_lint.lint_exposition(text) == []
        servers = {
            lbls["server"]
            for _ln, _name, lbls, _v in metrics_lint.parse_samples(text)
            if "server" in lbls
        }
        assert len(servers) >= 2, f"cluster view has {servers}"
        for url in cluster["urls"]:
            assert url in servers

    def test_validator_catches_breakage(self):
        bad = (
            "# HELP m_total count\n"
            "# TYPE m_total counter\n"
            'm_total{a="1"} 5\n'
            'm_total{a="1"} 6\n'  # duplicate sample
        )
        assert any("duplicate sample" in p for p in metrics_lint.validate_exposition(bad))
        nohelp = "# TYPE x_total counter\nx_total 1\n"
        assert any("TYPE without HELP" in p for p in metrics_lint.validate_exposition(nohelp))
        nonmono = (
            "# HELP h request hist\n"
            "# TYPE h histogram\n"
            'h_bucket{le="0.1"} 5\n'
            'h_bucket{le="1"} 3\n'
            'h_bucket{le="+Inf"} 6\n'
            "h_sum 1.0\n"
            "h_count 6\n"
        )
        assert any("not monotone" in p for p in metrics_lint.validate_exposition(nonmono))
        badcount = (
            "# HELP h request hist\n"
            "# TYPE h histogram\n"
            'h_bucket{le="+Inf"} 6\n'
            "h_sum 1.0\n"
            "h_count 7\n"
        )
        assert any("_count" in p for p in metrics_lint.validate_exposition(badcount))


class TestPerfEndpoint:
    """The always-on attribution surface: with NO trace subscriber, a PUT
    must leave non-zero stage histograms behind, served by /mtpu/admin/v1
    /perf with p50/p95/p99 per stage (the ISSUE's acceptance criterion)."""

    # > SMALL_FILE_THRESHOLD (128 KiB) so the PUT takes the streaming path
    # and exercises encode -> shard-fanout -> commit.
    BODY = b"p" * (256 << 10)

    def test_put_populates_stage_histograms_without_subscriber(self, cluster):
        client = cluster["clients"][0]
        assert not GLOBAL_TRACE.enabled()
        assert client.put_object("obs", "perf.bin", self.BODY).status_code == 200
        assert client.get_object("obs", "perf.bin").status_code == 200

        r = client.request("GET", "/mtpu/admin/v1/perf")
        assert r.status_code == 200, r.text
        doc = r.json()
        stages = doc["node"]["stages"]
        assert stages["api"]["auth"]["count"] > 0
        for stage in ("encode", "shard-fanout", "commit"):
            row = stages["object"][stage]
            assert row["count"] > 0, stage
            for k in ("p50_ms", "p95_ms", "p99_ms", "mean_ms", "total_ms"):
                assert row[k] >= 0
            assert row["p50_ms"] <= row["p95_ms"] <= row["p99_ms"]
        # GET side: the shard gather and the response stream are attributed.
        assert stages["object"]["shard-read"]["count"] > 0
        assert stages["api"]["response-write"]["count"] > 0
        # Storage calls + internode RPC feed the ledger outside spans too.
        assert "storage" in stages
        assert any(s.startswith("/") for s in stages.get("rpc-peer", {})), stages.keys()
        # Satellite: drive EWMAs + breaker state ride the same payload.
        assert doc["drives"], "no drive latency rows"
        some = next(iter(doc["drives"].values()))
        assert "api" in some and "breaker" in some
        assert "slow" in doc

    def test_cluster_view_merges_peers(self, cluster):
        client = cluster["clients"][0]
        assert client.put_object("obs", "perf2.bin", self.BODY).status_code == 200
        r = client.request("GET", "/mtpu/admin/v1/perf", query=[("cluster", "1")])
        assert r.status_code == 200, r.text
        doc = r.json()
        assert doc["peers"], "no peers consulted"
        assert all(p["ok"] for p in doc["peers"].values()), doc["peers"]
        merged = doc["cluster"]["stages"]
        node = doc["node"]["stages"]
        # The merged view contains at least everything this node recorded.
        assert merged["object"]["commit"]["count"] >= node["object"]["commit"]["count"]

    def test_perf_slow_surface_and_reset(self, cluster):
        client = cluster["clients"][0]
        r = client.request("GET", "/mtpu/admin/v1/perf/slow")
        assert r.status_code == 200, r.text
        doc = r.json()
        for k in ("budget_ms", "max_traces", "max_bytes", "max_spans_per_trace",
                  "evicted_spans", "evicted_traces"):
            assert k in doc["stats"], k
        assert isinstance(doc["traces"], list)

        # ?reset=1 opens a clean measurement window.
        r = client.request("GET", "/mtpu/admin/v1/perf", query=[("reset", "1")])
        assert r.status_code == 200 and r.json().get("reset") is True
        r = client.request("GET", "/mtpu/admin/v1/perf")
        stages = r.json()["node"]["stages"]
        # Only the reset GET itself may have recorded since: no object ops.
        assert "object" not in stages or all(
            s not in stages["object"] for s in ("encode", "shard-fanout", "commit")
        )

    def test_stage_histograms_reach_prometheus(self, cluster):
        client = cluster["clients"][0]
        assert client.put_object("obs", "perf3.bin", self.BODY).status_code == 200
        r = client.request("GET", "/minio/v2/metrics/node")
        assert r.status_code == 200
        text = r.text
        assert "minio_tpu_stage_duration_seconds_bucket" in text
        # Codec observatory: the native gauge always renders; the batching
        # series appear only when the device codec is installed (the CPU
        # test cluster serves the host codec -- see test_perf.py for the
        # device-codec exposition).
        assert "minio_tpu_native_codec_available" in text
        # The new histogram family passes the extended exposition checks
        # (monotone le, +Inf == _count, consistent boundaries per family).
        assert metrics_lint.validate_exposition(text) == []
        assert metrics_lint.lint_exposition(text) == []
        stage_samples = [
            (name, lbls, v)
            for _ln, name, lbls, v in metrics_lint.parse_samples(text)
            if name.startswith("minio_tpu_stage_duration_seconds")
        ]
        assert any(
            name.endswith("_count") and lbls.get("stage") == "commit" and v > 0
            for name, lbls, v in stage_samples
        ), "commit stage not exported"


class TestIAMCascade:
    def test_remove_user_cascades_to_children(self):
        from minio_tpu.control.iam import IAMSys
        from minio_tpu.utils import errors

        iam = IAMSys("root", "rootsecret12")
        iam.add_user("alice", "alicesecret1")
        sa = iam.new_service_account("alice")
        assert sa.access_key in iam.users
        iam.remove_user("alice")
        assert "alice" not in iam.users
        assert sa.access_key not in iam.users, "service account survived cascade"
        with pytest.raises(errors.StorageError):
            iam.remove_user("alice")
