"""Local drive + xl.meta + format tests (xl-storage_test.go analogues)."""

import os

import pytest

from minio_tpu.storage import format as fmt
from minio_tpu.storage.local import LocalDrive
from minio_tpu.storage.types import ErasureInfo, FileInfo, ObjectPartInfo
from minio_tpu.storage.xlmeta import XLMeta
from minio_tpu.utils import errors


@pytest.fixture
def drive(tmp_path):
    return LocalDrive(str(tmp_path / "disk0"))


def _fi(version_id="", name="obj", inline=b"", data_dir="", mod_time=1.0):
    return FileInfo(
        volume="bucket",
        name=name,
        version_id=version_id,
        data_dir=data_dir,
        mod_time=mod_time,
        size=len(inline),
        metadata={"etag": "abc"},
        parts=[ObjectPartInfo(1, len(inline))],
        erasure=ErasureInfo(data_blocks=2, parity_blocks=1, index=1, distribution=[1, 2, 3]),
        inline_data=inline,
    )


class TestXLMeta:
    def test_roundtrip_with_inline(self):
        m = XLMeta()
        m.add_version(_fi("v1", inline=b"hello", mod_time=1.0))
        m.add_version(_fi("v2", inline=b"world!", mod_time=2.0))
        raw = m.to_bytes()
        m2 = XLMeta.from_bytes(raw)
        assert [v.version_id for v in m2.versions] == ["v2", "v1"]
        assert m2.find_version("v1").inline_data == b"hello"
        assert m2.find_version("v2").inline_data == b"world!"
        assert m2.latest().version_id == "v2"

    def test_checksum_detects_corruption(self):
        m = XLMeta()
        m.add_version(_fi("v1", inline=b"data"))
        raw = bytearray(m.to_bytes())
        raw[12] ^= 0xFF
        with pytest.raises(errors.FileCorrupt):
            XLMeta.from_bytes(bytes(raw))

    def test_delete_version(self):
        m = XLMeta()
        m.add_version(_fi("v1", mod_time=1.0))
        m.add_version(_fi("v2", mod_time=2.0))
        m.delete_version("v2")
        assert m.latest().version_id == "v1"
        with pytest.raises(errors.FileVersionNotFound):
            m.delete_version("nope")

    def test_replace_same_version(self):
        m = XLMeta()
        m.add_version(_fi("v1", inline=b"a", mod_time=1.0))
        m.add_version(_fi("v1", inline=b"bb", mod_time=2.0))
        assert len(m.versions) == 1
        assert m.latest().inline_data == b"bb"


class TestLocalDrive:
    def test_volumes(self, drive):
        drive.make_vol("bucket")
        with pytest.raises(errors.VolumeExists):
            drive.make_vol("bucket")
        assert [v.name for v in drive.list_vols()] == ["bucket"]
        drive.delete_vol("bucket")
        with pytest.raises(errors.VolumeNotFound):
            drive.stat_vol("bucket")

    def test_write_read_all(self, drive):
        drive.make_vol("b")
        drive.write_all("b", "cfg/x.json", b"{}")
        assert drive.read_all("b", "cfg/x.json") == b"{}"
        with pytest.raises(errors.FileNotFound):
            drive.read_all("b", "missing")
        with pytest.raises(errors.VolumeNotFound):
            drive.read_all("nope", "missing")

    def test_path_escape_blocked(self, drive):
        drive.make_vol("b")
        with pytest.raises(errors.StorageError):
            drive.read_all("b", "../../../etc/passwd")

    def test_metadata_versions(self, drive):
        drive.make_vol("bucket")
        drive.write_metadata("bucket", "a/obj", _fi("v1", inline=b"xx", mod_time=1.0))
        drive.write_metadata("bucket", "a/obj", _fi("v2", inline=b"yy", mod_time=2.0))
        fi = drive.read_version("bucket", "a/obj")
        assert fi.version_id == "v2"
        assert fi.is_latest
        fi1 = drive.read_version("bucket", "a/obj", "v1")
        assert not fi1.is_latest
        assert fi1.inline_data == b"xx"

    def test_rename_data_commit(self, drive):
        drive.make_vol("bucket")
        # Stage shard files in tmp, then commit.
        tmp = ".minio_tpu.sys/tmp"
        drive.create_file("bucket", f"{tmp}/upload1/part.1", b"shard-bytes")
        fi = _fi("v1", data_dir="datadir-uuid")
        drive.rename_data("bucket", f"{tmp}/upload1", fi, "bucket", "obj")
        assert drive.read_file("bucket", "obj/datadir-uuid/part.1") == b"shard-bytes"
        assert drive.read_version("bucket", "obj").version_id == "v1"
        # Staged dir is gone.
        with pytest.raises(errors.FileNotFound):
            drive.read_file("bucket", f"{tmp}/upload1/part.1")

    def test_delete_version_flow(self, drive):
        drive.make_vol("bucket")
        drive.create_file("bucket", ".minio_tpu.sys/tmp/u1/part.1", b"d1")
        drive.rename_data("bucket", ".minio_tpu.sys/tmp/u1", _fi("v1", data_dir="dd1"), "bucket", "obj")
        drive.delete_version("bucket", "obj", _fi("v1", data_dir="dd1"))
        with pytest.raises(errors.FileNotFound):
            drive.read_xl("bucket", "obj")
        # Data dir removed and object dir pruned.
        assert not os.path.exists(os.path.join(drive.root, "bucket", "obj"))

    def test_delete_marker(self, drive):
        drive.make_vol("bucket")
        drive.write_metadata("bucket", "obj", _fi("v1", inline=b"x", mod_time=1.0))
        dm = _fi("v2", mod_time=2.0)
        dm.deleted = True
        drive.delete_version("bucket", "obj", dm)
        meta = drive.read_xl("bucket", "obj")
        assert meta.latest().deleted
        assert len(meta.versions) == 2

    def test_walk_dir(self, drive):
        drive.make_vol("bucket")
        for name in ["a/1", "a/2", "b/x/deep", "top"]:
            drive.write_metadata("bucket", name, _fi("v1", inline=b"d"))
        entries = [path for path, _ in drive.walk_dir("bucket")]
        assert entries == ["a/1", "a/2", "b/x/deep", "top"]
        shallow = [path for path, _ in drive.walk_dir("bucket", recursive=False)]
        assert shallow == ["a/", "b/", "top"]

    def test_list_dir(self, drive):
        drive.make_vol("bucket")
        drive.write_all("bucket", "d/f1", b"1")
        drive.write_all("bucket", "f2", b"2")
        assert drive.list_dir("bucket", "") == ["d/", "f2"]


class TestFormat:
    def test_init_and_quorum(self, tmp_path):
        formats = fmt.init_format(2, 4)
        assert len(formats) == 8
        dep = formats[0].deployment_id
        assert all(f.deployment_id == dep for f in formats)
        # Save/load roundtrip.
        root = str(tmp_path / "d0")
        os.makedirs(root)
        formats[0].save(root)
        loaded = fmt.DriveFormat.load(root)
        assert loaded.this_id == formats[0].this_id
        assert loaded.find_disk(loaded.this_id) == (0, 0)
        # Quorum picks majority layout.
        q = fmt.quorum_format(list(formats[:5]) + [None] * 3)
        assert q.deployment_id == dep
        with pytest.raises(errors.UnformattedDisk):
            fmt.quorum_format([None, None])

    def test_quorum_not_reached(self):
        formats = fmt.init_format(1, 4)
        with pytest.raises(errors.ErasureReadQuorum):
            fmt.quorum_format(formats[:2] + [None, None])

    def test_disk_id(self, tmp_path):
        root = str(tmp_path / "d1")
        drive = LocalDrive(root)
        assert drive.disk_id() == ""
        f = fmt.init_format(1, 1)[0]
        f.save(root)
        drive2 = LocalDrive(root)
        assert drive2.disk_id() == f.this_id
