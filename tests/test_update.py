"""Self-update: signed release verification, staging, apply/rollback
(cmd/update.go:587 role)."""

import base64
import hashlib
import io
import json
import os
import subprocess
import sys
import tarfile

import pytest

from minio_tpu.control import update as upd


def _keypair():
    from cryptography.hazmat.primitives.asymmetric.ed25519 import Ed25519PrivateKey
    from cryptography.hazmat.primitives.serialization import (
        Encoding, PublicFormat,
    )

    priv = Ed25519PrivateKey.generate()
    pub_raw = priv.public_key().public_bytes(Encoding.Raw, PublicFormat.Raw)
    return priv, base64.b64encode(pub_raw).decode()


def _make_release(tmp_path, version="0.6.0", tamper=None, sign=True, priv=None):
    """Build a release mirror dir; returns (base_url, pubkey_b64)."""
    priv_new, pub = (None, "")
    if priv is None:
        priv, pub = _keypair()
    else:
        pub = ""  # caller manages the key
    mirror = tmp_path / f"mirror-{version}"
    mirror.mkdir()
    # a tiny "package": one top-level dir with a marker file
    buf = io.BytesIO()
    with tarfile.open(fileobj=buf, mode="w:gz") as tf:
        data = f"version = {version!r}\n".encode()
        ti = tarfile.TarInfo(f"minio_tpu/{'version.py'}")
        ti.size = len(data)
        tf.addfile(ti, io.BytesIO(data))
    blob = buf.getvalue()
    (mirror / "pkg.tar.gz").write_bytes(blob)
    manifest = json.dumps(
        {"version": version, "sha256": hashlib.sha256(blob).hexdigest(), "archive": "pkg.tar.gz"}
    ).encode()
    if tamper == "manifest":
        manifest = manifest.replace(version.encode(), b"6.6.6")
    (mirror / "RELEASE.json").write_bytes(manifest)
    if sign:
        sig = priv.sign(json.dumps(
            {"version": version, "sha256": hashlib.sha256(blob).hexdigest(), "archive": "pkg.tar.gz"}
        ).encode())
        (mirror / "RELEASE.json.sig").write_bytes(sig)
    if tamper == "archive":
        (mirror / "pkg.tar.gz").write_bytes(blob + b"x")
    return f"file://{mirror}", pub


class TestUpdate:
    def test_signed_check_stage_apply_rollback(self, tmp_path):
        url, pub = _make_release(tmp_path)
        info = upd.check_update(url, pubkey_b64=pub)
        assert info.version == "0.6.0"
        staged = upd.download_and_stage(info, str(tmp_path / "stage"))
        assert os.path.isfile(os.path.join(staged, "minio_tpu", "version.py"))
        # apply swaps the install dir and keeps a rollback
        install = tmp_path / "install"
        install.mkdir()
        (install / "old.txt").write_text("previous")
        backup = upd.apply_staged(staged, str(install))
        assert os.path.isfile(install / "minio_tpu" / "version.py")
        assert os.path.isfile(os.path.join(backup, "old.txt"))

    def test_tampered_manifest_rejected(self, tmp_path):
        url, pub = _make_release(tmp_path, tamper="manifest")
        with pytest.raises(upd.UpdateError, match="signature"):
            upd.check_update(url, pubkey_b64=pub)

    def test_tampered_archive_rejected(self, tmp_path):
        url, pub = _make_release(tmp_path, tamper="archive")
        info = upd.check_update(url, pubkey_b64=pub)
        with pytest.raises(upd.UpdateError, match="sha256"):
            upd.download_and_stage(info, str(tmp_path / "stage"))

    def test_unsigned_refused_without_optin(self, tmp_path):
        url, _ = _make_release(tmp_path, sign=False)
        with pytest.raises(upd.UpdateError, match="public key"):
            upd.check_update(url, pubkey_b64="")
        info = upd.check_update(url, pubkey_b64="", allow_unsigned=True)
        assert info.version == "0.6.0"

    def test_wrong_key_rejected(self, tmp_path):
        url, _ = _make_release(tmp_path)
        _, other_pub = _keypair()
        with pytest.raises(upd.UpdateError, match="signature"):
            upd.check_update(url, pubkey_b64=other_pub)

    def test_path_traversal_blocked(self, tmp_path):
        priv, pub = _keypair()
        mirror = tmp_path / "evil"
        mirror.mkdir()
        buf = io.BytesIO()
        with tarfile.open(fileobj=buf, mode="w:gz") as tf:
            data = b"pwned"
            ti = tarfile.TarInfo("../escape.txt")
            ti.size = len(data)
            tf.addfile(ti, io.BytesIO(data))
        blob = buf.getvalue()
        (mirror / "pkg.tar.gz").write_bytes(blob)
        manifest = json.dumps(
            {"version": "1", "sha256": hashlib.sha256(blob).hexdigest(), "archive": "pkg.tar.gz"}
        ).encode()
        (mirror / "RELEASE.json").write_bytes(manifest)
        (mirror / "RELEASE.json.sig").write_bytes(priv.sign(manifest))
        info = upd.check_update(f"file://{mirror}", pubkey_b64=pub)
        with pytest.raises(upd.UpdateError, match="escapes|extraction"):
            upd.download_and_stage(info, str(tmp_path / "stage"))
        assert not (tmp_path / "escape.txt").exists()

    def test_symlink_entry_blocked(self, tmp_path):
        priv, pub = _keypair()
        mirror = tmp_path / "sym"
        mirror.mkdir()
        buf = io.BytesIO()
        with tarfile.open(fileobj=buf, mode="w:gz") as tf:
            ti = tarfile.TarInfo("link")
            ti.type = tarfile.SYMTYPE
            ti.linkname = "/etc/passwd"
            tf.addfile(ti)
        blob = buf.getvalue()
        (mirror / "pkg.tar.gz").write_bytes(blob)
        manifest = json.dumps(
            {"version": "1", "sha256": hashlib.sha256(blob).hexdigest(), "archive": "pkg.tar.gz"}
        ).encode()
        (mirror / "RELEASE.json").write_bytes(manifest)
        (mirror / "RELEASE.json.sig").write_bytes(priv.sign(manifest))
        info = upd.check_update(f"file://{mirror}", pubkey_b64=pub)
        with pytest.raises(upd.UpdateError, match="link"):
            upd.download_and_stage(info, str(tmp_path / "stage"))

    def test_admin_update_endpoint(self, tmp_path, monkeypatch):
        # Admin POST /update checks + stages (never applies over HTTP).
        from types import SimpleNamespace

        from minio_tpu.api.server import ThreadedServer
        from minio_tpu.dist.node import Node
        from minio_tpu.object.codec import HostCodec
        from tests.s3client import S3TestClient

        url, pub = _make_release(tmp_path, version="0.8.0")
        monkeypatch.setenv(upd.PUBKEY_ENV, pub)
        dirs = []
        for i in range(4):
            d = str(tmp_path / f"d{i}")
            os.makedirs(d)
            dirs.append(d)
        node = Node(dirs, root_user="upadmin", root_password="updsecret1", codec=HostCodec())
        ts = ThreadedServer(SimpleNamespace(app=node.make_app()))
        base = ts.start()
        try:
            node.build()
            c = S3TestClient(base, "upadmin", "updsecret1")
            r = c.request("GET", "/mtpu/admin/v1/update")
            assert r.status_code == 200 and r.json()["pubkey_configured"] is True
            r = c.request(
                "POST", "/mtpu/admin/v1/update",
                query=[("url", url), ("stage-dir", str(tmp_path / "adm-stage"))],
            )
            assert r.status_code == 200, r.text
            doc = r.json()
            assert doc["available"] == "0.8.0"
            assert os.path.isdir(doc["staged"])
            # tampered mirror -> clean admin error, nothing staged
            bad_url, _ = _make_release(tmp_path, version="0.9.0", tamper="manifest")
            r = c.request("POST", "/mtpu/admin/v1/update", query=[("url", bad_url)])
            assert r.status_code >= 400
        finally:
            ts.stop()

    def test_cli_update_stages(self, tmp_path):
        url, pub = _make_release(tmp_path, version="0.7.0")
        env = {**os.environ, "MINIO_TPU_UPDATE_PUBKEY": pub,
               "PYTHONPATH": os.path.dirname(os.path.dirname(os.path.abspath(__file__)))}
        r = subprocess.run(
            [sys.executable, "-m", "minio_tpu", "update", url,
             "--stage-dir", str(tmp_path / "cli-stage")],
            capture_output=True, text=True, timeout=60, env=env,
        )
        assert r.returncode == 0, r.stderr
        assert "staged:" in r.stdout and "not applied" in r.stdout
        assert (tmp_path / "cli-stage" / "minio_tpu-0.7.0" / "minio_tpu").is_dir()
