"""FS backend + gateway adapters.

Mirrors the reference's dual-backend test strategy (test-utils_test.go
ExecObjectLayerTest runs each object-API test on FS and erasure): the FS
layer serves the same S3 front; the S3 gateway proxies a backing cluster.
"""

import io
import os
import zipfile

import pytest

from minio_tpu.api.server import S3Server, ThreadedServer
from minio_tpu.control.iam import IAMSys
from minio_tpu.object.fs import FSObjectLayer
from minio_tpu.object.gateway import NASGateway, S3Gateway
from minio_tpu.object.pools import ServerPools
from minio_tpu.object.sets import ErasureSets
from minio_tpu.object.types import PutObjectOptions
from minio_tpu.utils import errors
from tests.harness import ErasureHarness
from tests.s3client import S3TestClient

AK, SK = "fsroot", "fsroot-secret"


# -- FS layer directly --------------------------------------------------------


@pytest.fixture()
def fs(tmp_path):
    return FSObjectLayer(str(tmp_path / "fsroot"))


def test_fs_bucket_lifecycle(fs):
    fs.make_bucket("docs")
    assert fs.bucket_exists("docs")
    with pytest.raises(errors.BucketExists):
        fs.make_bucket("docs")
    assert [b.name for b in fs.list_buckets()] == ["docs"]
    fs.put_object("docs", "a.txt", b"hello")
    with pytest.raises(errors.BucketNotEmpty):
        fs.delete_bucket("docs")
    fs.delete_object("docs", "a.txt")
    fs.delete_bucket("docs")
    assert not fs.bucket_exists("docs")


def test_fs_object_roundtrip(fs):
    fs.make_bucket("data")
    payload = os.urandom(100_000)
    oi = fs.put_object("data", "nested/deep/blob.bin", payload,
                       PutObjectOptions(user_defined={"x-amz-meta-k": "v"}))
    assert oi.etag
    info = fs.get_object_info("data", "nested/deep/blob.bin")
    assert info.size == len(payload)
    assert info.user_defined.get("x-amz-meta-k") == "v"
    _, got = fs.get_object("data", "nested/deep/blob.bin")
    assert got == payload
    _, part = fs.get_object("data", "nested/deep/blob.bin", offset=10, length=20)
    assert part == payload[10:30]
    fs.delete_object("data", "nested/deep/blob.bin")
    with pytest.raises(errors.ObjectNotFound):
        fs.get_object_info("data", "nested/deep/blob.bin")
    # Empty parent prefixes trimmed.
    assert not os.path.exists(os.path.join(fs.root, "data", "nested"))


def test_fs_object_name_traversal_rejected(fs):
    fs.make_bucket("safe")
    with pytest.raises(errors.InvalidArgument):
        fs.put_object("safe", "../escape.txt", b"x")


def test_fs_listing(fs):
    fs.make_bucket("lst")
    for name in ["a.txt", "dir/one.txt", "dir/two.txt", "z.txt"]:
        fs.put_object("lst", name, b"x")
    res = fs.list_objects("lst")
    assert [o.name for o in res.objects] == ["a.txt", "dir/one.txt", "dir/two.txt", "z.txt"]
    res = fs.list_objects("lst", delimiter="/")
    assert [o.name for o in res.objects] == ["a.txt", "z.txt"]
    assert res.prefixes == ["dir/"]
    res = fs.list_objects("lst", prefix="dir/")
    assert [o.name for o in res.objects] == ["dir/one.txt", "dir/two.txt"]
    res = fs.list_objects("lst", max_keys=2)
    assert res.is_truncated and len(res.objects) == 2


def test_fs_multipart(fs):
    fs.make_bucket("mp")
    uid = fs.new_multipart_upload("mp", "big.bin")
    p1 = fs.put_object_part("mp", "big.bin", uid, 1, b"A" * 1000)
    p2 = fs.put_object_part("mp", "big.bin", uid, 2, b"B" * 500)
    parts = fs.list_parts("mp", "big.bin", uid)
    assert [p.number for p in parts] == [1, 2]
    oi = fs.complete_multipart_upload("mp", "big.bin", uid, [(1, p1.etag), (2, p2.etag)])
    assert oi.etag.endswith("-2")
    _, got = fs.get_object("mp", "big.bin")
    assert got == b"A" * 1000 + b"B" * 500
    assert fs.list_multipart_uploads("mp") == []


def test_fs_serves_full_s3_front(tmp_path):
    """The FS layer behind the real signed S3 server (ExecObjectLayerTest's
    FS half)."""
    layer = FSObjectLayer(str(tmp_path / "fssrv"))
    srv = S3Server(layer, IAMSys(AK, SK), check_skew=False)
    ts = ThreadedServer(srv)
    c = S3TestClient(ts.start(), AK, SK)
    try:
        assert c.make_bucket("web").status_code == 200
        data = os.urandom(50_000)
        assert c.put_object("web", "file.bin", data).status_code == 200
        assert c.get_object("web", "file.bin").content == data
        # Bucket policy persists through the FS-backed metadata store.
        r = c.request("GET", "/web", query=[("location", "")])
        assert r.status_code == 200
        # Zip extension works over FS too.
        buf = io.BytesIO()
        with zipfile.ZipFile(buf, "w") as zf:
            zf.writestr("inner.txt", b"zipped")
        c.put_object("web", "a.zip", buf.getvalue())
        r = c.request("GET", "/web/a.zip/inner.txt", headers={"x-minio-extract": "true"})
        assert r.status_code == 200 and r.content == b"zipped"
        assert c.request("DELETE", "/web/file.bin").status_code == 204
    finally:
        ts.stop()


# -- gateways -----------------------------------------------------------------


def test_nas_gateway_is_fs_over_mount(tmp_path):
    nas = NASGateway(str(tmp_path / "mount"))
    nas.make_bucket("shared")
    nas.put_object("shared", "f.txt", b"on the NAS")
    _, got = nas.get_object("shared", "f.txt")
    assert got == b"on the NAS"


@pytest.fixture(scope="module")
def backing(tmp_path_factory):
    """A real erasure cluster acting as the gateway's backing store."""
    tmp = tmp_path_factory.mktemp("backing")
    hz = ErasureHarness(tmp, n_disks=4)
    layer = ServerPools([ErasureSets(list(hz.drives), 4)])
    srv = S3Server(layer, IAMSys("backak", "backsk-secret"), check_skew=False)
    ts = ThreadedServer(srv)
    endpoint = ts.start()
    yield endpoint
    ts.stop()


def test_s3_gateway_proxies(backing):
    gw = S3Gateway(backing, "backak", "backsk-secret")
    gw.make_bucket("gwbkt")
    assert gw.bucket_exists("gwbkt")
    data = os.urandom(80_000)
    oi = gw.put_object("gwbkt", "through.bin", data, PutObjectOptions())
    assert oi.etag
    info = gw.get_object_info("gwbkt", "through.bin")
    assert info.size == len(data)
    _, got = gw.get_object("gwbkt", "through.bin")
    assert got == data
    _, rng = gw.get_object("gwbkt", "through.bin", offset=100, length=50)
    assert rng == data[100:150]
    listing = gw.list_objects("gwbkt")
    assert [o.name for o in listing.objects] == ["through.bin"]
    gw.delete_object("gwbkt", "through.bin")
    with pytest.raises(errors.ObjectNotFound):
        gw.get_object_info("gwbkt", "through.bin")
    gw.delete_bucket("gwbkt")


def test_s3_gateway_multipart(backing):
    gw = S3Gateway(backing, "backak", "backsk-secret")
    gw.make_bucket("gwmp")
    uid = gw.new_multipart_upload("gwmp", "big.bin")
    assert uid
    part_size = 5 * 1024 * 1024  # the backing store's S3 min part size
    p1 = gw.put_object_part("gwmp", "big.bin", uid, 1, b"X" * part_size)
    p2 = gw.put_object_part("gwmp", "big.bin", uid, 2, b"Y" * 100)
    oi = gw.complete_multipart_upload("gwmp", "big.bin", uid, [(1, p1.etag), (2, p2.etag)])
    assert oi.size == part_size + 100
    _, got = gw.get_object("gwmp", "big.bin", offset=part_size - 2, length=4)
    assert got == b"XXYY"


def test_s3_gateway_serves_full_front(backing, tmp_path):
    """Gateway behind its own S3 server: clients of the gateway get auth/
    policy handling locally, data lands in the backing cluster."""
    gw = S3Gateway(backing, "backak", "backsk-secret")
    srv = S3Server(gw, IAMSys("gwroot", "gwroot-secret"), check_skew=False)
    ts = ThreadedServer(srv)
    c = S3TestClient(ts.start(), "gwroot", "gwroot-secret")
    try:
        assert c.make_bucket("fronted").status_code == 200
        data = b"via gateway" * 1000
        assert c.put_object("fronted", "obj.bin", data).status_code == 200
        assert c.get_object("fronted", "obj.bin").content == data
        # Backing cluster really holds it.
        back = S3TestClient(backing, "backak", "backsk-secret")
        assert back.get_object("fronted", "obj.bin").content == data
    finally:
        ts.stop()
