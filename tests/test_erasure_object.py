"""Erasure object layer tests: put/get/delete/heal under faults.

Mirrors the reference's object-layer test surface (cmd/erasure-object_test.go,
erasure-healing_test.go): roundtrips across size classes, degraded reads with
offline drives, bitrot corruption recovery, quorum failures, versioned
deletes, and corrupt-then-heal cycles -- all on the in-process 16-drive
harness.
"""

import os

import pytest

from minio_tpu.object.types import DeleteObjectOptions, GetObjectOptions, PutObjectOptions
from minio_tpu.utils import errors
from tests.harness import ErasureHarness

BUCKET = "testbucket"


@pytest.fixture
def hz(tmp_path):
    h = ErasureHarness(tmp_path, n_disks=16)
    h.layer.make_bucket(BUCKET)
    return h


def _data(n: int, seed: int = 0) -> bytes:
    import numpy as np

    return np.random.default_rng(seed).integers(0, 256, n).astype("u1").tobytes()


class TestPutGet:
    @pytest.mark.parametrize(
        "size",
        [0, 1, 100, 128 * 1024 - 1, 128 * 1024, 1 << 20, (1 << 20) + 1, 3 * (1 << 20) + 12345],
    )
    def test_roundtrip(self, hz, size):
        data = _data(size)
        oi = hz.layer.put_object(BUCKET, f"obj-{size}", data)
        assert oi.size == size
        got_oi, got = hz.layer.get_object(BUCKET, f"obj-{size}")
        assert got == data
        assert got_oi.size == size
        import hashlib

        from minio_tpu.object.erasure import fast_etag
        from minio_tpu.storage.xlmeta import SMALL_FILE_THRESHOLD

        if size < SMALL_FILE_THRESHOLD:
            # Inline objects keep the content md5.
            assert got_oi.etag == hashlib.md5(data).hexdigest()
        else:
            # Streaming objects use the digest-stream etag (computed here
            # independently, per block, to pin grouping-independence).
            assert got_oi.etag == fast_etag(data, hz.layer.drive_count - hz.layer.parity, hz.layer.parity)

    def test_range_read(self, hz):
        data = _data(2 * (1 << 20) + 500)
        hz.layer.put_object(BUCKET, "obj", data)
        for off, ln in [(0, 100), (1 << 20, 100), ((1 << 20) - 50, 100), (2 * (1 << 20), 500), (0, -1)]:
            _, got = hz.layer.get_object(BUCKET, "obj", offset=off, length=ln)
            want = data[off:] if ln < 0 else data[off : off + ln]
            assert got == want, (off, ln)

    def test_missing_object(self, hz):
        with pytest.raises(errors.ObjectNotFound):
            hz.layer.get_object(BUCKET, "nope")
        with pytest.raises(errors.BucketNotFound):
            hz.layer.get_object("nobucket", "nope")

    def test_overwrite(self, hz):
        hz.layer.put_object(BUCKET, "obj", b"first")
        hz.layer.put_object(BUCKET, "obj", b"second")
        _, got = hz.layer.get_object(BUCKET, "obj")
        assert got == b"second"


class TestDegraded:
    def test_get_with_parity_disks_offline(self, hz):
        data = _data(2 * (1 << 20), seed=1)
        hz.layer.put_object(BUCKET, "obj", data)
        hz.take_offline(0, 3, 7, 11)  # parity = 4 on 16 drives
        _, got = hz.layer.get_object(BUCKET, "obj")
        assert got == data

    def test_get_with_too_many_offline(self, hz):
        data = _data(1 << 20, seed=2)
        hz.layer.put_object(BUCKET, "obj", data)
        hz.take_offline(0, 1, 2, 3, 4)  # 5 > parity 4
        with pytest.raises(errors.InsufficientReadQuorum):
            hz.layer.get_object(BUCKET, "obj")

    def test_put_with_offline_within_quorum(self, hz):
        hz.take_offline(0, 1, 2, 3)
        data = _data(1 << 20, seed=3)
        hz.layer.put_object(BUCKET, "obj", data)
        _, got = hz.layer.get_object(BUCKET, "obj")
        assert got == data

    def test_put_quorum_failure(self, hz):
        hz.take_offline(0, 1, 2, 3, 4)  # only 11 < write quorum 12
        with pytest.raises(errors.ErasureWriteQuorum):
            hz.layer.put_object(BUCKET, "obj", b"x" * 1000)

    def test_small_object_degraded(self, hz):
        data = _data(1000, seed=4)
        hz.layer.put_object(BUCKET, "small", data)
        hz.take_offline(1, 2, 5, 9)
        _, got = hz.layer.get_object(BUCKET, "small")
        assert got == data


class TestCorruption:
    def test_bitrot_corruption_recovered(self, hz):
        data = _data(1 << 20, seed=5)
        hz.layer.put_object(BUCKET, "obj", data)
        corrupted = 0
        for i in range(16):
            if hz.corrupt_shard(i, BUCKET, "obj", at=40) and (corrupted := corrupted + 1) >= 3:
                break
        assert corrupted == 3
        _, got = hz.layer.get_object(BUCKET, "obj")
        assert got == data

    def test_shard_files_deleted_recovered(self, hz):
        data = _data((1 << 20) + 777, seed=6)
        hz.layer.put_object(BUCKET, "obj", data)
        deleted = 0
        for i in range(16):
            if hz.delete_shard(i, BUCKET, "obj") and (deleted := deleted + 1) >= 4:
                break
        assert deleted == 4
        _, got = hz.layer.get_object(BUCKET, "obj")
        assert got == data


class TestDelete:
    def test_simple_delete(self, hz):
        hz.layer.put_object(BUCKET, "obj", b"data" * 100)
        hz.layer.delete_object(BUCKET, "obj")
        with pytest.raises(errors.ObjectNotFound):
            hz.layer.get_object(BUCKET, "obj")

    def test_versioned_delete_marker(self, hz):
        opts = PutObjectOptions(versioned=True)
        oi1 = hz.layer.put_object(BUCKET, "obj", b"v1-data", opts)
        assert oi1.version_id
        res = hz.layer.delete_object(BUCKET, "obj", DeleteObjectOptions(versioned=True))
        assert res.delete_marker
        with pytest.raises(errors.ObjectNotFound):
            hz.layer.get_object(BUCKET, "obj")
        # The original version is still readable by id.
        _, got = hz.layer.get_object(BUCKET, "obj", GetObjectOptions(version_id=oi1.version_id))
        assert got == b"v1-data"
        # Deleting the marker restores the object.
        hz.layer.delete_object(BUCKET, "obj", DeleteObjectOptions(version_id=res.version_id))
        _, got = hz.layer.get_object(BUCKET, "obj")
        assert got == b"v1-data"

    def test_delete_specific_version(self, hz):
        opts = PutObjectOptions(versioned=True)
        oi1 = hz.layer.put_object(BUCKET, "obj", b"one", opts)
        oi2 = hz.layer.put_object(BUCKET, "obj", b"two", opts)
        hz.layer.delete_object(BUCKET, "obj", DeleteObjectOptions(version_id=oi2.version_id))
        _, got = hz.layer.get_object(BUCKET, "obj")
        assert got == b"one"


class TestBuckets:
    def test_bucket_lifecycle(self, hz):
        hz.layer.make_bucket("b2")
        assert {b.name for b in hz.layer.list_buckets()} >= {BUCKET, "b2"}
        with pytest.raises(errors.BucketExists):
            hz.layer.make_bucket("b2")
        hz.layer.delete_bucket("b2")
        with pytest.raises(errors.BucketNotFound):
            hz.layer.get_bucket_info("b2")
        with pytest.raises(errors.BucketNotFound):
            hz.layer.delete_bucket("b2")

    def test_delete_nonempty_bucket(self, hz):
        hz.layer.put_object(BUCKET, "obj", b"x")
        with pytest.raises(errors.BucketNotEmpty):
            hz.layer.delete_bucket(BUCKET)
        hz.layer.delete_bucket(BUCKET, force=True)


class TestHeal:
    def test_heal_deleted_shards(self, hz):
        data = _data((1 << 20) + 99, seed=7)
        hz.layer.put_object(BUCKET, "obj", data)
        for i in (0, 5, 10):
            hz.delete_object_dir(i, BUCKET, "obj")
        res = hz.layer.heal_object(BUCKET, "obj")
        assert res.disks_healed == 3
        # The healed drives now carry valid shards: knock out 4 OTHER drives
        # (= parity budget) and the read must still succeed, which forces the
        # healed copies into use.
        hz.take_offline(1, 2, 3, 4)
        _, got = hz.layer.get_object(BUCKET, "obj")
        assert got == data

    def test_heal_corrupt_shard(self, hz):
        data = _data(1 << 20, seed=8)
        hz.layer.put_object(BUCKET, "obj", data)
        for i in range(16):
            if hz.corrupt_shard(i, BUCKET, "obj"):
                break  # corrupt exactly one drive's shard
        res = hz.layer.heal_object(BUCKET, "obj")
        assert res.disks_healed >= 1
        # Now corruption is gone: a fresh heal finds nothing to do.
        res2 = hz.layer.heal_object(BUCKET, "obj")
        assert res2.disks_healed == 0

    def test_heal_small_inline_object(self, hz):
        data = _data(500, seed=9)
        hz.layer.put_object(BUCKET, "small", data)
        for i in (2, 4):
            os.remove(hz.xl_meta_file(i, BUCKET, "small"))
        res = hz.layer.heal_object(BUCKET, "small")
        assert res.disks_healed == 2
        _, got = hz.layer.get_object(BUCKET, "small")
        assert got == data

    def test_unhealable_raises(self, hz):
        data = _data(1 << 20, seed=10)
        hz.layer.put_object(BUCKET, "obj", data)
        for i in range(13):  # 13 > parity(4): < K survivors
            hz.delete_object_dir(i, BUCKET, "obj")
        with pytest.raises((errors.InsufficientReadQuorum, errors.ErasureReadQuorum)):
            hz.layer.heal_object(BUCKET, "obj")


class TestWholeFileBitrot:
    """Legacy whole-file bitrot layout (cmd/bitrot-whole.go): raw shard
    files + one checksum per part per row in metadata; VERDICT r3 #10."""

    @pytest.mark.parametrize("algo", ["sha256", "blake2b", "highwayhash256"])
    def test_roundtrip_and_range(self, hz, algo):
        data = _data((1 << 20) + 4321, seed=30)
        hz.layer.put_object(BUCKET, "legacy", data, PutObjectOptions(bitrot_algorithm=algo))
        _, got = hz.layer.get_object(BUCKET, "legacy")
        assert got == data
        _, part = hz.layer.get_object(BUCKET, "legacy", offset=999_000, length=50_000)
        assert part == data[999_000 : 999_000 + 50_000]

    def test_corrupt_then_read_uses_spares(self, hz):
        data = _data(2 * (1 << 20) + 7, seed=31)
        hz.layer.put_object(
            BUCKET, "legacy", data, PutObjectOptions(bitrot_algorithm="sha256")
        )
        corrupted = 0
        for i in range(16):
            if hz.corrupt_shard(i, BUCKET, "legacy", at=50) and (
                corrupted := corrupted + 1
            ) >= 2:
                break
        assert corrupted == 2
        _, got = hz.layer.get_object(BUCKET, "legacy")
        assert got == data

    def test_corrupt_then_heal(self, hz):
        data = _data((1 << 20) + 99, seed=32)
        hz.layer.put_object(
            BUCKET, "legacy", data, PutObjectOptions(bitrot_algorithm="sha256")
        )
        assert hz.corrupt_shard(3, BUCKET, "legacy", at=10)
        res = hz.layer.heal_object(BUCKET, "legacy")
        assert res.disks_healed == 1
        # Healed copy carries a fresh whole-file checksum; clean re-heal.
        res2 = hz.layer.heal_object(BUCKET, "legacy", dry_run=True)
        assert res2.disks_healed == 0
        _, got = hz.layer.get_object(BUCKET, "legacy")
        assert got == data

    def test_too_many_corrupt_rows_fails(self, hz):
        data = _data((1 << 20) + 5, seed=33)
        hz.layer.put_object(
            BUCKET, "legacy", data, PutObjectOptions(bitrot_algorithm="sha256")
        )
        corrupted = 0
        for i in range(16):
            if hz.corrupt_shard(i, BUCKET, "legacy", at=20) and (
                corrupted := corrupted + 1
            ) >= 5:
                break
        assert corrupted == 5  # parity is 4: unhealable/unreadable
        with pytest.raises(errors.InsufficientReadQuorum):
            hz.layer.get_object(BUCKET, "legacy")


class TestListBucketsQuorum:
    def test_stray_bucket_on_one_drive_not_listed(self, hz):
        hz.layer.make_bucket("realb")
        os.makedirs(os.path.join(hz.dirs[0], "straggler"), exist_ok=True)
        names = [b.name for b in hz.layer.list_buckets()]
        assert "realb" in names and BUCKET in names
        assert "straggler" not in names

    def test_bucket_survives_minority_drive_loss(self, hz):
        hz.layer.make_bucket("quorumb")
        hz.take_offline(0, 1, 2)
        names = [b.name for b in hz.layer.list_buckets()]
        assert "quorumb" in names
