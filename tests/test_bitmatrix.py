"""Property tests for the XOR-bitmatrix codec stack.

Three independent layers cross-checked against each other and the GF
oracle (ops/rs_ref, ops/gf):

  * the bitmatrix lift + XOR-schedule compiler (ops/bitmatrix) -- pure
    numpy, no JAX;
  * the Pallas kernel (ops/rs_pallas) -- interpret mode on CPU-only
    hosts, so these tests pin kernel *semantics* everywhere;
  * the fused encode+hash step (ops/fused) vs the standalone hash.

Randomized over geometry (k, m) and ragged shard lengths with fixed
seeds: the schedules are data-dependent (the generator matrix changes
with k, m), so sweeping geometry is what actually exercises the compiler.
"""

from __future__ import annotations

import numpy as np
import pytest

from minio_tpu.ops import bitmatrix, rs_matrix, rs_ref
from minio_tpu.ops.rs_pallas import RSPallasCodec, apply


GEOMETRIES = [(2, 1), (2, 2), (3, 2), (4, 2), (5, 3), (8, 4), (12, 4), (16, 4)]


# -- schedule compiler vs GF oracle -------------------------------------------


@pytest.mark.parametrize("k,m", GEOMETRIES)
def test_encode_schedule_matches_gf_oracle(k, m):
    rng = np.random.default_rng(k * 100 + m)
    for s in (1, 7, 64, 257):
        shards = rng.integers(0, 256, (k, s), dtype=np.uint8)
        got = bitmatrix.eval_bytes(bitmatrix.encode_schedule(k, m), shards)
        want = rs_ref.encode(shards, m)[k:]
        np.testing.assert_array_equal(got, want)


@pytest.mark.parametrize("seed", range(8))
def test_random_coeff_schedule_matches_apply_coeffs(seed):
    """Arbitrary [R, K] coefficient matrices (the reconstruct path feeds
    these), not just Cauchy parity rows."""
    rng = np.random.default_rng(seed)
    r, k, s = int(rng.integers(1, 6)), int(rng.integers(1, 9)), int(rng.integers(1, 400))
    coeffs = rng.integers(0, 256, (r, k), dtype=np.uint8)
    shards = rng.integers(0, 256, (k, s), dtype=np.uint8)
    sched = bitmatrix.schedule_for_coeffs(coeffs)
    np.testing.assert_array_equal(
        bitmatrix.eval_bytes(sched, shards), rs_ref.apply_coeffs(coeffs, shards)
    )


@pytest.mark.parametrize("k,m", GEOMETRIES)
def test_cse_invariants(k, m):
    sched = bitmatrix.encode_schedule(k, m)
    assert sched.scheduled_xors <= sched.naive_xors
    assert sched.cse_saved == sched.naive_xors - sched.scheduled_xors
    assert sched.n_inputs == k * 8 and sched.n_rows == m * 8
    # Every op references an already-defined node (straight-line program).
    for i, (a, b) in enumerate(sched.ops):
        assert 0 <= a < sched.n_inputs + i
        assert 0 <= b < sched.n_inputs + i
    for r in sched.roots:
        assert -1 <= r < sched.n_inputs + len(sched.ops)
    # Parity rows of a Cauchy matrix are never all-zero.
    assert all(r >= 0 for r in sched.roots)
    assert sched.depth >= 1
    stats = sched.stats()
    assert stats["scheduled_xors"] == len(sched.ops)


def test_production_geometry_cse_actually_saves():
    # 12+4 is the serving geometry; Paar sharing must beat naive by a
    # meaningful margin (measured 58% -- gate far below that).
    sched = bitmatrix.encode_schedule(12, 4)
    assert sched.cse_saved > sched.naive_xors * 0.3
    assert sched.depth <= 24  # log-ish depth from the balanced phase 2


def test_schedule_cache_returns_same_object():
    a = bitmatrix.encode_schedule(4, 2)
    b = bitmatrix.encode_schedule(4, 2)
    assert a is b  # lru_cache identity => free jit static-arg reuse


def test_zero_rows_allowed():
    sched = bitmatrix.schedule_for_coeffs(np.zeros((1, 2), dtype=np.uint8))
    shards = np.arange(16, dtype=np.uint8).reshape(2, 8)
    np.testing.assert_array_equal(
        bitmatrix.eval_bytes(sched, shards), np.zeros((1, 8), dtype=np.uint8)
    )


# -- Pallas kernel (interpret mode on CPU) vs both oracles ---------------------


@pytest.mark.parametrize("k,m", GEOMETRIES)
def test_pallas_encode_matches_oracles(k, m):
    rng = np.random.default_rng(k * 7 + m)
    for s in (1, 100, 4096, 5000):  # ragged tails included
        shards = rng.integers(0, 256, (2, k, s), dtype=np.uint8)
        got = np.asarray(RSPallasCodec(k, m).encode(shards))
        for b in range(shards.shape[0]):
            want = rs_ref.encode(shards[b], m)[k:]
            np.testing.assert_array_equal(got[b], want)
            np.testing.assert_array_equal(
                got[b], bitmatrix.eval_bytes(bitmatrix.encode_schedule(k, m), shards[b])
            )


@pytest.mark.parametrize("seed", range(4))
def test_pallas_apply_random_bitmatrix(seed):
    rng = np.random.default_rng(100 + seed)
    r, k, s = int(rng.integers(1, 5)), int(rng.integers(1, 7)), int(rng.integers(1, 600))
    coeffs = rng.integers(0, 256, (r, k), dtype=np.uint8)
    shards = rng.integers(0, 256, (1, k, s), dtype=np.uint8)
    w_bits = rs_matrix.bit_expand(coeffs)
    got = np.asarray(apply(shards, w_bits))[0]
    np.testing.assert_array_equal(got, rs_ref.apply_coeffs(coeffs, shards[0]))


@pytest.mark.parametrize("k,m,missing", [(4, 2, (0,)), (12, 4, (0, 5, 13, 14)), (8, 4, (1, 2))])
def test_pallas_reconstruct_matches_oracle(k, m, missing):
    rng = np.random.default_rng(k + m)
    s = 333
    shards = rng.integers(0, 256, (k, s), dtype=np.uint8)
    full = rs_ref.encode(shards, m)
    present = tuple(i not in missing for i in range(k + m))
    survivors = np.stack([full[i] for i in range(k + m) if present[i]][:k])
    coeffs = rs_matrix.reconstruct_rows(k, m, present, tuple(missing))
    sched = bitmatrix.schedule_for_coeffs(coeffs)
    got = bitmatrix.eval_bytes(sched, survivors)
    for idx, w in enumerate(missing):
        np.testing.assert_array_equal(got[idx], full[w])


# -- fused encode+hash vs standalone hash --------------------------------------


def test_fused_digests_match_hash_batch():
    from minio_tpu.ops import fused as fused_ops
    from minio_tpu.ops import highwayhash_jax as hhj

    k, m, s = 4, 2, 2048
    rng = np.random.default_rng(5)
    data = rng.integers(0, 256, (3, k, s), dtype=np.uint8)
    shards, digests = fused_ops.fused_encode_hash(data, k, m, "pallas", "xla")
    shards, digests = np.asarray(shards), np.asarray(digests)
    assert shards.shape == (3, k + m, s) and digests.shape == (3, k + m, 32)
    for b in range(3):
        np.testing.assert_array_equal(shards[b], rs_ref.encode(data[b], m))
        want = np.asarray(hhj.hash256_batch(shards[b]))
        np.testing.assert_array_equal(digests[b], want)


def test_fused_xla_and_pallas_rs_agree():
    from minio_tpu.ops import fused as fused_ops

    k, m, s = 6, 3, 1024
    rng = np.random.default_rng(6)
    data = rng.integers(0, 256, (2, k, s), dtype=np.uint8)
    sp, dp = fused_ops.fused_encode_hash(data, k, m, "pallas", "xla")
    sx, dx = fused_ops.fused_encode_hash(data, k, m, "xla", "xla")
    np.testing.assert_array_equal(np.asarray(sp), np.asarray(sx))
    np.testing.assert_array_equal(np.asarray(dp), np.asarray(dx))
