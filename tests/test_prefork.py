"""Pre-fork front end (api/prefork.py): gating probes and master lifecycle.

The fork tests use trivial children (bind a shared SO_REUSEPORT port, touch
a file, exit) -- the full-server path is exercised by the same serve() body
the single-process tests already cover; what needs pinning here is the
fork/wait/respawn plumbing and the opt-in gates."""

from __future__ import annotations

import os
import signal
import socket

import pytest

from minio_tpu.api import prefork

_HAS_FORK = hasattr(os, "fork") and hasattr(socket, "SO_REUSEPORT")


class TestPlanWorkers:
    def test_unset_serves_single_process(self):
        n, why = prefork.plan_workers({})
        assert n == 1 and "unset" in why

    def test_garbage_value_serves_single_process(self):
        n, why = prefork.plan_workers({"MTPU_WORKERS": "lots"})
        assert n == 1 and "not an integer" in why

    def test_one_or_less_serves_single_process(self):
        assert prefork.plan_workers({"MTPU_WORKERS": "1"})[0] == 1
        assert prefork.plan_workers({"MTPU_WORKERS": "0"})[0] == 1

    def test_worker_child_never_reforks(self):
        n, why = prefork.plan_workers(
            {"MTPU_WORKERS": "4", prefork.WORKER_ENV: "1"}
        )
        assert n == 1 and "child" in why

    def test_opt_in_respects_platform_gates(self):
        n, why = prefork.plan_workers({"MTPU_WORKERS": "4"})
        if not _HAS_FORK:
            assert n == 1
        elif not prefork.gil_enabled():
            assert n == 1 and "free-threaded" in why
        else:
            assert n == 4 and "SO_REUSEPORT" in why


@pytest.fixture
def restored_signals():
    """run_master installs its own SIGTERM/SIGINT handlers; put the test
    process's handlers back afterwards."""
    old_term = signal.getsignal(signal.SIGTERM)
    old_int = signal.getsignal(signal.SIGINT)
    yield
    signal.signal(signal.SIGTERM, old_term)
    signal.signal(signal.SIGINT, old_int)


@pytest.mark.skipif(not _HAS_FORK, reason="needs fork() + SO_REUSEPORT")
class TestRunMaster:
    def test_workers_share_one_port(self, tmp_path, restored_signals, monkeypatch):
        monkeypatch.setenv("MTPU_WORKER_RESPAWNS", "0")
        probe = socket.socket()
        probe.bind(("127.0.0.1", 0))
        port = probe.getsockname()[1]
        probe.close()

        def child(wid: int) -> int:
            s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
            s.bind(("127.0.0.1", port))  # both workers: same port, no EADDRINUSE
            s.listen(1)
            (tmp_path / f"bound{wid}").write_text(str(port))
            s.close()
            return 0

        rc = prefork.run_master(2, child, log=lambda _m: None)
        assert rc == 0
        assert sorted(p.name for p in tmp_path.glob("bound*")) == ["bound0", "bound1"]

    def test_crashed_worker_respawns_up_to_budget(
        self, tmp_path, restored_signals, monkeypatch
    ):
        monkeypatch.setenv("MTPU_WORKER_RESPAWNS", "1")

        def child(_wid: int) -> int:
            runs = len(list(tmp_path.glob("run*")))
            (tmp_path / f"run{runs}").write_text("")
            return 3

        rc = prefork.run_master(1, child, log=lambda _m: None)
        assert rc == 3
        # Initial spawn + exactly one respawn, then the budget is spent.
        assert len(list(tmp_path.glob("run*"))) == 2
