"""Test configuration: force an 8-device virtual CPU mesh before jax is used.

Benches run on the real TPU chip; tests exercise the same code on a virtual
multi-device CPU platform so sharding/collective paths are covered without
hardware (mirrors the reference's in-process multi-disk harness philosophy,
/root/reference/cmd/test-utils_test.go:199).

The environment may pre-register a hardware TPU backend (tunnel plugin) via
sitecustomize before this file runs, and its client init both bypasses
JAX_PLATFORMS and can block on the tunnel. Tests must never touch it, so we
both repoint jax's platform config at cpu and drop the plugin's backend
factory before any backend is initialized.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

# Durability barriers off by default in tests: /tmp is a real filesystem
# here, and ~900 tests x fsync-per-commit would dominate the suite's wall
# clock without covering anything the crash tests (tests/test_crash.py,
# tools/crashcheck.py) don't already pin under MTPU_FSYNC=commit. Tests
# that exercise the barriers set the mode explicitly.
os.environ.setdefault("MTPU_FSYNC", "never")

# Recovery re-probe daemons off by default: tests that install a host codec
# in auto mode must not leave a timer thread re-probing (and re-installing a
# device codec) behind later tests' backs. Recovery tests set this per-test.
os.environ.setdefault("MTPU_PROBE_RECOVERY_S", "0")

# Flight-recorder trigger thread off by default: hundreds of tests build
# throwaway nodes, and an armed SLO watcher would dump diagnostic bundles to
# /tmp whenever a test intentionally provokes errors. The span ring and the
# manual/fanout capture paths stay live; flight tests arm the thread
# explicitly (tests/test_flight.py).
os.environ.setdefault("MTPU_FLIGHT", "0")

import jax

jax.config.update("jax_platforms", "cpu")
# Persistent compile cache: the suite re-jits the same kernels every run.
jax.config.update("jax_compilation_cache_dir", "/tmp/jax_test_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
try:
    from jax._src import xla_bridge as _xb

    _xb._backend_factories.pop("axon", None)
except Exception:  # pragma: no cover - jax internals moved; cpu config still set
    pass


# -- child-process hygiene (round-2 verdict: one pytest run orphaned 11 wedged
# probe children). A session fixture snapshots our child PIDs at start and
# asserts the table is clean at exit; probe children are killed as process
# groups by runtime.probe_device, so anything left is a real leak.

import subprocess  # noqa: E402
import sys  # noqa: E402

import pytest  # noqa: E402

# -- race-stress mode (the `buildscripts/race.sh` analogue, tools/race_gate.py):
# MINIO_TPU_RACE=1 shrinks the interpreter's thread switch interval ~1000x so
# the scheduler interleaves threads at nearly every bytecode boundary. Latent
# check-then-act races in the quorum writers, batching queues, lock refresh
# loops, and pubsub hubs become orders of magnitude more likely to fire while
# the assertions stay exactly the same.
if os.environ.get("MINIO_TPU_RACE") == "1":
    sys.setswitchinterval(2e-6)


def pytest_configure(config):
    # Tier-1 runs `-m "not slow"`; the full chaos matrix (tools/chaos_check.py)
    # includes slow scenarios.
    config.addinivalue_line(
        "markers", "slow: long-running scenario tests excluded from tier-1"
    )
    # tools/race_gate.py discovers its file list from this marker.
    config.addinivalue_line(
        "markers", "race: concurrency-sensitive tests rerun by tools/race_gate.py"
    )


def _child_pids() -> set[int]:
    try:
        out = subprocess.run(
            ["ps", "-o", "pid=,ppid=,args=", "-e"],
            capture_output=True,
            text=True,
            timeout=10,
        ).stdout
    except Exception:  # pragma: no cover - ps unavailable
        return set()
    me = os.getpid()
    procs = []
    for line in out.splitlines():
        parts = line.split(None, 2)
        if len(parts) >= 2:
            procs.append((int(parts[0]), int(parts[1]), parts[2] if len(parts) > 2 else ""))
    # Transitive children of this process, excluding the ps we just ran.
    children: set[int] = set()
    added = True
    roots = {me}
    while added:
        added = False
        for pid, ppid, _ in procs:
            if ppid in roots | children and pid not in children and pid != me:
                children.add(pid)
                added = True
    return {
        pid
        for pid in children
        for p, pp, args in procs
        if p == pid and "ps -o" not in args and "<defunct>" not in args
    }


@pytest.fixture(scope="session", autouse=True)
def _no_leaked_threads():
    """Stop every Node's background workers at session end. Tests build
    in-process clusters ad hoc and rarely own their teardown; without this
    the daemon threads (replication workers, MRF heal, disk-heal monitor)
    pile up across the session and mtpusan's leaked-thread detector --
    which runs at interpreter exit, after this hook -- reports every one."""
    yield
    try:
        from minio_tpu.dist.node import Node

        Node.close_all()
    except Exception:  # pragma: no cover - teardown must not mask failures
        pass


@pytest.fixture(scope="session", autouse=True)
def _no_leaked_children():
    yield
    # Reap the probe process groups eagerly (atexit would fire later anyway;
    # the assert below must not race it).
    try:
        from minio_tpu import runtime as _rt

        _rt._reap_live_probes()
    except Exception:
        pass
    import time as _time

    for _ in range(20):  # allow daemon-thread subprocesses a moment to die
        leftover = _child_pids()
        if not leftover:
            break
        _time.sleep(0.25)
    assert not leftover, f"test suite leaked child processes: {sorted(leftover)}"
