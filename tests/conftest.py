"""Test configuration: force an 8-device virtual CPU mesh before jax imports.

Benches run on the real TPU chip; tests exercise the same code on a virtual
multi-device CPU platform so sharding/collective paths are covered without
hardware (mirrors the reference's in-process multi-disk harness philosophy,
/root/reference/cmd/test-utils_test.go:199).
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "1")
