"""Test configuration: force an 8-device virtual CPU mesh before jax is used.

Benches run on the real TPU chip; tests exercise the same code on a virtual
multi-device CPU platform so sharding/collective paths are covered without
hardware (mirrors the reference's in-process multi-disk harness philosophy,
/root/reference/cmd/test-utils_test.go:199).

The environment may pre-register a hardware TPU backend (tunnel plugin) via
sitecustomize before this file runs, and its client init both bypasses
JAX_PLATFORMS and can block on the tunnel. Tests must never touch it, so we
both repoint jax's platform config at cpu and drop the plugin's backend
factory before any backend is initialized.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax

jax.config.update("jax_platforms", "cpu")
# Persistent compile cache: the suite re-jits the same kernels every run.
jax.config.update("jax_compilation_cache_dir", "/tmp/jax_test_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
try:
    from jax._src import xla_bridge as _xb

    _xb._backend_factories.pop("axon", None)
except Exception:  # pragma: no cover - jax internals moved; cpu config still set
    pass
