"""Zip extension tests: list/get inside stored zip archives
(reference cmd/s3-zip-handlers.go — x-minio-extract)."""

import io
import xml.etree.ElementTree as ET
import zipfile

import pytest

from tests.test_s3_api import stack  # noqa: F401 (fixture reuse)

BUCKET = "zipbkt"
NS = "{http://s3.amazonaws.com/doc/2006-03-01/}"


def _make_zip() -> bytes:
    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
        zf.writestr("readme.txt", b"hello from zip")
        zf.writestr("docs/a.md", b"# doc a")
        zf.writestr("docs/b.md", b"# doc b")
        zf.writestr("docs/sub/deep.bin", bytes(range(200)))
        zf.writestr("empty-dir/", b"")
    return buf.getvalue()


@pytest.fixture()
def zipped(stack):  # noqa: F811
    client = stack["client"]
    if client.request("HEAD", f"/{BUCKET}").status_code != 200:
        client.make_bucket(BUCKET)
    client.put_object(BUCKET, "archive.zip", _make_zip())
    return client


def test_get_inner_file(zipped):
    r = zipped.request(
        "GET", f"/{BUCKET}/archive.zip/readme.txt", headers={"x-minio-extract": "true"}
    )
    assert r.status_code == 200, r.text
    assert r.content == b"hello from zip"
    assert r.headers["Content-Type"].startswith("text/plain")

    r = zipped.request(
        "GET", f"/{BUCKET}/archive.zip/docs/sub/deep.bin", headers={"x-minio-extract": "true"}
    )
    assert r.status_code == 200 and r.content == bytes(range(200))


def test_head_inner_file(zipped):
    r = zipped.request(
        "HEAD", f"/{BUCKET}/archive.zip/docs/a.md", headers={"x-minio-extract": "true"}
    )
    assert r.status_code == 200
    assert r.headers["Content-Length"] == "7"


def test_missing_inner_file_404(zipped):
    r = zipped.request(
        "GET", f"/{BUCKET}/archive.zip/nope.txt", headers={"x-minio-extract": "true"}
    )
    assert r.status_code == 404


def test_without_header_is_plain_key_lookup(zipped):
    # No x-minio-extract: the full path is treated as a literal key.
    r = zipped.request("GET", f"/{BUCKET}/archive.zip/readme.txt")
    assert r.status_code == 404


def test_range_read_inside_zip(zipped):
    r = zipped.request(
        "GET",
        f"/{BUCKET}/archive.zip/docs/sub/deep.bin",
        headers={"x-minio-extract": "true", "Range": "bytes=10-19"},
    )
    assert r.status_code == 206
    assert r.content == bytes(range(10, 20))


def test_list_zip_contents(zipped):
    r = zipped.request(
        "GET",
        f"/{BUCKET}",
        query=[("list-type", "2"), ("prefix", "archive.zip/")],
        headers={"x-minio-extract": "true"},
    )
    assert r.status_code == 200, r.text
    root = ET.fromstring(r.text)
    keys = [c.find(f"{NS}Key").text for c in root.findall(f"{NS}Contents")]
    assert f"archive.zip/readme.txt" in keys
    assert f"archive.zip/docs/a.md" in keys
    assert all(not k.endswith("/") for k in keys)  # dirs excluded


def test_list_zip_with_delimiter(zipped):
    r = zipped.request(
        "GET",
        f"/{BUCKET}",
        query=[("list-type", "2"), ("prefix", "archive.zip/"), ("delimiter", "/")],
        headers={"x-minio-extract": "true"},
    )
    root = ET.fromstring(r.text)
    keys = [c.find(f"{NS}Key").text for c in root.findall(f"{NS}Contents")]
    cps = [p.find(f"{NS}Prefix").text for p in root.findall(f"{NS}CommonPrefixes")]
    assert keys == ["archive.zip/readme.txt"]
    assert "archive.zip/docs/" in cps


def test_list_zip_inner_prefix(zipped):
    r = zipped.request(
        "GET",
        f"/{BUCKET}",
        query=[("list-type", "2"), ("prefix", "archive.zip/docs/")],
        headers={"x-minio-extract": "true"},
    )
    root = ET.fromstring(r.text)
    keys = [c.find(f"{NS}Key").text for c in root.findall(f"{NS}Contents")]
    assert keys == [
        "archive.zip/docs/a.md",
        "archive.zip/docs/b.md",
        "archive.zip/docs/sub/deep.bin",
    ]


def test_not_a_zip_errors(zipped):
    zipped.put_object(BUCKET, "fake.zip", b"this is not a zip archive")
    r = zipped.request(
        "GET", f"/{BUCKET}/fake.zip/anything", headers={"x-minio-extract": "true"}
    )
    assert r.status_code == 400


def test_zip_list_pagination(zipped):
    # Page through with max-keys=2; every entry appears exactly once.
    seen, token = [], ""
    for _ in range(10):
        q = [("list-type", "2"), ("prefix", "archive.zip/"), ("max-keys", "2")]
        if token:
            q.append(("continuation-token", token))
        r = zipped.request("GET", f"/{BUCKET}", query=q, headers={"x-minio-extract": "true"})
        root = ET.fromstring(r.text)
        seen += [c.find(f"{NS}Key").text for c in root.findall(f"{NS}Contents")]
        t = root.find(f"{NS}NextContinuationToken")
        if t is None:
            break
        token = t.text
    assert sorted(seen) == [
        "archive.zip/docs/a.md",
        "archive.zip/docs/b.md",
        "archive.zip/docs/sub/deep.bin",
        "archive.zip/readme.txt",
    ]
    assert len(seen) == len(set(seen))  # no duplicates across pages


def test_zip_list_v1_marker(zipped):
    r = zipped.request(
        "GET",
        f"/{BUCKET}",
        query=[("prefix", "archive.zip/"), ("marker", "archive.zip/docs/b.md")],
        headers={"x-minio-extract": "true"},
    )
    root = ET.fromstring(r.text)
    keys = [c.find(f"{NS}Key").text for c in root.findall(f"{NS}Contents")]
    assert keys == ["archive.zip/docs/sub/deep.bin", "archive.zip/readme.txt"]
    assert root.find(f"{NS}Marker") is not None  # V1 response shape


def test_range_on_empty_inner_file(zipped):
    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w") as zf:
        zf.writestr("void.txt", b"")
    zipped.put_object(BUCKET, "empty.zip", buf.getvalue())
    r = zipped.request(
        "GET",
        f"/{BUCKET}/empty.zip/void.txt",
        headers={"x-minio-extract": "true", "Range": "bytes=0-9"},
    )
    assert r.status_code == 416
    # And a plain GET of the empty entry succeeds.
    r = zipped.request("GET", f"/{BUCKET}/empty.zip/void.txt", headers={"x-minio-extract": "true"})
    assert r.status_code == 200 and r.content == b""
