"""Bucket replication tests: two single-node clusters, source -> target.

The analogue of the reference's replication integration tests
(.github/workflows/replication.yaml + bucket-replication tests): a source
cluster with a replication rule pointing at a second in-process cluster,
exercising async replication, status transitions, delete-marker replication,
version preservation, and existing-object resync.
"""

import json
from types import SimpleNamespace

import pytest

from minio_tpu.api.server import ThreadedServer
from minio_tpu.control import kms as _kms_mod
from minio_tpu.dist.node import Node
from tests.s3client import S3TestClient
from tests.test_dist import _free_port

# Stressed under adversarial thread scheduling by tools/race_gate.py.
pytestmark = pytest.mark.race


ROOT = "replroot"
SECRET = "repl-secret-key"
ADMIN = "/mtpu/admin/v1"


def _boot(tmp, name):
    endpoints = [str(tmp / name / f"d{i}") for i in range(4)]
    node = Node(endpoints, root_user=ROOT, root_password=SECRET)
    port = _free_port()
    ts = ThreadedServer(SimpleNamespace(app=node.make_app()), port=port)
    ts.start()
    node.build()
    url = f"http://127.0.0.1:{port}"
    return {"node": node, "ts": ts, "url": url, "client": S3TestClient(url, ROOT, SECRET)}


@pytest.fixture(scope="module")
def pair(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("repl")
    src = _boot(tmp, "src")
    dst = _boot(tmp, "dst")
    yield src, dst
    src["ts"].stop()
    dst["ts"].stop()


def _enable_versioning(client, bucket):
    xml = (
        '<VersioningConfiguration xmlns="http://s3.amazonaws.com/doc/2006-03-01/">'
        "<Status>Enabled</Status></VersioningConfiguration>"
    )
    r = client.request("PUT", f"/{bucket}", query=[("versioning", "")], body=xml.encode())
    assert r.status_code == 200, r.text


def _configure(src, dst, bucket, extra_rule_xml=""):
    """Register dst as a remote target and install a replication rule."""
    r = src["client"].request(
        "POST",
        f"{ADMIN}/replication/target",
        body=json.dumps(
            {
                "bucket": bucket,
                "endpoint": dst["url"],
                "targetBucket": bucket,
                "accessKey": ROOT,
                "secretKey": SECRET,
            }
        ).encode(),
    )
    assert r.status_code == 200, r.text
    arn = r.json()["arn"]
    xml = (
        '<ReplicationConfiguration xmlns="http://s3.amazonaws.com/doc/2006-03-01/">'
        "<Role></Role><Rule><ID>r1</ID><Status>Enabled</Status><Priority>1</Priority>"
        "<DeleteMarkerReplication><Status>Enabled</Status></DeleteMarkerReplication>"
        f"{extra_rule_xml}"
        "<Filter><Prefix></Prefix></Filter>"
        f"<Destination><Bucket>{arn}</Bucket></Destination></Rule>"
        "</ReplicationConfiguration>"
    )
    r = src["client"].request(
        "PUT", f"/{bucket}", query=[("replication", "")], body=xml.encode()
    )
    assert r.status_code == 200, r.text
    return arn


class TestReplication:
    def test_put_replicates(self, pair):
        src, dst = pair
        assert src["client"].make_bucket("rbkt").status_code == 200
        assert dst["client"].make_bucket("rbkt").status_code == 200
        _enable_versioning(src["client"], "rbkt")
        _enable_versioning(dst["client"], "rbkt")
        _configure(src, dst, "rbkt")

        r = src["client"].put_object(
            "rbkt",
            "hello.txt",
            b"replicate me",
            headers={"x-amz-meta-color": "green", "Content-Type": "text/plain"},
        )
        assert r.status_code == 200
        src_vid = r.headers["x-amz-version-id"]
        assert src["node"].replication.drain(15)

        # Target copy: same bytes, metadata, and version id; REPLICA status.
        r = dst["client"].request("GET", "/rbkt/hello.txt")
        assert r.status_code == 200
        assert r.content == b"replicate me"
        assert r.headers["x-amz-meta-color"] == "green"
        assert r.headers["x-amz-replication-status"] == "REPLICA"
        assert r.headers["x-amz-version-id"] == src_vid

        # Source shows COMPLETED after the async write-back.
        r = src["client"].request("HEAD", "/rbkt/hello.txt")
        assert r.headers["x-amz-replication-status"] == "COMPLETED"

    def test_delete_marker_replicates(self, pair):
        src, dst = pair
        src["client"].put_object("rbkt", "doomed.txt", b"bye")
        assert src["node"].replication.drain(15)
        assert dst["client"].request("HEAD", "/rbkt/doomed.txt").status_code == 200

        r = src["client"].request("DELETE", "/rbkt/doomed.txt")
        assert r.status_code == 204
        assert r.headers.get("x-amz-delete-marker") == "true"
        assert src["node"].replication.drain(15)
        assert dst["client"].request("HEAD", "/rbkt/doomed.txt").status_code == 404

    def test_status_endpoint(self, pair):
        src, _ = pair
        r = src["client"].request("GET", f"{ADMIN}/replication/status")
        assert r.status_code == 200
        stats = r.json()
        assert stats["completed"] >= 2
        assert stats["replicatedBytes"] > 0

    def test_resync_existing_objects(self, pair):
        src, dst = pair
        assert src["client"].make_bucket("oldbkt").status_code == 200
        assert dst["client"].make_bucket("oldbkt").status_code == 200
        _enable_versioning(src["client"], "oldbkt")
        _enable_versioning(dst["client"], "oldbkt")
        # Objects written BEFORE any replication config exists.
        for i in range(3):
            src["client"].put_object("oldbkt", f"pre-{i}", f"old {i}".encode())
        _configure(
            src,
            dst,
            "oldbkt",
            extra_rule_xml="<ExistingObjectReplication><Status>Enabled</Status>"
            "</ExistingObjectReplication>",
        )
        r = src["client"].request(
            "POST",
            f"{ADMIN}/replication/resync",
            body=json.dumps({"bucket": "oldbkt"}).encode(),
        )
        assert r.status_code == 200, r.text
        assert r.json()["queued"] == 3
        assert src["node"].replication.drain(15)
        for i in range(3):
            r = dst["client"].request("GET", f"/oldbkt/pre-{i}")
            assert r.status_code == 200
            assert r.content == f"old {i}".encode()

    def test_replica_not_re_replicated(self, pair):
        """A REPLICA object on the target must not loop back even if the
        target itself had a rule (loop prevention via replica status)."""
        src, dst = pair
        # Target object carries REPLICA status; on_put must skip it.
        r = dst["client"].request("HEAD", "/rbkt/hello.txt")
        assert r.headers["x-amz-replication-status"] == "REPLICA"

    def test_rule_prefix_filter(self, pair):
        src, dst = pair
        assert src["client"].make_bucket("pfx").status_code == 200
        assert dst["client"].make_bucket("pfx").status_code == 200
        r = src["client"].request(
            "POST",
            f"{ADMIN}/replication/target",
            body=json.dumps(
                {
                    "bucket": "pfx",
                    "endpoint": dst["url"],
                    "targetBucket": "pfx",
                    "accessKey": ROOT,
                    "secretKey": SECRET,
                }
            ).encode(),
        )
        arn = r.json()["arn"]
        xml = (
            '<ReplicationConfiguration xmlns="http://s3.amazonaws.com/doc/2006-03-01/">'
            "<Rule><ID>p</ID><Status>Enabled</Status><Priority>1</Priority>"
            "<Filter><Prefix>logs/</Prefix></Filter>"
            f"<Destination><Bucket>{arn}</Bucket></Destination></Rule>"
            "</ReplicationConfiguration>"
        )
        assert (
            src["client"]
            .request("PUT", "/pfx", query=[("replication", "")], body=xml.encode())
            .status_code
            == 200
        )
        src["client"].put_object("pfx", "logs/a", b"in scope")
        src["client"].put_object("pfx", "data/b", b"out of scope")
        assert src["node"].replication.drain(15)
        assert dst["client"].request("HEAD", "/pfx/logs/a").status_code == 200
        assert dst["client"].request("HEAD", "/pfx/data/b").status_code == 404

    def test_forged_replica_header_denied(self, pair):
        """A plain user may not forge x-minio-source-replication-request to
        overwrite versions in place or mark objects REPLICA."""
        src, _ = pair
        # Narrow policy: object read/write but NOT s3:ReplicateObject.
        doc = {
            "Version": "2012-10-17",
            "Statement": [
                {
                    "Effect": "Allow",
                    "Action": ["s3:PutObject", "s3:GetObject", "s3:ListBucket"],
                    "Resource": ["arn:aws:s3:::*"],
                }
            ],
        }
        r = src["client"].request(
            "PUT", f"{ADMIN}/policies/putonly", body=json.dumps(doc).encode()
        )
        assert r.status_code == 200, r.text
        r = src["client"].request(
            "POST",
            f"{ADMIN}/users",
            body=json.dumps(
                {"accessKey": "mallory", "secretKey": "mallory-secret1", "policies": ["putonly"]}
            ).encode(),
        )
        assert r.status_code == 200, r.text
        mallory = S3TestClient(src["url"], "mallory", "mallory-secret1")
        r = mallory.put_object(
            "rbkt",
            "forged.txt",
            b"evil",
            headers={
                "x-minio-source-replication-request": "true",
                "x-minio-source-version-id": "00000000-0000-0000-0000-000000000001",
            },
        )
        assert r.status_code == 403

    def test_version_delete_replicates_versioned(self, pair):
        """Permanent version deletes only replicate under DeleteReplication,
        and remove exactly that version on the target."""
        src, dst = pair
        assert src["client"].make_bucket("vdel").status_code == 200
        assert dst["client"].make_bucket("vdel").status_code == 200
        _enable_versioning(src["client"], "vdel")
        _enable_versioning(dst["client"], "vdel")
        _configure(
            src,
            dst,
            "vdel",
            extra_rule_xml="<DeleteReplication><Status>Enabled</Status></DeleteReplication>",
        )
        v1 = src["client"].put_object("vdel", "k", b"one").headers["x-amz-version-id"]
        v2 = src["client"].put_object("vdel", "k", b"two").headers["x-amz-version-id"]
        assert src["node"].replication.drain(15)
        # Delete the OLD version on the source; target's latest must survive.
        r = src["client"].request("DELETE", "/vdel/k", query=[("versionId", v1)])
        assert r.status_code == 204
        assert src["node"].replication.drain(15)
        r = dst["client"].request("GET", "/vdel/k")
        assert r.status_code == 200 and r.content == b"two"
        assert r.headers["x-amz-version-id"] == v2
        r = dst["client"].request("GET", "/vdel/k", query=[("versionId", v1)])
        assert r.status_code == 404

    def test_tags_replicate(self, pair):
        src, dst = pair
        r = src["client"].put_object(
            "rbkt", "tagged.txt", b"tagged", headers={"x-amz-tagging": "env=prod&team=ml"}
        )
        assert r.status_code == 200
        assert src["node"].replication.drain(15)
        r = dst["client"].request("GET", "/rbkt/tagged.txt", query=[("tagging", "")])
        assert r.status_code == 200
        assert "env" in r.text and "prod" in r.text

    def test_bulk_delete_replicates(self, pair):
        src, dst = pair
        for i in range(3):
            src["client"].put_object("rbkt", f"bulk-{i}", b"x")
        assert src["node"].replication.drain(15)
        for i in range(3):
            assert dst["client"].request("HEAD", f"/rbkt/bulk-{i}").status_code == 200
        xml = "<Delete>" + "".join(
            f"<Object><Key>bulk-{i}</Key></Object>" for i in range(3)
        ) + "</Delete>"
        import hashlib, base64

        r = src["client"].request(
            "POST",
            "/rbkt",
            query=[("delete", "")],
            body=xml.encode(),
            headers={"Content-Md5": base64.b64encode(hashlib.md5(xml.encode()).digest()).decode()},
        )
        assert r.status_code == 200, r.text
        assert src["node"].replication.drain(15)
        for i in range(3):
            assert dst["client"].request("HEAD", f"/rbkt/bulk-{i}").status_code == 404

    def test_active_active_no_ping_pong(self, pair):
        """Bidirectional rules must not loop: replica PUTs are skipped via
        REPLICA status, replica DELETEs via the source-replication header."""
        src, dst = pair
        for c in (src["client"], dst["client"]):
            assert c.make_bucket("bidir").status_code == 200
            _enable_versioning(c, "bidir")
        _configure(src, dst, "bidir")
        _configure(dst, src, "bidir")

        src["client"].put_object("bidir", "ping", b"pong")
        assert src["node"].replication.drain(15)
        assert dst["node"].replication.drain(15)
        assert src["node"].replication.drain(5)  # nothing bounced back
        r = dst["client"].request("HEAD", "/bidir/ping")
        assert r.headers["x-amz-replication-status"] == "REPLICA"

        src["client"].request("DELETE", "/bidir/ping")
        assert src["node"].replication.drain(15)
        assert dst["node"].replication.drain(15)
        assert src["node"].replication.drain(5)
        # Exactly one marker version on each side (no ping-pong growth).
        for c in (src["client"], dst["client"]):
            r = c.request("GET", "/bidir", query=[("versions", "")])
            assert r.text.count("<DeleteMarker>") == 1, r.text

    @pytest.mark.skipif(
        _kms_mod.AESGCM is None,
        reason="cryptography not installed: node boots KMS-less, secrets unsealed",
    )
    def test_target_secret_sealed_at_rest(self, pair):
        """The stored bucket metadata must not contain the target's secret
        key in cleartext (sealed with the cluster KMS)."""
        src, _ = pair
        raw = src["node"].s3.bucket_meta.get("rbkt").targets_json
        assert SECRET not in raw
        assert "sealed:" in raw
        # Round-trip still yields a working client (covered implicitly by the
        # other tests, but assert the unsealed value directly).
        ts = src["node"].replication.targets.list_targets("rbkt")
        assert ts and ts[0].secret_key == SECRET

    def test_target_listing_and_removal(self, pair):
        src, _ = pair
        r = src["client"].request("GET", f"{ADMIN}/replication/target", query=[("bucket", "pfx")])
        targets = r.json()
        assert len(targets) == 1
        assert "secret_key" not in targets[0]
        r = src["client"].request(
            "DELETE",
            f"{ADMIN}/replication/target",
            body=json.dumps({"bucket": "pfx", "arn": targets[0]["arn"]}).encode(),
        )
        assert r.status_code == 200
        r = src["client"].request("GET", f"{ADMIN}/replication/target", query=[("bucket", "pfx")])
        assert r.json() == []


class TestBandwidth:
    """Replication bandwidth limits + monitoring
    (internal/bucket/bandwidth role, admin-handlers.go:1935)."""

    def test_token_bucket_and_monitor(self):
        import time as _t

        from minio_tpu.control.bandwidth import BandwidthMonitor, _TokenBucket

        tb = _TokenBucket(100_000)  # 100 KB/s, 100 KB burst
        assert tb.consume(50_000) == 0.0  # rides the burst
        t0 = _t.monotonic()
        tb.consume(100_000)  # must wait for ~50 KB of refill
        assert _t.monotonic() - t0 >= 0.3

        mon = BandwidthMonitor()
        mon.set_limit("b", "arn:x", 1_000_000)
        mon.record("b", "arn:x", 500_000)
        rep = mon.report()
        assert rep["b"]["arn:x"]["limitInBytesPerSecond"] == 1_000_000
        assert rep["b"]["arn:x"]["currentBandwidthInBytesPerSecond"] > 0
        mon.set_limit("b", "arn:x", 0)  # unlimited clears the throttle
        assert mon.throttle("b", "arn:x", 10_000_000) == 0.0

    def test_throttled_replication_and_admin_report(self, pair):
        import time as _t

        src, dst = pair
        for c in (src["client"], dst["client"]):
            assert c.make_bucket("bwbkt").status_code in (200, 409)
        _enable_versioning(src["client"], "bwbkt")
        _enable_versioning(dst["client"], "bwbkt")
        # Target with a 64 KB/s cap.
        r = src["client"].request(
            "POST",
            f"{ADMIN}/replication/target",
            body=json.dumps(
                {
                    "bucket": "bwbkt",
                    "endpoint": dst["url"],
                    "targetBucket": "bwbkt",
                    "accessKey": ROOT,
                    "secretKey": SECRET,
                    "bandwidth": 64_000,
                }
            ).encode(),
        )
        assert r.status_code == 200, r.text
        arn = r.json()["arn"]
        xml = (
            '<ReplicationConfiguration xmlns="http://s3.amazonaws.com/doc/2006-03-01/">'
            "<Role></Role><Rule><ID>bw</ID><Status>Enabled</Status><Priority>1</Priority>"
            "<DeleteMarkerReplication><Status>Enabled</Status></DeleteMarkerReplication>"
            "<Filter><Prefix></Prefix></Filter>"
            f"<Destination><Bucket>{arn}</Bucket></Destination></Rule>"
            "</ReplicationConfiguration>"
        )
        assert (
            src["client"]
            .request("PUT", "/bwbkt", query=[("replication", "")], body=xml.encode())
            .status_code
            == 200
        )
        # 192 KB at 64 KB/s with a 64 KB burst: >= ~1.5s of throttle.
        t0 = _t.monotonic()
        assert src["client"].put_object("bwbkt", "big", b"z" * 192_000).status_code == 200
        deadline = _t.monotonic() + 20
        while _t.monotonic() < deadline:
            if dst["client"].get_object("bwbkt", "big").status_code == 200:
                break
            _t.sleep(0.25)
        assert dst["client"].get_object("bwbkt", "big").content == b"z" * 192_000
        assert _t.monotonic() - t0 >= 1.0  # the cap actually delayed the replica
        # Admin bandwidth report shows the limit and a nonzero observed rate.
        r = src["client"].request("GET", f"{ADMIN}/bandwidth", query=[("bucket", "bwbkt")])
        assert r.status_code == 200, r.text
        rep = r.json()["bwbkt"][arn]
        assert rep["limitInBytesPerSecond"] == 64_000
        assert rep["currentBandwidthInBytesPerSecond"] > 0


class TestReplicationReset:
    """PUT ?replication-reset resyncs existing objects
    (ResetBucketReplicationStateHandler, api-router.go:420)."""

    def test_reset_requeues_existing(self, pair):
        import time as _t

        src, dst = pair
        for c in (src["client"], dst["client"]):
            assert c.make_bucket("rstbkt").status_code in (200, 409)
        _enable_versioning(src["client"], "rstbkt")
        _enable_versioning(dst["client"], "rstbkt")
        # Object written BEFORE any replication config exists.
        assert src["client"].put_object("rstbkt", "pre-existing", b"old data").status_code == 200
        _configure(
            src,
            dst,
            "rstbkt",
            extra_rule_xml=(
                "<ExistingObjectReplication><Status>Enabled</Status>"
                "</ExistingObjectReplication>"
            ),
        )
        assert dst["client"].get_object("rstbkt", "pre-existing").status_code == 404
        r = src["client"].request("PUT", "/rstbkt", query=[("replication-reset", "")])
        assert r.status_code == 200, r.text
        assert r.json()["queued"] >= 1
        deadline = _t.monotonic() + 15
        while _t.monotonic() < deadline:
            if dst["client"].get_object("rstbkt", "pre-existing").status_code == 200:
                break
            _t.sleep(0.25)
        assert dst["client"].get_object("rstbkt", "pre-existing").content == b"old data"

    def test_reset_without_config_errors(self, pair):
        src, _ = pair
        assert src["client"].make_bucket("norepl").status_code in (200, 409)
        r = src["client"].request("PUT", "/norepl", query=[("replication-reset", "")])
        assert r.status_code == 404
        assert b"ReplicationConfigurationNotFoundError" in r.content


class TestReplicationMetrics:
    """Prometheus exposition includes replication counters + link rates.
    Self-contained: builds its own replicated bucket so the class passes
    under -k selection or sharded runs."""

    def test_metrics_and_s3_endpoint(self, pair):
        src, dst = pair
        for c in (src["client"], dst["client"]):
            assert c.make_bucket("metbkt").status_code in (200, 409)
        _enable_versioning(src["client"], "metbkt")
        _enable_versioning(dst["client"], "metbkt")
        _configure(src, dst, "metbkt")
        assert src["client"].put_object("metbkt", "m1", b"metrics!").status_code == 200
        assert src["node"].replication.drain(15)

        r = src["client"].request("GET", f"{ADMIN}/metrics")
        assert r.status_code == 200
        body = r.text
        assert "minio_tpu_replication_completed_total" in body
        assert "minio_tpu_replication_sent_bytes" in body
        # The link gauges appear for this bucket's target.
        assert 'minio_tpu_replication_link_bytes_per_second{bucket="metbkt"' in body

        # GET ?replication-metrics returns live counters (the latent
        # pending-property 500 is pinned here).
        r = src["client"].request("GET", "/metbkt", query=[("replication-metrics", "")])
        assert r.status_code == 200, r.text
        doc = r.json()
        assert doc["completed"] >= 1 and "pending" in doc
