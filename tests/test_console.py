"""Embedded web console (minio/console role): login, info, browse."""

import json
import os
from types import SimpleNamespace

import pytest
import requests

from minio_tpu.api.server import ThreadedServer
from minio_tpu.dist.node import Node
from minio_tpu.object.codec import HostCodec

ROOT, SECRET = "consoleadmin", "consolesecret"


@pytest.fixture(scope="module")
def srv(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("console")
    dirs = []
    for i in range(4):
        d = str(tmp / f"d{i}")
        os.makedirs(d)
        dirs.append(d)
    node = Node(dirs, root_user=ROOT, root_password=SECRET, codec=HostCodec())
    ts = ThreadedServer(SimpleNamespace(app=node.make_app()))
    base = ts.start()
    node.build()
    yield {"node": node, "base": base}
    ts.stop()


def _login(base, ak=ROOT, sk=SECRET):
    return requests.post(
        f"{base}/mtpu/console/api/login",
        data=json.dumps({"accessKey": ak, "secretKey": sk}),
        timeout=10,
    )


def test_page_served(srv):
    r = requests.get(f"{srv['base']}/mtpu/console/", timeout=10)
    assert r.status_code == 200
    assert "console" in r.text


def test_login_info_and_browse(srv):
    base, node = srv["base"], srv["node"]
    r = _login(base)
    assert r.status_code == 200, r.text
    hdrs = {"Authorization": f"Bearer {r.json()['token']}"}

    r = requests.get(f"{base}/mtpu/console/api/info", headers=hdrs, timeout=10)
    assert r.status_code == 200
    info = r.json()
    assert info["drivesTotal"] == 4 and info["drivesOnline"] == 4

    node.pools.make_bucket("conb")
    node.pools.put_object("conb", "dir/x", b"hello world")
    r = requests.get(f"{base}/mtpu/console/api/buckets", headers=hdrs, timeout=10)
    assert any(b["name"] == "conb" for b in r.json()["buckets"])

    r = requests.get(
        f"{base}/mtpu/console/api/objects", params={"bucket": "conb"},
        headers=hdrs, timeout=10,
    )
    assert r.json()["prefixes"] == ["dir/"]
    r = requests.get(
        f"{base}/mtpu/console/api/objects",
        params={"bucket": "conb", "prefix": "dir/"},
        headers=hdrs, timeout=10,
    )
    assert [o["name"] for o in r.json()["objects"]] == ["dir/x"]

    r = requests.get(f"{base}/mtpu/console/api/metrics", headers=hdrs, timeout=10)
    assert r.status_code == 200


def test_bad_credentials_rejected(srv):
    base = srv["base"]
    assert _login(base, sk="wrong").status_code == 401
    assert requests.get(f"{base}/mtpu/console/api/info", timeout=10).status_code == 401
    r = requests.get(
        f"{base}/mtpu/console/api/info",
        headers={"Authorization": "Bearer junk.junk.junk"},
        timeout=10,
    )
    assert r.status_code == 401


def test_non_admin_user_rejected(srv):
    srv["node"].iam.add_user("plainuser", "plainsecret1234")
    assert _login(srv["base"], ak="plainuser", sk="plainsecret1234").status_code == 403


def test_503_before_build(tmp_path):
    dirs = []
    for i in range(4):
        d = str(tmp_path / f"u{i}")
        os.makedirs(d)
        dirs.append(d)
    node = Node(dirs, root_user=ROOT, root_password=SECRET, codec=HostCodec())
    ts = ThreadedServer(SimpleNamespace(app=node.make_app()))
    base = ts.start()
    try:
        r = _login(base)
        assert r.status_code == 503
        r = requests.get(f"{base}/mtpu/console/api/info", timeout=10)
        assert r.status_code == 503
    finally:
        ts.stop()
