"""Embedded web console (minio/console role): login, info, browse."""

import json
import os
from types import SimpleNamespace

import pytest
import requests

from minio_tpu.api.server import ThreadedServer
from minio_tpu.dist.node import Node
from minio_tpu.object.codec import HostCodec

ROOT, SECRET = "consoleadmin", "consolesecret"


@pytest.fixture(scope="module")
def srv(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("console")
    dirs = []
    for i in range(4):
        d = str(tmp / f"d{i}")
        os.makedirs(d)
        dirs.append(d)
    node = Node(dirs, root_user=ROOT, root_password=SECRET, codec=HostCodec())
    ts = ThreadedServer(SimpleNamespace(app=node.make_app()))
    base = ts.start()
    node.build()
    yield {"node": node, "base": base}
    ts.stop()


def _login(base, ak=ROOT, sk=SECRET):
    return requests.post(
        f"{base}/mtpu/console/api/login",
        data=json.dumps({"accessKey": ak, "secretKey": sk}),
        timeout=10,
    )


def test_page_served(srv):
    r = requests.get(f"{srv['base']}/mtpu/console/", timeout=10)
    assert r.status_code == 200
    assert "console" in r.text


def test_login_info_and_browse(srv):
    base, node = srv["base"], srv["node"]
    r = _login(base)
    assert r.status_code == 200, r.text
    hdrs = {"Authorization": f"Bearer {r.json()['token']}"}

    r = requests.get(f"{base}/mtpu/console/api/info", headers=hdrs, timeout=10)
    assert r.status_code == 200
    info = r.json()
    assert info["drivesTotal"] == 4 and info["drivesOnline"] == 4

    node.pools.make_bucket("conb")
    node.pools.put_object("conb", "dir/x", b"hello world")
    r = requests.get(f"{base}/mtpu/console/api/buckets", headers=hdrs, timeout=10)
    assert any(b["name"] == "conb" for b in r.json()["buckets"])

    r = requests.get(
        f"{base}/mtpu/console/api/objects", params={"bucket": "conb"},
        headers=hdrs, timeout=10,
    )
    assert r.json()["prefixes"] == ["dir/"]
    r = requests.get(
        f"{base}/mtpu/console/api/objects",
        params={"bucket": "conb", "prefix": "dir/"},
        headers=hdrs, timeout=10,
    )
    assert [o["name"] for o in r.json()["objects"]] == ["dir/x"]

    r = requests.get(f"{base}/mtpu/console/api/metrics", headers=hdrs, timeout=10)
    assert r.status_code == 200


def test_bad_credentials_rejected(srv):
    base = srv["base"]
    assert _login(base, sk="wrong").status_code == 401
    assert requests.get(f"{base}/mtpu/console/api/info", timeout=10).status_code == 401
    r = requests.get(
        f"{base}/mtpu/console/api/info",
        headers={"Authorization": "Bearer junk.junk.junk"},
        timeout=10,
    )
    assert r.status_code == 401


def test_non_admin_user_rejected(srv):
    srv["node"].iam.add_user("plainuser", "plainsecret1234")
    assert _login(srv["base"], ak="plainuser", sk="plainsecret1234").status_code == 403


def test_management_loop(srv):
    """The operator's basic management loop, console API only: create a
    bucket, create a user with a policy, re-attach policies, mint a
    service account, delete everything — no raw admin REST involved."""
    base = srv["base"]
    hdrs = {"Authorization": "Bearer " + _login(base).json()["token"]}

    def call(method, path, body=None, **kw):
        return requests.request(
            method, f"{base}/mtpu/console/api{path}",
            headers=hdrs, data=json.dumps(body) if body is not None else None,
            timeout=10, **kw,
        )

    # bucket create / duplicate / delete
    assert call("POST", "/buckets", {"name": "mgmtb"}).status_code == 200
    assert call("POST", "/buckets", {"name": "mgmtb"}).status_code == 409
    names = [b["name"] for b in call("GET", "/buckets").json()["buckets"]]
    assert "mgmtb" in names

    # user create with policy, listed without secrets
    r = call("POST", "/users",
             {"accessKey": "conuser", "secretKey": "consecret123", "policies": ["readonly"]})
    assert r.status_code == 200, r.text
    users = {u["accessKey"]: u for u in call("GET", "/users").json()["users"]}
    assert users["conuser"]["policies"] == ["readonly"]
    assert users["conuser"]["secretKey"] == ""
    # root cannot be overwritten through the console
    assert call("POST", "/users",
                {"accessKey": ROOT, "secretKey": "x" * 12}).status_code == 403

    # the created user actually works against S3 (policy-scoped)
    import sys
    sys.path.insert(0, os.path.dirname(__file__))
    from s3client import S3TestClient

    cu = S3TestClient(base, "conuser", "consecret123")
    assert cu.request("GET", "/mgmtb", query=[("list-type", "2")]).status_code == 200
    assert cu.request("PUT", "/mgmtb/denied.txt", body=b"x").status_code == 403

    # policy re-attach widens access
    assert call("PUT", "/users/policy",
                {"accessKey": "conuser", "policies": ["readwrite"]}).status_code == 200
    assert cu.request("PUT", "/mgmtb/ok.txt", body=b"x").status_code == 200

    # service account under the user; creds shown once and usable
    sa = call("POST", "/service-accounts", {"parent": "conuser"}).json()
    sc = S3TestClient(base, sa["accessKey"], sa["secretKey"])
    assert sc.request("GET", "/mgmtb", query=[("list-type", "2")]).status_code == 200

    # policies list covers canned + custom
    assert "readonly" in call("GET", "/policies").json()["policies"]

    # a bare-string policies field must 400, not fragment per character
    assert call("POST", "/users",
                {"accessKey": "frag", "secretKey": "fragsecret12",
                 "policies": "readonly"}).status_code == 400

    # cleanup: deleting the user cascades to its service accounts
    assert call("DELETE", "/users", params={"accessKey": "conuser"}).status_code == 200
    assert _login(base, ak="conuser", sk="consecret123").status_code == 401
    remaining = {u["accessKey"] for u in call("GET", "/users").json()["users"]}
    assert sa["accessKey"] not in remaining, "orphan service account survived"
    assert sc.request("GET", "/mgmtb", query=[("list-type", "2")]).status_code == 403
    cu2 = S3TestClient(base, "conuser", "consecret123")
    assert cu2.request("GET", "/mgmtb", query=[("list-type", "2")]).status_code == 403
    assert call("DELETE", "/buckets", params={"name": "mgmtb"}).status_code == 409  # not empty
    srv["node"].pools.delete_object("mgmtb", "ok.txt")
    assert call("DELETE", "/buckets", params={"name": "mgmtb"}).status_code == 200
    assert call("DELETE", "/buckets", params={"name": "mgmtb"}).status_code == 404


def test_group_management(srv):
    """Console groups view: create-by-add, policy attach actually gates S3
    access, disable/enable, member remove, delete."""
    base = srv["base"]
    hdrs = {"Authorization": "Bearer " + _login(base).json()["token"]}

    def call(method, path, body=None, **kw):
        return requests.request(
            method, f"{base}/mtpu/console/api{path}", headers=hdrs,
            data=json.dumps(body) if body is not None else None, timeout=10, **kw,
        )

    assert call("POST", "/users",
                {"accessKey": "gcuser", "secretKey": "gcsecret12345"}).status_code == 200
    r = call("POST", "/groups", {"name": "cg", "members": ["gcuser"]})
    assert r.status_code == 200, r.text
    r = call("POST", "/groups", {"name": "cg", "policies": ["readwrite"]})
    assert r.status_code == 200, r.text
    groups = call("GET", "/groups").json()["groups"]
    assert groups[0]["members"] == ["gcuser"] and groups[0]["policies"] == ["readwrite"]

    import sys
    sys.path.insert(0, os.path.dirname(__file__))
    from s3client import S3TestClient

    gu = S3TestClient(base, "gcuser", "gcsecret12345")
    assert gu.make_bucket("cgbkt").status_code == 200
    assert call("POST", "/groups", {"name": "cg", "status": "disabled"}).status_code == 200
    assert gu.request("PUT", "/cgbkt/x", body=b"x").status_code == 403
    assert call("POST", "/groups",
                {"name": "cg", "isRemove": True, "members": ["gcuser"]}).status_code == 200
    assert call("DELETE", "/groups", params={"name": "cg"}).status_code == 200
    assert call("GET", "/groups").json()["groups"] == []
    # bad shapes 400
    assert call("POST", "/groups", {"name": "x", "members": "notalist"}).status_code == 400
    call("DELETE", "/users", params={"accessKey": "gcuser"})
    srv["node"].pools.delete_bucket("cgbkt", force=True)


def test_503_before_build(tmp_path):
    dirs = []
    for i in range(4):
        d = str(tmp_path / f"u{i}")
        os.makedirs(d)
        dirs.append(d)
    node = Node(dirs, root_user=ROOT, root_password=SECRET, codec=HostCodec())
    ts = ThreadedServer(SimpleNamespace(app=node.make_app()))
    base = ts.start()
    try:
        r = _login(base)
        assert r.status_code == 503
        r = requests.get(f"{base}/mtpu/console/api/info", timeout=10)
        assert r.status_code == 503
    finally:
        ts.stop()
