"""Pool placement + lifecycle-status routing tests (ServerPools).

The placement half of cmd/erasure-server-pool.go: new objects go to the
ACTIVE pool with the most free space (deterministic tie-break by pool
index), overwrites follow the holding pool, reads/deletes/listings span
every non-decommissioned pool, and a draining pool never receives writes.
"""

import os
from dataclasses import replace

import pytest

from minio_tpu.object.pools import (
    POOL_ACTIVE,
    POOL_DECOMMISSIONED,
    POOL_DRAINING,
    ServerPools,
)
from minio_tpu.object.sets import ErasureSets
from minio_tpu.storage import format as fmt
from minio_tpu.storage.local import LocalDrive
from minio_tpu.utils import errors


def make_pools(tmp_path, n_pools=2, n_disks=4) -> ServerPools:
    pools = []
    for pi in range(n_pools):
        formats = fmt.init_format(1, n_disks)
        drives = []
        for i in range(n_disks):
            root = str(tmp_path / f"pool{pi}" / f"disk{i}")
            os.makedirs(root, exist_ok=True)
            formats[i].save(root)
            drives.append(LocalDrive(root))
        pools.append(ErasureSets.from_drives(drives, formats[0], pool_index=pi))
    return ServerPools(pools)


def _override_free(pool: ErasureSets, free: int) -> None:
    """Pin every drive's reported free bytes (instance-level shadow of
    disk_info): placement tests need capacities the shared tmp filesystem
    can't provide."""
    for d in pool.disks:
        real = d.disk_info()
        d.disk_info = lambda di=replace(real, free=free): di


@pytest.fixture
def layer(tmp_path):
    lp = make_pools(tmp_path)
    lp.make_bucket("bucket")
    return lp


class TestPlacement:
    def test_most_free_pool_wins(self, layer):
        _override_free(layer.pools[0], 10 << 20)
        _override_free(layer.pools[1], 50 << 20)
        assert layer._pool_with_space() is layer.pools[1]

    def test_tie_breaks_to_lowest_index(self, layer):
        _override_free(layer.pools[0], 42 << 20)
        _override_free(layer.pools[1], 42 << 20)
        # Equal free bytes on every probe: deterministic, index 0 wins --
        # every node running the same pool config must place identically.
        for _ in range(5):
            assert layer._pool_with_space() is layer.pools[0]

    def test_capacity_weighted_put(self, layer):
        _override_free(layer.pools[0], 1 << 20)
        _override_free(layer.pools[1], 100 << 20)
        layer.put_object("bucket", "fresh", b"x" * 128)
        oi = layer.pools[1].get_object_info("bucket", "fresh")
        assert oi.name == "fresh"
        with pytest.raises(errors.ObjectError):
            layer.pools[0].get_object_info("bucket", "fresh")

    def test_overwrite_lands_in_holding_pool(self, layer):
        # Seed the object in pool 1 directly, then make pool 1 look FULLER
        # than pool 0: the overwrite must still follow the holding pool.
        layer.pools[1].put_object("bucket", "sticky", b"v1")
        _override_free(layer.pools[0], 100 << 20)
        _override_free(layer.pools[1], 1 << 20)
        layer.put_object("bucket", "sticky", b"v2")
        _, data = layer.pools[1].get_object("bucket", "sticky")
        assert data == b"v2"
        with pytest.raises(errors.ObjectError):
            layer.pools[0].get_object_info("bucket", "sticky")

    def test_no_active_pool_is_disk_full(self, layer):
        layer.set_pool_status(0, POOL_DRAINING)
        layer.set_pool_status(1, POOL_DRAINING)
        with pytest.raises(errors.DiskFull):
            layer._pool_with_space()


class TestLifecycleRouting:
    def test_draining_pool_excluded_from_writes(self, layer):
        _override_free(layer.pools[0], 100 << 20)
        _override_free(layer.pools[1], 1 << 20)
        layer.set_pool_status(0, POOL_DRAINING)
        # Pool 0 has far more room but is draining: writes go to pool 1.
        layer.put_object("bucket", "routed", b"data")
        assert layer.pools[1].get_object_info("bucket", "routed").name == "routed"
        with pytest.raises(errors.ObjectError):
            layer.pools[0].get_object_info("bucket", "routed")

    def test_overwrite_of_draining_pool_object_places_fresh(self, layer):
        layer.pools[0].put_object("bucket", "mig", b"old")
        layer.set_pool_status(0, POOL_DRAINING)
        layer.put_object("bucket", "mig", b"new")
        # New copy in the active pool; reads resolve the NEWEST copy even
        # though the stale source copy still exists mid-migration.
        _, data = layer.get_object("bucket", "mig")
        assert data == b"new"
        assert layer.pools[1].get_object_info("bucket", "mig").name == "mig"

    def test_draining_pool_still_serves_reads(self, layer):
        layer.pools[0].put_object("bucket", "readable", b"still-here")
        layer.set_pool_status(0, POOL_DRAINING)
        _, data = layer.get_object("bucket", "readable")
        assert data == b"still-here"

    def test_decommissioned_pool_skipped_entirely(self, layer):
        layer.pools[1].put_object("bucket", "kept", b"kept")
        layer.set_pool_status(0, POOL_DECOMMISSIONED)
        assert layer.statuses == [POOL_DECOMMISSIONED, POOL_ACTIVE]
        assert [i for i, _ in layer._probe_pools()] == [1]
        # Single live candidate: the negative-lookup fast path answers
        # without probing, and a miss is a clean ObjectNotFound.
        _, data = layer.get_object("bucket", "kept")
        assert data == b"kept"
        with pytest.raises(errors.ObjectNotFound):
            layer.get_object("bucket", "no-such-key")


class TestNamespaceSpansPools:
    def test_listing_merges_pools(self, layer):
        layer.pools[0].put_object("bucket", "a-zero", b"0")
        layer.pools[1].put_object("bucket", "b-one", b"1")
        names = [o.name for o in layer.list_objects("bucket").objects]
        assert names == ["a-zero", "b-one"]

    def test_listing_dedupes_newest_copy(self, layer):
        layer.pools[0].put_object("bucket", "dup", b"old")
        layer.pools[1].put_object("bucket", "dup", b"new")
        listed = layer.list_objects("bucket").objects
        assert [o.name for o in listed] == ["dup"]
        _, data = layer.get_object("bucket", "dup")
        assert data == b"new"

    def test_delete_sweeps_every_pool(self, layer):
        # Mid-migration the object exists in BOTH pools; a delete must not
        # let the second copy resurrect it.
        layer.pools[0].put_object("bucket", "both", b"copy0")
        layer.pools[1].put_object("bucket", "both", b"copy1")
        layer.delete_object("bucket", "both")
        with pytest.raises(errors.ObjectNotFound):
            layer.get_object("bucket", "both")
        assert layer.list_objects("bucket").objects == []

    def test_bucket_ops_span_pools(self, layer):
        layer.make_bucket("span-bucket")
        for p in layer.pools:
            assert p.get_bucket_info("span-bucket").name == "span-bucket"
        layer.delete_bucket("span-bucket")
        assert not layer.bucket_exists("span-bucket")
