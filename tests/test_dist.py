"""Distributed tests: multi-node cluster in one process.

The analogue of the reference's distributed harnesses
(buildscripts/verify-healing.sh: multiple server processes on localhost;
internal/dsync/dsync-server_test.go: in-process lock servers): several Node
instances with their own HTTP servers on localhost ports, sharing nothing but
the endpoint list. Covers remote StorageAPI, format handshake, cross-node
object IO, node-loss degradation, dsync quorum locks.
"""

import os
import socket
import threading
import time
from types import SimpleNamespace

import pytest

from minio_tpu.api.server import ThreadedServer
from minio_tpu.dist.locks import DRWMutex, LocalLocker, RemoteLocker
from minio_tpu.dist.node import Node
from minio_tpu.dist.peer import PeerClient
from minio_tpu.dist.storage_rest import RemoteDrive
from minio_tpu.dist.transport import cluster_token
from minio_tpu.storage.local import LocalDrive
from minio_tpu.utils import errors
from tests.s3client import S3TestClient

# Stressed under adversarial thread scheduling by tools/race_gate.py.
pytestmark = pytest.mark.race



def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


ROOT = "clusteradmin"
SECRET = "cluster-secret-key"


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("cluster")
    ports = [_free_port(), _free_port()]
    urls = [f"http://127.0.0.1:{p}" for p in ports]
    endpoints = []
    for ni in range(2):
        for di in range(4):
            endpoints.append(f"{urls[ni]}{tmp}/n{ni}d{di}")
    nodes = [
        Node(endpoints, url=urls[ni], root_user=ROOT, root_password=SECRET, set_drive_count=8)
        for ni in range(2)
    ]
    servers = []
    for ni, node in enumerate(nodes):
        ts = ThreadedServer(SimpleNamespace(app=node.make_app()), port=ports[ni])
        ts.start()
        servers.append(ts)
    # Build concurrently: node 0 leads the format, node 1 waits for quorum.
    threads = [threading.Thread(target=n.build) for n in nodes]
    for t in threads:
        t.start()
    for t in threads:
        t.join(60)
    assert all(n.pools is not None for n in nodes), "cluster failed to build"
    clients = [S3TestClient(urls[ni], ROOT, SECRET) for ni in range(2)]
    yield {"nodes": nodes, "clients": clients, "urls": urls, "tmp": tmp}
    for ts in servers:
        ts.stop()


class TestRemoteDrive:
    def test_remote_storage_api(self, cluster):
        node0 = cluster["nodes"][0]
        # A drive on node 1, accessed from node 0's perspective.
        remote = next(d for d in node0.drives if isinstance(d, RemoteDrive))
        assert remote.is_online()
        assert remote.disk_id()
        remote.make_vol("remvol")
        remote.write_all("remvol", "a/b.txt", b"remote-bytes")
        assert remote.read_all("remvol", "a/b.txt") == b"remote-bytes"
        remote.create_file("remvol", "f/shard.bin", b"\x01" * 100)
        assert remote.read_file("remvol", "f/shard.bin", 10, 5) == b"\x01" * 5
        assert remote.stat_file("remvol", "f/shard.bin") == 100
        assert "a/" in remote.list_dir("remvol", "")
        with pytest.raises(errors.FileNotFound):
            remote.read_all("remvol", "missing")
        remote.delete_vol("remvol", force=True)
        with pytest.raises(errors.VolumeNotFound):
            remote.stat_vol("remvol")

    def test_formats_agree(self, cluster):
        n0, n1 = cluster["nodes"]
        ids0 = sorted(d.disk_id() for d in n0.drives)
        ids1 = sorted(d.disk_id() for d in n1.drives)
        assert ids0 == ids1
        assert len(set(ids0)) == 8


class TestCrossNodeIO:
    def test_delete_on_a_immediately_404s_put_on_b(self, cluster):
        """The bucket-existence cache is TTL'd per node; a cross-node
        delete must invalidate peers NOW (peer reload hook), not after the
        cache window — a stale hit would accept PUTs into the deleted
        namespace."""
        ca, cb = cluster["clients"]
        ca.make_bucket("xdel")
        # Warm node B's existence cache with a successful op.
        assert cb.put_object("xdel", "warm.bin", b"w").status_code == 200
        assert cb.request("DELETE", "/xdel/warm.bin").status_code in (200, 204)
        assert ca.request("DELETE", "/xdel").status_code in (200, 204)
        r = cb.request("PUT", "/xdel/after.bin", body=b"x")
        assert r.status_code == 404, f"stale peer bucket cache: {r.status_code}"

    def test_bucket_policy_on_a_applies_on_b(self, cluster):
        """Bucket metadata is cached per node with NO TTL; a config write
        must broadcast invalidation or peers serve the old policy forever."""
        import json as json_mod

        ca, cb = cluster["clients"]
        ca.make_bucket("xpol")
        ca.put_object("xpol", "pub.txt", b"public-read")
        # Warm node B's meta cache with the no-policy state.
        r = cb.request("GET", "/xpol/pub.txt", anonymous=True)
        assert r.status_code == 403
        pol = {
            "Version": "2012-10-17",
            "Statement": [{"Effect": "Allow", "Principal": "*",
                           "Action": ["s3:GetObject"],
                           "Resource": ["arn:aws:s3:::xpol/*"]}],
        }
        assert ca.request(
            "PUT", "/xpol", query=[("policy", "")],
            body=json_mod.dumps(pol).encode(),
        ).status_code in (200, 204)
        r = cb.request("GET", "/xpol/pub.txt", anonymous=True)
        assert r.status_code == 200, f"stale bucket policy on peer: {r.status_code}"
        assert r.content == b"public-read"

    def test_put_on_a_get_on_b(self, cluster):
        c0, c1 = cluster["clients"]
        assert c0.make_bucket("distbucket").status_code == 200
        data = b"cross-node-payload" * 5000
        assert c0.put_object("distbucket", "big/obj", data).status_code == 200
        r = c1.get_object("distbucket", "big/obj")
        assert r.status_code == 200
        assert r.content == data
        # Listing agrees on both nodes.
        import xml.etree.ElementTree as ET

        NS = "{http://s3.amazonaws.com/doc/2006-03-01/}"
        for c in (c0, c1):
            keys = [
                e.text
                for e in ET.fromstring(c.list_objects("distbucket").content).iter(f"{NS}Key")
            ]
            assert keys == ["big/obj"]

    def test_delete_propagates(self, cluster):
        c0, c1 = cluster["clients"]
        c0.make_bucket("delbucket")
        c0.put_object("delbucket", "k", b"x")
        assert c1.delete_object("delbucket", "k").status_code == 204
        assert c0.get_object("delbucket", "k").status_code == 404


class TestPeer:
    def test_ping_and_info(self, cluster):
        node0 = cluster["nodes"][0]
        peer = PeerClient(cluster["urls"][1], node0.token)
        assert peer.ping()
        info = peer.server_info()
        assert len(info["drives"]) == 4
        assert all(d["ok"] for d in info["drives"])

    def test_speedtest(self, cluster):
        node0 = cluster["nodes"][0]
        peer = PeerClient(cluster["urls"][1], node0.token)
        res = peer.speedtest(size=4096, count=2)
        assert res["put_bytes_per_s"] > 0
        assert res["get_bytes_per_s"] > 0


class TestDsync:
    def test_exclusive_across_nodes(self, cluster):
        n0, n1 = cluster["nodes"]
        lockers0 = [n0.locker, RemoteLocker(cluster["urls"][1], n0.token)]
        lockers1 = [RemoteLocker(cluster["urls"][0], n1.token), n1.locker]
        m0 = DRWMutex(lockers0, "bucket/lock-test")
        m1 = DRWMutex(lockers1, "bucket/lock-test")
        assert m0.acquire(writer=True, timeout=5)
        assert not m1.acquire(writer=True, timeout=0.5)
        m0.release()
        assert m1.acquire(writer=True, timeout=5)
        m1.release()

    def test_read_locks_share(self, cluster):
        n0, n1 = cluster["nodes"]
        lockers = [n0.locker, RemoteLocker(cluster["urls"][1], n0.token)]
        m0 = DRWMutex(lockers, "bucket/rlock")
        m1 = DRWMutex(lockers, "bucket/rlock")
        assert m0.acquire(writer=False, timeout=2)
        assert m1.acquire(writer=False, timeout=2)
        mw = DRWMutex(lockers, "bucket/rlock")
        assert not mw.acquire(writer=True, timeout=0.5)
        m0.release()
        m1.release()
        assert mw.acquire(writer=True, timeout=2)
        mw.release()

    def test_local_locker_expiry(self):
        from minio_tpu.dist import locks as locks_mod

        lk = LocalLocker()
        assert lk.lock("res", "uid1", True)
        # Simulate a crashed holder: age the entry past expiry.
        lk._map["res"].uids["uid1"] -= locks_mod.EXPIRY + 1
        assert lk.lock("res", "uid2", True)  # expired entry swept


class TestDegraded:
    def test_read_survives_node_loss(self, cluster, tmp_path_factory):
        # Build a fresh 2-node cluster so we can kill one side safely.
        tmp = tmp_path_factory.mktemp("degraded")
        ports = [_free_port(), _free_port()]
        urls = [f"http://127.0.0.1:{p}" for p in ports]
        endpoints = []
        for ni in range(2):
            for di in range(4):
                endpoints.append(f"{urls[ni]}{tmp}/n{ni}d{di}")
        nodes = [
            Node(endpoints, url=urls[ni], root_user=ROOT, root_password=SECRET, set_drive_count=8)
            for ni in range(2)
        ]
        servers = [
            ThreadedServer(SimpleNamespace(app=nodes[ni].make_app()), port=ports[ni])
            for ni in range(2)
        ]
        for s in servers:
            s.start()
        ths = [threading.Thread(target=n.build) for n in nodes]
        for t in ths:
            t.start()
        for t in ths:
            t.join(60)
        c0 = S3TestClient(urls[0], ROOT, SECRET)
        c0.make_bucket("survive")
        data = b"survives-node-loss" * 1000
        c0.put_object("survive", "obj", data)
        # Kill node 1: its 4 drives (= parity budget on 8 drives) vanish.
        servers[1].stop()
        time.sleep(0.2)
        r = c0.get_object("survive", "obj")
        assert r.status_code == 200
        assert r.content == data
        servers[0].stop()

    def test_write_during_node_loss_then_heal_on_rejoin(self, tmp_path_factory):
        """The verify-healing.sh scenario (buildscripts/verify-healing.sh:16):
        a node dies, writes continue at quorum, the node rejoins, heal
        restores its shards, and a clean re-heal reports nothing to do."""
        # 3 nodes x 2 drives (set of 6, parity 3): one node's loss leaves 4
        # drives = the k+1 write quorum, so writes continue — the same shape
        # verify-healing.sh gets from 3 processes (losing half the drives
        # would correctly block writes, hence not 2 nodes here).
        tmp = tmp_path_factory.mktemp("healcycle")
        ports = [_free_port(), _free_port(), _free_port()]
        urls = [f"http://127.0.0.1:{p}" for p in ports]
        endpoints = []
        for ni in range(3):
            for di in range(2):
                endpoints.append(f"{urls[ni]}{tmp}/n{ni}d{di}")

        def boot(ni, node):
            srv = ThreadedServer(SimpleNamespace(app=node.make_app()), port=ports[ni])
            srv.start()
            return srv

        nodes = [
            Node(endpoints, url=urls[ni], root_user=ROOT, root_password=SECRET, set_drive_count=6)
            for ni in range(3)
        ]
        servers = [boot(ni, nodes[ni]) for ni in range(3)]
        ths = [threading.Thread(target=n.build) for n in nodes]
        for t in ths:
            t.start()
        for t in ths:
            t.join(60)
        c0 = S3TestClient(urls[0], ROOT, SECRET)
        c0.make_bucket("healcyc")

        # Node 2 dies; a write lands at quorum (4 of 6 drives alive).
        servers[2].stop()
        time.sleep(0.2)
        data = b"written-while-down" * 3000
        r = c0.put_object("healcyc", "obj", data)
        assert r.status_code == 200, r.text

        # Node 2 rejoins (fresh process over the same drives).
        node2b = Node(
            endpoints, url=urls[2], root_user=ROOT, root_password=SECRET, set_drive_count=6
        )
        servers[2] = boot(2, node2b)
        node2b.build()
        # Node 0's REST clients hold a failure backoff (HEALTH_INTERVAL);
        # wait until every remote drive answers again before healing.
        deadline = time.time() + 15
        while time.time() < deadline:
            if all(d.is_online() and d.disk_id() for d in nodes[0].drives):
                break
            time.sleep(0.5)

        healed = nodes[0].pools.heal_object("healcyc", "obj")
        assert healed.disks_healed >= 1  # node 2's shard rows rebuilt
        again = nodes[0].pools.heal_object("healcyc", "obj", dry_run=True)
        assert again.disks_healed == 0  # clean after heal
        assert c0.get_object("healcyc", "obj").content == data
        # The healed copy is readable THROUGH the rejoined node too.
        c2 = S3TestClient(urls[2], ROOT, SECRET)
        assert c2.get_object("healcyc", "obj").content == data
        for s in servers:
            s.stop()

    def test_node_killed_mid_write_under_load(self, tmp_path_factory):
        """The harder half of verify-healing.sh: the node dies WHILE puts
        are streaming (buildscripts/verify-healing.sh kills server
        processes under load), not between them. Concurrent writers must
        keep succeeding at quorum through the kill, the rejoined node gets
        healed, and every object reads back bit-exact through BOTH sides."""
        tmp = tmp_path_factory.mktemp("killload")
        ports = [_free_port(), _free_port(), _free_port()]
        urls = [f"http://127.0.0.1:{p}" for p in ports]
        endpoints = []
        for ni in range(3):
            for di in range(2):
                endpoints.append(f"{urls[ni]}{tmp}/n{ni}d{di}")

        def boot(ni, node):
            srv = ThreadedServer(SimpleNamespace(app=node.make_app()), port=ports[ni])
            srv.start()
            return srv

        nodes = [
            Node(endpoints, url=urls[ni], root_user=ROOT, root_password=SECRET, set_drive_count=6)
            for ni in range(3)
        ]
        servers = [boot(ni, nodes[ni]) for ni in range(3)]
        ths = [threading.Thread(target=n.build) for n in nodes]
        for t in ths:
            t.start()
        for t in ths:
            t.join(60)
        c0 = S3TestClient(urls[0], ROOT, SECRET)
        c0.make_bucket("killb")

        # 4 writer threads stream 2 MiB objects through node 0 continuously;
        # the kill lands while several puts are mid-flight.
        import hashlib as _hl

        n_writers, per_writer = 4, 6
        bodies: dict[str, bytes] = {}
        results: dict[str, int] = {}
        ready = threading.Barrier(n_writers + 1)

        def writer(w):
            c = S3TestClient(urls[0], ROOT, SECRET)
            ready.wait()
            for r in range(per_writer):
                key = f"w{w}-r{r}"
                body = _hl.sha256(key.encode()).digest() * (2 * 1024 * 1024 // 32)
                bodies[key] = body
                resp = c.put_object("killb", key, body)
                results[key] = resp.status_code

        writers = [threading.Thread(target=writer, args=(w,)) for w in range(n_writers)]
        for t in writers:
            t.start()
        ready.wait()
        # Gate the kill on observed progress, not wall clock: wait until a
        # couple of puts have completed (writers are mid-stream on the
        # rest), so the kill provably lands under load on any machine speed.
        deadline = time.time() + 60
        while len(results) < 2 and time.time() < deadline:
            time.sleep(0.02)
        assert len(results) >= 2, "writers made no progress"
        assert len(results) < n_writers * per_writer, "all puts finished before the kill"
        servers[2].stop()  # kill node 2 under load
        for t in writers:
            t.join(120)
        # Every put must have succeeded at quorum (4 of 6 drives alive).
        assert all(code == 200 for code in results.values()), results
        assert len(results) == n_writers * per_writer

        # Node 2 rejoins over the same drives; wait out the REST backoff.
        node2b = Node(
            endpoints, url=urls[2], root_user=ROOT, root_password=SECRET, set_drive_count=6
        )
        servers[2] = boot(2, node2b)
        node2b.build()
        deadline = time.time() + 20
        while time.time() < deadline:
            if all(d.is_online() and d.disk_id() for d in nodes[0].drives):
                break
            time.sleep(0.5)

        # Heal converges: every object that lost shards rebuilds, and a
        # second pass is clean.
        healed_total = 0
        for key in bodies:
            healed_total += nodes[0].pools.heal_object("killb", key).disks_healed
        assert healed_total >= 1, "kill landed after all writes? (timing too late)"
        for key in bodies:
            assert nodes[0].pools.heal_object("killb", key, dry_run=True).disks_healed == 0
        # Bit-exact through the original node AND the rejoined one.
        c2 = S3TestClient(urls[2], ROOT, SECRET)
        for key, body in bodies.items():
            assert c0.get_object("killb", key).content == body, key
            assert c2.get_object("killb", key).content == body, key
        for s in servers:
            s.stop()


class TestMultiPool:
    """Node-level multi-pool construction (round-3 weak #9): one node, two
    pools, objects placed/readable across the pooled namespace."""

    def test_two_pool_node(self, tmp_path):
        pools = []
        for pi in range(2):
            dirs = []
            for i in range(4):
                d = str(tmp_path / f"p{pi}d{i}")
                os.makedirs(d)
                dirs.append(d)
            pools.append(dirs)
        from minio_tpu.object.codec import HostCodec

        node = Node(pools, root_user=ROOT, root_password=SECRET, codec=HostCodec())
        node.build()
        assert len(node.pools.pools) == 2
        # Pools share one deployment id (cluster identity).
        assert node.pools.pools[0].deployment_id == node.pools.pools[1].deployment_id
        layer = node.pools
        layer.make_bucket("mpool")
        for i in range(8):
            layer.put_object("mpool", f"obj-{i}", f"data-{i}".encode() * 1000)
        for i in range(8):
            _, got = layer.get_object("mpool", f"obj-{i}")
            assert got == f"data-{i}".encode() * 1000
        names = [o.name for o in layer.list_objects("mpool").objects]
        assert names == [f"obj-{i}" for i in range(8)]

    def test_cli_pool_argument_split(self):
        from minio_tpu.cli import expand_ellipses

        # each ellipsis argument expands independently (pool grouping rule)
        a = expand_ellipses("/data/p0/disk{1...4}")
        b = expand_ellipses("/data/p1/disk{1...4}")
        assert len(a) == 4 and len(b) == 4 and not set(a) & set(b)


class TestWalkStream:
    def test_remote_walk_streams(self, cluster):
        """Remote WalkDir rides the streaming endpoint (metacache-walk.go
        streaming discipline), entries identical to the buffered path."""
        c0 = cluster["clients"][0]
        c0.make_bucket("walkb")
        for i in range(25):
            c0.put_object("walkb", f"w/obj-{i:02d}", b"x")
        node0 = cluster["nodes"][0]
        remote = next(d for d in node0.drives if isinstance(d, RemoteDrive))

        streamed = list(remote.walk_dir("walkb"))
        assert [n for n, _ in streamed] == [f"w/obj-{i:02d}" for i in range(25)]
        buffered = list(
            remote._call("walkdir", {"volume": "walkb", "base": "", "recursive": True})
        )
        assert [[n, r] for n, r in streamed] == buffered

        # Typed errors surface BEFORE the stream starts (lazy-generator
        # VolumeNotFound must not become a mid-stream connection abort).
        with pytest.raises(errors.VolumeNotFound):
            list(remote.walk_dir("no-such-bucket-walk"))


class TestCrossNodeListen:
    """ListenNotification merges peer event streams: a watcher on node A
    sees puts served by node B (cmd/listen-notification-handlers.go:31 +
    peer-rest-server.go:985 peer subscription)."""

    def test_watch_on_a_sees_put_on_b(self, cluster):
        import json as _json

        c0, c1 = cluster["clients"]
        assert c0.make_bucket("watchd").status_code in (200, 409)
        got: list[dict] = []
        ready = threading.Event()
        done = threading.Event()

        def listen():
            r = c0.request(
                "GET", "/watchd", query=[("events", "s3:ObjectCreated:*")], stream=True
            )
            assert r.status_code == 200
            ready.set()
            for line in r.iter_lines():
                if line.strip():
                    got.append(_json.loads(line))
                    break
            r.close()
            done.set()

        t = threading.Thread(target=listen, daemon=True)
        t.start()
        assert ready.wait(10)
        time.sleep(0.8)  # let the peer pump attach to node B's stream
        # PUT through node B (the other node's S3 endpoint).
        assert c1.put_object("watchd", "from-b", b"payload").status_code == 200
        assert done.wait(15), "peer event never reached node A's watcher"
        rec = got[0]
        assert rec["Records"][0]["s3"]["object"]["key"] == "from-b"


class TestClusterQuota:
    """Quota set through node A is enforced by node B, whose scanner never
    ran: B reads the leader-persisted usage tree and A's quota write
    invalidates B's bucket-meta cache (cmd/bucket-quota.go:72-112)."""

    def test_quota_enforced_on_non_leader(self, cluster):
        import json as _json

        c0, c1 = cluster["clients"]
        n0, n1 = cluster["nodes"]
        assert c0.make_bucket("qbkt").status_code in (200, 409)
        assert c0.put_object("qbkt", "seed", b"x" * 65536).status_code == 200
        # Warm B's bucket-meta cache so the invalidation matters.
        c1.get_object("qbkt", "seed")
        n0.scanner.scan_cycle()  # the leader persists the usage tree
        r = c0.request(
            "PUT",
            "/mtpu/admin/v1/quota",
            query=[("bucket", "qbkt")],
            body=_json.dumps({"quota": 70000, "quotatype": "hard"}).encode(),
        )
        assert r.status_code == 200, r.text
        assert n1.scanner.usage.last_update == 0  # B never scanned
        r = c1.put_object("qbkt", "big", b"y" * 8192)
        assert r.status_code == 400 and b"XMinioAdminBucketQuotaExceeded" in r.content
        assert c1.put_object("qbkt", "small", b"z" * 1024).status_code == 200


class TestClusterProfiling:
    """Profile start broadcasts to peers; stop returns one dump per node
    (admin-handlers.go:511-716 peer broadcast + per-node zip)."""

    def test_profile_all_nodes(self, cluster):
        import io
        import zipfile

        c0 = cluster["clients"][0]
        r = c0.request("POST", "/mtpu/admin/v1/profile/start")
        assert r.status_code == 200, r.text
        # In-process test cluster: cProfile is interpreter-global, so the
        # co-hosted peer may refuse (real deployments are one process per
        # node); the local profile always starts and the response still
        # carries one zip entry per node.
        assert "local" in r.json()["nodes"]
        try:
            c0.request("GET", "/")  # some work
        finally:
            r = c0.request("POST", "/mtpu/admin/v1/profile/stop")
        assert r.status_code == 200
        z = zipfile.ZipFile(io.BytesIO(r.content))
        names = z.namelist()
        assert len(names) == 2 and any(n.startswith("local/") for n in names)
        assert "cumulative" in z.read([n for n in names if n.startswith("local/")][0]).decode()


class TestDynamicTimeout:
    """Self-tuning channel timeout (cmd/dynamic-timeouts.go:36 semantics)."""

    def test_adjusts_both_ways(self):
        from minio_tpu.dist.transport import DynamicTimeout

        dt = DynamicTimeout(30.0, minimum=1.0)
        # 16 fast successes: shrinks halfway toward 1.25x the slowest.
        for _ in range(16):
            dt.log_success(0.08)
        assert dt.timeout() == pytest.approx((30.0 + 0.1) / 2)
        # Sustained failures (> 33%): grows 25% per window.
        before = dt.timeout()
        for _ in range(16):
            dt.log_failure()
        assert dt.timeout() == pytest.approx(before * 1.25)
        # Sustained fast successes converge exactly to the floor.
        for _ in range(200):
            dt.log_success(0.01)
        assert dt.timeout() == pytest.approx(1.0)

    def test_mid_band_failure_rate_leaves_timeout_unchanged(self):
        """10-33% failures is the hysteresis band: neither grow nor shrink,
        so a channel with occasional blips doesn't flap between sizes."""
        from minio_tpu.dist.transport import DynamicTimeout

        dt = DynamicTimeout(30.0, minimum=1.0)
        # 3/16 = 18.75% failures -- inside (10%, 33%).
        for i in range(16):
            if i % 6 == 0:
                dt.log_failure()
            else:
                dt.log_success(0.05)
        assert dt.timeout() == pytest.approx(30.0)
        # The band holds across repeated windows, not just the first.
        for i in range(32):
            if i % 8 == 0:
                dt.log_failure()  # 12.5% failures
            else:
                dt.log_success(0.05)
        assert dt.timeout() == pytest.approx(30.0)

    def test_rest_client_uses_tuned_timeout(self, cluster):
        node0 = cluster["nodes"][0]
        peer = PeerClient(cluster["urls"][1], node0.token)
        for _ in range(20):
            assert peer.ping()
        # 16+ fast pings tuned the /ping endpoint's own timeout downward;
        # other endpoints are untouched (per-endpoint tuners).
        tuner = peer.client._tuners["/ping"]
        assert tuner.timeout() < peer.client.timeout
        assert "/serverinfo" not in peer.client._tuners
