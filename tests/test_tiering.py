"""ILM tiering tests: remote tiers, transition, tiered reads, restore,
deferred remote deletes.

The analogue of the reference's tier + lifecycle-transition coverage
(cmd/tier.go TierConfigMgr, cmd/bucket-lifecycle.go transition/restore,
cmd/tier-journal.go): transition frees local shard data, reads stream from
the tier, RestoreObject materializes a temporary local copy, deletes journal
the remote object for reclamation.
"""

import json
import os
from types import SimpleNamespace

import pytest

from minio_tpu.api.server import ThreadedServer
from minio_tpu.control import tiering as tiering_mod
from minio_tpu.dist.node import Node
from tests.s3client import S3TestClient
from tests.test_dist import _free_port

ROOT = "tierroot1"
SECRET = "tier-secret-key1"
ADMIN = "/mtpu/admin/v1"

BIG = os.urandom(256 * 1024)  # above the 128 KiB inline threshold


@pytest.fixture(scope="module")
def srv(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("tiersrv")
    node = Node([str(tmp / f"d{i}") for i in range(4)], root_user=ROOT, root_password=SECRET)
    port = _free_port()
    ts = ThreadedServer(SimpleNamespace(app=node.make_app()), port=port)
    ts.start()
    node.build()
    client = S3TestClient(f"http://127.0.0.1:{port}", ROOT, SECRET)
    tier_dir = str(tmp / "coldstore")
    r = client.request(
        "POST",
        f"{ADMIN}/tiers",
        body=json.dumps({"name": "COLD", "type": "fs", "dir": tier_dir, "prefix": "x/"}).encode(),
    )
    assert r.status_code == 200, r.text
    yield {"client": client, "node": node, "tier_dir": tier_dir, "tmp": tmp,
           "url": f"http://127.0.0.1:{port}"}
    ts.stop()


def _local_part_files(node, bucket, key):
    out = []
    for d in node.local_drives.values():
        obj_dir = os.path.join(d.root, bucket, key)
        if not os.path.isdir(obj_dir):
            continue
        for sub in os.listdir(obj_dir):
            p = os.path.join(obj_dir, sub)
            if os.path.isdir(p):
                out.extend(os.path.join(p, f) for f in os.listdir(p))
    return out


class TestTiering:
    def test_tier_crud(self, srv):
        c = srv["client"]
        tiers = c.request("GET", f"{ADMIN}/tiers").json()
        assert [t["name"] for t in tiers] == ["COLD"]
        assert all("secret_key" not in t for t in tiers)
        # Duplicate add rejected.
        r = c.request(
            "POST", f"{ADMIN}/tiers",
            body=json.dumps({"name": "COLD", "type": "fs", "dir": "/tmp/x"}).encode(),
        )
        assert r.status_code == 400

    def test_transition_frees_local_data_and_reads_from_tier(self, srv):
        c, node = srv["client"], srv["node"]
        assert c.make_bucket("arch").status_code == 200
        assert c.put_object("arch", "big.bin", BIG).status_code == 200
        assert _local_part_files(node, "arch", "big.bin")

        oi = node.tiering.transition(node.pools, "arch", "big.bin", "", "COLD")
        assert tiering_mod.is_transitioned(oi.internal)
        # Local shard files reclaimed; remote copy exists under the prefix.
        assert not _local_part_files(node, "arch", "big.bin")
        remote = [
            os.path.join(dp, f)
            for dp, _, fs in os.walk(srv["tier_dir"])
            for f in fs
        ]
        assert len(remote) == 1 and "/x/" in remote[0]

        # Transparent GET streams from the tier; HEAD shows the tier as the
        # storage class.
        r = c.request("GET", "/arch/big.bin")
        assert r.status_code == 200 and r.content == BIG
        r = c.request("HEAD", "/arch/big.bin")
        assert r.headers["x-amz-storage-class"] == "COLD"

    def test_ranged_read_on_transitioned(self, srv):
        c = srv["client"]
        r = c.request("GET", "/arch/big.bin", headers={"Range": "bytes=100-199"})
        assert r.status_code == 206
        assert r.content == BIG[100:200]

    def test_heal_is_noop_on_transitioned(self, srv):
        node = srv["node"]
        res = node.pools.heal_object("arch", "big.bin")
        assert res.disks_healed == 0

    def test_restore_materializes_local_copy(self, srv):
        c, node = srv["client"], srv["node"]
        r = c.request("POST", "/arch/big.bin", query=[("restore", "")],
                      body=b"<RestoreRequest><Days>2</Days></RestoreRequest>")
        assert r.status_code == 202, r.text
        r = c.request("HEAD", "/arch/big.bin")
        assert 'ongoing-request="false"' in r.headers.get("x-amz-restore", "")
        # Reads now come from the restored copy even if the tier vanishes.
        backend = node.tiering.backend("COLD")
        remote_key = node.pools.get_object_info(
            "arch", "big.bin"
        ).internal[tiering_mod.META_TRANSITION_NAME]
        blob = backend.get(remote_key)
        backend.delete(remote_key)
        r = c.request("GET", "/arch/big.bin")
        assert r.status_code == 200 and r.content == BIG
        backend.put(remote_key, blob)  # put back for later tests
        # Second restore refreshes -> 200.
        r = c.request("POST", "/arch/big.bin", query=[("restore", "")],
                      body=b"<RestoreRequest><Days>1</Days></RestoreRequest>")
        assert r.status_code == 200

    def test_delete_journals_remote_reclamation(self, srv):
        c, node = srv["client"], srv["node"]
        assert c.put_object("arch", "doomed.bin", BIG).status_code == 200
        node.tiering.transition(node.pools, "arch", "doomed.bin", "", "COLD")
        remote_key = node.pools.get_object_info(
            "arch", "doomed.bin"
        ).internal[tiering_mod.META_TRANSITION_NAME]
        backend = node.tiering.backend("COLD")
        assert backend.get(remote_key)  # exists remotely
        assert c.request("DELETE", "/arch/doomed.bin").status_code == 204
        assert node.tiering.drain_journal() == 1
        with pytest.raises(Exception):
            backend.get(remote_key)

    def test_lifecycle_transition_via_scanner(self, srv):
        c, node = srv["client"], srv["node"]
        assert c.make_bucket("ilmbkt").status_code == 200
        lc = (
            '<LifecycleConfiguration xmlns="http://s3.amazonaws.com/doc/2006-03-01/">'
            "<Rule><ID>t</ID><Status>Enabled</Status><Filter><Prefix></Prefix></Filter>"
            "<Transition><Days>0</Days><StorageClass>COLD</StorageClass></Transition>"
            "</Rule></LifecycleConfiguration>"
        )
        assert c.request("PUT", "/ilmbkt", query=[("lifecycle", "")], body=lc.encode()).status_code == 200
        assert c.put_object("ilmbkt", "aging.bin", BIG).status_code == 200
        node.scanner.scan_cycle()
        oi = node.pools.get_object_info("ilmbkt", "aging.bin")
        assert tiering_mod.is_transitioned(oi.internal)
        assert node.scanner.objects_transitioned >= 1
        r = c.request("GET", "/ilmbkt/aging.bin")
        assert r.status_code == 200 and r.content == BIG

    def test_s3_tier_to_second_cluster(self, srv, tmp_path_factory):
        """Tier of type "s3": cold data lands in another cluster's bucket."""
        tmp = tmp_path_factory.mktemp("tierdst")
        dnode = Node([str(tmp / f"d{i}") for i in range(4)], root_user=ROOT, root_password=SECRET)
        port = _free_port()
        dts = ThreadedServer(SimpleNamespace(app=dnode.make_app()), port=port)
        dts.start()
        dnode.build()
        dc = S3TestClient(f"http://127.0.0.1:{port}", ROOT, SECRET)
        assert dc.make_bucket("coldbkt").status_code == 200
        try:
            c, node = srv["client"], srv["node"]
            r = c.request(
                "POST",
                f"{ADMIN}/tiers",
                body=json.dumps(
                    {
                        "name": "REMOTE",
                        "type": "s3",
                        "endpoint": f"http://127.0.0.1:{port}",
                        "bucket": "coldbkt",
                        "access_key": ROOT,
                        "secret_key": SECRET,
                    }
                ).encode(),
            )
            assert r.status_code == 200, r.text
            assert c.put_object("arch", "tos3.bin", BIG).status_code == 200
            node.tiering.transition(node.pools, "arch", "tos3.bin", "", "REMOTE")
            # Bytes are in the second cluster now.
            listing = dc.request("GET", "/coldbkt")
            assert listing.status_code == 200
            r = c.request("GET", "/arch/tos3.bin")
            assert r.status_code == 200 and r.content == BIG
        finally:
            dts.stop()

    def test_sealed_tier_secrets_at_rest(self, srv):
        pytest.importorskip(
            "cryptography", reason="node boots KMS-less without the crypto backend"
        )
        node = srv["node"]
        raw = node.pools and node.tiering.store.get(tiering_mod.CONFIG_PATH)
        assert raw is not None
        doc = json.loads(raw)
        remote = [t for t in doc if t["name"] == "REMOTE"]
        if remote:
            assert remote[0]["secret_key"].startswith("sealed:")
            assert SECRET not in json.dumps(remote)


def test_copy_of_transitioned_object(srv):
    """CopyObject with a transitioned source must stream it back from the
    tier (the GET path's discipline) instead of 5xx-ing on freed local
    shards; the destination lands as a normal local object."""
    node, c = srv["node"], srv["client"]
    assert c.make_bucket("arch").status_code in (200, 409)  # own setup
    body = os.urandom(200 * 1024)
    c.put_object("arch", "cp-tiered.bin", body)
    node.tiering.transition(node.pools, "arch", "cp-tiered.bin", "", "COLD")
    r = c.request("PUT", "/arch/cp-tiered-dst.bin",
                  headers={"x-amz-copy-source": "/arch/cp-tiered.bin"})
    assert r.status_code == 200, r.text
    assert c.get_object("arch", "cp-tiered-dst.bin").content == body
    oi = node.pools.get_object_info("arch", "cp-tiered-dst.bin")
    assert not tiering_mod.is_transitioned(oi.internal)
    # the source stays tiered and readable
    assert tiering_mod.is_transitioned(
        node.pools.get_object_info("arch", "cp-tiered.bin").internal
    )
    assert c.get_object("arch", "cp-tiered.bin").content == body


def test_select_on_transitioned_object(srv):
    """S3 Select over a transitioned object recalls it from the tier (the
    shared logical-read path) instead of 5xx-ing on freed shards."""
    node, c = srv["node"], srv["client"]
    assert c.make_bucket("arch").status_code in (200, 409)
    csv = b"a,b\n" + b"".join(b"%d,%d\n" % (i, i) for i in range(50000))
    c.put_object("arch", "sel.csv", csv)
    node.tiering.transition(node.pools, "arch", "sel.csv", "", "COLD")
    sel = c.request(
        "POST", "/arch/sel.csv", query=[("select", ""), ("select-type", "2")],
        body=b"""<?xml version="1.0"?><SelectObjectContentRequest>
          <Expression>SELECT count(*) FROM S3Object</Expression>
          <ExpressionType>SQL</ExpressionType>
          <InputSerialization><CSV><FileHeaderInfo>USE</FileHeaderInfo></CSV></InputSerialization>
          <OutputSerialization><CSV/></OutputSerialization>
        </SelectObjectContentRequest>""",
    )
    assert sel.status_code == 200, sel.text
    from minio_tpu.s3select import decode_messages

    recs = b"".join(
        m["payload"] for m in decode_messages(sel.content)
        if m["headers"].get(":event-type") == "Records"
    )
    assert recs.strip() == b"50000", recs
