"""mtpulint rule/engine tests: every rule has a firing and a non-firing
fixture, plus suppression- and baseline-handling coverage.

Fixtures are tiny synthetic trees under tmp_path (the engine resolves
relpaths against whatever root it is given), so each test pins exactly one
behavior without depending on the real minio_tpu sources. The real tree is
gated separately by tests/test_static_analysis.py."""

from __future__ import annotations

import textwrap

from tools.mtpulint import (
    apply_baseline,
    format_baseline,
    lint_tree,
    load_baseline,
)
from tools.mtpulint.rules import (
    CondWaitLoopRule,
    DeadlineRebindRule,
    DoubleReleaseRule,
    HotPathCopyRule,
    InterfaceConformanceRule,
    LockBlockingIORule,
    LockOrderRule,
    MetricsRenderedRule,
    RawTransportRule,
    ReleaseOnAllPathsRule,
    ResourceLeakRule,
    SharedPublishRule,
    StageKeyRule,
    SwallowedExceptRule,
    TypedErrorsRule,
    UnjoinedThreadRule,
    UnlockedGlobalRule,
    UnsyncedCommitRule,
    ViewEscapeRule,
)


def run_rule(tmp_path, files: dict[str, str], rule) -> list:
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src).lstrip("\n"))
    return lint_tree(str(tmp_path), ["minio_tpu"], [rule])


# -- swallowed-except ---------------------------------------------------------


def test_swallowed_except_fires_on_silent_broad_handler(tmp_path):
    findings = run_rule(tmp_path, {
        "minio_tpu/api/x.py": """
            def f():
                try:
                    g()
                except Exception:
                    pass
        """,
    }, SwallowedExceptRule())
    assert [f.rule for f in findings] == ["swallowed-except"]
    assert findings[0].line == 4


def test_swallowed_except_fires_on_bare_except_and_bare_return(tmp_path):
    findings = run_rule(tmp_path, {
        "minio_tpu/dist/x.py": """
            def f():
                try:
                    g()
                except:
                    return
        """,
    }, SwallowedExceptRule())
    assert len(findings) == 1 and "bare except" in findings[0].message


def test_swallowed_except_quiet_when_narrow_or_observable(tmp_path):
    findings = run_rule(tmp_path, {
        "minio_tpu/api/x.py": """
            def f(log):
                try:
                    g()
                except ValueError:
                    pass
                try:
                    g()
                except Exception:
                    log.warning("g failed")
                try:
                    g()
                except Exception:
                    raise
        """,
    }, SwallowedExceptRule())
    assert findings == []


def test_swallowed_except_ignores_cold_paths(tmp_path):
    findings = run_rule(tmp_path, {
        "minio_tpu/control/x.py": """
            def f():
                try:
                    g()
                except Exception:
                    pass
        """,
    }, SwallowedExceptRule())
    assert findings == []


# -- raw-transport ------------------------------------------------------------


def test_raw_transport_fires_on_import_and_call(tmp_path):
    findings = run_rule(tmp_path, {
        "minio_tpu/dist/peer.py": """
            import requests

            def f(url):
                return requests.get(url)
        """,
    }, RawTransportRule())
    assert [f.line for f in findings] == [1, 4]
    assert all(f.rule == "raw-transport" for f in findings)


def test_raw_transport_allows_transport_py_itself(tmp_path):
    findings = run_rule(tmp_path, {
        "minio_tpu/dist/transport.py": """
            import requests
            import socket
        """,
    }, RawTransportRule())
    assert findings == []


# -- deadline-rebind ----------------------------------------------------------


def test_deadline_rebind_fires_when_transport_loses_markers(tmp_path):
    findings = run_rule(tmp_path, {
        "minio_tpu/dist/transport.py": """
            def call(url):
                return url
        """,
    }, DeadlineRebindRule())
    msgs = " ".join(f.message for f in findings)
    assert len(findings) == 3
    assert "deadline.remaining()" in msgs
    assert "DEADLINE_HEADER" in msgs
    assert "DeadlineExceeded" in msgs


def test_deadline_rebind_fires_on_server_without_bind(tmp_path):
    findings = run_rule(tmp_path, {
        "minio_tpu/dist/some_rest.py": """
            def handler(request):
                tok = request.headers.get(TOKEN_HEADER)
                return tok
        """,
    }, DeadlineRebindRule())
    assert len(findings) == 1
    assert "bind_header" in findings[0].message


def test_deadline_rebind_quiet_on_complete_plumbing(tmp_path):
    findings = run_rule(tmp_path, {
        "minio_tpu/dist/transport.py": """
            def call(headers, deadline):
                if deadline.remaining() <= 0:
                    raise DeadlineExceeded("spent")
                headers[DEADLINE_HEADER] = "1.5"
        """,
        "minio_tpu/dist/some_rest.py": """
            def handler(request):
                tok = request.headers.get(TOKEN_HEADER)
                deadline.bind_header(request.headers.get("X-Mtpu-Deadline"))
                return tok
        """,
    }, DeadlineRebindRule())
    assert findings == []


# -- lock-blocking-io ---------------------------------------------------------


def test_lock_blocking_io_fires_on_sleep_and_open_under_lock(tmp_path):
    findings = run_rule(tmp_path, {
        "minio_tpu/storage/x.py": """
            import time

            def f(self, path):
                with self._lock:
                    time.sleep(1)
                    fh = open(path)
                return fh
        """,
    }, LockBlockingIORule())
    assert sorted(f.line for f in findings) == [5, 6]


def test_lock_blocking_io_quiet_outside_lock_or_in_nested_def(tmp_path):
    findings = run_rule(tmp_path, {
        "minio_tpu/dist/x.py": """
            import time

            def f(self, pool):
                time.sleep(1)
                with self._lock:
                    def deferred():
                        time.sleep(1)
                    pool.submit(deferred)
                with self.items:
                    time.sleep(1)
        """,
    }, LockBlockingIORule())
    assert findings == []


# -- resource-leak ------------------------------------------------------------


def test_resource_leak_fires_on_unclosed_open(tmp_path):
    findings = run_rule(tmp_path, {
        "minio_tpu/storage/x.py": """
            def f(path):
                fh = open(path)
                return fh.name
        """,
    }, ResourceLeakRule())
    assert [f.rule for f in findings] == ["resource-leak"]


def test_resource_leak_quiet_on_with_finally_and_escape(tmp_path):
    findings = run_rule(tmp_path, {
        "minio_tpu/storage/x.py": """
            def ok_with(path):
                with open(path) as f:
                    return f.read()

            def ok_finally(path):
                f = open(path)
                try:
                    return f.read()
                finally:
                    f.close()

            def ok_escape(path):
                return open(path)

            def ok_handoff(path, sink):
                sink.adopt(open(path))
        """,
    }, ResourceLeakRule())
    assert findings == []


# -- stage-key ----------------------------------------------------------------

_PERF_FIXTURE = """
    STAGES = frozenset({("api", "auth"), ("object", "encode")})
    DYNAMIC_STAGE_LAYERS = frozenset({"rpc"})
"""


def test_stage_key_fires_on_unregistered_literal(tmp_path):
    findings = run_rule(tmp_path, {
        "minio_tpu/control/perf.py": _PERF_FIXTURE,
        "minio_tpu/object/x.py": """
            def f():
                with tracing.span("typo-stage", "api"):
                    pass
        """,
    }, StageKeyRule())
    assert len(findings) == 1
    assert "('api', 'typo-stage')" in findings[0].message


def test_stage_key_quiet_on_registered_and_dynamic(tmp_path):
    findings = run_rule(tmp_path, {
        "minio_tpu/control/perf.py": _PERF_FIXTURE,
        "minio_tpu/object/x.py": """
            def f(GLOBAL_PERF, name):
                with tracing.span("auth", "api"):
                    pass
                GLOBAL_PERF.ledger.record("rpc", name, 0.1)
                GLOBAL_PERF.ledger.record("rpc", "peer-call", 0.1)
        """,
    }, StageKeyRule())
    assert findings == []


def test_stage_key_reports_missing_registry(tmp_path):
    findings = run_rule(tmp_path, {
        "minio_tpu/control/perf.py": "X = 1\n",
    }, StageKeyRule())
    assert len(findings) == 1
    assert "registry literal not found" in findings[0].message


# -- metrics-rendered ---------------------------------------------------------

_DEGRADE_FIXTURE = """
    class DegradeStats:
        def hit(self):
            self.mystery_counter += 1
"""


def test_metrics_rendered_fires_on_unexported_counter(tmp_path):
    findings = run_rule(tmp_path, {
        "minio_tpu/control/degrade.py": _DEGRADE_FIXTURE,
        "minio_tpu/control/metrics.py": "def render():\n    return ''\n",
    }, MetricsRenderedRule())
    assert len(findings) == 1
    assert "'mystery_counter'" in findings[0].message


def test_metrics_rendered_quiet_when_rendered(tmp_path):
    findings = run_rule(tmp_path, {
        "minio_tpu/control/degrade.py": _DEGRADE_FIXTURE,
        "minio_tpu/control/metrics.py": """
            def render(snap):
                return snap["mystery_counter"]
        """,
    }, MetricsRenderedRule())
    assert findings == []


# -- typed-errors -------------------------------------------------------------


def test_typed_errors_fires_on_untyped_raise(tmp_path):
    findings = run_rule(tmp_path, {
        "minio_tpu/api/x.py": """
            def f():
                raise Exception("boom")

            def g():
                raise RuntimeError("boom")
        """,
    }, TypedErrorsRule())
    assert sorted(f.line for f in findings) == [2, 5]


def test_typed_errors_quiet_on_typed_raise(tmp_path):
    findings = run_rule(tmp_path, {
        "minio_tpu/api/x.py": """
            def f():
                raise S3Error("NoSuchKey")
        """,
    }, TypedErrorsRule())
    assert findings == []


# -- unlocked-global ----------------------------------------------------------


def test_unlocked_global_fires_on_bare_mutation(tmp_path):
    findings = run_rule(tmp_path, {
        "minio_tpu/models/x.py": """
            _CACHE = {}

            def put(k, v):
                _CACHE[k] = v
        """,
    }, UnlockedGlobalRule())
    assert [f.rule for f in findings] == ["unlocked-global"]


def test_unlocked_global_quiet_when_locked_or_marked(tmp_path):
    findings = run_rule(tmp_path, {
        "minio_tpu/models/x.py": """
            import threading

            _CACHE = {}
            _CACHE_LOCK = threading.Lock()
            _TABLE = {"a": 1}  # mtpulint: immutable -- built once at import

            def put(k, v):
                with _CACHE_LOCK:
                    _CACHE[k] = v

            def get(k):
                return _TABLE.get(k)
        """,
    }, UnlockedGlobalRule())
    assert findings == []


# -- suppressions -------------------------------------------------------------

_SWALLOW = """
    def f():
        try:
            g()
        except Exception:{inline}
            pass
"""


def test_inline_suppression_same_line(tmp_path):
    findings = run_rule(tmp_path, {
        "minio_tpu/api/x.py": _SWALLOW.format(
            inline="  # mtpulint: disable=swallowed-except"
        ),
    }, SwallowedExceptRule())
    assert findings == []


def test_suppression_comment_above_with_justification(tmp_path):
    findings = run_rule(tmp_path, {
        "minio_tpu/api/x.py": """
            def f():
                try:
                    g()
                # mtpulint: disable=swallowed-except -- g() is fire-and-forget
                # and failures are observed by its own retry loop.
                except Exception:
                    pass
        """,
    }, SwallowedExceptRule())
    assert findings == []


def test_suppression_for_other_rule_does_not_hide(tmp_path):
    findings = run_rule(tmp_path, {
        "minio_tpu/api/x.py": _SWALLOW.format(
            inline="  # mtpulint: disable=typed-errors"
        ),
    }, SwallowedExceptRule())
    assert len(findings) == 1


def test_file_level_suppression(tmp_path):
    findings = run_rule(tmp_path, {
        "minio_tpu/api/x.py": "# mtpulint: disable-file=swallowed-except\n"
        + textwrap.dedent(_SWALLOW.format(inline="")),
    }, SwallowedExceptRule())
    assert findings == []


def test_parse_error_is_reported_as_finding(tmp_path):
    findings = run_rule(tmp_path, {
        "minio_tpu/api/x.py": "def f(:\n",
    }, SwallowedExceptRule())
    assert [f.rule for f in findings] == ["parse-error"]


# -- baseline -----------------------------------------------------------------


def _mk(relpath, rule, line):
    from tools.mtpulint import Finding

    return Finding(rule=rule, relpath=relpath, line=line, message="m")


def test_load_baseline_parses_and_skips_junk(tmp_path):
    p = tmp_path / "baseline.txt"
    p.write_text(
        "# comment\n"
        "\n"
        "minio_tpu/api/x.py::swallowed-except::2\n"
        "not-a-valid-line\n"
        "minio_tpu/api/x.py::swallowed-except::1\n"  # additive duplicate
    )
    assert load_baseline(str(p)) == {("minio_tpu/api/x.py", "swallowed-except"): 3}


def test_load_baseline_missing_file_is_empty(tmp_path):
    assert load_baseline(str(tmp_path / "nope.txt")) == {}


def test_apply_baseline_grandfathers_up_to_quota(tmp_path):
    findings = [
        _mk("a.py", "r", 1),
        _mk("a.py", "r", 5),
        _mk("a.py", "r", 9),
    ]
    new, stale = apply_baseline(findings, {("a.py", "r"): 2})
    assert [f.line for f in new] == [9]
    assert stale == []


def test_apply_baseline_reports_stale_entries(tmp_path):
    new, stale = apply_baseline([_mk("a.py", "r", 1)], {("a.py", "r"): 3})
    assert new == []
    assert len(stale) == 1 and "shrink the baseline" in stale[0]


def test_format_baseline_round_trips(tmp_path):
    findings = [_mk("a.py", "r", 1), _mk("a.py", "r", 2), _mk("b.py", "q", 7)]
    text = format_baseline(findings, header="# hdr")
    p = tmp_path / "baseline.txt"
    p.write_text(text)
    assert load_baseline(str(p)) == {("a.py", "r"): 2, ("b.py", "q"): 1}


# -- lock-order ---------------------------------------------------------------


_SAN_WITH_ORDER = """
    LOCK_ORDER = (
        "A._outer_lock",
        "A._inner_lock",
    )
"""


def test_lock_order_fires_on_declared_order_violation(tmp_path):
    findings = run_rule(tmp_path, {
        "minio_tpu/control/sanitizer.py": _SAN_WITH_ORDER,
        "minio_tpu/storage/x.py": """
            class A:
                def f(self):
                    with self._inner_lock:
                        with self._outer_lock:
                            pass
        """,
    }, LockOrderRule())
    assert [f.rule for f in findings] == ["lock-order"]
    assert "LOCK_ORDER" in findings[0].message


def test_lock_order_quiet_when_nesting_matches_declaration(tmp_path):
    findings = run_rule(tmp_path, {
        "minio_tpu/control/sanitizer.py": _SAN_WITH_ORDER,
        "minio_tpu/storage/x.py": """
            class A:
                def f(self):
                    with self._outer_lock:
                        with self._inner_lock:
                            pass
        """,
    }, LockOrderRule())
    assert findings == []


def test_lock_order_detects_cross_module_cycle(tmp_path):
    # a.py takes X then Y; b.py takes Y then X -- a cycle even with no
    # declared order covering either lock.
    findings = run_rule(tmp_path, {
        "minio_tpu/dist/a.py": """
            class P:
                def f(self):
                    with self._x_lock:
                        with self._y_lock:
                            pass
        """,
        "minio_tpu/dist/b.py": """
            class P:
                def g(self):
                    with self._y_lock:
                        with self._x_lock:
                            pass
        """,
    }, LockOrderRule())
    assert len(findings) == 1
    assert "cycle" in findings[0].message


def test_lock_order_ignores_non_lock_context_managers(tmp_path):
    findings = run_rule(tmp_path, {
        "minio_tpu/dist/a.py": """
            class P:
                def f(self):
                    with self.session:
                        with self._x_lock:
                            pass
                def g(self):
                    with self._x_lock:
                        with self.session:
                            pass
        """,
    }, LockOrderRule())
    assert findings == []


def test_lock_order_nested_def_resets_held_stack(tmp_path):
    # The inner function body runs later, not under the outer with.
    findings = run_rule(tmp_path, {
        "minio_tpu/dist/a.py": """
            class P:
                def f(self):
                    with self._x_lock:
                        def cb():
                            with self._y_lock:
                                pass
                        return cb
                def g(self):
                    with self._y_lock:
                        with self._x_lock:
                            pass
        """,
    }, LockOrderRule())
    assert findings == []


# -- unjoined-thread ----------------------------------------------------------


def test_unjoined_thread_fires_without_stop_path(tmp_path):
    findings = run_rule(tmp_path, {
        "minio_tpu/control/x.py": """
            import threading

            class W:
                def start(self):
                    self._t = threading.Thread(target=self._run, daemon=True)
                    self._t.start()
        """,
    }, UnjoinedThreadRule())
    assert [f.rule for f in findings] == ["unjoined-thread"]


def test_unjoined_thread_quiet_when_class_stop_joins(tmp_path):
    findings = run_rule(tmp_path, {
        "minio_tpu/control/x.py": """
            import threading

            class W:
                def start(self):
                    self._t = threading.Thread(target=self._run, daemon=True)
                    self._t.start()

                def stop(self):
                    self._t.join(timeout=5.0)
        """,
    }, UnjoinedThreadRule())
    assert findings == []


def test_unjoined_thread_quiet_when_joined_in_same_function(tmp_path):
    findings = run_rule(tmp_path, {
        "minio_tpu/control/x.py": """
            import threading

            def scatter(fns):
                ts = [threading.Thread(target=f, daemon=True) for f in fns]
                for t in ts:
                    t.start()
                for t in ts:
                    t.join()
        """,
    }, UnjoinedThreadRule())
    assert findings == []


def test_unjoined_thread_ignores_non_daemon(tmp_path):
    findings = run_rule(tmp_path, {
        "minio_tpu/control/x.py": """
            import threading

            class W:
                def start(self):
                    self._t = threading.Thread(target=self._run)
                    self._t.start()
        """,
    }, UnjoinedThreadRule())
    assert findings == []


def test_unjoined_thread_inline_suppression(tmp_path):
    findings = run_rule(tmp_path, {
        "minio_tpu/control/x.py": """
            import threading

            class W:
                def start(self):
                    # mtpulint: disable=unjoined-thread -- process-lifetime
                    # singleton by design.
                    self._t = threading.Thread(target=self._run, daemon=True)
                    self._t.start()
        """,
    }, UnjoinedThreadRule())
    assert findings == []


# -- cond-wait-loop -----------------------------------------------------------


def test_cond_wait_loop_fires_on_bare_wait(tmp_path):
    findings = run_rule(tmp_path, {
        "minio_tpu/parallel/x.py": """
            import threading

            class Q:
                def __init__(self):
                    self._cv = threading.Condition()

                def get(self):
                    with self._cv:
                        if not self.items:
                            self._cv.wait()
                        return self.items.pop()
        """,
    }, CondWaitLoopRule())
    assert [f.rule for f in findings] == ["cond-wait-loop"]


def test_cond_wait_loop_quiet_inside_while(tmp_path):
    findings = run_rule(tmp_path, {
        "minio_tpu/parallel/x.py": """
            import threading

            class Q:
                def __init__(self):
                    self._cv = threading.Condition()

                def get(self):
                    with self._cv:
                        while not self.items:
                            self._cv.wait()
                        return self.items.pop()
        """,
    }, CondWaitLoopRule())
    assert findings == []


def test_cond_wait_loop_exempts_wait_for_and_events(tmp_path):
    findings = run_rule(tmp_path, {
        "minio_tpu/parallel/x.py": """
            import threading

            class Q:
                def __init__(self):
                    self._cv = threading.Condition()
                    self._stop = threading.Event()

                def get(self):
                    with self._cv:
                        self._cv.wait_for(lambda: self.items)
                    self._stop.wait()
        """,
    }, CondWaitLoopRule())
    assert findings == []


# -- shared-publish -----------------------------------------------------------


def test_shared_publish_fires_on_unlocked_augassign_in_worker(tmp_path):
    findings = run_rule(tmp_path, {
        "minio_tpu/control/x.py": """
            import threading

            class W:
                def start(self):
                    t = threading.Thread(target=self._run, daemon=True)
                    t.start()

                def _run(self):
                    self.count += 1
        """,
    }, SharedPublishRule())
    assert [f.rule for f in findings] == ["shared-publish"]
    assert "self.count" in findings[0].message


def test_shared_publish_quiet_under_lock(tmp_path):
    findings = run_rule(tmp_path, {
        "minio_tpu/control/x.py": """
            import threading

            class W:
                def start(self):
                    t = threading.Thread(target=self._run, daemon=True)
                    t.start()

                def _run(self):
                    with self._lock:
                        self.count += 1
        """,
    }, SharedPublishRule())
    assert findings == []


def test_shared_publish_follows_helper_calls(tmp_path):
    # _run -> self._tick(): the AugAssign lives in a helper reached only
    # transitively from the thread target.
    findings = run_rule(tmp_path, {
        "minio_tpu/control/x.py": """
            import threading

            class W:
                def start(self):
                    t = threading.Thread(target=self._run, daemon=True)
                    t.start()

                def _run(self):
                    self._tick()

                def _tick(self):
                    self.stats["n"] += 1
        """,
    }, SharedPublishRule())
    assert len(findings) == 1
    assert "self.stats[...]" in findings[0].message


def test_shared_publish_exempts_atomic_publishes_and_request_path(tmp_path):
    findings = run_rule(tmp_path, {
        "minio_tpu/control/x.py": """
            import threading

            class W:
                def start(self):
                    t = threading.Thread(target=self._run, daemon=True)
                    t.start()

                def _run(self):
                    self.last = 1          # plain assignment: atomic publish
                    self.items.append(2)   # append: atomic under the GIL

                def serve(self):
                    self.requests += 1     # not reachable from the worker
        """,
    }, SharedPublishRule())
    assert findings == []

# -- hot-path-copy ------------------------------------------------------------


def test_hot_path_copy_fires_on_bytes_join_and_augassign(tmp_path):
    findings = run_rule(tmp_path, {
        "minio_tpu/object/erasure.py": """
            def f(view, parts):
                blob = bytes(view)
                joined = b"".join(parts)
                out = bytearray()
                for p in parts:
                    out += p
                return blob, joined, out
        """,
    }, HotPathCopyRule())
    assert [f.rule for f in findings] == ["hot-path-copy"] * 3
    assert sorted(f.line for f in findings) == [2, 3, 6]


def test_hot_path_copy_quiet_on_text_allocs_and_counters(tmp_path):
    findings = run_rule(tmp_path, {
        "minio_tpu/api/streaming.py": """
            import os

            def f(raw, names, blocks):
                header = bytes(raw[:12]).decode("latin-1")   # text parse
                zeros = bytes(64)                            # alloc, not a copy
                path = os.path.join("a", "b")                # not a byte join
                csv = ",".join(names)                        # str join
                total = 0
                for b in blocks:
                    total += len(b)                          # int counter
                return header, zeros, path, csv, total
        """,
    }, HotPathCopyRule())
    assert findings == []


def test_hot_path_copy_augassign_tracks_per_scope_accumulators(tmp_path):
    findings = run_rule(tmp_path, {
        "minio_tpu/storage/local.py": """
            def f(parts):
                out = []
                for p in parts:
                    out += [p]
                return out

            def g(parts):
                out = b""
                for p in parts:
                    out += p
                return out
        """,
    }, HotPathCopyRule())
    assert [f.rule for f in findings] == ["hot-path-copy"]
    assert findings[0].line == 10


def test_hot_path_copy_scoped_to_data_plane_files(tmp_path):
    findings = run_rule(tmp_path, {
        "minio_tpu/control/metrics.py": """
            def f(view):
                return bytes(view)
        """,
    }, HotPathCopyRule())
    assert findings == []


def test_hot_path_copy_fires_in_memcache(tmp_path):
    # The hot-read tier (object/memcache.py) is GET-path scope: a cache
    # hit that materializes the cached bytes instead of handing out views
    # is exactly the copy the tier exists to avoid.
    findings = run_rule(tmp_path, {
        "minio_tpu/object/memcache.py": """
            def serve(entry):
                buf = bytearray()
                for c in entry.chunks():
                    buf += c
                return bytes(buf)
        """,
    }, HotPathCopyRule())
    assert [f.rule for f in findings] == ["hot-path-copy"] * 2
    assert sorted(f.line for f in findings) == [4, 5]


def test_hot_path_copy_suppressed_with_justification(tmp_path):
    findings = run_rule(tmp_path, {
        "minio_tpu/object/memcache.py": """
            def serve(entry):
                buf = bytearray()
                for c in entry.chunks():
                    buf += c  # mtpulint: disable=hot-path-copy -- buffered convenience API
                return bytes(buf)  # mtpulint: disable=hot-path-copy -- buffered convenience API
        """,
    }, HotPathCopyRule())
    assert findings == []


# -- unsynced-commit ----------------------------------------------------------


def test_unsynced_commit_fires_on_bare_replace(tmp_path):
    findings = run_rule(tmp_path, {
        "minio_tpu/storage/x.py": """
            import os

            def save(p, data):
                tmp = p + ".tmp"
                with open(tmp, "w") as f:
                    f.write(data)
                os.replace(tmp, p)
        """,
    }, UnsyncedCommitRule())
    assert [f.rule for f in findings] == ["unsynced-commit"]
    assert findings[0].line == 7


def test_unsynced_commit_quiet_with_barrier_in_function(tmp_path):
    findings = run_rule(tmp_path, {
        "minio_tpu/storage/x.py": """
            import os

            def save(p, data):
                with open(p + ".tmp", "w") as f:
                    f.write(data)
                    os.fsync(f.fileno())
                os.replace(p + ".tmp", p)

            def rename(self, src, dst):
                self._sync_path(src)
                os.rename(src, dst)
                _sync_dir(dst)
        """,
    }, UnsyncedCommitRule())
    assert findings == []


def test_unsynced_commit_fsync_mode_call_is_not_a_barrier(tmp_path):
    # fsync_mode() only *reads* the knob; it must not satisfy the rule.
    findings = run_rule(tmp_path, {
        "minio_tpu/object/x.py": """
            import os

            def save(p):
                mode = fsync_mode()
                os.replace(p + ".tmp", p)
        """,
    }, UnsyncedCommitRule())
    assert len(findings) == 1


def test_unsynced_commit_nested_def_scopes_are_independent(tmp_path):
    # The outer function's barrier does not cover a nested commit closure:
    # the closure runs later, possibly after the barrier's effect is moot.
    findings = run_rule(tmp_path, {
        "minio_tpu/storage/x.py": """
            import os

            def outer(p, fd):
                os.fsync(fd)

                def commit():
                    os.replace(p + ".tmp", p)
                return commit
        """,
    }, UnsyncedCommitRule())
    assert len(findings) == 1


def test_unsynced_commit_scoped_and_suppressible(tmp_path):
    findings = run_rule(tmp_path, {
        "minio_tpu/control/x.py": """
            import os

            def save(p):
                os.replace(p + ".tmp", p)
        """,
        "minio_tpu/object/y.py": """
            import os

            def save(p):
                # mtpulint: disable=unsynced-commit -- best-effort file
                os.replace(p + ".tmp", p)
        """,
    }, UnsyncedCommitRule())
    assert findings == []


# -- release-on-all-paths -----------------------------------------------------


def test_release_on_all_paths_fires_when_never_released(tmp_path):
    findings = run_rule(tmp_path, {
        "minio_tpu/object/x.py": """
            def f(pool, data):
                pb = pool.acquire()
                fill(data, pb.view())
        """,
    }, ReleaseOnAllPathsRule())
    assert [f.rule for f in findings] == ["release-on-all-paths"]
    assert "never released" in findings[0].message
    assert findings[0].line == 2


def test_release_on_all_paths_fires_on_straight_line_release(tmp_path):
    findings = run_rule(tmp_path, {
        "minio_tpu/object/x.py": """
            def f(pool, data):
                pb = pool.acquire()
                fill(data, pb.view())  # a raise here leaks the window
                pb.release()
        """,
    }, ReleaseOnAllPathsRule())
    assert len(findings) == 1
    assert "straight-line" in findings[0].message


def test_release_on_all_paths_quiet_with_finally_or_handler(tmp_path):
    findings = run_rule(tmp_path, {
        "minio_tpu/object/x.py": """
            def f(pool, data):
                pb = pool.acquire()
                try:
                    fill(data, pb.view())
                finally:
                    pb.release()

            def g(pool, data):
                pb = pool.acquire()
                try:
                    filled = fill(data, pb.view())
                except BaseException:
                    pb.release()
                    raise
                pb.release()
                return filled
        """,
    }, ReleaseOnAllPathsRule())
    assert findings == []


def test_release_on_all_paths_quiet_on_ownership_transfer(tmp_path):
    findings = run_rule(tmp_path, {
        "minio_tpu/object/x.py": """
            def f(pool, data):
                pb = pool.acquire()
                return stream_windows(data, pool, pb)

            def g(pool, bufs):
                pb = pool.acquire()
                bufs.add(pb)
        """,
    }, ReleaseOnAllPathsRule())
    assert findings == []


def test_release_on_all_paths_ignores_locks_and_semaphores(tmp_path):
    findings = run_rule(tmp_path, {
        "minio_tpu/object/x.py": """
            def f(lk, sem):
                got = lk.acquire(writer=True, timeout=30)
                ok = sem.acquire(blocking=False)
        """,
    }, ReleaseOnAllPathsRule())
    assert findings == []


def test_release_on_all_paths_suppressed_with_justification(tmp_path):
    findings = run_rule(tmp_path, {
        "minio_tpu/object/x.py": """
            def f(pool, data):
                # mtpulint: disable=release-on-all-paths -- test harness leak on purpose
                pb = pool.acquire()
                fill(data, pb.view())
        """,
    }, ReleaseOnAllPathsRule())
    assert findings == []


# -- double-release -----------------------------------------------------------


def test_double_release_fires_on_sequential_releases(tmp_path):
    findings = run_rule(tmp_path, {
        "minio_tpu/object/x.py": """
            def f(pool):
                pb = pool.acquire()
                pb.release()
                pb.release()
        """,
    }, DoubleReleaseRule())
    assert [f.rule for f in findings] == ["double-release"]
    assert findings[0].line == 4


def test_double_release_fires_on_unguarded_finally(tmp_path):
    findings = run_rule(tmp_path, {
        "minio_tpu/object/x.py": """
            def f(pool, data):
                pb = pool.acquire()
                try:
                    fill(data, pb.view())
                    pb.release()
                finally:
                    pb.release()
        """,
    }, DoubleReleaseRule())
    assert len(findings) == 1
    assert "finally" in findings[0].message


def test_double_release_quiet_with_none_rebind_guard(tmp_path):
    findings = run_rule(tmp_path, {
        "minio_tpu/object/x.py": """
            def f(pool, bufs, data):
                pb = pool.acquire()
                try:
                    fill(data, pb.view())
                    bufs.add(pb)
                    pb = None
                finally:
                    if pb is not None:
                        pb.release()
        """,
    }, DoubleReleaseRule())
    assert findings == []


def test_double_release_quiet_with_retain_between(tmp_path):
    findings = run_rule(tmp_path, {
        "minio_tpu/object/x.py": """
            def f(pool):
                pb = pool.acquire()
                pb.release()
                pb.retain()
                pb.release()
        """,
    }, DoubleReleaseRule())
    assert findings == []


# -- view-escape --------------------------------------------------------------


def test_view_escape_fires_on_self_assign_and_return(tmp_path):
    findings = run_rule(tmp_path, {
        "minio_tpu/object/x.py": """
            class C:
                def f(self, pool):
                    pb = pool.acquire()
                    v = pb.view(0, 64)
                    self.window = v
                    pb.release()

            def g(pool):
                pb = pool.acquire()
                v = pb.view()
                pb.release()
                return v
        """,
    }, ViewEscapeRule())
    assert [f.rule for f in findings] == ["view-escape", "view-escape"]
    assert findings[0].line == 5
    assert "stored outside" in findings[0].message
    assert "returned" in findings[1].message


def test_view_escape_fires_on_container_append_and_submit(tmp_path):
    findings = run_rule(tmp_path, {
        "minio_tpu/object/x.py": """
            def f(pool, batch, ex):
                pb = pool.acquire()
                batch.append(pb.view(0, 32))
                ex.submit(consume, pb.view(32, 64))
                pb.release()
        """,
    }, ViewEscapeRule())
    assert len(findings) == 2
    assert "container" in findings[0].message
    assert "submit" in findings[1].message


def test_view_escape_fires_on_closure_capture(tmp_path):
    findings = run_rule(tmp_path, {
        "minio_tpu/object/x.py": """
            def f(pool, ex):
                pb = pool.acquire()
                v = pb.view()

                def worker():
                    return consume(v)

                ex.submit(worker)
                pb.release()
        """,
    }, ViewEscapeRule())
    assert len(findings) == 1
    assert "closure" in findings[0].message


def test_view_escape_quiet_with_retain_or_plain_calls(tmp_path):
    findings = run_rule(tmp_path, {
        "minio_tpu/object/x.py": """
            def f(pool, data, batch):
                pb = pool.acquire()
                filled = fill(data, pb.view())   # synchronous use: fine
                pb.retain()
                batch.append(pb.view(0, filled)) # rides the retained buffer
                pb.release()
                return filled
        """,
    }, ViewEscapeRule())
    assert findings == []


def test_view_escape_suppressed_with_justification(tmp_path):
    findings = run_rule(tmp_path, {
        "minio_tpu/object/x.py": """
            def f(pool):
                pb = pool.acquire()
                v = pb.view()
                # mtpulint: disable=view-escape -- caller releases via the window object
                return v
        """,
    }, ViewEscapeRule())
    assert findings == []


# -- interface-conformance ----------------------------------------------------

_IFACE_SRC = """
    import abc

    class StorageAPI(abc.ABC):
        @abc.abstractmethod
        def read_all(self, volume, path): ...

        @abc.abstractmethod
        def write_all(self, volume, path, data): ...

        def read_file_into(self, volume, path, offset, buf):
            return 0
"""


def test_interface_conformance_fires_on_missing_methods(tmp_path):
    findings = run_rule(tmp_path, {
        "minio_tpu/storage/interface.py": _IFACE_SRC,
        "minio_tpu/storage/wrap.py": """
            class PartialWrapper:
                def __init__(self, inner):
                    self.__dict__["inner"] = inner

                def read_all(self, volume, path):
                    return self.inner.read_all(volume, path)
        """,
    }, InterfaceConformanceRule())
    missing = sorted(f.message.split("StorageAPI.")[1].split(" ")[0] for f in findings)
    assert [f.rule for f in findings] == ["interface-conformance"] * 2
    assert missing == ["read_file_into", "write_all"]


def test_interface_conformance_quiet_with_getattr_delegation(tmp_path):
    findings = run_rule(tmp_path, {
        "minio_tpu/storage/interface.py": _IFACE_SRC,
        "minio_tpu/chaos/wrap.py": """
            class Delegating:
                def __init__(self, inner):
                    self.inner = inner

                def __getattr__(self, name):
                    return getattr(self.inner, name)

                def read_all(self, volume, path):
                    return self.inner.read_all(volume, path)
        """,
    }, InterfaceConformanceRule())
    assert findings == []


def test_interface_conformance_ignores_non_wrappers(tmp_path):
    findings = run_rule(tmp_path, {
        "minio_tpu/storage/interface.py": _IFACE_SRC,
        "minio_tpu/storage/other.py": """
            class NotAWrapper:
                def __init__(self, path):
                    self.path = path
        """,
    }, InterfaceConformanceRule())
    assert findings == []
