"""Served traffic routes through the installed data-plane codec.

Round-2 wiring (VERDICT #2): the server boot installs the batching device
codec via runtime.install_data_plane_codec, and a PutObject through the
object layer demonstrably runs the device pipeline (the reference's
equivalent always-on fast codec, cmd/erasure-coding.go:63).
"""

from __future__ import annotations

import numpy as np
import pytest

from minio_tpu import runtime
from minio_tpu.object import codec as codec_mod
from minio_tpu.object.codec import HostCodec
from minio_tpu.parallel.batching import BatchingDeviceCodec

from .harness import ErasureHarness


@pytest.fixture(autouse=True)
def _restore_default_codec():
    prev = codec_mod._default
    yield
    codec_mod.set_default_codec(prev) if prev is not None else None
    codec_mod._default = prev


def test_install_host_mode():
    codec = runtime.install_data_plane_codec(mode="host")
    assert isinstance(codec, HostCodec)
    assert codec_mod.default_codec() is codec


def test_install_auto_falls_back_without_device(monkeypatch):
    monkeypatch.setattr(runtime, "probe_device", lambda t: runtime.ProbeResult(None, error="x"))
    codec = runtime.install_data_plane_codec(mode="auto")
    assert isinstance(codec, HostCodec)


def test_install_auto_cpu_platform_uses_host(monkeypatch):
    monkeypatch.setattr(runtime, "probe_device", lambda t: runtime.ProbeResult("cpu"))
    codec = runtime.install_data_plane_codec(mode="auto")
    assert isinstance(codec, HostCodec)


def test_install_auto_accelerator_uses_batching(monkeypatch):
    monkeypatch.setattr(runtime, "probe_device", lambda t: runtime.ProbeResult("tpu"))
    codec = runtime.install_data_plane_codec(mode="auto")
    try:
        assert isinstance(codec, BatchingDeviceCodec)
    finally:
        runtime.shutdown_data_plane(codec)


def test_put_object_runs_device_pipeline(tmp_path):
    """A served PutObject routes its full blocks through the batching
    pipeline when the device codec is installed -- even on a layer built
    before the install (lazy default-codec resolution)."""
    hz = ErasureHarness(tmp_path, n_disks=8)  # built while HostCodec is default
    codec = runtime.install_data_plane_codec(mode="device")
    try:
        assert isinstance(codec, BatchingDeviceCodec)
        assert hz.layer.codec is codec
        rng = np.random.default_rng(7)
        body = rng.integers(0, 256, (1 << 20) + 4096, dtype=np.uint8).tobytes()
        hz.layer.make_bucket("b")
        hz.layer.put_object("b", "o", body)
        # Warmup may add blocks; the served full block must be among them.
        assert codec.blocks_encoded >= 1
        assert codec.batches_run >= 1
        _, got = hz.layer.get_object("b", "o")
        assert got == body
    finally:
        runtime.shutdown_data_plane(codec)


def test_background_upgrade_reaches_serving_layer(tmp_path, monkeypatch):
    """Auto+background install: boot serves on HostCodec, and when the probe
    lands on an accelerator the layer's lazy codec resolution picks up the
    batching codec for subsequent traffic -- including layers built by
    Node.build() before the upgrade landed."""
    import threading

    from minio_tpu.dist.node import Node

    probe_started = threading.Event()
    probe_release = threading.Event()

    def slow_probe(timeout):
        probe_started.set()
        probe_release.wait(10)
        return runtime.ProbeResult("tpu")

    monkeypatch.setattr(runtime, "probe_device", slow_probe)
    monkeypatch.setenv("MINIO_TPU_CODEC", "auto")
    endpoints = [str(tmp_path / f"d{i}") for i in range(4)]
    node = Node(endpoints, root_user="a" * 8, root_password="b" * 12).build()
    try:
        assert isinstance(node.codec, HostCodec)  # boot never blocked
        layer = node.pools.pools[0].sets[0]
        assert isinstance(layer.codec, HostCodec)
        assert probe_started.wait(5)
        probe_release.set()
        deadline = 10
        import time

        t0 = time.monotonic()
        while not isinstance(codec_mod.default_codec(), BatchingDeviceCodec):
            assert time.monotonic() - t0 < deadline, "upgrade never landed"
            time.sleep(0.05)
        # The SAME layer object now serves through the device codec.
        assert isinstance(layer.codec, BatchingDeviceCodec)
    finally:
        runtime.shutdown_data_plane(node.codec)


def test_node_build_installs_codec(tmp_path, monkeypatch):
    """Node.build() installs the data-plane codec at boot and the layer
    serves through it."""
    from minio_tpu.dist.node import Node

    monkeypatch.setenv("MINIO_TPU_CODEC", "device")
    endpoints = [str(tmp_path / f"d{i}") for i in range(4)]
    node = Node(endpoints, root_user="a" * 8, root_password="b" * 12).build()
    try:
        assert isinstance(node.codec, BatchingDeviceCodec)
        assert codec_mod.default_codec() is node.codec
        layer = node.pools
        rng = np.random.default_rng(9)
        body = rng.integers(0, 256, 1 << 20, dtype=np.uint8).tobytes()
        layer.make_bucket("bkt")
        layer.put_object("bkt", "obj", body)
        assert node.codec.blocks_encoded >= 1
        _, got = layer.get_object("bkt", "obj")
        assert got == body
    finally:
        runtime.shutdown_data_plane(node.codec)


# -- probe verdict transitions (fallback / recovery) --------------------------


@pytest.fixture
def _probe_cache_file(tmp_path, monkeypatch):
    path = str(tmp_path / "probe.json")
    monkeypatch.setenv("MTPU_PROBE_CACHE", path)
    monkeypatch.setattr(runtime, "_last_transition", None)
    return path


def test_probe_store_records_fallback_and_recovery(_probe_cache_file):
    import json

    runtime._store_probe_file(runtime.ProbeResult("tpu", "v5e"))
    with open(_probe_cache_file) as f:
        assert json.load(f)["transition"] is None  # first verdict: no flip

    runtime._store_probe_file(runtime.ProbeResult(None, error="wedged"))
    with open(_probe_cache_file) as f:
        doc = json.load(f)
    assert doc["transition"]["kind"] == "fallback"
    assert doc["transition"]["from"] == "tpu" and doc["transition"]["to"] is None

    runtime._store_probe_file(runtime.ProbeResult("tpu", "v5e"))
    with open(_probe_cache_file) as f:
        doc = json.load(f)
    assert doc["transition"]["kind"] == "recovery"
    assert [t["kind"] for t in doc["transitions"]] == ["fallback", "recovery"]
    # the accessor surfaces the latest flip (bench JSON reads this)
    assert runtime.probe_transition()["kind"] == "recovery"


def test_probe_transition_read_from_file_by_fresh_process(_probe_cache_file, monkeypatch):
    runtime._store_probe_file(runtime.ProbeResult("tpu"))
    runtime._store_probe_file(runtime.ProbeResult(None, error="x"))
    # Simulate a fresh process: no in-memory transition, only the file.
    monkeypatch.setattr(runtime, "_last_transition", None)
    t = runtime.probe_transition()
    assert t is not None and t["kind"] == "fallback"


def test_probe_same_verdict_is_not_a_transition(_probe_cache_file):
    import json

    runtime._store_probe_file(runtime.ProbeResult(None, error="a"))
    runtime._store_probe_file(runtime.ProbeResult("cpu"))  # fail -> cpu: still not ok
    runtime._store_probe_file(runtime.ProbeResult(None, error="b"))
    with open(_probe_cache_file) as f:
        doc = json.load(f)
    assert doc["transitions"] == [] and doc["transition"] is None


def test_probe_transition_counts(monkeypatch):
    monkeypatch.setattr(runtime, "_transition_counts", {"fallback": 0, "recovery": 0})
    monkeypatch.setattr(runtime, "_last_transition", None)
    runtime._note_transition(
        runtime._transition_between("tpu", runtime.ProbeResult(None, error="x"))
    )
    runtime._note_transition(
        runtime._transition_between(None, runtime.ProbeResult("tpu"))
    )
    runtime._note_transition(None)  # same-verdict: no flip, no count
    assert runtime.probe_transition_counts() == {"fallback": 1, "recovery": 1}


# -- periodic recovery re-probe (BENCH r04-r05 wedge: CPU-parked node) ---------


def test_recovery_reprobe_reinstalls_device_codec(monkeypatch):
    """A node that booted onto the host codec (failed probe) re-acquires the
    device on the recovery cadence without a restart."""
    import time

    verdicts = [runtime.ProbeResult(None, error="wedged at boot")]

    def probe(t):
        return verdicts.pop(0) if verdicts else runtime.ProbeResult("tpu")

    monkeypatch.setattr(runtime, "probe_device", probe)
    monkeypatch.setenv("MTPU_PROBE_RECOVERY_S", "0.05")
    codec = runtime.install_data_plane_codec(mode="auto")
    try:
        assert isinstance(codec, HostCodec)  # boot verdict: fall back
        t0 = time.monotonic()
        while not isinstance(codec_mod.default_codec(), BatchingDeviceCodec):
            assert time.monotonic() - t0 < 10, "recovery re-probe never landed"
            time.sleep(0.02)
        # The daemon exits after the swap: one recovery, then done.
        t = runtime._reprobe_thread
        if t is not None:
            t.join(timeout=5)
            assert not t.is_alive()
    finally:
        runtime.shutdown_data_plane(codec_mod._default)


def test_recovery_reprobe_disabled_by_env(monkeypatch):
    monkeypatch.setenv("MTPU_PROBE_RECOVERY_S", "0")
    monkeypatch.setattr(runtime, "probe_device", lambda t: runtime.ProbeResult(None, error="x"))
    monkeypatch.setattr(runtime, "_reprobe_thread", None)
    codec = runtime.install_data_plane_codec(mode="auto")
    assert isinstance(codec, HostCodec)
    assert runtime._reprobe_thread is None  # no daemon armed


def test_recovery_reprobe_stops_on_shutdown(monkeypatch):
    """shutdown_data_plane stops a still-waiting recovery daemon (the probe
    keeps failing, so only the stop event can end it)."""
    monkeypatch.setattr(runtime, "probe_device", lambda t: runtime.ProbeResult(None, error="x"))
    monkeypatch.setenv("MTPU_PROBE_RECOVERY_S", "30")
    codec = runtime.install_data_plane_codec(mode="auto")
    assert isinstance(codec, HostCodec)
    t = runtime._reprobe_thread
    assert t is not None and t.is_alive()
    runtime.shutdown_data_plane(codec)
    t.join(timeout=5)
    assert not t.is_alive()


def test_probe_summary_shape(monkeypatch):
    monkeypatch.setenv("MTPU_PROBE_RECOVERY_S", "0")
    s = runtime.probe_summary()
    assert set(s) >= {"done", "ok", "platform", "cached",
                      "transition", "transition_counts", "recovery"}
    assert s["recovery"]["interval_s"] == 0.0
    assert set(s["transition_counts"]) == {"fallback", "recovery"}
