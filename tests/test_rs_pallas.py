"""Fused Pallas RS kernel: bit-identical to the XLA path and the host
reference (interpret mode on CPU; same kernel runs on TPU).

Pins ops/rs_pallas against ops/rs_ref -- which is itself pinned against the
reference's boot self-test golden vectors (tests/golden_rs.py, mirroring
/root/reference/cmd/erasure-coding.go:158-216).
"""

from __future__ import annotations

import numpy as np
import pytest

from minio_tpu.ops import rs, rs_matrix, rs_ref
from minio_tpu.ops.rs_pallas import RSPallasCodec, apply


@pytest.mark.parametrize("k,m,s", [(12, 4, 64), (4, 2, 100), (2, 2, 1), (16, 4, 257)])
def test_encode_matches_reference(k, m, s):
    rng = np.random.default_rng(k * 100 + m)
    data = rng.integers(0, 256, (3, k, s), dtype=np.uint8)
    codec = RSPallasCodec(k, m)
    got = np.asarray(codec.encode(data))
    for b in range(data.shape[0]):
        want = rs_ref.encode(data[b], m)[k:]
        np.testing.assert_array_equal(got[b], want)


def test_encode_matches_xla_path():
    rng = np.random.default_rng(7)
    data = rng.integers(0, 256, (2, 12, 4096), dtype=np.uint8)
    want = np.asarray(rs.RSCodec(12, 4).encode(data))
    got = np.asarray(RSPallasCodec(12, 4).encode(data))
    np.testing.assert_array_equal(got, want)


def test_reconstruct_roundtrip():
    k, m, s = 12, 4, 333
    rng = np.random.default_rng(11)
    data = rng.integers(0, 256, (2, k, s), dtype=np.uint8)
    codec = RSPallasCodec(k, m)
    full = np.asarray(codec.encode_all(data))
    missing = (0, 5, 14)  # two data rows + one parity row lost
    present = tuple(i not in missing for i in range(k + m))
    surv = np.stack(
        [full[:, i] for i in range(k + m) if present[i]][:k], axis=1
    )  # [B, K, S] survivor rows in index order
    w = codec.reconstruct_weights(present, missing)
    rebuilt = np.asarray(codec.apply(surv, w))
    for j, row in enumerate(missing):
        np.testing.assert_array_equal(rebuilt[:, j], full[:, row])


def test_apply_matches_gf_matmul_orientation():
    """apply() takes bit_expand-oriented weights exactly like rs.gf_matmul."""
    k, m = 4, 2
    rng = np.random.default_rng(3)
    data = rng.integers(0, 256, (1, k, 50), dtype=np.uint8)
    w = rs_matrix.bit_expand(rs_matrix.parity_matrix(k, m)).astype(np.int8)
    got = np.asarray(apply(data, w))
    want = np.asarray(rs.gf_matmul(data, w))
    np.testing.assert_array_equal(got, want)
