"""mtpusan runtime sanitizer tests: every detector has a firing and a
non-firing fixture, plus cycle math, report/baseline plumbing, the
metrics exposition when armed, and the disarmed pass-through guarantee.

The seeded lock-order inversion here is the acceptance fixture for the
whole subsystem: the SAME inversion is caught statically (mtpulint's
lock-order rule, test_lint.py) and at runtime (graph cycle below) --
sequentially, so the suite itself can never deadlock on it.
"""

from __future__ import annotations

import importlib.util
import json
import textwrap
import threading
import time
from pathlib import Path

import pytest

import minio_tpu.control.sanitizer as sm
from minio_tpu.control.sanitizer import (
    SanCondition,
    Sanitizer,
    SanLock,
    SanRLock,
    san_condition,
    san_lock,
    san_rlock,
)

_REPO = Path(__file__).resolve().parent.parent
_LINT_PATH = _REPO / "tools" / "metrics_lint.py"
_spec = importlib.util.spec_from_file_location("metrics_lint", _LINT_PATH)
metrics_lint = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(metrics_lint)


@pytest.fixture
def armed_san():
    """Arm a fresh Sanitizer for one test, restoring prior state after --
    including the case where the whole session already runs armed (a
    sanitized run of this very file must not disarm itself)."""
    was_armed = sm.armed()
    prev = sm.GLOBAL_SAN
    san = sm.arm(Sanitizer(hold_threshold_s=0.05))
    yield san
    if not was_armed:
        sm.disarm()
    sm.GLOBAL_SAN = prev


def _unsuppressed(san):
    return [f for f in san.report()["findings"] if "suppressed" not in f]


# -- disarmed pass-through (the overhead guarantee) ---------------------------


def test_disarmed_factories_return_plain_primitives():
    if sm.armed():  # pragma: no cover - only under a sanitized outer run
        pytest.skip("session armed: pass-through not observable")
    assert type(san_lock("x")) is type(threading.Lock())
    assert isinstance(san_rlock("x"), type(threading.RLock()))
    assert isinstance(san_condition("x"), threading.Condition)
    assert sm.profile_if_armed() is None


def test_armed_factories_return_instrumented_primitives(armed_san):
    assert isinstance(san_lock("a"), SanLock)
    assert isinstance(san_rlock("b"), SanRLock)
    assert isinstance(san_condition("c"), SanCondition)
    assert sm.profile_if_armed() is not None


# -- lock-order-inversion -----------------------------------------------------


def test_seeded_inversion_detected_at_runtime_without_deadlock(armed_san):
    """A->B in one call path, B->A in another: the graph closes a cycle and
    reports it even though nothing ever wedged (both nestings run on one
    thread, sequentially)."""
    a = SanLock(armed_san, "Seed._a_lock")
    b = SanLock(armed_san, "Seed._b_lock")
    with a:
        with b:
            pass
    with b:
        with a:
            pass
    rules = [f["rule"] for f in _unsuppressed(armed_san)]
    assert rules == ["lock-order-inversion"]
    (f,) = _unsuppressed(armed_san)
    assert "Seed._a_lock" in f["message"] and "Seed._b_lock" in f["message"]
    assert f["stacks"]  # acquisition stacks for both directions


def test_consistent_order_is_clean(armed_san):
    a = SanLock(armed_san, "Seed._a_lock")
    b = SanLock(armed_san, "Seed._b_lock")
    for _ in range(3):
        with a:
            with b:
                pass
    assert _unsuppressed(armed_san) == []
    assert armed_san.report()["lock_order_edges"] == 1


def test_transitive_cycle_through_three_locks(armed_san):
    """A->B, B->C, then C->A: the cycle spans the whole chain, not just
    the closing edge pair."""
    a = SanLock(armed_san, "T._a_lock")
    b = SanLock(armed_san, "T._b_lock")
    c = SanLock(armed_san, "T._c_lock")
    with a:
        with b:
            pass
    with b:
        with c:
            pass
    with c:
        with a:
            pass
    (f,) = _unsuppressed(armed_san)
    assert f["rule"] == "lock-order-inversion"
    for name in ("T._a_lock", "T._b_lock", "T._c_lock"):
        assert name in f["message"]


def test_same_inversion_reported_once(armed_san):
    a = SanLock(armed_san, "O._a_lock")
    b = SanLock(armed_san, "O._b_lock")
    for _ in range(3):
        with a:
            with b:
                pass
        with b:
            with a:
                pass
    assert len(_unsuppressed(armed_san)) == 1


def test_static_rule_catches_the_same_seeded_inversion(tmp_path):
    """The acceptance pairing: the runtime cycle above, expressed as source,
    is also a static lock-order finding before the code ever runs."""
    from tools.mtpulint import lint_tree
    from tools.mtpulint.rules import LockOrderRule

    src = tmp_path / "minio_tpu" / "dist" / "seed.py"
    src.parent.mkdir(parents=True)
    src.write_text(textwrap.dedent("""
        class Seed:
            def forward(self):
                with self._a_lock:
                    with self._b_lock:
                        pass

            def backward(self):
                with self._b_lock:
                    with self._a_lock:
                        pass
    """))
    findings = lint_tree(str(tmp_path), ["minio_tpu"], [LockOrderRule()])
    assert [f.rule for f in findings] == ["lock-order"]
    assert "cycle" in findings[0].message


# -- self-deadlock ------------------------------------------------------------


def test_self_deadlock_raises_instead_of_hanging(armed_san):
    lk = SanLock(armed_san, "S._lock")
    lk.acquire()
    try:
        with pytest.raises(RuntimeError, match="self-deadlock"):
            lk.acquire()
    finally:
        lk.release()
    rules = [f["rule"] for f in _unsuppressed(armed_san)]
    assert rules == ["self-deadlock"]


def test_rlock_reentry_is_clean(armed_san):
    lk = SanRLock(armed_san, "S._rlock")
    with lk:
        with lk:
            pass
    assert _unsuppressed(armed_san) == []


# -- lock-held-long -----------------------------------------------------------


def test_long_hold_fires(armed_san):
    lk = SanLock(armed_san, "H._lock")
    with lk:
        t0 = time.perf_counter()
        while time.perf_counter() - t0 < 0.08:  # busy: sleep would ALSO fire
            pass
    (f,) = _unsuppressed(armed_san)
    assert f["rule"] == "lock-held-long"
    assert f["site"] == "H._lock"


def test_short_hold_is_clean(armed_san):
    lk = SanLock(armed_san, "H._lock")
    with lk:
        pass
    assert _unsuppressed(armed_san) == []


# -- lock-over-blocking -------------------------------------------------------


def test_sleep_under_lock_fires(armed_san):
    lk = SanLock(armed_san, "B._lock")
    with lk:
        time.sleep(0.001)
    rules = {f["rule"] for f in _unsuppressed(armed_san)}
    assert "lock-over-blocking" in rules


def test_sleep_outside_lock_is_clean(armed_san):
    lk = SanLock(armed_san, "B._lock")
    with lk:
        pass
    time.sleep(0.001)
    assert _unsuppressed(armed_san) == []


# -- cond-wait-no-loop --------------------------------------------------------


def test_bare_wait_outside_while_fires(armed_san):
    cond = SanCondition(armed_san, "C._cv")
    with cond:
        cond.wait(timeout=0.01)
    (f,) = _unsuppressed(armed_san)
    assert f["rule"] == "cond-wait-no-loop"


def test_wait_inside_while_predicate_is_clean(armed_san):
    cond = SanCondition(armed_san, "C._cv")
    done = [False]
    with cond:
        while not done[0]:
            cond.wait(timeout=0.01)
            done[0] = True
    assert _unsuppressed(armed_san) == []


def test_wait_for_is_clean(armed_san):
    cond = SanCondition(armed_san, "C._cv")
    with cond:
        cond.wait_for(lambda: True, timeout=0.01)
    assert _unsuppressed(armed_san) == []


# -- teardown: leaked threads / fds -------------------------------------------


def test_leaked_thread_detected_at_teardown(armed_san):
    armed_san.snapshot_baseline()
    release = threading.Event()
    t = threading.Thread(target=release.wait, name="orphan-worker", daemon=True)
    t.start()
    try:
        armed_san.teardown_check()
        leaks = [
            f for f in _unsuppressed(armed_san) if f["rule"] == "leaked-thread"
        ]
        assert [f["site"] for f in leaks] == ["orphan-worker"]
    finally:
        release.set()
        t.join(5.0)


def test_joined_thread_is_clean_and_suppression_table_applies(armed_san):
    armed_san.snapshot_baseline()
    t = threading.Thread(target=lambda: None, name="short-worker")
    t.start()
    t.join(5.0)
    release = threading.Event()
    # Name matches the justified lock-refresh suppression row.
    d = threading.Thread(target=release.wait, name="lock-refresh-0", daemon=True)
    d.start()
    try:
        armed_san.teardown_check()
        assert _unsuppressed(armed_san) == []
        sup = [
            f for f in armed_san.report()["findings"] if "suppressed" in f
        ]
        assert len(sup) == 1 and sup[0]["site"] == "lock-refresh-0"
    finally:
        release.set()
        d.join(5.0)


def test_fd_leak_detected_with_slack(armed_san, monkeypatch):
    armed_san._baseline_fds = 100
    monkeypatch.setattr(sm, "_fd_count", lambda: 300)
    armed_san.teardown_check()
    assert any(f["rule"] == "fd-leak" for f in _unsuppressed(armed_san))


def test_fd_growth_within_slack_is_clean(armed_san, monkeypatch):
    armed_san._baseline_fds = 100
    monkeypatch.setattr(sm, "_fd_count", lambda: 130)
    armed_san.teardown_check()
    assert not any(f["rule"] == "fd-leak" for f in _unsuppressed(armed_san))


# -- profile / contention stats -----------------------------------------------


def test_profile_counts_acquisitions_and_contention(armed_san):
    lk = SanLock(armed_san, "P._lock")
    with lk:
        pass
    hold = threading.Event()
    entered = threading.Event()

    def holder():
        with lk:
            entered.set()
            hold.wait(5.0)

    t = threading.Thread(target=holder)
    t.start()
    assert entered.wait(5.0)
    acquired = threading.Event()

    def contender():
        with lk:
            acquired.set()

    t2 = threading.Thread(target=contender)
    t2.start()
    # Let the contender actually block on the inner lock before releasing.
    deadline = time.monotonic() + 5.0
    while not lk.locked() and time.monotonic() < deadline:
        pass
    hold.set()
    assert acquired.wait(5.0)
    t.join(5.0)
    t2.join(5.0)
    prof = armed_san.profile()["P._lock"]
    assert prof["acquisitions"] == 3
    assert prof["contended"] >= 1
    assert prof["wait_s"] >= 0.0
    assert prof["hold_s"] > 0.0


def test_report_shape_and_json_round_trip(armed_san, tmp_path):
    lk = SanLock(armed_san, "R._lock")
    with lk:
        pass
    out = tmp_path / "san.json"
    armed_san.write_report(str(out))
    rep = json.loads(out.read_text())
    assert rep["mtpusan"] == 1
    assert rep["armed"] is True
    assert rep["unsuppressed"] == 0
    assert "R._lock" in rep["lock_profile"]
    assert set(rep) >= {
        "findings", "lock_order_edges", "lock_profile", "hold_threshold_ms",
    }


# -- metrics exposition (armed only) ------------------------------------------


def test_san_metrics_rendered_when_armed_and_lint_clean(armed_san):
    from minio_tpu.control.metrics import MetricsSys

    ms = MetricsSys()
    lk = SanLock(armed_san, "M._lock")
    with lk:
        pass
    text = ms.render_node()
    assert 'minio_tpu_san_lock_acquisitions_total{lock="M._lock"}' in text
    assert "minio_tpu_san_lock_hold_seconds_max" in text
    assert "minio_tpu_san_lock_order_edges" in text
    assert metrics_lint.validate_exposition(text) == []
    assert metrics_lint.lint_exposition(text) == []


def test_san_metrics_absent_when_disarmed():
    if sm.armed():  # pragma: no cover - only under a sanitized outer run
        pytest.skip("session armed")
    from minio_tpu.control.metrics import MetricsSys

    text = MetricsSys().render_node()
    assert "minio_tpu_san_" not in text
    assert metrics_lint.validate_exposition(text) == []


def test_san_findings_metric_by_rule(armed_san):
    from minio_tpu.control.metrics import MetricsSys

    armed_san.add_finding("lock-held-long", "X._lock", "m")
    armed_san.add_finding("lock-held-long", "Y._lock", "m")
    text = MetricsSys().render_node()
    assert 'minio_tpu_san_findings_total{rule="lock-held-long"} 2' in text


# -- driver: merge + baseline gate --------------------------------------------


def test_mtpusan_merge_dedupes_and_splits_suppressed():
    from tools import mtpusan

    reports = [
        {"source": "a", "findings": [
            {"rule": "lock-held-long", "site": "X._lock", "message": "m"},
            {"rule": "leaked-thread", "site": "lock-refresh-0",
             "message": "m", "suppressed": "why"},
        ]},
        {"source": "b", "findings": [
            {"rule": "lock-held-long", "site": "X._lock", "message": "m"},
            {"rule": "lock-held-long", "site": "Y._lock", "message": "m"},
        ]},
    ]
    unsup, sup = mtpusan.merge_findings(reports)
    assert sorted(f["site"] for f in unsup) == ["X._lock", "Y._lock"]
    assert [f["site"] for f in sup] == ["lock-refresh-0"]


def test_mtpusan_gate_baseline_round_trip(tmp_path, capsys):
    from tools import mtpusan

    baseline = tmp_path / "baseline.txt"
    finding = {"rule": "lock-held-long", "site": "X._lock", "message": "m"}
    # No baseline: the finding gates.
    assert mtpusan.gate([finding], str(baseline), write=False) == 1
    # Grandfather it, then the same finding passes ...
    assert mtpusan.gate([finding], str(baseline), write=True) == 0
    assert mtpusan.gate([finding], str(baseline), write=False) == 0
    # ... but a new site still gates (shrink-only semantics).
    extra = {"rule": "lock-held-long", "site": "Z._lock", "message": "m"}
    assert mtpusan.gate([finding, extra], str(baseline), write=False) == 1


def test_shipped_baseline_is_empty():
    """The acceptance bar: no grandfathered runtime findings ship."""
    from tools.mtpulint import load_baseline

    assert load_baseline(str(_REPO / "tools" / "mtpusan_baseline.txt")) == {}
