"""Graceful-degradation units: deadline budget, circuit breaker, admission
gate, degradation metrics, and the deadline-propagation lint.

The chaos-level invariants (hedge wins under an injected slow drive, breaker
trip/re-close under drive faults, deadline aborts of stalled RPC chains)
live in tests/chaos_scenarios.py; this file pins the building blocks and the
API surface those scenarios compose.
"""

from __future__ import annotations

import inspect
import time

import pytest

from minio_tpu.control.degrade import DegradeStats, GLOBAL_DEGRADE
from minio_tpu.storage.breaker import CircuitBreaker, HealthGatedDrive
from minio_tpu.utils import deadline, errors
from tests.harness import ErasureHarness

ROOT_AK = "minioadmin"
ROOT_SK = "minioadmin-secret"


# ---------------------------------------------------------------------------
# Deadline budget (utils/deadline.py)
# ---------------------------------------------------------------------------


class TestDeadlineModule:
    def test_no_deadline_by_default(self):
        assert deadline.remaining() is None
        assert deadline.header_value() is None
        deadline.check("noop")  # never raises without a budget

    def test_scope_counts_down_and_restores(self):
        with deadline.scope(5.0):
            rem = deadline.remaining()
            assert rem is not None and 4.5 < rem <= 5.0
            assert deadline.header_value() is not None
        assert deadline.remaining() is None

    def test_nested_scopes_only_shrink(self):
        with deadline.scope(10.0):
            with deadline.scope(1.0):
                assert deadline.remaining() <= 1.0
            # Inner scope exit restores the OUTER budget, not None.
            assert deadline.remaining() > 5.0
            with deadline.scope(60.0):
                # An inner layer cannot grant itself more time.
                assert deadline.remaining() <= 10.0

    def test_scope_none_is_passthrough(self):
        with deadline.scope(None):
            assert deadline.remaining() is None

    def test_check_raises_once_spent(self):
        with deadline.scope(0.001):
            time.sleep(0.005)
            with pytest.raises(errors.DeadlineExceeded):
                deadline.check("unit")

    def test_parse_header(self):
        assert deadline.parse_header(None) is None
        assert deadline.parse_header("") is None
        assert deadline.parse_header("garbage") is None
        assert deadline.parse_header("1.500") == pytest.approx(1.5)
        assert deadline.parse_header("-3") == 0.0  # already expired
        assert deadline.parse_header("nan") == 0.0

    def test_bind_header_adopts_budget(self):
        with deadline.bind_header("0.750"):
            rem = deadline.remaining()
            assert rem is not None and 0.5 < rem <= 0.75
        with deadline.bind_header(None):
            assert deadline.remaining() is None

    def test_budget_survives_parallel_map_workers(self):
        from minio_tpu.object import metadata as meta_mod

        with deadline.scope(5.0):
            rems = meta_mod.parallel_map(lambda _i: deadline.remaining(), [0, 1, 2])
        assert all(r is not None and r[0] is not None and r[0] > 0 for r in rems)


# ---------------------------------------------------------------------------
# Circuit breaker (storage/breaker.py)
# ---------------------------------------------------------------------------


class TestCircuitBreaker:
    def test_trips_after_consecutive_health_errors(self):
        b = CircuitBreaker(name="t", error_threshold=3)
        for _ in range(2):
            b.record_error(errors.FaultyDisk("x"), 1.0)
        assert b.allows()
        b.record_error(errors.FaultyDisk("x"), 1.0)
        assert not b.allows()
        assert b.snapshot()["state"] == "open"
        assert b.snapshot()["trips"] == 1

    def test_app_level_errors_reset_the_counter(self):
        b = CircuitBreaker(name="t", error_threshold=3)
        b.record_error(errors.FaultyDisk("x"), 1.0)
        b.record_error(errors.FaultyDisk("x"), 1.0)
        # The drive answered correctly: not a health signal.
        b.record_error(errors.FileNotFound("b", "o"), 1.0)
        b.record_error(errors.FaultyDisk("x"), 1.0)
        assert b.allows()  # counter restarted, threshold not reached

    def test_success_resets_the_counter(self):
        b = CircuitBreaker(name="t", error_threshold=2)
        b.record_error(errors.FaultyDisk("x"), 1.0)
        b.record_success(1.0)
        b.record_error(errors.FaultyDisk("x"), 1.0)
        assert b.allows()

    def test_latency_ewma_trips(self):
        b = CircuitBreaker(
            name="t", latency_limit_ms=100.0, latency_min_samples=4
        )
        for _ in range(3):
            b.record_success(10_000.0)
        assert b.allows()  # min_samples guards cold-start noise
        b.record_success(10_000.0)
        assert not b.allows()

    def test_probe_recloses(self):
        healthy = []
        b = CircuitBreaker(
            name="t", error_threshold=1, cooldown=0.05, max_cooldown=0.2,
            probe=lambda: healthy.append(1),
        )
        b.record_error(errors.FaultyDisk("x"), 1.0)
        assert not b.allows()
        waited = time.monotonic() + 3.0
        while time.monotonic() < waited and not b.allows():
            time.sleep(0.01)
        assert b.allows()
        assert healthy  # the probe really ran

    def test_reset_is_operator_override(self):
        b = CircuitBreaker(name="t", error_threshold=1)
        b.record_error(errors.FaultyDisk("x"), 1.0)
        assert not b.allows()
        b.reset()
        assert b.allows()
        assert b.snapshot()["consecutive_errors"] == 0


class TestHealthGatedDrive:
    @pytest.fixture()
    def drive(self, tmp_path):
        hz = ErasureHarness(tmp_path, n_disks=4, parity=2)
        return hz.drives[0]

    def test_open_breaker_fails_fast_and_reports_offline(self, drive):
        g = HealthGatedDrive(drive, breaker=CircuitBreaker(error_threshold=1))
        assert g.is_online()
        g.breaker.record_error(errors.FaultyDisk("x"), 1.0)
        assert not g.is_online()
        with pytest.raises(errors.CircuitOpen):
            g.disk_info()

    def test_full_inflight_window_sheds_drive_busy(self, drive):
        g = HealthGatedDrive(drive, max_inflight=1)
        before = GLOBAL_DEGRADE.snapshot()["sheds"].get("drive", 0)
        assert g._sem.acquire(blocking=False)  # occupy the only slot
        try:
            with pytest.raises(errors.DriveBusy):
                g.disk_info()
        finally:
            g._sem.release()
        assert GLOBAL_DEGRADE.snapshot()["sheds"].get("drive", 0) == before + 1
        assert g.disk_info().total > 0  # slot free again: calls flow

    def test_outcomes_feed_the_breaker(self, drive):
        g = HealthGatedDrive(drive, breaker=CircuitBreaker(error_threshold=2))
        g.make_vol("gv")
        g.write_all("gv", "a", b"x")
        assert g.read_all("gv", "a") == b"x"
        # App-level miss: answered correctly, breaker stays closed.
        with pytest.raises(errors.FileNotFound):
            g.read_all("gv", "missing")
        assert g.breaker.allows()
        assert g.breaker_state()["consecutive_errors"] == 0

    def test_walk_dir_stays_a_generator(self, drive):
        assert inspect.isgeneratorfunction(HealthGatedDrive.walk_dir)
        g = HealthGatedDrive(drive)
        g.make_vol("wv")
        g.write_all("wv", "obj/xl.meta", b"m")  # walk emits xl.meta holders
        assert list(g.walk_dir("wv")) == [("obj", b"m")]

    def test_non_gated_attributes_pass_through(self, drive):
        g = HealthGatedDrive(drive)
        assert g.endpoint() == drive.endpoint()
        assert g.root == drive.root


# ---------------------------------------------------------------------------
# Degrade counters + metrics rendering
# ---------------------------------------------------------------------------


class TestDegradeStats:
    def test_counters_accumulate(self):
        st = DegradeStats()
        st.record_hedge(3, 1)
        st.record_hedge(0, 0)  # no-op fast path
        st.record_deadline_abort("rpc")
        st.record_deadline_abort("rpc")
        st.record_shed("read")
        st.record_breaker(tripped=True)
        st.record_breaker(tripped=False)
        snap = st.snapshot()
        assert snap["hedge_launched"] == 3 and snap["hedge_wins"] == 1
        assert snap["deadline_aborts"] == {"rpc": 2}
        assert snap["sheds"] == {"read": 1}
        assert snap["breaker_trips"] == 1 and snap["breaker_closes"] == 1

    def test_metrics_render_degrade_families(self, tmp_path):
        from minio_tpu.control.metrics import MetricsSys
        from minio_tpu.object.pools import ServerPools
        from minio_tpu.object.sets import ErasureSets

        hz = ErasureHarness(tmp_path, n_disks=4, parity=2)
        gated = [HealthGatedDrive(d) for d in hz.drives]
        layer = ServerPools([ErasureSets(gated, 4)])
        m = MetricsSys()
        m.layer = layer
        GLOBAL_DEGRADE.record_hedge(1, 1)
        GLOBAL_DEGRADE.record_deadline_abort("unit-test")
        text = m.render_node()
        assert "minio_tpu_hedge_wins_total" in text
        assert "minio_tpu_hedge_launched_total" in text
        assert 'minio_tpu_deadline_aborts_total{stage="unit-test"}' in text
        assert "minio_tpu_breaker_trips_total" in text
        # Per-drive breaker gauges walk the layer like the drive EWMAs do.
        assert 'minio_tpu_drive_breaker_state{drive=' in text


# ---------------------------------------------------------------------------
# API admission gate + SlowDown mapping (api/server.py satellite surface)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def api_stack(tmp_path_factory):
    from minio_tpu.api.server import S3Server, ThreadedServer
    from minio_tpu.control.iam import IAMSys
    from minio_tpu.object.pools import ServerPools
    from minio_tpu.object.sets import ErasureSets
    from tests.s3client import S3TestClient

    tmp = tmp_path_factory.mktemp("degrade-api")
    hz = ErasureHarness(tmp, n_disks=4, parity=2)
    layer = ServerPools([ErasureSets(list(hz.drives), 4)])
    srv = S3Server(layer, IAMSys(ROOT_AK, ROOT_SK), check_skew=False)
    ts = ThreadedServer(srv)
    endpoint = ts.start()
    client = S3TestClient(endpoint, ROOT_AK, ROOT_SK)
    yield {"srv": srv, "client": client, "layer": layer}
    ts.stop()


class TestApiDegradation:
    def test_admission_gate_sheds_with_retry_after(self, api_stack):
        srv, client = api_stack["srv"], api_stack["client"]
        saved_max, saved_inflight = srv._max_requests, srv._inflight
        srv._max_requests = 1
        srv._inflight = 1  # the node is "full"
        try:
            r = client.request("GET", "/")
            assert r.status_code == 503
            assert "SlowDownRead" in r.text
            assert r.headers.get("Retry-After") == "1"
            r = client.request("PUT", "/shedbkt")
            assert r.status_code == 503
            assert "SlowDownWrite" in r.text
        finally:
            srv._max_requests, srv._inflight = saved_max, saved_inflight
        assert client.request("GET", "/").status_code == 200  # gate reopened

    def test_deadline_exceeded_maps_to_slowdown_503(self, api_stack, monkeypatch):
        client, layer = api_stack["client"], api_stack["layer"]
        monkeypatch.setattr(
            layer, "list_buckets",
            lambda *a, **k: (_ for _ in ()).throw(errors.DeadlineExceeded("spent")),
        )
        r = client.request("GET", "/")
        assert r.status_code == 503
        assert "SlowDownRead" in r.text
        assert r.headers.get("Retry-After") == "1"

    def test_client_deadline_header_binds_the_dispatch(self, api_stack, monkeypatch):
        client, layer = api_stack["client"], api_stack["layer"]
        seen: list[float | None] = []
        real = layer.list_buckets

        def spying(*a, **k):
            seen.append(deadline.remaining())
            return real(*a, **k)

        monkeypatch.setattr(layer, "list_buckets", spying)
        r = client.request("GET", "/", headers={"X-Mtpu-Deadline": "30.000"})
        assert r.status_code == 200
        assert seen and seen[-1] is not None and 0 < seen[-1] <= 30.0
        # Without the header, no budget binds.
        r = client.request("GET", "/")
        assert r.status_code == 200
        assert seen[-1] is None


# ---------------------------------------------------------------------------
# Deadline lint (tools/deadline_lint.py) wired into tier-1
# ---------------------------------------------------------------------------


def test_deadline_lint_tree_is_clean():
    import importlib.util
    import os

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "deadline_lint", os.path.join(root, "tools", "deadline_lint.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert mod.lint() == []
