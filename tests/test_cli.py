"""CLI bootstrap tests: ellipses expansion, boot self-tests, and a real
`python -m minio_tpu server` subprocess serving S3.

The analogue of the reference's endpoint-ellipses_test.go set math tests and
buildscripts/verify-build.sh (boot a real server process and run functional
requests against it).
"""

import os
import signal
import subprocess
import sys
import time

import pytest

from minio_tpu.cli import boot_self_test, expand_ellipses, expand_endpoints
from tests.s3client import S3TestClient
from tests.test_dist import _free_port


class TestEllipses:
    def test_no_pattern(self):
        assert expand_ellipses("/data/disk1") == ["/data/disk1"]

    def test_simple_range(self):
        assert expand_ellipses("/data/disk{1...4}") == [
            "/data/disk1",
            "/data/disk2",
            "/data/disk3",
            "/data/disk4",
        ]

    def test_zero_padded(self):
        out = expand_ellipses("/d{01...12}")
        assert out[0] == "/d01" and out[-1] == "/d12" and len(out) == 12

    def test_cartesian_host_times_disk(self):
        out = expand_ellipses("http://node{1...2}:9000/disk{1...3}")
        assert len(out) == 6
        assert out[0] == "http://node1:9000/disk1"
        assert out[-1] == "http://node2:9000/disk3"
        # Host-major order, like the reference's argument expansion.
        assert out[3] == "http://node2:9000/disk1"

    def test_bad_range(self):
        with pytest.raises(ValueError):
            expand_ellipses("/d{4...1}")

    def test_duplicate_rejected(self):
        with pytest.raises(ValueError):
            expand_endpoints(["/d{1...2}", "/d1"])

    def test_typoed_ellipsis_rejected(self):
        with pytest.raises(ValueError):
            expand_ellipses("/data/disk{1..4}")  # two dots
        with pytest.raises(ValueError):
            expand_ellipses("/data/disk{a...d}")  # non-numeric


def test_boot_self_test_passes():
    boot_self_test()  # raises SystemExit on kernel regression


def test_server_subprocess(tmp_path):
    """Full black-box boot: subprocess serves S3 until SIGTERM."""
    port = _free_port()
    env = dict(
        os.environ,
        MINIO_ROOT_USER="cliroot01",
        MINIO_ROOT_PASSWORD="cli-secret-key1",
        MINIO_STORAGE_CLASS_STANDARD="EC:1",
    )
    proc = subprocess.Popen(
        [
            sys.executable,
            "-m",
            "minio_tpu",
            "server",
            "--address",
            f"127.0.0.1:{port}",
            "--json",
            str(tmp_path) + "/disk{1...4}",
        ],
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
    )
    try:
        client = S3TestClient(f"http://127.0.0.1:{port}", "cliroot01", "cli-secret-key1")
        deadline = time.monotonic() + 60
        up = False
        while time.monotonic() < deadline:
            try:
                if client.request("GET", "/").status_code == 200:
                    up = True
                    break
            except Exception:
                pass
            time.sleep(0.3)
        assert up, "server did not come up"
        assert client.make_bucket("clibkt").status_code == 200
        assert client.put_object("clibkt", "hello", b"from the CLI").status_code == 200
        r = client.request("GET", "/clibkt/hello")
        assert r.status_code == 200 and r.content == b"from the CLI"
        # Four drives formatted on disk.
        assert all(
            os.path.isfile(tmp_path / f"disk{i}" / ".minio_tpu.sys" / "format.json")
            for i in range(1, 5)
        )
        proc.send_signal(signal.SIGTERM)
        assert proc.wait(timeout=15) == 0
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()


def _boot_server(tmp_path, port, env):
    return subprocess.Popen(
        [
            sys.executable, "-m", "minio_tpu", "server",
            "--address", f"127.0.0.1:{port}", "--json",
            str(tmp_path) + "/disk{1...4}",
        ],
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
    )


def _wait_up(client, timeout=60):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            if client.request("GET", "/").status_code == 200:
                return True
        except Exception:
            pass
        time.sleep(0.3)
    return False


def test_server_restart_preserves_data(tmp_path):
    """Durability across process restarts (the reference's upgrade/restart
    verification): a second boot over the same drives serves the data the
    first wrote, without reformatting."""
    env = dict(
        os.environ,
        MINIO_ROOT_USER="cliroot01",
        MINIO_ROOT_PASSWORD="cli-secret-key1",
        MINIO_STORAGE_CLASS_STANDARD="EC:1",
    )
    port = _free_port()
    client = S3TestClient(f"http://127.0.0.1:{port}", "cliroot01", "cli-secret-key1")

    proc = _boot_server(tmp_path, port, env)
    try:
        assert _wait_up(client), "first boot did not come up"
        client.make_bucket("persist")
        client.put_object("persist", "keep/me", b"survives restart" * 100)
        fmt = (tmp_path / "disk1" / ".minio_tpu.sys" / "format.json").read_text()
        proc.send_signal(signal.SIGTERM)
        assert proc.wait(timeout=15) == 0
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()

    port2 = _free_port()
    client2 = S3TestClient(f"http://127.0.0.1:{port2}", "cliroot01", "cli-secret-key1")
    proc = _boot_server(tmp_path, port2, env)
    try:
        assert _wait_up(client2), "restart did not come up"
        r = client2.request("GET", "/persist/keep/me")
        assert r.status_code == 200 and r.content == b"survives restart" * 100
        # Same deployment: format untouched by the restart.
        fmt2 = (tmp_path / "disk1" / ".minio_tpu.sys" / "format.json").read_text()
        assert fmt == fmt2
        proc.send_signal(signal.SIGTERM)
        assert proc.wait(timeout=15) == 0
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()


def test_server_restart_preserves_iam(tmp_path):
    """IAM durability (iam-object-store.go role): admin-created users,
    their policies, and service accounts must survive a process restart —
    they persist through the erasure-backed config store, not memory."""
    import json as json_mod

    env = dict(
        os.environ,
        MINIO_ROOT_USER="cliroot02",
        MINIO_ROOT_PASSWORD="cli-secret-key2",
        MINIO_STORAGE_CLASS_STANDARD="EC:1",
    )
    port = _free_port()
    client = S3TestClient(f"http://127.0.0.1:{port}", "cliroot02", "cli-secret-key2")
    proc = _boot_server(tmp_path, port, env)
    sa = {}
    try:
        assert _wait_up(client), "first boot did not come up"
        r = client.request(
            "POST", "/mtpu/admin/v1/users",
            body=json_mod.dumps(
                {"accessKey": "keepuser", "secretKey": "keepsecret123", "policies": ["readwrite"]}
            ).encode(),
        )
        assert r.status_code == 200, r.text
        r = client.request("POST", "/mtpu/admin/v1/service-accounts",
                           body=json_mod.dumps({"parent": "keepuser"}).encode())
        assert r.status_code == 200, r.text
        sa = r.json()
        proc.send_signal(signal.SIGTERM)
        assert proc.wait(timeout=15) == 0
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()

    port2 = _free_port()
    client2 = S3TestClient(f"http://127.0.0.1:{port2}", "cliroot02", "cli-secret-key2")
    proc = _boot_server(tmp_path, port2, env)
    try:
        assert _wait_up(client2), "restart did not come up"
        users = client2.request("GET", "/mtpu/admin/v1/users").json()
        assert "keepuser" in users, f"user lost across restart: {users}"
        assert users["keepuser"]["policies"] == ["readwrite"]
        assert sa["accessKey"] in users, "service account lost across restart"
        # The persisted credentials actually authenticate and are scoped.
        cu = S3TestClient(f"http://127.0.0.1:{port2}", "keepuser", "keepsecret123")
        assert cu.make_bucket("iamkept").status_code == 200
        proc.send_signal(signal.SIGTERM)
        assert proc.wait(timeout=15) == 0
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()
