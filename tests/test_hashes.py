"""SipHash-2-4 / crc placement hash tests (canonical vectors + properties)."""

from minio_tpu.utils import hashes

# Canonical SipHash-2-4 64-bit test vectors (reference C implementation):
# key = 000102..0f, msg = [] / [0] / [0,1] / [0,1,2].
SIP_VECTORS = [
    0x726FDB47DD0E0E31,
    0x74F839C593DC67FD,
    0x0D6C8009D9A94F5A,
    0x85676696D7FB7E2D,
]


def test_siphash_vectors():
    k0 = int.from_bytes(bytes(range(8)), "little")
    k1 = int.from_bytes(bytes(range(8, 16)), "little")
    for i, want in enumerate(SIP_VECTORS):
        msg = bytes(range(i))
        assert hashes.siphash24(k0, k1, msg) == want, i


def test_sip_hash_mod_stable():
    dep = bytes(range(16))
    a = hashes.sip_hash_mod("bucket/object", 16, dep)
    assert a == hashes.sip_hash_mod("bucket/object", 16, dep)
    assert 0 <= a < 16
    assert hashes.sip_hash_mod("x", 0, dep) == -1


def test_hash_order_properties():
    order = hashes.hash_order("object-name", 16)
    assert sorted(order) == list(range(1, 17))
    assert order == hashes.hash_order("object-name", 16)
    assert hashes.hash_order("k", 0) == []


def test_crc_hash_mod():
    # crc32("" ) == 0 -> 0 mod anything
    assert hashes.crc_hash_mod("", 7) == 0
    assert 0 <= hashes.crc_hash_mod("abc", 5) < 5
