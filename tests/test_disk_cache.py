"""Disk cache layer: hits, validation, invalidation, LRU GC, offline serving.

Mirrors the reference's disk-cache test surface (cmd/disk-cache_test.go):
cache fill on GET, ETag validation against the backend, stale-entry
invalidation on overwrite, serving from cache when the backend is down,
`after` hit-count threshold, and watermark-driven LRU eviction.
"""

import os

import pytest

from minio_tpu.object.cache import CacheConfig, CacheObjectLayer
from minio_tpu.object.pools import ServerPools
from minio_tpu.object.sets import ErasureSets
from minio_tpu.utils import errors
from tests.harness import ErasureHarness

BUCKET = "cachebkt"


class CountingBackend:
    """Delegating wrapper that counts data reads (to prove cache hits)."""

    def __init__(self, layer):
        self._layer = layer
        self.get_calls = 0
        self.offline = False

    def __getattr__(self, name):
        return getattr(self._layer, name)

    def get_object(self, *a, **kw):
        if self.offline:
            raise errors.StorageError("backend down")
        self.get_calls += 1
        return self._layer.get_object(*a, **kw)

    def get_object_info(self, *a, **kw):
        if self.offline:
            raise errors.StorageError("backend down")
        return self._layer.get_object_info(*a, **kw)


@pytest.fixture()
def cached(tmp_path):
    hz = ErasureHarness(tmp_path / "disks", n_disks=4)
    layer = ServerPools([ErasureSets(list(hz.drives), 4)])
    layer.make_bucket(BUCKET)
    backend = CountingBackend(layer)
    cfg = CacheConfig(drives=[str(tmp_path / "cache0"), str(tmp_path / "cache1")])
    return backend, CacheObjectLayer(backend, cfg)


def test_cache_fill_and_hit(cached):
    backend, cache = cached
    data = os.urandom(10_000)
    cache.put_object(BUCKET, "hot.bin", data)
    _, got = cache.get_object(BUCKET, "hot.bin")  # miss -> fill
    assert got == data
    calls_after_fill = backend.get_calls
    for _ in range(3):
        _, got = cache.get_object(BUCKET, "hot.bin")
        assert got == data
    assert backend.get_calls == calls_after_fill  # served from cache
    st = cache.stats()
    assert st["hits"] == 3 and st["misses"] == 1


def test_overwrite_invalidates(cached):
    backend, cache = cached
    cache.put_object(BUCKET, "obj", b"v1" * 100)
    cache.get_object(BUCKET, "obj")
    cache.put_object(BUCKET, "obj", b"v2" * 100)
    _, got = cache.get_object(BUCKET, "obj")
    assert got == b"v2" * 100


def test_stale_etag_revalidates(cached):
    backend, cache = cached
    cache.put_object(BUCKET, "obj", b"old" * 50)
    cache.get_object(BUCKET, "obj")
    # Write through the RAW layer (bypassing cache invalidation) to create a
    # stale cache entry; the ETag check must catch it.
    backend._layer.put_object(BUCKET, "obj", b"new" * 50)
    _, got = cache.get_object(BUCKET, "obj")
    assert got == b"new" * 50


def test_backend_down_serves_cached(cached):
    backend, cache = cached
    data = b"survive" * 1000
    cache.put_object(BUCKET, "offline.bin", data)
    cache.get_object(BUCKET, "offline.bin")  # fill
    backend.offline = True
    oi, got = cache.get_object(BUCKET, "offline.bin")
    assert got == data
    # Uncached objects fail as usual while the backend is down.
    with pytest.raises(errors.StorageError):
        cache.get_object(BUCKET, "never-cached.bin")


def test_delete_invalidates(cached):
    backend, cache = cached
    cache.put_object(BUCKET, "gone", b"x" * 100)
    cache.get_object(BUCKET, "gone")
    cache.delete_object(BUCKET, "gone")
    with pytest.raises(errors.ObjectNotFound):
        cache.get_object(BUCKET, "gone")


def test_after_threshold(tmp_path):
    hz = ErasureHarness(tmp_path / "disks", n_disks=4)
    layer = ServerPools([ErasureSets(list(hz.drives), 4)])
    layer.make_bucket(BUCKET)
    backend = CountingBackend(layer)
    cache = CacheObjectLayer(backend, CacheConfig(drives=[str(tmp_path / "c")], after=3))
    cache.put_object(BUCKET, "warm", b"w" * 500)
    for _ in range(2):  # below threshold: every read hits the backend
        cache.get_object(BUCKET, "warm")
    calls = backend.get_calls
    cache.get_object(BUCKET, "warm")  # 3rd read caches
    assert backend.get_calls == calls + 1
    cache.get_object(BUCKET, "warm")  # now served from cache
    assert backend.get_calls == calls + 1


def test_range_reads(cached):
    backend, cache = cached
    data = bytes(range(256)) * 100
    cache.put_object(BUCKET, "ranged", data)
    cache.get_object(BUCKET, "ranged")  # whole-object fill
    calls = backend.get_calls
    _, part = cache.get_object(BUCKET, "ranged", offset=100, length=50)
    assert part == data[100:150]
    assert backend.get_calls == calls  # range served from whole-object entry


def test_lru_gc_watermarks(tmp_path):
    hz = ErasureHarness(tmp_path / "disks", n_disks=4)
    layer = ServerPools([ErasureSets(list(hz.drives), 4)])
    layer.make_bucket(BUCKET)
    cfg = CacheConfig(drives=[str(tmp_path / "c")], quota_bytes=100_000)
    cache = CacheObjectLayer(layer, cfg)
    for i in range(12):  # 12 x 10 KB > 80 KB high watermark
        cache.put_object(BUCKET, f"o{i}", bytes([i]) * 10_000)
        cache.get_object(BUCKET, f"o{i}")
    usage = cache.drives[0].usage()
    assert usage <= cfg.quota_bytes * cfg.watermark_high + 11_000
    # Newest entries survive (LRU evicts the oldest atimes first).
    st = cache.stats()
    assert st["drives"][0]["usage"] == usage


def test_versioned_reads_bypass_cache(cached):
    from minio_tpu.object.types import GetObjectOptions, PutObjectOptions

    backend, cache = cached
    v1 = cache.put_object(BUCKET, "ver", b"one", PutObjectOptions(versioned=True)).version_id
    cache.put_object(BUCKET, "ver", b"two", PutObjectOptions(versioned=True))
    cache.get_object(BUCKET, "ver")
    calls = backend.get_calls
    _, got = cache.get_object(BUCKET, "ver", GetObjectOptions(version_id=v1))
    assert got == b"one"
    assert backend.get_calls == calls + 1  # versioned read went to the backend


def test_exclude_patterns(tmp_path):
    hz = ErasureHarness(tmp_path / "disks", n_disks=4)
    layer = ServerPools([ErasureSets(list(hz.drives), 4)])
    layer.make_bucket(BUCKET)
    backend = CountingBackend(layer)
    cache = CacheObjectLayer(
        backend, CacheConfig(drives=[str(tmp_path / "c")], exclude=[f"{BUCKET}/tmp"])
    )
    cache.put_object(BUCKET, "tmp/skip.bin", b"s" * 100)
    cache.put_object(BUCKET, "keep.bin", b"k" * 100)
    for _ in range(2):
        cache.get_object(BUCKET, "tmp/skip.bin")
        cache.get_object(BUCKET, "keep.bin")
    # excluded: 2 backend reads; cached: 1 backend read.
    assert backend.get_calls == 3


def test_internal_metadata_survives_cache_hit(cached):
    """SSE/compression markers live in ObjectInfo.internal; the handler's
    decrypt/decompress path keys off them, so a cache hit must return them."""
    from minio_tpu.object.types import PutObjectOptions

    backend, cache = cached
    opts = PutObjectOptions(user_defined={"x-internal-compression": "s2"})
    cache.put_object(BUCKET, "marked", b"m" * 200, opts)
    oi1, _ = cache.get_object(BUCKET, "marked")  # fill
    oi2, _ = cache.get_object(BUCKET, "marked")  # hit
    assert oi2.internal == oi1.internal
    assert oi2.internal.get("x-internal-compression") == "s2"
