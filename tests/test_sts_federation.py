"""STS federation flows: WebIdentity / ClientGrants (OIDC JWT), Certificate,
LDAP gating (reference cmd/sts-handlers.go:301-692)."""

import base64
import json
import time

import pytest

from minio_tpu.api import jwt as jwt_mod
from minio_tpu.api.server import S3Server, ThreadedServer
from minio_tpu.control.config import ConfigSys
from minio_tpu.control.iam import IAMSys
from minio_tpu.object.pools import ServerPools
from minio_tpu.object.sets import ErasureSets
from tests.harness import ErasureHarness
from tests.s3client import S3TestClient

HMAC_SECRET = "oidc-shared-secret"
READ_POLICY = {
    "Version": "2012-10-17",
    "Statement": [
        {"Effect": "Allow", "Action": ["s3:GetObject", "s3:ListBucket"], "Resource": ["arn:aws:s3:::*"]}
    ],
}


def _rsa_keypair():
    from cryptography.hazmat.primitives.asymmetric import rsa

    key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    pub = key.public_key().public_numbers()

    def b64url_uint(v: int) -> str:
        raw = v.to_bytes((v.bit_length() + 7) // 8, "big")
        return base64.urlsafe_b64encode(raw).rstrip(b"=").decode()

    jwks = {"keys": [{"kty": "RSA", "kid": "k1", "n": b64url_uint(pub.n), "e": b64url_uint(pub.e)}]}
    return key, jwks


def _sign_rs256(key, payload: dict, kid: str = "k1") -> str:
    from cryptography.hazmat.primitives import hashes
    from cryptography.hazmat.primitives.asymmetric import padding

    def enc(obj) -> str:
        return base64.urlsafe_b64encode(json.dumps(obj).encode()).rstrip(b"=").decode()

    signing_input = f"{enc({'alg': 'RS256', 'typ': 'JWT', 'kid': kid})}.{enc(payload)}"
    sig = key.sign(signing_input.encode(), padding.PKCS1v15(), hashes.SHA256())
    return signing_input + "." + base64.urlsafe_b64encode(sig).rstrip(b"=").decode()


@pytest.fixture(scope="module")
def fed(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("stsfed")
    hz = ErasureHarness(tmp, n_disks=4)
    layer = ServerPools([ErasureSets(list(hz.drives), 4)])
    iam = IAMSys("fedroot", "fedroot-secret")
    iam.set_policy("token-readers", READ_POLICY)
    config = ConfigSys()
    key, jwks = _rsa_keypair()
    config.set("identity_openid", "jwks", json.dumps(jwks))
    config.set("identity_openid", "hmac_secret", HMAC_SECRET)
    config.set("identity_openid", "client_id", "mtpu-app")
    srv = S3Server(layer, iam, check_skew=False, config=config)
    ts = ThreadedServer(srv)
    endpoint = ts.start()
    root = S3TestClient(endpoint, "fedroot", "fedroot-secret")
    root.make_bucket("fedbkt")
    root.put_object("fedbkt", "data.txt", b"federated read")
    yield {"endpoint": endpoint, "key": key, "root": root, "iam": iam, "config": config}
    ts.stop()


def _sts_post(endpoint, form: dict) -> "requests.Response":
    import requests

    return requests.post(endpoint + "/", data=form, timeout=10)


def _extract_creds(xml_text: str) -> tuple[str, str]:
    import re

    ak = re.search(r"<AccessKeyId>([^<]+)</AccessKeyId>", xml_text).group(1)
    sk = re.search(r"<SecretAccessKey>([^<]+)</SecretAccessKey>", xml_text).group(1)
    return ak, sk


def test_web_identity_rs256(fed):
    token = _sign_rs256(
        fed["key"],
        {"sub": "alice@idp", "aud": "mtpu-app", "exp": time.time() + 3600, "policy": "token-readers"},
    )
    r = _sts_post(
        fed["endpoint"],
        {"Action": "AssumeRoleWithWebIdentity", "WebIdentityToken": token, "Version": "2011-06-15"},
    )
    assert r.status_code == 200, r.text
    assert "<SubjectFromWebIdentityToken>alice@idp</SubjectFromWebIdentityToken>" in r.text
    ak, sk = _extract_creds(r.text)
    c = S3TestClient(fed["endpoint"], ak, sk)
    assert c.get_object("fedbkt", "data.txt").content == b"federated read"
    # The mapped policy grants reads only.
    assert c.put_object("fedbkt", "write.txt", b"nope").status_code == 403


def test_client_grants_hs256(fed):
    token = jwt_mod.sign_hs256(
        {"sub": "svc-1", "aud": "mtpu-app", "exp": time.time() + 600, "policy": "token-readers"},
        HMAC_SECRET,
    )
    r = _sts_post(
        fed["endpoint"],
        {"Action": "AssumeRoleWithClientGrants", "Token": token, "Version": "2011-06-15"},
    )
    assert r.status_code == 200, r.text
    ak, sk = _extract_creds(r.text)
    c = S3TestClient(fed["endpoint"], ak, sk)
    assert c.get_object("fedbkt", "data.txt").status_code == 200


def test_bad_signature_rejected(fed):
    token = jwt_mod.sign_hs256(
        {"sub": "eve", "aud": "mtpu-app", "exp": time.time() + 600, "policy": "token-readers"},
        "wrong-secret",
    )
    r = _sts_post(
        fed["endpoint"],
        {"Action": "AssumeRoleWithWebIdentity", "WebIdentityToken": token},
    )
    assert r.status_code == 403


def test_expired_token_rejected(fed):
    token = jwt_mod.sign_hs256(
        {"sub": "late", "aud": "mtpu-app", "exp": time.time() - 10, "policy": "token-readers"},
        HMAC_SECRET,
    )
    r = _sts_post(
        fed["endpoint"],
        {"Action": "AssumeRoleWithWebIdentity", "WebIdentityToken": token},
    )
    assert r.status_code == 403


def test_audience_mismatch_rejected(fed):
    token = jwt_mod.sign_hs256(
        {"sub": "other", "aud": "other-app", "exp": time.time() + 600, "policy": "token-readers"},
        HMAC_SECRET,
    )
    r = _sts_post(
        fed["endpoint"],
        {"Action": "AssumeRoleWithWebIdentity", "WebIdentityToken": token},
    )
    assert r.status_code == 403


def test_missing_policy_claim_rejected(fed):
    token = jwt_mod.sign_hs256(
        {"sub": "nopol", "aud": "mtpu-app", "exp": time.time() + 600},
        HMAC_SECRET,
    )
    r = _sts_post(
        fed["endpoint"],
        {"Action": "AssumeRoleWithWebIdentity", "WebIdentityToken": token},
    )
    assert r.status_code == 403


def test_cred_lifetime_capped_by_token_exp(fed):
    token = jwt_mod.sign_hs256(
        {"sub": "short", "aud": "mtpu-app", "exp": time.time() + 1000, "policy": "token-readers"},
        HMAC_SECRET,
    )
    r = _sts_post(
        fed["endpoint"],
        {
            "Action": "AssumeRoleWithWebIdentity",
            "WebIdentityToken": token,
            "DurationSeconds": "86400",
        },
    )
    assert r.status_code == 200
    ak, _ = _extract_creds(r.text)
    ident = fed["iam"].users[ak]
    assert ident.expiration <= time.time() + 1001


def test_ldap_gated(fed):
    r = _sts_post(fed["endpoint"], {"Action": "AssumeRoleWithLDAPIdentity"})
    assert r.status_code == 501


# -- LDAP identity (stub LDAP server; cmd/sts-handlers.go:447 role) ----------

ALICE_DN = "uid=alice,ou=people,dc=example,dc=org"
DEVS_DN = "cn=devs,ou=groups,dc=example,dc=org"


@pytest.fixture()
def ldap(fed):
    from tests.ldapstub import StubLDAP

    stub = StubLDAP(
        directory={
            ALICE_DN: {"uid": ["alice"], "objectclass": ["person"]},
            "uid=bob,ou=people,dc=example,dc=org": {"uid": ["bob"], "objectclass": ["person"]},
            DEVS_DN: {"objectclass": ["groupOfNames"], "member": [ALICE_DN]},
        },
        passwords={
            ALICE_DN: "alice-pw",
            "uid=bob,ou=people,dc=example,dc=org": "bob-pw",
            "cn=lookup,dc=example,dc=org": "lookup-pw",
        },
    )
    cfg_keys = {
        "server_addr": stub.addr,
        "lookup_bind_dn": "cn=lookup,dc=example,dc=org",
        "lookup_bind_password": "lookup-pw",
        "user_dn_search_base_dn": "ou=people,dc=example,dc=org",
        "user_dn_search_filter": "(uid=%s)",
        "group_search_base_dn": "ou=groups,dc=example,dc=org",
        "group_search_filter": "(&(objectclass=groupOfNames)(member=%d))",
    }
    config = fed["config"]  # fed shares one ConfigSys
    for k, v in cfg_keys.items():
        config.set("identity_ldap", k, v)
    yield stub
    for k in cfg_keys:
        config.unset("identity_ldap", k)
    fed["iam"].ldap_policy_map.clear()
    stub.close()


def _ldap_sts(fed, user, pw):
    return _sts_post(
        fed["endpoint"],
        {
            "Action": "AssumeRoleWithLDAPIdentity",
            "LDAPUsername": user,
            "LDAPPassword": pw,
            "Version": "2011-06-15",
        },
    )


def test_ldap_sts_flow_end_to_end(fed, ldap):
    fed["iam"].set_ldap_policy(ALICE_DN, ["token-readers"])
    r = _ldap_sts(fed, "alice", "alice-pw")
    assert r.status_code == 200, r.text
    ak, sk = _extract_creds(r.text)
    c = S3TestClient(fed["endpoint"], ak, sk)
    assert c.get_object("fedbkt", "data.txt").content == b"federated read"
    # read-only policy: writes are denied
    assert c.request("PUT", "/fedbkt/new.txt", body=b"x").status_code == 403


def test_ldap_group_policy_mapping(fed, ldap):
    # Policy attached to the GROUP DN only; alice inherits via membership.
    fed["iam"].set_ldap_policy(DEVS_DN, ["token-readers"])
    r = _ldap_sts(fed, "alice", "alice-pw")
    assert r.status_code == 200, r.text
    ak, sk = _extract_creds(r.text)
    c = S3TestClient(fed["endpoint"], ak, sk)
    assert c.get_object("fedbkt", "data.txt").status_code == 200
    # bob is not in devs and has no mapping
    r = _ldap_sts(fed, "bob", "bob-pw")
    assert r.status_code == 403


def test_ldap_wrong_password_rejected(fed, ldap):
    fed["iam"].set_ldap_policy(ALICE_DN, ["token-readers"])
    r = _ldap_sts(fed, "alice", "wrong")
    assert r.status_code == 403
    # the user bind was attempted and failed; no credential was minted
    assert "<AccessKeyId>" not in r.text


def test_ldap_empty_password_rejected(fed, ldap):
    # RFC 4513 anonymous-bind bypass: empty password must be rejected
    # client-side, never sent to the server as a bind.
    fed["iam"].set_ldap_policy(ALICE_DN, ["token-readers"])
    before = list(ldap.binds)
    r = _ldap_sts(fed, "alice", "")
    assert r.status_code == 400
    assert ldap.binds == before


def test_ldap_unknown_user(fed, ldap):
    r = _ldap_sts(fed, "mallory", "x")
    assert r.status_code == 403


def test_ldap_filter_injection_escaped(fed, ldap):
    # A username that would widen the filter to (uid=*) must not match.
    fed["iam"].set_ldap_policy(ALICE_DN, ["token-readers"])
    r = _ldap_sts(fed, "*", "alice-pw")
    assert r.status_code == 403


def test_ldap_filter_compile_unit():
    from minio_tpu.control import ldap as ldap_mod

    f = ldap_mod.compile_filter("(&(objectclass=person)(uid=al\\2aice))")
    assert f[0] == ldap_mod.FILTER_AND
    assert ldap_mod.compile_filter("(uid=*)")[0] == ldap_mod.FILTER_PRESENT
    with pytest.raises(ldap_mod.LDAPError):
        ldap_mod.compile_filter("(uid=par*tial)")
    assert ldap_mod.escape_filter_value("a*(b)\\c") == "a\\2a\\28b\\29\\5cc"


def test_certificate_flow_unit():
    """Certificate flow exercised at the handler level with a fake mTLS
    transport (booting real mTLS needs CA tooling; the ssl-module cert dict
    shape is what aiohttp exposes)."""
    from minio_tpu.api import sts as sts_mod
    from minio_tpu.api.errors import S3Error

    iam = IAMSys("r", "rsecretsecret")
    iam.set_policy("edge-device", READ_POLICY)
    config = ConfigSys()

    class FakeTransport:
        def __init__(self, cert):
            self._cert = cert

        def get_extra_info(self, name):
            return self._cert if name == "peercert" else None

    class FakeRequest:
        def __init__(self, cert):
            self.transport = FakeTransport(cert)

    cert = {"subject": ((("commonName", "edge-device"),),)}

    # Gated off by default.
    with pytest.raises(S3Error) as ei:
        sts_mod.handle_sts(iam, "", {"Action": "AssumeRoleWithCertificate"}, config, FakeRequest(cert))
    assert ei.value.code == "NotImplemented"

    config.set("identity_tls", "enable", "on")
    resp = sts_mod.handle_sts(
        iam, "", {"Action": "AssumeRoleWithCertificate"}, config, FakeRequest(cert)
    )
    assert resp.status == 200
    text = resp.body.decode()
    ak, _ = _extract_creds(text)
    assert iam.users[ak].policies == ["edge-device"]

    # No certificate on the connection -> InvalidRequest.
    with pytest.raises(S3Error) as ei:
        sts_mod.handle_sts(iam, "", {"Action": "AssumeRoleWithCertificate"}, config, FakeRequest(None))
    assert ei.value.code == "InvalidRequest"


def test_session_policy_narrows_federated_creds(fed):
    """The Policy parameter can only NARROW the mapped policies (the
    unenforced-session-policy hole: creds must not exceed the session
    policy even though the claim maps to a broader policy)."""
    narrow = {
        "Version": "2012-10-17",
        "Statement": [
            {"Effect": "Allow", "Action": ["s3:ListBucket"], "Resource": ["arn:aws:s3:::fedbkt"]}
        ],
    }
    token = jwt_mod.sign_hs256(
        {"sub": "narrowed", "aud": "mtpu-app", "exp": time.time() + 600, "policy": "token-readers"},
        HMAC_SECRET,
    )
    r = _sts_post(
        fed["endpoint"],
        {
            "Action": "AssumeRoleWithWebIdentity",
            "WebIdentityToken": token,
            "Policy": json.dumps(narrow),
        },
    )
    assert r.status_code == 200, r.text
    ak, sk = _extract_creds(r.text)
    c = S3TestClient(fed["endpoint"], ak, sk)
    # ListBucket allowed by both; GetObject allowed by mapped policy but
    # denied by the session policy.
    assert c.request("GET", "/fedbkt", query=[("list-type", "2")]).status_code == 200
    assert c.get_object("fedbkt", "data.txt").status_code == 403
