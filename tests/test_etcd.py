"""etcd-backed IAM store (iam-etcd-store.go role) against an in-process
stub speaking the v3 JSON gateway."""

import base64
import json
import threading

import pytest

from minio_tpu.control.etcd import EtcdClient, EtcdStore, etcd_store_from_env
from minio_tpu.control.iam import IAMSys
from minio_tpu.utils import errors


class StubEtcd:
    """v3 JSON gateway subset: /v3/kv/put, /v3/kv/range, /v3/kv/deleterange
    over an in-memory dict. Counts requests for wiring assertions."""

    def __init__(self):
        import http.server

        self.kv: dict[bytes, bytes] = {}
        self.requests: list[str] = []
        stub = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_POST(self):
                stub.requests.append(self.path)
                n = int(self.headers.get("Content-Length", 0))
                req = json.loads(self.rfile.read(n) or b"{}")
                key = base64.b64decode(req.get("key", ""))
                if self.path.endswith("/kv/put"):
                    stub.kv[key] = base64.b64decode(req.get("value", ""))
                    out = {}
                elif self.path.endswith("/kv/range"):
                    v = stub.kv.get(key)
                    out = {"kvs": [] if v is None else [
                        {"key": base64.b64encode(key).decode(),
                         "value": base64.b64encode(v).decode()}
                    ], "count": "0" if v is None else "1"}
                elif self.path.endswith("/kv/deleterange"):
                    out = {"deleted": str(int(stub.kv.pop(key, None) is not None))}
                elif self.path.endswith("/maintenance/status"):
                    out = {"version": "3.5-stub"}
                else:
                    self.send_response(404)
                    self.end_headers()
                    return
                body = json.dumps(out).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self.httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.endpoint = f"http://127.0.0.1:{self.httpd.server_address[1]}"
        threading.Thread(target=self.httpd.serve_forever, daemon=True).start()

    def close(self):
        self.httpd.shutdown()


@pytest.fixture()
def etcd():
    stub = StubEtcd()
    yield stub
    stub.close()


class TestEtcd:
    def test_kv_roundtrip(self, etcd):
        c = EtcdClient(etcd.endpoint)
        c.put(b"k1", b"v1")
        assert c.get(b"k1") == b"v1"
        assert c.get(b"absent") is None
        c.delete(b"k1")
        assert c.get(b"k1") is None
        assert c.status()["online"] is True

    def test_unreachable_raises_not_none(self):
        c = EtcdClient("http://127.0.0.1:9")  # discard port: refused
        with pytest.raises(errors.StorageError):
            c.get(b"k")  # "can't read" must never read as "empty store"
        assert c.status()["online"] is False

    def test_iam_persists_in_etcd_sealed(self, etcd):
        store = EtcdStore(EtcdClient(etcd.endpoint))
        iam = IAMSys("rootak", "root-secret-key", store=store)
        iam.add_user("etcduser", "etcdsecret123", ["readonly"])
        # sealed at rest inside etcd, as the reference encrypts its
        # etcd IAM payloads
        blob = etcd.kv[b"minio_tpu/config/iam/users.json"]
        assert b"etcdsecret123" not in blob
        assert blob.startswith(b"MTPUIAM1")
        # a second node sharing the etcd cluster sees the identity
        other = IAMSys("rootak", "root-secret-key", store=store)
        other.load()
        assert other.lookup("etcduser").secret_key == "etcdsecret123"
        assert other.users["etcduser"].policies == ["readonly"]

    def test_two_gateways_no_lock_still_converge(self, etcd):
        # Two gateway processes share one etcd, NO cluster lock: serialized
        # mutations must still not clobber each other (refresh-before-apply
        # is unconditional when a store is present).
        store = EtcdStore(EtcdClient(etcd.endpoint))
        a = IAMSys("rootak", "root-secret-key", store=store)
        b = IAMSys("rootak", "root-secret-key", store=store)
        a.add_user("gw-a", "secretaaaa123")
        b.add_user("gw-b", "secretbbbb123")
        a.attach_policy("gw-b", ["readonly"])  # A can even see B's user now
        fresh = IAMSys("rootak", "root-secret-key", store=store)
        fresh.load()
        assert fresh.lookup("gw-a") is not None
        assert fresh.lookup("gw-b") is not None
        assert fresh.users["gw-b"].policies == ["readonly"]

    def test_env_wiring(self, etcd, monkeypatch):
        monkeypatch.setenv("MINIO_TPU_ETCD_ENDPOINT", etcd.endpoint)
        store = etcd_store_from_env()
        assert store is not None
        store.put("x", b"y")
        assert etcd.kv[b"minio_tpu/x"] == b"y"
        monkeypatch.delenv("MINIO_TPU_ETCD_ENDPOINT")
        assert etcd_store_from_env() is None

    def test_node_boot_uses_etcd_for_iam(self, etcd, tmp_path, monkeypatch):
        # Full node boot with MINIO_TPU_ETCD_ENDPOINT: IAM mutations land in
        # etcd, and a second node (fresh drives, same etcd) sees them — the
        # federated-IAM sharing mode the reference uses etcd for.
        import os

        from minio_tpu.dist.node import Node
        from minio_tpu.object.codec import HostCodec

        monkeypatch.setenv("MINIO_TPU_ETCD_ENDPOINT", etcd.endpoint)
        dirs = []
        for i in range(4):
            d = str(tmp_path / f"e{i}")
            os.makedirs(d)
            dirs.append(d)
        node = Node(dirs, root_user="edroot", root_password="edsecret1234", codec=HostCodec())
        node.build()
        node.iam.add_user("shared", "sharedsecret1")
        assert any(k.endswith(b"users.json") for k in etcd.kv)

        dirs2 = []
        for i in range(4):
            d = str(tmp_path / f"f{i}")
            os.makedirs(d)
            dirs2.append(d)
        node2 = Node(dirs2, root_user="edroot", root_password="edsecret1234", codec=HostCodec())
        node2.build()
        assert node2.iam.lookup("shared").secret_key == "sharedsecret1"
