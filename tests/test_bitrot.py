"""Bitrot format tests: interleaved stream layout, verification, corruption."""

import numpy as np
import pytest

from minio_tpu.ops import bitrot
from minio_tpu.ops.bitrot import BitrotAlgorithm, BitrotCorrupt


def test_shard_file_size_formula():
    # ceil(size/shardSize)*32 + size (cmd/bitrot.go:146-151)
    assert bitrot.shard_file_size(0, 100) == 0
    assert bitrot.shard_file_size(100, 100) == 132
    assert bitrot.shard_file_size(101, 100) == 165
    assert bitrot.shard_file_size(87382 * 16, 87382) == 87382 * 16 + 16 * 32
    assert bitrot.shard_file_size(500, 100, BitrotAlgorithm.SHA256) == 500


def _build_stream(part_size=1000, shard_size=256):
    rng = np.random.default_rng(0)
    data = rng.integers(0, 256, part_size).astype(np.uint8).tobytes()
    w = bitrot.StreamingBitrotWriter()
    for off in range(0, part_size, shard_size):
        w.write(data[off : off + shard_size])
    return data, w.getvalue()


def test_roundtrip_and_verify():
    part_size, shard_size = 1000, 256
    data, blob = _build_stream(part_size, shard_size)
    assert len(blob) == bitrot.shard_file_size(part_size, shard_size)
    bitrot.verify_stream(blob, part_size, shard_size)
    r = bitrot.StreamingBitrotReader(blob, shard_size)
    out = b"".join(r.read_chunk(off) for off in range(0, part_size, shard_size))
    assert out == data


def test_corruption_detected():
    part_size, shard_size = 1000, 256
    _, blob = _build_stream(part_size, shard_size)
    bad = bytearray(blob)
    bad[40] ^= 0xFF  # flip a data byte in the first chunk
    with pytest.raises(BitrotCorrupt):
        bitrot.verify_stream(bytes(bad), part_size, shard_size)
    r = bitrot.StreamingBitrotReader(bytes(bad), shard_size)
    with pytest.raises(BitrotCorrupt):
        r.read_chunk(0)
    # Later chunks still verify (damage is localized).
    assert r.read_chunk(256)


def test_truncation_detected():
    part_size, shard_size = 1000, 256
    _, blob = _build_stream(part_size, shard_size)
    with pytest.raises(BitrotCorrupt):
        bitrot.verify_stream(blob[:-1], part_size, shard_size)


def test_whole_file_algorithms():
    data = b"hello world" * 10
    for algo in (BitrotAlgorithm.SHA256, BitrotAlgorithm.BLAKE2B512, BitrotAlgorithm.HIGHWAYHASH256):
        h = algo.new()
        h.update(data)
        digest = h.digest()
        bitrot.verify_stream(data, len(data), 0, algo, want_sum=digest)
        with pytest.raises(BitrotCorrupt):
            bitrot.verify_stream(data + b"x", 0, 0, algo, want_sum=digest)


def test_precomputed_digest_path():
    # Device-batch path: digests computed elsewhere and handed to the writer.
    from minio_tpu.ops import highwayhash as hh

    chunk = b"z" * 128
    w = bitrot.StreamingBitrotWriter()
    w.write(chunk, digest=hh.hash256(chunk))
    bitrot.verify_stream(w.getvalue(), 128, 128)
