"""Multipart upload tests: object layer + S3 API (erasure-multipart_test.go
analogues)."""

import xml.etree.ElementTree as ET

import numpy as np
import pytest

from minio_tpu.utils import errors
from tests.harness import ErasureHarness

BUCKET = "mpbucket"
MIN_PART = 5 * (1 << 20)
NS = "{http://s3.amazonaws.com/doc/2006-03-01/}"


def _data(n, seed=0):
    return np.random.default_rng(seed).integers(0, 256, n).astype("u1").tobytes()


@pytest.fixture
def hz(tmp_path):
    h = ErasureHarness(tmp_path, n_disks=8)
    h.layer.make_bucket(BUCKET)
    return h


class TestMultipartLayer:
    def test_full_flow(self, hz):
        mp = hz.layer.multipart
        uid = mp.new_multipart_upload(BUCKET, "big-obj")
        p1_data = _data(MIN_PART, 1)
        p2_data = _data(MIN_PART + 12345, 2)
        p3_data = _data(1000, 3)  # last part may be small
        p1 = mp.put_object_part(BUCKET, "big-obj", uid, 1, p1_data)
        p2 = mp.put_object_part(BUCKET, "big-obj", uid, 2, p2_data)
        p3 = mp.put_object_part(BUCKET, "big-obj", uid, 3, p3_data)
        parts = mp.list_parts(BUCKET, "big-obj", uid)
        assert [p.number for p in parts] == [1, 2, 3]
        oi = mp.complete_multipart_upload(
            BUCKET, "big-obj", uid, [(1, p1.etag), (2, p2.etag), (3, p3.etag)]
        )
        assert oi.size == len(p1_data) + len(p2_data) + len(p3_data)
        assert oi.etag.endswith("-3")
        _, got = hz.layer.get_object(BUCKET, "big-obj")
        assert got == p1_data + p2_data + p3_data
        # Upload is gone after completion.
        with pytest.raises(errors.InvalidUploadID):
            mp.list_parts(BUCKET, "big-obj", uid)

    def test_part_overwrite(self, hz):
        mp = hz.layer.multipart
        uid = mp.new_multipart_upload(BUCKET, "obj")
        mp.put_object_part(BUCKET, "obj", uid, 1, _data(MIN_PART, 4))
        newer = mp.put_object_part(BUCKET, "obj", uid, 1, _data(MIN_PART, 5))
        oi = mp.complete_multipart_upload(BUCKET, "obj", uid, [(1, newer.etag)])
        _, got = hz.layer.get_object(BUCKET, "obj")
        assert got == _data(MIN_PART, 5)

    def test_abort(self, hz):
        mp = hz.layer.multipart
        uid = mp.new_multipart_upload(BUCKET, "obj")
        mp.put_object_part(BUCKET, "obj", uid, 1, b"x" * 100)
        mp.abort_multipart_upload(BUCKET, "obj", uid)
        with pytest.raises(errors.InvalidUploadID):
            mp.put_object_part(BUCKET, "obj", uid, 2, b"y")
        with pytest.raises(errors.ObjectNotFound):
            hz.layer.get_object(BUCKET, "obj")

    def test_bad_part_etag(self, hz):
        mp = hz.layer.multipart
        uid = mp.new_multipart_upload(BUCKET, "obj")
        mp.put_object_part(BUCKET, "obj", uid, 1, b"x" * 100)
        with pytest.raises(errors.InvalidPart):
            mp.complete_multipart_upload(BUCKET, "obj", uid, [(1, "deadbeef" * 4)])

    def test_min_part_size_enforced(self, hz):
        mp = hz.layer.multipart
        uid = mp.new_multipart_upload(BUCKET, "obj")
        p1 = mp.put_object_part(BUCKET, "obj", uid, 1, b"small")
        p2 = mp.put_object_part(BUCKET, "obj", uid, 2, b"also-small")
        with pytest.raises(errors.InvalidArgument):
            mp.complete_multipart_upload(BUCKET, "obj", uid, [(1, p1.etag), (2, p2.etag)])

    def test_unknown_upload(self, hz):
        mp = hz.layer.multipart
        with pytest.raises(errors.InvalidUploadID):
            mp.put_object_part(BUCKET, "obj", "no-such-id", 1, b"x")

    def test_list_uploads(self, hz):
        mp = hz.layer.multipart
        uid1 = mp.new_multipart_upload(BUCKET, "a/obj1")
        uid2 = mp.new_multipart_upload(BUCKET, "b/obj2")
        ups = mp.list_multipart_uploads(BUCKET)
        assert {(u["object"], u["upload_id"]) for u in ups} == {("a/obj1", uid1), ("b/obj2", uid2)}

    def test_multipart_object_heals(self, hz):
        mp = hz.layer.multipart
        uid = mp.new_multipart_upload(BUCKET, "healme")
        p1 = mp.put_object_part(BUCKET, "healme", uid, 1, _data(MIN_PART, 6))
        p2 = mp.put_object_part(BUCKET, "healme", uid, 2, _data(2000, 7))
        mp.complete_multipart_upload(BUCKET, "healme", uid, [(1, p1.etag), (2, p2.etag)])
        hz.delete_object_dir(0, BUCKET, "healme")
        res = hz.layer.heal_object(BUCKET, "healme")
        assert res.disks_healed == 1
        hz.take_offline(1, 2)  # parity=2 on 8 drives... keep within budget
        _, got = hz.layer.get_object(BUCKET, "healme")
        assert got == _data(MIN_PART, 6) + _data(2000, 7)


# The ErasureHarness exposes a single-set layer; ServerPools-level multipart
# goes through the S3 API tests below.


class TestMultipartAPI:
    @pytest.fixture(scope="class")
    def stack(self, tmp_path_factory):
        from minio_tpu.api.server import S3Server, ThreadedServer
        from minio_tpu.control.iam import IAMSys
        from minio_tpu.object.pools import ServerPools
        from minio_tpu.object.sets import ErasureSets
        from tests.s3client import S3TestClient

        tmp = tmp_path_factory.mktemp("mpapi")
        hz = ErasureHarness(tmp, n_disks=8)
        layer = ServerPools([ErasureSets(list(hz.drives), 8)])
        iam = IAMSys("ak", "sk-secret")
        srv = S3Server(layer, iam, check_skew=False)
        from minio_tpu.api.server import ThreadedServer as TS

        ts = TS(srv)
        endpoint = ts.start()
        client = S3TestClient(endpoint, "ak", "sk-secret")
        client.make_bucket("mpapi")
        yield client
        ts.stop()

    def test_api_flow(self, stack):
        client = stack
        r = client.request("POST", "/mpapi/big", query=[("uploads", "")])
        assert r.status_code == 200, r.text
        uid = ET.fromstring(r.content).find(f"{NS}UploadId").text
        data1 = _data(MIN_PART, 8)
        data2 = _data(100, 9)
        e1 = client.request(
            "PUT", "/mpapi/big", query=[("partNumber", "1"), ("uploadId", uid)], body=data1
        ).headers["ETag"]
        e2 = client.request(
            "PUT", "/mpapi/big", query=[("partNumber", "2"), ("uploadId", uid)], body=data2
        ).headers["ETag"]
        # List parts.
        r = client.request("GET", "/mpapi/big", query=[("uploadId", uid)])
        nums = [int(e.text) for e in ET.fromstring(r.content).iter(f"{NS}PartNumber")]
        assert nums == [1, 2]
        # List in-progress uploads.
        r = client.request("GET", "/mpapi", query=[("uploads", "")])
        assert uid in r.text
        body = (
            f"<CompleteMultipartUpload><Part><PartNumber>1</PartNumber><ETag>{e1}</ETag></Part>"
            f"<Part><PartNumber>2</PartNumber><ETag>{e2}</ETag></Part></CompleteMultipartUpload>"
        ).encode()
        r = client.request("POST", "/mpapi/big", query=[("uploadId", uid)], body=body)
        assert r.status_code == 200, r.text
        assert b"CompleteMultipartUploadResult" in r.content
        got = client.get_object("mpapi", "big")
        assert got.content == data1 + data2
        assert got.headers["ETag"].endswith('-2"')

    def test_api_abort(self, stack):
        client = stack
        r = client.request("POST", "/mpapi/ab", query=[("uploads", "")])
        uid = ET.fromstring(r.content).find(f"{NS}UploadId").text
        client.request("PUT", "/mpapi/ab", query=[("partNumber", "1"), ("uploadId", uid)], body=b"x")
        r = client.request("DELETE", "/mpapi/ab", query=[("uploadId", uid)])
        assert r.status_code == 204
        r = client.request("GET", "/mpapi/ab", query=[("uploadId", uid)])
        assert r.status_code == 404


def test_multipart_rrs_storage_class(tmp_path):
    from minio_tpu.object.types import PutObjectOptions
    from tests.harness import ErasureHarness

    hz = ErasureHarness(tmp_path, n_disks=8)
    hz.layer.make_bucket("mprrs")
    mp = hz.layer.multipart
    uid = mp.new_multipart_upload(
        "mprrs", "obj", PutObjectOptions(storage_class="REDUCED_REDUNDANCY")
    )
    body = b"m" * (5 << 20)
    p1 = mp.put_object_part("mprrs", "obj", uid, 1, body)
    p2 = mp.put_object_part("mprrs", "obj", uid, 2, b"tail")
    oi = mp.complete_multipart_upload("mprrs", "obj", uid, [(1, p1.etag), (2, p2.etag)])
    assert oi.storage_class == "REDUCED_REDUNDANCY"
    fi, _, _ = hz.layer._read_quorum_fi("mprrs", "obj", "")
    assert fi.erasure.parity_blocks == 2 and fi.erasure.data_blocks == 6
    _, got = hz.layer.get_object("mprrs", "obj")
    assert got == body + b"tail"
