"""In-process stub LDAP server for STS tests.

Speaks the same RFC 4511 BER subset as minio_tpu.control.ldap (whose module
helpers it reuses from the server side): simple bind against a credential
map, subtree search with and/or/not/equality/present filters evaluated over
a tiny in-memory directory, unbind. Single-threaded per connection.
"""

from __future__ import annotations

import socket
import threading

from minio_tpu.control.ldap import (
    APP_BIND_REQ,
    APP_BIND_RESP,
    APP_SEARCH_DONE,
    APP_SEARCH_ENTRY,
    APP_SEARCH_REQ,
    APP_UNBIND,
    FILTER_AND,
    FILTER_EQ,
    FILTER_NOT,
    FILTER_OR,
    FILTER_PRESENT,
    TAG_OCTET,
    TAG_SEQ,
    LDAPError,
    ber_int,
    ber_read,
    ber_read_int,
    tlv,
)


def _parse_filter(tag: int, content: bytes):
    """BER filter -> ("and"|"or"|"not", [subs]) | ("eq", a, v) | ("present", a)."""
    if tag in (FILTER_AND, FILTER_OR, FILTER_NOT):
        subs, pos = [], 0
        while pos < len(content):
            t, c, pos = ber_read(content, pos)
            subs.append(_parse_filter(t, c))
        kind = {FILTER_AND: "and", FILTER_OR: "or", FILTER_NOT: "not"}[tag]
        return (kind, subs)
    if tag == FILTER_EQ:
        _, attr, pos = ber_read(content)
        _, val, _ = ber_read(content, pos)
        return ("eq", attr.decode().lower(), val.decode())
    if tag == FILTER_PRESENT:
        return ("present", content.decode().lower())
    raise LDAPError(f"stub: unsupported filter tag 0x{tag:02x}")


def _matches(flt, attrs: dict[str, list[str]]) -> bool:
    kind = flt[0]
    if kind == "and":
        return all(_matches(f, attrs) for f in flt[1])
    if kind == "or":
        return any(_matches(f, attrs) for f in flt[1])
    if kind == "not":
        return not _matches(flt[1][0], attrs)
    if kind == "eq":
        return flt[2] in attrs.get(flt[1], [])
    return flt[1] in attrs  # present


class StubLDAP:
    """directory: {dn: {attr: [values]}}; passwords: {dn: password}."""

    def __init__(self, directory: dict, passwords: dict):
        self.directory = {dn.lower(): (dn, attrs) for dn, attrs in directory.items()}
        self.passwords = {dn.lower(): pw for dn, pw in passwords.items()}
        self.binds: list[str] = []
        self._srv = socket.create_server(("127.0.0.1", 0))
        self.addr = f"127.0.0.1:{self._srv.getsockname()[1]}"
        self._stop = False
        threading.Thread(target=self._accept_loop, daemon=True).start()

    def close(self) -> None:
        self._stop = True
        try:
            self._srv.close()
        except OSError:
            pass

    def _accept_loop(self) -> None:
        while not self._stop:
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,), daemon=True).start()

    def _serve(self, conn: socket.socket) -> None:
        buf = b""
        bound_dn = ""
        try:
            while True:
                try:
                    tag, content, nxt = ber_read(buf)
                except LDAPError:
                    chunk = conn.recv(65536)
                    if not chunk:
                        return
                    buf += chunk
                    continue
                buf = buf[nxt:]
                assert tag == TAG_SEQ
                _, mid_raw, pos = ber_read(content)
                mid = ber_read_int(mid_raw)
                op_tag, op, _ = ber_read(content, pos)
                if op_tag == APP_UNBIND:
                    return
                if op_tag == APP_BIND_REQ:
                    _, _ver, pos = ber_read(op)
                    _, dn_raw, pos = ber_read(op, pos)
                    _, pw_raw, _ = ber_read(op, pos)
                    dn = dn_raw.decode()
                    self.binds.append(dn)
                    # RFC 4513: empty password = anonymous bind, always ok.
                    if not pw_raw:
                        bound_dn = ""
                        code = 0
                    elif self.passwords.get(dn.lower()) == pw_raw.decode():
                        bound_dn = dn
                        code = 0
                    else:
                        code = 49  # invalidCredentials
                    self._reply(conn, mid, APP_BIND_RESP, code)
                elif op_tag == APP_SEARCH_REQ:
                    _, base_raw, pos = ber_read(op)
                    _, _scope, pos = ber_read(op, pos)
                    _, _deref, pos = ber_read(op, pos)
                    _, _sz, pos = ber_read(op, pos)
                    _, _tm, pos = ber_read(op, pos)
                    _, _types, pos = ber_read(op, pos)
                    ftag = op[pos]
                    _, fcontent, pos = ber_read(op, pos)
                    flt = _parse_filter(ftag, fcontent)
                    base = base_raw.decode().lower()
                    for dn_l, (dn, attrs) in self.directory.items():
                        if not dn_l.endswith(base):
                            continue
                        low = {k.lower(): v for k, v in attrs.items()}
                        if _matches(flt, low):
                            attr_seq = b"".join(
                                tlv(TAG_SEQ,
                                    tlv(TAG_OCTET, k.encode())
                                    + tlv(0x31, b"".join(tlv(TAG_OCTET, v.encode()) for v in vs)))
                                for k, vs in attrs.items()
                            )
                            entry = tlv(
                                APP_SEARCH_ENTRY,
                                tlv(TAG_OCTET, dn.encode()) + tlv(TAG_SEQ, attr_seq),
                            )
                            conn.sendall(tlv(TAG_SEQ, ber_int(mid) + entry))
                    self._reply(conn, mid, APP_SEARCH_DONE, 0)
                else:
                    return
        except (OSError, AssertionError, LDAPError):
            return
        finally:
            try:
                conn.close()
            except OSError:
                pass

    @staticmethod
    def _reply(conn, mid: int, op_tag: int, code: int) -> None:
        body = (
            ber_int(code, 0x0A) + tlv(TAG_OCTET, b"") + tlv(TAG_OCTET, b"")
        )
        conn.sendall(tlv(TAG_SEQ, ber_int(mid) + tlv(op_tag, body)))
