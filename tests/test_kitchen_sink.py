"""Kitchen-sink integration: every round-5 subsystem live in ONE stack.

Per-feature suites prove features in isolation; this boots a single node
with compression + KES KMS + LDAP identity + groups + quotas +
notifications all configured at once, exercises the cross-feature flows,
then restarts the process-equivalent (fresh Node over the same drives)
and asserts the durable state all came back.
"""

import json
import os
from types import SimpleNamespace

import pytest

from minio_tpu.api.server import ThreadedServer
from minio_tpu.dist.node import Node
from minio_tpu.object.codec import HostCodec
from tests.ldapstub import StubLDAP
from tests.s3client import S3TestClient
from tests.test_sse_compress import _StubKES

ROOT, SECRET = "sinkroot1", "sink-secret-key1"
ALICE_DN = "uid=alice,ou=people,dc=sink,dc=org"


@pytest.fixture()
def stack(tmp_path, monkeypatch):
    kes = _StubKES()
    ldap = StubLDAP(
        directory={ALICE_DN: {"uid": ["alice"], "objectclass": ["person"]}},
        passwords={ALICE_DN: "alice-pw"},
    )
    monkeypatch.setenv("MINIO_TPU_KMS_KES_ENDPOINT", kes.endpoint)
    dirs = [str(tmp_path / f"d{i}") for i in range(4)]
    node = Node(dirs, root_user=ROOT, root_password=SECRET, codec=HostCodec())
    ts = ThreadedServer(SimpleNamespace(app=node.make_app()))
    base = ts.start()
    node.build()
    c = S3TestClient(base, ROOT, SECRET)
    node.config.set("compression", "enable", "on")
    for k, v in {
        "server_addr": ldap.addr,
        "lookup_bind_dn": "",
        "lookup_bind_password": "",
        "user_dn_search_base_dn": "ou=people,dc=sink,dc=org",
        "user_dn_search_filter": "(uid=%s)",
    }.items():
        node.config.set("identity_ldap", k, v)
    yield {"node": node, "ts": ts, "c": c, "base": base, "dirs": dirs,
           "kes": kes, "ldap": ldap}
    ts.stop()
    kes.close()
    ldap.close()


def test_everything_together_and_survives_restart(stack, tmp_path):
    c, node, base = stack["c"], stack["node"], stack["base"]

    # IAM: user in a group whose policy grants readwrite; LDAP mapping too.
    assert c.request(
        "POST", "/mtpu/admin/v1/users",
        body=json.dumps({"accessKey": "sinkuser", "secretKey": "sinksecret12"}).encode(),
    ).status_code == 200
    assert c.request("PUT", "/mtpu/admin/v1/groups/team",
                     body=json.dumps({"members": ["sinkuser"]}).encode()).status_code == 200
    assert c.request("PUT", "/mtpu/admin/v1/groups/team/policy",
                     body=json.dumps({"policies": ["readwrite"]}).encode()).status_code == 200
    assert c.request("PUT", "/mtpu/admin/v1/idp/ldap/policy",
                     body=json.dumps({"dn": ALICE_DN, "policies": ["readonly"]}).encode()
                     ).status_code == 200

    # Bucket with notification config; a compressed + SSE-KMS object.
    c.make_bucket("sink")
    xml = (
        '<NotificationConfiguration xmlns="http://s3.amazonaws.com/doc/2006-03-01/">'
        "<QueueConfiguration><Id>q</Id><Queue>arn:minio:sqs::1:sinktgt</Queue>"
        "<Event>s3:ObjectCreated:*</Event></QueueConfiguration>"
        "</NotificationConfiguration>"
    )
    assert c.request("PUT", "/sink", query=[("notification", "")], body=xml.encode()
                     ).status_code in (200, 204)
    events = []
    node.notifier.register_target(
        type("T", (), {"id": "sinktgt", "send": lambda self, r: events.append(r)})()
    )
    body = (b"sink payload %04d\n" * 3000) % tuple(range(3000))
    r = c.request("PUT", "/sink/data.txt", body=body,
                  headers={"x-amz-server-side-encryption": "aws:kms"})
    assert r.status_code == 200, r.text
    assert c.get_object("sink", "data.txt").content == body
    assert any("/v1/key/" in p for p in stack["kes"].requests), "KES never consulted"
    assert events and events[0]["Records"][0]["s3"]["object"]["size"] == len(body)

    # Group member writes via group policy; LDAP identity reads via STS.
    gu = S3TestClient(base, "sinkuser", "sinksecret12")
    assert gu.request("PUT", "/sink/by-group.txt", body=b"g").status_code == 200
    import re

    import requests

    sts = requests.post(base + "/", data={
        "Action": "AssumeRoleWithLDAPIdentity", "LDAPUsername": "alice",
        "LDAPPassword": "alice-pw", "Version": "2011-06-15"}, timeout=10)
    assert sts.status_code == 200, sts.text
    ak = re.search(r"<AccessKeyId>([^<]+)</AccessKeyId>", sts.text).group(1)
    sk = re.search(r"<SecretAccessKey>([^<]+)</SecretAccessKey>", sts.text).group(1)
    lu = S3TestClient(base, ak, sk)
    assert lu.get_object("sink", "data.txt").content == body  # readonly works
    assert lu.request("PUT", "/sink/denied.txt", body=b"x").status_code == 403

    # Copy the transformed object; attributes + listing agree on size.
    assert c.request("PUT", "/sink/copy.txt",
                     headers={"x-amz-copy-source": "/sink/data.txt"}).status_code == 200
    assert c.get_object("sink", "copy.txt").content == body
    lst = c.request("GET", "/sink", query=[("list-type", "2"), ("prefix", "data.txt")])
    assert f"<Size>{len(body)}</Size>" in lst.text

    # Restart: fresh Node over the same drives (same env). Everything
    # durable must come back — users, groups, LDAP map, notification rules.
    stack["ts"].stop()
    node2 = Node(stack["dirs"], root_user=ROOT, root_password=SECRET, codec=HostCodec())
    ts2 = ThreadedServer(SimpleNamespace(app=node2.make_app()))
    base2 = ts2.start()
    try:
        node2.build()
        c2 = S3TestClient(base2, ROOT, SECRET)
        assert c2.get_object("sink", "data.txt").content == body
        users = c2.request("GET", "/mtpu/admin/v1/users").json()
        assert "sinkuser" in users
        info = c2.request("GET", "/mtpu/admin/v1/groups/team").json()
        assert info["members"] == ["sinkuser"] and info["policies"] == ["readwrite"]
        assert c2.request("GET", "/mtpu/admin/v1/idp/ldap/policy").json() == {
            ALICE_DN: ["readonly"]
        }
        assert node2.notifier.bucket_rules.get("sink"), "notification rules lost"
        gu2 = S3TestClient(base2, "sinkuser", "sinksecret12")
        assert gu2.request("PUT", "/sink/after-restart.txt", body=b"x").status_code == 200
    finally:
        ts2.stop()
