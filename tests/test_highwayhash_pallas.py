"""Pallas HighwayHash kernel vs numpy oracle (interpret mode on CPU).

Lengths cover: below one chunk (pure XLA path), exact chunk multiples,
chain + remainder packets, and tail bytes -- plus non-TILE_N stream counts
exercising the lane padding.
"""

import numpy as np
import pytest

from minio_tpu.ops import highwayhash as hh
from minio_tpu.ops import highwayhash_pallas as hhp


@pytest.mark.parametrize(
    "n_streams,length",
    [
        (3, 100),          # no full chunk: pure XLA fallback path
        (2, 8 * 32),       # exactly one kernel chunk
        (5, 8 * 32 + 32),  # chain + 1 remainder packet
        (4, 16 * 32 + 7),  # two chunks + tail bytes
        (1, 3 * 8 * 32 + 21),
    ],
)
def test_matches_oracle(n_streams, length):
    rng = np.random.default_rng(n_streams * 1000 + length)
    data = rng.integers(0, 256, (n_streams, length)).astype(np.uint8)
    want = hh.hash256_batch(data)
    got = np.asarray(hhp.hash256_batch(data))
    assert np.array_equal(want, got)


def test_matches_oracle_shard_chunk():
    """The production shape: 1 MiB / 12 shard chunks."""
    rng = np.random.default_rng(9)
    data = rng.integers(0, 256, (4, 87382)).astype(np.uint8)
    want = hh.hash256_batch(data)
    got = np.asarray(hhp.hash256_batch(data))
    assert np.array_equal(want, got)


def test_3d_batch_shape():
    rng = np.random.default_rng(11)
    data = rng.integers(0, 256, (2, 3, 512)).astype(np.uint8)
    got = np.asarray(hhp.hash256_batch(data))
    want = hh.hash256_batch(data.reshape(6, 512)).reshape(2, 3, 32)
    assert np.array_equal(want, got)
