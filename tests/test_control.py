"""Control-plane tests: config, events, scanner, usage, heal manager,
metrics, lifecycle, pubsub."""

import os
import time

import pytest

from minio_tpu.control import config as cfg_mod
from minio_tpu.control import events as ev_mod
from minio_tpu.control import metrics as met_mod
from minio_tpu.control.healmgr import HealManager, MRFQueue
from minio_tpu.control.lifecycle import Lifecycle
from minio_tpu.control.pubsub import PubSub, TraceSys
from minio_tpu.control.scanner import DataScanner
from minio_tpu.utils import errors
from tests.harness import ErasureHarness

NS = "http://s3.amazonaws.com/doc/2006-03-01/"


class TestConfig:
    def test_defaults_and_set(self):
        c = cfg_mod.ConfigSys()
        assert c.get(cfg_mod.SUBSYS_SCANNER, "delay") == "10"
        assert c.set(cfg_mod.SUBSYS_SCANNER, "delay", "20") is True  # dynamic
        assert c.get_int(cfg_mod.SUBSYS_SCANNER, "delay") == 20
        c.unset(cfg_mod.SUBSYS_SCANNER, "delay")
        assert c.get_int(cfg_mod.SUBSYS_SCANNER, "delay") == 10
        with pytest.raises(errors.InvalidArgument):
            c.get("nope", "nope")
        with pytest.raises(errors.InvalidArgument):
            c.set(cfg_mod.SUBSYS_SCANNER, "bogus", "1")

    def test_env_override_wins(self):
        c = cfg_mod.ConfigSys()
        os.environ["MINIO_TPU_SCANNER_DELAY"] = "99"
        try:
            assert c.get_int(cfg_mod.SUBSYS_SCANNER, "delay") == 99
        finally:
            del os.environ["MINIO_TPU_SCANNER_DELAY"]

    def test_dump(self):
        c = cfg_mod.ConfigSys()
        d = c.dump()
        assert d[cfg_mod.SUBSYS_ENCODER]["max_batch"] == "32"


class TestEvents:
    def test_rule_matching(self):
        r = ev_mod.Rule(events=["s3:ObjectCreated:*"], prefix="logs/", suffix=".txt")
        assert r.matches("s3:ObjectCreated:Put", "logs/a.txt")
        assert not r.matches("s3:ObjectRemoved:Delete", "logs/a.txt")
        assert not r.matches("s3:ObjectCreated:Put", "other/a.txt")
        assert not r.matches("s3:ObjectCreated:Put", "logs/a.json")

    def test_parse_notification_xml(self):
        xml = f"""<NotificationConfiguration xmlns="{NS}">
          <QueueConfiguration>
            <Queue>arn:minio:sqs::primary:webhook</Queue>
            <Event>s3:ObjectCreated:*</Event>
            <Filter><S3Key>
              <FilterRule><Name>prefix</Name><Value>img/</Value></FilterRule>
            </S3Key></Filter>
          </QueueConfiguration>
        </NotificationConfiguration>"""
        rules = ev_mod.parse_notification_xml(xml)
        assert len(rules) == 1
        assert rules[0].target_ids == ["webhook"]
        assert rules[0].prefix == "img/"

    def test_emit_to_target_with_queue(self, tmp_path):
        sent = []

        class FakeTarget:
            id = "webhook"

            def send(self, record):
                sent.append(record)

        n = ev_mod.EventNotifier()
        n.register_target(FakeTarget())
        n.set_bucket_rules_from_xml(
            "bkt",
            f'<NotificationConfiguration xmlns="{NS}"><QueueConfiguration>'
            "<Queue>arn:minio:sqs::1:webhook</Queue><Event>s3:ObjectCreated:*</Event>"
            "</QueueConfiguration></NotificationConfiguration>",
        )
        n.emit(ev_mod.Event(name="s3:ObjectCreated:Put", bucket="bkt", object_name="x", size=3))
        n.emit(ev_mod.Event(name="s3:ObjectRemoved:Delete", bucket="bkt", object_name="x"))
        assert len(sent) == 1
        assert sent[0]["EventName"] == "s3:ObjectCreated:Put"
        assert sent[0]["Records"][0]["s3"]["object"]["size"] == 3

    def test_queue_store_retries_and_spools(self, tmp_path):
        fails = {"n": 2}
        delivered = []

        def send(record):
            if fails["n"] > 0:
                fails["n"] -= 1
                raise RuntimeError("broker down")
            delivered.append(record)

        q = ev_mod.TargetQueue(send, queue_dir=str(tmp_path / "spool"))
        q.put({"EventName": "e1"})
        deadline = time.time() + 5
        while not delivered and time.time() < deadline:
            time.sleep(0.05)
        assert delivered and delivered[0]["EventName"] == "e1"
        assert q.pending() == 0
        q.close()

    def test_listen_hub(self):
        n = ev_mod.EventNotifier()
        sub = n.listen_hub.subscribe()
        n.emit(ev_mod.Event(name="s3:ObjectCreated:Put", bucket="b", object_name="k"))
        rec = sub.get(timeout=1)
        assert rec["Key"] == "b/k"


class TestLifecycle:
    def test_parse_and_eval(self):
        xml = f"""<LifecycleConfiguration xmlns="{NS}">
          <Rule><ID>exp</ID><Status>Enabled</Status>
            <Filter><Prefix>tmp/</Prefix></Filter>
            <Expiration><Days>1</Days></Expiration></Rule>
          <Rule><ID>keep</ID><Status>Disabled</Status>
            <Filter><Prefix></Prefix></Filter>
            <Expiration><Days>1</Days></Expiration></Rule>
        </LifecycleConfiguration>"""
        lc = Lifecycle.from_xml(xml)
        assert len(lc.rules) == 2
        old = time.time() - 2 * 86400
        assert lc.eval("tmp/x", old) == "expire"
        assert lc.eval("tmp/x", time.time()) == ""
        assert lc.eval("other/x", old) == ""  # prefix mismatch

    def test_transition_rule(self):
        xml = f"""<LifecycleConfiguration xmlns="{NS}">
          <Rule><ID>t</ID><Status>Enabled</Status><Prefix></Prefix>
            <Transition><Days>1</Days><StorageClass>COLD</StorageClass></Transition>
          </Rule></LifecycleConfiguration>"""
        lc = Lifecycle.from_xml(xml)
        assert lc.eval("x", time.time() - 2 * 86400) == "transition:COLD"


class TestScannerAndHeal:
    @pytest.fixture
    def hz(self, tmp_path):
        h = ErasureHarness(tmp_path, n_disks=8)
        h.layer.make_bucket("scanb")
        return h

    def test_usage_accounting(self, hz):
        for i in range(5):
            hz.layer.put_object("scanb", f"dir/obj{i}", b"x" * 1000)

        class OnePool:
            pools = [None]

        # DataScanner expects a pools-shaped layer; wrap the single set.
        layer = _PoolsShim(hz)
        sc = DataScanner(layer, heal_sample=10**9)
        sc.scan_cycle()
        s = sc.usage.summary()
        assert s["objectsCount"] == 5
        assert s["objectsTotalSize"] == 5000
        assert s["bucketsUsage"]["scanb"]["objectsCount"] == 5

    def test_scanner_heals_damage(self, hz):
        data = b"d" * 200_000
        hz.layer.put_object("scanb", "obj", data)
        hz.delete_shard(0, "scanb", "obj") or hz.delete_object_dir(0, "scanb", "obj")
        layer = _PoolsShim(hz)
        sc = DataScanner(layer, heal_sample=1)  # check everything
        sc.scan_cycle()
        res = hz.layer.heal_object("scanb", "obj", dry_run=True)
        assert res.disks_healed == 0  # already repaired by the scan

    def test_mrf_queue(self, hz):
        hz.layer.put_object("scanb", "obj", b"mrf" * 50_000)
        hz.delete_object_dir(2, "scanb", "obj")
        layer = _PoolsShim(hz)
        mrf = MRFQueue(layer)
        mrf.add("scanb", "obj")
        deadline = time.time() + 5
        while mrf.healed == 0 and time.time() < deadline:
            time.sleep(0.05)
        mrf.stop()
        assert mrf.healed == 1
        assert hz.layer.heal_object("scanb", "obj", dry_run=True).disks_healed == 0

    def test_heal_sequence(self, hz):
        for i in range(3):
            hz.layer.put_object("scanb", f"o{i}", b"x" * 150_000)
        hz.delete_object_dir(1, "scanb", "o0")
        layer = _PoolsShim(hz)
        hm = HealManager(layer)
        seq = hm.start_sequence()
        deadline = time.time() + 10
        while hm.get_status(seq).running and time.time() < deadline:
            time.sleep(0.05)
        st = hm.get_status(seq)
        assert not st.running
        assert st.scanned == 3
        assert st.healed == 1


class _PoolsShim:
    """Adapts the single-set harness to the pools-shaped layer API the
    control plane consumes."""

    def __init__(self, hz):
        from minio_tpu.object.sets import ErasureSets

        self._sets = ErasureSets(list(hz.layer.disks), len(hz.layer.disks))
        # Reuse the SAME set object so offline state matches.
        self._sets.sets = [hz.layer]
        self.pools = [self._sets]
        self.hz = hz

    def list_buckets(self):
        return self.hz.layer.list_buckets()

    def heal_object(self, *a, **k):
        return self.hz.layer.heal_object(*a, **k)

    def heal_bucket(self, bucket):
        pass

    def delete_object(self, bucket, name, opts=None):
        return self.hz.layer.delete_object(bucket, name, opts)

    def list_multipart_uploads(self, bucket, prefix=""):
        return self.hz.layer.multipart.list_multipart_uploads(bucket, prefix)

    def abort_multipart_upload(self, bucket, object_name, upload_id):
        return self.hz.layer.multipart.abort_multipart_upload(bucket, object_name, upload_id)


class TestMetrics:
    def test_render(self):
        m = met_mod.MetricsSys()
        m.record_http("GET", 200)
        m.record_api("GetObject", 0.01, True, tx=100)
        m.record_api("PutObject", 0.5, False, rx=200)
        m.record_encode(32, 5_000_000)
        out = m.render()
        assert 'minio_tpu_http_requests_total{method="GET",status="200"} 1' in out
        assert 'minio_tpu_s3_requests_total{api="GetObject"} 1' in out
        assert 'minio_tpu_s3_requests_errors_total{api="PutObject"} 1' in out
        assert "minio_tpu_encode_blocks_total 32" in out


class TestPubSub:
    def test_zero_overhead_when_unsubscribed(self):
        t = TraceSys()
        assert not t.enabled()
        t.publish("http", path="/x")  # no-op
        sub = t.subscribe()
        assert t.enabled()
        t.publish("http", path="/y")
        item = sub.get(timeout=1)
        assert item["path"] == "/y"
        t.unsubscribe(sub)
        assert not t.enabled()

    def test_slow_subscriber_drops(self):
        ps = PubSub()
        q = ps.subscribe(maxsize=2)
        for i in range(5):
            ps.publish(i)
        assert q.qsize() == 2  # overflow dropped, publisher never blocked


class TestAbortIncompleteMultipart:
    def test_stale_uploads_aborted(self, tmp_path):
        import time as _t

        from minio_tpu.control.bucket_meta import BucketMetadataSys
        from minio_tpu.control.lifecycle import Lifecycle
        from tests.harness import ErasureHarness

        hz = ErasureHarness(tmp_path, n_disks=8)
        hz.layer.make_bucket("mpab")
        uid = hz.layer.multipart.new_multipart_upload("mpab", "stale/obj")
        hz.layer.multipart.put_object_part("mpab", "stale/obj", uid, 1, b"x" * 1000)
        fresh_uid = hz.layer.multipart.new_multipart_upload("mpab", "fresh/obj")

        xml = f"""<LifecycleConfiguration xmlns="{NS}">
          <Rule><ID>a</ID><Status>Enabled</Status><Prefix>stale/</Prefix>
            <AbortIncompleteMultipartUpload><DaysAfterInitiation>1</DaysAfterInitiation>
            </AbortIncompleteMultipartUpload></Rule></LifecycleConfiguration>"""
        lc = Lifecycle.from_xml(xml)
        assert lc.eval_abort_mpu("stale/obj", _t.time() - 2 * 86400)
        assert not lc.eval_abort_mpu("stale/obj", _t.time() - 3600)
        assert not lc.eval_abort_mpu("other/obj", 0)

        # Wire through the scanner: backdate the upload, give the bucket the
        # lifecycle, run a cycle.
        layer = _PoolsShim(hz)
        meta = BucketMetadataSys(layer)
        meta.update("mpab", lifecycle_xml=xml)

        # Backdate the stale upload's initiation time on every drive.
        import json as _json
        import os as _os

        for d in hz.dirs:
            root = _os.path.join(d, ".minio_tpu.sys", "multipart", "mpab")
            for dirpath, _, files in _os.walk(root):
                for f in files:
                    if f == "upload.json":
                        p = _os.path.join(dirpath, f)
                        doc = _json.loads(open(p, "rb").read())
                        doc["created"] = _t.time() - 3 * 86400
                        open(p, "w").write(_json.dumps(doc))

        sc = DataScanner(layer, heal_sample=10**9, bucket_meta=meta)
        sc.scan_cycle()
        remaining = {u["upload_id"] for u in hz.layer.multipart.list_multipart_uploads("mpab")}
        assert uid not in remaining  # stale/ upload aborted
        assert fresh_uid in remaining  # fresh/ prefix not covered by the rule
        assert sc.uploads_aborted >= 1


def test_metrics_duration_histogram():
    from minio_tpu.control.metrics import MetricsSys

    m = MetricsSys()
    m.record_api("GetObject", 0.003, True)
    m.record_api("GetObject", 0.2, True)
    m.record_api("GetObject", 42.0, False)
    out = m.render()
    assert 'minio_tpu_s3_request_duration_seconds_bucket{api="GetObject",le="0.005"} 1' in out
    assert 'minio_tpu_s3_request_duration_seconds_bucket{api="GetObject",le="0.25"} 2' in out
    assert 'minio_tpu_s3_request_duration_seconds_bucket{api="GetObject",le="+Inf"} 3' in out
    assert 'minio_tpu_s3_request_duration_seconds_count{api="GetObject"} 3' in out


def test_notification_rules_rehydrate_on_boot(tmp_path):
    """A restart must reload persisted bucket notification configs into the
    notifier — the rules live in memory, the config in bucket metadata; a
    fresh process otherwise silently stops delivering events."""
    import os as os_mod

    from minio_tpu.dist.node import Node
    from minio_tpu.object.codec import HostCodec

    dirs = []
    for i in range(4):
        d = str(tmp_path / f"nb{i}")
        os_mod.makedirs(d)
        dirs.append(d)
    node = Node(dirs, root_user="nbroot", root_password="nbsecret123", codec=HostCodec())
    node.build()
    node.pools.make_bucket("evb")
    xml = (
        '<NotificationConfiguration xmlns="http://s3.amazonaws.com/doc/2006-03-01/">'
        "<QueueConfiguration><Id>q1</Id><Queue>arn:minio:sqs::primary:webhook</Queue>"
        "<Event>s3:ObjectCreated:*</Event></QueueConfiguration>"
        "</NotificationConfiguration>"
    )
    node.s3.bucket_meta.update("evb", notification_xml=xml)
    node.notifier.set_bucket_rules_from_xml("evb", xml)
    assert node.notifier.bucket_rules.get("evb")

    # Fresh process over the same drives: rules must come back on boot.
    node2 = Node(dirs, root_user="nbroot", root_password="nbsecret123", codec=HostCodec())
    node2.build()
    rules = node2.notifier.bucket_rules.get("evb")
    assert rules, "notification rules lost across restart"
