"""Crash-consistency plane tests: the registry, the recovery scan, torn
writes, worker death, and the crashcheck smoke slice.

tools/crashcheck.py proves the full kill-at-every-point matrix in real
subprocesses (gated by `chaos_check --invariants`); this file pins the
pieces in-process where they are cheap and debuggable: CrashSpec/Registry
semantics (determinism, skip schedules, target filters, raise mode), the
admin-plane routing of ``kind: "crash"`` specs, the recovery sweeps over
hand-crafted crash debris, torn-shard writes flowing into bitrot-detect ->
heal, and a forked "prefork worker" dying mid-PUT whose staging the next
scan collects.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time

import pytest

from minio_tpu.chaos import crash
from minio_tpu.storage import recovery
from minio_tpu.utils import errors
from tests.harness import ErasureHarness

SYS = ".minio_tpu.sys"


def _payload(tag: str, size: int) -> bytes:
    import random

    return random.Random(tag).randbytes(size)


def _dead_pid() -> int:
    """A real-but-dead pid: spawn a no-op child and reap it."""
    proc = subprocess.Popen([sys.executable, "-c", "pass"])
    proc.wait()
    return proc.pid if not recovery._pid_alive(proc.pid) else 999999999


@pytest.fixture(autouse=True)
def _clean_registry():
    crash.REGISTRY.disarm_all()
    recovery.reset_counters()
    yield
    crash.REGISTRY.disarm_all()


# ---------------------------------------------------------------------------
# Registry semantics
# ---------------------------------------------------------------------------


class TestCrashRegistry:
    def test_spec_validation(self):
        with pytest.raises(ValueError):
            crash.CrashSpec(point="not.a.point")
        with pytest.raises(ValueError):
            crash.CrashSpec(point="put.after-stage", mode="melt")
        with pytest.raises(ValueError):
            # only TORN_POINTS accept torn modes
            crash.CrashSpec(point="put.after-stage", mode=crash.TORN)
        with pytest.raises(ValueError):
            crash.CrashSpec(point="put.after-stage", skip=-1)
        with pytest.raises(ValueError):
            crash.CrashSpec.from_dict({"mode": "kill"})  # no point

    def test_roundtrip_carries_kind(self):
        spec = crash.CrashSpec(point="put.mid-commit", mode=crash.RAISE, skip=3)
        doc = spec.to_dict()
        assert doc["kind"] == crash.CRASH_KIND
        again = crash.CrashSpec.from_dict(doc)
        assert again.point == spec.point and again.skip == 3

    def test_disarmed_is_free_and_inert(self):
        assert crash.REGISTRY.points is None
        crash.crash_point("put.after-stage")  # must not raise
        assert crash.torn_hint("storage.append-iov.torn", "x", 100) is None

    def test_skip_schedule_fires_on_nth_hit(self):
        reg = crash.CrashRegistry()
        reg.arm(crash.CrashSpec(point="put.mid-commit", mode=crash.RAISE, skip=2))
        reg.hit("put.mid-commit")  # skipped
        reg.hit("put.mid-commit")  # skipped
        with pytest.raises(errors.CrashInjected):
            reg.hit("put.mid-commit")
        assert reg.fired_counts() == {"put.mid-commit": 1}

    def test_target_substring_filter(self):
        reg = crash.CrashRegistry()
        reg.arm(crash.CrashSpec(point="put.mid-commit", mode=crash.RAISE, target="disk3"))
        reg.hit("put.mid-commit", "http://n0/disk1")  # no match: passes
        with pytest.raises(errors.CrashInjected):
            reg.hit("put.mid-commit", "/drives/disk3")

    def test_point_filter_and_disarm(self):
        reg = crash.CrashRegistry()
        fid = reg.arm(crash.CrashSpec(point="put.before-commit", mode=crash.RAISE))
        reg.hit("put.after-commit")  # different point: passes
        assert reg.disarm(fid)
        reg.hit("put.before-commit")  # disarmed: passes
        assert reg.points is None

    def test_torn_hint_is_seeded_and_deterministic(self):
        def draws(seed):
            reg = crash.CrashRegistry()
            reg.arm(crash.CrashSpec(
                point="storage.append-iov.torn", mode=crash.TORN, seed=seed))
            return [reg.torn_hint("storage.append-iov.torn", "d", 4096)
                    for _ in range(3)]

        a, b = draws(7), draws(7)
        assert a == b  # same seed, same cut schedule
        assert all(h is not None and 0 <= h[0] < 4096 and h[1] is False for h in a)
        # torn-kill reports kill_after=True
        reg = crash.CrashRegistry()
        reg.arm(crash.CrashSpec(
            point="storage.append-iov.torn", mode=crash.TORN_KILL, seed=7))
        cut, kill = reg.torn_hint("storage.append-iov.torn", "d", 4096)
        assert kill is True

    def test_arm_from_env(self):
        fids = crash.arm_from_env({"MTPU_CRASH": "put.after-stage:raise:2:9"})
        try:
            assert len(fids) == 1
            (armed,) = [s for s in crash.REGISTRY.list() if s["fault_id"] == fids[0]]
            assert armed["point"] == "put.after-stage"
            assert armed["mode"] == crash.RAISE
            assert armed["skip"] == 2 and armed["seed"] == 9
        finally:
            crash.REGISTRY.disarm_all()
        assert crash.arm_from_env({"MTPU_CRASH": ""}) == []
        with pytest.raises(ValueError):
            crash.arm_from_env({"MTPU_CRASH": "no-such-point"})

    def test_admin_plane_routes_crash_kind(self):
        from minio_tpu.loadgen.target import InProcessAdmin

        admin = InProcessAdmin()
        fid = admin.arm_fault({"kind": "crash", "point": "put.mid-commit",
                               "mode": "raise"})
        try:
            assert any(s["fault_id"] == fid for s in crash.REGISTRY.list())
        finally:
            admin.disarm_fault(fid)
        assert not crash.REGISTRY.list()

    def test_raise_mode_aborts_put_without_killing(self, tmp_path):
        hz = ErasureHarness(tmp_path, n_disks=8, parity=2)
        hz.layer.make_bucket("cb")
        crash.REGISTRY.arm(crash.CrashSpec(point="put.before-commit", mode=crash.RAISE))
        data = _payload("raise-mode", (1 << 20) + 17)
        with pytest.raises(errors.CrashInjected):
            hz.layer.put_object("cb", "doomed", data)
        crash.REGISTRY.disarm_all()
        # The aborted PUT never committed; the name does not exist.
        with pytest.raises(errors.ObjectNotFound):
            hz.layer.get_object("cb", "doomed")
        # The plane still works after the abort.
        hz.layer.put_object("cb", "ok", data)
        assert hz.layer.get_object("cb", "ok")[1] == data


# ---------------------------------------------------------------------------
# Recovery sweeps over crafted debris
# ---------------------------------------------------------------------------


class TestRecoveryScan:
    def test_tmp_dirs_dead_owner_swept_live_owner_kept(self, tmp_path):
        hz = ErasureHarness(tmp_path, n_disks=4, parity=1)
        root = hz.dirs[0]
        dead = _dead_pid()
        dead_dir = os.path.join(root, SYS, "tmp", f"{dead}.aaaa")
        live_dir = os.path.join(root, SYS, "tmp", f"{os.getpid()}.bbbb")
        legacy_dir = os.path.join(root, SYS, "tmp", "no-pid-prefix")
        for d in (dead_dir, live_dir, legacy_dir):
            os.makedirs(d)
            with open(os.path.join(d, "0"), "wb") as f:
                f.write(b"shard")
        delta = recovery.recover_drive(hz.drives[0])
        assert delta["tmp_dirs"] == 2  # dead + legacy (unscoped = collectable)
        assert not os.path.exists(dead_dir)
        assert not os.path.exists(legacy_dir)
        assert os.path.exists(live_dir)  # a live sibling's staging survives

    def test_multipart_stage_files_swept_upload_kept(self, tmp_path):
        hz = ErasureHarness(tmp_path, n_disks=4, parity=1)
        root = hz.dirs[0]
        udir = os.path.join(root, SYS, "multipart", "b", "o", "uid1")
        os.makedirs(udir)
        dead = _dead_pid()
        stale = os.path.join(udir, f"part.1.tmp.{dead}.deadbeef")
        live = os.path.join(udir, f"part.2.tmp.{os.getpid()}.cafecafe")
        published = os.path.join(udir, "part.1")
        for p in (stale, live, published):
            with open(p, "wb") as f:
                f.write(b"x")
        delta = recovery.recover_drive(hz.drives[0])
        assert delta["stage_files"] == 1
        assert not os.path.exists(stale)
        assert os.path.exists(live) and os.path.exists(published)

    def test_volume_sweep_tmp_files_and_orphan_data_dirs(self, tmp_path):
        hz = ErasureHarness(tmp_path, n_disks=8, parity=2)
        hz.layer.make_bucket("rb")
        data = _payload("sweep", (1 << 20) + 3)
        hz.layer.put_object("rb", "obj", data)
        obj_dir = os.path.join(hz.dirs[0], "rb", "obj")
        # atomic-write staging that never reached os.replace
        stray = os.path.join(obj_dir, "xl.meta.tmp0badc0de")
        with open(stray, "wb") as f:
            f.write(b"half")
        # a data dir no version references (rename_data died pre-meta)
        orphan = os.path.join(os.path.dirname(obj_dir), "ghost", "some-uuid")
        os.makedirs(orphan)
        with open(os.path.join(orphan, "part.1"), "wb") as f:
            f.write(b"shard")
        delta = recovery.recover_drive(hz.drives[0])
        assert delta["tmp_files"] == 1 and not os.path.exists(stray)
        assert delta["orphan_data_dirs"] == 1
        assert not os.path.exists(os.path.dirname(orphan))  # empty parent walked
        # the committed object is untouched
        assert hz.layer.get_object("rb", "obj")[1] == data

    def test_second_pass_is_idempotent(self, tmp_path):
        hz = ErasureHarness(tmp_path, n_disks=4, parity=1)
        root = hz.dirs[0]
        os.makedirs(os.path.join(root, SYS, "tmp", f"{_dead_pid()}.cccc"))
        first = recovery.recover_drive(hz.drives[0])
        assert first["tmp_dirs"] == 1
        second = recovery.recover_drive(hz.drives[0])
        assert all(second[k] == 0 for k in second if k != "scans")

    def test_partial_version_above_quorum_feeds_heal(self, tmp_path):
        hz = ErasureHarness(tmp_path, n_disks=8, parity=2)
        hz.layer.make_bucket("pb")
        data = _payload("heal-me", (1 << 20) + 11)
        hz.layer.put_object("pb", "partial", data)
        hz.delete_object_dir(0, "pb", "partial")  # 7/8 holders >= k=6
        healed = []
        delta = recovery.recover_set(hz.layer, heal=lambda b, o, v: healed.append((b, o, v)))
        assert delta["partial_healed"] == 1 and delta["partial_gc"] == 0
        assert healed and healed[0][0] == "pb" and healed[0][1] == "partial"
        # drive the heal and confirm convergence back to full width
        hz.layer.heal_object("pb", "partial", version_id=healed[0][2])
        assert os.path.exists(hz.xl_meta_file(0, "pb", "partial"))
        assert hz.layer.get_object("pb", "partial")[1] == data

    def test_partial_version_below_quorum_rolled_back(self, tmp_path):
        hz = ErasureHarness(tmp_path, n_disks=8, parity=2)
        hz.layer.make_bucket("pb")
        hz.layer.put_object("pb", "torn-ack", _payload("rollback", (1 << 20) + 7))
        for i in range(1, 8):
            hz.delete_object_dir(i, "pb", "torn-ack")  # 1/8 < k=6: un-ackable
        delta = recovery.recover_set(hz.layer, heal=lambda *a: None)
        assert delta["partial_gc"] == 1 and delta["partial_healed"] == 0
        with pytest.raises(errors.ObjectNotFound):
            hz.layer.get_object("pb", "torn-ack")

    def test_below_quorum_left_alone_when_a_drive_is_dark(self, tmp_path):
        """Rolling-restart guard: rollback needs EVERY drive visible."""
        hz = ErasureHarness(tmp_path, n_disks=8, parity=2)
        hz.layer.make_bucket("pb")
        hz.layer.put_object("pb", "maybe", _payload("dark", (1 << 20) + 5))
        for i in range(1, 8):
            hz.delete_object_dir(i, "pb", "maybe")
        hz.take_offline(7)
        delta = recovery.recover_set(hz.layer, heal=lambda *a: None)
        assert delta["partial_gc"] == 0  # can't prove it never reached quorum
        assert os.path.exists(hz.xl_meta_file(0, "pb", "maybe"))


# ---------------------------------------------------------------------------
# Torn shard writes -> bitrot detect -> heal (satellite: torn-write coverage)
# ---------------------------------------------------------------------------


TORN = "storage.append-iov.torn"


@pytest.mark.parametrize("fsync_env", ["never", "commit", "always"])
class TestTornWrites:
    def _arm(self, hz, drive_index: int):
        crash.REGISTRY.arm(crash.CrashSpec(
            point=TORN, mode=crash.TORN,
            target=os.path.basename(hz.dirs[drive_index]), seed=13))

    def _assert_heals_bit_identical(self, hz, bucket, obj, data, torn_disk):
        # Detection: the torn shard fails its bitrot digest on read and the
        # decode falls back to parity -- the client still sees exact bytes.
        assert hz.layer.get_object(bucket, obj)[1] == data
        hz.layer.heal_object(bucket, obj)
        # The healed shard must carry real data again: force a read that
        # NEEDS the formerly-torn drive by downing `parity` other drives.
        offline = [i for i in range(len(hz.dirs)) if i != torn_disk][:2]
        hz.take_offline(*offline)
        try:
            assert hz.layer.get_object(bucket, obj)[1] == data
        finally:
            hz.bring_online(*offline)

    def test_streaming_put(self, tmp_path, monkeypatch, fsync_env):
        monkeypatch.setenv("MTPU_FSYNC", fsync_env)
        hz = ErasureHarness(tmp_path, n_disks=8, parity=2)
        hz.layer.make_bucket("tb")
        data = _payload(f"torn-{fsync_env}", (2 << 20) + 4097)
        self._arm(hz, drive_index=3)
        hz.layer.put_object("tb", "torn", data)  # torn shard is silent
        assert crash.REGISTRY.fired_counts().get(TORN, 0) >= 1
        crash.REGISTRY.disarm_all()
        self._assert_heals_bit_identical(hz, "tb", "torn", data, torn_disk=3)

    def test_multipart_part(self, tmp_path, monkeypatch, fsync_env):
        from minio_tpu.object.multipart import MultipartManager

        monkeypatch.setenv("MTPU_FSYNC", fsync_env)
        hz = ErasureHarness(tmp_path, n_disks=8, parity=2)
        hz.layer.make_bucket("tb")
        mp = MultipartManager(hz.layer)
        p1 = _payload(f"mp1-{fsync_env}", 5 << 20)
        p2 = _payload(f"mp2-{fsync_env}", (1 << 20) + 9)
        uid = mp.new_multipart_upload("tb", "mobj")
        self._arm(hz, drive_index=5)
        e1 = mp.put_object_part("tb", "mobj", uid, 1, p1).etag
        assert crash.REGISTRY.fired_counts().get(TORN, 0) >= 1
        crash.REGISTRY.disarm_all()
        e2 = mp.put_object_part("tb", "mobj", uid, 2, p2).etag
        mp.complete_multipart_upload("tb", "mobj", uid, [(1, e1), (2, e2)])
        self._assert_heals_bit_identical(hz, "tb", "mobj", p1 + p2, torn_disk=5)


# ---------------------------------------------------------------------------
# Worker death mid-PUT (satellite: prefork stage-file/pool-buffer leak)
# ---------------------------------------------------------------------------


class TestWorkerDeathMidPut:
    def test_dead_workers_staging_is_swept_not_live(self, tmp_path):
        """Fork a 'worker', kill it at put.after-stage, and prove the parent
        (the respawn path runs the same scan via Node.build) collects its
        staging while the data plane stays intact."""
        from minio_tpu.utils import bufpool

        hz = ErasureHarness(tmp_path, n_disks=8, parity=2)
        hz.layer.make_bucket("wb")
        data = _payload("worker-death", (1 << 20) + 257)
        hz.layer.put_object("wb", "acked", data)  # committed before the crash

        child = os.fork()
        if child == 0:
            # The forked "worker": own layer over the same drives, armed to
            # die with shards staged but nothing committed. The parent's
            # drive-IO fan-out pool already has worker threads, and threads
            # do not survive fork -- submitting to the inherited executor
            # would hang forever, so the child installs a fresh one (the
            # real prefork plane forks before any pool spins up).
            try:
                from concurrent.futures import ThreadPoolExecutor

                from minio_tpu.object import metadata as meta_mod
                from minio_tpu.object.erasure import ErasureObjects
                from minio_tpu.storage.local import LocalDrive
                from minio_tpu.utils import iopool

                meta_mod._POOL = ThreadPoolExecutor(
                    max_workers=16, thread_name_prefix="drive-io")
                iopool._SHARED = None  # rebuild the lane pool with live threads
                victim_layer = ErasureObjects(
                    [LocalDrive(d) for d in hz.dirs], parity=2)
                crash.REGISTRY.arm(crash.CrashSpec(point="put.after-stage"))
                victim_layer.put_object("wb", "doomed", data)
            except BaseException:
                pass
            os._exit(3)  # only reached if the crash point never fired

        # Bounded reap: a wedged child must fail the test, not hang pytest.
        status = None
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            pid, st = os.waitpid(child, os.WNOHANG)
            if pid == child:
                status = st
                break
            time.sleep(0.05)
        if status is None:
            os.kill(child, signal.SIGKILL)
            os.waitpid(child, 0)
            pytest.fail("forked worker wedged instead of dying at the crash point")
        assert os.waitstatus_to_exitcode(status) == 137, "worker did not die at the point"

        # Its pid-scoped staging is on the drives...
        stage_dirs = [
            os.path.join(d, SYS, "tmp", name)
            for d in hz.dirs
            if os.path.isdir(os.path.join(d, SYS, "tmp"))
            for name in os.listdir(os.path.join(d, SYS, "tmp"))
            if name.startswith(f"{child}.")
        ]
        assert stage_dirs, "worker death left no staged shards to recover"
        # ...and the restart scan sweeps every one (owner pid is dead now).
        swept = sum(recovery.recover_drive(d)["tmp_dirs"] for d in hz.drives)
        assert swept >= len(stage_dirs)
        assert not any(os.path.exists(p) for p in stage_dirs)

        # Invariants after recovery: acked object intact, name never
        # half-appears, fresh writes work, no pooled windows leaked here.
        assert hz.layer.get_object("wb", "acked")[1] == data
        with pytest.raises(errors.ObjectNotFound):
            hz.layer.get_object("wb", "doomed")
        hz.layer.put_object("wb", "after", data)
        assert hz.layer.get_object("wb", "after")[1] == data
        assert bufpool.window_pool().outstanding() == 0


# ---------------------------------------------------------------------------
# crashcheck smoke slice (tier-1 face of tools/crashcheck.py)
# ---------------------------------------------------------------------------


class TestCrashcheckSmoke:
    def test_smoke_points_pass(self, tmp_path):
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env = dict(os.environ, JAX_PLATFORMS="cpu", MTPU_FSYNC="commit")
        proc = subprocess.run(
            [sys.executable, os.path.join(root, "tools", "crashcheck.py"),
             "--smoke", "--json"],
            cwd=root, env=env, capture_output=True, text=True, timeout=600,
        )
        assert proc.returncode == 0, f"crashcheck --smoke failed:\n{proc.stdout}\n{proc.stderr}"
        report = json.loads(proc.stdout[proc.stdout.index("{"):])
        assert report["failed"] == 0 and len(report["points"]) >= 3
