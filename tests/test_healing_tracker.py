"""Persistent healing tracker + new-disk monitor.

Mirrors the reference's fresh-drive heal story
(cmd/background-newdisks-heal-ops.go): a replaced drive gets a persisted
`.healing.bin`-style tracker at format-heal time; the background monitor
sweeps the drive's erasure set onto it, checkpoints a resume cursor, and
removes the tracker when the drive is fully re-protected.
"""

import os
import shutil

import pytest

from minio_tpu.control.healmgr import (
    DiskHealMonitor,
    HealingTracker,
    mark_drive_for_healing,
)
from minio_tpu.object.pools import ServerPools
from minio_tpu.object.sets import ErasureSets
from minio_tpu.storage import format as fmt
from minio_tpu.storage.local import LocalDrive
from minio_tpu.utils import errors
from tests.harness import ErasureHarness

# Stressed under adversarial thread scheduling by tools/race_gate.py.
pytestmark = pytest.mark.race


BUCKET = "tracked"


def _pools(hz: ErasureHarness) -> ServerPools:
    return ServerPools([ErasureSets(list(hz.drives), len(hz.drives))])


def _replace_drive(hz: ErasureHarness, idx: int) -> LocalDrive:
    """Wipe a drive dir and re-create it formatted (what the node's
    format-heal does for a fresh replacement), returning the new drive."""
    old_fmt = fmt.DriveFormat.load(hz.dirs[idx])
    shutil.rmtree(hz.dirs[idx])
    os.makedirs(hz.dirs[idx])
    old_fmt.save(hz.dirs[idx])
    fresh = LocalDrive(hz.dirs[idx])
    hz.drives[idx] = fresh
    hz.layer.disks[idx] = fresh
    return fresh


def test_tracker_roundtrip(tmp_path):
    hz = ErasureHarness(tmp_path, n_disks=4)
    d = hz.drives[0]
    tr = mark_drive_for_healing(d)
    assert tr.endpoint == d.endpoint()
    loaded = HealingTracker.load(d)
    assert loaded is not None and loaded.disk_id == d.disk_id()
    loaded.objects_scanned = 7
    loaded.resume_bucket, loaded.resume_object = "b", "o"
    loaded.save(d)
    again = HealingTracker.load(d)
    assert again.objects_scanned == 7 and again.resume_object == "o"
    HealingTracker.remove(d)
    assert HealingTracker.load(d) is None


def test_monitor_heals_replaced_drive(tmp_path):
    hz = ErasureHarness(tmp_path, n_disks=8)
    layer = _pools(hz)
    layer.make_bucket(BUCKET)
    payloads = {f"obj-{i}": os.urandom(200_000 + i) for i in range(6)}
    for name, data in payloads.items():
        layer.put_object(BUCKET, name, data)

    fresh = _replace_drive(hz, 3)
    for s in layer.pools[0].sets:
        s.disks[3] = fresh
    mark_drive_for_healing(fresh)

    mon = DiskHealMonitor(layer, start=False)
    healed = mon.tick()
    assert healed == 1
    assert HealingTracker.load(fresh) is None  # tracker removed on completion
    assert mon.completed and mon.completed[0].objects_scanned == len(payloads)

    # Every object now readable with ONLY the healed drive's row restored:
    # corrupt nothing, take the other half of the set offline beyond parity
    # tolerance minus the healed drive to prove its shards are real.
    for name, data in payloads.items():
        _, got = layer.get_object(BUCKET, name)
        assert got == data
    # The healed drive holds either a shard file or inline metadata per object.
    for name in payloads:
        assert fresh.read_xl(BUCKET, name) is not None


def test_monitor_resumes_from_cursor(tmp_path):
    hz = ErasureHarness(tmp_path, n_disks=4)
    layer = _pools(hz)
    layer.make_bucket(BUCKET)
    names = sorted(f"obj-{i}" for i in range(8))
    for n in names:
        layer.put_object(BUCKET, n, b"x" * 1000)

    fresh = _replace_drive(hz, 1)
    for s in layer.pools[0].sets:
        s.disks[1] = fresh
    tr = mark_drive_for_healing(fresh)
    # Pretend a previous run already healed the first half.
    tr.resume_bucket, tr.resume_object = BUCKET, names[3]
    tr.objects_scanned = 4
    tr.save(fresh)

    mon = DiskHealMonitor(layer, start=False)
    assert mon.tick() == 1
    done = mon.completed[0]
    # 4 pre-done + 4 walked this run.
    assert done.objects_scanned == 8
    # Only the resumed tail was actually healed this run.
    for n in names[4:]:
        assert fresh.read_xl(BUCKET, n) is not None


def test_monitor_checkpoints_cursor(tmp_path):
    hz = ErasureHarness(tmp_path, n_disks=4)
    layer = _pools(hz)
    layer.make_bucket(BUCKET)
    for i in range(5):
        layer.put_object(BUCKET, f"obj-{i}", b"y" * 500)

    fresh = _replace_drive(hz, 0)
    for s in layer.pools[0].sets:
        s.disks[0] = fresh
    mark_drive_for_healing(fresh)

    # checkpoint_every=1 forces a save per object; interrupt by loading the
    # tracker after completion is impossible (it's removed), so instead run
    # with a wrapped save that captures intermediate cursors.
    seen = []
    orig_save = HealingTracker.save

    def spy(self, disk):
        seen.append((self.resume_bucket, self.resume_object))
        orig_save(self, disk)

    HealingTracker.save = spy
    try:
        mon = DiskHealMonitor(layer, checkpoint_every=1, start=False)
        assert mon.tick() == 1
    finally:
        HealingTracker.save = orig_save
    assert ("", "") not in seen[1:]
    assert any(obj for _, obj in seen if obj)  # cursor advanced during sweep


def test_monitor_heals_versions_and_delete_markers(tmp_path):
    hz = ErasureHarness(tmp_path, n_disks=4)
    layer = _pools(hz)
    layer.make_bucket(BUCKET)
    from minio_tpu.object.types import DeleteObjectOptions, PutObjectOptions

    opts = PutObjectOptions(versioned=True)
    v1 = layer.put_object(BUCKET, "doc", b"one", opts).version_id
    v2 = layer.put_object(BUCKET, "doc", b"two", opts).version_id
    layer.delete_object(BUCKET, "doc", DeleteObjectOptions(versioned=True))

    fresh = _replace_drive(hz, 2)
    for s in layer.pools[0].sets:
        s.disks[2] = fresh
    mark_drive_for_healing(fresh)
    mon = DiskHealMonitor(layer, start=False)
    assert mon.tick() == 1

    xl = fresh.read_xl(BUCKET, "doc")
    vids = {v.version_id for v in xl.versions}
    assert v1 in vids and v2 in vids
    assert any(v.deleted for v in xl.versions)  # delete marker healed too


def test_monitor_heals_sys_bucket_first(tmp_path):
    """Config/IAM shards in META_BUCKET must be re-protected too (the
    reference heals .minio.sys before user buckets)."""
    from minio_tpu.object.erasure import META_BUCKET

    hz = ErasureHarness(tmp_path, n_disks=4)
    layer = _pools(hz)
    layer.make_bucket(BUCKET)
    layer.put_object(BUCKET, "user-obj", b"u" * 1000)
    for d in hz.drives:
        try:
            d.make_vol(META_BUCKET)
        except errors.VolumeExists:
            pass
    layer.put_object(META_BUCKET, "config/config.json", b"cfg" * 100)

    fresh = _replace_drive(hz, 1)
    for s in layer.pools[0].sets:
        s.disks[1] = fresh
    mark_drive_for_healing(fresh)
    mon = DiskHealMonitor(layer, start=False)
    assert mon.tick() == 1
    assert fresh.read_xl(META_BUCKET, "config/config.json") is not None
    assert fresh.read_xl(BUCKET, "user-obj") is not None
