"""bufsan acceptance: the same seeded buffer-lifetime bugs are caught by
BOTH halves of the sanitizer -- the static mtpulint dataflow rules
(view-escape & friends over the AST) and the runtime MTPU_BUFSAN
detectors (sentinel poisoning, export probes, weakref leak tracking).

The static half lints tiny synthetic trees (the test_lint.py idiom); the
runtime half arms a private BufSanitizer instance against real BufferPool
traffic, so the bufpool hooks -- note_acquire / note_view / note_recycle /
note_double_release -- are exercised exactly as MTPU_BUFSAN=1 wires them.
"""

from __future__ import annotations

import gc
import importlib.util
import json
import textwrap
from pathlib import Path

from tools.mtpulint import lint_tree
from tools.mtpulint.rules import ReleaseOnAllPathsRule, ViewEscapeRule

from minio_tpu.control import bufsan
from minio_tpu.utils.bufpool import BufferPool

_REPO = Path(__file__).resolve().parent.parent
_LINT_PATH = _REPO / "tools" / "metrics_lint.py"
_spec = importlib.util.spec_from_file_location("metrics_lint", _LINT_PATH)
metrics_lint = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(metrics_lint)


def _lint(tmp_path, src: str, rule) -> list:
    p = tmp_path / "minio_tpu" / "api" / "seeded.py"
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(textwrap.dedent(src).lstrip("\n"))
    return lint_tree(str(tmp_path), ["minio_tpu"], [rule])


class _Armed:
    """Arm a fresh sanitizer for one test; always disarm."""

    def __enter__(self) -> bufsan.BufSanitizer:
        self.san = bufsan.BufSanitizer()
        bufsan.arm(self.san)
        return self.san

    def __exit__(self, *exc) -> None:
        bufsan.disarm()


def _rules(san: bufsan.BufSanitizer) -> list[str]:
    return [f["rule"] for f in san.findings]


# -- seeded bug #1: view escapes the buffer's lifetime ------------------------


SEEDED_VIEW_ESCAPE = """
    def stash(self, pool):
        pb = pool.acquire()
        try:
            self.cache = pb.view(0, 128)
        finally:
            pb.release()
"""


def test_seeded_view_escape_caught_by_static_rule(tmp_path):
    findings = _lint(tmp_path, SEEDED_VIEW_ESCAPE, ViewEscapeRule())
    assert [f.rule for f in findings] == ["view-escape"]
    assert "retain()" in findings[0].message


def test_seeded_view_escape_caught_by_runtime_probe():
    pool = BufferPool(buf_size=64, capacity=2)
    with _Armed() as san:
        pb = pool.acquire()
        stashed = pb.view(0, 16)  # escapes: still alive at the release
        pb.release()
        assert "view-outlives-buffer" in _rules(san)
        (finding,) = [f for f in san.findings
                      if f["rule"] == "view-outlives-buffer"]
        # The finding names the acquisition site (this test file), so a
        # triager can jump straight to the leak.
        assert "test_bufsan.py" in finding["site"]
    stashed.release()


def test_runtime_probe_quiet_when_views_die_first():
    pool = BufferPool(buf_size=64, capacity=2)
    with _Armed() as san:
        pb = pool.acquire()
        mv = pb.view(0, 16)
        mv[:4] = b"abcd"
        mv.release()
        pb.release()
        assert _rules(san) == []


def test_runtime_probe_quiet_for_discarded_storage():
    # discard() exists exactly so exception paths can hand traceback-pinned
    # views to the allocator instead of the free list: no recycle, no
    # corruption window, no finding.
    pool = BufferPool(buf_size=64, capacity=2)
    with _Armed() as san:
        pb = pool.acquire()
        pinned = pb.view(0, 16)
        pb.discard()
        assert _rules(san) == []
    assert len(pinned) == 16  # the allocator keeps the bytes alive


# -- seeded bug #2: write-after-release ---------------------------------------


SEEDED_STRAIGHT_LINE_RELEASE = """
    def fill(pool, reader):
        pb = pool.acquire()
        n = reader.readinto(pb.view())
        pb.release()
        return n
"""


def test_seeded_straight_line_release_caught_by_static_rule(tmp_path):
    # The static half of the write-after-release story: a release with no
    # exception-edge coverage is how a buffer ends up recycled while the
    # raising frame still writes into it.
    findings = _lint(
        tmp_path, SEEDED_STRAIGHT_LINE_RELEASE, ReleaseOnAllPathsRule()
    )
    assert [f.rule for f in findings] == ["release-on-all-paths"]
    assert "straight-line" in findings[0].message


def test_seeded_write_after_release_caught_by_sentinel():
    pool = BufferPool(buf_size=64, capacity=2)
    with _Armed() as san:
        pb = pool.acquire()
        storage = pb.data  # the bug: a raw handle kept past the release
        pb.release()  # storage recycles; bufsan sentinel-poisons it
        storage[5] = 0x7F  # stale write lands in pooled memory
        pool.acquire()  # re-acquire verifies the sentinel
        assert "write-after-release" in _rules(san)
        (finding,) = [f for f in san.findings
                      if f["rule"] == "write-after-release"]
        assert "byte 5" in finding["message"]


def test_sentinel_quiet_on_clean_reuse():
    pool = BufferPool(buf_size=64, capacity=2)
    with _Armed() as san:
        pool.acquire().release()
        pb = pool.acquire()
        assert _rules(san) == []
        assert san.counters["sentinel_checks"] == 1
        pb.release()


# -- the remaining runtime detectors ------------------------------------------


def test_double_release_recorded_before_raise():
    pool = BufferPool(buf_size=64, capacity=2)
    with _Armed() as san:
        pb = pool.acquire()
        pb.release()
        try:
            pb.release()
        except RuntimeError:
            pass
        assert "double-release" in _rules(san)


def test_buffer_leak_reported_for_collected_unreleased_handle():
    pool = BufferPool(buf_size=64, capacity=2)
    with _Armed() as san:
        pool.acquire()  # dropped without release()
        gc.collect()
        assert "buffer-leak" in _rules(san)


def test_teardown_check_flags_still_live_unreleased_handles():
    pool = BufferPool(buf_size=64, capacity=2)
    with _Armed() as san:
        pb = pool.acquire()
        san.teardown_check()
        assert "buffer-leak" in _rules(san)
        pb.release()


def test_report_artifact_round_trips(tmp_path):
    out = tmp_path / "bufsan.json"
    pool = BufferPool(buf_size=64, capacity=2)
    with _Armed() as san:
        pb = pool.acquire()
        leaked = pb.view(0, 8)
        pb.release()
        san.write_report(str(out))
    rep = json.loads(out.read_text())
    assert rep["bufsan"] == 1
    assert rep["counters"]["acquires"] == 1
    assert [f["rule"] for f in rep["findings"]] == ["view-outlives-buffer"]
    assert rep["unsuppressed"] == 1
    leaked.release()


# -- metrics exposition (armed only) ------------------------------------------


def test_bufsan_metrics_rendered_when_armed_and_lint_clean():
    from minio_tpu.control.metrics import MetricsSys

    pool = BufferPool(buf_size=64, capacity=2)
    with _Armed() as san:
        pool.acquire().release()
        san.add_finding("view-outlives-buffer", "x.py:1", "m")
        text = MetricsSys().render_node()
        assert "minio_tpu_bufsan_acquires_total 1" in text
        assert "minio_tpu_bufsan_sentinel_fills_total 1" in text
        assert ('minio_tpu_bufsan_findings_total'
                '{rule="view-outlives-buffer"} 1') in text
        assert metrics_lint.validate_exposition(text) == []
        assert metrics_lint.lint_exposition(text) == []


def test_bufsan_metrics_absent_when_disarmed():
    from minio_tpu.control.metrics import MetricsSys

    bufsan.disarm()
    text = MetricsSys().render_node()
    assert "minio_tpu_bufsan_" not in text
    assert metrics_lint.validate_exposition(text) == []


def test_disarmed_pool_records_nothing():
    san = bufsan.arm(bufsan.BufSanitizer())
    bufsan.disarm()
    assert bufsan.ACTIVE is None
    pool = BufferPool(buf_size=64, capacity=2)
    pb = pool.acquire()
    pb.view(0, 8)
    pb.release()
    assert san.counters["acquires"] == 0
    assert san.findings == []
