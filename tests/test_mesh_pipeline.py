"""Mesh-sharded encode pipeline vs host oracle (VERDICT r3 #5).

Runs the full SPMD encode+hash step on the conftest's 8-device virtual CPU
platform: the erasure matmul sp-sharded, the encode->hash boundary as an
explicit lax.all_to_all, streams tp-sliced. Pins sharded outputs bit-exactly
against the host reference so a sharding regression cannot ship green.
"""

import numpy as np
import pytest

import jax

from minio_tpu.models.pipeline import ErasurePipeline, Geometry
from minio_tpu.ops import highwayhash as hh
from minio_tpu.ops import rs_ref
from minio_tpu.parallel import mesh as mesh_lib

K, M = 12, 4


def _host_oracle(data):
    """[B, K, S] -> (shards, digests) via the numpy reference."""
    shards = np.stack([rs_ref.encode(data[i], M) for i in range(data.shape[0])])
    digests = np.stack(
        [
            np.stack(
                [
                    np.frombuffer(hh.hash256(shards[i, j].tobytes()), dtype=np.uint8)
                    for j in range(K + M)
                ]
            )
            for i in range(data.shape[0])
        ]
    )
    return shards, digests


@pytest.mark.parametrize("shape", [(2, 2, 2), (4, 2, 1), (8, 1, 1), (1, 2, 4)])
def test_mesh_encode_matches_host(shape):
    if jax.device_count() < 8:
        pytest.skip("needs the 8-device virtual platform from conftest")
    mesh = mesh_lib.make_mesh(8, shape=shape)
    dp, tp, sp = shape
    geom = Geometry(K, M, block_size=K * 64 * max(sp, 1))
    pipe = ErasurePipeline(geom, mesh=mesh)
    rng = np.random.default_rng(42)
    data = rng.integers(0, 256, (2 * dp, K, geom.shard_size), dtype=np.uint8)
    arr = jax.device_put(data, mesh_lib.data_sharding(mesh))

    shards, digests = pipe.encode(arr)
    want_shards, want_digests = _host_oracle(data)
    assert np.array_equal(np.asarray(shards), want_shards)
    assert np.array_equal(np.asarray(digests), want_digests)


def test_mesh_factoring():
    assert mesh_lib.factor_mesh(1) == (1, 1, 1)
    for n in (2, 4, 8, 16, 64):
        dp, tp, sp = mesh_lib.factor_mesh(n)
        assert dp * tp * sp == n
        assert dp >= tp >= sp


def test_default_mesh_dryrun():
    """The exact program the driver's dryrun_multichip exercises."""
    if jax.device_count() < 8:
        pytest.skip("needs the 8-device virtual platform from conftest")
    mesh = mesh_lib.make_mesh(8)
    geom = Geometry(K, M, block_size=K * 128 * mesh.shape["sp"])
    pipe = ErasurePipeline(geom, mesh=mesh)
    rng = np.random.default_rng(7)
    data = rng.integers(
        0, 256, (2 * mesh.shape["dp"], K, geom.shard_size), dtype=np.uint8
    )
    shards, digests = pipe.encode(jax.device_put(data, mesh_lib.data_sharding(mesh)))
    want_shards, want_digests = _host_oracle(data)
    assert np.array_equal(np.asarray(shards), want_shards)
    assert np.array_equal(np.asarray(digests), want_digests)
