"""Mesh-sharded encode pipeline vs host oracle (VERDICT r3 #5).

Runs the full SPMD encode+hash step on the conftest's 8-device virtual CPU
platform: the erasure matmul sp-sharded, the encode->hash boundary as an
explicit lax.all_to_all, streams tp-sliced. Pins sharded outputs bit-exactly
against the host reference so a sharding regression cannot ship green.
"""

import numpy as np
import pytest

import jax

from minio_tpu.models.pipeline import ErasurePipeline, Geometry
from minio_tpu.ops import highwayhash as hh
from minio_tpu.ops import rs_ref
from minio_tpu.parallel import mesh as mesh_lib

K, M = 12, 4


def _host_oracle(data):
    """[B, K, S] -> (shards, digests) via the numpy reference."""
    shards = np.stack([rs_ref.encode(data[i], M) for i in range(data.shape[0])])
    digests = np.stack(
        [
            np.stack(
                [
                    np.frombuffer(hh.hash256(shards[i, j].tobytes()), dtype=np.uint8)
                    for j in range(K + M)
                ]
            )
            for i in range(data.shape[0])
        ]
    )
    return shards, digests


@pytest.mark.parametrize("shape", [(2, 2, 2), (4, 2, 1), (8, 1, 1), (1, 2, 4)])
def test_mesh_encode_matches_host(shape):
    if jax.device_count() < 8:
        pytest.skip("needs the 8-device virtual platform from conftest")
    mesh = mesh_lib.make_mesh(8, shape=shape)
    dp, tp, sp = shape
    geom = Geometry(K, M, block_size=K * 64 * max(sp, 1))
    pipe = ErasurePipeline(geom, mesh=mesh)
    rng = np.random.default_rng(42)
    data = rng.integers(0, 256, (2 * dp, K, geom.shard_size), dtype=np.uint8)
    arr = jax.device_put(data, mesh_lib.data_sharding(mesh))

    shards, digests = pipe.encode(arr)
    want_shards, want_digests = _host_oracle(data)
    assert np.array_equal(np.asarray(shards), want_shards)
    assert np.array_equal(np.asarray(digests), want_digests)


def test_mesh_factoring():
    assert mesh_lib.factor_mesh(1) == (1, 1, 1)
    for n in (2, 4, 8, 16, 64):
        dp, tp, sp = mesh_lib.factor_mesh(n)
        assert dp * tp * sp == n
        assert dp >= tp >= sp


class TestMeshShapeEnv:
    """MTPU_MESH_SHAPE parsing + the cached codec mesh BatchingDeviceCodec
    fans batches over."""

    def test_explicit_shape(self, monkeypatch):
        monkeypatch.setenv("MTPU_MESH_SHAPE", "4,2,1")
        assert mesh_lib.mesh_shape_from_env(8) == (4, 2, 1)

    def test_off_disables(self, monkeypatch):
        for raw in ("off", "0", "1"):
            monkeypatch.setenv("MTPU_MESH_SHAPE", raw)
            assert mesh_lib.mesh_shape_from_env(8) is None

    def test_auto_and_malformed_fall_back_to_factoring(self, monkeypatch):
        want = mesh_lib.factor_mesh(8)
        for raw in ("", "auto", "banana", "2,2", "3,3,3", "-1,4,2"):
            monkeypatch.setenv("MTPU_MESH_SHAPE", raw)
            assert mesh_lib.mesh_shape_from_env(8) == want

    def test_codec_mesh_cached(self, monkeypatch):
        if jax.device_count() < 8:
            pytest.skip("needs the 8-device virtual platform from conftest")
        monkeypatch.setattr(mesh_lib, "_codec_mesh_cache", [])
        monkeypatch.setenv("MTPU_MESH_SHAPE", "8,1,1")
        m1 = mesh_lib.codec_mesh()
        assert m1 is not None and m1.shape["dp"] == 8
        # Cached: a later env change does not rebuild (one mesh per process).
        monkeypatch.setenv("MTPU_MESH_SHAPE", "off")
        assert mesh_lib.codec_mesh() is m1

    def test_codec_mesh_off(self, monkeypatch):
        monkeypatch.setattr(mesh_lib, "_codec_mesh_cache", [])
        monkeypatch.setenv("MTPU_MESH_SHAPE", "off")
        assert mesh_lib.codec_mesh() is None


def test_pallas_rs_under_mesh_matches_host():
    """The XOR-bitmatrix Pallas codec shard_mapped data-parallel over all 8
    virtual devices stays bit-identical to the host oracle (the bench's
    multichip_encode_gibs program)."""
    if jax.device_count() < 8:
        pytest.skip("needs the 8-device virtual platform from conftest")
    from jax.sharding import PartitionSpec as P

    from minio_tpu.ops.rs_pallas import RSPallasCodec

    n = 8
    mesh = mesh_lib.make_mesh(n, (n, 1, 1))
    codec = RSPallasCodec(K, M)
    enc = jax.jit(
        mesh_lib.shard_map_compat(
            codec.encode, mesh=mesh,
            in_specs=P("dp", None, None), out_specs=P("dp", None, None),
        )
    )
    rng = np.random.default_rng(13)
    data = rng.integers(0, 256, (n, K, 4096), dtype=np.uint8)
    got = np.asarray(enc(jax.device_put(data, mesh_lib.data_sharding(mesh))))
    for i in range(n):
        np.testing.assert_array_equal(got[i], rs_ref.encode(data[i], M)[K:])


def test_default_mesh_dryrun():
    """The exact program the driver's dryrun_multichip exercises."""
    if jax.device_count() < 8:
        pytest.skip("needs the 8-device virtual platform from conftest")
    mesh = mesh_lib.make_mesh(8)
    geom = Geometry(K, M, block_size=K * 128 * mesh.shape["sp"])
    pipe = ErasurePipeline(geom, mesh=mesh)
    rng = np.random.default_rng(7)
    data = rng.integers(
        0, 256, (2 * mesh.shape["dp"], K, geom.shard_size), dtype=np.uint8
    )
    shards, digests = pipe.encode(jax.device_put(data, mesh_lib.data_sharding(mesh)))
    want_shards, want_digests = _host_oracle(data)
    assert np.array_equal(np.asarray(shards), want_shards)
    assert np.array_equal(np.asarray(digests), want_digests)
