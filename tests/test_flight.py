"""Flight recorder: SLO-triggered, cluster-correlated diagnostic capture.

Covers control/flight.py end to end -- injected-clock trigger math for every
trigger kind, cooldown suppression, the pre-sampling span ring, bundle
schema round-trip against tools/flight_check.py, on-disk retention, the
2-node correlated capture over the `flightcapture` peer verb -- plus the
satellite planes that shipped with it: the buffered WebhookTarget audit
sink (control/logging.py) and the PubSub drop disclosure (control/pubsub.py).

The end-to-end acceptance test stands up a real 2-node in-process cluster,
runs a loadgen scenario with an armed drive-fault window, and asserts the
flight gate: every node auto-captured a bundle covering the fault window,
and the healthy phase produced none.
"""

import importlib.util
import json
import os
import queue
import threading
import time
from pathlib import Path

import pytest

from minio_tpu.control import tracing
from minio_tpu.control.degrade import DegradeStats
from minio_tpu.control.flight import (
    BUNDLE_SCHEMA,
    TRIGGER_KINDS,
    FlightRecorder,
    GLOBAL_FLIGHT,
    SpanRing,
    _safe_tag,
)
from minio_tpu.control.logging import WebhookTarget
from minio_tpu.control.perf import PerfSys
from minio_tpu.control.pubsub import GLOBAL_TRACE, PubSub

_REPO = Path(__file__).resolve().parent.parent
_spec = importlib.util.spec_from_file_location(
    "flight_check", _REPO / "tools" / "flight_check.py"
)
flight_check = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(flight_check)

_LINT_SPEC = importlib.util.spec_from_file_location(
    "metrics_lint", _REPO / "tools" / "metrics_lint.py"
)
metrics_lint = importlib.util.module_from_spec(_LINT_SPEC)
_LINT_SPEC.loader.exec_module(metrics_lint)


class _Span:
    """Minimal stand-in for tracing.Span in record_span tests."""

    def __init__(self, name="op", layer="api", trace_id="t-1"):
        self.name = name
        self.layer = layer
        self.trace_id = trace_id


def _recorder(tmp_path, **kw) -> FlightRecorder:
    """A recorder with every knob pinned (no env dependence) over a private
    PerfSys/DegradeStats pair, so injected-clock tests see only their own
    traffic."""
    args = dict(
        dir=str(tmp_path),
        window_s=30.0,
        cooldown_s=60.0,
        retain=16,
        poll_s=1.0,
        err_rate=0.5,
        p99_ms=0.0,
        min_ops=10,
        deadline_burst=3,
        perf=PerfSys(),
        degrade=DegradeStats(),
    )
    args.update(kw)
    return FlightRecorder(**args)


# The injected clock: check_triggers(now) judges second int(now) - 1.
T = 1000.0


class TestTriggerMath:
    """Every trigger kind against an injected clock and private counters."""

    def test_error_spike_fires_on_closed_second(self, tmp_path):
        fr = _recorder(tmp_path, min_ops=5)
        for _ in range(6):
            fr.perf.timeseries.record("get", 0.01, ok=False, now=T - 0.8)
        for _ in range(4):
            fr.perf.timeseries.record("get", 0.01, ok=True, now=T - 0.8)
        fired = fr.check_triggers(now=T + 0.5)
        reasons = [r for r, _ in fired]
        assert reasons == ["error-spike"]
        detail = fired[0][1]
        assert detail["second"] == int(T) - 1
        assert detail["count"] == 10 and detail["errors"] == 6
        assert detail["rate"] == pytest.approx(0.6)

    def test_min_ops_floor_mutes_tiny_seconds(self, tmp_path):
        # 3 ops, 100% errors: statistically meaningless, must not fire.
        fr = _recorder(tmp_path, min_ops=5)
        for _ in range(3):
            fr.perf.timeseries.record("get", 0.01, ok=False, now=T - 0.8)
        assert fr.check_triggers(now=T + 0.5) == []

    def test_p99_threshold_fires_without_errors(self, tmp_path):
        fr = _recorder(tmp_path, min_ops=5, p99_ms=50.0)
        for _ in range(20):
            fr.perf.timeseries.record("get", 0.2, ok=True, now=T - 0.8)
        fired = fr.check_triggers(now=T + 0.5)
        reasons = [r for r, _ in fired]
        assert reasons == ["p99"]  # zero errors: no error-spike co-fire
        assert fired[0][1]["p99_ms"] >= 50.0

    def test_each_second_judged_once(self, tmp_path):
        fr = _recorder(tmp_path, min_ops=5)
        for _ in range(10):
            fr.perf.timeseries.record("get", 0.01, ok=False, now=T - 0.8)
        assert len(fr.check_triggers(now=T + 0.5)) == 1
        # Same second re-checked: already judged, and the degrade counters
        # didn't move, so nothing fires.
        assert fr.check_triggers(now=T + 0.6) == []

    def test_shed_edge_fires_after_baseline(self, tmp_path):
        fr = _recorder(tmp_path)
        # First poll only establishes the baseline -- a recorder attaching
        # to a long-lived process must not fire on history.
        fr.degrade.record_shed("read")
        assert fr.check_triggers(now=T + 0.5) == []
        fr.degrade.record_shed("read")
        fired = fr.check_triggers(now=T + 1.5)
        assert [r for r, _ in fired] == ["shed"]
        assert fired[0][1]["sheds"] == 1

    def test_breaker_open_edge(self, tmp_path):
        fr = _recorder(tmp_path)
        assert fr.check_triggers(now=T + 0.5) == []
        fr.degrade.record_breaker(tripped=True)
        fired = fr.check_triggers(now=T + 1.5)
        assert [r for r, _ in fired] == ["breaker-open"]

    def test_deadline_burst_needs_threshold(self, tmp_path):
        fr = _recorder(tmp_path, deadline_burst=3)
        assert fr.check_triggers(now=T + 0.5) == []
        fr.degrade.record_deadline_abort("erasure.read")
        fr.degrade.record_deadline_abort("erasure.read")
        assert fr.check_triggers(now=T + 1.5) == []  # 2 < burst threshold
        for _ in range(3):
            fr.degrade.record_deadline_abort("erasure.read")
        fired = fr.check_triggers(now=T + 2.5)
        assert [r for r, _ in fired] == ["deadline-burst"]
        assert fired[0][1]["aborts"] == 3

    def test_poll_once_cooldown_suppresses_second_incident(self, tmp_path):
        fr = _recorder(tmp_path, min_ops=5, cooldown_s=60.0, window_s=5.0)
        for _ in range(10):
            fr.perf.timeseries.record("get", 0.01, ok=False, now=T - 0.8)
        inc = fr.poll_once(now=T + 0.5)
        assert inc is not None and inc["reason"] == "error-spike"
        # A second spike inside the cooldown: evaluated but muted.
        for _ in range(10):
            fr.perf.timeseries.record("get", 0.01, ok=False, now=T + 4.2)
        assert fr.poll_once(now=T + 5.5) is None
        assert fr.stats()["suppressed"] == 1
        assert fr.stats()["triggers"] == {"error-spike": 1}

    def test_cofired_reasons_ride_in_detail_also(self, tmp_path):
        fr = _recorder(tmp_path, min_ops=5, p99_ms=50.0, window_s=5.0)
        for _ in range(10):
            fr.perf.timeseries.record("get", 0.2, ok=False, now=T - 0.8)
        inc = fr.poll_once(now=T + 0.5)
        assert inc["reason"] == "error-spike"  # one incident, not two
        assert inc["detail"]["also"] == ["p99"]
        assert fr.stats()["triggers"] == {"error-spike": 1}

    def test_incident_window_matches_window_knob(self, tmp_path):
        fr = _recorder(tmp_path, window_s=12.0)
        inc = fr.trigger("manual", now=T, fan_out=False)
        assert inc["t1"] == T and inc["t0"] == T - 12.0
        assert inc["reason"] in TRIGGER_KINDS


class TestSpanRing:
    def test_bounded_eviction_is_oldest_first(self):
        ring = SpanRing(32)
        for i in range(100):
            ring.append({"t": float(i)})
        assert len(ring) == 32
        assert ring.window(0, 1000) == [{"t": float(i)} for i in range(68, 100)]

    def test_maxlen_floor(self):
        assert SpanRing(2).maxlen == 16

    def test_window_filters_inclusive(self):
        ring = SpanRing(64)
        for t in (1.0, 2.0, 3.0, 4.0):
            ring.append({"t": t})
        assert [r["t"] for r in ring.window(2.0, 3.0)] == [2.0, 3.0]


class TestBundleStore:
    def test_manual_trigger_round_trips_through_flight_check(self, tmp_path):
        fr = _recorder(tmp_path)
        fr.record_span(_Span("GetObject", "api", "tr-1"), 0.005)
        fr.record_span(_Span("PutObject", "api", "tr-2"), 0.050, error="faulted")
        inc = fr.trigger("manual", detail={"via": "test"}, fan_out=False)
        metas = fr.list()
        assert len(metas) == 1
        bundle = fr.get(metas[0]["id"])
        assert flight_check.check_bundle(bundle, "test") == []
        assert bundle["flight_bundle"] == BUNDLE_SCHEMA
        assert bundle["id"] == f"{inc['incident']}__{_safe_tag(fr.node_id)}"
        names = {s["name"] for s in bundle["spans"]}
        assert names == {"GetObject", "PutObject"}
        errs = [s for s in bundle["spans"] if s.get("error")]
        assert len(errs) == 1 and errs[0]["error"] == "faulted"
        # Bare incident id resolves to the same bundle (GET /flight/{id}).
        assert fr.get(inc["incident"])["id"] == bundle["id"]

    def test_capture_is_idempotent_per_incident_and_node(self, tmp_path):
        fr = _recorder(tmp_path)
        inc = fr.trigger("manual", fan_out=False)
        assert fr.stats()["bundles_written"] == 1
        assert fr.capture(inc) is None  # replayed fanout: no-op
        assert fr.stats()["bundles_written"] == 1
        # The receiving side arms its cooldown off the incident window.
        assert fr.stats()["last_trigger_time"] >= inc["t1"]

    def test_retention_prunes_oldest_per_node(self, tmp_path):
        fr = _recorder(tmp_path, retain=2)
        incidents = [fr.trigger("manual", fan_out=False) for _ in range(4)]
        files = [n for n in os.listdir(str(tmp_path)) if n.startswith("flight-")]
        assert len(files) == 2
        assert fr.stats()["bundles_written"] == 4
        assert fr.stats()["bundles_pruned"] == 2
        # The survivors are the two NEWEST incidents.
        kept = {m["incident"] for m in fr.list()}
        assert kept == {i["incident"] for i in incidents[2:]}
        assert flight_check.check_dir(str(tmp_path), retain=2) == []

    def test_list_is_newest_first(self, tmp_path):
        fr = _recorder(tmp_path)
        a = fr.trigger("manual", fan_out=False)
        time.sleep(0.02)
        b = fr.trigger("manual", fan_out=False)
        metas = fr.list()
        assert [m["incident"] for m in metas] == [b["incident"], a["incident"]]

    def test_corrupt_bundle_files_are_skipped(self, tmp_path):
        fr = _recorder(tmp_path)
        fr.trigger("manual", fan_out=False)
        (tmp_path / "flight-garbage__local.json").write_text("{not json")
        assert len(fr.list()) == 1

    def test_flight_check_flags_a_tampered_bundle(self, tmp_path):
        fr = _recorder(tmp_path)
        inc = fr.trigger("manual", fan_out=False)
        bundle = fr.get(inc["incident"])
        bundle["reason"] = "not-a-reason"
        problems = flight_check.check_bundle(bundle, "test")
        assert problems and any("reason" in p for p in problems)


class TestPreSamplingRing:
    """Satellite: MTPU_TRACE_SAMPLE must never blind the black box."""

    def test_sampled_out_root_still_feeds_flight_ring(self, monkeypatch):
        monkeypatch.setenv("MTPU_TRACE_SAMPLE", "0")  # sample NOTHING
        GLOBAL_FLIGHT.ring.clear()
        with tracing.root_span("op", "flightlayer", "trace-flight-presample") as root:
            assert root.sampled is False
            with tracing.span("child-stage", "flightlayer"):
                pass
        recs = [
            r for r in GLOBAL_FLIGHT.ring.window(0, time.time() + 1)
            if r["trace"] == "trace-flight-presample"
        ]
        # The root landed despite the 0% sample rate; the child did not
        # (the ring holds ROOT spans only -- the bundle is a request index,
        # the full tree lives in the trace plane).
        assert [r["name"] for r in recs] == ["op"]
        assert recs[0]["layer"] == "flightlayer"

    def test_record_span_overhead_is_microseconds(self, tmp_path):
        # Tier-1 smoke for the O(1) off-lock append claim: the hot-path
        # feed must stay far under 500us per span (same budget the
        # disarmed stage-mark test in test_perf.py holds).
        fr = _recorder(tmp_path)
        span = _Span("GetObject", "api", "tr-bench")
        n = 2000
        t0 = time.perf_counter()
        for _ in range(n):
            fr.record_span(span, 0.001)
        dt = time.perf_counter() - t0
        assert dt / n < 500e-6, f"record_span cost {dt / n * 1e6:.1f}us"


class TestWebhookTargetQueue:
    """Satellite: the audit webhook never blocks the request path."""

    class _StubSession:
        def __init__(self, gate=None, fail_times=0):
            self.gate = gate
            self.fail_times = fail_times
            self.posts = []

        def post(self, endpoint, json=None, timeout=None):
            if self.gate is not None:
                self.gate.wait()
            if self.fail_times > 0:
                self.fail_times -= 1
                raise OSError("connection refused")
            self.posts.append(json)

    def _target(self, **kw) -> WebhookTarget:
        t = WebhookTarget("http://127.0.0.1:1/audit", **kw)
        t.session = self._stub  # swap before any entry is enqueued
        return t

    def test_full_queue_drops_and_counts(self):
        gate = threading.Event()  # held: the sender blocks inside post()
        self._stub = self._StubSession(gate=gate)
        t = self._target(queue_size=2)
        try:
            t.send({"n": 0})
            deadline = time.time() + 5
            while t._q.qsize() and time.time() < deadline:
                time.sleep(0.005)  # sender picked n=0 and is parked in post()
            assert t._q.qsize() == 0
            for n in (1, 2, 3):  # two fit the queue, the third drops
                t.send({"n": n})
            assert t.stats()["dropped"] == 1
        finally:
            gate.set()
            t.close()
        assert t.stats()["sent"] == 3
        assert t.stats()["failed"] == 0

    def test_retry_then_success(self):
        self._stub = self._StubSession(fail_times=1)
        t = self._target(retries=2, retry_wait_s=0.01)
        t.send({"n": 1})
        t.close()
        st = t.stats()
        assert st["sent"] == 1 and st["failed"] == 0 and st["dropped"] == 0
        assert self._stub.posts == [{"n": 1}]

    def test_exhausted_retries_count_as_failed(self):
        self._stub = self._StubSession(fail_times=100)
        t = self._target(retries=1, retry_wait_s=0.01)
        t.send({"n": 1})
        t.close()
        st = t.stats()
        assert st["failed"] == 1 and st["sent"] == 0

    def test_close_flushes_the_queue(self):
        self._stub = self._StubSession()
        t = self._target(queue_size=100)
        for n in range(20):
            t.send({"n": n})
        t.close()
        st = t.stats()
        assert st["sent"] == 20 and st["queued"] == 0 and st["dropped"] == 0

    def test_send_never_blocks_with_dead_sink(self):
        # Even with the sender wedged, send() returns immediately.
        gate = threading.Event()
        self._stub = self._StubSession(gate=gate)
        t = self._target(queue_size=1)
        try:
            t0 = time.perf_counter()
            for n in range(50):
                t.send({"n": n})
            assert time.perf_counter() - t0 < 0.5
            assert t.stats()["dropped"] >= 48
        finally:
            gate.set()
            t.close()


class TestPubSubDropDisclosure:
    """Satellite: a slow subscriber loses messages observably, and never
    stalls publishers or starves fast subscribers."""

    def test_slow_subscriber_drops_are_counted_per_hub(self):
        hub = PubSub("testhub")
        slow = hub.subscribe(maxsize=1)
        fast = hub.subscribe(maxsize=10)
        for i in range(3):
            hub.publish({"i": i})
        assert hub.dropped == 2  # slow kept 1 of 3; fast kept all
        assert slow.qsize() == 1
        assert [fast.get_nowait()["i"] for _ in range(3)] == [0, 1, 2]

    def test_hub_names_label_the_metric(self):
        from minio_tpu.control.events import EventNotifier
        from minio_tpu.control.logging import GLOBAL_LOGGER

        assert GLOBAL_TRACE.hub.name == "trace"
        assert GLOBAL_LOGGER.audit_hub.name == "audit"
        assert EventNotifier().listen_hub.name == "listen"


class TestSpecFlightGate:
    def test_parse_flight_block(self):
        from minio_tpu.loadgen.spec import parse_scenario

        sc = parse_scenario({
            "name": "t", "bucket": "b",
            "phases": [{"name": "p0", "mix": {"GET": 1.0}, "ops": 1}],
            "flight": {"phase": "p0", "max_wait_s": 5},
        })
        assert sc.flight == {"phase": "p0", "max_wait_s": 5.0}

    def test_unknown_phase_rejected(self):
        from minio_tpu.loadgen.spec import SpecError, parse_scenario

        with pytest.raises(SpecError, match="unknown phase"):
            parse_scenario({
                "name": "t", "bucket": "b",
                "phases": [{"name": "p0", "mix": {"GET": 1.0}, "ops": 1}],
                "flight": {"phase": "nope"},
            })

    def test_canonical_scenario_declares_the_gate(self):
        from minio_tpu.loadgen import load_scenario

        sc = load_scenario(str(_REPO / "scenarios" / "flight_recorder.yaml"))
        assert sc.flight == {"phase": "faulted", "max_wait_s": 10.0}
        assert sc.env.get("MTPU_FLIGHT") == "1"
        faulted = next(p for p in sc.phases if p.name == "faulted")
        assert faulted.chaos, "the gated phase must arm a fault window"


class TestClusterCorrelatedCapture:
    """An incident on one node freezes the SAME wall-clock window on every
    node via the `flightcapture` peer verb (real internode REST)."""

    def test_two_node_capture_same_window(self, tmp_path, monkeypatch):
        from minio_tpu.loadgen.cluster import InProcessCluster

        store = tmp_path / "flightstore"
        monkeypatch.setenv("MTPU_FLIGHT_DIR", str(store))
        # MTPU_FLIGHT stays 0 (conftest): the trigger THREAD is off, but
        # the capture plane is always live -- fire the incident by hand.
        cluster = InProcessCluster(
            str(tmp_path / "data"), n_nodes=2, drives_per_node=4
        )
        try:
            GLOBAL_FLIGHT.configure()  # pick up the store dir
            assert GLOBAL_FLIGHT.node_id in cluster.urls  # build wired us
            inc = GLOBAL_FLIGHT.trigger("manual", detail={"via": "test"})
            metas = [
                m for m in GLOBAL_FLIGHT.list()
                if m["incident"] == inc["incident"]
            ]
            assert {m["node"] for m in metas} == set(cluster.urls)
            # Correlation is the point: identical window on every node.
            assert {json.dumps(m["window"]) for m in metas} == {
                json.dumps({"t0": inc["t0"], "t1": inc["t1"]})
            }
            for m in metas:
                assert m["origin"] == GLOBAL_FLIGHT.node_id
            assert flight_check.check_dir(str(store)) == []
            # The flight/pubsub/audit series ride the node exposition and
            # stay lint-clean.
            text = cluster.nodes[0].metrics.render_node()
            assert metrics_lint.validate_exposition(text) == []
            for series in (
                "minio_tpu_flight_triggers_total",
                "minio_tpu_flight_bundles_written_total",
                "minio_tpu_flight_ring_spans",
                "minio_tpu_pubsub_dropped_total",
                "minio_tpu_audit_dropped_total",
            ):
                assert series in text, series
            assert 'reason="manual"' in text
        finally:
            cluster.stop()
            GLOBAL_FLIGHT.stop()
            monkeypatch.undo()
            GLOBAL_FLIGHT.configure()
            GLOBAL_FLIGHT.reset()


class TestFlightGateEndToEnd:
    """Acceptance: a loadgen run with an armed fault window auto-captures a
    bundle on EVERY node covering the fault's wall-clock window, and the
    healthy phase produces none."""

    def test_fault_window_produces_cluster_bundle_set(self, tmp_path, monkeypatch):
        from minio_tpu.loadgen.cluster import InProcessCluster
        from minio_tpu.loadgen.runner import ScenarioRunner
        from minio_tpu.loadgen.spec import parse_scenario
        from minio_tpu.loadgen.target import InProcessAdmin, S3Target

        store = tmp_path / "flightstore"
        monkeypatch.setenv("MTPU_FLIGHT", "1")
        monkeypatch.setenv("MTPU_FLIGHT_DIR", str(store))
        monkeypatch.setenv("MTPU_FLIGHT_ERR_RATE", "0.3")
        monkeypatch.setenv("MTPU_FLIGHT_MIN_OPS", "5")
        monkeypatch.setenv("MTPU_FLIGHT_COOLDOWN_S", "30")
        monkeypatch.setenv("MTPU_FLIGHT_WINDOW_S", "10")
        sc = parse_scenario({
            "name": "flight_gate_ci",
            "seed": 7,
            "bucket": "flgate",
            "cluster": {"nodes": 2, "drives_per_node": 4},
            "keyspace": {"keys": 32, "prepopulate": 32, "prefix": "fl/",
                         "zipf_theta": 0.9},
            # Over SMALL_FILE_THRESHOLD (128 KiB): sub-threshold objects
            # inline their shards in xl.meta, so a shard-read fault would
            # never touch the GET path.
            "sizes": {"kind": "fixed", "bytes": 262144},
            "slo": {"GET": {"p99_ms": 30000, "error_budget": 1.0},
                    "PUT": {"p99_ms": 30000, "error_budget": 1.0}},
            "phases": [
                {"name": "healthy",
                 "mix": {"GET": 0.7, "PUT": 0.3},
                 "concurrency": 4, "duration_s": 2, "ops": 400},
                {"name": "faulted",
                 "mix": {"GET": 0.9, "PUT": 0.1},
                 "concurrency": 8, "duration_s": 4, "ops": 1600,
                 "chaos": [{"at_s": 0.5, "for_s": 2.5,
                            "fault": {"kind": "drive-error",
                                      "ops": ["read_file",
                                              "read_file_into"],
                                      "probability": 1.0, "seed": 7}}]},
            ],
            "flight": {"phase": "faulted", "max_wait_s": 10},
        })
        # Env must be live BEFORE the cluster builds: Node.build() arms the
        # trigger engine (ensure_started re-reads every MTPU_FLIGHT_* knob).
        cluster = InProcessCluster(
            str(tmp_path / "data"), n_nodes=2, drives_per_node=4
        )
        try:
            target = S3Target(cluster.urls, cluster.root_user,
                              cluster.root_password)
            report = ScenarioRunner(sc, target, InProcessAdmin()).run()
            fl = report["flight"]
            assert fl["ok"] is True, fl
            assert fl["false_triggers"] == []
            assert sorted(fl["nodes_captured"]) == sorted(cluster.urls)
            # Every captured bundle covers the same incident window and
            # validates against the bundle schema.
            incidents = {m["incident"] for m in fl["bundles"]}
            assert len(incidents) == 1, fl["bundles"]
            for meta in fl["bundles"]:
                bundle = GLOBAL_FLIGHT.get(meta["id"])
                assert flight_check.check_bundle(bundle, meta["id"]) == []
        finally:
            cluster.stop()
            GLOBAL_FLIGHT.stop()
            monkeypatch.undo()
            GLOBAL_FLIGHT.configure()
            GLOBAL_FLIGHT.reset()
