"""Parquet reader + S3 Select over parquet.

The reader is validated two ways: against real pyarrow-written files from
the reference's test data when present (spec compliance), and against
hand-assembled spec-exact files covering encodings the fixtures don't
(snappy, dictionary pages, nulls, page v2 headers via the snappy path).
"""

import io
import os
import struct

import pytest

from minio_tpu.s3select import parquet as pq

REF_TESTDATA = "/root/reference/internal/s3select/testdata"


# -- snappy -------------------------------------------------------------------


def _snappy_compress_literal(data: bytes) -> bytes:
    """Minimal valid snappy stream: one literal (enough for roundtrips)."""
    out = bytearray()
    n = len(data)
    v = n
    while True:
        b = v & 0x7F
        v >>= 7
        out.append(b | (0x80 if v else 0))
        if not v:
            break
    if n == 0:
        return bytes(out)  # preamble only: zero-length stream
    length = n - 1
    if length < 60:
        out.append(length << 2)
    else:
        extra = (length.bit_length() + 7) // 8
        out.append((59 + extra) << 2)
        out += length.to_bytes(extra, "little")
    out += data
    return bytes(out)


def test_snappy_literal_roundtrip():
    for payload in (b"", b"x", b"hello world" * 100, os.urandom(5000)):
        assert pq.snappy_decompress(_snappy_compress_literal(payload)) == payload


def test_snappy_copy_ops():
    # literal "abcd" + copy(offset=4, length=4) => "abcdabcd" (overlap safe).
    stream = bytes([8]) + bytes([3 << 2]) + b"abcd" + bytes([(0 << 2) | 1 | ((4 - 4) << 2)]) + b""
    # Build explicitly: tag1 = copy kind1, len=4 -> ((4-4)<<2)|1, offset=4 -> tag |= 0<<5, next byte 4
    stream = bytes([8, 3 << 2]) + b"abcd" + bytes([1, 4])
    assert pq.snappy_decompress(stream) == b"abcdabcd"


# -- reference fixtures (real pyarrow output) ---------------------------------


@pytest.mark.skipif(not os.path.isdir(REF_TESTDATA), reason="reference testdata absent")
def test_reads_real_pyarrow_file():
    data = open(os.path.join(REF_TESTDATA, "testdata.parquet"), "rb").read()
    names, rows = pq.read_rows(data)
    assert {"one", "two", "three"} <= set(names)
    assert len(rows) == 3
    assert rows[0]["one"] == -1.0 and rows[0]["two"] == "foo" and rows[0]["three"] is True
    assert rows[1]["one"] is None  # null preserved through def levels
    assert rows[2]["one"] == 2.5 and rows[2]["two"] == "baz" and rows[2]["three"] is True


@pytest.mark.skipif(not os.path.isdir(REF_TESTDATA), reason="reference testdata absent")
def test_reads_date_column():
    data = open(os.path.join(REF_TESTDATA, "lineitem_shipdate.parquet"), "rb").read()
    names, rows = pq.read_rows(data)
    assert names == ["shipdate"]
    assert len(rows) == 10
    # DATE converted type -> ISO date strings (1996-03-13 era lineitem data).
    assert all(isinstance(r["shipdate"], str) and r["shipdate"][:2] == "19" for r in rows)


# -- hand-assembled files (writer below is test-only) -------------------------


def _thrift_varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        out.append(b | (0x80 if n else 0))
        if not n:
            return bytes(out)


def _thrift_zigzag(n: int) -> bytes:
    return _thrift_varint((n << 1) ^ (n >> 63) if n < 0 else n << 1)


class _TW:
    """Tiny thrift compact writer for the structs the reader parses."""

    def __init__(self):
        self.buf = bytearray()
        self.last_id = [0]

    def field(self, fid: int, ctype: int):
        delta = fid - self.last_id[-1]
        if 0 < delta < 16:
            self.buf.append((delta << 4) | ctype)
        else:
            self.buf.append(ctype)
            self.buf += _thrift_zigzag(fid)
        self.last_id[-1] = fid

    def i32(self, fid: int, v: int):
        self.field(fid, 5)
        self.buf += _thrift_zigzag(v)

    def i64(self, fid: int, v: int):
        self.field(fid, 6)
        self.buf += _thrift_zigzag(v)

    def binary(self, fid: int, v: bytes):
        self.field(fid, 8)
        self.buf += _thrift_varint(len(v)) + v

    def start_struct(self, fid: int):
        self.field(fid, 12)
        self.last_id.append(0)

    def end_struct(self):
        self.buf.append(0)
        self.last_id.pop()

    def start_list(self, fid: int, elem: int, size: int):
        self.field(fid, 9)
        if size < 15:
            self.buf.append((size << 4) | elem)
        else:
            self.buf.append(0xF0 | elem)
            self.buf += _thrift_varint(size)

    def stop(self):
        self.buf.append(0)
        return bytes(self.buf)


def _write_simple_parquet(int_col, str_col, codec=pq.CODEC_UNCOMPRESSED) -> bytes:
    """One row group, two required columns (INT64 'n', UTF8 's'), PLAIN."""
    n = len(int_col)
    blob = bytearray(pq.MAGIC)

    def page(col_vals, ptype):
        if ptype == pq.INT64:
            body = struct.pack(f"<{n}q", *col_vals)
        else:
            body = b"".join(
                struct.pack("<i", len(v.encode())) + v.encode() for v in col_vals
            )
        comp = body if codec == pq.CODEC_UNCOMPRESSED else _snappy_compress_literal(body)
        w = _TW()
        w.i32(1, pq.PAGE_DATA)  # type
        w.i32(2, len(body))  # uncompressed
        w.i32(3, len(comp))  # compressed
        w.start_struct(5)  # DataPageHeader
        w.i32(1, n)
        w.i32(2, pq.ENC_PLAIN)
        w.i32(3, pq.ENC_RLE)
        w.i32(4, pq.ENC_RLE)
        w.end_struct()
        return w.stop() + comp

    offsets = []
    for vals, ptype in ((int_col, pq.INT64), (str_col, pq.BYTE_ARRAY)):
        offsets.append(len(blob))
        blob += page(vals, ptype)

    fmd = _TW()
    fmd.i32(1, 1)  # version
    # schema list: root + 2 cols
    fmd.start_list(2, 12, 3)

    def schema_el(name, ptype=None, conv=None):
        w = _TW()
        if ptype is not None:
            w.i32(1, ptype)
            w.i32(3, 0)  # required
        w.binary(4, name.encode())
        if ptype is None:
            w.i32(5, 2)  # num_children on root
        if conv is not None:
            w.i32(6, conv)
        return w.stop()

    fmd.buf += schema_el("root")[:-0] if False else b""
    # Write the three SchemaElement structs inline (list elements).
    for el in (schema_el("root"), schema_el("n", pq.INT64), schema_el("s", pq.BYTE_ARRAY, conv=0)):
        fmd.buf += el
    fmd.i64(3, n)  # num_rows
    # row_groups list with one RowGroup
    fmd.start_list(4, 12, 1)
    rg = _TW()
    rg.start_list(1, 12, 2)  # columns
    for off, (vals, ptype, name) in zip(
        offsets, ((int_col, pq.INT64, b"n"), (str_col, pq.BYTE_ARRAY, b"s"))
    ):
        cc = _TW()
        cc.start_struct(3)  # meta_data
        cc.i32(1, ptype)
        cc.start_list(3, 8, 1)  # path_in_schema
        cc.buf += _thrift_varint(len(name)) + name
        cc.i32(4, codec)
        cc.i64(5, n)
        cc.i64(7, 0)  # total_compressed_size (unused)
        cc.i64(9, off)  # data_page_offset
        cc.end_struct()
        rg.buf += cc.stop()
    rg.i64(2, 0)  # total_byte_size
    rg.i64(3, n)  # num_rows
    fmd.buf += rg.stop()
    meta = fmd.stop()
    blob += meta
    blob += struct.pack("<I", len(meta)) + pq.MAGIC
    return bytes(blob)


def test_hand_assembled_plain():
    data = _write_simple_parquet([1, 2, 300], ["a", "bb", "ccc"])
    names, rows = pq.read_rows(data)
    assert names == ["n", "s"]
    assert rows == [
        {"n": 1, "s": "a"},
        {"n": 2, "s": "bb"},
        {"n": 300, "s": "ccc"},
    ]


def test_hand_assembled_snappy():
    data = _write_simple_parquet([10, -20], ["x", "y"], codec=pq.CODEC_SNAPPY)
    _, rows = pq.read_rows(data)
    assert rows == [{"n": 10, "s": "x"}, {"n": -20, "s": "y"}]


def test_rejects_garbage():
    with pytest.raises(pq.ParquetError):
        pq.read_rows(b"PAR1 this is not parquet PAR1")
    with pytest.raises(pq.ParquetError):
        pq.read_rows(b"plainly not parquet at all")


# -- S3 Select over parquet through the live API ------------------------------


@pytest.mark.skipif(not os.path.isdir(REF_TESTDATA), reason="reference testdata absent")
def test_select_parquet_over_http(tmp_path):
    from minio_tpu.api.server import S3Server, ThreadedServer
    from minio_tpu.control.iam import IAMSys
    from minio_tpu.object.pools import ServerPools
    from minio_tpu.object.sets import ErasureSets
    from minio_tpu.s3select import decode_messages
    from tests.harness import ErasureHarness
    from tests.s3client import S3TestClient

    hz = ErasureHarness(tmp_path, n_disks=4)
    layer = ServerPools([ErasureSets(list(hz.drives), 4)])
    srv = S3Server(layer, IAMSys("pak", "pak-secret-key"), check_skew=False)
    ts = ThreadedServer(srv)
    c = S3TestClient(ts.start(), "pak", "pak-secret-key")
    try:
        c.make_bucket("parq")
        raw = open(os.path.join(REF_TESTDATA, "testdata.parquet"), "rb").read()
        c.put_object("parq", "t.parquet", raw)
        body = b"""<?xml version="1.0" encoding="UTF-8"?>
<SelectObjectContentRequest>
  <Expression>SELECT two, one FROM S3Object WHERE three = TRUE</Expression>
  <ExpressionType>SQL</ExpressionType>
  <InputSerialization><Parquet/></InputSerialization>
  <OutputSerialization><CSV/></OutputSerialization>
</SelectObjectContentRequest>"""
        r = c.request(
            "POST", "/parq/t.parquet",
            query=[("select", ""), ("select-type", "2")], body=body,
        )
        assert r.status_code == 200, r.text
        records = b"".join(
            m["payload"]
            for m in decode_messages(r.content)
            if m["headers"].get(":event-type") == "Records"
        )
        lines = records.decode().strip().splitlines()
        assert lines == ["foo,-1", "baz,2.5"]
    finally:
        ts.stop()


def test_corrupt_metadata_is_client_error(tmp_path):
    """Truncated thrift metadata must surface in-band, not as a 500."""
    from minio_tpu.s3select.select import S3SelectRequest, SelectError, run_select

    good = _write_simple_parquet([1], ["a"])
    # Clobber the metadata region while keeping magic + footer length intact.
    bad = bytearray(good)
    for i in range(8, min(40, len(bad) - 12)):
        bad[i] = 0xFF
    req = S3SelectRequest(expression="SELECT * FROM S3Object")
    req.input_format = "parquet"
    with pytest.raises(SelectError) as ei:
        list(run_select(req, lambda a, b: bytes(bad)))
    assert ei.value.code == "InvalidDataSource"


def test_scan_range_rejected_for_parquet():
    from minio_tpu.s3select.select import S3SelectRequest, SelectError, run_select

    data = _write_simple_parquet([1], ["a"])
    req = S3SelectRequest(expression="SELECT * FROM S3Object")
    req.input_format = "parquet"
    req.scan_start, req.scan_end = 0, 100
    with pytest.raises(SelectError) as ei:
        list(run_select(req, lambda a, b: data))
    assert ei.value.code == "UnsupportedScanRangeInput"
