"""Collection shim: pytest only collects test_*.py, but the chaos scenario
harness lives at tests/chaos_scenarios.py (the path the reliability docs and
tools/chaos_check.py reference). Importing * re-exports every scenario so the
normal suite runs them; markers (slow) ride along with the objects."""

from tests.chaos_scenarios import *  # noqa: F401,F403
