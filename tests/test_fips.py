"""FIPS mode (internal/fips role, runtime-switched): SigV2 refused,
SigV4 unchanged, mode reported. Bitrot/ETag stay unchanged by design —
the reference's FIPS build also keeps HighwayHash bitrot and MD5 ETags
(integrity checksums, not security controls)."""

import os

import pytest

from minio_tpu.utils import fips


@pytest.fixture()
def fips_on(monkeypatch):
    monkeypatch.setenv("MINIO_TPU_FIPS", "on")
    yield


class TestFips:
    def test_flag_parsing(self, monkeypatch):
        for v, want in (("on", True), ("1", True), ("true", True),
                        ("off", False), ("", False), ("0", False)):
            monkeypatch.setenv("MINIO_TPU_FIPS", v)
            assert fips.enabled() is want

    def test_sigv2_refused_sigv4_serves(self, fips_on, tmp_path):
        from types import SimpleNamespace

        from minio_tpu.api.server import ThreadedServer
        from minio_tpu.dist.node import Node
        from minio_tpu.object.codec import HostCodec
        from tests.s3client import S3TestClient

        dirs = []
        for i in range(4):
            d = str(tmp_path / f"n{i}")
            os.makedirs(d)
            dirs.append(d)
        node = Node(dirs, root_user="fipsroot", root_password="fipssecret1", codec=HostCodec())
        ts = ThreadedServer(SimpleNamespace(app=node.make_app()))
        base = ts.start()
        try:
            node.build()
            c = S3TestClient(base, "fipsroot", "fipssecret1")
            assert c.make_bucket("fv4").status_code == 200  # SigV4 works
            body = os.urandom(1 << 20)
            c.put_object("fv4", "o.bin", body)
            assert c.get_object("fv4", "o.bin").content == body
            # A V2-style Authorization header must be refused outright.
            import requests

            r = requests.get(
                f"{base}/fv4",
                headers={"Authorization": "AWS fipsroot:AAAAAAAAAAAAAAAAAAAAAAAAAAA="},
                timeout=10,
            )
            assert r.status_code == 400
            assert "FIPS" in r.text
            # V2 presigned is refused too.
            r = requests.get(
                f"{base}/fv4/o.bin",
                params={"AWSAccessKeyId": "fipsroot", "Signature": "x", "Expires": "9999999999"},
                timeout=10,
            )
            assert r.status_code == 400
            info = c.request("GET", "/mtpu/admin/v1/info")
            assert info.json()["fips"] is True
        finally:
            ts.stop()

    def test_sigv2_serves_without_fips(self, tmp_path, monkeypatch):
        monkeypatch.delenv("MINIO_TPU_FIPS", raising=False)
        from minio_tpu.api.sigv2 import SigV2Verifier

        SigV2Verifier(lambda ak: None)  # constructs fine when FIPS is off
