"""Loadgen harness tests: spec parsing (typed errors), generator
determinism and shape, report/SLO math, and a fast in-process smoke run.

Replay identity is the property everything else leans on: the same
scenario + seed must produce the byte-identical op sequence anywhere, so
two CI runs of a report diff compare the SYSTEM, not the dice. The smoke
run is the tier-1 witness that the whole chain (spec -> generators ->
runner -> cluster -> report -> exposition) holds together.
"""

from __future__ import annotations

import importlib.util
import json
import random
from pathlib import Path

import pytest

from minio_tpu.loadgen import (
    SizeDistribution,
    SpecError,
    ZipfianGenerator,
    build_report,
    evaluate_slo,
    generate_ops,
    load_scenario,
    op_sequence_hash,
    parse_scenario,
    render_prometheus,
)
from minio_tpu.loadgen.report import BURN_CAP
from minio_tpu.loadgen.runner import PhaseResult

_REPO = Path(__file__).resolve().parent.parent
_LINT_PATH = _REPO / "tools" / "metrics_lint.py"
_spec = importlib.util.spec_from_file_location("metrics_lint", _LINT_PATH)
metrics_lint = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(metrics_lint)


def _doc(**over) -> dict:
    """A minimal valid scenario document, overridable per test."""
    doc = {
        "name": "t",
        "seed": 9,
        "keyspace": {"keys": 32, "prepopulate": 16, "prefix": "t/", "zipf_theta": 0.9},
        "phases": [{"name": "p0", "mix": {"GET": 0.7, "PUT": 0.3}, "ops": 50}],
    }
    doc.update(over)
    return doc


class TestSpecParser:
    def test_all_shipped_scenarios_parse(self):
        files = sorted((_REPO / "scenarios").glob("*.yaml"))
        assert len(files) >= 5, "canonical scenario set went missing"
        for f in files:
            sc = load_scenario(str(f))
            assert sc.phases, f.name

    def test_missing_name_is_typed(self):
        with pytest.raises(SpecError) as ei:
            parse_scenario({"phases": []})
        assert ei.value.path == "$.name"

    def test_unknown_op_kind_names_the_field(self):
        with pytest.raises(SpecError) as ei:
            parse_scenario(_doc(phases=[{"name": "p", "mix": {"FROB": 1.0}, "ops": 1}]))
        assert "FROB" in ei.value.path

    def test_zero_weight_mix_rejected(self):
        with pytest.raises(SpecError):
            parse_scenario(_doc(phases=[{"name": "p", "mix": {"GET": 0.0}, "ops": 1}]))

    def test_phase_needs_some_budget(self):
        with pytest.raises(SpecError) as ei:
            parse_scenario(_doc(phases=[{"name": "p", "mix": {"GET": 1.0}}]))
        assert "ops or duration_s" in str(ei.value)

    def test_duplicate_phase_names_rejected(self):
        ph = {"name": "p", "mix": {"GET": 1.0}, "ops": 1}
        with pytest.raises(SpecError) as ei:
            parse_scenario(_doc(phases=[ph, dict(ph)]))
        assert ei.value.path == "$.phases"

    def test_compare_must_reference_real_phases(self):
        with pytest.raises(SpecError) as ei:
            parse_scenario(_doc(compare={"a": "p0", "b": "ghost"}))
        assert ei.value.path == "$.compare.b"

    def test_compare_sweep_list_validates_each_entry(self):
        ok = parse_scenario(_doc(compare=[
            {"a": "p0", "b": "p0", "min_ratio": 1.0},
            {"a": "p0", "b": "p0"},
        ]))
        assert isinstance(ok.compare, list) and len(ok.compare) == 2
        with pytest.raises(SpecError) as ei:
            parse_scenario(_doc(compare=[
                {"a": "p0", "b": "p0"},
                {"a": "p0", "b": "ghost"},
            ]))
        assert ei.value.path == "$.compare[1].b"
        with pytest.raises(SpecError) as ei:
            parse_scenario(_doc(compare=[]))
        assert ei.value.path == "$.compare"

    def test_prepopulate_bounded_by_keyspace(self):
        with pytest.raises(SpecError) as ei:
            parse_scenario(_doc(keyspace={"keys": 4, "prepopulate": 9}))
        assert ei.value.path == "$.keyspace.prepopulate"

    def test_unknown_size_kind_rejected(self):
        with pytest.raises(SpecError) as ei:
            parse_scenario(_doc(sizes={"kind": "pareto"}))
        assert ei.value.path == "$.sizes.kind"

    def test_error_budget_over_one_rejected(self):
        with pytest.raises(SpecError) as ei:
            parse_scenario(_doc(slo={"GET": {"error_budget": 1.5}}))
        assert "error_budget" in ei.value.path

    def test_chaos_fault_needs_kind(self):
        ph = {
            "name": "p", "mix": {"GET": 1.0}, "ops": 1,
            "chaos": [{"at_s": 0, "for_s": 1, "fault": {"prob": 1.0}}],
        }
        with pytest.raises(SpecError) as ei:
            parse_scenario(_doc(phases=[ph]))
        assert ei.value.path.endswith(".fault")

    def test_wrong_type_names_expected_type(self):
        with pytest.raises(SpecError) as ei:
            parse_scenario(
                _doc(phases=[{"name": "p", "mix": {"GET": 1.0}, "ops": 1,
                              "concurrency": "four"}])
            )
        assert "expected" in str(ei.value)

    def test_json_specs_load(self, tmp_path):
        p = tmp_path / "s.json"
        p.write_text(json.dumps(_doc()))
        assert load_scenario(str(p)).name == "t"

    def test_unreadable_and_invalid_files_are_typed(self, tmp_path):
        with pytest.raises(SpecError):
            load_scenario(str(tmp_path / "missing.json"))
        bad = tmp_path / "bad.json"
        bad.write_text("{nope")
        with pytest.raises(SpecError) as ei:
            load_scenario(str(bad))
        assert "invalid JSON" in str(ei.value)

    def test_mix_weights_normalize(self):
        sc = parse_scenario(
            _doc(phases=[{"name": "p", "mix": {"GET": 3, "PUT": 1}, "ops": 1}])
        )
        assert sc.phases[0].mix == {"GET": 0.75, "PUT": 0.25}


class TestZipfian:
    def test_same_seed_same_sequence(self):
        a = ZipfianGenerator(128, 0.99, random.Random(7))
        b = ZipfianGenerator(128, 0.99, random.Random(7))
        assert [a.next_key() for _ in range(500)] == [b.next_key() for _ in range(500)]

    def test_keys_stay_in_range(self):
        g = ZipfianGenerator(64, 0.99, random.Random(1))
        assert all(0 <= g.next_key() < 64 for _ in range(2000))

    def test_theta_skews_the_head(self):
        g = ZipfianGenerator(256, 0.99, random.Random(3))
        ranks = [g.next_rank() for _ in range(5000)]
        head_share = sum(1 for r in ranks if r == 0) / len(ranks)
        assert head_share > 5 / 256  # way above the uniform 1/n share

    def test_theta_zero_is_uniform_ish(self):
        g = ZipfianGenerator(64, 0.0, random.Random(5))
        ranks = [g.next_rank() for _ in range(4000)]
        assert len(set(ranks)) > 50  # mass spreads over most of the space

    def test_invalid_parameters_raise(self):
        with pytest.raises(ValueError):
            ZipfianGenerator(0, 0.5, random.Random(1))
        with pytest.raises(ValueError):
            ZipfianGenerator(8, 1.0, random.Random(1))


class TestSizeDistribution:
    def test_fixed(self):
        d = SizeDistribution({"kind": "fixed", "bytes": 4096})
        assert d.sample(random.Random(1)) == 4096

    def test_uniform_bounds(self):
        d = SizeDistribution({"kind": "uniform", "min": 10, "max": 20})
        rng = random.Random(2)
        assert all(10 <= d.sample(rng) <= 20 for _ in range(500))

    def test_lognormal_clamps(self):
        d = SizeDistribution(
            {"kind": "lognormal", "mean": 1000, "sigma": 2.0, "min": 100, "max": 5000}
        )
        rng = random.Random(3)
        assert all(100 <= d.sample(rng) <= 5000 for _ in range(500))

    def test_choice_only_picks_listed(self):
        d = SizeDistribution(
            {"kind": "choice", "choices": [
                {"bytes": 1, "weight": 1}, {"bytes": 2, "weight": 0}
            ]}
        )
        rng = random.Random(4)
        assert {d.sample(rng) for _ in range(200)} == {1}


class TestGenerateOps:
    def test_same_seed_identical_hash(self):
        sc = parse_scenario(_doc())
        h1 = op_sequence_hash(generate_ops(sc, sc.phases[0], 200))
        h2 = op_sequence_hash(generate_ops(sc, sc.phases[0], 200))
        assert h1 == h2

    def test_different_seed_different_hash(self):
        a = parse_scenario(_doc(seed=1))
        b = parse_scenario(_doc(seed=2))
        assert op_sequence_hash(generate_ops(a, a.phases[0], 200)) != op_sequence_hash(
            generate_ops(b, b.phases[0], 200)
        )

    def test_only_declared_kinds_appear(self):
        sc = parse_scenario(_doc())
        kinds = {op.kind for op in generate_ops(sc, sc.phases[0], 300)}
        assert kinds <= {"GET", "PUT"}

    def test_empty_keyspace_get_degrades_to_put(self):
        sc = parse_scenario(
            _doc(keyspace={"keys": 8, "prepopulate": 0},
                 phases=[{"name": "p", "mix": {"GET": 1.0}, "ops": 20}])
        )
        ops = generate_ops(sc, sc.phases[0], 20)
        assert ops[0].kind == "PUT"  # nothing to read yet

    def test_reads_target_keys_that_exist_at_that_point(self):
        sc = parse_scenario(
            _doc(keyspace={"keys": 16, "prepopulate": 4, "prefix": "t/"},
                 phases=[{"name": "p",
                          "mix": {"GET": 0.4, "PUT": 0.3, "DELETE": 0.3},
                          "ops": 400}])
        )
        live = {f"t/key-{k:06d}" for k in range(4)}
        for op in generate_ops(sc, sc.phases[0], 400):
            if op.kind in ("GET", "SELECT"):
                assert op.key in live, f"read of dead key at op {op.index}"
            elif op.kind == "PUT":
                live.add(op.key)
            elif op.kind == "DELETE":
                assert op.key in live
                live.discard(op.key)

    def test_phase_sizes_override_scenario_sizes(self):
        sc = parse_scenario(
            _doc(sizes={"kind": "fixed", "bytes": 4096},
                 phases=[
                     {"name": "a", "mix": {"PUT": 1.0}, "ops": 10},
                     {"name": "b", "mix": {"PUT": 1.0}, "ops": 10,
                      "sizes": {"kind": "fixed", "bytes": 7777}},
                 ])
        )
        assert {o.size for o in generate_ops(sc, sc.phases[0], 10)} == {4096}
        assert {o.size for o in generate_ops(sc, sc.phases[1], 10)} == {7777}

    def test_list_ops_carry_prefix(self):
        sc = parse_scenario(
            _doc(phases=[{"name": "p", "mix": {"LIST": 1.0}, "ops": 5}])
        )
        for op in generate_ops(sc, sc.phases[0], 5):
            assert op.kind == "LIST" and op.prefix == "t/" and op.size == 0


def _phase_result(name: str, kinds: dict, latencies: dict, wall_s: float = 2.0):
    """Synthetic PhaseResult: counters + ledger observations."""
    pr = PhaseResult(name=name, concurrency=4, wall_s=wall_s, op_hash="x")
    pr.kinds = kinds
    pr.executed = sum(
        row["ok"] + sum(row["errors"].values()) for row in kinds.values()
    )
    pr.generated = pr.executed
    for kind, durs in latencies.items():
        for d in durs:
            pr.ledger.record("loadgen", kind, d)
    return pr


class TestReportAndSlo:
    def _scenario(self, **over):
        doc = _doc(
            slo={"GET": {"p99_ms": 100.0, "error_budget": 0.02}}, **over
        )
        return parse_scenario(doc)

    def test_4xx_errors_do_not_burn_budget(self):
        sc = self._scenario()
        merged = {
            "GET": {"ok": 96, "errors": {"4xx:NoSuchKey": 4}, "p99_ms": 50.0}
        }
        row = evaluate_slo(sc, merged)["GET"]
        assert row["budget_burn"] == 0.0
        assert row["ok"] is True

    def test_5xx_errors_burn(self):
        sc = self._scenario()
        merged = {
            "GET": {"ok": 96, "errors": {"5xx:SlowDownRead": 4}, "p99_ms": 50.0}
        }
        row = evaluate_slo(sc, merged)["GET"]
        assert row["budget_burn"] == pytest.approx(0.04 / 0.02, rel=1e-3)
        assert row["burn_ok"] is False and row["ok"] is False

    def test_client_errors_burn_counts_4xx(self):
        sc = parse_scenario(
            _doc(slo={"GET": {"p99_ms": 100.0, "error_budget": 0.02,
                              "client_errors_burn": True}})
        )
        merged = {
            "GET": {"ok": 96, "errors": {"4xx:NoSuchKey": 4}, "p99_ms": 50.0}
        }
        row = evaluate_slo(sc, merged)["GET"]
        assert row["budget_burn"] == pytest.approx(0.04 / 0.02, rel=1e-3)
        assert row["ok"] is False

    def test_get_miss_is_loss_spec_guards(self):
        # A deleting phase makes every miss ambiguous; an under-prepopulated
        # keyspace makes misses expected. Both must be typed spec errors.
        with pytest.raises(SpecError) as ei:
            parse_scenario(_doc(
                get_miss_is_loss=True,
                keyspace={"keys": 32, "prepopulate": 32},
                phases=[{"name": "p", "mix": {"GET": 0.5, "DELETE": 0.5},
                         "ops": 10}],
            ))
        assert "DELETE" in str(ei.value)
        with pytest.raises(SpecError) as ei:
            parse_scenario(_doc(get_miss_is_loss=True))
        assert ei.value.path == "$.keyspace.prepopulate"

    def test_zero_budget_uses_cap_sentinel(self):
        sc = parse_scenario(
            _doc(slo={"GET": {"p99_ms": 0, "error_budget": 0.0}})
        )
        merged = {"GET": {"ok": 9, "errors": {"transport:timeout": 1}, "p99_ms": 1.0}}
        assert evaluate_slo(sc, merged)["GET"]["budget_burn"] == BURN_CAP

    def test_unexercised_op_is_skipped_not_failed(self):
        sc = self._scenario()
        assert "skipped" in evaluate_slo(sc, {})["GET"]

    def test_p99_target_judgment(self):
        sc = self._scenario()
        merged = {"GET": {"ok": 10, "errors": {}, "p99_ms": 250.0}}
        row = evaluate_slo(sc, merged)["GET"]
        assert row["p99_ok"] is False and row["ok"] is False

    def test_build_report_schema_and_compare(self):
        sc = parse_scenario(
            _doc(
                phases=[
                    {"name": "single", "mix": {"PUT": 1.0}, "ops": 4},
                    {"name": "concurrent", "mix": {"PUT": 1.0}, "ops": 8},
                ],
                compare={"a": "single", "b": "concurrent", "op": "PUT",
                         "metric": "bytes_per_s", "min_ratio": 2.0},
            )
        )
        a = _phase_result(
            "single", {"PUT": {"ok": 4, "bytes": 4000, "errors": {}}},
            {"PUT": [0.01] * 4}, wall_s=1.0,
        )
        b = _phase_result(
            "concurrent", {"PUT": {"ok": 8, "bytes": 1600, "errors": {}}},
            {"PUT": [0.02] * 8}, wall_s=1.0,
        )
        rep = build_report(sc, [a, b], stage_breakdown={"api": {}}, degrade={})
        assert rep["loadgen_report"] == 1
        put = rep["ops"]["PUT"]
        for k in ("p50_ms", "p95_ms", "p99_ms", "p999_ms", "max_ms",
                  "ops_per_s", "bytes_per_s", "error_rate"):
            assert k in put, k
        assert rep["phases"]["single"]["op_sequence_sha256"] == "x"
        cmp = rep["compare"]
        assert cmp["ratio"] == pytest.approx(4000 / 1600, rel=1e-3)
        assert cmp["reproduced"] is True  # 2.5x >= 2.0

    def test_build_report_acked_object_loss_verdict(self):
        sc = parse_scenario(_doc(
            get_miss_is_loss=True,
            keyspace={"keys": 32, "prepopulate": 32},
            phases=[{"name": "p", "mix": {"GET": 1.0}, "ops": 10}],
        ))
        clean = _phase_result(
            "p", {"GET": {"ok": 10, "bytes": 100, "errors": {}}},
            {"GET": [0.01] * 10}, wall_s=1.0,
        )
        rep = build_report(sc, [clean], stage_breakdown={}, degrade={})
        assert rep["acked_object_loss"] == {"get_miss_count": 0, "ok": True}
        lossy = _phase_result(
            "p",
            {"GET": {"ok": 9, "bytes": 90,
                     "errors": {"4xx:NoSuchKey": 1, "5xx:SlowDownRead": 2}}},
            {"GET": [0.01] * 12}, wall_s=1.0,
        )
        rep = build_report(sc, [lossy], stage_breakdown={}, degrade={})
        # Only the miss is loss; the sheds are availability, not durability.
        assert rep["acked_object_loss"] == {"get_miss_count": 1, "ok": False}

    def test_build_report_compare_sweep_emits_one_verdict_per_rung(self):
        sc = parse_scenario(
            _doc(
                phases=[
                    {"name": "c1", "mix": {"PUT": 1.0}, "ops": 2},
                    {"name": "c4", "mix": {"PUT": 1.0}, "ops": 8},
                ],
                compare=[
                    {"a": "c4", "b": "c1", "op": "PUT",
                     "metric": "bytes_per_s", "min_ratio": 1.0},
                    {"a": "c4", "b": "c1", "op": "PUT",
                     "metric": "bytes_per_s", "min_ratio": 9.0},
                ],
            )
        )
        a = _phase_result(
            "c1", {"PUT": {"ok": 2, "bytes": 1000, "errors": {}}},
            {"PUT": [0.01] * 2}, wall_s=1.0,
        )
        b = _phase_result(
            "c4", {"PUT": {"ok": 8, "bytes": 3000, "errors": {}}},
            {"PUT": [0.01] * 8}, wall_s=1.0,
        )
        rep = build_report(sc, [a, b], stage_breakdown={}, degrade={})
        cmp = rep["compare"]
        assert isinstance(cmp, list) and len(cmp) == 2
        assert cmp[0]["reproduced"] is True   # 3x >= 1.0
        assert cmp[1]["reproduced"] is False  # 3x < 9.0
        assert cmp[1]["ratio"] == pytest.approx(3.0, rel=1e-3)

    def test_render_prometheus_is_lint_clean(self):
        sc = self._scenario()
        pr = _phase_result(
            "p0",
            {"GET": {"ok": 5, "bytes": 100, "errors": {"5xx:Err": 1}}},
            {"GET": [0.001] * 6},
        )
        rep = build_report(sc, [pr], stage_breakdown={}, degrade={})
        text = render_prometheus(rep)
        assert metrics_lint.validate_exposition(text) == []
        assert metrics_lint.lint_exposition(text) == []
        for series in (
            "minio_tpu_loadgen_ops_total",
            "minio_tpu_loadgen_latency_ms",
            "minio_tpu_loadgen_throughput_bytes_per_second",
            "minio_tpu_loadgen_slo_burn",
        ):
            assert series in text, series


class TestSmokeRun:
    """End-to-end: tiny scenario against a real 2-node in-process cluster.

    This is the tier-1 witness for the whole harness; the bigger canonical
    scenarios run through tools/loadgen.py out-of-band.
    """

    def test_smoke_scenario_end_to_end(self, tmp_path):
        from minio_tpu.loadgen.cluster import InProcessCluster
        from minio_tpu.loadgen.runner import ScenarioRunner
        from minio_tpu.loadgen.target import InProcessAdmin, S3Target

        sc = parse_scenario(
            {
                "name": "ci_smoke",
                "seed": 3,
                "bucket": "lgsmoke",
                "cluster": {"nodes": 2, "drives_per_node": 4},
                "keyspace": {"keys": 16, "prepopulate": 8, "prefix": "sm/",
                             "zipf_theta": 0.9},
                "sizes": {"kind": "fixed", "bytes": 2048},
                # In-process CI clusters shed under GET/DELETE races (503
                # SlowDownRead) -- the budget tolerates a few.
                "slo": {"GET": {"p99_ms": 30000, "error_budget": 0.25},
                        "PUT": {"p99_ms": 30000, "error_budget": 0.25}},
                "phases": [
                    {"name": "mixed",
                     "mix": {"GET": 0.5, "PUT": 0.3, "LIST": 0.1, "DELETE": 0.1},
                     "concurrency": 3, "ops": 30}
                ],
            }
        )
        cluster = InProcessCluster(str(tmp_path), n_nodes=2, drives_per_node=4)
        try:
            target = S3Target(cluster.urls, cluster.root_user, cluster.root_password)
            report = ScenarioRunner(sc, target, InProcessAdmin()).run()
        finally:
            cluster.stop()

        assert report["loadgen_report"] == 1
        assert report["phases"]["mixed"]["executed"] == 30
        assert set(report["ops"]) <= {"GET", "PUT", "LIST", "DELETE"}
        for row in report["ops"].values():
            assert "p99_ms" in row and "max_ms" in row
        # The cluster's own stage attribution rode along.
        assert "api" in report["stage_breakdown"]
        assert "sheds" in report["degrade"]
        # SLO section judged both declared ops.
        assert set(report["slo"]) == {"GET", "PUT"}
        # Exposition of a real run stays lint-clean.
        text = render_prometheus(report)
        assert metrics_lint.validate_exposition(text) == []
        assert metrics_lint.lint_exposition(text) == []
        # Replay identity: regenerating the phase reproduces the hash.
        regen = op_sequence_hash(generate_ops(sc, sc.phases[0], 30))
        assert report["phases"]["mixed"]["op_sequence_sha256"] == regen
