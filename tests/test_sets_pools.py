"""Erasure sets + server pools tests: routing, listing, multi-set namespaces.

Mirrors cmd/erasure-sets_test.go (distribution stability) and the listing
behavior exercised by cmd/bucket-listobjects-handlers tests.
"""

import os

import pytest

from minio_tpu.object.pools import ServerPools
from minio_tpu.object.sets import ErasureSets
from minio_tpu.object.types import PutObjectOptions
from minio_tpu.storage import format as fmt
from minio_tpu.storage.local import LocalDrive
from minio_tpu.utils import errors


def make_pools(tmp_path, n_disks=8, set_drive_count=4, n_pools=1) -> ServerPools:
    pools = []
    for pi in range(n_pools):
        drives = []
        formats = fmt.init_format(n_disks // set_drive_count, set_drive_count)
        for i in range(n_disks):
            root = str(tmp_path / f"pool{pi}" / f"disk{i}")
            os.makedirs(root, exist_ok=True)
            formats[i].save(root)
            drives.append(LocalDrive(root))
        pools.append(
            ErasureSets.from_drives(drives, formats[0], pool_index=pi)
        )
    return ServerPools(pools)


@pytest.fixture
def layer(tmp_path):
    lp = make_pools(tmp_path, n_disks=8, set_drive_count=4)
    lp.make_bucket("bucket")
    return lp


class TestSets:
    def test_routing_stable_and_spread(self, layer):
        sets = layer.pools[0]
        assert len(sets.sets) == 2
        idx = {name: sets.get_set_index(name) for name in ("a", "b", "c", "obj-7", "x/y/z")}
        for name, i in idx.items():
            assert sets.get_set_index(name) == i  # deterministic
            assert 0 <= i < 2

    def test_objects_across_sets(self, layer):
        for i in range(20):
            layer.put_object("bucket", f"obj-{i}", f"data-{i}".encode())
        for i in range(20):
            _, got = layer.get_object("bucket", f"obj-{i}")
            assert got == f"data-{i}".encode()
        # Objects really landed on different sets.
        sets = layer.pools[0]
        indexes = {sets.get_set_index(f"obj-{i}") for i in range(20)}
        assert indexes == {0, 1}

    def test_from_drives_arrangement(self, tmp_path):
        formats = fmt.init_format(2, 4)
        drives = []
        for i, f in enumerate(formats):
            root = str(tmp_path / f"d{i}")
            os.makedirs(root)
            f.save(root)
            drives.append(LocalDrive(root))
        # Shuffle drive order; from_drives must restore format positions.
        shuffled = drives[::-1]
        sets = ErasureSets.from_drives(shuffled, formats[0])
        for s in range(2):
            for i in range(4):
                d = sets.sets[s].disks[i]
                assert d is not None
                assert d.disk_id() == formats[0].sets[s][i]


class TestListing:
    def test_flat_listing(self, layer):
        names = ["a.txt", "b/one", "b/two", "c.txt", "d/e/deep"]
        for n in names:
            layer.put_object("bucket", n, b"x")
        res = layer.list_objects("bucket")
        assert [o.name for o in res.objects] == sorted(names)
        assert not res.is_truncated

    def test_delimiter_listing(self, layer):
        for n in ["a.txt", "b/one", "b/two", "c/three", "d.txt"]:
            layer.put_object("bucket", n, b"x")
        res = layer.list_objects("bucket", delimiter="/")
        assert [o.name for o in res.objects] == ["a.txt", "d.txt"]
        assert res.prefixes == ["b/", "c/"]

    def test_prefix_listing(self, layer):
        for n in ["logs/2024/a", "logs/2024/b", "logs/2025/c", "data/x"]:
            layer.put_object("bucket", n, b"x")
        res = layer.list_objects("bucket", prefix="logs/")
        assert [o.name for o in res.objects] == ["logs/2024/a", "logs/2024/b", "logs/2025/c"]
        res2 = layer.list_objects("bucket", prefix="logs/", delimiter="/")
        assert res2.prefixes == ["logs/2024/", "logs/2025/"]

    def test_marker_pagination(self, layer):
        names = [f"obj-{i:03d}" for i in range(10)]
        for n in names:
            layer.put_object("bucket", n, b"x")
        page1 = layer.list_objects("bucket", max_keys=4)
        assert len(page1.objects) == 4
        assert page1.is_truncated
        page2 = layer.list_objects("bucket", marker=page1.objects[-1].name, max_keys=100)
        assert [o.name for o in page2.objects] == names[4:]
        assert not page2.is_truncated

    def test_deleted_objects_not_listed(self, layer):
        layer.put_object("bucket", "keep", b"x")
        layer.put_object("bucket", "gone", b"x")
        layer.delete_object("bucket", "gone")
        res = layer.list_objects("bucket")
        assert [o.name for o in res.objects] == ["keep"]

    def test_list_versions(self, layer):
        opts = PutObjectOptions(versioned=True)
        v1 = layer.put_object("bucket", "obj", b"one", opts)
        v2 = layer.put_object("bucket", "obj", b"two", opts)
        res = layer.list_object_versions("bucket")
        assert len(res.objects) == 2
        assert res.objects[0].version_id == v2.version_id
        assert res.objects[0].is_latest
        assert res.objects[1].version_id == v1.version_id

    def test_missing_bucket_listing(self, layer):
        with pytest.raises(errors.BucketNotFound):
            layer.list_objects("nope")


class TestPools:
    def test_multi_pool_namespace(self, tmp_path):
        lp = make_pools(tmp_path, n_disks=4, set_drive_count=4, n_pools=2)
        lp.make_bucket("bkt")
        lp.put_object("bkt", "x", b"data-x")
        _, got = lp.get_object("bkt", "x")
        assert got == b"data-x"
        res = lp.list_objects("bkt")
        assert [o.name for o in res.objects] == ["x"]
        lp.delete_object("bkt", "x")
        with pytest.raises(errors.ObjectNotFound):
            lp.get_object("bkt", "x")

    def test_bucket_name_validation(self, layer):
        for bad in ["ab", "-bad", "BAD", "a" * 64, ".start"]:
            with pytest.raises(errors.BucketNameInvalid):
                layer.make_bucket(bad)

    def test_object_name_validation(self, layer):
        for bad in ["", "/lead", "a/../b", "a\\b"]:
            with pytest.raises(errors.ObjectNameInvalid):
                layer.put_object("bucket", bad, b"x")

    def test_bulk_delete(self, layer):
        for i in range(5):
            layer.put_object("bucket", f"o{i}", b"x")
        results = layer.delete_objects("bucket", [(f"o{i}", "") for i in range(5)])
        assert all(e is None for _, e in results)
        assert layer.list_objects("bucket").objects == []

    def test_delete_nonempty_refused(self, layer):
        layer.put_object("bucket", "obj", b"x")
        with pytest.raises(errors.BucketNotEmpty):
            layer.delete_bucket("bucket")


class TestMetacache:
    """Persistent listing cache (VERDICT r3 #8): paging must not re-walk
    every drive per page; writes invalidate; cold processes can reuse a
    fresh persisted image (cmd/metacache-server-pool.go:59 semantics)."""

    def test_paging_walks_once(self, layer):
        sets = layer.pools[0]
        for i in range(50):
            layer.put_object("bucket", f"pg/obj-{i:04d}", b"x")
        sets.metacache.walks = 0
        marker = ""
        seen = []
        while True:
            res = sets.list_objects("bucket", prefix="pg/", marker=marker, max_keys=7)
            seen.extend(o.name for o in res.objects)
            if not res.is_truncated:
                break
            marker = res.next_marker
        assert seen == [f"pg/obj-{i:04d}" for i in range(50)]
        assert sets.metacache.walks == 1
        assert sets.metacache.hits >= 7

    def test_write_invalidates(self, layer):
        sets = layer.pools[0]
        layer.put_object("bucket", "inv/a", b"x")
        assert [o.name for o in sets.list_objects("bucket", prefix="inv/").objects] == ["inv/a"]
        layer.put_object("bucket", "inv/b", b"x")
        names = [o.name for o in sets.list_objects("bucket", prefix="inv/").objects]
        assert names == ["inv/a", "inv/b"]
        layer.delete_object("bucket", "inv/a")
        names = [o.name for o in sets.list_objects("bucket", prefix="inv/").objects]
        assert names == ["inv/b"]

    def test_persisted_image_reused_cold(self, tmp_path):
        lp = make_pools(tmp_path, n_disks=8, set_drive_count=4)
        lp.make_bucket("bucket")
        for i in range(10):
            lp.put_object("bucket", f"cold/obj-{i}", b"x")
        sets = lp.pools[0]
        sets.list_objects("bucket", prefix="cold/")  # fills + persists

        # A "restarted" namespace over the same drives: fresh manager state.
        from minio_tpu.object.sets import ErasureSets
        from minio_tpu.storage.local import LocalDrive

        drives = [LocalDrive(d.root) for d in sets.disks if d is not None]
        import minio_tpu.storage.format as fmtmod

        fmt2 = fmtmod.DriveFormat.load(drives[0].root)
        cold = ErasureSets.from_drives(drives, fmt2)
        res = cold.list_objects("bucket", prefix="cold/")
        assert len(res.objects) == 10
        assert cold.metacache.walks == 0  # served from the persisted image
        assert cold.metacache.hits == 1


class TestVersionPaging:
    def test_version_listing_pages_without_loss_or_dupes(self, layer):
        sets = layer.pools[0]
        from minio_tpu.object.types import PutObjectOptions

        # 4 objects x 3 versions = 12 version entries.
        for i in range(4):
            for v in range(3):
                layer.put_object(
                    "bucket", f"vp/obj-{i}", f"v{v}".encode(),
                    PutObjectOptions(versioned=True),
                )
        seen: list[tuple[str, str]] = []
        km, vm = "", ""
        for _ in range(20):
            res = sets.list_object_versions(
                "bucket", prefix="vp/", key_marker=km, version_marker=vm, max_keys=5
            )
            seen.extend((o.name, o.version_id) for o in res.objects)
            if not res.is_truncated:
                break
            km, vm = res.next_key_marker, res.next_version_marker
        assert len(seen) == 12
        assert len(set(seen)) == 12  # no duplicates
        assert sorted({n for n, _ in seen}) == [f"vp/obj-{i}" for i in range(4)]
        # Newest-first within each key.
        full = sets.list_object_versions("bucket", prefix="vp/", max_keys=1000)
        assert [(o.name, o.version_id) for o in full.objects] == seen
