"""Batching device codec tests: bit-identical with host codec, under
concurrency."""

import threading

import numpy as np
import pytest

from minio_tpu.object.codec import HostCodec
from minio_tpu.parallel.batching import BatchingDeviceCodec

# Stressed under adversarial thread scheduling by tools/race_gate.py.
pytestmark = pytest.mark.race


BLOCK = 1 << 20


@pytest.fixture(scope="module")
def batcher():
    b = BatchingDeviceCodec(block_size=BLOCK, max_batch=8, batch_timeout_s=0.002)
    yield b
    b.close()


def test_single_block_matches_host(batcher):
    rng = np.random.default_rng(0)
    block = rng.integers(0, 256, BLOCK).astype(np.uint8).tobytes()
    dev = batcher.encode([block], 4, 2)
    host = HostCodec().encode([block], 4, 2)
    assert dev[0][0] == host[0][0]
    assert dev[0][1] == host[0][1]


def test_partial_block_falls_back_to_host(batcher):
    rng = np.random.default_rng(1)
    block = rng.integers(0, 256, 12345).astype(np.uint8).tobytes()
    dev = batcher.encode([block], 4, 2)
    host = HostCodec().encode([block], 4, 2)
    assert dev[0][0] == host[0][0]


def test_concurrent_requests_batched(batcher):
    rng = np.random.default_rng(2)
    blocks = [rng.integers(0, 256, BLOCK).astype(np.uint8).tobytes() for _ in range(6)]
    host = HostCodec().encode(blocks, 4, 2)
    results = [None] * 6

    def work(i):
        results[i] = batcher.encode([blocks[i]], 4, 2)[0]

    threads = [threading.Thread(target=work, args=(i,)) for i in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30)
    for i in range(6):
        assert results[i] is not None, i
        assert results[i][0] == host[i][0], i
        assert results[i][1] == host[i][1], i


def test_mixed_sizes_one_call(batcher):
    rng = np.random.default_rng(3)
    blocks = [
        rng.integers(0, 256, BLOCK).astype(np.uint8).tobytes(),
        rng.integers(0, 256, 777).astype(np.uint8).tobytes(),
        rng.integers(0, 256, BLOCK).astype(np.uint8).tobytes(),
    ]
    dev = batcher.encode(blocks, 4, 2)
    host = HostCodec().encode(blocks, 4, 2)
    for i in range(3):
        assert dev[i][0] == host[i][0], i
        assert dev[i][1] == host[i][1], i


class TestDeviceReconstructServing:
    """The decode/heal serving path runs the batched device pipeline
    (VERDICT r3 #3): degraded GETs and heal must advance the reconstruct
    counters, not silently punt to the host codec."""

    def _harness(self, tmp_path):
        from tests.harness import ErasureHarness

        batcher = BatchingDeviceCodec(block_size=BLOCK, max_batch=8, batch_timeout_s=0.002)
        h = ErasureHarness(tmp_path, n_disks=16, codec=batcher)
        h.layer.make_bucket("b")
        return h, batcher

    def _data_row_drives(self, layer, bucket, name, n, k=12):
        """Indices of n drives whose shard row is a data row."""
        fi, _, _ = layer._read_quorum_fi(bucket, name, "")
        out = [i for i, rot in enumerate(fi.erasure.distribution) if rot - 1 < k]
        return out[:n]

    def test_degraded_get_runs_device_batch(self, tmp_path):
        h, batcher = self._harness(tmp_path)
        try:
            rng = np.random.default_rng(10)
            data = rng.integers(0, 256, 3 * BLOCK).astype(np.uint8).tobytes()
            h.layer.put_object("b", "obj", data)
            h.take_offline(*self._data_row_drives(h.layer, "b", "obj", 2))
            before = batcher.blocks_reconstructed
            _, got = h.layer.get_object("b", "obj")
            assert got == data
            assert batcher.blocks_reconstructed >= before + 3  # all 3 full blocks
            assert batcher.recon_batches_run >= 1
        finally:
            batcher.close()

    def test_heal_runs_device_batch(self, tmp_path):
        h, batcher = self._harness(tmp_path)
        try:
            rng = np.random.default_rng(11)
            data = rng.integers(0, 256, 3 * BLOCK).astype(np.uint8).tobytes()
            h.layer.put_object("b", "obj", data)
            deleted = 0
            for i in self._data_row_drives(h.layer, "b", "obj", 3):
                assert h.delete_shard(i, "b", "obj")
                deleted += 1
            assert deleted == 3
            before = batcher.blocks_reconstructed
            h.layer.heal_object("b", "obj")
            assert batcher.blocks_reconstructed >= before + 3
            _, got = h.layer.get_object("b", "obj")
            assert got == data
        finally:
            batcher.close()

    def test_degraded_tail_block_host_fallback_is_exact(self, tmp_path):
        """Tail blocks (irregular window) must still read back correctly."""
        h, batcher = self._harness(tmp_path)
        try:
            rng = np.random.default_rng(12)
            data = rng.integers(0, 256, 2 * BLOCK + 12345).astype(np.uint8).tobytes()
            h.layer.put_object("b", "obj", data)
            h.take_offline(*self._data_row_drives(h.layer, "b", "obj", 2))
            _, got = h.layer.get_object("b", "obj")
            assert got == data
        finally:
            batcher.close()


class TestSmallObjectBatching:
    """Cross-request coalescing of sub-block objects (MTPU_BATCH_WAIT_US):
    many concurrent small PUTs ride ONE device dispatch, bit-identical to
    the host codec."""

    def test_small_objects_coalesce_into_one_batch(self, monkeypatch):
        monkeypatch.setenv("MTPU_BATCH_WAIT_US", "20000")
        b = BatchingDeviceCodec(block_size=BLOCK, max_batch=8, batch_timeout_s=0.002)
        try:
            rng = np.random.default_rng(30)
            sizes = [5000, 9000, 40000, 123457]
            blocks = [rng.integers(0, 256, n).astype(np.uint8).tobytes() for n in sizes]
            host = HostCodec().encode(blocks, 4, 2)
            results = [None] * len(blocks)

            def work(i):
                results[i] = b.encode([blocks[i]], 4, 2)[0]

            threads = [threading.Thread(target=work, args=(i,)) for i in range(len(blocks))]
            for t in threads:
                t.start()
            for t in threads:
                t.join(30)
            for i in range(len(blocks)):
                assert results[i] is not None, i
                assert results[i][0] == host[i][0], i
                assert results[i][1] == host[i][1], i
            st = b.stats()
            assert st["small_blocks_encoded"] == len(blocks)
            # The 20 ms window must have coalesced 4 concurrent requests
            # into fewer dispatches than requests.
            assert 1 <= st["small_batches_run"] < len(blocks)
        finally:
            b.close()

    def test_small_path_disabled_when_wait_unset(self, monkeypatch):
        monkeypatch.delenv("MTPU_BATCH_WAIT_US", raising=False)
        b = BatchingDeviceCodec(block_size=BLOCK, max_batch=8, batch_timeout_s=0.002)
        try:
            assert b.small_wait_s is None or b.small_wait_s >= 0  # default on (500us)
        finally:
            b.close()
        monkeypatch.setenv("MTPU_BATCH_WAIT_US", "off")
        b2 = BatchingDeviceCodec(block_size=BLOCK, max_batch=8, batch_timeout_s=0.002)
        try:
            assert b2.small_wait_s is None
            rng = np.random.default_rng(31)
            block = rng.integers(0, 256, 12345).astype(np.uint8).tobytes()
            dev = b2.encode([block], 4, 2)
            host = HostCodec().encode([block], 4, 2)
            assert dev[0][0] == host[0][0]
            assert b2.stats()["small_blocks_encoded"] == 0  # host path served
        finally:
            b2.close()

    def test_tiny_objects_stay_on_host(self, monkeypatch):
        # Below _SMALL_MIN a device round-trip costs more than it saves.
        monkeypatch.setenv("MTPU_BATCH_WAIT_US", "1000")
        b = BatchingDeviceCodec(block_size=BLOCK, max_batch=8, batch_timeout_s=0.002)
        try:
            block = b"\x42" * 512
            dev = b.encode([block], 4, 2)
            host = HostCodec().encode([block], 4, 2)
            assert dev[0][0] == host[0][0]
            assert b.stats()["small_blocks_encoded"] == 0
        finally:
            b.close()


def test_mesh_and_double_buffer_counters():
    """Full-block batches at the production geometry report mesh fan-out and
    per-chip accounting (12+4 tiles the virtual 8-device mesh; 4+2 does not
    and runs single-device)."""
    b = BatchingDeviceCodec(block_size=BLOCK, max_batch=8, batch_timeout_s=0.002)
    try:
        rng = np.random.default_rng(40)
        blocks = [rng.integers(0, 256, BLOCK).astype(np.uint8).tobytes() for _ in range(4)]
        host = HostCodec().encode(blocks, 12, 4)
        for _ in range(3):
            dev = b.encode(blocks, 12, 4)
        for i in range(4):
            assert dev[i][0] == host[i][0], i
            assert dev[i][1] == host[i][1], i
        st = b.stats()
        assert st["mesh_devices"] >= 1
        if st["mesh_devices"] > 1:  # conftest forces 8 virtual devices
            # chip_blocks has one entry per data-parallel group.
            assert 1 <= len(st["chip_blocks"]) <= st["mesh_devices"]
            assert sum(st["chip_blocks"]) == st["blocks_encoded"]
        assert st["double_buffered_batches"] >= 0
    finally:
        b.close()


def test_scanner_deep_scan_runs_device_verify(tmp_path):
    """The scanner's sampled deep-check verifies bitrot through the batched
    device pipeline (VERDICT r3 #9): verify counters must advance."""
    from tests.harness import ErasureHarness
    from tests.test_control import _PoolsShim
    from minio_tpu.control.scanner import DataScanner

    batcher = BatchingDeviceCodec(block_size=BLOCK, max_batch=8, batch_timeout_s=0.002)
    try:
        h = ErasureHarness(tmp_path, n_disks=16, codec=batcher)
        h.layer.make_bucket("scanb")
        rng = np.random.default_rng(21)
        h.layer.put_object(
            "scanb", "obj", rng.integers(0, 256, 2 * BLOCK).astype(np.uint8).tobytes()
        )
        sc = DataScanner(_PoolsShim(h), heal_sample=1)  # deep-check everything
        sc.scan_cycle()
        assert batcher.verify_batches_run >= 1
        assert batcher.digests_verified >= 16  # at least one full row set
    finally:
        batcher.close()
