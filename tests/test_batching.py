"""Batching device codec tests: bit-identical with host codec, under
concurrency."""

import threading

import numpy as np
import pytest

from minio_tpu.object.codec import HostCodec
from minio_tpu.parallel.batching import BatchingDeviceCodec

BLOCK = 1 << 20


@pytest.fixture(scope="module")
def batcher():
    b = BatchingDeviceCodec(block_size=BLOCK, max_batch=8, batch_timeout_s=0.002)
    yield b
    b.close()


def test_single_block_matches_host(batcher):
    rng = np.random.default_rng(0)
    block = rng.integers(0, 256, BLOCK).astype(np.uint8).tobytes()
    dev = batcher.encode([block], 4, 2)
    host = HostCodec().encode([block], 4, 2)
    assert dev[0][0] == host[0][0]
    assert dev[0][1] == host[0][1]


def test_partial_block_falls_back_to_host(batcher):
    rng = np.random.default_rng(1)
    block = rng.integers(0, 256, 12345).astype(np.uint8).tobytes()
    dev = batcher.encode([block], 4, 2)
    host = HostCodec().encode([block], 4, 2)
    assert dev[0][0] == host[0][0]


def test_concurrent_requests_batched(batcher):
    rng = np.random.default_rng(2)
    blocks = [rng.integers(0, 256, BLOCK).astype(np.uint8).tobytes() for _ in range(6)]
    host = HostCodec().encode(blocks, 4, 2)
    results = [None] * 6

    def work(i):
        results[i] = batcher.encode([blocks[i]], 4, 2)[0]

    threads = [threading.Thread(target=work, args=(i,)) for i in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30)
    for i in range(6):
        assert results[i] is not None, i
        assert results[i][0] == host[i][0], i
        assert results[i][1] == host[i][1], i


def test_mixed_sizes_one_call(batcher):
    rng = np.random.default_rng(3)
    blocks = [
        rng.integers(0, 256, BLOCK).astype(np.uint8).tobytes(),
        rng.integers(0, 256, 777).astype(np.uint8).tobytes(),
        rng.integers(0, 256, BLOCK).astype(np.uint8).tobytes(),
    ]
    dev = batcher.encode(blocks, 4, 2)
    host = HostCodec().encode(blocks, 4, 2)
    for i in range(3):
        assert dev[i][0] == host[i][0], i
        assert dev[i][1] == host[i][1], i
