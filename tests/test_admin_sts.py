"""Admin API + STS tests against a full single-node server."""

import json
import threading
import time
import xml.etree.ElementTree as ET
from types import SimpleNamespace

import pytest

from minio_tpu.api.server import ThreadedServer
from minio_tpu.dist.node import Node
from tests.s3client import S3TestClient
from tests.test_dist import _free_port

ROOT = "adminroot"
SECRET = "admin-secret-key"
ADMIN = "/mtpu/admin/v1"


@pytest.fixture(scope="module")
def srv(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("adminsrv")
    endpoints = [str(tmp / f"d{i}") for i in range(4)]
    node = Node(endpoints, root_user=ROOT, root_password=SECRET)
    port = _free_port()
    ts = ThreadedServer(SimpleNamespace(app=node.make_app()), port=port)
    ts.start()
    node.build()
    url = f"http://127.0.0.1:{port}"
    client = S3TestClient(url, ROOT, SECRET)
    yield {"client": client, "node": node, "url": url}
    ts.stop()


class TestAdmin:
    def test_group_management_loop(self, srv):
        """mc admin group add/info/disable/policy/remove over the REST
        surface (cmd/admin-handlers-users.go UpdateGroupMembers etc.),
        with the membership actually gating S3 access."""
        import json as json_mod

        c = srv["client"]
        assert c.request(
            "POST", f"{ADMIN}/users",
            body=json_mod.dumps({"accessKey": "grpuser", "secretKey": "grpsecret1234"}).encode(),
        ).status_code == 200
        r = c.request("PUT", f"{ADMIN}/groups/team",
                      body=json_mod.dumps({"members": ["grpuser"]}).encode())
        assert r.status_code == 200, r.text
        r = c.request("PUT", f"{ADMIN}/groups/team/policy",
                      body=json_mod.dumps({"policies": ["readwrite"]}).encode())
        assert r.status_code == 200, r.text
        info = c.request("GET", f"{ADMIN}/groups/team").json()
        assert info["members"] == ["grpuser"] and info["policies"] == ["readwrite"]
        assert "team" in c.request("GET", f"{ADMIN}/groups").json()["groups"]
        # Group policy actually grants S3 access to the member.
        gu = S3TestClient(srv["url"], "grpuser", "grpsecret1234")
        assert gu.make_bucket("grpbkt").status_code == 200
        # Disable -> access revoked; re-enable -> back.
        c.request("PUT", f"{ADMIN}/groups/team/status",
                  body=json_mod.dumps({"status": "disabled"}).encode())
        assert gu.request("PUT", "/grpbkt/x.txt", body=b"x").status_code == 403
        c.request("PUT", f"{ADMIN}/groups/team/status",
                  body=json_mod.dumps({"status": "enabled"}).encode())
        assert gu.request("PUT", "/grpbkt/x.txt", body=b"x").status_code == 200
        # Remove member then the group; non-empty delete refuses first.
        assert c.request("DELETE", f"{ADMIN}/groups/team").status_code == 400
        c.request("PUT", f"{ADMIN}/groups/team",
                  body=json_mod.dumps({"members": ["grpuser"], "isRemove": True}).encode())
        assert gu.request("PUT", "/grpbkt/y.txt", body=b"y").status_code == 403
        assert c.request("DELETE", f"{ADMIN}/groups/team").status_code == 200
        # cleanup
        srv["node"].pools.delete_object("grpbkt", "x.txt")
        c.request("DELETE", f"{ADMIN}/users/grpuser")

    def test_info(self, srv):
        r = srv["client"].request("GET", f"{ADMIN}/info")
        assert r.status_code == 200, r.text
        info = r.json()
        assert info["drivesOnline"] == 4
        assert info["mode"] == "online"

    def test_config_roundtrip(self, srv):
        c = srv["client"]
        r = c.request("GET", f"{ADMIN}/config")
        assert r.json()["scanner"]["delay"] == "10"
        r = c.request(
            "PUT",
            f"{ADMIN}/config",
            body=json.dumps({"subsys": "scanner", "key": "delay", "value": "30"}).encode(),
        )
        assert r.json()["dynamic"] is True
        assert c.request("GET", f"{ADMIN}/config").json()["scanner"]["delay"] == "30"

    def test_user_management(self, srv):
        c = srv["client"]
        r = c.request(
            "POST",
            f"{ADMIN}/users",
            body=json.dumps(
                {"accessKey": "alice", "secretKey": "alice-secret-12", "policies": ["readwrite"]}
            ).encode(),
        )
        assert r.status_code == 200, r.text
        users = c.request("GET", f"{ADMIN}/users").json()
        assert users["alice"]["policies"] == ["readwrite"]
        # Alice can use S3 but not admin.
        alice = S3TestClient(srv["url"], "alice", "alice-secret-12")
        assert alice.make_bucket("alicebkt").status_code == 200
        assert alice.request("GET", f"{ADMIN}/info").status_code == 403
        # Disable and remove.
        c.request("PUT", f"{ADMIN}/users/alice/status", body=b'{"status": "disabled"}')
        assert alice.request("GET", "/").status_code == 403
        assert c.request("DELETE", f"{ADMIN}/users/alice").status_code == 200

    def test_policies_crud(self, srv):
        c = srv["client"]
        doc = {
            "Version": "2012-10-17",
            "Statement": [{"Effect": "Allow", "Action": ["s3:GetObject"], "Resource": ["arn:aws:s3:::pub/*"]}],
        }
        assert c.request("PUT", f"{ADMIN}/policies/getonly", body=json.dumps(doc).encode()).status_code == 200
        pols = c.request("GET", f"{ADMIN}/policies").json()
        assert "getonly" in pols and "readonly" in pols
        assert c.request("DELETE", f"{ADMIN}/policies/getonly").status_code == 200

    def test_service_account(self, srv):
        c = srv["client"]
        r = c.request("POST", f"{ADMIN}/service-accounts", body=b"{}")
        sa = r.json()
        sa_client = S3TestClient(srv["url"], sa["accessKey"], sa["secretKey"])
        assert sa_client.request("GET", "/").status_code == 200  # inherits root

    def test_heal_sequence_api(self, srv):
        c = srv["client"]
        c.make_bucket("healapib")
        c.put_object("healapib", "obj", b"y" * 200_000)
        r = c.request("POST", f"{ADMIN}/heal", body=b"{}")
        seq = r.json()["healSequence"]
        deadline = time.time() + 10
        while time.time() < deadline:
            st = c.request("GET", f"{ADMIN}/heal/{seq}").json()
            if not st["running"]:
                break
            time.sleep(0.05)
        assert st["scanned"] >= 1

    def test_speedtest(self, srv):
        r = srv["client"].request("POST", f"{ADMIN}/speedtest", body=b'{"size": 8192, "count": 2}')
        res = r.json()
        assert res["putSpeedBytesPerSec"] > 0

    def test_toplocks_and_service(self, srv):
        c = srv["client"]
        assert c.request("GET", f"{ADMIN}/toplocks").status_code == 200
        r = c.request("POST", f"{ADMIN}/service", body=b'{"action": "restart"}')
        assert r.json()["ok"] is True
        assert c.request("POST", f"{ADMIN}/service", body=b'{"action": "bogus"}').status_code == 400

    def test_profiling(self, srv):
        c = srv["client"]
        assert c.request("POST", f"{ADMIN}/profile/start").status_code == 200
        c.request("GET", "/")  # some work
        r = c.request("POST", f"{ADMIN}/profile/stop")
        assert r.status_code == 200
        assert "cumulative" in r.text

    def test_metrics_endpoints(self, srv):
        c = srv["client"]
        r = c.request("GET", f"{ADMIN}/metrics")
        assert "minio_tpu_uptime_seconds" in r.text
        # Public prometheus path (unauthenticated scrape).
        import requests

        r = requests.get(srv["url"] + "/minio/v2/metrics/cluster")
        assert r.status_code == 200
        # Cluster view stamps every sample with the reporting node.
        import re

        assert re.search(
            r'minio_tpu_cluster_drives_online_total\{server="[^"]*"\} 4\b', r.text
        ), r.text[:500]

    def test_trace_stream(self, srv):
        c = srv["client"]
        results = []

        def consume():
            import requests

            from minio_tpu.api.auth import sign_request

            headers = sign_request(
                c.creds, "GET", f"{ADMIN}/trace", [], {"host": c.host}, b""
            )
            headers.pop("host")
            with requests.get(
                srv["url"] + f"{ADMIN}/trace", headers=headers, stream=True, timeout=10
            ) as r:
                for line in r.iter_lines():
                    if line:
                        results.append(json.loads(line))
                        # Storage traces interleave with HTTP ones now that
                        # drives are metered; read until an http trace shows.
                        if results[-1]["type"] == "http" or len(results) > 50:
                            break

        t = threading.Thread(target=consume, daemon=True)
        t.start()
        time.sleep(0.5)
        for _ in range(5):
            c.request("GET", "/")
            time.sleep(0.1)
        t.join(5)
        assert any(item["type"] == "http" for item in results), results[:3]


class TestSTS:
    def test_assume_role(self, srv):
        c = srv["client"]
        c.request(
            "POST",
            f"{ADMIN}/users",
            body=json.dumps(
                {"accessKey": "bob", "secretKey": "bob-secret-123", "policies": ["readonly"]}
            ).encode(),
        )
        bob = S3TestClient(srv["url"], "bob", "bob-secret-123")
        r = bob.request(
            "POST",
            "/",
            body=b"Action=AssumeRole&Version=2011-06-15&DurationSeconds=900",
        )
        assert r.status_code == 200, r.text
        root = ET.fromstring(r.content)
        ns = "{https://sts.amazonaws.com/doc/2011-06-15/}"
        ak = root.find(f".//{ns}AccessKeyId").text
        sk = root.find(f".//{ns}SecretAccessKey").text
        temp = S3TestClient(srv["url"], ak, sk)
        # Inherits bob's readonly: can read objects, cannot create buckets
        # (readonly does not grant ListAllMyBuckets, as in the reference).
        c.make_bucket("stsread")
        c.put_object("stsread", "k", b"readonly-data")
        assert temp.get_object("stsread", "k").content == b"readonly-data"
        assert temp.make_bucket("stsbkt").status_code == 403

    def test_assume_role_with_session_policy(self, srv):
        c = srv["client"]
        c.make_bucket("stsdata")
        c.put_object("stsdata", "k", b"v")
        import urllib.parse

        policy = json.dumps(
            {
                "Version": "2012-10-17",
                "Statement": [
                    {"Effect": "Allow", "Action": ["s3:GetObject"], "Resource": ["arn:aws:s3:::stsdata/*"]}
                ],
            }
        )
        r = c.request(
            "POST",
            "/",
            body=f"Action=AssumeRole&Version=2011-06-15&Policy={urllib.parse.quote(policy)}".encode(),
        )
        assert r.status_code == 200, r.text
        ns = "{https://sts.amazonaws.com/doc/2011-06-15/}"
        root = ET.fromstring(r.content)
        temp = S3TestClient(
            srv["url"],
            root.find(f".//{ns}AccessKeyId").text,
            root.find(f".//{ns}SecretAccessKey").text,
        )
        assert temp.get_object("stsdata", "k").content == b"v"
        # Session policy narrows root: no bucket creation.
        assert temp.make_bucket("other-bkt").status_code == 403

    def test_speedtest_autotune(self, srv):
        r = srv["client"].request(
            "POST", f"{ADMIN}/speedtest", body=b'{"size": 4096, "autotune": true}'
        )
        assert r.status_code == 200, r.text
        doc = r.json()
        assert doc["putSpeedBytesPerSec"] > 0 and doc["getSpeedBytesPerSec"] > 0
        assert doc["concurrency"] >= 4
        assert len(doc["ramp"]) >= 1


class TestBucketQuota:
    """Hard bucket quota: admin config + PUT-time enforcement
    (cmd/admin-bucket-handlers.go:43,83 + cmd/bucket-quota.go:112)."""

    def test_quota_roundtrip_and_enforcement(self, srv):
        c = srv["client"]
        node = srv["node"]
        assert c.make_bucket("quotabkt").status_code == 200
        # No quota yet.
        r = c.request("GET", f"{ADMIN}/quota", query=[("bucket", "quotabkt")])
        assert r.status_code == 200 and r.json()["quota"] == 0
        # Fill ~64 KiB, then scan so the usage tree sees it.
        assert c.put_object("quotabkt", "seed", b"x" * 65536).status_code == 200
        node.scanner.scan_cycle()
        # Set a quota just above current usage.
        r = c.request(
            "PUT",
            f"{ADMIN}/quota",
            query=[("bucket", "quotabkt")],
            body=json.dumps({"quota": 70000, "quotatype": "hard"}).encode(),
        )
        assert r.status_code == 200, r.text
        r = c.request("GET", f"{ADMIN}/quota", query=[("bucket", "quotabkt")])
        assert r.json() == {"quota": 70000, "quotatype": "hard"}
        # A put that would cross the quota is rejected with the admin code.
        r = c.put_object("quotabkt", "big", b"y" * 8192)
        assert r.status_code == 400 and b"XMinioAdminBucketQuotaExceeded" in r.content
        # A put that fits still lands.
        assert c.put_object("quotabkt", "small", b"z" * 1024).status_code == 200
        # Lifting the quota unblocks writes.
        c.request(
            "PUT",
            f"{ADMIN}/quota",
            query=[("bucket", "quotabkt")],
            body=json.dumps({"quota": 0}).encode(),
        )
        assert c.put_object("quotabkt", "big2", b"y" * 8192).status_code == 200
        # FIFO quota type is refused (deprecated upstream).
        r = c.request(
            "PUT",
            f"{ADMIN}/quota",
            query=[("bucket", "quotabkt")],
            body=json.dumps({"quota": 1000, "quotatype": "fifo"}).encode(),
        )
        assert r.status_code == 400


class TestKmsAndInspect:
    """KMS status roundtrip checks + raw-file inspect zip
    (cmd/admin-handlers.go:1267,1305,2198)."""

    def test_kms_status(self, srv):
        pytest.importorskip(
            "cryptography", reason="node boots KMS-less without the crypto backend"
        )
        c = srv["client"]
        r = c.request("GET", f"{ADMIN}/kms/status")
        assert r.status_code == 200, r.text
        st = r.json()
        assert st["key-check"]["encryption-err"] == ""
        r = c.request("GET", f"{ADMIN}/kms/key/status", query=[("key-id", "default-key")])
        assert r.status_code == 200 and r.json()["encryption-err"] == ""

    def test_inspect_xlmeta_from_all_drives(self, srv):
        import io
        import zipfile

        c = srv["client"]
        assert c.make_bucket("insp").status_code in (200, 409)
        assert c.put_object("insp", "obj", b"inspect-me" * 100).status_code == 200
        r = c.request(
            "GET",
            f"{ADMIN}/inspect",
            query=[("volume", "insp"), ("file", "obj/xl.meta")],
        )
        assert r.status_code == 200, r.text
        z = zipfile.ZipFile(io.BytesIO(r.content))
        names = z.namelist()
        # Every online drive holds a copy of the object's xl.meta.
        assert len(names) == 4 and all(n.endswith("obj/xl.meta") for n in names)
        assert all(len(z.read(n)) > 0 for n in names)
        # Missing files 404.
        r = c.request(
            "GET", f"{ADMIN}/inspect", query=[("volume", "insp"), ("file", "nope")]
        )
        assert r.status_code == 404
