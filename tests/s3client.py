"""Minimal SigV4-signing S3 test client (requests-based).

Plays the role of the reference's signed-request test helpers
(cmd/test-utils_test.go newTestSignedRequestV4): every call is a properly
V4-signed HTTP request against the in-process server.
"""

from __future__ import annotations

import urllib.parse

import requests

from minio_tpu.api.auth import Credentials, sign_request


class S3TestClient:
    def __init__(self, endpoint: str, access_key: str, secret_key: str, region="us-east-1"):
        self.endpoint = endpoint.rstrip("/")
        self.creds = Credentials(access_key, secret_key)
        self.region = region
        self.host = urllib.parse.urlparse(self.endpoint).netloc
        self.session = requests.Session()

    def request(
        self,
        method: str,
        path: str,
        query: list[tuple[str, str]] | None = None,
        body: bytes = b"",
        headers: dict[str, str] | None = None,
        anonymous: bool = False,
        stream: bool = False,
    ) -> requests.Response:
        query = query or []
        headers = dict(headers or {})
        url = self.endpoint + urllib.parse.quote(path)
        if query:
            url += "?" + urllib.parse.urlencode(query)
        if not anonymous:
            headers["host"] = self.host
            headers = sign_request(
                self.creds, method, path, query, headers, body, region=self.region
            )
            headers.pop("host")
        return self.session.request(method, url, data=body, headers=headers, stream=stream)

    # Convenience wrappers -----------------------------------------------

    def make_bucket(self, bucket: str):
        return self.request("PUT", f"/{bucket}")

    def delete_bucket(self, bucket: str):
        return self.request("DELETE", f"/{bucket}")

    def head_bucket(self, bucket: str):
        return self.request("HEAD", f"/{bucket}")

    def put_object(self, bucket: str, key: str, data: bytes, headers=None):
        return self.request("PUT", f"/{bucket}/{key}", body=data, headers=headers)

    def get_object(self, bucket: str, key: str, headers=None, query=None):
        return self.request("GET", f"/{bucket}/{key}", headers=headers, query=query)

    def head_object(self, bucket: str, key: str):
        return self.request("HEAD", f"/{bucket}/{key}")

    def delete_object(self, bucket: str, key: str, query=None):
        return self.request("DELETE", f"/{bucket}/{key}", query=query)

    def list_objects(self, bucket: str, **params):
        return self.request("GET", f"/{bucket}", query=list(params.items()))
