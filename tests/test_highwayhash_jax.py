"""Device HighwayHash vs numpy oracle, across remainder lengths and batches."""

import numpy as np
import pytest

from minio_tpu.ops import highwayhash as hh
from minio_tpu.ops import highwayhash_jax as hhj


@pytest.mark.parametrize("n", [1, 3, 16, 31, 32, 33, 64, 100, 1000])
def test_jax_matches_numpy(n):
    rng = np.random.default_rng(n)
    data = rng.integers(0, 256, (4, n)).astype(np.uint8)
    want = hh.hash256_batch(data)
    got = np.asarray(hhj.hash256_batch(data))
    assert np.array_equal(want, got)


def test_large_batch():
    rng = np.random.default_rng(7)
    data = rng.integers(0, 256, (64, 333)).astype(np.uint8)
    want = hh.hash256_batch(data)
    got = np.asarray(hhj.hash256_batch(data))
    assert np.array_equal(want, got)
