"""HighwayHash-256 bit-exactness tests.

The chain test replicates the reference's boot-time bitrot self-test
(/root/reference/cmd/bitrot.go:214-245) with its golden checksums, which pins
the keyed hash (magic key, cmd/bitrot.go:37) on whole-packet inputs; the
streaming/chunking tests cover the remainder path and buffering.
"""

import hashlib

import numpy as np
import pytest

from minio_tpu.ops import highwayhash as hh

# Golden self-test checksums from cmd/bitrot.go:215-220.
GOLDEN_CHAIN = {
    "sha256": "a7677ff19e0182e4d52e3a3db727804abc82a5818749336369552e54b838b004",
    "blake2b": "e519b7d84b1c3c917985f544773a35cf265dcab10948be3550320d156bab612124a5ae2ae5a8c73c0eea360f68b0e28136f26e858756dbfe7375a7389f26c669",
    "highwayhash256": "39c0407ed3f01b18d22c85db4aeff11e060ca5f43131b0126731ca197cd42313",
}


def _chain(new_hasher, size: int, block_size: int) -> bytes:
    msg = b""
    sum_ = b""
    for _ in range(0, size * block_size, size):
        h = new_hasher()
        h.update(msg)
        sum_ = h.digest()
        msg += sum_
    return sum_


def test_chain_sha256():
    assert _chain(hashlib.sha256, 32, 64).hex() == GOLDEN_CHAIN["sha256"]


def test_chain_blake2b():
    assert (
        _chain(lambda: hashlib.blake2b(digest_size=64), 64, 128).hex()
        == GOLDEN_CHAIN["blake2b"]
    )


def test_chain_highwayhash():
    assert (
        _chain(hh.HighwayHash256, 32, 32).hex() == GOLDEN_CHAIN["highwayhash256"]
    )


def test_oneshot_matches_streaming():
    rng = np.random.default_rng(0)
    for n in [0, 1, 3, 4, 15, 16, 17, 31, 32, 33, 63, 64, 100, 1000, 87382]:
        data = rng.integers(0, 256, n).astype(np.uint8).tobytes()
        h = hh.HighwayHash256()
        h.update(data)
        assert h.digest() == hh.hash256(data), n


def test_streaming_chunked():
    rng = np.random.default_rng(1)
    data = rng.integers(0, 256, 10_000).astype(np.uint8).tobytes()
    for chunks in [(1,), (7, 13), (32,), (31, 33, 64), (4096,)]:
        h = hh.HighwayHash256()
        pos = 0
        i = 0
        while pos < len(data):
            step = chunks[i % len(chunks)]
            h.update(data[pos : pos + step])
            pos += step
            i += 1
        assert h.digest() == hh.hash256(data), chunks


def test_digest_does_not_disturb_stream():
    h = hh.HighwayHash256()
    h.update(b"hello")
    d1 = h.digest()
    assert h.digest() == d1
    h.update(b" world")
    full = hh.hash256(b"hello world")
    assert h.digest() == full


def test_batch_matches_single():
    rng = np.random.default_rng(2)
    data = rng.integers(0, 256, (8, 1234)).astype(np.uint8)
    out = hh.hash256_batch(data)
    for i in range(8):
        assert out[i].tobytes() == hh.hash256(data[i].tobytes()), i


def test_key_sensitivity():
    other = bytes(32)
    assert hh.hash256(b"x") != hh.hash256(b"x", key=other)
