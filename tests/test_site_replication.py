"""Site replication: two in-process clusters joined into one federation.

The analogue of the reference's site-replication flow (cmd/site-replication.go
AddPeerClusters :256 + SRPeer* admin RPCs): after the join, bucket
create/delete, bucket metadata, IAM items, and object data all mirror across
sites, with data riding the bucket-replication engine in both directions.
"""

import json
import time
from types import SimpleNamespace

import pytest

from minio_tpu.api.server import ThreadedServer
from minio_tpu.dist.node import Node
from tests.s3client import S3TestClient
from tests.test_dist import _free_port

ROOT = "siteroot"
SECRET = "site-secret-key"
ADMIN = "/mtpu/admin/v1"


def _boot(tmp, name):
    port = _free_port()
    url = f"http://127.0.0.1:{port}"
    endpoints = [str(tmp / name / f"d{i}") for i in range(4)]
    node = Node(endpoints, url=url, root_user=ROOT, root_password=SECRET)
    ts = ThreadedServer(SimpleNamespace(app=node.make_app()), port=port)
    ts.start()
    node.build()
    return {"node": node, "ts": ts, "url": url, "client": S3TestClient(url, ROOT, SECRET)}


@pytest.fixture(scope="module")
def sites(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("siterepl")
    a = _boot(tmp, "a")
    b = _boot(tmp, "b")
    # Pre-existing state on A that the join must seed to B.
    a["client"].make_bucket("preexisting")
    a["client"].put_object("preexisting", "seed.txt", b"seeded before join")
    r = a["client"].request(
        "POST",
        f"{ADMIN}/site-replication/add",
        body=json.dumps(
            {
                "sites": [
                    {"name": "site-a", "endpoint": a["url"], "access_key": ROOT, "secret_key": SECRET},
                    {"name": "site-b", "endpoint": b["url"], "access_key": ROOT, "secret_key": SECRET},
                ]
            }
        ).encode(),
    )
    assert r.status_code == 200, r.text
    yield a, b
    a["ts"].stop()
    b["ts"].stop()


def _drain(site):
    assert site["node"].replication.drain(timeout=15.0)


def test_join_status(sites):
    a, b = sites
    for side, me in ((a, "site-a"), (b, "site-b")):
        r = side["client"].request("GET", f"{ADMIN}/site-replication/info")
        assert r.status_code == 200
        info = r.json()
        assert info["enabled"] is True
        assert info["name"] == me
        assert {s["name"] for s in info["sites"]} == {"site-a", "site-b"}
        peers = [s for s in info["sites"] if not s["self"]]
        assert all(p["online"] for p in peers)


def test_preexisting_bucket_seeded(sites):
    a, b = sites
    assert b["client"].request("HEAD", "/preexisting").status_code == 200
    _drain(a)
    r = b["client"].get_object("preexisting", "seed.txt")
    assert r.status_code == 200 and r.content == b"seeded before join"


def test_new_bucket_mirrors(sites):
    a, b = sites
    a["client"].make_bucket("made-on-a")
    assert b["client"].request("HEAD", "/made-on-a").status_code == 200
    # Versioning auto-enabled on both sides (site replication invariant).
    for side in (a, b):
        r = side["client"].request("GET", "/made-on-a", query=[("versioning", "")])
        assert "<Status>Enabled</Status>" in r.text


def test_object_data_replicates_both_ways(sites):
    a, b = sites
    a["client"].make_bucket("data-sync")
    a["client"].put_object("data-sync", "from-a.bin", b"A" * 50_000)
    _drain(a)
    r = b["client"].get_object("data-sync", "from-a.bin")
    assert r.status_code == 200 and r.content == b"A" * 50_000

    b["client"].put_object("data-sync", "from-b.bin", b"B" * 30_000)
    _drain(b)
    r = a["client"].get_object("data-sync", "from-b.bin")
    assert r.status_code == 200 and r.content == b"B" * 30_000


def test_bucket_policy_mirrors(sites):
    a, b = sites
    a["client"].make_bucket("polbkt")
    policy = {
        "Version": "2012-10-17",
        "Statement": [
            {
                "Effect": "Allow",
                "Principal": {"AWS": ["*"]},
                "Action": ["s3:GetObject"],
                "Resource": ["arn:aws:s3:::polbkt/*"],
            }
        ],
    }
    r = a["client"].request(
        "PUT", "/polbkt", query=[("policy", "")], body=json.dumps(policy).encode()
    )
    assert r.status_code == 204, r.text
    r = b["client"].request("GET", "/polbkt", query=[("policy", "")])
    assert r.status_code == 200
    assert json.loads(r.text)["Statement"][0]["Action"] == ["s3:GetObject"]


def test_bucket_tagging_and_lifecycle_mirror(sites):
    a, b = sites
    a["client"].make_bucket("metabkt")
    tag_xml = (
        '<Tagging xmlns="http://s3.amazonaws.com/doc/2006-03-01/"><TagSet>'
        "<Tag><Key>team</Key><Value>storage</Value></Tag></TagSet></Tagging>"
    )
    assert (
        a["client"].request("PUT", "/metabkt", query=[("tagging", "")], body=tag_xml.encode()).status_code
        == 200
    )
    r = b["client"].request("GET", "/metabkt", query=[("tagging", "")])
    assert r.status_code == 200 and "<Key>team</Key>" in r.text

    lc_xml = (
        '<LifecycleConfiguration><Rule><ID>exp</ID><Status>Enabled</Status>'
        "<Filter><Prefix>tmp/</Prefix></Filter><Expiration><Days>7</Days></Expiration>"
        "</Rule></LifecycleConfiguration>"
    )
    assert (
        a["client"].request("PUT", "/metabkt", query=[("lifecycle", "")], body=lc_xml.encode()).status_code
        == 200
    )
    r = b["client"].request("GET", "/metabkt", query=[("lifecycle", "")])
    assert r.status_code == 200 and "<ID>exp</ID>" in r.text


def test_iam_mirrors(sites):
    a, b = sites
    # Custom policy.
    doc = {
        "Version": "2012-10-17",
        "Statement": [{"Effect": "Allow", "Action": ["s3:GetObject"], "Resource": ["arn:aws:s3:::*"]}],
    }
    r = a["client"].request(
        "PUT", f"{ADMIN}/policies/site-shared", body=json.dumps(doc).encode()
    )
    assert r.status_code == 200, r.text
    r = b["client"].request("GET", f"{ADMIN}/policies")
    assert "site-shared" in r.json()

    # User with the policy attached.
    r = a["client"].request(
        "POST",
        f"{ADMIN}/users",
        body=json.dumps(
            {"accessKey": "siteuser", "secretKey": "siteuser-secret", "policies": ["site-shared"]}
        ).encode(),
    )
    assert r.status_code == 200, r.text
    users = b["client"].request("GET", f"{ADMIN}/users").json()
    assert "siteuser" in users and users["siteuser"]["policies"] == ["site-shared"]

    # The mirrored user can sign requests on site B (same secret).
    ub = S3TestClient(b["url"], "siteuser", "siteuser-secret")
    r = ub.request("GET", "/data-sync", query=[("location", "")])
    assert r.status_code in (200, 403)  # signature accepted (403 only if policy denies)

    # Removal mirrors too.
    assert a["client"].request("DELETE", f"{ADMIN}/users/siteuser").status_code == 200
    assert "siteuser" not in b["client"].request("GET", f"{ADMIN}/users").json()


def test_delete_marker_replicates(sites):
    a, b = sites
    a["client"].make_bucket("delbkt")
    a["client"].put_object("delbkt", "gone.txt", b"bye")
    _drain(a)
    assert b["client"].get_object("delbkt", "gone.txt").status_code == 200
    r = a["client"].request("DELETE", "/delbkt/gone.txt")
    assert r.status_code == 204
    _drain(a)
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        if b["client"].get_object("delbkt", "gone.txt").status_code == 404:
            break
        time.sleep(0.1)
    assert b["client"].get_object("delbkt", "gone.txt").status_code == 404


def test_bucket_delete_mirrors(sites):
    a, b = sites
    a["client"].make_bucket("shortlived")
    assert b["client"].request("HEAD", "/shortlived").status_code == 200
    r = a["client"].request("DELETE", "/shortlived")
    assert r.status_code == 204
    assert b["client"].request("HEAD", "/shortlived").status_code == 404


def test_join_rejects_nonempty_peer(tmp_path):
    a = _boot(tmp_path, "na")
    b = _boot(tmp_path, "nb")
    try:
        b["client"].make_bucket("already-there")
        r = a["client"].request(
            "POST",
            f"{ADMIN}/site-replication/add",
            body=json.dumps(
                {
                    "sites": [
                        {"name": "na", "endpoint": a["url"], "access_key": ROOT, "secret_key": SECRET},
                        {"name": "nb", "endpoint": b["url"], "access_key": ROOT, "secret_key": SECRET},
                    ]
                }
            ).encode(),
        )
        assert r.status_code == 400
        assert "not empty" in r.text
        # Nothing was committed on either side.
        for side in (a, b):
            info = side["client"].request("GET", f"{ADMIN}/site-replication/info").json()
            assert info["enabled"] is False
    finally:
        a["ts"].stop()
        b["ts"].stop()


def test_down_peer_does_not_fail_local_writes(tmp_path):
    a = _boot(tmp_path, "da")
    b = _boot(tmp_path, "db")
    try:
        r = a["client"].request(
            "POST",
            f"{ADMIN}/site-replication/add",
            body=json.dumps(
                {
                    "sites": [
                        {"name": "da", "endpoint": a["url"], "access_key": ROOT, "secret_key": SECRET},
                        {"name": "db", "endpoint": b["url"], "access_key": ROOT, "secret_key": SECRET},
                    ]
                }
            ).encode(),
        )
        assert r.status_code == 200, r.text
        a["client"].make_bucket("survivor")
        b["ts"].stop()  # peer outage

        # Local mutations still succeed; the fan-out parks in the retry queue.
        tag_xml = (
            '<Tagging><TagSet><Tag><Key>k</Key><Value>v</Value></Tag></TagSet></Tagging>'
        )
        r = a["client"].request(
            "PUT", "/survivor", query=[("tagging", "")], body=tag_xml.encode()
        )
        assert r.status_code == 200, r.text
        sr = a["node"].site_repl
        assert sr.pending_fanout() >= 1
        assert "db" in sr.last_errors
    finally:
        a["ts"].stop()
        try:
            b["ts"].stop()
        except Exception:
            pass
