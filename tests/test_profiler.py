"""Continuous profiling plane: windows, GIL probe, copy ledger, /profile.

Covers control/profiler.py end to end -- window rotation under a bounded
ring, thread-role aggregation, the calibrated GIL-load probe (loaded vs
idle ordering), copy-ledger conservation across a real in-process PUT+GET,
the cluster-merged /mtpu/admin/v1/profile surface, and the sampler's
self-measured overhead bound -- plus the SamplingProfiler elapsed-time
regressions and a smoke of tools/profile_diff.py.
"""

from __future__ import annotations

import importlib.util
import json
import threading
import time
from pathlib import Path

import pytest

from minio_tpu.control.profiler import (
    COPIED,
    GLOBAL_PROFILER,
    MOVED,
    ROLE_PREFIXES,
    ContinuousProfiler,
    CopyLedger,
    GilLoadProbe,
    ProfilerSys,
    SamplingProfiler,
    merge_profiles,
    thread_role,
)

_REPO = Path(__file__).resolve().parent.parent
_spec = importlib.util.spec_from_file_location(
    "profile_diff", _REPO / "tools" / "profile_diff.py"
)
profile_diff = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(profile_diff)


class TestThreadRoles:
    def test_known_prefixes_map_to_roles(self):
        cases = {
            "asyncio_0": "api-executor",
            "http-server": "api-loop",
            "lg-worker-3": "loadgen",
            "drive-io-7": "drive-io",
            "encode-batch-1": "codec-batch",
            "codec-warmup": "codec-batch",
            "etag-md5": "hash",
            "peer-stream-pump": "rpc",
            "lock-refresh": "rpc",
            "data-scanner": "scanner",
            "mrf-heal": "scanner",
            "prof-continuous": "profiler",
            "gil-probe": "profiler",
            "MainThread": "main",
        }
        for name, role in cases.items():
            assert thread_role(name) == role, name

    def test_unknown_names_fall_into_other(self):
        assert thread_role("ThreadPoolExecutor-0_0") == "other"
        assert thread_role("") == "other"


class TestSamplingProfilerElapsed:
    """The two elapsed-time bugs the ISSUE names: report() before stop()
    used to claim "over 0.0s", and a stop() arriving long after the
    max_duration_s safety valve inflated the denominator."""

    def test_report_mid_run_shows_live_elapsed(self):
        p = SamplingProfiler(interval_s=0.002)
        p.start()
        try:
            time.sleep(0.15)
            rpt = p.report()
            assert p.elapsed_s > 0.05
            assert "over 0.0s" not in rpt
        finally:
            p.stop()

    def test_late_stop_after_valve_does_not_inflate_elapsed(self):
        p = SamplingProfiler(interval_s=0.002, max_duration_s=0.05)
        p.start()
        t = p._thread
        t.join(5)
        assert not t.is_alive(), "safety valve never fired"
        # A stop() arriving long after the valve must not grow elapsed.
        frozen = p.elapsed_s
        time.sleep(0.3)
        p.stop()
        assert p.elapsed_s == frozen
        assert p.elapsed_s < 0.25, p.elapsed_s

    def test_samples_attributed_per_thread(self):
        stop = threading.Event()

        def busy():
            while not stop.is_set():
                sum(i * i for i in range(500))

        w = threading.Thread(target=busy, daemon=True, name="lg-busy-sampled")
        w.start()
        p = SamplingProfiler(interval_s=0.002)
        p.start()
        time.sleep(0.2)
        p.stop()
        stop.set()
        w.join(2)
        # A full pytest run leaves hundreds of parked pool threads alive;
        # an unbounded report keeps the assertion independent of how many
        # share the top-60 rows.
        assert "[lg-busy-sampled]" in p.report(top=10**6)


class TestContinuousWindows:
    def test_rotation_and_ring_bound(self):
        cp = ContinuousProfiler(interval_s=0.002, window_s=0.04, max_windows=3)
        cp.start()
        try:
            time.sleep(0.5)
        finally:
            cp.stop()
        assert cp.windows_rotated >= 3
        wins = cp.windows()
        # stop() folds the live window into the same bounded ring.
        assert 1 <= len(wins) <= 3
        for w in wins:
            assert w["closed"] is True
            assert w["samples"] >= 1
            assert w["duration_s"] > 0
            assert w["overhead_ratio"] >= 0
            assert set(w["roles"]) <= {r for _, r in ROLE_PREFIXES} | {"other"}

    def test_collapsed_output_is_flamegraph_format(self):
        cp = ContinuousProfiler(interval_s=0.002, window_s=10.0)
        cp.start()
        try:
            time.sleep(0.1)
        finally:
            cp.stop()
        text = cp.collapsed()
        assert text, "no stacks sampled"
        for line in text.strip().splitlines():
            stack, _, count = line.rpartition(" ")
            assert stack and count.isdigit(), line
            # role;file:func;file:func
            role = stack.split(";", 1)[0]
            assert role and ":" not in role, line
            assert ":" in stack.split(";", 1)[1], line

    def test_overhead_ratio_stays_low(self):
        cp = ContinuousProfiler(interval_s=0.010, window_s=10.0)
        cp.start()
        try:
            time.sleep(0.4)
        finally:
            cp.stop()
        # Self-measured duty cycle: each tick costs ~100us against a 10 ms
        # interval. The bound is generous (CI noise) but still catches a
        # sampler that busy-loops.
        assert 0.0 <= cp.overhead_ratio() < 0.2


class TestGilProbe:
    def test_value_zero_until_calibrated(self):
        probe = GilLoadProbe()
        assert probe.value() == 0.0

    def test_loaded_interpreter_reads_higher_than_idle(self):
        probe = GilLoadProbe(interval_s=0.004)
        probe.start()
        try:
            deadline = time.monotonic() + 10
            # Calibration floor + a ring of idle delays first.
            while probe.ticks < probe._CALIB_TICKS + 12:
                assert time.monotonic() < deadline, "probe never calibrated"
                time.sleep(0.01)
            idle = probe.value()

            stop = threading.Event()

            def burn():
                while not stop.is_set():
                    sum(i * i for i in range(2000))

            workers = [
                threading.Thread(target=burn, daemon=True, name=f"lg-burn-{i}")
                for i in range(4)
            ]
            for w in workers:
                w.start()
            time.sleep(0.5)
            loaded = probe.value()
            stop.set()
            for w in workers:
                w.join(2)
        finally:
            probe.stop()
        assert loaded > idle, (loaded, idle)
        assert loaded > 0.05, loaded
        assert 0.0 <= idle <= 1.0 and 0.0 <= loaded <= 1.0


class TestCopyLedger:
    def test_record_and_snapshot(self):
        cl = CopyLedger()
        cl.record("socket-read", COPIED, 100)
        cl.record("socket-read", COPIED, 50)
        cl.record("drive-write", MOVED, 400)
        cl.record("drive-write", COPIED, 0)   # no-op
        cl.record("drive-write", COPIED, -5)  # no-op
        snap = cl.snapshot()
        assert snap["hops"]["socket-read"] == {
            "copied_bytes": 150, "copied_ops": 2,
            "moved_bytes": 0, "moved_ops": 0,
        }
        assert snap["hops"]["drive-write"] == {
            "copied_bytes": 0, "copied_ops": 0,
            "moved_bytes": 400, "moved_ops": 1,
        }

    def test_merge_sums_elementwise(self):
        a = {"hops": {"h": {"copied_bytes": 10, "copied_ops": 1,
                            "moved_bytes": 0, "moved_ops": 0}}}
        b = {"hops": {"h": {"copied_bytes": 5, "copied_ops": 2,
                            "moved_bytes": 7, "moved_ops": 1},
                      "g": {"copied_bytes": 1, "copied_ops": 1,
                            "moved_bytes": 0, "moved_ops": 0}}}
        m = CopyLedger.merge([a, b, None, {}])
        assert m["hops"]["h"]["copied_bytes"] == 15
        assert m["hops"]["h"]["copied_ops"] == 3
        assert m["hops"]["h"]["moved_bytes"] == 7
        assert m["hops"]["g"]["copied_ops"] == 1

    def test_reset_clears(self):
        cl = CopyLedger()
        cl.record("h", COPIED, 9)
        cl.reset()
        assert cl.snapshot() == {"hops": {}}


class TestCopyConservation:
    """The ledger against a real erasure PUT+GET: every hop the ISSUE's
    data-path walk names must see at least the object's bytes -- and since
    the zero-copy PUT pipeline, the pooled PUT hops must see them as MOVES,
    not copies."""

    SIZE = 1 << 20  # > SMALL_FILE_THRESHOLD: takes the streaming shard path

    def test_put_get_hops_account_for_object_bytes(self, tmp_path):
        from minio_tpu.storage.metered import MeteredDrive
        from tests.harness import ErasureHarness

        hz = ErasureHarness(tmp_path, n_disks=8)
        # Production nodes wrap every drive (dist/node.py); the drive-write/
        # drive-read hops live on that metered boundary.
        hz.layer.disks = [MeteredDrive(d) for d in hz.layer.disks]
        hz.layer.make_bucket("cb")
        data = bytes(range(256)) * (self.SIZE // 256)

        GLOBAL_PROFILER.copy.reset()
        hz.layer.put_object("cb", "obj", data)
        put_hops = GLOBAL_PROFILER.copy.snapshot()["hops"]
        # Zero-copy staging: a buffer input is sliced into block windows by
        # reference, the encoder scatter-writes iovec views, and the drive
        # append is a gathered writev -- every PUT hop moves, nothing
        # copies (bytes >= size because parity shards ride the same hops).
        assert put_hops["erasure-stage"]["moved_bytes"] >= self.SIZE
        assert put_hops["erasure-stage"]["copied_bytes"] == 0
        assert put_hops["shard-fanout"]["moved_bytes"] >= self.SIZE
        assert put_hops["drive-write"]["moved_bytes"] >= self.SIZE
        assert put_hops["drive-write"]["moved_ops"] >= 1

        GLOBAL_PROFILER.copy.reset()
        _, got = hz.layer.get_object("cb", "obj")
        assert got == data
        get_hops = GLOBAL_PROFILER.copy.snapshot()["hops"]
        # Zero-copy healthy read: drives readinto pooled shard buffers
        # (moved), frame parsing slices them by reference (moved), no
        # decode happens -- so the whole GET copies NOTHING. (The buffered
        # get_object() convenience join above sits outside the ledger; the
        # server streams the same views straight to the socket.)
        assert get_hops["drive-read"]["moved_bytes"] >= self.SIZE
        assert get_hops["drive-read"]["copied_bytes"] == 0
        assert get_hops["frame-parse"]["moved_bytes"] >= self.SIZE
        assert get_hops["frame-parse"]["copied_bytes"] == 0
        assert "decode" not in get_hops
        copied = sum(h["copied_bytes"] for h in get_hops.values())
        assert copied == 0, f"healthy GET copied {copied} bytes: {get_hops}"

    def test_degraded_read_pays_the_decode_copy(self, tmp_path):
        from tests.harness import ErasureHarness

        hz = ErasureHarness(tmp_path, n_disks=8)
        hz.layer.make_bucket("cb")
        data = b"d" * self.SIZE
        hz.layer.put_object("cb", "obj", data)

        # The shard layout is a per-object permutation: with 4 parity slots
        # on 8 drives, at least one of drives 0..4 holds a DATA row, so
        # knocking each out in turn must trigger reconstruction at least
        # once (pigeonhole) while parity keeps every read succeeding.
        # With k=4 data rows and one drive out, a degraded read rebuilds
        # exactly one row per block: SIZE/4 bytes -- the decode hop must
        # charge exactly that, never the whole object.
        shard_bytes = self.SIZE // 4
        decoded = 0
        for i in range(5):
            hz.take_offline(i)
            GLOBAL_PROFILER.copy.reset()
            _, got = hz.layer.get_object("cb", "obj")
            assert got == data
            hops = GLOBAL_PROFILER.copy.snapshot()["hops"]
            this = hops.get("decode", {}).get("copied_bytes", 0)
            assert this in (0, shard_bytes), (
                f"drive {i}: decode charged {this}, want 0 or {shard_bytes}"
            )
            decoded += this
            hz.bring_online(i)
        assert decoded > 0, "no offline drive ever forced a decode"


class TestMergeProfiles:
    def _snap(self, node, stack_n, gil):
        return {
            "node": node,
            "armed": True,
            "gil_load": gil,
            "copy": {"hops": {"socket-read": {
                "copied_bytes": 10, "copied_ops": 1,
                "moved_bytes": 0, "moved_ops": 0}}},
            "windows": [{
                "samples": stack_n,
                "roles": {"api-executor": stack_n},
                "stacks": {"api-executor;server.py:handle": stack_n},
            }],
        }

    def test_stacks_sum_and_gil_stays_per_node(self):
        m = merge_profiles([self._snap("n0", 3, 0.2), self._snap("n1", 5, 0.9)])
        assert m["samples"] == 8
        assert m["stacks"]["api-executor;server.py:handle"] == 8
        assert m["roles"]["api-executor"] == 8
        # GIL pressure is per-interpreter: merged as a dict, never summed.
        assert m["gil_load"] == {"n0": 0.2, "n1": 0.9}
        assert m["copy"]["hops"]["socket-read"]["copied_bytes"] == 20

    def test_empty_and_missing_snaps_tolerated(self):
        m = merge_profiles([None, {}, self._snap("a", 1, 0.0)])
        assert m["samples"] == 1
        assert list(m["gil_load"]) == ["a"]


class TestProfilerSys:
    def test_mtpu_profile_0_vetoes(self, monkeypatch):
        monkeypatch.setenv("MTPU_PROFILE", "0")
        ps = ProfilerSys()
        assert ps.ensure_started() is False
        assert ps.armed is False
        assert ps.sampler is None

    def test_lifecycle_snapshot_and_summary(self, monkeypatch):
        monkeypatch.delenv("MTPU_PROFILE", raising=False)
        ps = ProfilerSys()
        try:
            assert ps.ensure_started(interval_s=0.002, window_s=0.05,
                                     max_windows=2) is True
            assert ps.ensure_started() is True  # idempotent
            assert ps.armed
            time.sleep(0.2)
            ps.copy.record("socket-read", COPIED, 42)

            snap = ps.snapshot(top=5)
            assert snap["profile"] == 1 and snap["armed"] is True
            assert 0.0 <= snap["gil_load"] <= 1.0
            assert snap["copy"]["hops"]["socket-read"]["copied_bytes"] == 42
            assert snap["sampler"]["windows_rotated"] >= 1
            assert snap["windows"], "no windows retained"
            assert all(w["samples"] >= 1 for w in snap["windows"])

            summ = ps.summary(top=3)
            for k in ("armed", "gil_load", "samples", "sampler_overhead_ratio",
                      "roles", "top_stacks", "copy"):
                assert k in summ, k
            assert summ["samples"] >= 1
            assert len(summ["top_stacks"]) <= 3
            for row in summ["top_stacks"]:
                assert 0.0 <= row["share"] <= 1.0
        finally:
            ps.stop()
        assert ps.armed is False
        # Counters and windows survive the stop; only the threads die.
        assert ps.summary()["samples"] >= 1

    def test_snapshot_without_stacks(self, monkeypatch):
        monkeypatch.delenv("MTPU_PROFILE", raising=False)
        ps = ProfilerSys()
        try:
            ps.ensure_started(interval_s=0.002, window_s=0.05)
            time.sleep(0.1)
            snap = ps.snapshot(include_stacks=False)
            assert snap["windows"]
            assert all("stacks" not in w for w in snap["windows"])
        finally:
            ps.stop()


@pytest.fixture(scope="module")
def lg_cluster(tmp_path_factory):
    from minio_tpu.loadgen.cluster import InProcessCluster

    tmp = tmp_path_factory.mktemp("prof-cluster")
    cluster = InProcessCluster(str(tmp), n_nodes=2, drives_per_node=4)
    yield cluster
    cluster.stop()


class TestProfileEndpoint:
    """GET /mtpu/admin/v1/profile on a real 2-node cluster: node snapshot,
    collapsed download, summary block, and the ?cluster=1 peer merge."""

    def _client(self, cluster):
        from tests.s3client import S3TestClient

        return S3TestClient(cluster.urls[0], cluster.root_user,
                            cluster.root_password)

    def _warm(self, client):
        client.make_bucket("profb")
        assert client.put_object(
            "profb", "p.bin", b"z" * (256 << 10)).status_code == 200
        assert client.get_object("profb", "p.bin").status_code == 200

    def test_node_snapshot_armed_with_windows(self, lg_cluster):
        client = self._client(lg_cluster)
        self._warm(client)
        deadline = time.monotonic() + 10
        while True:
            r = client.request("GET", "/mtpu/admin/v1/profile")
            assert r.status_code == 200, r.text
            doc = r.json()
            assert doc["armed"] is True, "node build did not arm the plane"
            if doc.get("windows") and any(w["samples"] for w in doc["windows"]):
                break
            assert time.monotonic() < deadline, "sampler never took a sample"
            time.sleep(0.1)
        assert doc["sampler"]["interval_ms"] > 0
        assert doc["sampler"]["overhead_ratio"] < 0.2
        # The PUT above walked the data path: its hops are in the ledger.
        hops = doc["copy"]["hops"]
        for hop in ("socket-read", "erasure-stage", "drive-write"):
            assert hops.get(hop, {}).get("copied_bytes", 0) + \
                hops.get(hop, {}).get("moved_bytes", 0) > 0, hop

    def test_collapsed_download(self, lg_cluster):
        client = self._client(lg_cluster)
        r = client.request("GET", "/mtpu/admin/v1/profile",
                           query=[("collapsed", "1")])
        assert r.status_code == 200
        assert r.headers["Content-Type"].startswith("text/plain")
        assert "profile.collapsed" in r.headers.get("Content-Disposition", "")
        for line in r.text.strip().splitlines():
            stack, _, count = line.rpartition(" ")
            assert stack and count.isdigit(), line

    def test_summary_block(self, lg_cluster):
        client = self._client(lg_cluster)
        r = client.request("GET", "/mtpu/admin/v1/profile",
                           query=[("summary", "1")])
        assert r.status_code == 200, r.text
        doc = r.json()
        for k in ("armed", "gil_load", "samples", "sampler_overhead_ratio",
                  "roles", "top_stacks", "copy"):
            assert k in doc, k

    def test_cluster_merge(self, lg_cluster):
        client = self._client(lg_cluster)
        r = client.request("GET", "/mtpu/admin/v1/profile",
                           query=[("cluster", "1")])
        assert r.status_code == 200, r.text
        doc = r.json()
        assert doc["peers"], "no peers consulted"
        assert all(p["ok"] for p in doc["peers"].values()), doc["peers"]
        merged = doc["cluster"]
        node_samples = sum(w["samples"] for w in doc["node"].get("windows", []))
        assert merged["samples"] >= node_samples
        assert isinstance(merged["gil_load"], dict) and merged["gil_load"]
        assert merged["copy"]["hops"]
        assert merged["stacks"]

    def test_bad_top_is_invalid_argument(self, lg_cluster):
        client = self._client(lg_cluster)
        r = client.request("GET", "/mtpu/admin/v1/profile",
                           query=[("top", "abc")])
        assert r.status_code == 400

    def test_profiler_series_reach_prometheus(self, lg_cluster):
        lint_spec = importlib.util.spec_from_file_location(
            "metrics_lint", _REPO / "tools" / "metrics_lint.py")
        metrics_lint = importlib.util.module_from_spec(lint_spec)
        lint_spec.loader.exec_module(metrics_lint)

        client = self._client(lg_cluster)
        r = client.request("GET", "/minio/v2/metrics/node")
        assert r.status_code == 200
        text = r.text
        for series in (
            "minio_tpu_gil_load",
            "minio_tpu_profiler_overhead_ratio",
            "minio_tpu_profiler_samples_window",
            "minio_tpu_profiler_windows_rotated_total",
            "minio_tpu_copy_bytes_total",
            "minio_tpu_copy_ops_total",
            "minio_tpu_stage_cpu_seconds_total",
        ):
            assert series in text, series
        assert metrics_lint.validate_exposition(text) == []
        assert metrics_lint.lint_exposition(text) == []


class TestLoadgenProfileBlock:
    def test_profile_true_embeds_summary_in_report(self, tmp_path):
        from minio_tpu.loadgen import parse_scenario
        from minio_tpu.loadgen.cluster import InProcessCluster
        from minio_tpu.loadgen.runner import ScenarioRunner
        from minio_tpu.loadgen.target import InProcessAdmin, S3Target

        sc = parse_scenario(
            {
                "name": "prof_smoke",
                "seed": 3,
                "bucket": "lgprof",
                "profile": True,
                "cluster": {"nodes": 2, "drives_per_node": 4},
                "keyspace": {"keys": 8, "prepopulate": 4, "prefix": "pf/",
                             "zipf_theta": 0.9},
                "sizes": {"kind": "fixed", "bytes": 2048},
                "slo": {"GET": {"p99_ms": 30000, "error_budget": 0.25},
                        "PUT": {"p99_ms": 30000, "error_budget": 0.25}},
                "phases": [
                    {"name": "mixed", "mix": {"GET": 0.5, "PUT": 0.5},
                     "concurrency": 2, "ops": 12}
                ],
            }
        )
        assert sc.profile is True
        cluster = InProcessCluster(str(tmp_path), n_nodes=2, drives_per_node=4)
        try:
            target = S3Target(cluster.urls, cluster.root_user,
                              cluster.root_password)
            report = ScenarioRunner(sc, target, InProcessAdmin()).run()
        finally:
            cluster.stop()

        prof = report.get("profile")
        assert prof, "profile: true did not embed the summary block"
        assert prof["armed"] is True
        for k in ("gil_load", "samples", "sampler_overhead_ratio",
                  "roles", "top_stacks", "copy"):
            assert k in prof, k
        assert prof["samples"] >= 1
        # The run's PUTs left data-path hops in the embedded copy ledger.
        assert any(
            row["copied_bytes"] + row["moved_bytes"] > 0
            for row in prof["copy"].values()
        )

    def test_canonical_collapse_scenario_opts_in(self):
        from minio_tpu.loadgen import load_scenario

        sc = load_scenario(str(_REPO / "scenarios" / "concurrent_put_collapse.yaml"))
        assert sc.profile is True, (
            "concurrent_put_collapse must embed the profile block so the "
            "report names its bottleneck"
        )


class TestProfileDiff:
    def _write(self, tmp_path, name, text):
        p = tmp_path / name
        p.write_text(text)
        return str(p)

    def test_collapsed_text_round_trip_and_diff(self, tmp_path):
        before = self._write(
            tmp_path, "before.collapsed",
            "api-executor;a.py:f 80\ncodec-batch;b.py:g 20\n")
        after = self._write(
            tmp_path, "after.collapsed",
            "api-executor;a.py:f 40\ncodec-batch;b.py:g 60\n")
        b = profile_diff.load_capture(before)
        a = profile_diff.load_capture(after)
        rows = profile_diff.diff_captures(b, a)
        by_stack = {r["stack"]: r for r in rows}
        assert by_stack["codec-batch;b.py:g"]["delta"] == pytest.approx(0.4)
        assert by_stack["api-executor;a.py:f"]["delta"] == pytest.approx(-0.4)

    def test_json_payloads_load(self, tmp_path):
        node = self._write(tmp_path, "node.json", json.dumps({
            "windows": [{"stacks": {"s1": 3}}, {"stacks": {"s1": 2, "s2": 5}}],
        }))
        merged = self._write(tmp_path, "cluster.json", json.dumps({
            "stacks": {"s1": 10, "s2": 1},
        }))
        assert profile_diff.load_capture(node) == {"s1": 5.0, "s2": 5.0}
        assert profile_diff.load_capture(merged) == {"s1": 10.0, "s2": 1.0}

    def test_main_exit_codes_and_output(self, tmp_path, capsys):
        before = self._write(tmp_path, "b.collapsed", "x;a:f 10\ny;b:g 10\n")
        after = self._write(tmp_path, "a.collapsed", "x;a:f 30\ny;b:g 10\n")
        assert profile_diff.main([before, after]) == 0
        out = capsys.readouterr().out
        assert "regressed (share grew):" in out
        assert "improved (share shrank):" in out
        assert "x;a:f" in out

        assert profile_diff.main([before, after, "--json"]) == 0
        doc = json.loads(capsys.readouterr().out)
        assert doc["diff"]

        assert profile_diff.main([before, str(tmp_path / "missing")]) == 2
        assert "profile_diff:" in capsys.readouterr().err

    def test_bad_capture_is_a_typed_failure(self, tmp_path):
        bad = self._write(tmp_path, "bad.json", json.dumps({"not": "profile"}))
        with pytest.raises(ValueError):
            profile_diff.load_capture(bad)
