"""SSE (SSE-S3 + SSE-C) and transparent compression tests."""

import base64
import hashlib

import pytest

from minio_tpu.control import compress as compress_mod
from minio_tpu.control import crypto as crypto_mod
from minio_tpu.control.kms import StaticKeyKMS
from minio_tpu.utils import errors


class TestCrypto:
    def test_package_roundtrip(self):
        key = b"k" * 32
        for n in [0, 1, 100, 64 * 1024, 64 * 1024 + 1, 200_000]:
            data = bytes(i % 251 for i in range(n))
            blob = crypto_mod.encrypt_stream(data, key)
            assert crypto_mod.decrypt_stream(blob, key) == data

    def test_tamper_detected(self):
        key = b"k" * 32
        blob = bytearray(crypto_mod.encrypt_stream(b"secret data", key))
        blob[20] ^= 1
        with pytest.raises(errors.FileCorrupt):
            crypto_mod.decrypt_stream(bytes(blob), key)

    def test_sse_s3_seal_unseal(self):
        kms = StaticKeyKMS()
        res = crypto_mod.sse_s3_encrypt(b"payload", kms, "b", "o")
        assert res.data != b"payload"
        out = crypto_mod.sse_s3_decrypt(res.data, res.metadata, kms, "b", "o")
        assert out == b"payload"
        # Wrong KMS master fails.
        with pytest.raises(errors.StorageError):
            crypto_mod.sse_s3_decrypt(res.data, res.metadata, StaticKeyKMS(), "b", "o")

    def test_sse_c_wrong_key_rejected(self):
        k1, k2 = b"1" * 32, b"2" * 32
        res = crypto_mod.sse_c_encrypt(b"data", k1, "b", "o")
        assert crypto_mod.sse_c_decrypt(res.data, res.metadata, k1, "b", "o") == b"data"
        with pytest.raises(errors.PreconditionFailed):
            crypto_mod.sse_c_decrypt(res.data, res.metadata, k2, "b", "o")

    def test_kms_env(self, monkeypatch):
        master = base64.b64encode(b"m" * 32).decode()
        monkeypatch.setenv("MINIO_TPU_KMS_SECRET_KEY", f"mykey:{master}")
        kms = StaticKeyKMS.from_env()
        assert kms.name == "mykey"
        dk = kms.generate_key()
        assert kms.decrypt_key(dk.key_id, dk.ciphertext) == dk.plaintext


class TestCompress:
    def test_roundtrip_and_filters(self):
        data = b"abc " * 10000
        blob, meta = compress_mod.compress(data)
        assert len(blob) < len(data)
        assert compress_mod.decompress(blob, meta) == data
        assert compress_mod.is_compressible("a.txt", "application/octet-stream")
        assert compress_mod.is_compressible("a.dat", "text/plain")
        assert not compress_mod.is_compressible("a.jpg", "image/jpeg")

    def test_snappy_native_cross_checked_against_python_decoder(self):
        from minio_tpu.ops import native as native_mod
        from minio_tpu.s3select.parquet import snappy_decompress as py_snappy

        if not native_mod.snappy_available():
            pytest.skip("native toolchain absent")
        import numpy as np

        rng = np.random.default_rng(11)
        cases = [
            b"", b"x", b"hello world " * 500,
            bytes(rng.integers(0, 256, 50_000, dtype=np.uint8)),   # incompressible
            bytes(rng.integers(0, 4, 200_000, dtype=np.uint8)),    # compressible
            b"\x00" * 300_000,                                     # offset-1 RLE
            b"abc" * 100_001,                                      # tiny-offset RLE
        ]
        for d in cases:
            c = native_mod.snappy_compress(d)
            assert native_mod.snappy_decompress(c) == d
            # the parquet reader's spec-derived decoder is an independent
            # implementation: agreement pins the wire format, not just
            # self-consistency
            assert py_snappy(c) == d

    def test_snappy_rejects_corrupt_stream(self):
        from minio_tpu.ops import native as native_mod

        if not native_mod.snappy_available():
            pytest.skip("native toolchain absent")
        good = native_mod.snappy_compress(b"payload " * 1000)
        for bad in (b"\xff" * 10, good[:-3], good[:1], b"\x05\x00"):
            with pytest.raises(ValueError):
                native_mod.snappy_decompress(bad)

    def test_zlib_written_objects_still_decompress(self):
        # Objects written by an older build (or a toolchain-less host)
        # carry the zlib algo tag; reads must keep working.
        import zlib

        data = b"legacy " * 5000
        blob = zlib.compress(data, level=1)
        meta = {
            compress_mod.META_COMPRESSION: compress_mod.ALGO_ZLIB,
            compress_mod.META_ACTUAL_SIZE: str(len(data)),
        }
        assert compress_mod.decompress(blob, meta) == data


class _StubKES:
    """In-process KES server: the API surface KESClient speaks
    (/v1/key/generate, /v1/key/decrypt, /v1/status), sealing data keys with
    a local master key. Counts requests so tests can assert the client's
    decrypt cache actually short-circuits the network."""

    def __init__(self, api_key: str = ""):
        import http.server
        import json
        import secrets
        import threading

        from cryptography.hazmat.primitives.ciphers.aead import AESGCM

        self.master = secrets.token_bytes(32)
        self.requests: list[str] = []
        self.api_key = api_key
        stub = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _send(self, code, obj):
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                stub.requests.append(self.path)
                if self.path == "/v1/status":
                    self._send(200, {"version": "stub", "uptime": "1s"})
                else:
                    self._send(404, {})

            def do_POST(self):
                stub.requests.append(self.path)
                if stub.api_key and self.headers.get("Authorization") != f"Bearer {stub.api_key}":
                    self._send(401, {"message": "not authorized"})
                    return
                n = int(self.headers.get("Content-Length", 0))
                req = json.loads(self.rfile.read(n) or b"{}")
                aes = AESGCM(stub.master)
                if self.path.startswith("/v1/key/generate/"):
                    import secrets as sec

                    plain = sec.token_bytes(32)
                    nonce = sec.token_bytes(12)
                    ctx = base64.b64decode(req.get("context", ""))
                    sealed = nonce + aes.encrypt(nonce, plain, ctx)
                    self._send(200, {
                        "plaintext": base64.b64encode(plain).decode(),
                        "ciphertext": base64.b64encode(sealed).decode(),
                    })
                elif self.path.startswith("/v1/key/decrypt/"):
                    sealed = base64.b64decode(req["ciphertext"])
                    ctx = base64.b64decode(req.get("context", ""))
                    try:
                        plain = aes.decrypt(sealed[:12], sealed[12:], ctx)
                    except Exception:
                        self._send(400, {"message": "decrypt failed"})
                        return
                    self._send(200, {"plaintext": base64.b64encode(plain).decode()})
                else:
                    self._send(404, {})

        self.httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.endpoint = f"http://127.0.0.1:{self.httpd.server_address[1]}"
        threading.Thread(target=self.httpd.serve_forever, daemon=True).start()

    def close(self):
        self.httpd.shutdown()


class TestKESClient:
    @pytest.fixture()
    def kes(self):
        stub = _StubKES()
        yield stub
        stub.close()

    def test_generate_decrypt_roundtrip(self, kes):
        from minio_tpu.control.kms import KESClient

        c = KESClient(kes.endpoint, default_key="obj-key")
        dk = c.generate_key(context="b/o")
        assert dk.key_id == "obj-key" and len(dk.plaintext) == 32
        c2 = KESClient(kes.endpoint, default_key="obj-key")  # cold cache
        assert c2.decrypt_key("obj-key", dk.ciphertext, "b/o") == dk.plaintext

    def test_decrypt_cache_short_circuits_network(self, kes):
        from minio_tpu.control.kms import KESClient

        c = KESClient(kes.endpoint)
        dk = c.generate_key(context="x")
        before = len(kes.requests)
        for _ in range(5):
            assert c.decrypt_key(dk.key_id, dk.ciphertext, "x") == dk.plaintext
        assert len(kes.requests) == before  # generate seeded the cache

    def test_api_key_auth(self, kes):
        from minio_tpu.control.kms import KESClient
        from minio_tpu.utils import errors as errs

        kes.api_key = "secret-token"
        ok = KESClient(kes.endpoint, api_key="secret-token")
        assert ok.generate_key(context="c").plaintext
        bad = KESClient(kes.endpoint, api_key="wrong")
        with pytest.raises(errs.StorageError):
            bad.generate_key(context="c")

    def test_stat_online_offline(self, kes):
        from minio_tpu.control.kms import KESClient

        c = KESClient(kes.endpoint)
        assert c.stat()["online"] is True
        kes.close()
        assert c.stat()["online"] is False

    def test_sse_kms_roundtrip_through_crypto(self, kes):
        # The full SSE-KMS seal/unseal path (crypto.py) delegating to KES.
        from minio_tpu.control import crypto as crypto_mod
        from minio_tpu.control.kms import KESClient

        c = KESClient(kes.endpoint)
        data = b"secret payload " * 1000
        res = crypto_mod.sse_s3_encrypt(data, c, "buck", "obj")
        assert res.data != data
        fresh = KESClient(kes.endpoint)  # no warm cache: forces a decrypt call
        out = crypto_mod.sse_s3_decrypt(res.data, res.metadata, fresh, "buck", "obj")
        assert out == data

    def test_kms_from_env_prefers_kes(self, kes, monkeypatch):
        from minio_tpu.control import kms as kms_mod

        monkeypatch.setenv("MINIO_TPU_KMS_KES_ENDPOINT", kes.endpoint)
        monkeypatch.setenv("MINIO_TPU_KMS_KES_KEY_NAME", "envkey")
        k = kms_mod.kms_from_env()
        assert isinstance(k, kms_mod.KESClient) and k.default_key == "envkey"

    def test_sse_kms_through_s3_api(self, kes, tmp_path):
        # Signed HTTP PUT with x-amz-server-side-encryption against a server
        # whose KMS is the network KES client; GET decrypts via KES.
        from minio_tpu.api.server import S3Server, ThreadedServer
        from minio_tpu.control.iam import IAMSys
        from minio_tpu.control.kms import KESClient
        from minio_tpu.object.pools import ServerPools
        from minio_tpu.object.sets import ErasureSets
        from tests.harness import ErasureHarness
        from tests.s3client import S3TestClient

        hz = ErasureHarness(tmp_path, n_disks=8)
        layer = ServerPools([ErasureSets(list(hz.drives), 8)])
        srv = S3Server(
            layer, IAMSys("ak", "sk-secret"), check_skew=False,
            kms=KESClient(kes.endpoint),
        )
        ts = ThreadedServer(srv)
        client = S3TestClient(ts.start(), "ak", "sk-secret")
        try:
            client.make_bucket("kesb")
            body = b"kms-protected " * 4096
            r = client.request(
                "PUT", "/kesb/enc.bin", body=body,
                headers={"x-amz-server-side-encryption": "aws:kms"},
            )
            assert r.status_code == 200, r.text
            got = client.get_object("kesb", "enc.bin")
            assert got.content == body
            assert any("/v1/key/" in p for p in kes.requests)
        finally:
            ts.stop()


class TestAPIIntegration:
    @pytest.fixture(scope="class")
    def stack(self, tmp_path_factory):
        from minio_tpu.api.server import S3Server, ThreadedServer
        from minio_tpu.control.config import ConfigSys
        from minio_tpu.control.iam import IAMSys
        from minio_tpu.object.pools import ServerPools
        from minio_tpu.object.sets import ErasureSets
        from tests.harness import ErasureHarness
        from tests.s3client import S3TestClient

        tmp = tmp_path_factory.mktemp("sse")
        hz = ErasureHarness(tmp, n_disks=8)
        layer = ServerPools([ErasureSets(list(hz.drives), 8)])
        iam = IAMSys("ak", "sk-secret")
        cfg = ConfigSys()
        srv = S3Server(layer, iam, check_skew=False, kms=StaticKeyKMS(), config=cfg)
        ts = ThreadedServer(srv)
        endpoint = ts.start()
        client = S3TestClient(endpoint, "ak", "sk-secret")
        client.make_bucket("sseb")
        yield {"client": client, "config": cfg, "hz": hz}
        ts.stop()

    def test_sse_s3_roundtrip(self, stack):
        c = stack["client"]
        data = b"top-secret-bytes" * 1000
        r = c.put_object("sseb", "enc", data, headers={"x-amz-server-side-encryption": "AES256"})
        assert r.status_code == 200, r.text
        assert r.headers.get("x-amz-server-side-encryption") == "AES256"
        # Ciphertext at rest: raw shards differ from plaintext path.
        r = c.get_object("sseb", "enc")
        assert r.content == data
        assert r.headers.get("x-amz-server-side-encryption") == "AES256"
        # HEAD reports logical size.
        assert int(c.head_object("sseb", "enc").headers["Content-Length"]) == len(data)

    def test_sse_s3_at_rest_is_ciphertext(self, stack):
        c = stack["client"]
        hz = stack["hz"]
        plaintext = b"findable-plaintext-marker" * 100
        c.put_object("sseb", "enc2", plaintext, headers={"x-amz-server-side-encryption": "AES256"})
        # No shard on any disk contains the plaintext marker.
        import os

        for i in range(8):
            root = hz.dirs[i]
            for dirpath, _, files in os.walk(os.path.join(root, "sseb")):
                for f in files:
                    with open(os.path.join(dirpath, f), "rb") as fh:
                        assert b"findable-plaintext-marker" not in fh.read()

    def test_sse_c_roundtrip(self, stack):
        c = stack["client"]
        key = b"s" * 32
        headers = {
            "x-amz-server-side-encryption-customer-algorithm": "AES256",
            "x-amz-server-side-encryption-customer-key": base64.b64encode(key).decode(),
            "x-amz-server-side-encryption-customer-key-md5": base64.b64encode(
                hashlib.md5(key).digest()
            ).decode(),
        }
        data = b"client-encrypted" * 500
        assert c.put_object("sseb", "ssec", data, headers=headers).status_code == 200
        # GET without the key fails.
        assert c.get_object("sseb", "ssec").status_code == 400
        # GET with the key succeeds.
        r = c.get_object("sseb", "ssec", headers=headers)
        assert r.content == data
        # Wrong key rejected.
        bad = dict(headers)
        bad["x-amz-server-side-encryption-customer-key"] = base64.b64encode(b"x" * 32).decode()
        bad["x-amz-server-side-encryption-customer-key-md5"] = base64.b64encode(
            hashlib.md5(b"x" * 32).digest()
        ).decode()
        assert c.get_object("sseb", "ssec", headers=bad).status_code == 412

    def test_range_on_encrypted(self, stack):
        c = stack["client"]
        data = bytes(range(256)) * 500
        c.put_object("sseb", "encrange", data, headers={"x-amz-server-side-encryption": "AES256"})
        r = c.get_object("sseb", "encrange", headers={"Range": "bytes=1000-1099"})
        assert r.status_code == 206
        assert r.content == data[1000:1100]

    def test_copy_of_transformed_objects(self, stack):
        """CopyObject must read LOGICAL source bytes (decompress/decrypt)
        and re-apply the destination's transforms — copying the raw stored
        form dropped the transform metadata and served ciphertext/deflate
        under a 200 (cmd/object-handlers.go CopyObject decrypt/recompress
        semantics)."""
        c = stack["client"]
        stack["config"].set("compression", "enable", "on")
        try:
            body = (b"copyable text %05d\n" * 1500) % tuple(range(1500))
            c.put_object("sseb", "cp-src.txt", body)
            # compressed -> plain copy
            r = c.request("PUT", "/sseb/cp-dst.txt",
                          headers={"x-amz-copy-source": "/sseb/cp-src.txt"})
            assert r.status_code == 200, r.text
            assert c.get_object("sseb", "cp-dst.txt").content == body
            # encrypted source
            r = c.request("PUT", "/sseb/cp-enc.txt", body=body,
                          headers={"x-amz-server-side-encryption": "AES256"})
            assert r.status_code == 200
            r = c.request("PUT", "/sseb/cp-enc-dst.txt",
                          headers={"x-amz-copy-source": "/sseb/cp-enc.txt"})
            assert r.status_code == 200
            assert c.get_object("sseb", "cp-enc-dst.txt").content == body
            # plain source -> encrypted destination on the copy request
            r = c.request("PUT", "/sseb/cp-to-enc.txt", headers={
                "x-amz-copy-source": "/sseb/cp-src.txt",
                "x-amz-server-side-encryption": "AES256",
            })
            assert r.status_code == 200
            assert c.get_object("sseb", "cp-to-enc.txt").content == body
            # SSE-C source: the key travels in the copy-source header
            # family; the destination here is re-encrypted under a
            # DIFFERENT SSE-C key.
            key1, key2 = b"k" * 32, b"m" * 32
            k1b, k2b = base64.b64encode(key1).decode(), base64.b64encode(key2).decode()
            k1md5 = base64.b64encode(hashlib.md5(key1).digest()).decode()
            k2md5 = base64.b64encode(hashlib.md5(key2).digest()).decode()
            r = c.request("PUT", "/sseb/cp-ssec.txt", body=body, headers={
                "x-amz-server-side-encryption-customer-algorithm": "AES256",
                "x-amz-server-side-encryption-customer-key": k1b,
                "x-amz-server-side-encryption-customer-key-md5": k1md5,
            })
            assert r.status_code == 200, r.text
            r = c.request("PUT", "/sseb/cp-ssec-dst.txt", headers={
                "x-amz-copy-source": "/sseb/cp-ssec.txt",
                "x-amz-copy-source-server-side-encryption-customer-algorithm": "AES256",
                "x-amz-copy-source-server-side-encryption-customer-key": k1b,
                "x-amz-copy-source-server-side-encryption-customer-key-md5": k1md5,
                "x-amz-server-side-encryption-customer-algorithm": "AES256",
                "x-amz-server-side-encryption-customer-key": k2b,
                "x-amz-server-side-encryption-customer-key-md5": k2md5,
            })
            assert r.status_code == 200, r.text
            r = c.request("GET", "/sseb/cp-ssec-dst.txt", headers={
                "x-amz-server-side-encryption-customer-algorithm": "AES256",
                "x-amz-server-side-encryption-customer-key": k2b,
                "x-amz-server-side-encryption-customer-key-md5": k2md5,
            })
            assert r.status_code == 200 and r.content == body
            # failed precondition must 412 BEFORE any key-required error
            r = c.request("PUT", "/sseb/cp-pre.txt", headers={
                "x-amz-copy-source": "/sseb/cp-ssec.txt",
                "x-amz-copy-source-if-match": '"not-the-etag"',
            })
            assert r.status_code == 412, r.status_code
            # UploadPartCopy from a compressed source
            import re

            r = c.request("POST", "/sseb/cp-mp.bin", query=[("uploads", "")])
            uid = re.search(r"<UploadId>([^<]+)</UploadId>", r.text).group(1)
            r = c.request("PUT", "/sseb/cp-mp.bin",
                          query=[("uploadId", uid), ("partNumber", "1")],
                          headers={"x-amz-copy-source": "/sseb/cp-src.txt"})
            assert r.status_code == 200, r.text
            et = r.headers.get("ETag", "").strip('"') or re.search(
                r"<ETag>&quot;([^&]+)&quot;</ETag>", r.text
            ).group(1)
            r = c.request(
                "POST", "/sseb/cp-mp.bin", query=[("uploadId", uid)],
                body=(
                    "<CompleteMultipartUpload><Part><PartNumber>1</PartNumber>"
                    f'<ETag>"{et}"</ETag></Part></CompleteMultipartUpload>'
                ).encode(),
            )
            assert r.status_code == 200, r.text
            assert c.get_object("sseb", "cp-mp.bin").content == body
        finally:
            stack["config"].unset("compression", "enable")

    def test_listing_shows_actual_size_of_compressed(self, stack):
        """Sync tools compare listing <Size> against local files; a
        compressed object must list its ACTUAL size, not the stored form
        (the reference's ObjectInfo.GetActualSize in listings)."""
        import re

        c = stack["client"]
        stack["config"].set("compression", "enable", "on")
        try:
            body = b"sizable line\n" * 10000
            c.put_object("sseb", "sz.txt", body)
            r = c.request("GET", "/sseb", query=[("list-type", "2"), ("prefix", "sz.txt")])
            size = int(re.search(r"<Size>(\d+)</Size>", r.text).group(1))
            assert size == len(body), f"listed {size}, actual {len(body)}"
            # versions listing too
            r = c.request("GET", "/sseb", query=[("versions", ""), ("prefix", "sz.txt")])
            size = int(re.search(r"<Size>(\d+)</Size>", r.text).group(1))
            assert size == len(body)
        finally:
            stack["config"].unset("compression", "enable")

    def test_get_object_attributes(self, stack):
        """?attributes must return the metadata document (unquoted ETag,
        logical ObjectSize), not fall through to a body GET."""
        c = stack["client"]
        stack["config"].set("compression", "enable", "on")
        try:
            body = b"attr text\n" * 8000
            c.put_object("sseb", "at.txt", body)
            r = c.request("GET", "/sseb/at.txt", query=[("attributes", "")],
                          headers={"x-amz-object-attributes": "ETag,ObjectSize,StorageClass"})
            assert r.status_code == 200, r.text
            assert b"GetObjectAttributesResponse" in r.content, r.content[:120]
            assert f"<ObjectSize>{len(body)}</ObjectSize>".encode() in r.content
            assert b"<ETag>" in r.content and b"&quot;" not in r.content
            # header required
            r = c.request("GET", "/sseb/at.txt", query=[("attributes", "")])
            assert r.status_code == 400
        finally:
            stack["config"].unset("compression", "enable")

    def test_compression_transparent(self, stack):
        c = stack["client"]
        stack["config"].set("compression", "enable", "on")
        try:
            data = b"compress me please " * 50_000  # ~1 MB, very compressible
            r = c.put_object("sseb", "logs/app.log", data)
            assert r.status_code == 200
            # Stored object is smaller than logical size.
            oi_stored = None
            from minio_tpu.object.types import GetObjectOptions

            hz = stack["hz"]
            oi, raw = hz.layer.get_object("sseb", "logs/app.log")
            assert len(raw) < len(data)
            # API returns original bytes + logical length.
            r = c.get_object("sseb", "logs/app.log")
            assert r.content == data
            assert int(c.head_object("sseb", "logs/app.log").headers["Content-Length"]) == len(data)
            # Ranges on logical bytes.
            r = c.get_object("sseb", "logs/app.log", headers={"Range": "bytes=5-24"})
            assert r.content == data[5:25]
        finally:
            stack["config"].unset("compression", "enable")

    def test_bucket_default_encryption(self, stack):
        c = stack["client"]
        NS = "http://s3.amazonaws.com/doc/2006-03-01/"
        xml = (
            f'<ServerSideEncryptionConfiguration xmlns="{NS}"><Rule>'
            "<ApplyServerSideEncryptionByDefault><SSEAlgorithm>AES256</SSEAlgorithm>"
            "</ApplyServerSideEncryptionByDefault></Rule></ServerSideEncryptionConfiguration>"
        )
        assert c.request("PUT", "/sseb", query=[("encryption", "")], body=xml.encode()).status_code == 200
        try:
            c.put_object("sseb", "auto-enc", b"auto-encrypted-data")
            r = c.get_object("sseb", "auto-enc")
            assert r.content == b"auto-encrypted-data"
            assert r.headers.get("x-amz-server-side-encryption") == "AES256"
        finally:
            c.request("PUT", "/sseb", query=[("encryption", "")], body=b"")
