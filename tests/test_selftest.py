"""Live-cluster self-measurement plane (control/selftest.py) + ops/s ring.

Covers the three probes end to end -- object speedtest with autotuned
concurrency and a scaling-efficiency verdict on a real 2-node cluster,
drive probe through the metered/chaos drive stack, full-mesh netperf --
plus the always-on per-second op-class ring (control/perf.py
OpsTimeSeries): rotation, stale-slot exclusion, cluster merge math, the
/mtpu/admin/v1/timeseries endpoint, and the Prometheus gauges, lint-clean
under tools/metrics_lint.py. The scratch-bucket lifecycle is pinned too:
invisible to ListBuckets, gone after a probe, swept by restart recovery
when a probe dies mid-run.
"""

import importlib.util
import json
import os
import socket
import threading
from pathlib import Path
from types import SimpleNamespace

import pytest

from minio_tpu.api.server import ThreadedServer
from minio_tpu.chaos.disk import FaultyDisk
from minio_tpu.chaos.faults import REGISTRY, FaultSpec
from minio_tpu.control import selftest
from minio_tpu.control.perf import (
    N_BUCKETS,
    OpsTimeSeries,
    merge_timeseries,
    op_class,
    summarize_timeseries,
)
from minio_tpu.dist.node import Node
from minio_tpu.storage import recovery
from minio_tpu.storage.local import LocalDrive
from minio_tpu.storage.metered import MeteredDrive
from minio_tpu.utils import errors
from tests.harness import ErasureHarness
from tests.s3client import S3TestClient

_LINT_PATH = Path(__file__).resolve().parent.parent / "tools" / "metrics_lint.py"
_spec = importlib.util.spec_from_file_location("metrics_lint", _LINT_PATH)
metrics_lint = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(metrics_lint)

ROOT = "selftestadmin"
SECRET = "selftest-secret-key"


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


# ---------------------------------------------------------------------------
# op classes + time-series ring (pure math, injectable clock)
# ---------------------------------------------------------------------------


class TestOpClass:
    def test_mapping(self):
        assert op_class("PutObject") == "put"
        assert op_class("CompleteMultipartUpload") == "put"
        assert op_class("CopyObject") == "put"
        assert op_class("GetObject") == "get"
        assert op_class("HeadBucket") == "get"
        assert op_class("DeleteObject") == "delete"
        assert op_class("AbortMultipartUpload") == "delete"
        assert op_class("ListObjectsV2") == "list"
        assert op_class("WeirdNewApi") == "other"


class TestOpsTimeSeries:
    def test_record_and_snapshot(self):
        ts = OpsTimeSeries(window_s=30)
        t0 = 5000
        for i in range(3):
            ts.record("get", 0.002, ok=True, nbytes=100, now=t0 + i)
        ts.record("put", 0.050, ok=False, nbytes=2048, now=t0)
        snap = ts.snapshot(now=t0 + 2)
        assert [s["t"] for s in snap["series"]] == [t0, t0 + 1, t0 + 2]
        first = snap["series"][0]["classes"]
        assert first["get"]["count"] == 1
        assert first["put"]["errors"] == 1
        assert first["put"]["bytes"] == 2048
        assert len(first["get"]["counts"]) == N_BUCKETS + 1

    def test_ring_rotation_reuses_slot_in_place(self):
        ts = OpsTimeSeries(window_s=10)
        t0 = 9000
        ts.record("get", 0.001, now=t0)
        # t0+10 maps to the SAME ring slot; the stale second must be
        # replaced, not summed into.
        ts.record("put", 0.001, now=t0 + 10)
        snap = ts.snapshot(now=t0 + 10)
        assert [s["t"] for s in snap["series"]] == [t0 + 10]
        classes = snap["series"][0]["classes"]
        assert "put" in classes and "get" not in classes

    def test_snapshot_excludes_seconds_older_than_window(self):
        ts = OpsTimeSeries(window_s=10)
        ts.record("get", 0.001, now=100)
        # Slot survives in the ring, but falls outside the window axis.
        assert ts.snapshot(now=200)["series"] == []

    def test_merge_sums_per_second_per_class(self):
        a = OpsTimeSeries(window_s=20)
        b = OpsTimeSeries(window_s=20)
        for node in (a, b):
            node.record("get", 0.004, nbytes=10, now=700)
        b.record("get", 0.004, nbytes=10, now=701)
        merged = merge_timeseries([a.snapshot(now=701), b.snapshot(now=701)])
        by_t = {s["t"]: s["classes"] for s in merged["series"]}
        assert by_t[700]["get"]["count"] == 2
        assert by_t[700]["get"]["bytes"] == 20
        assert by_t[701]["get"]["count"] == 1

    def test_summarize_reports_p99_ms_and_drops_raw_counts(self):
        ts = OpsTimeSeries(window_s=20)
        for _ in range(100):
            ts.record("get", 0.002, now=800)
        out = summarize_timeseries(ts.snapshot(now=800))
        row = out["series"][0]["classes"]["get"]
        assert row["count"] == 100
        assert "counts" not in row
        # log2 bucket upper edge containing 2 ms.
        assert 2.0 <= row["p99_ms"] <= 4.1

    def test_rates_trailing_horizon(self):
        ts = OpsTimeSeries(window_s=60)
        t0 = 2000
        for i in range(10):
            ts.record("put", 0.001, nbytes=1000, now=t0 + i)
        r = ts.rates(horizon_s=10, now=t0 + 9)
        assert r["put"]["ops_per_s"] == 1.0
        assert r["put"]["bytes_per_s"] == 1000.0

    def test_window_knob(self, monkeypatch):
        monkeypatch.setenv("MTPU_TIMESERIES_WINDOW_S", "45")
        assert OpsTimeSeries().window_s == 45


# ---------------------------------------------------------------------------
# autotune (fake target: no storage in the loop)
# ---------------------------------------------------------------------------


class TestAutotune:
    def test_converges_on_knee(self):
        curve = {1: 10.0, 2: 20.0, 4: 40.0, 8: 80.0, 16: 81.0, 32: 300.0}
        calls = []

        def fake(c):
            calls.append(c)
            return {"score": curve[c]}

        best, ramp = selftest.autotune(fake, start=1, max_concurrency=32)
        # 16 fails the 2.5% bar over 8: the ramp stops there and never
        # pays for 32, even though 32 would have scored higher.
        assert best["concurrency"] == 8
        assert calls == [1, 2, 4, 8, 16]
        assert [r["concurrency"] for r in ramp] == calls

    def test_respects_ceiling(self):
        best, ramp = selftest.autotune(
            lambda c: {"score": float(c)}, start=4, max_concurrency=16
        )
        assert best["concurrency"] == 16
        assert [r["concurrency"] for r in ramp] == [4, 8, 16]

    def test_single_step_when_flat(self):
        best, ramp = selftest.autotune(
            lambda c: {"score": 100.0}, start=4, max_concurrency=64
        )
        assert best["concurrency"] == 4
        assert len(ramp) == 2  # first step + the one that failed the bar


# ---------------------------------------------------------------------------
# drive probe through the production drive stack
# ---------------------------------------------------------------------------


class TestDriveProbe:
    def test_probe_through_metered_stack(self, tmp_path):
        h = ErasureHarness(tmp_path, n_disks=4)
        drives = {d: MeteredDrive(LocalDrive(d)) for d in h.dirs[:2]}
        out = selftest.drive_probe(drives, size=1 << 16, files=2, rand_reads=4)
        assert out["ok"] and out["probe"] == "drive"
        assert set(out["drives"]) == set(h.dirs[:2])
        for row in out["drives"].values():
            assert row["seq_write_bytes_per_s"] > 0
            assert row["seq_read_bytes_per_s"] > 0
            assert row["rand_read_iops"] > 0
        # The metered wrapper saw the probe's IO: results price the real
        # request path, not the bare device.
        lats = next(iter(drives.values())).api_latencies()
        assert sum(v["count"] for v in lats.values()) > 0
        # Scratch volume removed from every probed drive.
        for d in h.dirs[:2]:
            assert not os.path.isdir(os.path.join(d, selftest.SCRATCH_BUCKET))

    def test_armed_chaos_fails_probe_not_node(self, tmp_path):
        h = ErasureHarness(tmp_path, n_disks=4)
        path = h.dirs[0]
        stack = MeteredDrive(FaultyDisk(LocalDrive(path)))
        fid = REGISTRY.arm(FaultSpec(kind="drive-error", ops=("create_file",)))
        try:
            out = selftest.drive_probe({path: stack}, size=1 << 14, files=1, rand_reads=1)
        finally:
            REGISTRY.disarm(fid)
        # The probe REPORTS the fault instead of raising out of the admin
        # handler: node up, report says which drive is sick.
        assert out["ok"] is False
        row = out["drives"][path]
        assert row["ok"] is False and "FaultyDisk" in row["error"]
        # ...and the drive still works once the fault is disarmed.
        out2 = selftest.drive_probe({path: stack}, size=1 << 14, files=1, rand_reads=1)
        assert out2["ok"] is True


# ---------------------------------------------------------------------------
# scratch-bucket lifecycle: hidden, cleaned, swept on restart
# ---------------------------------------------------------------------------


class TestScratchLifecycle:
    def test_recovery_constant_matches(self):
        # storage/recovery.py keeps its own literal to avoid importing the
        # control plane; the two must never drift.
        assert recovery._SELFTEST_BUCKET == selftest.SCRATCH_BUCKET

    def test_hidden_from_list_buckets(self, tmp_path):
        h = ErasureHarness(tmp_path, n_disks=4)
        selftest.ensure_scratch_bucket(h.layer)
        assert selftest.SCRATCH_BUCKET not in [b.name for b in h.layer.list_buckets()]
        selftest.cleanup_scratch(h.layer)

    def test_aborted_probe_debris_swept_by_recovery(self, tmp_path):
        h = ErasureHarness(tmp_path, n_disks=4)
        # Simulate a probe that died mid-round: scratch bucket + objects
        # on disk, nobody left to clean them.
        selftest.ensure_scratch_bucket(h.layer)
        h.layer.put_object(selftest.SCRATCH_BUCKET, "probe/dead/x", b"y" * 4096)
        assert os.path.isdir(os.path.join(h.dirs[0], selftest.SCRATCH_BUCKET))
        before = recovery.counters()["selftest_debris"]
        for d in h.dirs:
            recovery.recover_drive(LocalDrive(d))
        for d in h.dirs:
            assert not os.path.isdir(os.path.join(d, selftest.SCRATCH_BUCKET))
        assert recovery.counters()["selftest_debris"] == before + len(h.dirs)

    def test_completed_speedtest_leaves_no_debris(self, tmp_path):
        h = ErasureHarness(tmp_path, n_disks=4)
        res = selftest.object_speedtest(
            h.layer, peers=[], node_url="n", size=1 << 14, start=2, max_concurrency=2
        )
        assert res["ok"]
        for d in h.dirs:
            assert not os.path.isdir(os.path.join(d, selftest.SCRATCH_BUCKET))


# ---------------------------------------------------------------------------
# 2-node cluster: admin endpoints, peer fan-out, merged time series
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("selftest-cluster")
    ports = [_free_port(), _free_port()]
    urls = [f"http://127.0.0.1:{p}" for p in ports]
    endpoints = []
    for ni in range(2):
        for di in range(4):
            endpoints.append(f"{urls[ni]}{tmp}/n{ni}d{di}")
    nodes = [
        Node(endpoints, url=urls[ni], root_user=ROOT, root_password=SECRET,
             set_drive_count=8)
        for ni in range(2)
    ]
    servers = []
    for ni, node in enumerate(nodes):
        ts = ThreadedServer(SimpleNamespace(app=node.make_app()), port=ports[ni])
        ts.start()
        servers.append(ts)
    threads = [threading.Thread(target=n.build) for n in nodes]
    for t in threads:
        t.start()
    for t in threads:
        t.join(60)
    assert all(n.pools is not None for n in nodes), "cluster failed to build"
    clients = [S3TestClient(urls[ni], ROOT, SECRET) for ni in range(2)]
    clients[0].make_bucket("stbkt")
    yield {"nodes": nodes, "clients": clients, "urls": urls}
    for ts in servers:
        ts.stop()


class TestClusterSelfTest:
    def _post(self, cluster, path, doc=None):
        return cluster["clients"][0].request(
            "POST", path, body=json.dumps(doc or {}).encode()
        )

    def test_object_speedtest_per_node_aggregate_and_verdict(self, cluster):
        r = self._post(
            cluster,
            "/mtpu/admin/v1/speedtest/object",
            {"size": 1 << 14, "concurrency": 2, "max_concurrency": 2},
        )
        assert r.status_code == 200, r.text
        doc = r.json()
        assert doc["ok"] is True
        # Per-node results keyed by node URL: the coordinator plus the peer
        # both drove load.
        for url in cluster["urls"]:
            row = doc["nodes"][url]
            assert row["ok"] and row["put_gibs"] >= 0 and row["put_ops_per_s"] > 0
        agg = doc["aggregate"]
        assert agg["put_gibs"] > 0 and agg["get_gibs"] > 0
        assert agg["total_ops_per_s"] > 0
        sc = doc["scaling"]
        assert sc["nodes"] == 2
        assert 0.0 < sc["efficiency"] <= 1.0 + 1e-9
        assert sc["verdict"] in ("linear", "sublinear", "poor")
        assert doc["ramp"], "autotune ramp missing"
        # GET re-serves the stored report without re-running.
        r2 = cluster["clients"][0].request("GET", "/mtpu/admin/v1/speedtest/object")
        assert r2.status_code == 200
        assert r2.json()["finished_at"] == doc["finished_at"]

    def test_object_speedtest_leaves_no_scratch(self, cluster):
        # After the run above: invisible via S3, gone from every drive on
        # disk, and gone at the layer (modulo the 2 s bucket-info TTL cache,
        # which we drop explicitly -- the probe bypasses the S3 surface, so
        # peers may serve stale info for one TTL).
        r = cluster["clients"][0].request("GET", "/")
        assert selftest.SCRATCH_BUCKET not in r.text
        for node in cluster["nodes"]:
            for path in node.local_drives:
                assert not os.path.isdir(
                    os.path.join(path, selftest.SCRATCH_BUCKET)
                )
            node.pools.pools[0].invalidate_bucket_cache()
            with pytest.raises(errors.StorageError):
                node.pools.get_bucket_info(selftest.SCRATCH_BUCKET)

    def test_netperf_full_mesh_matrix(self, cluster):
        r = self._post(
            cluster, "/mtpu/admin/v1/speedtest/net", {"size": 1 << 16, "rounds": 2}
        )
        assert r.status_code == 200, r.text
        doc = r.json()
        assert doc["ok"] is True
        u0, u1 = cluster["urls"]
        matrix = doc["matrix"]
        # Symmetry: each node has a row, each row targets the OTHER node.
        assert set(matrix) == {u0, u1}
        assert set(matrix[u0]) == {u1}
        assert set(matrix[u1]) == {u0}
        for row in matrix.values():
            for cell in row.values():
                assert cell["ok"] and cell["bytes_per_s"] > 0
                assert cell["rtt_ms"] >= 0

    def test_drive_probe_keyed_by_drive_path(self, cluster):
        r = self._post(
            cluster,
            "/mtpu/admin/v1/speedtest/drive",
            {"size": 1 << 14, "files": 1, "rand_reads": 2},
        )
        assert r.status_code == 200, r.text
        doc = r.json()
        assert doc["ok"] is True
        drives = doc["drives"]
        assert len(drives) == 4  # node 0's local drives
        for path, row in drives.items():
            assert "/n0d" in path
            assert row["ok"] and row["seq_write_bytes_per_s"] > 0

    def test_timeseries_cluster_merge(self, cluster):
        # Drive S3 traffic through BOTH nodes so each ring has data.
        for ci, client in enumerate(cluster["clients"]):
            assert client.put_object("stbkt", f"ts-{ci}", b"z" * 4096).status_code == 200
            assert client.get_object("stbkt", f"ts-{ci}").status_code == 200
        r = cluster["clients"][0].request(
            "GET", "/mtpu/admin/v1/timeseries", query=[("cluster", "1")]
        )
        assert r.status_code == 200, r.text
        doc = r.json()
        assert doc["window_s"] >= 10
        # The merged view saw both classes, and the peer answered.
        merged_classes = {
            cls for s in doc["cluster"]["series"] for cls in s["classes"]
        }
        assert {"put", "get"} <= merged_classes
        peer_url = cluster["urls"][1]
        assert doc["peers"][peer_url]["ok"] is True
        # Per-second rows carry the full schema, raw bucket arrays do not
        # ride the wire.
        row = doc["cluster"]["series"][-1]["classes"]
        for cell in row.values():
            assert {"count", "errors", "bytes", "p99_ms"} <= set(cell)
            assert "counts" not in cell
        # Cluster merge is a superset of (or equal to) the local view.
        local_total = sum(
            c["count"] for s in doc["node"]["series"] for c in s["classes"].values()
        )
        merged_total = sum(
            c["count"] for s in doc["cluster"]["series"] for c in s["classes"].values()
        )
        assert merged_total >= local_total

    def test_metrics_exposition_lint_clean_with_ops_family(self, cluster):
        r = cluster["clients"][0].request("GET", "/minio/v2/metrics/node")
        assert r.status_code == 200
        text = r.text
        assert metrics_lint.validate_exposition(text) == []
        assert metrics_lint.lint_exposition(text) == []
        assert "minio_tpu_ops_per_second" in text
        assert "minio_tpu_op_errors_per_second" in text
        assert "minio_tpu_selftest_runs_total" in text
        # The probes above ran on this process: counters moved.
        runs = {
            lbls.get("probe"): v
            for _ln, name, lbls, v in metrics_lint.parse_samples(text)
            if name == "minio_tpu_selftest_runs_total"
        }
        assert runs.get("object", 0) >= 1
        assert runs.get("net", 0) >= 1
        assert runs.get("drive", 0) >= 1

    def test_probe_ledger_attribution(self, cluster):
        # Probes are attributable in /perf: ("selftest", ...) stage rows.
        r = cluster["clients"][0].request("GET", "/mtpu/admin/v1/perf")
        assert r.status_code == 200
        rows = r.json()["node"]["stages"].get("selftest", {})
        assert "object-put" in rows and rows["object-put"]["count"] >= 1
        assert "net-stream" in rows


# ---------------------------------------------------------------------------
# selftest_gate (CI leg)
# ---------------------------------------------------------------------------


class TestSelftestGate:
    def _gate(self):
        spec = importlib.util.spec_from_file_location(
            "selftest_gate",
            Path(__file__).resolve().parent.parent / "tools" / "selftest_gate.py",
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def test_ok_and_floor_violations(self):
        gate = self._gate()
        speedtest = {
            "ok": True,
            "aggregate": {"put_gibs": 0.5},
            "scaling": {"nodes": 2, "efficiency": 0.9, "verdict": "linear"},
        }
        bench = {"putobject_gibs": 1.0}
        assert gate.findings(speedtest, bench) == []
        # Live throughput collapsed below the factor.
        slow = dict(speedtest, aggregate={"put_gibs": 0.01})
        kinds = [f["kind"] for f in gate.findings(slow, bench)]
        assert kinds == ["throughput-floor"]
        # Nodes that add nothing: efficiency floor (N>1 only).
        flat = dict(speedtest,
                    scaling={"nodes": 2, "efficiency": 0.2, "verdict": "poor"})
        kinds = [f["kind"] for f in gate.findings(flat, bench)]
        assert kinds == ["efficiency-floor"]
        single = dict(speedtest,
                      scaling={"nodes": 1, "efficiency": 0.2, "verdict": "poor"})
        assert gate.findings(single, bench) == []

    def test_failed_probe_blocks(self):
        gate = self._gate()
        bad = {"ok": False, "nodes": {"http://n1": {"ok": False, "error": "x"}},
               "aggregate": {"put_gibs": 9.9}}
        kinds = [f["kind"] for f in gate.findings(bad, {"putobject_gibs": 0.1})]
        assert kinds == ["probe-failed"]

    def test_main_last_json_line_contract(self, tmp_path):
        gate = self._gate()
        st = tmp_path / "SPEEDTEST_x.json"
        st.write_text(
            "noise\n"
            + json.dumps({
                "ok": True,
                "aggregate": {"put_gibs": 0.5},
                "scaling": {"nodes": 2, "efficiency": 0.9, "verdict": "linear"},
            })
            + "\n"
        )
        be = tmp_path / "BENCH_x.json"
        be.write_text(json.dumps({"putobject_gibs": 1.0}) + "\n")
        assert gate.main([str(st), str(be)]) == 0
        assert gate.main([str(st), str(be), "--factor=2.0"]) == 1
        be.write_text("not json\n")
        assert gate.main([str(st), str(be)]) == 2
