"""Object tagging, object lock (retention / legal hold), WORM enforcement.

Mirrors the reference's object-lock tests (internal/bucket/object/lock) and
the API-level tagging/retention handler behavior.
"""

import datetime

import pytest

from minio_tpu.control import objectlock as ol
from minio_tpu.api.errors import S3Error


@pytest.fixture(scope="module")
def http_stack(tmp_path_factory):
    from minio_tpu.api.server import S3Server, ThreadedServer
    from minio_tpu.control.iam import IAMSys
    from minio_tpu.object.pools import ServerPools
    from minio_tpu.object.sets import ErasureSets
    from tests.harness import ErasureHarness
    from tests.s3client import S3TestClient

    tmp = tmp_path_factory.mktemp("olock")
    hz = ErasureHarness(tmp, n_disks=8)
    layer = ServerPools([ErasureSets([d for d in hz.drives], 8)])
    iam = IAMSys("lockak", "lock-secret")
    srv = S3Server(layer, iam, check_skew=False)
    ts = ThreadedServer(srv)
    endpoint = ts.start()
    client = S3TestClient(endpoint, "lockak", "lock-secret")
    yield {"client": client, "iam": iam}
    ts.stop()


def _future(days=1):
    return ol.format_iso(
        datetime.datetime.now(datetime.timezone.utc) + datetime.timedelta(days=days)
    )


# ---------------------------------------------------------------- unit level


class TestLockConfig:
    def test_parse_enabled(self):
        cfg = ol.LockConfig.from_xml(
            "<ObjectLockConfiguration><ObjectLockEnabled>Enabled</ObjectLockEnabled>"
            "</ObjectLockConfiguration>"
        )
        assert cfg.enabled and cfg.default is None

    def test_parse_default_retention(self):
        cfg = ol.LockConfig.from_xml(
            "<ObjectLockConfiguration><ObjectLockEnabled>Enabled</ObjectLockEnabled>"
            "<Rule><DefaultRetention><Mode>GOVERNANCE</Mode><Days>30</Days>"
            "</DefaultRetention></Rule></ObjectLockConfiguration>"
        )
        assert cfg.default.mode == "GOVERNANCE" and cfg.default.days == 30
        meta = cfg.default_retention_meta(0.0)
        assert meta[ol.META_MODE] == "GOVERNANCE"
        assert meta[ol.META_RETAIN_UNTIL].startswith("1970-01-31")

    def test_days_and_years_rejected(self):
        with pytest.raises(S3Error):
            ol.LockConfig.from_xml(
                "<ObjectLockConfiguration><ObjectLockEnabled>Enabled</ObjectLockEnabled>"
                "<Rule><DefaultRetention><Mode>GOVERNANCE</Mode><Days>1</Days>"
                "<Years>1</Years></DefaultRetention></Rule></ObjectLockConfiguration>"
            )

    def test_delete_checks(self):
        future = _future()
        compliance = {ol.META_MODE: "COMPLIANCE", ol.META_RETAIN_UNTIL: future}
        governance = {ol.META_MODE: "GOVERNANCE", ol.META_RETAIN_UNTIL: future}
        hold = {ol.META_LEGAL_HOLD: "ON"}
        expired = {ol.META_MODE: "COMPLIANCE", ol.META_RETAIN_UNTIL: "2000-01-01T00:00:00Z"}
        with pytest.raises(S3Error):
            ol.check_delete_allowed(compliance, True, True)
        with pytest.raises(S3Error):
            ol.check_delete_allowed(governance, False, False)
        with pytest.raises(S3Error):
            ol.check_delete_allowed(governance, True, False)  # header but no perm
        ol.check_delete_allowed(governance, True, True)  # bypass ok
        with pytest.raises(S3Error):
            ol.check_delete_allowed(hold, True, True)
        ol.check_delete_allowed(expired, False, False)

    def test_retention_tighten(self):
        future = _future(1)
        later = _future(10)
        old = ol.LockState("GOVERNANCE", future, "")
        ol.check_retention_tighten(old, "GOVERNANCE", later, False, False)  # extend ok
        with pytest.raises(S3Error):
            ol.check_retention_tighten(old, "GOVERNANCE", "2000-01-01T00:00:00Z", False, False)
        old_c = ol.LockState("COMPLIANCE", later, "")
        with pytest.raises(S3Error):
            ol.check_retention_tighten(old_c, "COMPLIANCE", future, True, True)


# ----------------------------------------------------------------- HTTP e2e


class TestTaggingE2E:
    def test_tagging_crud(self, http_stack):
        c = http_stack["client"]
        c.make_bucket("tagbkt")
        c.put_object("tagbkt", "obj", b"data")
        body = (
            "<Tagging><TagSet>"
            "<Tag><Key>env</Key><Value>prod</Value></Tag>"
            "<Tag><Key>team</Key><Value>storage</Value></Tag>"
            "</TagSet></Tagging>"
        ).encode()
        r = c.request("PUT", "/tagbkt/obj", query=[("tagging", "")], body=body)
        assert r.status_code == 200, r.text
        r = c.request("GET", "/tagbkt/obj", query=[("tagging", "")])
        assert r.status_code == 200
        assert "<Key>env</Key>" in r.text and "<Value>prod</Value>" in r.text
        # tag count header on GET object
        r = c.get_object("tagbkt", "obj")
        assert r.headers.get("x-amz-tagging-count") == "2"
        r = c.request("DELETE", "/tagbkt/obj", query=[("tagging", "")])
        assert r.status_code == 204
        r = c.request("GET", "/tagbkt/obj", query=[("tagging", "")])
        assert "<Tag>" not in r.text

    def test_tagging_header_on_put(self, http_stack):
        c = http_stack["client"]
        c.make_bucket("tagbkt2")
        c.put_object("tagbkt2", "o2", b"x", headers={"x-amz-tagging": "a=1&b=2"})
        r = c.request("GET", "/tagbkt2/o2", query=[("tagging", "")])
        assert "<Key>a</Key>" in r.text

    def test_too_many_tags(self, http_stack):
        c = http_stack["client"]
        tags = "&".join(f"k{i}={i}" for i in range(11))
        r = c.put_object("tagbkt2", "o3", b"x", headers={"x-amz-tagging": tags})
        assert r.status_code == 400


class TestObjectLockE2E:
    def test_lock_bucket_creation(self, http_stack):
        c = http_stack["client"]
        r = c.request(
            "PUT", "/lockbkt", headers={"x-amz-bucket-object-lock-enabled": "true"}
        )
        assert r.status_code == 200
        r = c.request("GET", "/lockbkt", query=[("object-lock", "")])
        assert "Enabled" in r.text
        r = c.request("GET", "/lockbkt", query=[("versioning", "")])
        assert "Enabled" in r.text

    def test_retention_on_unlocked_bucket_rejected(self, http_stack):
        c = http_stack["client"]
        c.make_bucket("plainbkt")
        c.put_object("plainbkt", "o", b"x")
        body = f"<Retention><Mode>GOVERNANCE</Mode><RetainUntilDate>{_future()}</RetainUntilDate></Retention>".encode()
        r = c.request("PUT", "/plainbkt/o", query=[("retention", "")], body=body)
        assert r.status_code == 400

    def test_retention_and_delete_protection(self, http_stack):
        c = http_stack["client"]
        r = c.put_object("lockbkt", "held", b"precious")
        vid = r.headers.get("x-amz-version-id", "")
        assert vid
        body = f"<Retention><Mode>COMPLIANCE</Mode><RetainUntilDate>{_future()}</RetainUntilDate></Retention>".encode()
        r = c.request("PUT", "/lockbkt/held", query=[("retention", "")], body=body)
        assert r.status_code == 200, r.text
        r = c.request("GET", "/lockbkt/held", query=[("retention", "")])
        assert "<Mode>COMPLIANCE</Mode>" in r.text
        # deleting the locked version is denied (root has bypass permission,
        # but COMPLIANCE can never be bypassed)
        r = c.delete_object("lockbkt", "held", query=[("versionId", vid)])
        assert r.status_code == 403
        # delete marker (no versionId) is still allowed
        r = c.delete_object("lockbkt", "held")
        assert r.status_code == 204

    def test_governance_bypass(self, http_stack):
        c = http_stack["client"]
        r = c.put_object("lockbkt", "gov", b"guarded")
        vid = r.headers["x-amz-version-id"]
        body = f"<Retention><Mode>GOVERNANCE</Mode><RetainUntilDate>{_future()}</RetainUntilDate></Retention>".encode()
        assert c.request("PUT", "/lockbkt/gov", query=[("retention", "")], body=body).status_code == 200
        # without bypass header: denied
        r = c.delete_object("lockbkt", "gov", query=[("versionId", vid)])
        assert r.status_code == 403
        # with bypass header (root is allowed everything): succeeds
        r = c.request(
            "DELETE", "/lockbkt/gov", query=[("versionId", vid)],
            headers={"x-amz-bypass-governance-retention": "true"},
        )
        assert r.status_code == 204, r.text

    def test_legal_hold(self, http_stack):
        c = http_stack["client"]
        r = c.put_object("lockbkt", "lh", b"on hold")
        vid = r.headers["x-amz-version-id"]
        r = c.request(
            "PUT", "/lockbkt/lh", query=[("legal-hold", "")],
            body=b"<LegalHold><Status>ON</Status></LegalHold>",
        )
        assert r.status_code == 200, r.text
        r = c.request("GET", "/lockbkt/lh", query=[("legal-hold", "")])
        assert "<Status>ON</Status>" in r.text
        r = c.request(
            "DELETE", "/lockbkt/lh", query=[("versionId", vid)],
            headers={"x-amz-bypass-governance-retention": "true"},
        )
        assert r.status_code == 403  # legal hold ignores governance bypass
        r = c.request(
            "PUT", "/lockbkt/lh", query=[("legal-hold", "")],
            body=b"<LegalHold><Status>OFF</Status></LegalHold>",
        )
        assert r.status_code == 200
        r = c.delete_object("lockbkt", "lh", query=[("versionId", vid)])
        assert r.status_code == 204

    def test_lock_headers_on_put(self, http_stack):
        c = http_stack["client"]
        until = _future()
        r = c.put_object(
            "lockbkt", "hdr", b"x",
            headers={
                "x-amz-object-lock-mode": "GOVERNANCE",
                "x-amz-object-lock-retain-until-date": until,
            },
        )
        assert r.status_code == 200, r.text
        g = c.head_object("lockbkt", "hdr")
        assert g.headers.get("x-amz-object-lock-mode") == "GOVERNANCE"
        r = c.request("GET", "/lockbkt/hdr", query=[("retention", "")])
        assert "<Mode>GOVERNANCE</Mode>" in r.text

    def test_default_retention_applied(self, http_stack):
        c = http_stack["client"]
        cfg = (
            "<ObjectLockConfiguration><ObjectLockEnabled>Enabled</ObjectLockEnabled>"
            "<Rule><DefaultRetention><Mode>GOVERNANCE</Mode><Days>1</Days>"
            "</DefaultRetention></Rule></ObjectLockConfiguration>"
        ).encode()
        r = c.request("PUT", "/lockbkt", query=[("object-lock", "")], body=cfg)
        assert r.status_code == 200
        r = c.put_object("lockbkt", "defret", b"x")
        assert r.status_code == 200
        g = c.head_object("lockbkt", "defret")
        assert g.headers.get("x-amz-object-lock-mode") == "GOVERNANCE"
        assert g.headers.get("x-amz-object-lock-retain-until-date", "")


class TestLockHardening:
    """Regressions: bulk-delete WORM bypass, versioning-suspend invariant,
    PUT-header date validation, governance-to-compliance tighten."""

    def test_bulk_delete_respects_lock(self, http_stack):
        c = http_stack["client"]
        r = c.request("PUT", "/bulklock", headers={"x-amz-bucket-object-lock-enabled": "true"})
        assert r.status_code == 200
        r = c.put_object("bulklock", "locked", b"keep")
        vid = r.headers["x-amz-version-id"]
        body = (
            f"<Retention><Mode>COMPLIANCE</Mode><RetainUntilDate>{_future()}"
            "</RetainUntilDate></Retention>"
        ).encode()
        assert c.request("PUT", "/bulklock/locked", query=[("retention", "")], body=body).status_code == 200
        # bulk delete names the locked version explicitly
        del_xml = (
            f"<Delete><Object><Key>locked</Key><VersionId>{vid}</VersionId></Object></Delete>"
        ).encode()
        r = c.request("POST", "/bulklock", query=[("delete", "")], body=del_xml)
        assert r.status_code == 200
        assert "<Error>" in r.text and "AccessDenied" in r.text
        # version still present
        g = c.get_object("bulklock", "locked", query=[("versionId", vid)])
        assert g.status_code == 200 and g.content == b"keep"

    def test_versioning_suspend_rejected_on_lock_bucket(self, http_stack):
        c = http_stack["client"]
        r = c.request(
            "PUT", "/bulklock",
            query=[("versioning", "")],
            body=b"<VersioningConfiguration><Status>Suspended</Status></VersioningConfiguration>",
        )
        assert r.status_code == 409
        assert "InvalidBucketState" in r.text

    def test_object_lock_config_requires_versioning(self, http_stack):
        c = http_stack["client"]
        c.make_bucket("unvers")
        cfg = (
            "<ObjectLockConfiguration><ObjectLockEnabled>Enabled</ObjectLockEnabled>"
            "</ObjectLockConfiguration>"
        ).encode()
        r = c.request("PUT", "/unvers", query=[("object-lock", "")], body=cfg)
        assert r.status_code == 409

    def test_put_header_bad_date_rejected(self, http_stack):
        c = http_stack["client"]
        r = c.put_object(
            "bulklock", "bad", b"x",
            headers={
                "x-amz-object-lock-mode": "GOVERNANCE",
                "x-amz-object-lock-retain-until-date": "garbage",
            },
        )
        assert r.status_code == 400
        r = c.put_object(
            "bulklock", "bad", b"x",
            headers={
                "x-amz-object-lock-mode": "GOVERNANCE",
                "x-amz-object-lock-retain-until-date": "2001-01-01T00:00:00Z",
            },
        )
        assert r.status_code == 400

    def test_governance_to_compliance_tighten_allowed(self, http_stack):
        c = http_stack["client"]
        r = c.put_object("bulklock", "tighten", b"x")
        body = (
            f"<Retention><Mode>GOVERNANCE</Mode><RetainUntilDate>{_future(1)}"
            "</RetainUntilDate></Retention>"
        ).encode()
        assert c.request("PUT", "/bulklock/tighten", query=[("retention", "")], body=body).status_code == 200
        body = (
            f"<Retention><Mode>COMPLIANCE</Mode><RetainUntilDate>{_future(2)}"
            "</RetainUntilDate></Retention>"
        ).encode()
        r = c.request("PUT", "/bulklock/tighten", query=[("retention", "")], body=body)
        assert r.status_code == 200, r.text
