"""IAM persistence: sealed at rest, temp-cred-preserving reloads, and
degraded-store safety (iam-object-store.go role)."""

import json

import pytest

from minio_tpu.control.iam import IAMSys
from minio_tpu.utils import errors


class DictStore:
    def __init__(self):
        self.blobs: dict[str, bytes] = {}

    def put(self, path, data):
        self.blobs[path] = bytes(data)

    def get(self, path):
        return self.blobs.get(path)


class QuorumLostStore(DictStore):
    def get(self, path):
        raise errors.ErasureReadQuorum("meta", path)


class TestIamStore:
    def test_sealed_at_rest_and_reload(self):
        store = DictStore()
        iam = IAMSys("rootak", "root-secret-key", store=store)
        iam.add_user("alice", "alice-secret-12", ["readonly"])
        blob = store.blobs["config/iam/users.json"]
        # Secrets must not be recoverable from the raw stored bytes.
        assert b"alice-secret-12" not in blob
        assert blob.startswith(b"MTPUIAM1")
        fresh = IAMSys("rootak", "root-secret-key", store=store)
        fresh.load()
        assert fresh.lookup("alice").secret_key == "alice-secret-12"

    def test_wrong_root_credential_fails_closed(self):
        store = DictStore()
        IAMSys("rootak", "root-secret-key", store=store).add_user("u", "s" * 12)
        other = IAMSys("rootak", "DIFFERENT-root-key", store=store)
        with pytest.raises(errors.FileCorrupt):
            other.load()

    def test_plaintext_legacy_blob_still_loads(self):
        store = DictStore()
        legacy = {"old": {"accessKey": "old", "secretKey": "oldsecret1234",
                          "status": "enabled", "policies": [], "groups": [],
                          "parentUser": "", "sessionPolicy": None, "expiration": 0.0}}
        store.blobs["config/iam/users.json"] = json.dumps(legacy).encode()
        iam = IAMSys("rootak", "root-secret-key", store=store)
        iam.load()
        assert iam.lookup("old") is not None
        iam.add_user("new", "newsecret1234")  # next persist re-seals
        assert store.blobs["config/iam/users.json"].startswith(b"MTPUIAM1")

    def test_reload_preserves_unexpired_temp_credentials(self):
        store = DictStore()
        iam = IAMSys("rootak", "root-secret-key", store=store)
        iam.add_user("perm", "permsecret123")
        creds, _ = iam.new_sts_credentials("perm", 3600)
        # STS creds are memory-only: not in the stored blob...
        fresh = IAMSys("rootak", "root-secret-key", store=store)
        fresh.load()
        assert fresh.lookup(creds.access_key) is None
        # ...but a RELOAD on the issuing node must keep the live session.
        iam.load()
        assert iam.lookup(creds.access_key) is not None
        assert iam.lookup("perm") is not None

    def test_quorum_failure_is_not_an_empty_store(self):
        iam = IAMSys("rootak", "root-secret-key", store=QuorumLostStore())
        with pytest.raises(errors.StorageError):
            iam.load()  # callers (node boot) disable persistence on this

    def test_groups_policy_resolution_and_persistence(self):
        store = DictStore()
        iam = IAMSys("rootak", "root-secret-key", store=store)
        iam.set_policy("grp-read", {
            "Version": "2012-10-17",
            "Statement": [{"Effect": "Allow", "Action": ["s3:GetObject"],
                           "Resource": ["arn:aws:s3:::b/*"]}],
        })
        iam.add_user("member1", "membersecret1")
        iam.update_group_members("devs", ["member1"])
        iam.attach_group_policy("devs", ["grp-read"])
        # membership grants the group's policy...
        assert iam.is_allowed("member1", "s3:GetObject", "arn:aws:s3:::b/x")
        assert not iam.is_allowed("member1", "s3:PutObject", "arn:aws:s3:::b/x")
        # ...a disabled group stops granting...
        iam.set_group_status("devs", "disabled")
        assert not iam.is_allowed("member1", "s3:GetObject", "arn:aws:s3:::b/x")
        iam.set_group_status("devs", "enabled")
        # ...service accounts under the member inherit via the parent...
        sa = iam.new_service_account("member1")
        assert iam.is_allowed(sa.access_key, "s3:GetObject", "arn:aws:s3:::b/x")
        # ...and everything survives a reload.
        fresh = IAMSys("rootak", "root-secret-key", store=store)
        fresh.load()
        assert fresh.is_allowed("member1", "s3:GetObject", "arn:aws:s3:::b/x")
        assert fresh.groups["devs"]["members"] == ["member1"]
        # member removal revokes; empty group deletes; non-empty refuses
        iam.update_group_members("devs", ["member1"], remove=True)
        assert not iam.is_allowed("member1", "s3:GetObject", "arn:aws:s3:::b/x")
        iam.remove_group("devs")
        assert "devs" not in iam.groups

    def test_user_delete_leaves_no_group_ghost(self):
        iam = IAMSys("rootak", "root-secret-key", store=DictStore())
        iam.add_user("ghost", "ghostsecret12")
        iam.update_group_members("ops", ["ghost"])
        iam.remove_user("ghost")
        assert iam.groups["ops"]["members"] == []

    def test_mutation_refreshes_from_store_under_lock(self):
        # Two IAMSys instances sharing one store (two "nodes"): a mutation
        # on B must not clobber A's earlier write, because the cluster-lock
        # path reloads before persisting.
        from minio_tpu.dist.locks import NamespaceLock

        store = DictStore()
        lock = NamespaceLock()
        a = IAMSys("rootak", "root-secret-key", store=store)
        b = IAMSys("rootak", "root-secret-key", store=store)
        a.ns_lock = b.ns_lock = lock
        a.add_user("from-a", "secretaaaa123")
        b.add_user("from-b", "secretbbbb123")
        fresh = IAMSys("rootak", "root-secret-key", store=store)
        fresh.load()
        assert fresh.lookup("from-a") is not None, "A's user clobbered by B's snapshot"
        assert fresh.lookup("from-b") is not None
