"""Native C++ kernel tests: bit-exactness vs numpy oracles + golden vectors."""

import numpy as np
import pytest
import xxhash

from minio_tpu.ops import highwayhash as hh
from minio_tpu.ops import native, rs_matrix, rs_ref
from tests.golden_rs import GOLDEN

pytestmark = pytest.mark.skipif(not native.available(), reason="no native toolchain")

TESTDATA = bytes(range(256))


@pytest.mark.parametrize("geometry", [(2, 2), (5, 4), (12, 3), (14, 1)])
def test_native_rs_golden(geometry):
    k, m = geometry
    shards = rs_matrix.split(TESTDATA, k)
    parity = native.rs_encode(shards, rs_matrix.parity_matrix(k, m))
    enc = np.concatenate([shards, parity], axis=0)
    h = xxhash.xxh64()
    for i in range(k + m):
        h.update(bytes([i]))
        h.update(enc[i].tobytes())
    assert h.intdigest() == GOLDEN[geometry]


def test_native_rs_reconstruct():
    k, m = 12, 4
    rng = np.random.default_rng(0)
    data = rng.integers(0, 256, (k, 1024)).astype(np.uint8)
    full = rs_ref.encode(data, m)
    present = tuple(i not in (0, 5, 13) for i in range(k + m))
    survivors = np.stack([full[i] for i in range(k + m) if present[i]][:k])
    coeffs = rs_matrix.reconstruct_rows(k, m, present, (0, 5, 13))
    rebuilt = native.rs_apply(survivors, coeffs)
    for idx, i in enumerate((0, 5, 13)):
        assert np.array_equal(rebuilt[idx], full[i])


@pytest.mark.parametrize("n", [0, 1, 3, 17, 31, 32, 33, 63, 64, 100, 87382])
def test_native_hh_matches_numpy(n):
    rng = np.random.default_rng(n)
    d = rng.integers(0, 256, n).astype(np.uint8)
    assert native.hh256(d, hh.MAGIC_KEY) == hh.hash256(d.tobytes())


def test_native_hh_batch_and_frame():
    rng = np.random.default_rng(1)
    data = rng.integers(0, 256, (8, 500)).astype(np.uint8)
    batch = native.hh256_batch(data, hh.MAGIC_KEY)
    for i in range(8):
        assert batch[i].tobytes() == hh.hash256(data[i].tobytes())
    framed = native.hh256_frame(data, hh.MAGIC_KEY)
    pos = 0
    for i in range(8):
        assert framed[pos : pos + 32] == batch[i].tobytes()
        assert framed[pos + 32 : pos + 532] == data[i].tobytes()
        pos += 532


def test_host_codec_native_matches_plain():
    from minio_tpu.object.codec import HostCodec

    rng = np.random.default_rng(2)
    block = rng.integers(0, 256, 1 << 20).astype(np.uint8).tobytes()
    a = HostCodec(use_native=True).encode([block], 12, 4)
    b = HostCodec(use_native=False).encode([block], 12, 4)
    assert a[0][0] == b[0][0]
    assert a[0][1] == b[0][1]


# -- native IO layer (native/minio_io.cpp) -----------------------------------


class TestNativeIO:
    def test_roundtrip_various_sizes(self, tmp_path):
        import os

        from minio_tpu.ops import native

        if not native.io_available():
            pytest.skip("native lib unavailable")
        for size in (0, 1, 4095, 4096, 4097, 1 << 20, (4 << 20) + 77):
            data = os.urandom(size)
            p = str(tmp_path / f"f{size}")
            native.write_file(p, data, fsync=True)
            assert open(p, "rb").read() == data, size
            assert native.read_file(p, size) == data, size

    def test_offset_reads(self, tmp_path):
        import os

        from minio_tpu.ops import native

        if not native.io_available():
            pytest.skip("native lib unavailable")
        data = os.urandom(2 << 20)
        p = str(tmp_path / "off")
        native.write_file(p, data)
        assert native.read_file(p, 1000, offset=0) == data[:1000]
        assert native.read_file(p, 1000, offset=4096) == data[4096:5096]
        assert native.read_file(p, 1000, offset=12345) == data[12345:13345]
        # Short read past EOF.
        assert native.read_file(p, 1 << 20, offset=(2 << 20) - 100) == data[-100:]

    def test_error_on_missing(self, tmp_path):
        from minio_tpu.ops import native

        if not native.io_available():
            pytest.skip("native lib unavailable")
        with pytest.raises(OSError):
            native.read_file(str(tmp_path / "nope"), 100)

    def test_local_drive_large_files_take_native_path(self, tmp_path):
        import os

        from minio_tpu.ops import native
        from minio_tpu.storage.local import ODIRECT_THRESHOLD, LocalDrive

        if not native.io_available():
            pytest.skip("native lib unavailable")
        d = LocalDrive(str(tmp_path / "drive"))
        d.make_vol("vol")
        big = os.urandom(ODIRECT_THRESHOLD + 1234)
        d.create_file("vol", "big.bin", big)
        assert d.read_file("vol", "big.bin", 0, len(big)) == big
        assert d._odirect is not None  # probe ran on the native path
        small = b"s" * 1000
        d.create_file("vol", "small.bin", small)
        assert d.read_file("vol", "small.bin", 0, -1) == small
