"""HTTP-layer streaming tests: verified streaming PUT/GET over the wire.

The round-2 request pipeline (VERDICT #3 / weak #7): object PUT bodies flow
through verified readers into the erasure pipeline without buffering; GETs
stream decoded blocks to the socket. Digest mismatches fail the request and
never commit (the reference's hash.Reader + streaming-signature chain,
cmd/object-handlers.go:1638-1712).
"""

import datetime
import hashlib

import numpy as np
import pytest
import requests

from minio_tpu.api.auth import Credentials, sign_request
from minio_tpu.api.server import S3Server, ThreadedServer
from minio_tpu.api.streaming import STREAMING_PAYLOAD, encode_chunked
from minio_tpu.control.iam import IAMSys
from tests.harness import ErasureHarness
from tests.s3client import S3TestClient

AK = "streamak"
SK = "stream-secret-key"


@pytest.fixture(scope="module")
def stack(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("httpstream")
    hz = ErasureHarness(tmp, n_disks=8)
    from minio_tpu.object.pools import ServerPools
    from minio_tpu.object.sets import ErasureSets

    layer = ServerPools([ErasureSets([d for d in hz.drives], 8)])
    srv = S3Server(layer, IAMSys(AK, SK), check_skew=False)
    ts = ThreadedServer(srv)
    endpoint = ts.start()
    client = S3TestClient(endpoint, AK, SK)
    assert client.make_bucket("sbkt").status_code == 200
    yield {"client": client, "endpoint": endpoint, "layer": layer}
    ts.stop()


@pytest.fixture
def client(stack):
    return stack["client"]


def _body(size, seed=0):
    return np.random.default_rng(seed).integers(0, 256, size, dtype=np.uint8).tobytes()


def test_large_signed_put_and_streamed_get(stack, client):
    body = _body(3 * (1 << 20) + 17)
    r = client.put_object("sbkt", "large", body)
    assert r.status_code == 200, r.text
    # Streaming PUTs carry the digest-stream etag (see erasure.fast_etag);
    # recompute it independently from the payload + set geometry.
    from minio_tpu.object.erasure import fast_etag

    eo = stack["layer"].pools[0].sets[0]
    want = fast_etag(body, eo.drive_count - eo.parity, eo.parity)
    assert r.headers["ETag"].strip('"') == want
    r = client.get_object("sbkt", "large")
    assert r.status_code == 200
    assert r.headers["Content-Length"] == str(len(body))
    assert r.content == body


def test_sha256_mismatch_never_commits(stack, client):
    """Declared payload hash != streamed bytes: request fails AFTER staging,
    object is never committed."""
    body = _body(2 * (1 << 20), seed=1)
    wrong_hash = hashlib.sha256(b"something else").hexdigest()
    # Build the request manually with a lying payload hash.
    creds = Credentials(AK, SK)
    headers = sign_request(
        creds, "PUT", "/sbkt/mismatch", [], {"host": client.host}, body,
        payload_hash=wrong_hash,
    )
    headers.pop("host")
    r = requests.put(f"{stack['endpoint']}/sbkt/mismatch", data=body, headers=headers)
    assert r.status_code == 400, r.text
    assert b"XAmzContentSHA256Mismatch" in r.content
    assert client.get_object("sbkt", "mismatch").status_code == 404


def test_streaming_chunked_put(stack, client):
    """aws-chunked upload verified chunk by chunk while streaming."""
    payload = _body(2 * (1 << 20) + 999, seed=2)
    creds = Credentials(AK, SK)
    t = datetime.datetime.now(datetime.timezone.utc)
    amz_date = t.strftime("%Y%m%dT%H%M%SZ")
    headers = sign_request(
        creds, "PUT", "/sbkt/chunked", [], {"host": client.host}, None,
        payload_hash=STREAMING_PAYLOAD, timestamp=t,
    )
    seed_sig = headers["authorization"].rsplit("Signature=", 1)[1]
    body = encode_chunked(payload, seed_sig, creds, amz_date, "us-east-1", chunk_size=256 * 1024)
    headers.pop("host")
    r = requests.put(f"{stack['endpoint']}/sbkt/chunked", data=body, headers=headers)
    assert r.status_code == 200, r.text
    r = client.get_object("sbkt", "chunked")
    assert r.content == payload


def test_streaming_chunked_tamper_rejected(stack, client):
    payload = _body(512 * 1024, seed=3)
    creds = Credentials(AK, SK)
    t = datetime.datetime.now(datetime.timezone.utc)
    amz_date = t.strftime("%Y%m%dT%H%M%SZ")
    headers = sign_request(
        creds, "PUT", "/sbkt/tampered", [], {"host": client.host}, None,
        payload_hash=STREAMING_PAYLOAD, timestamp=t,
    )
    seed_sig = headers["authorization"].rsplit("Signature=", 1)[1]
    body = bytearray(
        encode_chunked(payload, seed_sig, creds, amz_date, "us-east-1", chunk_size=64 * 1024)
    )
    idx = body.find(b"\r\n") + 2 + 100  # flip a byte inside chunk 1's data
    body[idx] ^= 0xFF
    headers.pop("host")
    r = requests.put(f"{stack['endpoint']}/sbkt/tampered", data=bytes(body), headers=headers)
    assert r.status_code in (400, 403), r.text
    assert b"SignatureDoesNotMatch" in r.content
    assert client.get_object("sbkt", "tampered").status_code == 404


def test_oversized_chunk_header_rejected(stack, client):
    """A declared terabyte chunk is rejected before buffering."""
    creds = Credentials(AK, SK)
    t = datetime.datetime.now(datetime.timezone.utc)
    headers = sign_request(
        creds, "PUT", "/sbkt/hugechunk", [], {"host": client.host}, None,
        payload_hash=STREAMING_PAYLOAD, timestamp=t,
    )
    headers.pop("host")
    body = b"ffffffffff;chunk-signature=" + b"a" * 64 + b"\r\n" + b"x" * 4096
    r = requests.put(f"{stack['endpoint']}/sbkt/hugechunk", data=body, headers=headers)
    assert r.status_code == 400, r.text
    assert b"InvalidRequest" in r.content


def test_range_get_streams(client):
    body = _body(4 * (1 << 20), seed=4)
    assert client.put_object("sbkt", "ranged", body).status_code == 200
    r = client.get_object(
        "sbkt", "ranged", headers={"Range": "bytes=2097100-2097199"}
    )
    assert r.status_code == 206
    assert r.content == body[2097100:2097200]
    assert r.headers["Content-Range"] == f"bytes 2097100-2097199/{len(body)}"
    assert r.headers["Content-Length"] == "100"


def test_upload_part_streams(client):
    import re

    r = client.request("POST", "/sbkt/mpstream", query=[("uploads", "")])
    upid = re.search(r"<UploadId>([^<]+)</UploadId>", r.text).group(1)
    p1 = _body(5 * (1 << 20), seed=5)
    p2 = _body(1 << 20, seed=6)
    r1 = client.request(
        "PUT", "/sbkt/mpstream", query=[("uploadId", upid), ("partNumber", "1")], body=p1
    )
    r2 = client.request(
        "PUT", "/sbkt/mpstream", query=[("uploadId", upid), ("partNumber", "2")], body=p2
    )
    assert r1.status_code == 200 and r2.status_code == 200
    cx = (
        "<CompleteMultipartUpload>"
        f"<Part><PartNumber>1</PartNumber><ETag>{r1.headers['ETag']}</ETag></Part>"
        f"<Part><PartNumber>2</PartNumber><ETag>{r2.headers['ETag']}</ETag></Part>"
        "</CompleteMultipartUpload>"
    )
    r = client.request("POST", "/sbkt/mpstream", query=[("uploadId", upid)], body=cx.encode())
    assert r.status_code == 200, r.text
    r = client.get_object("sbkt", "mpstream")
    assert r.content == p1 + p2
