"""Stage-ledger / slow-capture / perf-gate unit tests (control/perf.py).

Histogram math is the foundation the admin /perf endpoint, the cluster
merge, and the bench stage_breakdown all stand on -- bucket assignment,
merge algebra, and quantile error bounds are pinned here independent of
any server plumbing.
"""

from __future__ import annotations

import importlib.util
import math
import os
import threading
import time

import pytest

from minio_tpu.control import perf, tracing
from minio_tpu.control.perf import (
    BUCKET_LE_S,
    BUCKET_LE_US,
    N_BUCKETS,
    SlowRequestCapture,
    StageLedger,
    bucket_index,
    bucket_max,
    merge_snapshots,
    quantile,
    summarize,
)

_GATE_PATH = os.path.join(os.path.dirname(__file__), "..", "tools", "perf_gate.py")
_spec = importlib.util.spec_from_file_location("perf_gate", _GATE_PATH)
perf_gate = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(perf_gate)


class TestBucketAssignment:
    def test_edges_are_log2_microseconds(self):
        assert len(BUCKET_LE_US) == N_BUCKETS
        assert BUCKET_LE_US[0] == 1.0
        assert all(b == 2 * a for a, b in zip(BUCKET_LE_US, BUCKET_LE_US[1:]))

    def test_boundary_values_land_in_their_bucket(self):
        # A duration EQUAL to an upper edge belongs to that bucket
        # (le semantics: count of observations <= edge).
        for i, le_s in enumerate(BUCKET_LE_S):
            assert bucket_index(le_s) == i, f"edge {le_s}s"

    def test_just_over_an_edge_goes_to_the_next_bucket(self):
        for i in range(1, 8):
            edge_us = 1 << i
            assert bucket_index((edge_us + 1) / 1e6) == i + 1

    def test_zero_negative_and_tiny_clamp_to_first(self):
        assert bucket_index(0.0) == 0
        assert bucket_index(-1.0) == 0
        assert bucket_index(1e-9) == 0
        assert bucket_index(1e-6) == 0

    def test_past_last_edge_is_inf_slot(self):
        assert bucket_index(BUCKET_LE_S[-1] * 4) == N_BUCKETS
        assert bucket_index(10_000.0) == N_BUCKETS


class TestLedger:
    def test_record_and_snapshot(self):
        led = StageLedger()
        led.record("api", "auth", 0.001)
        led.record("api", "auth", 0.002)
        led.record("object", "encode", 0.5)
        snap = led.snapshot()
        auth = snap["stages"]["api"]["auth"]
        assert sum(auth["counts"]) == 2
        assert auth["sum"] == pytest.approx(0.003)
        assert sum(snap["stages"]["object"]["encode"]["counts"]) == 1

    def test_reset_clears(self):
        led = StageLedger()
        led.record("a", "b", 0.1)
        led.reset()
        assert led.snapshot()["stages"] == {}

    def test_concurrent_recording_conserves_counts(self):
        led = StageLedger()
        n_threads, per_thread = 8, 2000
        stages = [("api", "auth"), ("object", "encode"), ("rpc", "call"), ("s", "t")]

        def work(tid: int):
            for i in range(per_thread):
                layer, stage = stages[(tid + i) % len(stages)]
                led.record(layer, stage, (i % 50) * 1e-5)

        threads = [threading.Thread(target=work, args=(t,)) for t in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        snap = led.snapshot()
        total = sum(
            sum(h["counts"])
            for stages_ in snap["stages"].values()
            for h in stages_.values()
        )
        assert total == n_threads * per_thread


class TestMerge:
    def _snap(self, *records):
        led = StageLedger()
        for layer, stage, s in records:
            led.record(layer, stage, s)
        return led.snapshot()

    def test_merge_is_commutative(self):
        a = self._snap(("api", "auth", 0.001), ("object", "encode", 0.1))
        b = self._snap(("api", "auth", 0.004), ("rpc", "x", 1.0))
        assert merge_snapshots([a, b]) == merge_snapshots([b, a])

    def test_merge_is_associative(self):
        a = self._snap(("api", "auth", 0.001))
        b = self._snap(("api", "auth", 0.01), ("object", "encode", 0.2))
        c = self._snap(("rpc", "x", 2.0))
        left = merge_snapshots([merge_snapshots([a, b]), c])
        right = merge_snapshots([a, merge_snapshots([b, c])])
        assert left == right

    def test_merge_sums_counts_and_sums(self):
        a = self._snap(("api", "auth", 0.001), ("api", "auth", 0.002))
        b = self._snap(("api", "auth", 0.004))
        m = merge_snapshots([a, b])
        auth = m["stages"]["api"]["auth"]
        assert sum(auth["counts"]) == 3
        assert auth["sum"] == pytest.approx(0.007)

    def test_version_skew_snapshot_is_skipped(self):
        a = self._snap(("api", "auth", 0.001))
        bad = {"buckets_us": [1.0, 2.0], "stages": {"api": {"auth": {"counts": [9, 9], "sum": 9.0}}}}
        m = merge_snapshots([a, bad, {}])
        assert sum(m["stages"]["api"]["auth"]["counts"]) == 1


class TestQuantile:
    def test_quantile_within_one_bucket_width(self):
        led = StageLedger()
        durations = [0.0001, 0.0002, 0.0004, 0.001, 0.002, 0.004, 0.01, 0.05]
        for d in durations:
            led.record("l", "s", d)
        counts = led.snapshot()["stages"]["l"]["s"]["counts"]
        for q in (0.5, 0.95, 0.99):
            # The ledger's q-th observation is the ceil(q*n)-th (1-indexed).
            true = sorted(durations)[max(math.ceil(q * len(durations)) - 1, 0)]
            est = quantile(counts, q)
            # The estimate is the upper edge of the true value's bucket:
            # within one log2 bucket width, i.e. est/2 < true <= est.
            assert true <= est <= max(true * 2, BUCKET_LE_S[0]), (q, true, est)

    def test_quantile_empty_is_zero(self):
        assert quantile([0] * (N_BUCKETS + 1), 0.5) == 0.0

    def test_inf_slot_reports_sentinel(self):
        counts = [0] * (N_BUCKETS + 1)
        counts[-1] = 5
        assert quantile(counts, 0.5) == BUCKET_LE_S[-1] * 2

    def test_summarize_shape(self):
        led = StageLedger()
        led.record("api", "auth", 0.002)
        s = summarize(led.snapshot())
        row = s["api"]["auth"]
        assert row["count"] == 1
        for k in ("total_ms", "mean_ms", "p50_ms", "p95_ms", "p99_ms", "p999_ms", "max_ms"):
            assert k in row

    def test_p999_separates_the_one_in_a_thousand_tail(self):
        led = StageLedger()
        for _ in range(998):
            led.record("l", "s", 0.001)
        for _ in range(2):
            led.record("l", "s", 4.0)  # tail spikes p99 must NOT show
        row = summarize(led.snapshot())["l"]["s"]
        assert row["p99_ms"] <= 2.0 * 1.024  # still in the ~1ms bucket
        assert row["p999_ms"] >= 4000.0      # tail quantile sees the spike
        assert row["max_ms"] >= 4000.0

    def test_bucket_max_is_occupied_upper_edge(self):
        led = StageLedger()
        led.record("l", "s", 0.003)
        counts = led.snapshot()["stages"]["l"]["s"]["counts"]
        est = bucket_max(counts)
        assert 0.003 <= est <= 0.006  # upper edge of the 3ms bucket

    def test_bucket_max_empty_is_zero(self):
        assert bucket_max([0] * (N_BUCKETS + 1)) == 0.0


class TestSlowCapture:
    def _rec(self, trace, name="op", parent="x"):
        return {"trace": trace, "name": name, "layer": "l", "span": "s", "parent": parent}

    def test_fast_roots_are_discarded(self):
        sc = SlowRequestCapture(budget_s=1.0, max_traces=4)
        sc.begin_trace("t1")
        sc.observe(self._rec("t1", parent=""), is_root=True, duration_s=0.01)
        assert sc.list() == []
        assert sc.stats()["pending_traces"] == 0

    def test_slow_roots_are_captured_with_children(self):
        sc = SlowRequestCapture(budget_s=0.5, max_traces=4)
        sc.begin_trace("t1")
        sc.observe(self._rec("t1", name="child"), is_root=False, duration_s=0.1)
        sc.observe(self._rec("t1", name="root", parent=""), is_root=True, duration_s=2.0)
        got = sc.list()
        assert len(got) == 1
        assert got[0]["root"] == "root"
        assert [s["name"] for s in got[0]["spans"]] == ["child", "root"]

    def test_ring_count_cap_evicts_oldest(self):
        sc = SlowRequestCapture(budget_s=0.0, max_traces=2)
        for i in range(5):
            sc.begin_trace(f"t{i}")
            sc.observe(self._rec(f"t{i}", parent=""), is_root=True, duration_s=1.0)
        got = sc.list()
        assert len(got) == 2
        assert [g["trace"] for g in got] == ["t4", "t3"]  # newest first
        assert sc.stats()["evicted_traces"] == 3
        assert sc.stats()["captured_total"] == 5

    def test_ring_byte_cap_evicts(self):
        cap = SlowRequestCapture._APPROX_SPAN_BYTES * 3
        sc = SlowRequestCapture(budget_s=0.0, max_traces=100, max_bytes=cap)
        for i in range(4):
            sc.begin_trace(f"t{i}")
            sc.observe(self._rec(f"t{i}", parent=""), is_root=True, duration_s=1.0)
        assert sc.stats()["retained_bytes_approx"] <= cap
        assert sc.stats()["evicted_traces"] >= 1

    def test_per_trace_span_cap_counts_evictions(self):
        sc = SlowRequestCapture(budget_s=0.0, max_traces=4, max_spans_per_trace=3)
        sc.begin_trace("t1")
        for i in range(10):
            sc.observe(self._rec("t1", name=f"c{i}"), is_root=False, duration_s=0.1)
        sc.observe(self._rec("t1", parent=""), is_root=True, duration_s=1.0)
        got = sc.list()
        assert len(got[0]["spans"]) == 3
        assert sc.stats()["evicted_spans"] == 8  # 7 children + the root itself

    def test_live_trace_cap_bounds_pending(self):
        sc = SlowRequestCapture(budget_s=0.0, max_live_traces=16)
        for i in range(100):
            sc.begin_trace(f"t{i}")
        assert sc.stats()["pending_traces"] == 16

    def test_unknown_trace_spans_are_ignored(self):
        sc = SlowRequestCapture(budget_s=0.0)
        assert not sc.wants("nope")
        sc.observe(self._rec("nope"), is_root=False, duration_s=0.1)
        assert sc.stats()["pending_traces"] == 0

    def test_reset_clears_ring_keeps_counters(self):
        sc = SlowRequestCapture(budget_s=0.0, max_traces=2)
        for i in range(3):
            sc.begin_trace(f"t{i}")
            sc.observe(self._rec(f"t{i}", parent=""), is_root=True, duration_s=1.0)
        sc.reset()
        assert sc.list() == []
        assert sc.stats()["captured_total"] == 3
        assert sc.stats()["evicted_traces"] == 1


class TestAlwaysOnWiring:
    def test_root_span_feeds_ledger_without_subscriber(self):
        perf.GLOBAL_PERF.ledger.reset()
        with tracing.root_span("op", "testlayer", "trace-ledger-1"):
            with tracing.span("stage-a", "testlayer"):
                pass
        snap = perf.GLOBAL_PERF.ledger.snapshot()
        assert sum(snap["stages"]["testlayer"]["op"]["counts"]) == 1
        assert sum(snap["stages"]["testlayer"]["stage-a"]["counts"]) == 1

    def test_orphan_span_stays_noop(self):
        # The zero-overhead guard for background sweeps survives the ledger.
        assert tracing.span("bg", "object") is tracing.NOOP

    def test_disarmed_stage_mark_overhead_is_microseconds(self):
        # Tier-1 smoke for the ISSUE's O(us) claim: a full span open/close
        # (no subscriber, inside a request tree) must stay far under 500us.
        perf.GLOBAL_PERF.ledger.reset()
        n = 2000
        with tracing.root_span("op", "bench-overhead", "trace-overhead"):
            t0 = time.perf_counter()
            for _ in range(n):
                with tracing.span("mark", "bench-overhead"):
                    pass
            dt = time.perf_counter() - t0
        assert dt / n < 500e-6, f"stage mark cost {dt / n * 1e6:.1f}us"


class TestTraceSampling:
    """MTPU_TRACE_SAMPLE: publication is sampled, attribution is not."""

    def _reset_counter(self):
        import itertools

        tracing._sample_counter = itertools.count()

    def test_sampled_out_root_feeds_ledger_and_hub_but_not_slow_ring(self, monkeypatch):
        """Sampling thins ONLY slow-capture buffering. The ledger (always-on
        attribution), the live hub (/trace watchers), and the flight ring
        (control/flight.py black box) all see sampled-out roots -- a thinned
        trace stream must never blind the diagnostics that matter most
        during an incident."""
        from minio_tpu.control.pubsub import TraceSys

        monkeypatch.setenv("MTPU_TRACE_SAMPLE", "0")
        perf.GLOBAL_PERF.ledger.reset()
        pending_before = perf.GLOBAL_PERF.slow.stats()["pending_traces"]
        tsys = TraceSys()
        q = tsys.subscribe()
        try:
            with tracing.root_span("op", "samplelayer", "trace-sampled-out", sys=tsys) as root:
                assert root.sampled is False
                with tracing.span("stage-b", "samplelayer", sys=tsys) as child:
                    assert child.sampled is False  # verdict inherited
        finally:
            tsys.unsubscribe(q)
        snap = perf.GLOBAL_PERF.ledger.snapshot()
        assert sum(snap["stages"]["samplelayer"]["op"]["counts"]) == 1
        assert sum(snap["stages"]["samplelayer"]["stage-b"]["counts"]) == 1
        assert not q.empty()  # hub publication is PRE-sampling
        # Slow-capture buffering is the only thing the verdict gates.
        assert perf.GLOBAL_PERF.slow.stats()["pending_traces"] == pending_before

    def test_rate_one_keeps_every_root(self, monkeypatch):
        monkeypatch.setenv("MTPU_TRACE_SAMPLE", "1")
        self._reset_counter()
        assert all(tracing._sample_next() for _ in range(8))

    def test_rate_half_is_deterministic_one_in_two(self, monkeypatch):
        monkeypatch.setenv("MTPU_TRACE_SAMPLE", "0.5")
        self._reset_counter()
        assert [tracing._sample_next() for _ in range(6)] == [
            True, False, True, False, True, False,
        ]

    def test_garbage_value_means_trace_all(self, monkeypatch):
        monkeypatch.setenv("MTPU_TRACE_SAMPLE", "banana")
        self._reset_counter()
        assert all(tracing._sample_next() for _ in range(4))

    def test_sampled_root_still_publishes(self, monkeypatch):
        from minio_tpu.control.pubsub import TraceSys

        monkeypatch.setenv("MTPU_TRACE_SAMPLE", "1")
        tsys = TraceSys()
        q = tsys.subscribe()
        try:
            with tracing.root_span("op", "samplelayer", "trace-sampled-in", sys=tsys):
                pass
        finally:
            tsys.unsubscribe(q)
        assert not q.empty()


class TestCodecObservatory:
    def test_batching_counters_reach_exposition(self):
        """The device-codec counters (occupancy, host fallbacks, compiled
        verify lengths) render as Prometheus series when the batching codec
        is installed -- the CPU cluster tests only see the host codec."""
        from minio_tpu.control.metrics import MetricsSys
        from minio_tpu.object import codec as codec_mod
        from minio_tpu.parallel.batching import BatchingDeviceCodec

        codec = BatchingDeviceCodec(max_batch=4)
        prev = codec_mod._default
        codec_mod._default = codec
        try:
            text = MetricsSys().render_node()
        finally:
            codec_mod._default = prev
            codec.close()
        for series in (
            "minio_tpu_codec_batch_occupancy",
            "minio_tpu_codec_host_fallback_total",
            "minio_tpu_codec_compiled_verify_lengths",
            "minio_tpu_codec_device_seconds_total",
            "minio_tpu_native_codec_available",
        ):
            assert series in text, series

    def test_batch_latencies_feed_ledger(self):
        """Host-fallback-eligible work still routes through digests_batch's
        device path counters; here we drive the HOST paths and assert the
        codec ledger stages appear once a device batch runs is covered by
        the batching suite -- this pins the stats() key the gauge reads."""
        from minio_tpu.parallel.batching import BatchingDeviceCodec

        codec = BatchingDeviceCodec(max_batch=4)
        try:
            st = codec.stats()
            assert st["compiled_verify_lens"] == 0
        finally:
            codec.close()


class TestPerfGate:
    def _bench(self, put_stages: dict) -> dict:
        return {
            "stage_breakdown": {
                "put": {"ops": 8, "end_to_end_ms": 1000.0, "stages": put_stages}
            }
        }

    def test_no_regression_passes(self):
        old = self._bench({"encode": {"share": 0.3, "total_ms": 300.0}})
        new = self._bench({"encode": {"share": 0.32, "total_ms": 310.0}})
        assert perf_gate.compare(old, new, threshold=0.10) == []

    def test_share_and_time_growth_flags(self):
        old = self._bench({"encode": {"share": 0.30, "total_ms": 300.0}})
        new = self._bench({"encode": {"share": 0.55, "total_ms": 700.0}})
        flagged = perf_gate.compare(old, new, threshold=0.10)
        assert len(flagged) == 1
        assert flagged[0]["stage"] == "encode"

    def test_share_growth_from_other_stages_speeding_up_is_not_flagged(self):
        # Share grew but absolute time SHRANK: the pipeline got faster
        # around it -- not a regression.
        old = self._bench({"encode": {"share": 0.30, "total_ms": 300.0}})
        new = self._bench({"encode": {"share": 0.60, "total_ms": 250.0}})
        assert perf_gate.compare(old, new, threshold=0.10) == []

    def test_new_stage_without_baseline_is_skipped(self):
        old = self._bench({})
        new = self._bench({"decode": {"share": 0.9, "total_ms": 900.0}})
        assert perf_gate.compare(old, new, threshold=0.10) == []

    def test_missing_breakdown_compares_empty(self):
        assert perf_gate.compare({}, {}, threshold=0.1) == []


class TestCodecFloor:
    """Device-claiming BENCH lines must beat their own recorded CPU floor."""

    def test_device_slower_than_cpu_floor_flags(self):
        new = {"device": True, "value": 1.2, "cpu_avx2_gibs": 2.0}
        findings = perf_gate.codec_floor_findings(new)
        assert [f["metric"] for f in findings] == ["value"]

    def test_device_beating_floor_passes(self):
        new = {"device": True, "value": 18.0, "cpu_avx2_gibs": 2.0,
               "pallas_fused_gibs": 9.0, "pallas_fused_error": ""}
        assert perf_gate.codec_floor_findings(new) == []

    def test_wedged_probe_round_never_gates(self):
        # device: false = CPU fallback (wedged tunnel): a probe finding,
        # not a codec regression -- even though value == cpu floor.
        new = {"device": False, "value": 2.0, "cpu_avx2_gibs": 2.0}
        assert perf_gate.codec_floor_findings(new) == []

    def test_fused_below_floor_flags_when_measured(self):
        new = {"device": True, "value": 18.0, "cpu_avx2_gibs": 2.0,
               "pallas_fused_gibs": 1.5, "pallas_fused_error": ""}
        findings = perf_gate.codec_floor_findings(new)
        assert [f["metric"] for f in findings] == ["pallas_fused_gibs"]

    def test_unmeasured_or_errored_fused_is_not_gated(self):
        # 0.0 = not measured; a recorded error = known-skipped secondary.
        for extra in ({"pallas_fused_gibs": 0.0},
                      {"pallas_fused_gibs": 1.0, "pallas_fused_error": "boom"}):
            new = {"device": True, "value": 18.0, "cpu_avx2_gibs": 2.0, **extra}
            assert perf_gate.codec_floor_findings(new) == []

    def test_missing_keys_never_gate(self):
        assert perf_gate.codec_floor_findings({"device": True}) == []
        assert perf_gate.codec_floor_findings({}) == []


class TestPerfGateSlo:
    """--slo mode over loadgen reports (tools/loadgen.py emissions)."""

    def _report(self, p99_ms: float, burn: float = 0.5, p99_ok: bool = True) -> dict:
        return {
            "ops": {"GET": {"p99_ms": p99_ms, "count": 100}},
            "slo": {
                "GET": {
                    "p99_ms": p99_ms,
                    "target_p99_ms": 500.0,
                    "p99_ok": p99_ok,
                    "budget_burn": burn,
                    "error_budget": 0.02,
                    "ok": p99_ok and burn <= 1.0,
                }
            },
        }

    def test_doctored_p99_regression_is_flagged(self):
        old = self._report(100.0)
        new = self._report(300.0)  # 3x, way past tol and floor
        kinds = [f["kind"] for f in perf_gate.compare_slo(old, new)]
        assert "p99-regression" in kinds

    def test_within_tolerance_passes(self):
        old = self._report(100.0)
        new = self._report(110.0)  # +10% < 25% tol
        assert perf_gate.compare_slo(old, new) == []

    def test_small_absolute_growth_is_noise(self):
        # 1ms -> 3ms triples but stays under the 5ms floor: bucket noise.
        old = self._report(1.0)
        new = self._report(3.0)
        assert perf_gate.compare_slo(old, new) == []

    def test_burn_violation_is_absolute(self):
        # No old-side data needed: burning the budget flags on its own.
        new = self._report(100.0, burn=4.9)
        findings = perf_gate.compare_slo({}, new)
        assert [f["kind"] for f in findings] == ["burn-violation"]
        assert findings[0]["budget_burn"] == pytest.approx(4.9)

    def test_p99_target_miss_is_flagged(self):
        new = self._report(900.0, p99_ok=False)
        kinds = [f["kind"] for f in perf_gate.compare_slo({}, new)]
        assert "p99-violation" in kinds

    def test_partial_shapes_tolerated(self):
        assert perf_gate.compare_slo({}, {}) == []

    def test_compare_violation_single_block(self):
        new = {"compare": {"a": "concurrent", "b": "single", "op": "PUT",
                           "metric": "bytes_per_s", "ratio": 0.9,
                           "min_ratio": 1.2, "reproduced": False}}
        findings = perf_gate.compare_slo({}, new)
        assert [f["kind"] for f in findings] == ["compare-violation"]
        assert findings[0]["ratio"] == 0.9

    def test_compare_violation_sweep_flags_only_missed_rungs(self):
        new = {"compare": [
            {"a": "c4", "b": "c1", "ratio": 1.4, "min_ratio": 1.0,
             "reproduced": True},
            {"a": "c16", "b": "c1", "ratio": 0.7, "min_ratio": 1.0,
             "reproduced": False},
        ]}
        findings = perf_gate.compare_slo({}, new)
        assert [f["kind"] for f in findings] == ["compare-violation"]
        assert findings[0]["a"] == "c16"
        assert perf_gate.compare_slo({"ops": None}, {"ops": {"GET": "oops"}}) == []
