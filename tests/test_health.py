"""Health probes + per-drive metering (reference healthinfo + disk-id-check)."""

import json

import pytest

from minio_tpu.control import health
from minio_tpu.control.pubsub import TraceSys
from minio_tpu.storage.local import LocalDrive
from minio_tpu.storage.metered import MeteredDrive
from minio_tpu.utils import errors


def test_probes_return_sane_shapes():
    cpu = health.cpu_info()
    assert cpu["cores"] > 0
    mem = health.mem_info()
    assert mem.get("memtotal", 0) > 0
    osn = health.os_info()
    assert osn["kernel"] and osn["uptime_seconds"] > 0
    assert isinstance(health.disk_iostats(), list)
    mounts = health.mount_info()
    assert any(m["mountpoint"] == "/" for m in mounts)
    assert isinstance(health.net_info(), list)
    info = health.health_info()
    assert set(info) >= {"timestamp", "cpu", "memory", "os", "iostats", "mounts", "network"}
    json.dumps(info)  # JSON-serializable end to end


def test_metered_drive_records_latencies(tmp_path):
    d = MeteredDrive(LocalDrive(str(tmp_path)))
    d.make_vol("v")
    d.write_all("v", "f", b"x" * 1000)
    assert d.read_all("v", "f") == b"x" * 1000
    lat = d.api_latencies()
    assert lat["write_all"]["count"] == 1
    assert lat["read_all"]["count"] == 1
    assert lat["make_vol"]["ewma_ms"] >= 0
    # Errors counted separately.
    with pytest.raises(errors.FileNotFound):
        d.read_all("v", "missing")
    assert d.api_latencies()["read_all"]["errors"] == 1
    # Non-storage attributes pass through untouched.
    assert d.endpoint() == d.inner.endpoint()
    assert d.is_local()


def test_metered_drive_traces_when_subscribed(tmp_path):
    trace = TraceSys()
    d = MeteredDrive(LocalDrive(str(tmp_path)), trace=trace)
    d.make_vol("v")
    sub = trace.hub.subscribe()
    d.write_all("v", "f", b"data")
    item = sub.get(timeout=2)
    assert item["type"] == "storage" and item["call"] == "write_all"
    trace.hub.unsubscribe(sub)
    # Zero-cost when nobody watches: publish path not taken (no exception,
    # nothing queued).
    d.write_all("v", "f2", b"data")
    assert sub.empty()


def test_healthinfo_includes_drives(tmp_path):
    from minio_tpu.object.pools import ServerPools
    from minio_tpu.object.sets import ErasureSets
    from tests.harness import ErasureHarness

    hz = ErasureHarness(tmp_path, n_disks=4)
    metered = [MeteredDrive(d) for d in hz.drives]
    layer = ServerPools([ErasureSets(metered, 4)])
    layer.make_bucket("healthbkt")
    layer.put_object("healthbkt", "o", b"x" * 1000)
    info = health.health_info(layer)
    assert len(info["drives"]) == 4
    for entry in info["drives"]:
        assert entry["state"] == "ok"
        assert entry["total"] > 0
        assert "api_latencies_ms" in entry
        assert entry["api_latencies_ms"]  # put recorded calls


def test_metered_walk_dir_times_full_iteration(tmp_path):
    d = MeteredDrive(LocalDrive(str(tmp_path)))
    d.make_vol("v")
    for i in range(5):
        d.write_all("v", f"o{i}/xl.meta", b"m")
    names = [n for n, _ in d.walk_dir("v")]
    assert len(names) == 5
    lat = d.api_latencies()
    assert lat["walk_dir"]["count"] == 1
    assert lat["walk_dir"]["ewma_ms"] > 0  # full-iteration time, not creation
    # Errors raised mid-iteration are counted.
    with pytest.raises(errors.StorageError):
        list(d.walk_dir("missing-vol"))
    assert d.api_latencies()["walk_dir"]["errors"] == 1
