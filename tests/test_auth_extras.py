"""Streaming-chunked SigV4, Signature V2, and POST policy tests.

Mirrors the reference's streaming-signature-v4_test.go, signature-v2 tests,
and postpolicyform_test.go coverage, plus signed end-to-end HTTP flows.
"""

import base64
import datetime
import json

import pytest
import requests

from minio_tpu.api.auth import Credentials, sign_request
from minio_tpu.api.errors import S3Error
from minio_tpu.api.postpolicy import (
    PostPolicy,
    build_post_form,
    parse_multipart_form,
    verify_post_signature,
)
from minio_tpu.api.sigv2 import (
    SigV2Verifier,
    presign_url_v2,
    sign_request_v2,
)
from minio_tpu.api.streaming import (
    STREAMING_PAYLOAD,
    decode_chunked,
    encode_chunked,
)

CREDS = Credentials("testak", "test-secret-key")
AMZ_DATE = "20260729T120000Z"
REGION = "us-east-1"


# ------------------------------------------------------------ streaming v4


class TestStreamingV4:
    def test_roundtrip(self):
        payload = b"hello streaming world" * 1000
        seed = "a" * 64
        body = encode_chunked(payload, seed, CREDS, AMZ_DATE, REGION, chunk_size=4096)
        out = decode_chunked(body, seed, CREDS.secret_key, AMZ_DATE, REGION)
        assert out == payload

    def test_empty_payload(self):
        body = encode_chunked(b"", "b" * 64, CREDS, AMZ_DATE, REGION)
        assert decode_chunked(body, "b" * 64, CREDS.secret_key, AMZ_DATE, REGION) == b""

    def test_tampered_chunk_rejected(self):
        payload = b"x" * 10000
        seed = "c" * 64
        body = bytearray(encode_chunked(payload, seed, CREDS, AMZ_DATE, REGION, chunk_size=1024))
        # flip a data byte inside the first chunk
        idx = body.find(b"\r\n") + 2 + 10
        body[idx] ^= 0xFF
        with pytest.raises(S3Error) as ei:
            decode_chunked(bytes(body), seed, CREDS.secret_key, AMZ_DATE, REGION)
        assert ei.value.code == "SignatureDoesNotMatch"

    def test_wrong_seed_rejected(self):
        body = encode_chunked(b"data", "d" * 64, CREDS, AMZ_DATE, REGION)
        with pytest.raises(S3Error):
            decode_chunked(body, "e" * 64, CREDS.secret_key, AMZ_DATE, REGION)

    def test_truncated_body(self):
        body = encode_chunked(b"data" * 100, "f" * 64, CREDS, AMZ_DATE, REGION)
        with pytest.raises(S3Error):
            decode_chunked(body[: len(body) // 2], "f" * 64, CREDS.secret_key, AMZ_DATE, REGION)


# ------------------------------------------------------------------- sig v2


class TestSigV2:
    def lookup(self, ak):
        return CREDS if ak == CREDS.access_key else None

    def test_signed_roundtrip(self):
        headers = sign_request_v2(
            CREDS.access_key, CREDS.secret_key, "GET", "/bkt/obj", [], {"content-type": "text/plain"}
        )
        v = SigV2Verifier(self.lookup)
        assert v.verify_signed("GET", "/bkt/obj", [], headers) == CREDS.access_key

    def test_signed_with_subresource(self):
        q = [("uploads", ""), ("ignored-param", "1")]
        headers = sign_request_v2(CREDS.access_key, CREDS.secret_key, "POST", "/bkt/obj", q, {})
        v = SigV2Verifier(self.lookup)
        assert v.verify_signed("POST", "/bkt/obj", q, headers) == CREDS.access_key

    def test_wrong_secret_rejected(self):
        headers = sign_request_v2(CREDS.access_key, "bad-secret", "GET", "/bkt/obj", [], {})
        v = SigV2Verifier(self.lookup)
        with pytest.raises(S3Error) as ei:
            v.verify_signed("GET", "/bkt/obj", [], headers)
        assert ei.value.code == "SignatureDoesNotMatch"

    def test_amz_headers_signed(self):
        headers = sign_request_v2(
            CREDS.access_key, CREDS.secret_key, "PUT", "/bkt/obj", [],
            {"x-amz-meta-color": "red"},
        )
        v = SigV2Verifier(self.lookup)
        assert v.verify_signed("PUT", "/bkt/obj", [], headers) == CREDS.access_key
        headers["x-amz-meta-color"] = "blue"
        with pytest.raises(S3Error):
            v.verify_signed("PUT", "/bkt/obj", [], headers)

    def test_presigned_roundtrip(self):
        url = presign_url_v2(CREDS.access_key, CREDS.secret_key, "GET", "/bkt/obj", "host:9000")
        import urllib.parse

        parsed = urllib.parse.urlparse(url)
        query = urllib.parse.parse_qsl(parsed.query, keep_blank_values=True)
        v = SigV2Verifier(self.lookup)
        assert v.verify_presigned("GET", "/bkt/obj", query) == CREDS.access_key

    def test_presigned_expired(self):
        url = presign_url_v2(CREDS.access_key, CREDS.secret_key, "GET", "/b/o", "h", expires_in=-10)
        import urllib.parse

        query = urllib.parse.parse_qsl(urllib.parse.urlparse(url).query, keep_blank_values=True)
        v = SigV2Verifier(self.lookup)
        with pytest.raises(S3Error) as ei:
            v.verify_presigned("GET", "/b/o", query)
        assert ei.value.code == "ExpiredPresignRequest"


# -------------------------------------------------------------- post policy


class TestPostPolicy:
    def lookup(self, ak):
        return CREDS if ak == CREDS.access_key else None

    def test_form_roundtrip(self):
        body, ctype = build_post_form(CREDS, "bkt", "obj.txt", b"hello")
        form = parse_multipart_form(body, ctype)
        assert form["file"] == b"hello"
        assert form["key"] == b"obj.txt"
        assert verify_post_signature(form, self.lookup) == CREDS.access_key

    def test_bad_signature(self):
        body, ctype = build_post_form(CREDS, "bkt", "obj.txt", b"hello")
        form = parse_multipart_form(body, ctype)
        form["x-amz-signature"] = b"0" * 64
        with pytest.raises(S3Error):
            verify_post_signature(form, self.lookup)

    def test_policy_conditions(self):
        doc = {
            "expiration": "2030-01-01T00:00:00.000Z",
            "conditions": [
                {"bucket": "bkt"},
                ["eq", "$key", "photos/cat.jpg"],
                ["starts-with", "$content-type", "image/"],
                ["content-length-range", 1, 100],
            ],
        }
        pol = PostPolicy.parse(json.dumps(doc).encode())
        good = {"key": b"photos/cat.jpg", "content-type": b"image/jpeg"}
        pol.check(good, 50, bucket="bkt")
        with pytest.raises(S3Error):
            pol.check({"key": b"other.jpg", "content-type": b"image/jpeg"}, 50, bucket="bkt")
        with pytest.raises(S3Error):
            pol.check({"key": b"photos/cat.jpg", "content-type": b"text/html"}, 50, bucket="bkt")
        with pytest.raises(S3Error) as ei:
            pol.check(good, 1000, bucket="bkt")
        assert ei.value.code == "EntityTooLarge"

    def test_policy_expired(self):
        doc = {"expiration": "2020-01-01T00:00:00.000Z", "conditions": []}
        pol = PostPolicy.parse(json.dumps(doc).encode())
        with pytest.raises(S3Error):
            pol.check({}, 1)


# ----------------------------------------------------------------- HTTP e2e


@pytest.fixture(scope="module")
def http_stack(tmp_path_factory):
    from minio_tpu.api.server import S3Server, ThreadedServer
    from minio_tpu.control.iam import IAMSys
    from minio_tpu.object.pools import ServerPools
    from minio_tpu.object.sets import ErasureSets
    from tests.harness import ErasureHarness
    from tests.s3client import S3TestClient

    tmp = tmp_path_factory.mktemp("authx")
    hz = ErasureHarness(tmp, n_disks=8)
    layer = ServerPools([ErasureSets([d for d in hz.drives], 8)])
    iam = IAMSys("authak", "auth-secret")
    srv = S3Server(layer, iam, check_skew=False)
    ts = ThreadedServer(srv)
    endpoint = ts.start()
    client = S3TestClient(endpoint, "authak", "auth-secret")
    client.make_bucket("authbkt")
    yield {"endpoint": endpoint, "client": client}
    ts.stop()


class TestAuthE2E:
    def test_streaming_put(self, http_stack):
        import urllib.parse

        ep = http_stack["endpoint"]
        host = urllib.parse.urlparse(ep).netloc
        creds = Credentials("authak", "auth-secret")
        payload = b"streamed object payload " * 500
        headers = {
            "host": host,
            "content-encoding": "aws-chunked",
            "x-amz-decoded-content-length": str(len(payload)),
        }
        headers = sign_request(
            creds, "PUT", "/authbkt/streamed.bin", [], headers, None,
            payload_hash=STREAMING_PAYLOAD,
        )
        seed = headers["authorization"].rsplit("Signature=", 1)[1]
        amz_date = headers["x-amz-date"]
        body = encode_chunked(payload, seed, creds, amz_date, "us-east-1", chunk_size=8192)
        headers.pop("host")
        r = requests.put(f"{ep}/authbkt/streamed.bin", data=body, headers=headers)
        assert r.status_code == 200, r.text
        # object content is the decoded payload, not the wire bytes
        r = http_stack["client"].get_object("authbkt", "streamed.bin")
        assert r.content == payload

    def test_streaming_put_tampered(self, http_stack):
        import urllib.parse

        ep = http_stack["endpoint"]
        host = urllib.parse.urlparse(ep).netloc
        creds = Credentials("authak", "auth-secret")
        payload = b"x" * 9000
        headers = {
            "host": host,
            "content-encoding": "aws-chunked",
            "x-amz-decoded-content-length": str(len(payload)),
        }
        headers = sign_request(
            creds, "PUT", "/authbkt/tampered.bin", [], headers, None,
            payload_hash=STREAMING_PAYLOAD,
        )
        seed = headers["authorization"].rsplit("Signature=", 1)[1]
        body = bytearray(
            encode_chunked(payload, seed, creds, headers["x-amz-date"], "us-east-1", chunk_size=4096)
        )
        idx = body.find(b"\r\n") + 2 + 5
        body[idx] ^= 0x01
        headers.pop("host")
        r = requests.put(f"{ep}/authbkt/tampered.bin", data=bytes(body), headers=headers)
        assert r.status_code == 403

    def test_v2_signed_get(self, http_stack):
        ep = http_stack["endpoint"]
        http_stack["client"].put_object("authbkt", "v2obj", b"v2 data")
        headers = sign_request_v2("authak", "auth-secret", "GET", "/authbkt/v2obj", [], {})
        r = requests.get(f"{ep}/authbkt/v2obj", headers=headers)
        assert r.status_code == 200 and r.content == b"v2 data"

    def test_v2_presigned_get(self, http_stack):
        import urllib.parse

        ep = http_stack["endpoint"]
        host = urllib.parse.urlparse(ep).netloc
        http_stack["client"].put_object("authbkt", "v2pre", b"presigned v2")
        url = presign_url_v2("authak", "auth-secret", "GET", "/authbkt/v2pre", host)
        r = requests.get(url)
        assert r.status_code == 200 and r.content == b"presigned v2"

    def test_v2_bad_signature(self, http_stack):
        ep = http_stack["endpoint"]
        headers = sign_request_v2("authak", "wrong-secret", "GET", "/authbkt/v2obj", [], {})
        r = requests.get(f"{ep}/authbkt/v2obj", headers=headers)
        assert r.status_code == 403

    def test_post_policy_upload(self, http_stack):
        ep = http_stack["endpoint"]
        creds = Credentials("authak", "auth-secret")
        body, ctype = build_post_form(
            creds, "authbkt", "posted/file.txt", b"posted content",
            extra_fields={"success_action_status": "201"},
        )
        r = requests.post(f"{ep}/authbkt", data=body, headers={"Content-Type": ctype})
        assert r.status_code == 201, r.text
        assert "<PostResponse>" in r.text
        g = http_stack["client"].get_object("authbkt", "posted/file.txt")
        assert g.content == b"posted content"

    def test_post_policy_bad_signature(self, http_stack):
        ep = http_stack["endpoint"]
        creds = Credentials("authak", "bad-secret")
        body, ctype = build_post_form(creds, "authbkt", "nope.txt", b"data")
        r = requests.post(f"{ep}/authbkt", data=body, headers={"Content-Type": ctype})
        assert r.status_code == 403

    def test_post_policy_size_limit(self, http_stack):
        ep = http_stack["endpoint"]
        creds = Credentials("authak", "auth-secret")
        body, ctype = build_post_form(
            creds, "authbkt", "big.txt", b"x" * 100,
            extra_conditions=[["content-length-range", 1, 10]],
        )
        r = requests.post(f"{ep}/authbkt", data=body, headers={"Content-Type": ctype})
        assert r.status_code == 400


class TestPostPolicyHardening:
    """Regressions for policy-bucket binding, unknown-field rejection,
    and ${filename} substitution."""

    def test_bucket_mismatch_rejected(self, http_stack):
        ep = http_stack["endpoint"]
        creds = Credentials("authak", "auth-secret")
        http_stack["client"].make_bucket("otherbkt")
        # policy signed for authbkt, posted to otherbkt
        body, ctype = build_post_form(creds, "authbkt", "sneak.txt", b"x")
        r = requests.post(f"{ep}/otherbkt", data=body, headers={"Content-Type": ctype})
        assert r.status_code == 403, r.text
        assert "bucket" in r.text

    def test_unauthorized_field_rejected(self, http_stack):
        ep = http_stack["endpoint"]
        creds = Credentials("authak", "auth-secret")
        body, ctype = build_post_form(creds, "authbkt", "inj.txt", b"x")
        # inject an extra form field the policy never mentioned
        boundary = ctype.split("boundary=", 1)[1]
        inject = (
            f'--{boundary}\r\nContent-Disposition: form-data; '
            'name="x-amz-meta-owner"\r\n\r\nadmin\r\n'
        ).encode()
        body = inject + body
        r = requests.post(f"{ep}/authbkt", data=body, headers={"Content-Type": ctype})
        assert r.status_code == 403
        assert "x-amz-meta-owner" in r.text

    def test_filename_substitution(self, http_stack):
        import json as _json

        ep = http_stack["endpoint"]
        creds = Credentials("authak", "auth-secret")
        # key uses ${filename}; the policy must allow the prefix
        body, ctype = build_post_form(
            creds, "authbkt", "photos/${filename}", b"catbytes",
        )
        # the form builder names the file part 'upload'; give it a real name
        body = body.replace(b'filename="upload"', b'filename="cat.jpg"')
        r = requests.post(f"{ep}/authbkt", data=body, headers={"Content-Type": ctype})
        # eq $key condition binds the literal '${filename}' template; AWS
        # evaluates the substituted key, so the eq must match post-substitution.
        # Our builder pins the template, so this documents the strictness.
        if r.status_code == 200 or r.status_code == 204:
            g = http_stack["client"].get_object("authbkt", "photos/cat.jpg")
            assert g.content == b"catbytes"
