"""Zero-copy data-plane plumbing: buffer pool, lane pool, gathered writes.

Three layers, bottom up:

  * BufferPool/PooledBuffer -- refcount lifecycle, overflow behavior, and
    the poison-on-recycle contract;
  * LanePool -- per-lane FIFO with cross-lane overlap (the shard fan-out's
    ordering requirement);
  * append_iov -- LocalDrive's gathered writev, the interface fallback, and
    the metered drive-write MOVED accounting;

then the integration invariants the ISSUE names: a reader-based PUT moves
(never copies) its bytes across the pooled hops, and -- pigeonhole -- every
pooled window is back in the pool after a PUT, even one that dies on
chaos-injected drive faults.
"""

from __future__ import annotations

import threading

import pytest

from minio_tpu.control.profiler import GLOBAL_PROFILER
from minio_tpu.utils import bufpool, iopool
from minio_tpu.utils.bufpool import BufferPool
from minio_tpu.utils.iopool import LanePool


class TestBufferPool:
    def test_acquire_release_recycles_storage(self):
        pool = BufferPool(buf_size=64, capacity=2)
        pb = pool.acquire()
        assert len(pb) == 64
        storage = pb.data
        pb.release()
        assert pool.outstanding() == 0
        pb2 = pool.acquire()
        assert pb2.data is storage  # same bytearray came back
        assert pool.stats()["reuses"] == 1

    def test_acquire_never_blocks_past_capacity(self):
        pool = BufferPool(buf_size=8, capacity=1)
        a, b, c = pool.acquire(), pool.acquire(), pool.acquire()
        assert pool.outstanding() == 3
        assert pool.stats()["overflow_allocs"] == 2
        for pb in (a, b, c):
            pb.release()
        assert pool.outstanding() == 0
        # Only `capacity` buffers were retained on the free list.
        assert pool.stats()["free"] == 1

    def test_refcount_retain_release(self):
        pool = BufferPool(buf_size=8, capacity=1)
        pb = pool.acquire()
        pb.retain()
        pb.release()
        assert pool.outstanding() == 1  # one ref still live
        pb.release()
        assert pool.outstanding() == 0

    def test_release_past_zero_and_retain_after_death_raise(self):
        pool = BufferPool(buf_size=8, capacity=1)
        pb = pool.acquire()
        pb.release()
        with pytest.raises(RuntimeError):
            pb.release()
        with pytest.raises(RuntimeError):
            pb.retain()

    def test_recycle_poisons_stale_handles(self):
        pool = BufferPool(buf_size=8, capacity=1)
        pb = pool.acquire()
        pb.release()
        # The handle's storage is detached: new views see nothing, so a
        # use-after-release bug reads empty instead of another PUT's bytes.
        assert len(pb.view()) == 0

    def test_view_is_writable_window(self):
        pool = BufferPool(buf_size=8, capacity=1)
        pb = pool.acquire()
        pb.view(2, 5)[:] = b"xyz"
        assert bytes(pb.data[2:5]) == b"xyz"
        pb.release()

    def test_window_pool_is_a_shared_singleton(self):
        assert bufpool.window_pool() is bufpool.window_pool()
        assert bufpool.window_pool().buf_size == bufpool.WINDOW_BYTES

    def test_discard_never_repools_storage(self):
        pool = BufferPool(buf_size=8, capacity=2)
        pb = pool.acquire()
        storage = pb.data
        pb.discard()
        assert pool.outstanding() == 0
        assert pool.stats()["free"] == 0  # storage went to the allocator
        assert pool.stats()["discards"] == 1
        pb2 = pool.acquire()
        assert pb2.data is not storage
        pb2.release()

    def test_discard_after_release_still_raises(self):
        pool = BufferPool(buf_size=8, capacity=1)
        pb = pool.acquire()
        pb.release()
        with pytest.raises(RuntimeError):
            pb.discard()

    def test_release_or_discard_repools_when_unexported(self):
        pool = BufferPool(buf_size=8, capacity=2)
        pb = pool.acquire()
        mv = pb.view(0, 4)
        mv.release()
        pb.release_or_discard()
        assert pool.stats()["free"] == 1
        assert pool.stats()["discards"] == 0

    def test_release_or_discard_demotes_when_exported(self):
        # The GET stream contract: a consumer that kept a yielded chunk
        # must keep reading ITS bytes -- the storage leaves the pool
        # instead of recycling under the view.
        pool = BufferPool(buf_size=8, capacity=2)
        pb = pool.acquire()
        held = pb.view(0, 4)
        held[:4] = b"mine"
        pb.release_or_discard()
        assert pool.stats()["free"] == 0
        assert pool.stats()["discards"] == 1
        assert bytes(held) == b"mine"  # still valid, never reused
        pb2 = pool.acquire()
        pb2.view()[:4] = b"XXXX"  # a new request cannot corrupt the holder
        assert bytes(held) == b"mine"
        pb2.release()
        held.release()


class TestViewBounds:
    """PooledBuffer.view() bounds: after the last release poisons the
    storage to 0 bytes, an out-of-range slice must raise -- a silently
    empty view would mask exactly the use-after-release that the
    poisoning exists to surface."""

    def test_view_beyond_storage_raises_on_live_buffer(self):
        pool = BufferPool(buf_size=8, capacity=1)
        pb = pool.acquire()
        try:
            with pytest.raises(ValueError):
                pb.view(0, 9)
            with pytest.raises(ValueError):
                pb.view(9, 12)
        finally:
            pb.release()

    def test_view_with_negative_or_inverted_bounds_raises(self):
        pool = BufferPool(buf_size=8, capacity=1)
        pb = pool.acquire()
        try:
            with pytest.raises(ValueError):
                pb.view(-1, 4)
            with pytest.raises(ValueError):
                pb.view(5, 2)
        finally:
            pb.release()

    def test_sized_view_after_release_raises_not_empty(self):
        pool = BufferPool(buf_size=8, capacity=1)
        pb = pool.acquire()
        pb.release()
        # The poisoned handle has 0-byte storage: asking for the bytes the
        # buffer USED to hold must fail loudly, not hand back b"".
        with pytest.raises(ValueError):
            pb.view(0, 8)
        # The no-argument probe form stays: len()==0 is the poison signal.
        assert len(pb.view()) == 0


@pytest.mark.race
class TestOverflowAccountingRace:
    """The ISSUE flags overflow counters bumped outside the pool lock; the
    accounting lives INSIDE acquire()'s critical section (see bufpool),
    and this pins it: a barrier-synchronized burst where every thread
    acquires before any release must count exactly max(0, T - capacity)
    overflow allocations -- lost increments under-count, double bumps
    over-count, and either fails the exact equality."""

    def test_barrier_burst_counts_overflow_exactly(self):
        capacity, threads = 4, 16
        pool = BufferPool(buf_size=32, capacity=capacity)
        start = threading.Barrier(threads)
        acquired = threading.Barrier(threads)
        errors: list[BaseException] = []

        def worker():
            try:
                start.wait(5)
                pb = pool.acquire()
                acquired.wait(5)  # hold until EVERY thread has acquired
                pb.release()
            except BaseException as e:  # pragma: no cover - surfaced below
                errors.append(e)

        ts = [threading.Thread(target=worker) for _ in range(threads)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=10)
        assert not errors
        stats = pool.stats()
        assert stats["gets"] == threads
        assert stats["overflow_allocs"] == threads - capacity
        assert stats["outstanding"] == 0
        # Repeat rounds reuse the (now-warm) free list and must not drift
        # the overflow count: the free list never exceeds capacity.
        for _ in range(3):
            pbs = [pool.acquire() for _ in range(capacity)]
            for pb in pbs:
                pb.release()
        assert pool.stats()["overflow_allocs"] == threads - capacity


class TestLanePool:
    def test_per_lane_fifo_order(self):
        pool = LanePool(workers=4)
        out: list[int] = []
        ev = threading.Event()

        def slow_then_record(i):
            if i == 0:
                ev.wait(2)  # stall the lane head; followers must still wait
            out.append(i)

        futs = [pool.submit("d0", slow_then_record, i) for i in range(5)]
        ev.set()
        for f in futs:
            f.result(timeout=5)
        assert out == [0, 1, 2, 3, 4]
        pool.shutdown()

    def test_lanes_overlap_across_drives(self):
        # Lane A's task completes only after lane B's runs: if lanes were
        # serialized on one another this would deadlock (timeout).
        pool = LanePool(workers=2)
        b_ran = threading.Event()
        fa = pool.submit("a", lambda: b_ran.wait(5))
        fb = pool.submit("b", b_ran.set)
        assert fb.result(timeout=5) is None
        assert fa.result(timeout=5) is True
        pool.shutdown()

    def test_exception_surfaces_through_future_and_lane_survives(self):
        pool = LanePool(workers=1)

        def boom():
            raise OSError("disk on fire")

        f1 = pool.submit("d0", boom)
        f2 = pool.submit("d0", lambda: "fine")
        with pytest.raises(OSError):
            f1.result(timeout=5)
        assert f2.result(timeout=5) == "fine"
        pool.shutdown()

    def test_shard_writer_pool_is_a_shared_singleton(self):
        assert iopool.shard_writer_pool() is iopool.shard_writer_pool()


class TestAppendIov:
    def _drive(self, tmp_path):
        from minio_tpu.storage.local import LocalDrive

        d = LocalDrive(str(tmp_path))
        d.make_vol("v")
        return d

    def test_gathered_write_matches_joined_append(self, tmp_path):
        d = self._drive(tmp_path)
        d.append_iov("v", "f", [b"abc", memoryview(b"defg"), bytearray(b"hi")])
        d.append_iov("v", "f", [b"-tail"])
        assert d.read_all("v", "f") == b"abcdefghi-tail"

    def test_empty_iovecs_are_skipped(self, tmp_path):
        d = self._drive(tmp_path)
        d.append_iov("v", "g", [b"", b"x", memoryview(b""), b"y"])
        assert d.read_all("v", "g") == b"xy"

    def test_creates_missing_parent_dirs(self, tmp_path):
        d = self._drive(tmp_path)
        d.append_iov("v", "deep/nested/f", [b"data"])
        assert d.read_all("v", "deep/nested/f") == b"data"

    def test_interface_default_falls_back_to_append_file(self):
        from minio_tpu.storage.interface import StorageAPI

        calls = []

        class Fake:
            def append_file(self, volume, path, data):
                calls.append((volume, path, bytes(data)))

        StorageAPI.append_iov(Fake(), "v", "p", [b"ab", memoryview(b"cd")])
        assert calls == [("v", "p", b"abcd")]

    def test_metered_drive_records_drive_write_moves(self, tmp_path):
        from minio_tpu.storage.metered import MeteredDrive

        d = MeteredDrive(self._drive(tmp_path))
        GLOBAL_PROFILER.copy.reset()
        d.append_iov("v", "m", [b"12345", b"678"])
        hops = GLOBAL_PROFILER.copy.snapshot()["hops"]
        assert hops["drive-write"]["moved_bytes"] == 8
        assert hops["drive-write"]["copied_bytes"] == 0


class _ReadintoReader:
    """Reader exposing readinto() -- the pooled fill path's fast lane."""

    def __init__(self, data: bytes, chunk: int = 1 << 16):
        self._data = data
        self._pos = 0
        self._chunk = chunk

    def readinto(self, dest) -> int:
        n = min(len(dest), self._chunk, len(self._data) - self._pos)
        if n <= 0:
            return 0
        dest[:n] = self._data[self._pos : self._pos + n]
        self._pos += n
        return n

    def read(self, n: int = -1) -> bytes:  # pragma: no cover - readinto wins
        raise AssertionError("pooled fill must prefer readinto()")


class TestPutPipelineConservation:
    def _harness(self, tmp_path):
        from minio_tpu.storage.metered import MeteredDrive
        from tests.harness import ErasureHarness

        hz = ErasureHarness(tmp_path, n_disks=8)
        hz.layer.disks = [MeteredDrive(d) for d in hz.layer.disks]
        hz.layer.make_bucket("zb")
        return hz

    def test_reader_put_moves_never_copies_on_pooled_hops(self, tmp_path):
        hz = self._harness(tmp_path)
        size = (1 << 20) + 4097
        data = bytes(i % 241 for i in range(size))

        GLOBAL_PROFILER.copy.reset()
        hz.layer.put_object("zb", "obj", _ReadintoReader(data))
        hops = GLOBAL_PROFILER.copy.snapshot()["hops"]
        # The ISSUE's acceptance walk: socket-read -> ... -> shard-fanout
        # hops carry the object as MOVES; zero copied bytes on the pooled
        # path (this process has no socket hop -- the reader IS the body).
        assert hops["erasure-stage"]["moved_bytes"] >= size
        assert hops["erasure-stage"]["copied_bytes"] == 0
        assert hops["shard-fanout"]["moved_bytes"] >= size
        assert hops["shard-fanout"]["copied_bytes"] == 0
        assert hops["drive-write"]["moved_bytes"] >= size
        _, got = hz.layer.get_object("zb", "obj")
        assert got == data

    def test_pool_windows_all_returned_after_clean_put(self, tmp_path):
        hz = self._harness(tmp_path)
        pool = bufpool.window_pool()
        before = pool.outstanding()
        data = bytes(199) * 9000  # ~1.7 MiB, beyond the inline threshold
        hz.layer.put_object("zb", "clean", _ReadintoReader(data))
        assert pool.outstanding() == before


class TestPoolPigeonholeUnderChaos:
    """Every pooled window is back after a PUT the chaos layer kills."""

    def test_faulted_puts_leak_no_windows(self, tmp_path):
        from minio_tpu.chaos.faults import DRIVE_ERROR, FaultSpec
        from tests.chaos_scenarios import chaos_harness

        hz, reg = chaos_harness(tmp_path, n_disks=8, parity=2)
        hz.layer.make_bucket("zb")
        pool = bufpool.window_pool()
        before = pool.outstanding()
        data = bytes(197) * 11000  # > 2 MiB: streams through the pool

        # Errors on every drive: the PUT must fail its write quorum.
        reg.arm(FaultSpec(kind=DRIVE_ERROR, target="", count=-1, seed=3))
        try:
            with pytest.raises(Exception):
                hz.layer.put_object("zb", "doomed", _ReadintoReader(data))
        finally:
            reg.disarm_all()
        assert pool.outstanding() == before

        # Partial fault: two drives erroring stays within parity quorum --
        # the PUT succeeds, and still returns every window.
        reg.arm(FaultSpec(kind=DRIVE_ERROR, target="disk1", count=-1, seed=5))
        reg.arm(FaultSpec(kind=DRIVE_ERROR, target="disk6", count=-1, seed=6))
        try:
            hz.layer.put_object("zb", "survives", _ReadintoReader(data))
        finally:
            reg.disarm_all()
        assert pool.outstanding() == before
        _, got = hz.layer.get_object("zb", "survives")
        assert got == data
