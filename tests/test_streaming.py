"""Streaming data-path tests (VERDICT #3): bounded-memory put/get/range.

Mirrors the reference's discipline: 1 MiB blocks stream end to end, range
reads map to block/shard offsets and touch only covered frames
(cmd/erasure-encode.go:73-109, erasure-decode.go:31-202,
erasure-coding.go:141 ShardFileOffset).
"""

from __future__ import annotations

import os
import subprocess
import sys

import numpy as np
import pytest

from minio_tpu.object.codec import HostCodec
from minio_tpu.object.erasure import (
    BLOCK_SIZE,
    DIGEST_LEN,
    GROUP_BLOCKS,
    ErasureObjects,
)
from minio_tpu.storage import format as fmt
from minio_tpu.storage.local import LocalDrive


class CountingDrive(LocalDrive):
    """LocalDrive recording every shard-file read (path, offset, length)."""

    def __init__(self, root):
        super().__init__(root)
        self.reads: list[tuple[str, int, int]] = []

    def read_file(self, volume, path, offset=0, length=-1):
        data = super().read_file(volume, path, offset, length)
        self.reads.append((path, offset, len(data)))
        return data

    def read_file_into(self, volume, path, offset, buf):
        n = super().read_file_into(volume, path, offset, buf)
        self.reads.append((path, offset, n))
        return n


class RecordingCodec(HostCodec):
    def __init__(self):
        super().__init__()
        self.encode_sizes: list[int] = []

    def encode(self, blocks, k, m):
        self.encode_sizes.append(len(blocks))
        return super().encode(blocks, k, m)

    def encode_frames(self, blocks, k, m):
        # The streaming writer encodes via the framed-row entry point; count
        # group sizes here too, but only once per group (the default
        # implementation recurses into encode()).
        uniform = self._native is not None and blocks and len({len(b) for b in blocks}) == 1
        if uniform:
            self.encode_sizes.append(len(blocks))
        return super().encode_frames(blocks, k, m)

    def encode_group(self, blocks, k, m):
        # The PUT pipeline's scatter entry point: count native-path groups
        # directly; irregular groups recurse into encode() which counts.
        uniform = self._native is not None and blocks and len({len(b) for b in blocks}) == 1
        if uniform:
            self.encode_sizes.append(len(blocks))
        return super().encode_group(blocks, k, m)


@pytest.fixture
def counted(tmp_path):
    n = 8
    dirs = [str(tmp_path / f"disk{i}") for i in range(n)]
    formats = fmt.init_format(1, n)
    drives = []
    for d, f in zip(dirs, formats):
        os.makedirs(d, exist_ok=True)
        f.save(d)
        drives.append(CountingDrive(d))
    codec = RecordingCodec()
    layer = ErasureObjects(drives, codec=codec)
    layer.make_bucket("b")
    return layer, drives, codec


def _body(size: int, seed: int = 0) -> bytes:
    return np.random.default_rng(seed).integers(0, 256, size, dtype=np.uint8).tobytes()


def test_round_trip_and_ranges(counted):
    layer, drives, codec = counted
    body = _body(5 * BLOCK_SIZE + 12345)
    layer.put_object("b", "o", body)
    _, got = layer.get_object("b", "o")
    assert got == body
    for off, ln in [(0, 10), (BLOCK_SIZE - 5, 10), (3 * BLOCK_SIZE + 7, 2 * BLOCK_SIZE),
                    (len(body) - 9, 9), (len(body), 0), (0, -1), (12345, -1)]:
        _, got = layer.get_object("b", "o", offset=off, length=ln)
        end = len(body) if ln < 0 else min(off + ln, len(body))
        assert got == body[off:end], (off, ln)


def test_range_read_touches_only_covered_blocks(counted):
    """A small range read of a large object reads <=2 blocks' frames per
    shard file, from the mapped offset -- never the whole file."""
    layer, drives, codec = counted
    k = layer._data_blocks()
    body = _body(32 * BLOCK_SIZE)  # 32 MiB, 32 blocks
    layer.put_object("b", "big", body)
    for d in drives:
        d.reads.clear()

    off = 17 * BLOCK_SIZE + 100
    _, got = layer.get_object("b", "big", offset=off, length=1000)
    assert got == body[off : off + 1000]

    chunk_full = -(-BLOCK_SIZE // k)
    frame_full = DIGEST_LEN + chunk_full
    part_reads = [r for d in drives for r in d.reads if "part.1" in r[0]]
    # Only the k data shards are read, one windowed read each.
    assert len(part_reads) == k, part_reads
    for path, offset, length in part_reads:
        assert offset == 17 * frame_full
        assert length <= 2 * frame_full


def test_streaming_put_bounded_groups(counted):
    """Encode runs in GROUP_BLOCKS batches -- the working set is bounded."""
    layer, drives, codec = counted
    body = _body(40 * BLOCK_SIZE + 777)
    layer.put_object("b", "g", body)
    assert max(codec.encode_sizes) <= GROUP_BLOCKS
    # 41 blocks -> at least 3 groups.
    put_calls = [s for s in codec.encode_sizes if s > 0]
    assert sum(put_calls) == 41
    _, got = layer.get_object("b", "g")
    assert got == body


def test_streaming_reader_input(counted):
    """put_object accepts a .read(n) stream and never materializes it."""
    layer, drives, codec = counted

    class ChunkReader:
        def __init__(self, total, chunk=65536):
            self.total, self.pos, self.chunk = total, 0, chunk

        def read(self, n):
            n = min(n, self.chunk, self.total - self.pos)
            if n <= 0:
                return b""
            out = (self.pos % 251).to_bytes(1, "big") * n
            self.pos += n
            return out

    total = 7 * BLOCK_SIZE + 99
    oi = layer.put_object("b", "r", ChunkReader(total))
    assert oi.size == total
    _, got = layer.get_object("b", "r")
    want = b"".join((p % 251).to_bytes(1, "big") for p in range(0, 1))  # spot checks below
    assert len(got) == total
    # Spot-check bytes at chunk boundaries.
    for pos in [0, 65535, 65536, BLOCK_SIZE, total - 1]:
        assert got[pos : pos + 1] == ((pos - pos % 65536) % 251).to_bytes(1, "big"), pos


def test_degraded_windowed_read(counted, tmp_path):
    """Range reads reconstruct from parity when data shards are lost or
    corrupt -- spares loaded for the same window only."""
    layer, drives, codec = counted
    body = _body(10 * BLOCK_SIZE + 5)
    layer.put_object("b", "d", body)

    # Kill two drives entirely (parity for 8 drives = 4).
    layer.disks[0] = None
    layer.disks[3] = None
    _, got = layer.get_object("b", "d", offset=9 * BLOCK_SIZE, length=BLOCK_SIZE + 5)
    assert got == body[9 * BLOCK_SIZE :]
    _, got = layer.get_object("b", "d")
    assert got == body


def test_multipart_zero_byte_part(counted):
    """S3 permits a zero-byte (only/last) part -- e.g. an empty object
    created via multipart upload."""
    layer, drives, codec = counted
    mp = layer.multipart
    up = mp.new_multipart_upload("b", "empty")
    p1 = mp.put_object_part("b", "empty", up, 1, b"")
    assert p1.size == 0
    mp.complete_multipart_upload("b", "empty", up, [(1, p1.etag)])
    oi, got = layer.get_object("b", "empty")
    assert got == b""
    assert oi.size == 0


def test_multipart_streaming_and_cross_part_range(counted):
    layer, drives, codec = counted
    mp = layer.multipart
    up = mp.new_multipart_upload("b", "mp")
    p1_body = _body(5 * (1 << 20), seed=1)
    p2_body = _body(3 * (1 << 20) + 17, seed=2)
    p1 = mp.put_object_part("b", "mp", up, 1, p1_body)
    p2 = mp.put_object_part("b", "mp", up, 2, p2_body)
    assert max(codec.encode_sizes) <= GROUP_BLOCKS
    mp.complete_multipart_upload("b", "mp", up, [(1, p1.etag), (2, p2.etag)])
    full = p1_body + p2_body
    _, got = layer.get_object("b", "mp")
    assert got == full
    # Range crossing the part boundary.
    off = 5 * (1 << 20) - 1000
    _, got = layer.get_object("b", "mp", offset=off, length=2000)
    assert got == full[off : off + 2000]


def test_get_object_stream_yields_chunks(counted):
    layer, drives, codec = counted
    body = _body(3 * BLOCK_SIZE)
    layer.put_object("b", "s", body)
    oi, stream = layer.get_object_stream("b", "s")
    chunks = list(stream)
    assert all(len(c) <= BLOCK_SIZE for c in chunks)
    assert b"".join(chunks) == body
    assert oi.size == len(body)


_RSS_SCRIPT = r"""
import os, resource, sys
sys.path.insert(0, {repo!r})
from minio_tpu.object.erasure import ErasureObjects, BLOCK_SIZE
from minio_tpu.storage import format as fmt
from minio_tpu.storage.local import LocalDrive

root = {root!r}
n = 8
drives = []
formats = fmt.init_format(1, n)
for i, f in enumerate(formats):
    d = os.path.join(root, f"disk{{i}}")
    os.makedirs(d, exist_ok=True)
    f.save(d)
    drives.append(LocalDrive(d))
layer = ErasureObjects(drives)
layer.make_bucket("b")
baseline_mib = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024

TOTAL = 512 * (1 << 20)

class Gen:
    def __init__(self):
        self.pos = 0
    def read(self, nbytes):
        nbytes = min(nbytes, TOTAL - self.pos)
        if nbytes <= 0:
            return b""
        out = bytes([self.pos // BLOCK_SIZE % 256]) * nbytes
        self.pos += nbytes
        return out

layer.put_object("b", "huge", Gen())
oi, stream = layer.get_object_stream("b", "huge")
total = 0
for i, chunk in enumerate(stream):
    total += len(chunk)
assert total == TOTAL, total
peak_mib = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024
delta = peak_mib - baseline_mib
print("BASELINE_MIB", baseline_mib, "PEAK_MIB", peak_mib, "DELTA_MIB", delta)
assert delta < 160, f"RSS grew {{delta}} MiB over baseline (O(objectSize) would be >1200)"
print("OK")
"""


def test_large_object_bounded_rss(tmp_path):
    """512 MiB object put+get in a clean subprocess grows RSS by <160 MiB
    over the post-import baseline (O(objectSize) buffering would need
    ~1.2 GiB: the object plus its encoded shard files)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = _RSS_SCRIPT.format(repo=repo, root=str(tmp_path))
    out = subprocess.run(
        [sys.executable, "-c", script],
        capture_output=True,
        text=True,
        timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert out.returncode == 0, out.stdout + out.stderr
    assert "OK" in out.stdout
