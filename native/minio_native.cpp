// Native host kernels: Reed-Solomon GF(2^8) + HighwayHash-256.
//
// Role of the reference's assembly-backed Go deps (SURVEY.md section 2.9:
// klauspost/reedsolomon AVX2 + minio/highwayhash SIMD): the CPU side of the
// framework. Two jobs:
//   1. low-latency fallback codec for small/cold requests where a device
//      round-trip isn't worth it (the batching runtime decides);
//   2. the honest AVX2 CPU baseline that bench.py compares the TPU path to.
//
// RS encode uses the classic PSHUFB nibble-table scheme on AVX2 (32 bytes per
// instruction per coefficient) with a portable scalar fallback; bit-exact
// with ops/gf.py tables (poly 0x11d, Vandermonde-systematic matrix fed in by
// the Python side). HighwayHash is the frozen 2017 spec, bit-exact with
// ops/highwayhash.py.
//
// Built as libminio_native.so via native/Makefile; loaded with ctypes
// (minio_tpu/ops/native.py). No Python.h dependency.

#include <cstdint>
#include <cstring>
#include <cstddef>

#ifdef __AVX2__
#include <immintrin.h>
#endif

extern "C" {

// ---------------------------------------------------------------------------
// GF(2^8), poly 0x11d
// ---------------------------------------------------------------------------

static uint8_t GF_MUL[256][256];
static uint8_t GF_LO[256][16];  // GF_LO[c][n] = c * n
static uint8_t GF_HI[256][16];  // GF_HI[c][n] = c * (n << 4)
static bool gf_ready = false;

static void gf_init() {
    if (gf_ready) return;
    uint8_t exp[512];
    int log[256];
    int x = 1;
    for (int i = 0; i < 255; i++) {
        exp[i] = (uint8_t)x;
        log[x] = i;
        x <<= 1;
        if (x & 0x100) x ^= 0x11d;
    }
    for (int i = 255; i < 512; i++) exp[i] = exp[i - 255];
    for (int a = 0; a < 256; a++) {
        GF_MUL[0][a] = GF_MUL[a][0] = 0;
    }
    for (int a = 1; a < 256; a++)
        for (int b = 1; b < 256; b++)
            GF_MUL[a][b] = exp[log[a] + log[b]];
    for (int c = 0; c < 256; c++) {
        for (int n = 0; n < 16; n++) {
            GF_LO[c][n] = GF_MUL[c][n];
            GF_HI[c][n] = GF_MUL[c][n << 4];
        }
    }
    gf_ready = true;
}

// out[m][s] ^= coeff * in[s] for one (coeff, input-shard, output-shard) triple.
static void gf_mul_xor(uint8_t coeff, const uint8_t* in, uint8_t* out, size_t n) {
    if (coeff == 0) return;
    size_t i = 0;
#ifdef __AVX2__
    const __m256i lo_tab = _mm256_broadcastsi128_si256(
        _mm_loadu_si128((const __m128i*)GF_LO[coeff]));
    const __m256i hi_tab = _mm256_broadcastsi128_si256(
        _mm_loadu_si128((const __m128i*)GF_HI[coeff]));
    const __m256i mask = _mm256_set1_epi8(0x0f);
    for (; i + 32 <= n; i += 32) {
        __m256i v = _mm256_loadu_si256((const __m256i*)(in + i));
        __m256i lo = _mm256_and_si256(v, mask);
        __m256i hi = _mm256_and_si256(_mm256_srli_epi64(v, 4), mask);
        __m256i r = _mm256_xor_si256(_mm256_shuffle_epi8(lo_tab, lo),
                                     _mm256_shuffle_epi8(hi_tab, hi));
        __m256i o = _mm256_loadu_si256((const __m256i*)(out + i));
        _mm256_storeu_si256((__m256i*)(out + i), _mm256_xor_si256(o, r));
    }
#endif
    const uint8_t* mul = GF_MUL[coeff];
    for (; i < n; i++) out[i] ^= mul[in[i]];
}

// Encode: data [k][shard_len] contiguous, matrix [m][k], out [m][shard_len].
void rs_encode(int k, int m, const uint8_t* matrix, const uint8_t* data,
               uint8_t* out, size_t shard_len) {
    gf_init();
    memset(out, 0, (size_t)m * shard_len);
    for (int mi = 0; mi < m; mi++) {
        uint8_t* dst = out + (size_t)mi * shard_len;
        for (int ki = 0; ki < k; ki++) {
            gf_mul_xor(matrix[mi * k + ki], data + (size_t)ki * shard_len, dst,
                       shard_len);
        }
    }
}

// Apply an arbitrary [r][k] coefficient matrix (decode/reconstruct path).
void rs_apply(int k, int r, const uint8_t* matrix, const uint8_t* data,
              uint8_t* out, size_t shard_len) {
    rs_encode(k, r, matrix, data, out, shard_len);
}

// ---------------------------------------------------------------------------
// HighwayHash-256 (frozen spec)
// ---------------------------------------------------------------------------

typedef struct {
    uint64_t v0[4], v1[4], mul0[4], mul1[4];
} hh_state;

static const uint64_t HH_INIT0[4] = {0xdbe6d5d5fe4cce2fULL, 0xa4093822299f31d0ULL,
                                     0x13198a2e03707344ULL, 0x243f6a8885a308d3ULL};
static const uint64_t HH_INIT1[4] = {0x3bd39e10cb0ef593ULL, 0xc0acf169b5f18a8cULL,
                                     0xbe5466cf34e90c6cULL, 0x452821e638d01377ULL};

static inline uint64_t rot32(uint64_t x) { return (x >> 32) | (x << 32); }

static void hh_reset(hh_state* s, const uint8_t* key32) {
    uint64_t k[4];
    memcpy(k, key32, 32);
    for (int i = 0; i < 4; i++) {
        s->mul0[i] = HH_INIT0[i];
        s->mul1[i] = HH_INIT1[i];
        s->v0[i] = HH_INIT0[i] ^ k[i];
        s->v1[i] = HH_INIT1[i] ^ rot32(k[i]);
    }
}

static inline void zipper_merge_add(uint64_t v1, uint64_t v0, uint64_t* add1,
                                    uint64_t* add0) {
    *add0 += (((v0 & 0xff000000ULL) | (v1 & 0xff00000000ULL)) >> 24) |
             (((v0 & 0xff0000000000ULL) | (v1 & 0xff000000000000ULL)) >> 16) |
             (v0 & 0xff0000ULL) | ((v0 & 0xff00ULL) << 32) |
             ((v1 & 0xff00000000000000ULL) >> 8) | (v0 << 56);
    *add1 += (((v1 & 0xff000000ULL) | (v0 & 0xff00000000ULL)) >> 24) |
             (v1 & 0xff0000ULL) | ((v1 & 0xff0000000000ULL) >> 16) |
             ((v1 & 0xff00ULL) << 24) | ((v0 & 0xff000000000000ULL) >> 8) |
             ((v1 & 0xffULL) << 48) | (v0 & 0xff00000000000000ULL);
}

static void hh_update(hh_state* s, const uint64_t lanes[4]) {
    for (int i = 0; i < 4; i++) {
        s->v1[i] += s->mul0[i] + lanes[i];
        s->mul0[i] ^= (s->v1[i] & 0xffffffffULL) * (s->v0[i] >> 32);
        s->v0[i] += s->mul1[i];
        s->mul1[i] ^= (s->v0[i] & 0xffffffffULL) * (s->v1[i] >> 32);
    }
    zipper_merge_add(s->v1[1], s->v1[0], &s->v0[1], &s->v0[0]);
    zipper_merge_add(s->v1[3], s->v1[2], &s->v0[3], &s->v0[2]);
    zipper_merge_add(s->v0[1], s->v0[0], &s->v1[1], &s->v1[0]);
    zipper_merge_add(s->v0[3], s->v0[2], &s->v1[3], &s->v1[2]);
}

static void hh_update_packet(hh_state* s, const uint8_t* p) {
    uint64_t lanes[4];
    memcpy(lanes, p, 32);
    hh_update(s, lanes);
}

#ifdef __AVX2__
// AVX2 packet chain: the whole 4-lane state rides one ymm per variable, the
// zipper merge is a single PSHUFB whose byte map is derived from (and pinned
// against) the scalar zipper_merge_add above. Remainder + finalization stay
// scalar -- they are O(10) updates vs O(len/32) in the chain.
typedef struct {
    __m256i v0, v1, mul0, mul1;
} hh_state_avx;

static inline __m256i hh_zipper_avx(__m256i v) {
    // Per 128-bit lane-pair: out bytes [0..7] = src [3,12,2,5,14,1,15,0],
    // out [8..15] = src [11,4,10,13,9,6,8,7] (LSB-first, == scalar masks).
    const __m256i zmask = _mm256_set_epi64x(
        0x070806090D0A040BULL, 0x000F010E05020C03ULL,
        0x070806090D0A040BULL, 0x000F010E05020C03ULL);
    return _mm256_shuffle_epi8(v, zmask);
}

static inline void hh_update_avx(hh_state_avx* s, __m256i lanes) {
    s->v1 = _mm256_add_epi64(s->v1, _mm256_add_epi64(s->mul0, lanes));
    s->mul0 = _mm256_xor_si256(
        s->mul0, _mm256_mul_epu32(s->v1, _mm256_srli_epi64(s->v0, 32)));
    s->v0 = _mm256_add_epi64(s->v0, s->mul1);
    s->mul1 = _mm256_xor_si256(
        s->mul1, _mm256_mul_epu32(s->v0, _mm256_srli_epi64(s->v1, 32)));
    s->v0 = _mm256_add_epi64(s->v0, hh_zipper_avx(s->v1));
    s->v1 = _mm256_add_epi64(s->v1, hh_zipper_avx(s->v0));
}

static inline hh_state_avx hh_load_avx(const hh_state* s) {
    hh_state_avx a;
    a.v0 = _mm256_loadu_si256((const __m256i*)s->v0);
    a.v1 = _mm256_loadu_si256((const __m256i*)s->v1);
    a.mul0 = _mm256_loadu_si256((const __m256i*)s->mul0);
    a.mul1 = _mm256_loadu_si256((const __m256i*)s->mul1);
    return a;
}

static inline void hh_store_avx(const hh_state_avx* a, hh_state* s) {
    _mm256_storeu_si256((__m256i*)s->v0, a->v0);
    _mm256_storeu_si256((__m256i*)s->v1, a->v1);
    _mm256_storeu_si256((__m256i*)s->mul0, a->mul0);
    _mm256_storeu_si256((__m256i*)s->mul1, a->mul1);
}

// Run the full-packet chain for one stream on the vector unit.
static void hh_chain_avx(hh_state* s, const uint8_t* data, size_t n_packets) {
    hh_state_avx a = hh_load_avx(s);
    for (size_t i = 0; i < n_packets; i++)
        hh_update_avx(&a, _mm256_loadu_si256((const __m256i*)(data + i * 32)));
    hh_store_avx(&a, s);
}

// Two independent streams interleaved: each update is a serial dependency
// chain, so a second in-flight state nearly doubles throughput (ILP), the
// same per-shard parallelism the batched device hash exploits.
static void hh_chain_avx2x(hh_state* s0, const uint8_t* d0, hh_state* s1,
                           const uint8_t* d1, size_t n_packets) {
    hh_state_avx a0 = hh_load_avx(s0), a1 = hh_load_avx(s1);
    for (size_t i = 0; i < n_packets; i++) {
        hh_update_avx(&a0, _mm256_loadu_si256((const __m256i*)(d0 + i * 32)));
        hh_update_avx(&a1, _mm256_loadu_si256((const __m256i*)(d1 + i * 32)));
    }
    hh_store_avx(&a0, s0);
    hh_store_avx(&a1, s1);
}
#endif  // __AVX2__

static void hh_permute_update(hh_state* s) {
    uint64_t p[4] = {rot32(s->v0[2]), rot32(s->v0[3]), rot32(s->v0[0]),
                     rot32(s->v0[1])};
    hh_update(s, p);
}

static void hh_remainder(hh_state* s, const uint8_t* bytes, size_t size_mod32) {
    const size_t size_mod4 = size_mod32 & 3;
    const uint8_t* remainder = bytes + (size_mod32 & ~3ULL);
    uint8_t packet[32] = {0};
    for (int i = 0; i < 4; i++)
        s->v0[i] += ((uint64_t)size_mod32 << 32) + size_mod32;
    // Rotate both 32-bit halves of each v1 lane left by size_mod32.
    for (int i = 0; i < 4; i++) {
        uint32_t lo = (uint32_t)s->v1[i], hi = (uint32_t)(s->v1[i] >> 32);
        if (size_mod32) {
            lo = (lo << size_mod32) | (lo >> (32 - size_mod32));
            hi = (hi << size_mod32) | (hi >> (32 - size_mod32));
        }
        s->v1[i] = ((uint64_t)hi << 32) | lo;
    }
    memcpy(packet, bytes, size_mod32 & ~3ULL);
    if (size_mod32 & 16) {
        for (int i = 0; i < 4; i++)
            packet[28 + i] = remainder[(ptrdiff_t)(i + size_mod4) - 4];
    } else if (size_mod4) {
        packet[16] = remainder[0];
        packet[17] = remainder[size_mod4 >> 1];
        packet[18] = remainder[size_mod4 - 1];
    }
    hh_update_packet(s, packet);
}

static void hh_modular_reduction(uint64_t a3u, uint64_t a2, uint64_t a1,
                                 uint64_t a0, uint64_t* m1, uint64_t* m0) {
    uint64_t a3 = a3u & 0x3fffffffffffffffULL;
    *m1 = a1 ^ ((a3 << 1) | (a2 >> 63)) ^ ((a3 << 2) | (a2 >> 62));
    *m0 = a0 ^ (a2 << 1) ^ (a2 << 2);
}

// Remainder + 10 permute rounds + modular reduction (scalar; O(10) updates).
static void hh_finalize(hh_state* s, const uint8_t* tail, size_t r,
                        uint8_t* out32) {
    if (r) hh_remainder(s, tail, r);
    for (int i = 0; i < 10; i++) hh_permute_update(s);
    uint64_t h[4];
    hh_modular_reduction(s->v1[1] + s->mul1[1], s->v1[0] + s->mul1[0],
                         s->v0[1] + s->mul0[1], s->v0[0] + s->mul0[0], &h[1],
                         &h[0]);
    hh_modular_reduction(s->v1[3] + s->mul1[3], s->v1[2] + s->mul1[2],
                         s->v0[3] + s->mul0[3], s->v0[2] + s->mul0[2], &h[3],
                         &h[2]);
    memcpy(out32, h, 32);
}

void hh256(const uint8_t* key32, const uint8_t* data, size_t len,
           uint8_t* out32) {
    hh_state s;
    hh_reset(&s, key32);
    size_t n_full = len / 32;
#ifdef __AVX2__
    hh_chain_avx(&s, data, n_full);
#else
    for (size_t i = 0; i < n_full; i++) hh_update_packet(&s, data + i * 32);
#endif
    hh_finalize(&s, data + n_full * 32, len - n_full * 32, out32);
}

// Hash n equal-length streams laid out contiguously: data[i] at i*stride.
// Streams are independent, so pairs run interleaved to break the per-packet
// dependency chain (the scalar/AVX2 analogue of the device batch axis).
void hh256_batch(const uint8_t* key32, const uint8_t* data, size_t stride,
                 size_t len, size_t n, uint8_t* out) {
    size_t i = 0;
#ifdef __AVX2__
    size_t n_full = len / 32, r = len - n_full * 32;
    for (; i + 2 <= n; i += 2) {
        hh_state s0, s1;
        hh_reset(&s0, key32);
        hh_reset(&s1, key32);
        const uint8_t* d0 = data + i * stride;
        const uint8_t* d1 = data + (i + 1) * stride;
        hh_chain_avx2x(&s0, d0, &s1, d1, n_full);
        hh_finalize(&s0, d0 + n_full * 32, r, out + i * 32);
        hh_finalize(&s1, d1 + n_full * 32, r, out + (i + 1) * 32);
    }
#endif
    for (; i < n; i++) hh256(key32, data + i * stride, len, out + i * 32);
}

// Verify n interleaved H(chunk)||chunk frames in place (the GET/deep-scan
// read side of hh256_frame): data holds n frames of (32 + chunk_len) bytes;
// ok_out[i] = 1 when the stored digest matches the recomputed one. Streams
// are independent, so pairs run interleaved like the write side.
void hh256_verify_frames(const uint8_t* key32, const uint8_t* data,
                         size_t chunk_len, size_t n, uint8_t* ok_out) {
    // One batched hash over the chunks (stride = whole frame, so the stored
    // digests are skipped), then a memcmp per frame -- reuses hh256_batch's
    // interleaved SIMD loop instead of carrying a third copy of it.
    const size_t frame = 32 + chunk_len;
    uint8_t sums_stack[64 * 32];
    uint8_t* sums = sums_stack;
    uint8_t* heap = nullptr;
    if (n > 64) sums = heap = new uint8_t[n * 32];
    hh256_batch(key32, data + 32, frame, chunk_len, n, sums);
    for (size_t i = 0; i < n; i++)
        ok_out[i] = memcmp(sums + i * 32, data + i * frame, 32) == 0;
    delete[] heap;
}

// Interleaved bitrot framing in one pass: for each of n chunks of chunk_len
// bytes (stride apart), write H(chunk) || chunk into dst.
void hh256_frame(const uint8_t* key32, const uint8_t* data, size_t stride,
                 size_t chunk_len, size_t n, uint8_t* dst) {
    size_t i = 0;
    const size_t frame = 32 + chunk_len;
#ifdef __AVX2__
    size_t n_full = chunk_len / 32, r = chunk_len - n_full * 32;
    for (; i + 2 <= n; i += 2) {
        hh_state s0, s1;
        hh_reset(&s0, key32);
        hh_reset(&s1, key32);
        const uint8_t* d0 = data + i * stride;
        const uint8_t* d1 = data + (i + 1) * stride;
        hh_chain_avx2x(&s0, d0, &s1, d1, n_full);
        uint8_t* f0 = dst + i * frame;
        uint8_t* f1 = f0 + frame;
        hh_finalize(&s0, d0 + n_full * 32, r, f0);
        hh_finalize(&s1, d1 + n_full * 32, r, f1);
        memcpy(f0 + 32, d0, chunk_len);
        memcpy(f1 + 32, d1, chunk_len);
    }
#endif
    for (; i < n; i++) {
        uint8_t* f = dst + i * frame;
        hh256(key32, data + i * stride, chunk_len, f);
        memcpy(f + 32, data + i * stride, chunk_len);
    }
}

// ---------------------------------------------------------------------------
// Snappy block format: the fast transparent-compression codec.
//
// Role of the reference's S2 writer (cmd/object-api-utils.go:907,
// klauspost/compress/s2): an LZ77-class byte codec fast enough to sit in a
// GiB/s data plane. S2's wire format is a superset of snappy; this emits the
// interoperable snappy baseline: a uvarint uncompressed length, then literal
// and copy elements. Greedy 4-byte hash matching over independent 64 KiB
// windows (offsets always fit 16 bits, so only 1- and 2-byte-offset copy
// tags are emitted). Decoder accepts the full format incl. 4-byte offsets.
// ---------------------------------------------------------------------------

static inline uint32_t sn_load32(const uint8_t* p) {
    uint32_t v; memcpy(&v, p, 4); return v;
}
static inline uint64_t sn_load64(const uint8_t* p) {
    uint64_t v; memcpy(&v, p, 8); return v;
}
static inline uint32_t sn_hash(uint32_t v) {
    return (v * 0x1e35a7bdu) >> 18;  // 14-bit table
}

// Literal length-header ladder (tag byte + 0-4 length bytes).
static inline size_t sn_literal_header(uint8_t* dst, size_t len) {
    uint8_t* d = dst;
    size_t n = len - 1;
    if (n < 60) {
        *d++ = (uint8_t)(n << 2);
    } else if (n < (1u << 8)) {
        *d++ = 60 << 2; *d++ = (uint8_t)n;
    } else if (n < (1u << 16)) {
        *d++ = 61 << 2; *d++ = (uint8_t)n; *d++ = (uint8_t)(n >> 8);
    } else if (n < (1u << 24)) {
        *d++ = 62 << 2; *d++ = (uint8_t)n; *d++ = (uint8_t)(n >> 8);
        *d++ = (uint8_t)(n >> 16);
    } else {
        *d++ = 63 << 2; *d++ = (uint8_t)n; *d++ = (uint8_t)(n >> 8);
        *d++ = (uint8_t)(n >> 16); *d++ = (uint8_t)(n >> 24);
    }
    return (size_t)(d - dst);
}

// Tail-safe variant: exact-length copy, no overread. Used where src+16 may
// run past the input buffer (the block remainder and sub-16-byte blocks).
static size_t sn_emit_literal_tail(uint8_t* dst, const uint8_t* src, size_t len) {
    size_t h = sn_literal_header(dst, len);
    memcpy(dst + h, src, len);
    return h + len;
}

static size_t sn_emit_literal(uint8_t* dst, const uint8_t* src, size_t len) {
    if (len <= 16) {  // short literals dominate text; one 16B blast
        *dst = (uint8_t)((len - 1) << 2);  // (dst has MaxEncodedLen slack)
        memcpy(dst + 1, src, 16);
        return 1 + len;
    }
    size_t h = sn_literal_header(dst, len);
    memcpy(dst + h, src, len);
    return h + len;
}

static size_t sn_emit_copy(uint8_t* dst, size_t offset, size_t len) {
    uint8_t* d = dst;
    // Long matches: 64-byte 2-byte-offset copies, with the snappy trick of
    // leaving a 60..67-byte tail so the final copies stay in one element.
    while (len >= 68) {
        *d++ = (63 << 2) | 2; *d++ = (uint8_t)offset; *d++ = (uint8_t)(offset >> 8);
        len -= 64;
    }
    if (len > 64) {
        *d++ = (59 << 2) | 2; *d++ = (uint8_t)offset; *d++ = (uint8_t)(offset >> 8);
        len -= 60;
    }
    if (len >= 12 || offset >= 2048) {
        *d++ = (uint8_t)(((len - 1) << 2) | 2);
        *d++ = (uint8_t)offset; *d++ = (uint8_t)(offset >> 8);
    } else {
        *d++ = (uint8_t)(((offset >> 8) << 5) | ((len - 4) << 2) | 1);
        *d++ = (uint8_t)offset;
    }
    return (size_t)(d - dst);
}

// Greedy matcher over one block (n <= 65536). Returns bytes written.
static size_t sn_compress_block(const uint8_t* src, size_t n, uint8_t* dst) {
    uint16_t table[1 << 14];
    if (n < 16) return sn_emit_literal_tail(dst, src, n);
    memset(table, 0, sizeof(table));
    size_t d = 0;
    const size_t s_limit = n - 15;  // margin: 8-byte loads + copy slop stay in range
    size_t next_emit = 0;
    size_t s = 1;
    uint32_t next_hash = sn_hash(sn_load32(src + s));
    for (;;) {
        // Probe with accelerating skip: incompressible data costs ~1 probe
        // per 32 bytes instead of per byte.
        size_t skip = 32, next_s = s, candidate = 0;
        for (;;) {
            s = next_s;
            next_s = s + (skip >> 5);
            skip++;
            if (next_s > s_limit) goto remainder;
            candidate = table[next_hash];
            table[next_hash] = (uint16_t)s;
            next_hash = sn_hash(sn_load32(src + next_s));
            if (sn_load32(src + s) == sn_load32(src + candidate)) break;
        }
        d += sn_emit_literal(dst + d, src + next_emit, s - next_emit);
        for (;;) {
            size_t base = s, i = candidate + 4;
            s += 4;
            while (s + 8 <= n) {  // 8-byte compare + ctz beats byte-at-a-time
                uint64_t x = sn_load64(src + i) ^ sn_load64(src + s);
                if (x) { s += __builtin_ctzll(x) >> 3; goto matched; }
                i += 8; s += 8;
            }
            while (s < n && src[i] == src[s]) { i++; s++; }
        matched:
            d += sn_emit_copy(dst + d, base - candidate, s - base);
            next_emit = s;
            if (s >= s_limit) goto remainder;
            // Chain: re-seed the table at s-1 and test s immediately.
            uint64_t x = sn_load64(src + s - 1);
            table[sn_hash((uint32_t)x)] = (uint16_t)(s - 1);
            uint32_t cur = sn_hash((uint32_t)(x >> 8));
            candidate = table[cur];
            table[cur] = (uint16_t)s;
            if ((uint32_t)(x >> 8) != sn_load32(src + candidate)) {
                next_hash = sn_hash((uint32_t)(x >> 16));
                s++;
                break;
            }
        }
    }
remainder:
    if (next_emit < n) d += sn_emit_literal_tail(dst + d, src + next_emit, n - next_emit);
    return d;
}

// Worst case: uvarint header + incompressible literals (snappy MaxEncodedLen).
size_t sn_max_compressed(size_t n) { return 32 + n + n / 6; }

long long sn_compress(const uint8_t* src, size_t n, uint8_t* dst) {
    uint8_t* d = dst;
    size_t v = n;
    do { *d++ = (uint8_t)((v & 0x7f) | (v >= 0x80 ? 0x80 : 0)); v >>= 7; } while (v);
    for (size_t off = 0; off < n; off += 65536) {
        size_t blk = n - off < 65536 ? n - off : 65536;
        d += sn_compress_block(src + off, blk, d);
    }
    return (long long)(d - dst);
}

// Parsed uncompressed length, or -1 on a bad preamble.
long long sn_uncompressed_len(const uint8_t* src, size_t n) {
    uint64_t v = 0; int shift = 0; size_t i = 0;
    for (; i < n && i < 10; i++) {
        v |= (uint64_t)(src[i] & 0x7f) << shift;
        if (!(src[i] & 0x80)) return (long long)v;
        shift += 7;
    }
    return -1;
}

// Returns bytes written, or a negative errno-style code on corrupt input.
long long sn_decompress(const uint8_t* src, size_t n, uint8_t* dst, size_t cap) {
    uint64_t want = 0; int shift = 0; size_t s = 0;
    for (;;) {
        if (s >= n || s >= 10) return -1;
        want |= (uint64_t)(src[s] & 0x7f) << shift;
        if (!(src[s++] & 0x80)) break;
        shift += 7;
    }
    if (want > cap) return -2;
    size_t d = 0;
    while (s < n) {
        uint8_t tag = src[s++];
        size_t len, offset;
        switch (tag & 3) {
        case 0: {  // literal
            len = (tag >> 2) + 1;
            if (len > 60) {
                size_t extra = len - 60;
                if (s + extra > n) return -3;
                len = 0;
                for (size_t j = 0; j < extra; j++) len |= (size_t)src[s + j] << (8 * j);
                len++;
                s += extra;
            }
            if (s + len > n || d + len > want) return -3;
            memcpy(dst + d, src + s, len);
            s += len; d += len;
            continue;
        }
        case 1:  // copy, 1-byte offset
            if (s >= n) return -3;
            len = 4 + ((tag >> 2) & 7);
            offset = ((size_t)(tag >> 5) << 8) | src[s++];
            break;
        case 2:  // copy, 2-byte offset
            if (s + 2 > n) return -3;
            len = (tag >> 2) + 1;
            offset = (size_t)src[s] | ((size_t)src[s + 1] << 8);
            s += 2;
            break;
        default:  // copy, 4-byte offset
            if (s + 4 > n) return -3;
            len = (tag >> 2) + 1;
            offset = sn_load32(src + s);
            s += 4;
            break;
        }
        if (offset == 0 || offset > d || d + len > want) return -4;
        {
            uint8_t* op = dst + d;
            const uint8_t* sp = op - offset;
            if (offset >= 16 && len <= 16 && d + 16 <= cap) {
                memcpy(op, sp, 16);  // short copy blast (slop-covered)
            } else if (offset >= len) {
                memcpy(op, sp, len);
            } else if (offset >= 8 && d + len + 8 <= cap) {
                // Overlapping with lag >= 8: 8-byte strided blasts (may
                // overshoot len by up to 7 bytes inside the caller's slop).
                for (size_t j = 0; j < len; j += 8) memcpy(op + j, sp + j, 8);
            } else if (d + len + 8 <= cap) {
                // Tiny-offset RLE: seed one pattern period of >= 8 bytes
                // byte-wise, then blast with a lag that is a multiple of
                // the offset (so periodicity keeps every read correct).
                size_t lag = offset;
                while (lag < 8) lag += offset;
                size_t j = len < lag ? len : lag;
                for (size_t t = 0; t < j; t++) op[t] = sp[t];
                for (; j < len; j += 8) memcpy(op + j, op + j - lag, 8);
            } else {
                for (size_t j = 0; j < len; j++) op[j] = sp[j];
            }
            d += len;
        }
    }
    return d == want ? (long long)d : -5;
}

}  // extern "C"
