// Native IO layer: O_DIRECT aligned writes/reads (xl-storage's hot file path).
//
// Role of the reference's ncw/directio + internal/ioutil CopyAligned
// (cmd/xl-storage.go:1653-1740 CreateFile): large shard files are written
// through O_DIRECT with pooled aligned buffers so streaming uploads don't
// churn the page cache; the final unaligned tail drops O_DIRECT via fcntl
// (ioutil.DisableDirectIO) and writes normally; fdatasync seals the file.
// Reads mirror it (xl-storage.go ReadFileStream opens O_DIRECT for large
// files).
//
// Filesystems without O_DIRECT (tmpfs, some overlays) fall back to buffered
// IO transparently — same behavior as the reference's disk.ODirectPlatform
// probe. Exposed via ctypes from the same libminio_native.so as the
// RS/HighwayHash kernels.

#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>

#include <fcntl.h>
#include <sys/stat.h>
#include <sys/types.h>
#include <unistd.h>

#ifndef O_DIRECT
#define O_DIRECT 0
#endif

namespace {

constexpr size_t kAlign = 4096;        // logical block alignment
constexpr size_t kChunk = 4 << 20;     // 4 MiB staging buffer (ODirectPoolLarge)

struct AlignedBuf {
    uint8_t* p = nullptr;
    AlignedBuf(size_t n) {
        if (posix_memalign(reinterpret_cast<void**>(&p), kAlign, n) != 0) p = nullptr;
    }
    ~AlignedBuf() { free(p); }
};

bool disable_odirect(int fd) {
    int flags = fcntl(fd, F_GETFL);
    if (flags < 0) return false;
    return fcntl(fd, F_SETFL, flags & ~O_DIRECT) == 0;
}

}  // namespace

extern "C" {

// Probe whether a directory's filesystem accepts O_DIRECT
// (internal/disk/directio probe role). Returns 1/0.
int mt_odirect_supported(const char* dirpath) {
    if (O_DIRECT == 0) return 0;
    char tmpl[4096];
    snprintf(tmpl, sizeof(tmpl), "%s/.odirect-probe-XXXXXX", dirpath);
    int fd = mkstemp(tmpl);
    if (fd < 0) return 0;
    close(fd);
    int dfd = open(tmpl, O_WRONLY | O_DIRECT);
    unlink(tmpl);
    if (dfd < 0) return 0;
    close(dfd);
    return 1;
}

// Write `size` bytes to `path` (O_CREAT|O_TRUNC). Aligned body goes through
// O_DIRECT when the filesystem supports it; the tail is written buffered
// after dropping O_DIRECT (CopyAligned semantics). Returns bytes written,
// or -errno.
long long mt_write_file(const char* path, const uint8_t* data, size_t size,
                        int use_odirect, int do_fsync) {
    int flags = O_WRONLY | O_CREAT | O_TRUNC;
    bool odirect = use_odirect && O_DIRECT != 0 && size >= kAlign;
    int fd = -1;
    if (odirect) {
        fd = open(path, flags | O_DIRECT, 0644);
        if (fd < 0 && (errno == EINVAL || errno == EOPNOTSUPP)) odirect = false;
    }
    if (fd < 0) fd = open(path, flags, 0644);
    if (fd < 0) return -static_cast<long long>(errno);

    size_t off = 0;
    if (odirect) {
        AlignedBuf buf(kChunk);
        if (!buf.p) { close(fd); return -static_cast<long long>(ENOMEM); }
        size_t aligned_end = size - (size % kAlign);
        while (off < aligned_end) {
            size_t n = aligned_end - off;
            if (n > kChunk) n = kChunk;
            memcpy(buf.p, data + off, n);
            ssize_t w = write(fd, buf.p, n);
            if (w < 0) {
                if (errno == EINVAL && off == 0 && disable_odirect(fd)) {
                    odirect = false;  // fs lied at open; fall back buffered
                    break;
                }
                int e = errno; close(fd); return -static_cast<long long>(e);
            }
            off += static_cast<size_t>(w);
        }
        if (odirect && off < size) {
            // Unaligned tail: drop O_DIRECT (ioutil.DisableDirectIO) and
            // write the remainder buffered.
            if (!disable_odirect(fd)) { int e = errno; close(fd); return -static_cast<long long>(e); }
        }
    }
    while (off < size) {
        ssize_t w = write(fd, data + off, size - off);
        if (w < 0) { int e = errno; close(fd); return -static_cast<long long>(e); }
        off += static_cast<size_t>(w);
    }
    if (do_fsync && fdatasync(fd) != 0) {
        int e = errno; close(fd); return -static_cast<long long>(e);
    }
    if (close(fd) != 0) return -static_cast<long long>(errno);
    return static_cast<long long>(off);
}

// Read `size` bytes at `offset` into `out`. Uses O_DIRECT with an aligned
// bounce buffer when requested and supported, else plain pread. Returns
// bytes read (may be short at EOF) or -errno.
long long mt_read_file(const char* path, uint8_t* out, size_t size,
                       size_t offset, int use_odirect) {
    bool odirect = use_odirect && O_DIRECT != 0;
    int fd = -1;
    if (odirect) {
        fd = open(path, O_RDONLY | O_DIRECT);
        if (fd < 0 && (errno == EINVAL || errno == EOPNOTSUPP)) odirect = false;
    }
    if (fd < 0) fd = open(path, O_RDONLY);
    if (fd < 0) return -static_cast<long long>(errno);

    size_t got = 0;
    if (odirect) {
        AlignedBuf buf(kChunk);
        if (!buf.p) { close(fd); return -static_cast<long long>(ENOMEM); }
        // Aligned window covering [offset, offset+size).
        size_t astart = offset - (offset % kAlign);
        size_t lead = offset - astart;
        size_t pos = astart;
        while (got < size) {
            // Clamp to the align-rounded remainder: a 128 KiB read must not
            // pull a 4 MiB chunk off the disk.
            size_t want = lead + (size - got);
            want = ((want + kAlign - 1) / kAlign) * kAlign;
            if (want > kChunk) want = kChunk;
            ssize_t r = pread(fd, buf.p, want, static_cast<off_t>(pos));
            if (r < 0) {
                if (errno == EINVAL && pos == astart) { odirect = false; break; }
                int e = errno; close(fd); return -static_cast<long long>(e);
            }
            if (r == 0) break;  // EOF
            size_t usable = static_cast<size_t>(r) > lead ? static_cast<size_t>(r) - lead : 0;
            size_t n = usable < size - got ? usable : size - got;
            memcpy(out + got, buf.p + lead, n);
            got += n;
            pos += static_cast<size_t>(r);
            lead = 0;
            if (static_cast<size_t>(r) < want) break;  // EOF within chunk
        }
    }
    if (!odirect) {
        while (got < size) {
            ssize_t r = pread(fd, out + got, size - got, static_cast<off_t>(offset + got));
            if (r < 0) { int e = errno; close(fd); return -static_cast<long long>(e); }
            if (r == 0) break;
            got += static_cast<size_t>(r);
        }
    }
    close(fd);
    return static_cast<long long>(got);
}

}  // extern "C"
